"""LM generation with KV cache: the incremental (cached) decode must
reproduce the full-forward logits exactly, and a trained LM must continue
its learned pattern under greedy decoding."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models import zoo
from veles_tpu.models.generate import LMGenerator
from veles_tpu.models.standard_workflow import StandardWorkflow


def _lm_workflow(max_epochs=0, vocab=13, t=16, seed=31, mesh_config=None,
                 **zoo_kwargs):
    prng.seed_all(seed)
    r = np.random.RandomState(5)
    n = 192
    toks = ((np.arange(t)[None, :] * 2 + r.randint(0, 4, n)[:, None])
            % vocab).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=48,
                             class_lengths=[0, 48, 144])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=vocab, d_model=32, n_heads=4,
                                  n_layers=2, lr=5e-3, dropout=0.0,
                                  **zoo_kwargs),
        loader=loader, loss="lm",
        decision_config={"max_epochs": max(max_epochs, 1)},
        mesh_config=mesh_config, name="gen-lm")
    wf.initialize()
    if max_epochs > 0:
        wf.run()
    return wf, toks


@pytest.mark.parametrize("zoo_kwargs", [
    {}, {"n_kv_heads": 2}, {"pos": "rope"}])
def test_incremental_matches_full_forward(zoo_kwargs, f32_precision):
    wf, toks = _lm_workflow(max_epochs=0, **zoo_kwargs)
    gen = LMGenerator(wf.trainer, max_len=16)
    sample = toks[:4]
    inc = gen.score(sample)                      # [B, T-1, V]
    full = np.asarray(
        jax.jit(wf.trainer._forward, static_argnums=(2,))(
            wf.trainer.params, jnp.asarray(sample), False,
            jax.random.key(0)), np.float32)[:, :-1]
    np.testing.assert_allclose(inc, full, rtol=2e-4, atol=2e-4)


def test_greedy_generation_continues_pattern():
    wf, toks = _lm_workflow(max_epochs=15)
    gen = LMGenerator(wf.trainer, max_len=16)
    prompt = toks[:8, :8]
    out = gen.generate(prompt, max_new=8)
    assert out.shape == (8, 16)
    np.testing.assert_array_equal(out[:, :8], prompt)  # prompt untouched
    # the learned rule: every token advances by 2 (mod vocab)
    step_ok = ((out[:, 1:] - out[:, :-1]) % 13 == 2).mean()
    assert step_ok > 0.9, (step_ok, out[:2])


def test_temperature_sampling_reproducible():
    wf, toks = _lm_workflow(max_epochs=2)
    gen = LMGenerator(wf.trainer, max_len=16)
    a = gen.generate(toks[:2, :6], max_new=6, temperature=0.7, seed=3)
    b = gen.generate(toks[:2, :6], max_new=6, temperature=0.7, seed=3)
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.int32 and a.shape == (2, 12)


def test_rejects_overlong_prompt():
    wf, toks = _lm_workflow(max_epochs=0)
    gen = LMGenerator(wf.trainer, max_len=10)
    with pytest.raises(ValueError):
        gen.generate(toks[:2, :8], max_new=8)


def test_one_compile_per_batch_size():
    """Varying prompt lengths must reuse ONE compiled scan (prompt_len is
    traced) — a REST server sees arbitrary lengths per request."""
    wf, toks = _lm_workflow(max_epochs=0)
    gen = LMGenerator(wf.trainer, max_len=16)
    gen.generate(toks[:2, :4], max_new=2)
    gen.generate(toks[:2, :7], max_new=5)
    gen.generate(toks[:2, :10], max_new=1)
    assert len(gen._compiled) == 1, list(gen._compiled)


def test_compile_cache_is_bounded_lru():
    """Batch size is client-controlled over REST: the per-generator
    executable cache must evict, not grow without bound."""
    from veles_tpu.models import generate as gen_mod
    wf, toks = _lm_workflow(max_epochs=0)
    gen = LMGenerator(wf.trainer, max_len=16)
    cap = gen_mod.COMPILE_CACHE_SIZE
    for b in range(1, cap + 2):                  # cap + 1 distinct batches
        gen.generate(toks[:b, :4], max_new=2)
    assert len(gen._compiled) == cap, list(gen._compiled)
    assert 1 not in gen._compiled                # oldest evicted
    # recency, not FIFO: re-hit the current-oldest key, then insert one
    # more — the hit key must survive and the next-oldest must go
    gen.generate(toks[:2, :4], max_new=2)
    gen.generate(toks[: cap + 2, :4], max_new=2)
    assert 2 in gen._compiled
    assert 3 not in gen._compiled, list(gen._compiled)


def test_greedy_and_sampling_share_one_executable():
    """greedy is a traced per-row flag now — mixed request kinds at one
    batch size reuse a single compiled scan."""
    wf, toks = _lm_workflow(max_epochs=0)
    gen = LMGenerator(wf.trainer, max_len=16)
    gen.generate(toks[:2, :4], max_new=2)                     # greedy
    gen.generate(toks[:2, :4], max_new=2, temperature=0.8)    # sampling
    assert len(gen._compiled) == 1, list(gen._compiled)


def test_generate_batch_matches_solo_calls():
    """The serving coalescer's core invariant: a request's tokens are
    IDENTICAL whether it ran alone or merged into any batch (per-row
    params, per-(seed, position) sampling keys)."""
    wf, toks = _lm_workflow(max_epochs=8)
    gen = LMGenerator(wf.trainer, max_len=16)
    reqs = [
        (toks[0, :8],  {"max_new": 6}),                        # greedy
        (toks[1, :5],  {"max_new": 4, "temperature": 0.9,
                        "seed": 3}),
        (toks[2, :10], {"max_new": 3, "temperature": 0.7,
                        "top_k": 5, "seed": 11}),
        (toks[3, :6],  {"max_new": 8, "temperature": 1.1,
                        "top_p": 0.8, "seed": 4}),
    ]
    merged = gen.generate_batch([p for p, _ in reqs],
                                [o for _, o in reqs])
    for (prompt, opts), got in zip(reqs, merged):
        solo = gen.generate(prompt[None], **opts)[0]
        np.testing.assert_array_equal(got, solo)
    # and merging in a different order changes nothing either
    merged2 = gen.generate_batch([p for p, _ in reqs[::-1]],
                                 [o for _, o in reqs[::-1]])
    for a, b in zip(merged2, merged[::-1]):
        np.testing.assert_array_equal(a, b)


def test_top_k_and_top_p_sampling():
    """top_k=1 must equal greedy; top_p≈0 likewise; both reproducible."""
    wf, toks = _lm_workflow(max_epochs=6)
    gen = LMGenerator(wf.trainer, max_len=16)
    prompt = toks[:4, :8]
    greedy = gen.generate(prompt, max_new=6)
    k1 = gen.generate(prompt, max_new=6, temperature=0.9, top_k=1)
    np.testing.assert_array_equal(greedy, k1)
    p0 = gen.generate(prompt, max_new=6, temperature=0.9, top_p=1e-6)
    np.testing.assert_array_equal(greedy, p0)
    a = gen.generate(prompt, max_new=6, temperature=0.9, top_k=5,
                     top_p=0.9, seed=4)
    b = gen.generate(prompt, max_new=6, temperature=0.9, top_k=5,
                     top_p=0.9, seed=4)
    np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError):
        gen.generate(prompt, max_new=2, top_p=0.0)


def test_bf16_cache_dtype():
    wf, toks = _lm_workflow(max_epochs=4)
    import jax.numpy as jnp
    gen = LMGenerator(wf.trainer, max_len=16, cache_dtype=jnp.bfloat16)
    out = gen.generate(toks[:2, :8], max_new=4)
    assert out.shape == (2, 12)
    # bf16 cache vs f32 cache: same greedy continuation on this easy task
    ref = LMGenerator(wf.trainer, max_len=16).generate(toks[:2, :8],
                                                       max_new=4)
    np.testing.assert_array_equal(out, ref)


def test_int8_kv_cache_generation():
    """int8 KV cache (QuantCache): greedy continuation matches the f32
    cache on a trained model (quantization noise ≪ the logit margins),
    across the full-scan, prefill, and beam paths."""
    import jax.numpy as jnp

    t = 96
    wf, toks = _lm_workflow(max_epochs=8, t=t)
    gen8 = LMGenerator(wf.trainer, max_len=t, cache_dtype="int8")
    ref = LMGenerator(wf.trainer, max_len=t)
    # the cache really is int8 + scales
    c = gen8._init_caches(2, jnp.float32)
    assert c[0][0].data.dtype == jnp.int8
    assert c[0][0].scale.shape == (2, 4, t, 1)

    short = toks[:4, :8]                     # full-scan path
    np.testing.assert_array_equal(gen8.generate(short, max_new=6),
                                  ref.generate(short, max_new=6))
    long = toks[:4, :48]                     # chunked-prefill path
    np.testing.assert_array_equal(gen8.generate(long, max_new=8),
                                  ref.generate(long, max_new=8))
    bt8, _ = gen8.beam_search(long, max_new=5, beam=3)
    bt, _ = ref.beam_search(long, max_new=5, beam=3)
    np.testing.assert_array_equal(bt8, bt)
    # sampled decoding stays reproducible under quantization
    a = gen8.generate(long, max_new=6, temperature=0.8, seed=3)
    b = gen8.generate(long, max_new=6, temperature=0.8, seed=3)
    np.testing.assert_array_equal(a, b)


def test_sampling_params_do_not_recompile():
    """top_k/top_p are traced — distinct values reuse ONE executable."""
    wf, toks = _lm_workflow(max_epochs=0)
    gen = LMGenerator(wf.trainer, max_len=16)
    for tk, tp in ((0, 1.0), (5, 0.9), (3, 0.7), (8, 0.99)):
        gen.generate(toks[:2, :6], max_new=3, temperature=0.8,
                     top_k=tk, top_p=tp, seed=1)
    assert len(gen._compiled) == 1, list(gen._compiled)
    with pytest.raises(ValueError):
        gen.generate(toks[:2, :6], max_new=2, temperature=0.8, top_k=-1)
    with pytest.raises(ValueError):
        gen.generate(toks[:2, :6], max_new=2, temperature=0.8,
                     top_k=10 ** 6)


def test_beam_search_matches_greedy_at_beam1_and_scores_exactly():
    wf, toks = _lm_workflow(max_epochs=8)
    gen = LMGenerator(wf.trainer, max_len=16)
    prompt = toks[:4, :8]
    greedy = gen.generate(prompt, max_new=6)
    b1, s1 = gen.beam_search(prompt, max_new=6, beam=1)
    np.testing.assert_array_equal(b1, greedy)

    b4, s4 = gen.beam_search(prompt, max_new=6, beam=4)
    np.testing.assert_array_equal(b4[:, :8], prompt)
    # on this near-deterministic toy model the wider beam finds a
    # sequence at least as likely (NOT a beam-search guarantee in
    # general — pruning can lose the greedy prefix)
    assert (s4 >= s1 - 1e-4).all(), (s1, s4)

    # the returned score must equal the teacher-forced logprob of the
    # returned sequence (positions 8..13 predicted from 7..12)
    logits = gen.score(b4)                       # [B, T-1, V]
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits), axis=-1))
    want = np.take_along_axis(
        logp[:, 7:13], b4[:, 8:14, None], axis=-1)[..., 0].sum(axis=1)
    np.testing.assert_allclose(s4, want, rtol=1e-4, atol=1e-4)

    with pytest.raises(ValueError):
        gen.beam_search(prompt, max_new=6, beam=0)


def test_tensor_parallel_decode_matches_single_device(f32_precision):
    """A model trained under a {model: 2} mesh decodes through the SAME
    sharded params (column-parallel projections, head-sharded KV caches);
    greedy tokens must match the single-device path and the full logits
    must agree to numerical tolerance (the psum over the contracted
    model axis reorders float adds)."""
    import jax
    from veles_tpu.parallel import MeshConfig, make_mesh

    mc = MeshConfig(make_mesh({"model": 2}, jax.devices()[:2]))
    wf, toks = _lm_workflow(max_epochs=10, mesh_config=mc,
                            n_kv_heads=2)
    gen_tp = LMGenerator(wf.trainer, max_len=16)        # auto: trainer mesh
    assert gen_tp.mesh_cfg is mc
    prompt = toks[:4, :8]
    out_tp = gen_tp.generate(prompt, max_new=6)

    # reference: identical training run without a mesh
    wf1, _ = _lm_workflow(max_epochs=10, n_kv_heads=2)
    gen1 = LMGenerator(wf1.trainer, max_len=16)
    assert gen1.mesh_cfg is None
    np.testing.assert_allclose(
        np.asarray(wf.trainer.params["l00_embedding"]["table"]),
        np.asarray(wf1.trainer.params["l00_embedding"]["table"]),
        rtol=1e-4, atol=1e-5)                          # same training
    out1 = gen1.generate(prompt, max_new=6)
    np.testing.assert_array_equal(out_tp, out1)
    np.testing.assert_allclose(gen_tp.score(toks[:2]), gen1.score(toks[:2]),
                               rtol=2e-3, atol=2e-3)
    # beam search rides the same sharded step
    bt, bs = gen_tp.beam_search(prompt, max_new=4, beam=3)
    b1, s1 = gen1.beam_search(prompt, max_new=4, beam=3)
    np.testing.assert_array_equal(bt, b1)
    np.testing.assert_allclose(bs, s1, rtol=1e-3, atol=1e-3)


def test_tensor_parallel_decode_rejects_indivisible_kv_heads():
    import jax
    from veles_tpu.parallel import MeshConfig, make_mesh

    mc = MeshConfig(make_mesh({"model": 4}, jax.devices()[:4]))
    wf, _ = _lm_workflow(max_epochs=0, mesh_config=mc, n_kv_heads=2)
    with pytest.raises(ValueError, match="divisible by the model axis"):
        LMGenerator(wf.trainer, max_len=16)


@pytest.mark.parametrize("zoo_kwargs", [
    {}, {"n_kv_heads": 2}, {"pos": "rope"}, {"window": 24}])
def test_chunked_prefill_matches_full_scan(zoo_kwargs, f32_precision):
    """Long prompts route through the parallel prefill + short
    generation scan; tokens must match the position-by-position full
    scan exactly — greedy, sampled, and near-max_len overshoot."""
    t = 96
    wf, toks = _lm_workflow(max_epochs=6, t=t, **zoo_kwargs)
    gen = LMGenerator(wf.trainer, max_len=t)
    assert gen.prefill_min <= 48       # prompts below DO use prefill

    ref = LMGenerator(wf.trainer, max_len=t)
    ref.prefill_min = 10 ** 9          # force the full scan

    prompt = toks[:4, :48]
    for kwargs in ({}, {"temperature": 0.8, "seed": 5},
                   {"temperature": 0.7, "top_k": 5, "seed": 2}):
        got = gen.generate(prompt, max_new=12, **kwargs)
        want = ref.generate(prompt, max_new=12, **kwargs)
        np.testing.assert_array_equal(got, want)
    assert any(isinstance(k, tuple) and k[0] == "pre"
               for k in gen._compiled), list(gen._compiled)
    assert all(not (isinstance(k, tuple) and k[0] == "pre")
               for k in ref._compiled), list(ref._compiled)

    # near-max_len: the power-of-two generation bucket overshoots past
    # the last position and must clamp idempotently
    got = gen.generate(toks[:2, :90], max_new=6)
    want = ref.generate(toks[:2, :90], max_new=6)
    np.testing.assert_array_equal(got, want)


def test_chunked_prefill_beam_search_matches_full_scan(f32_precision):
    """Beam search with a long prompt routes through ONE batch-wide
    prefill tiled across the beams — tokens and scores must match the
    beam-per-position full scan exactly, incl. generating right up to
    max_len (no overshoot headroom)."""
    t = 96
    wf, toks = _lm_workflow(max_epochs=6, t=t, n_kv_heads=2)
    gen = LMGenerator(wf.trainer, max_len=t)
    ref = LMGenerator(wf.trainer, max_len=t)
    ref.prefill_min = 10 ** 9
    for t0, max_new, beam in ((48, 10, 4), (40, 7, 3), (90, 6, 2)):
        got_t, got_s = gen.beam_search(toks[:3, :t0], max_new=max_new,
                                       beam=beam)
        want_t, want_s = ref.beam_search(toks[:3, :t0], max_new=max_new,
                                         beam=beam)
        np.testing.assert_array_equal(got_t, want_t)
        np.testing.assert_allclose(got_s, want_s, rtol=1e-6, atol=1e-6)
    assert any(isinstance(k, tuple) and k[0] == "beamgen"
               for k in gen._compiled), list(gen._compiled)


def test_chunked_prefill_bf16_cache_rope_parity(f32_precision):
    """The dtype-ordering trap: the cache must hold rope(k) computed in
    the CACHE dtype (mha_step's ordering) on both paths, or bf16-cache
    serving diverges between prefill and full scan."""
    import jax.numpy as jnp

    t = 96
    wf, toks = _lm_workflow(max_epochs=6, t=t, pos="rope")
    gen = LMGenerator(wf.trainer, max_len=t, cache_dtype=jnp.bfloat16)
    ref = LMGenerator(wf.trainer, max_len=t, cache_dtype=jnp.bfloat16)
    ref.prefill_min = 10 ** 9
    for kwargs in ({}, {"temperature": 0.8, "seed": 11}):
        got = gen.generate(toks[:3, :40], max_new=10, **kwargs)
        want = ref.generate(toks[:3, :40], max_new=10, **kwargs)
        np.testing.assert_array_equal(got, want)


def test_chunked_prefill_generate_batch_mixed_lengths(f32_precision):
    """Mixed prompt lengths: prefill covers the common prefix, the scan
    teacher-forces the longer prompts' tails — same tokens as the full
    scan for every row."""
    t = 96
    wf, toks = _lm_workflow(max_epochs=6, t=t)
    gen = LMGenerator(wf.trainer, max_len=t)
    ref = LMGenerator(wf.trainer, max_len=t)
    ref.prefill_min = 10 ** 9
    prompts = [toks[0, :40], toks[1, :64], toks[2, :52]]
    opts = [{"max_new": 10},
            {"max_new": 8, "temperature": 0.9, "seed": 3},
            {"max_new": 12, "temperature": 0.8, "top_k": 4, "seed": 9}]
    got = gen.generate_batch(prompts, opts)
    want = ref.generate_batch(prompts, opts)
    for a, b in zip(got, want):
        np.testing.assert_array_equal(a, b)


def test_incremental_matches_full_forward_window(f32_precision):
    """Sliding-window stack: the KV-cache step must apply the same
    window mask the training forward uses."""
    wf, toks = _lm_workflow(max_epochs=0, window=5)
    gen = LMGenerator(wf.trainer, max_len=16)
    inc = gen.score(toks[:4])
    full = np.asarray(
        jax.jit(wf.trainer._forward, static_argnums=(2,))(
            wf.trainer.params, jnp.asarray(toks[:4]), False,
            jax.random.key(0)), np.float32)[:, :-1]
    np.testing.assert_allclose(inc, full, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("zoo_kwargs", [
    {}, {"n_kv_heads": 2, "pos": "rope"}])
def test_speculative_decode_matches_greedy(zoo_kwargs, f32_precision):
    """In-jit n-gram speculation is greedy-EXACT: identical tokens to
    generate() for any draft width, and on this repetitive corpus the
    round count proves multi-token acceptance actually happened."""
    t = 96
    wf, toks = _lm_workflow(max_epochs=8, t=t, **zoo_kwargs)
    gen = LMGenerator(wf.trainer, max_len=t)
    prompt = toks[:1, :48]
    want = gen.generate(prompt, max_new=20)
    for dk in (4, 8):
        got = gen.generate_speculative(prompt, max_new=20, draft_k=dk)
        np.testing.assert_array_equal(got, want)
    assert any(isinstance(k, tuple) and k[0] == "spec"
               for k in gen._compiled), list(gen._compiled)
    # UNTRAINED model: argmax never reproduces the prompt, so this
    # pins the teacher-forced tail (the bonus token must not overwrite
    # prompt positions) and true exactness, not corpus memorization
    wf0, toks0 = _lm_workflow(max_epochs=0, t=t, **zoo_kwargs)
    gen0 = LMGenerator(wf0.trainer, max_len=t)
    p0 = toks0[:1, :48]
    got0 = gen0.generate_speculative(p0, max_new=20, draft_k=8)
    np.testing.assert_array_equal(got0[:, :48], p0)   # prompt intact
    np.testing.assert_array_equal(got0, gen0.generate(p0, max_new=20))
    # fallbacks: batch > 1 and short prompts route to plain generate()
    np.testing.assert_array_equal(
        gen.generate_speculative(toks[:2, :48], max_new=4),
        gen.generate(toks[:2, :48], max_new=4))
    np.testing.assert_array_equal(
        gen.generate_speculative(toks[:1, :8], max_new=4),
        gen.generate(toks[:1, :8], max_new=4))
    with pytest.raises(ValueError, match="draft_k"):
        gen.generate_speculative(prompt, max_new=4, draft_k=1)


def test_rolling_window_cache_bounds_memory(f32_precision):
    """Sliding-window blocks get a ring-buffer cache of exactly
    ``window`` slots: serve-time KV memory is O(window) no matter how
    long the context — and generation still matches the training
    forward's window mask (score oracle) at positions far past the
    window."""
    import jax.numpy as jnp

    t, w = 96, 16
    wf, toks = _lm_workflow(max_epochs=6, t=t, window=w, pos="rope")
    gen = LMGenerator(wf.trainer, max_len=t)
    caches = gen._init_caches(2, jnp.float32)
    for ck, cv in caches:
        assert ck.shape == (2, 4, w, 8), ck.shape     # w slots, not t
    # logits match the full training forward (window mask) at every
    # position, incl. far beyond the window
    inc = gen.score(toks[:3])
    full = np.asarray(
        jax.jit(wf.trainer._forward, static_argnums=(2,))(
            wf.trainer.params, jnp.asarray(toks[:3]), False,
            jax.random.key(0)), np.float32)[:, :-1]
    np.testing.assert_allclose(inc, full, rtol=2e-3, atol=2e-3)
    # prefill path == full scan on the ring buffer, deep into the
    # context (prompt 11x the window)
    ref = LMGenerator(wf.trainer, max_len=t)
    ref.prefill_min = 10 ** 9
    for kwargs in ({}, {"temperature": 0.8, "seed": 7}):
        np.testing.assert_array_equal(
            gen.generate(toks[:3, :80], max_new=10, **kwargs),
            ref.generate(toks[:3, :80], max_new=10, **kwargs))
    # beam rides the ring too
    bt, bs = gen.beam_search(toks[:2, :70], max_new=6, beam=3)
    rt, rs = ref.beam_search(toks[:2, :70], max_new=6, beam=3)
    np.testing.assert_array_equal(bt, rt)
    np.testing.assert_allclose(bs, rs, rtol=1e-5, atol=1e-5)
    # int8 composes with the ring (QuantCache slots)
    gen8 = LMGenerator(wf.trainer, max_len=t, cache_dtype="int8")
    c8 = gen8._init_caches(2, jnp.float32)
    assert c8[0][0].data.shape == (2, 4, w, 8)
    np.testing.assert_array_equal(
        gen8.generate(toks[:3, :80], max_new=10),
        gen.generate(toks[:3, :80], max_new=10))
    # int8 + ring PREFILL == int8 + ring full scan (the in-chunk view
    # must be the quantized one everywhere, head positions included)
    ref8 = LMGenerator(wf.trainer, max_len=t, cache_dtype="int8")
    ref8.prefill_min = 10 ** 9
    for kwargs in ({}, {"temperature": 0.8, "seed": 5}):
        np.testing.assert_array_equal(
            gen8.generate(toks[:3, :80], max_new=10, **kwargs),
            ref8.generate(toks[:3, :80], max_new=10, **kwargs))


def test_generation_with_tied_embeddings(f32_precision):
    wf, toks = _lm_workflow(max_epochs=0, tie_embeddings=True)
    gen = LMGenerator(wf.trainer, max_len=16)
    inc = gen.score(toks[:4])
    full = np.asarray(
        jax.jit(wf.trainer._forward, static_argnums=(2,))(
            wf.trainer.params, jnp.asarray(toks[:4]), False,
            jax.random.key(0)), np.float32)[:, :-1]
    np.testing.assert_allclose(inc, full, rtol=2e-4, atol=2e-4)
    # temperature sampling path (logit scaling, not weight scaling)
    a = gen.generate(toks[:2, :6], max_new=4, temperature=0.8, seed=2)
    b = gen.generate(toks[:2, :6], max_new=4, temperature=0.8, seed=2)
    np.testing.assert_array_equal(a, b)


class TestInt8ServingWeights:
    """weights="int8" (ops.quant W8A8-dynamic): the serving params become
    int8 + scales, decode still works end to end, and the quantized
    logits track the float ones within quantization error."""

    def test_quant_ops_precision(self):
        from veles_tpu.ops import quant
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randn(4, 32), jnp.float32)
        w = jnp.asarray(r.randn(32, 48), jnp.float32) * 0.2
        qw = quant.quantize_weight(w)
        assert qw.q.dtype == jnp.int8 and qw.scale.shape == (48,)
        y, ref = quant.int8_matmul(x, qw), x @ w
        err = float(jnp.max(jnp.abs(y - ref)))
        assert err < 0.05 * float(jnp.max(jnp.abs(ref))), err
        # per-row table: gathered rows dequantize near-exactly and the
        # transposed direction (tied head) matches x @ tableT
        table = jnp.asarray(r.randn(13, 32), jnp.float32)
        qt = quant.quantize_weight(table, axis=1)
        rows = quant.take_rows(qt, jnp.asarray([0, 5, 12]))
        np.testing.assert_allclose(np.asarray(rows),
                                   np.asarray(table)[[0, 5, 12]],
                                   rtol=0.02, atol=0.02)
        yt = quant.int8_matmul_t(x, qt)
        reft = x @ table.T
        assert float(jnp.max(jnp.abs(yt - reft))) < \
            0.05 * float(jnp.max(jnp.abs(reft)))

    @pytest.mark.parametrize("zoo_kwargs", [
        {"pos": "rope", "n_kv_heads": 2}, {"tie_embeddings": True}])
    def test_int8_decode_tracks_float(self, zoo_kwargs, f32_precision):
        wf, toks = _lm_workflow(max_epochs=8, **zoo_kwargs)
        gen_f = LMGenerator(wf.trainer, max_len=16)
        gen_q = LMGenerator(wf.trainer, max_len=16, weights="int8")
        from veles_tpu.ops import quant
        flat = jax.tree_util.tree_leaves(
            gen_q.params, is_leaf=lambda x: isinstance(x,
                                                       quant.QuantWeight))
        assert any(isinstance(leaf, quant.QuantWeight) for leaf in flat)
        # per-position scores within quantization error of the float path
        sq = gen_q.score(toks[:4])
        sf = gen_f.score(toks[:4])
        scale = np.abs(sf).max()
        assert np.max(np.abs(sq - sf)) < 0.08 * scale
        # greedy decode runs, is deterministic, and (trained model,
        # peaked logits) matches the float continuation
        a = gen_q.generate(toks[:4, :8], max_new=6)
        b = gen_q.generate(toks[:4, :8], max_new=6)
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(
            a, gen_f.generate(toks[:4, :8], max_new=6))

    def test_bf16_serving_weights(self, f32_precision):
        """weights="bf16": the whole float tree casts down (halved
        decode weight traffic), scores stay close, decode matches the
        float continuation on a trained model."""
        wf, toks = _lm_workflow(max_epochs=8)
        gen_f = LMGenerator(wf.trainer, max_len=16)
        gen_h = LMGenerator(wf.trainer, max_len=16, weights="bf16")
        table = gen_h.params[gen_h._embed.name]["table"]
        assert table.dtype == jnp.bfloat16
        sf, sh = gen_f.score(toks[:4]), gen_h.score(toks[:4])
        assert np.max(np.abs(sh - sf)) < 0.05 * np.abs(sf).max()
        np.testing.assert_array_equal(
            gen_h.generate(toks[:4, :8], max_new=6),
            gen_f.generate(toks[:4, :8], max_new=6))

    def test_int8_tensor_parallel_decode(self, f32_precision):
        """int8 serving under a model-axis mesh (the lifted
        restriction): the int8 payload is re-placed with the sharding
        of the float weight it replaces, scales replicated — and the
        sharded decode must produce the single-device int8 decode's
        tokens."""
        from veles_tpu.parallel import MeshConfig, make_mesh
        mc = MeshConfig(make_mesh({"model": 2}, jax.devices()[:2]))
        wf, toks = _lm_workflow(max_epochs=10, mesh_config=mc,
                                n_kv_heads=2)
        gen_tp = LMGenerator(wf.trainer, max_len=16, weights="int8")
        assert gen_tp.mesh_cfg is mc
        # payload sharded like the original weight, scales replicated
        from veles_tpu.ops import quant
        qw = gen_tp.params["l02_transformer_block"]["mha"]["wq"]
        assert isinstance(qw, quant.QuantWeight)
        orig = wf.trainer.params["l02_transformer_block"]["mha"]["wq"]
        assert qw.q.sharding == orig.sharding
        assert qw.scale.sharding.is_fully_replicated
        wf1, _ = _lm_workflow(max_epochs=10, n_kv_heads=2)
        gen1 = LMGenerator(wf1.trainer, max_len=16, weights="int8")
        prompt = toks[:4, :8]
        np.testing.assert_array_equal(gen_tp.generate(prompt, max_new=6),
                                      gen1.generate(prompt, max_new=6))

    def test_quant_weight_guards(self):
        from veles_tpu.parallel import MeshConfig, make_mesh
        wf, _ = _lm_workflow(max_epochs=0, n_kv_heads=2)
        with pytest.raises(ValueError, match="int8"):
            LMGenerator(wf.trainer, max_len=16, weights="int4")
        mc = MeshConfig(make_mesh({"model": 2}, jax.devices()[:2]))
        # w4a8 keeps the single-device restriction (the nibble-packed
        # payload halves the contraction axis — training specs don't
        # describe it)
        with pytest.raises(ValueError, match="single-device"):
            LMGenerator(wf.trainer, max_len=16, mesh_cfg=mc,
                        weights="w4a8")
        wf_moe, _ = _lm_workflow(max_epochs=0, n_experts=2)
        with pytest.raises(ValueError, match="MoE"):
            LMGenerator(wf_moe.trainer, max_len=16, weights="int8")
        with pytest.raises(ValueError, match="MoE"):
            LMGenerator(wf_moe.trainer, max_len=16, weights="w4a8")


class TestContinuousBatching:
    @pytest.mark.parametrize("ticks_per_dispatch,chunked_prefill",
                             [(1, True), (4, True), (1, False), (4, False)])
    def test_staggered_requests_match_solo_greedy(self, f32_precision,
                                                  ticks_per_dispatch,
                                                  chunked_prefill):
        """In-flight batching: requests submitted at DIFFERENT ticks,
        sharing the slot pool mid-decode, must produce exactly the solo
        greedy continuation — slot placement and neighbors are
        invisible (the continuous-batching correctness contract), at
        per-token admission AND with K engine ticks fused into one
        dispatch (rows freeze in-jit at their budget)."""
        from veles_tpu.models.generate import ContinuousBatcher
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)
        cb = ContinuousBatcher(gen, slots=3,
                               ticks_per_dispatch=ticks_per_dispatch,
                               chunked_prefill=chunked_prefill)

        prompts = [toks[0, :4].tolist(), toks[1, :6].tolist(),
                   toks[2, :3].tolist(), toks[3, :5].tolist()]
        max_news = [8, 6, 9, 7]
        rids = [cb.submit(prompts[0], max_news[0]),
                cb.submit(prompts[1], max_news[1])]
        for _ in range(3):            # run partway before more arrive
            cb.tick()
        rids.append(cb.submit(prompts[2], max_news[2]))
        cb.tick()
        rids.append(cb.submit(prompts[3], max_news[3]))  # queues: 3 slots
        cb.run_all()

        for rid, prompt, max_new in zip(rids, prompts, max_news):
            got = cb.result(rid)
            want = gen.generate(np.asarray([prompt], np.int32),
                                max_new)[0].tolist()
            assert got == want, (rid, got, want)

    def test_sliding_window_model_rides_the_pool(self, f32_precision):
        """Rolling ring-buffer caches through the batcher: the prefill
        chunk rounds DOWN (ring slots must never hold a position past
        the cursor) and the tick's prompt-forcing finishes admission —
        outputs still match the solo generator."""
        from veles_tpu.models.generate import ContinuousBatcher
        wf, toks = _lm_workflow(max_epochs=8, window=6, impl="flash")
        gen = LMGenerator(wf.trainer, max_len=16)
        cb = ContinuousBatcher(gen, slots=2, ticks_per_dispatch=2)
        rids = [cb.submit(toks[i, :5].tolist(), 7) for i in range(3)]
        cb.run_all()
        for i, rid in enumerate(rids):
            want = gen.generate(toks[i:i + 1, :5], 7)[0].tolist()
            assert cb.result(rid) == want, (i, cb.result(rid), want)

    def test_slot_reuse_and_queueing(self, f32_precision):
        """More requests than slots: the queue drains through freed
        slots; every request completes with its own continuation."""
        from veles_tpu.models.generate import ContinuousBatcher
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)
        cb = ContinuousBatcher(gen, slots=2)
        rids = [cb.submit(toks[i, :4].tolist(), 5) for i in range(5)]
        cb.run_all()
        assert cb.idle()
        for i, rid in enumerate(rids):
            want = gen.generate(toks[i:i + 1, :4], 5)[0].tolist()
            assert cb.result(rid) == want

    def test_temperature_rows_deterministic_per_seed(self, f32_precision):
        """A sampled row's draws depend only on (seed, position) — the
        same request replayed alone reproduces its tokens."""
        from veles_tpu.models.generate import ContinuousBatcher
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)
        cb1 = ContinuousBatcher(gen, slots=3)
        r1 = cb1.submit(toks[0, :4].tolist(), 6, temperature=0.8, seed=7)
        cb1.submit(toks[1, :5].tolist(), 6)       # a neighbor
        cb1.run_all()
        cb2 = ContinuousBatcher(gen, slots=1)     # alone, different slot
        r2 = cb2.submit(toks[0, :4].tolist(), 6, temperature=0.8, seed=7)
        cb2.run_all()
        assert cb1.result(r1) == cb2.result(r2)
        # and BOTH match the solo generator's sampled path — the
        # batcher's key derivation cannot drift without this tripping
        want = gen.generate(toks[:1, :4], 6, temperature=0.8,
                            seed=7)[0].tolist()
        assert cb1.result(r1) == want


class TestPagedKV:
    """Block-table KV pool (PagedContinuousBatcher): exact parity with
    the dense batcher, memory scaling with the pool budget instead of
    slots x max_len, admission backpressure on pool exhaustion, and the
    guard rails."""

    def _run(self, cb, gen, toks):
        rids = [cb.submit(toks[0, :4].tolist(), 8),
                cb.submit(toks[1, :6].tolist(), 6,
                          temperature=0.7, seed=11)]
        for _ in range(3):
            cb.tick()
        rids.append(cb.submit(toks[2, :3].tolist(), 9))
        cb.run_all()
        return [cb.pop_result(r) for r in rids]

    @pytest.mark.parametrize("ticks_per_dispatch", [1, 4])
    def test_matches_dense_batcher_exactly(self, f32_precision,
                                           ticks_per_dispatch):
        from veles_tpu.models.generate import (ContinuousBatcher,
                                               PagedContinuousBatcher)
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)
        dense = self._run(ContinuousBatcher(
            gen, slots=3, ticks_per_dispatch=ticks_per_dispatch),
            gen, toks)
        paged = self._run(PagedContinuousBatcher(
            gen, slots=3, ticks_per_dispatch=ticks_per_dispatch,
            block=4, pool_tokens=48), gen, toks)
        assert paged == dense
        # and both match the solo generator (greedy rows)
        want = gen.generate(toks[:1, :4], 8)[0].tolist()
        assert paged[0] == want

    def test_pool_backpressure_and_block_accounting(self, f32_precision):
        """A pool too small for all requests at once still completes
        every request (queued ones wait for freed blocks), and every
        block returns to the free list."""
        from veles_tpu.models.generate import (ContinuousBatcher,
                                               PagedContinuousBatcher)
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)
        cb = PagedContinuousBatcher(gen, slots=3, block=4,
                                    pool_tokens=16)   # 4 blocks total
        assert cb.free_blocks() == 4
        rids = [cb.submit(toks[i, :4].tolist(), 8) for i in range(3)]
        # 12 tokens/request = 3 blocks: only ONE fits at a time
        cb.tick()
        assert sum(r is not None for r in cb._slot_req) == 1
        cb.run_all()
        dense = ContinuousBatcher(gen, slots=3)
        for r in rids:
            dense.submit(toks[rids.index(r), :4].tolist(), 8)
        dense.run_all()
        for i, rid in enumerate(rids):
            assert cb.pop_result(rid) == dense.pop_result(i)
        assert cb.free_blocks() == 4          # all blocks returned

    def test_pool_memory_scales_with_budget_not_slots(self,
                                                      f32_precision):
        wf, toks = _lm_workflow(max_epochs=0)
        gen = LMGenerator(wf.trainer, max_len=16)
        from veles_tpu.models.generate import (ContinuousBatcher,
                                               PagedContinuousBatcher)
        dense = ContinuousBatcher(gen, slots=8)
        paged = PagedContinuousBatcher(gen, slots=8, block=4,
                                       pool_tokens=32)
        db = sum(l.nbytes for l in
                 jax.tree_util.tree_leaves(dense._caches))
        pb = sum(l.nbytes for l in
                 jax.tree_util.tree_leaves(paged._pool))
        # 8 slots x 16 tokens dense vs 32-token budget (+1 dummy block)
        assert pb <= db * (32 + 4) / (8 * 16) + 1e-9, (db, pb)

    def test_guard_rails(self, f32_precision):
        from veles_tpu.models.generate import PagedContinuousBatcher
        wf, _ = _lm_workflow(max_epochs=0)
        gen = LMGenerator(wf.trainer, max_len=16)
        with pytest.raises(ValueError, match="block"):
            PagedContinuousBatcher(gen, block=5)      # 16 % 5 != 0
        wfw, _ = _lm_workflow(max_epochs=0, window=6, impl="flash")
        genw = LMGenerator(wfw.trainer, max_len=16)
        with pytest.raises(ValueError, match="not pageable"):
            PagedContinuousBatcher(genw, block=4)

    def test_fused_and_gather_ticks_agree(self, f32_precision):
        """The fused tick (pool read through the block table inside the
        Pallas kernel — no dense gather) must produce the gather tick's
        exact token streams; both already match the dense batcher
        above.  Covers both flavors explicitly so a default flip can
        never silently drop one."""
        from veles_tpu.models.generate import PagedContinuousBatcher
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)
        fused_cb = PagedContinuousBatcher(gen, slots=3, block=4,
                                          pool_tokens=48, fused=True)
        gather_cb = PagedContinuousBatcher(gen, slots=3, block=4,
                                           pool_tokens=48, fused=False)
        assert fused_cb.fused and not gather_cb.fused
        assert self._run(fused_cb, gen, toks) == \
            self._run(gather_cb, gen, toks)

    def test_fused_rope_gqa_model(self, f32_precision):
        """Per-row rope rotation + GQA grouping through the fused
        path: every slot decodes at its own depth, so a broadcast
        position bug would corrupt exactly these streams."""
        from veles_tpu.models.generate import (ContinuousBatcher,
                                               PagedContinuousBatcher)
        wf, toks = _lm_workflow(max_epochs=8, pos="rope",
                                n_kv_heads=2)
        gen = LMGenerator(wf.trainer, max_len=16)
        dense = self._run(ContinuousBatcher(gen, slots=3), gen, toks)
        cb = PagedContinuousBatcher(gen, slots=3, block=4,
                                    pool_tokens=48)
        assert cb.fused
        assert self._run(cb, gen, toks) == dense

    def test_window_ge_max_len_falls_back_to_gather(self,
                                                    f32_precision):
        """window >= max_len keeps a LINEAR cache (pageable) but the
        fused kernel has no window mask — the batcher must auto-select
        the gather tick, matching the dense batcher as before."""
        from veles_tpu.models.generate import (ContinuousBatcher,
                                               PagedContinuousBatcher)
        wf, toks = _lm_workflow(max_epochs=8, window=16, impl="flash")
        gen = LMGenerator(wf.trainer, max_len=16)
        cb = PagedContinuousBatcher(gen, slots=3, block=4,
                                    pool_tokens=48, fused=True)
        assert not cb.fused                   # auto-fallback
        dense = self._run(ContinuousBatcher(gen, slots=3), gen, toks)
        assert self._run(cb, gen, toks) == dense

    def test_quant_pool_runs_fused_kernel(self, f32_precision):
        """int8 KV pools (QuantCache leaves) now run the fused
        kernel's QUANTIZED variant — int8 tiles streamed from HBM,
        dequantized in kernel with f32 accumulation — and the token
        streams must still match the dense int8 batcher (same math,
        narrower wire).  The gather tick stays reachable via
        fused=False and must agree too."""
        from veles_tpu.models.generate import (ContinuousBatcher,
                                               PagedContinuousBatcher)
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16, cache_dtype="int8")
        cb = PagedContinuousBatcher(gen, slots=3, block=4,
                                    pool_tokens=48, fused=True)
        assert cb.fused                       # quantized kernel path
        dense = self._run(ContinuousBatcher(gen, slots=3), gen, toks)
        assert self._run(cb, gen, toks) == dense
        gather = PagedContinuousBatcher(gen, slots=3, block=4,
                                        pool_tokens=48, fused=False)
        assert not gather.fused
        assert self._run(gather, gen, toks) == dense

    def test_engine_metrics_expose_free_blocks(self, f32_precision):
        from veles_tpu.services.restful import ContinuousEngine
        wf, toks = _lm_workflow(max_epochs=0)
        gen = LMGenerator(wf.trainer, max_len=16)
        eng = ContinuousEngine(gen, slots=2, paged_block=4,
                               pool_tokens=32)
        try:
            eng.submit(toks[0, :4].tolist(), 4)
            m = eng.metrics()
            assert m["free_kv_blocks"] == 8   # all returned post-serve
        finally:
            eng.stop()


class TestSpeculativeTicks:
    """Speculative continuous batching (speculative_k > 0): every
    active row verifies up to k drafted tokens per tick.  The bar is
    EXACT stream equality with the 1-token pool across greedy,
    sampled, and mid-flight-prompt rows — speculation may only change
    how many ticks a stream takes, never its tokens."""

    def _run(self, cb, toks):
        rids = [cb.submit(toks[0, :4].tolist(), 8),
                cb.submit(toks[1, :6].tolist(), 4,
                          temperature=0.7, seed=11)]
        for _ in range(2):
            cb.tick()
        rids.append(cb.submit(toks[2, :3].tolist(), 7))
        cb.run_all()
        return [cb.pop_result(r) for r in rids]

    @pytest.mark.parametrize("ticks_per_dispatch", [1, 4])
    def test_exact_parity_with_one_token_pool(self, f32_precision,
                                              ticks_per_dispatch):
        from veles_tpu.models.generate import ContinuousBatcher
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)
        plain = self._run(ContinuousBatcher(
            gen, slots=3, ticks_per_dispatch=ticks_per_dispatch),
            toks)
        spec = self._run(ContinuousBatcher(
            gen, slots=3, ticks_per_dispatch=ticks_per_dispatch,
            speculative_k=4), toks)
        assert spec == plain
        # and the greedy stream matches the solo generator
        assert spec[0] == gen.generate(toks[:1, :4], 8)[0].tolist()

    def test_speculation_actually_accelerates(self, f32_precision):
        """On a periodic LM (vocab 5: the ramp's bigrams repeat inside
        the context, so drafts copy a whole earlier cycle), the spec
        pool must finish in FEWER ticks — otherwise the chunk verify
        is dead weight."""
        from veles_tpu.models.generate import ContinuousBatcher
        wf, toks = _lm_workflow(max_epochs=8, vocab=5)
        gen = LMGenerator(wf.trainer, max_len=16)

        def count(cb):
            rid = cb.submit(toks[0, :6].tolist(), 6)
            n = 0
            while not cb.idle():
                cb.tick()
                n += 1
            return n, cb.pop_result(rid)

        n1, out1 = count(ContinuousBatcher(gen, slots=1))
        nk, outk = count(ContinuousBatcher(gen, slots=1,
                                           speculative_k=4))
        assert outk == out1
        assert nk < n1, (nk, n1)

    def test_guard_rails(self, f32_precision):
        from veles_tpu.models.generate import (ContinuousBatcher,
                                               PagedContinuousBatcher)
        wf, toks = _lm_workflow(max_epochs=0)
        gen = LMGenerator(wf.trainer, max_len=16)
        cb = ContinuousBatcher(gen, slots=2, speculative_k=4)
        with pytest.raises(ValueError, match="speculative"):
            cb.submit(toks[0, :8].tolist(), 8)    # 8+8+4 > 16
        with pytest.raises(ValueError, match="dense-pool only"):
            PagedContinuousBatcher(gen, block=4, speculative_k=4)
        with pytest.raises(ValueError, match="\\[2, 64\\]"):
            ContinuousBatcher(gen, speculative_k=1)
        with pytest.raises(ValueError, match="no room"):
            ContinuousBatcher(gen, speculative_k=15)   # 15+2 > 16
        from veles_tpu.services.restful import ContinuousEngine
        with pytest.raises(ValueError, match="dense-pool only"):
            # the engine must FORWARD the knob so the paged guard
            # fires instead of silently serving without speculation
            ContinuousEngine(gen, slots=2, paged_block=4,
                             pool_tokens=32, speculative_k=4)
        wfw, _ = _lm_workflow(max_epochs=0, window=6, impl="flash")
        genw = LMGenerator(wfw.trainer, max_len=16)
        with pytest.raises(ValueError, match="linear"):
            ContinuousBatcher(genw, speculative_k=4)

    def test_adapter_routing_through_spec_ticks(self, f32_precision):
        """Adapter grafting rides the chunk verify too: a banked model
        through the spec pool must match the plain pool per adapter."""
        from veles_tpu.models.generate import ContinuousBatcher
        wf, toks = _lm_workflow(max_epochs=8)
        wf2, _ = _lm_workflow(max_epochs=8, seed=77)
        # bank needs lora-shaped adapters — reuse the lora fixture
        # machinery cheaply: train a rank-2 adapter on wf's base
        from veles_tpu.models import zoo
        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.models.standard_workflow import StandardWorkflow
        prng.seed_all(31)
        r = np.random.RandomState(5)
        toks2 = ((np.arange(16)[None, :] * 3
                  + r.randint(0, 4, 192)[:, None]) % 13).astype(
                      np.int32)
        loader = FullBatchLoader(None, data=toks2, labels=toks2,
                                 minibatch_size=48,
                                 class_lengths=[0, 48, 144])
        awf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=13, d_model=32,
                                      n_heads=4, n_layers=2, lr=5e-2,
                                      dropout=0.0, lora_rank=2),
            loader=loader, loss="lm",
            decision_config={"max_epochs": 6}, name="spec-adapter")
        awf.initialize()
        awf.warm_start({"params": wf.trainer.host_params()})
        awf.run()
        gen = LMGenerator(wf.trainer, max_len=16)
        gen.load_adapter_bank([awf.trainer.host_params()])
        prompt = toks[0, :4].tolist()

        def run(cb):
            rids = [cb.submit(prompt, 7, adapter=a) for a in (0, 1)]
            cb.run_all()
            return [cb.pop_result(x) for x in rids]

        plain = run(ContinuousBatcher(gen, slots=2))
        spec = run(ContinuousBatcher(gen, slots=2, speculative_k=4))
        assert spec == plain
        assert plain[0] != plain[1]       # routing genuinely distinct


@pytest.mark.parametrize("speculative_k", [0, 4])
def test_stream_partials_progress_and_cleanup(f32_precision,
                                              speculative_k):
    """stream_partials=True: partial(rid) grows monotonically tick by
    tick along the final result's prefix, and is dropped at
    completion (long-running servers must not accumulate).  Holds
    under speculative ticks too (multi-token jumps per update)."""
    from veles_tpu.models.generate import ContinuousBatcher
    wf, toks = _lm_workflow(max_epochs=8)
    gen = LMGenerator(wf.trainer, max_len=16)
    cb = ContinuousBatcher(gen, slots=2, speculative_k=speculative_k)
    cb.stream_partials = True
    rid = cb.submit(toks[0, :4].tolist(), 6)
    seen = []
    while not cb.idle():
        cb.tick()
        p = cb.partial(rid)
        if p is not None:
            assert not seen or p[:len(seen[-1])] == seen[-1]
            seen.append(p)
    want = gen.generate(toks[:1, :4], 6)[0].tolist()
    assert cb.pop_result(rid) == want
    assert seen and seen[-1] == want[:len(seen[-1])]
    assert len(seen) >= 3                  # genuinely incremental
    assert cb.partial(rid) is None         # dropped at completion


def test_engine_fused_dispatch_serves_identical_streams(f32_precision):
    """ticks_per_dispatch>1 through the ENGINE (the remote-device
    throughput knob), on BOTH batcher flavors: responses — buffered
    AND streamed — must be identical to the per-token engine."""
    from veles_tpu.services.restful import ContinuousEngine
    wf, toks = _lm_workflow(max_epochs=8)
    gen = LMGenerator(wf.trainer, max_len=16)
    e1 = ContinuousEngine(gen, slots=2)
    e4 = ContinuousEngine(gen, slots=2, ticks_per_dispatch=4)
    e4p = ContinuousEngine(gen, slots=2, paged_block=4,
                           pool_tokens=48, ticks_per_dispatch=4)
    try:
        p = toks[0, :4].tolist()
        assert e4.cb.ticks_per_dispatch == 4      # dense wiring
        assert e4p.cb.ticks_per_dispatch == 4     # paged wiring
        a = list(map(int, e1.submit(p, 7)))
        assert a == list(map(int, e4.submit(p, 7)))
        assert a == list(map(int, e4p.submit(p, 7)))
        sa = [c for ch in e1.stream(p, 7) for c in ch]
        sb = [c for ch in e4.stream(p, 7) for c in ch]
        assert sa == sb == a[len(p):]
    finally:
        e1.stop(); e4.stop(); e4p.stop()


class TestPrefixCache:
    """Copy-on-write prefix sharing in the paged pool: concurrent
    requests with a common prompt prefix share its KV blocks.  The
    bar: token streams stay EXACTLY the no-sharing batcher's, block
    accounting reflects the sharing, and every block returns to the
    free list when the last owner releases."""

    def _mk(self, **kw):
        from veles_tpu.models.generate import PagedContinuousBatcher
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)
        cb = PagedContinuousBatcher(gen, slots=3, block=4,
                                    pool_tokens=48, prefix_cache=True,
                                    **kw)
        return cb, gen, toks

    @pytest.mark.parametrize("fused", [True, False])
    def test_shared_prefix_tokens_and_accounting(self, f32_precision,
                                                 fused):
        from veles_tpu.models.generate import PagedContinuousBatcher
        cb, gen, toks = self._mk(fused=fused)
        base = PagedContinuousBatcher(gen, slots=3, block=4,
                                      pool_tokens=48, fused=fused)
        # 9-token prompt, block 4: blocks 0-1 end before position
        # plen-1=8 (the first decode write) -> 2 shareable blocks
        prompt = toks[0, :9].tolist()
        free0 = cb.free_blocks()
        r1 = cb.submit(prompt, 4)             # 13 tokens -> 4 blocks
        r2 = cb.submit(prompt, 4)
        cb.tick()                             # both admitted
        # 4 + 4 blocks without sharing; 2 shared -> 6 allocated
        assert free0 - cb.free_blocks() == 6
        cb.run_all()
        b1 = base.submit(prompt, 4); b2 = base.submit(prompt, 4)
        base.run_all()
        assert cb.pop_result(r1) == base.pop_result(b1)
        assert cb.pop_result(r2) == base.pop_result(b2)
        assert cb.free_blocks() == free0      # all returned

    def test_divergent_second_block_shares_first_only(self,
                                                      f32_precision):
        cb, gen, toks = self._mk()
        p1 = toks[0, :9].tolist()
        p2 = list(p1[:4]) + toks[1, 4:9].tolist()
        assert p1[:4] == p2[:4] and p1[4:8] != p2[4:8]
        free0 = cb.free_blocks()
        r1 = cb.submit(p1, 4)
        r2 = cb.submit(p2, 4)
        cb.tick()
        # 4 + 4 blocks; of the 2 shareable only block 0 matches (the
        # prompts diverge inside block 1) -> 7 allocated
        assert free0 - cb.free_blocks() == 7
        cb.run_all()
        # each stream matches its own solo decode
        assert cb.pop_result(r1) == gen.generate(
            np.asarray([p1], np.int32), 4)[0].tolist()
        assert cb.pop_result(r2) == gen.generate(
            np.asarray([p2], np.int32), 4)[0].tolist()
        assert cb.free_blocks() == free0

    def test_release_order_keeps_shared_blocks_alive(self,
                                                     f32_precision):
        """First sharer finishes while the second still decodes — the
        shared blocks must survive until the LAST owner releases."""
        cb, gen, toks = self._mk()
        prompt = toks[0, :8].tolist()
        free0 = cb.free_blocks()
        r1 = cb.submit(prompt, 2)             # finishes first
        r2 = cb.submit(prompt, 6)
        cb.run_all()
        assert cb.pop_result(r2) == gen.generate(
            np.asarray([prompt], np.int32), 6)[0].tolist()
        assert cb.pop_result(r1) == gen.generate(
            np.asarray([prompt], np.int32), 2)[0].tolist()
        assert cb.free_blocks() == free0
        assert not cb._prefix_reg and not cb._prefix_ref

    def test_shorter_sharer_never_writes_a_shared_block(self,
                                                        f32_precision):
        """Sharers with DIFFERENT prompt lengths: a 12-token owner
        registers blocks 0-1, but an 8-token sharer's first decode
        write lands at position 7 — inside block 1 — so it may match
        block 0 ONLY.  (The regression: matching by coverage alone
        would let it write into the shared block.)"""
        cb, gen, toks = self._mk()
        pa = toks[0, :12].tolist()
        pb = pa[:8]
        free0 = cb.free_blocks()
        ra = cb.submit(pa, 3)                 # 15 tokens -> 4 blocks
        rb = cb.submit(pb, 4)                 # 12 tokens -> 3 blocks
        cb.tick()
        # 4 + 3 minus exactly ONE shared (block 0) -> 6 allocated
        assert free0 - cb.free_blocks() == 6
        cb.run_all()
        assert cb.pop_result(ra) == gen.generate(
            np.asarray([pa], np.int32), 3)[0].tolist()
        assert cb.pop_result(rb) == gen.generate(
            np.asarray([pb], np.int32), 4)[0].tolist()
        assert cb.free_blocks() == free0

    def test_matched_admission_skips_the_prefix_forward(
            self, f32_precision):
        """The compute-skip contract: a second same-prefix request must
        admit through the RESUME path (chunk from the matched
        boundary), never re-run the full prompt prefill — and still
        produce the exact no-sharing stream (covered above; here we
        pin WHICH path ran)."""
        cb, gen, toks = self._mk()
        prompt = toks[0, :9].tolist()
        calls = {"full": 0, "resume": 0}
        orig_full, orig_res = gen._prefill_fn, gen._prefill_resume_fn

        def spy_full(*a, **k):
            calls["full"] += 1
            return orig_full(*a, **k)

        def spy_res(*a, **k):
            calls["resume"] += 1
            return orig_res(*a, **k)

        gen._prefill_fn, gen._prefill_resume_fn = spy_full, spy_res
        try:
            r1 = cb.submit(prompt, 3)
            r2 = cb.submit(prompt, 3)
            cb.run_all()
        finally:
            gen._prefill_fn, gen._prefill_resume_fn = (orig_full,
                                                       orig_res)
        assert calls == {"full": 1, "resume": 1}, calls
        assert cb.pop_result(r1) == cb.pop_result(r2)

    def test_engine_exposes_prefix_gauges(self, f32_precision):
        from veles_tpu.services.restful import ContinuousEngine
        wf, toks = _lm_workflow(max_epochs=0)
        gen = LMGenerator(wf.trainer, max_len=16)
        eng = ContinuousEngine(gen, slots=2, paged_block=4,
                               pool_tokens=48, prefix_cache=True)
        try:
            eng.submit(toks[0, :9].tolist(), 3)
            m = eng.metrics()
            # post-serve: all owners released, registry drained
            assert m["prefix_shared_blocks"] == 0
            assert m["prefix_block_refs"] == 0
            assert m["free_kv_blocks"] == 12
        finally:
            eng.stop()

    def test_sharing_lets_requests_fit_a_tight_pool(self,
                                                    f32_precision):
        """Two same-prefix requests that canNOT fit independently admit
        CONCURRENTLY once sharing is on — the memory win, observable
        through admission."""
        from veles_tpu.models.generate import PagedContinuousBatcher
        wf, toks = _lm_workflow(max_epochs=8)
        gen = LMGenerator(wf.trainer, max_len=16)
        prompt = toks[0, :9].tolist()         # 4 blocks per request
        tight = PagedContinuousBatcher(gen, slots=2, block=4,
                                       pool_tokens=24)  # 6 blocks
        tight.submit(prompt, 4); tight.submit(prompt, 4)
        tight.tick()
        assert sum(r is not None for r in tight._slot_req) == 1
        shared = PagedContinuousBatcher(gen, slots=2, block=4,
                                        pool_tokens=24,
                                        prefix_cache=True)
        r1 = shared.submit(prompt, 4); r2 = shared.submit(prompt, 4)
        shared.tick()
        assert sum(r is not None for r in shared._slot_req) == 2
        shared.run_all(); tight.run_all()
        want = gen.generate(np.asarray([prompt], np.int32),
                            4)[0].tolist()
        assert shared.pop_result(r1) == want
        assert shared.pop_result(r2) == want


def test_paged_rejects_request_larger_than_pool(f32_precision):
    """A request needing more blocks than the whole pool must fail at
    submit — accepted-but-never-admittable would deadlock run_all()
    and hang the serving engine forever."""
    from veles_tpu.models.generate import PagedContinuousBatcher
    wf, toks = _lm_workflow(max_epochs=0)
    gen = LMGenerator(wf.trainer, max_len=16)
    cb = PagedContinuousBatcher(gen, slots=2, block=4, pool_tokens=8)
    with pytest.raises(ValueError, match="pool only has"):
        cb.submit(toks[0, :8].tolist(), 8)    # 4 blocks > 2-block pool
    assert cb.idle() and cb.free_blocks() == 2
