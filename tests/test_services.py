"""Service-layer tests (ref SURVEY §4 'Service tests': the reference POSTs
to a live RESTfulAPI unit and spins real servers on localhost — same
approach here with the stdlib client)."""

import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest
from sklearn.datasets import load_digits

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.services.plotting import (AccumulatingPlotter, MatrixPlotter,
                                         bus)
from veles_tpu.services.restful import RESTfulAPI
from veles_tpu.services.web_status import WebStatusServer


def _post(url, payload):
    req = urllib.request.Request(
        url, json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def _get(url):
    with urllib.request.urlopen(url) as resp:
        return resp.read()


class TestRESTful:
    @pytest.fixture(scope="class")
    def served_model(self):
        prng.seed_all(17)
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        y = d.target.astype(np.int32)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                                 class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=[{"type": "softmax", "output_sample_shape": 10,
                     "learning_rate": 0.2, "gradient_moment": 0.9}],
            loader=loader, decision_config={"max_epochs": 5},
            name="rest-model")
        wf.initialize()
        wf.run()
        fwd = wf.forward_fn()
        params = wf.trainer.params
        api = RESTfulAPI(lambda xx: np.asarray(fwd(params, xx)),
                         (64,), port=0)
        api.start()
        yield api, x, y
        api.stop()

    def test_post_list_codec(self, served_model):
        api, x, y = served_model
        out = _post("http://127.0.0.1:%d/service" % api.port,
                    {"input": x[:3].tolist()})
        probs = np.asarray(out["result"])
        assert probs.shape == (3, 10)
        np.testing.assert_allclose(probs.sum(1), 1.0, rtol=1e-4)
        assert (probs.argmax(1) == y[:3]).mean() >= 2 / 3

    def test_post_base64_codec(self, served_model):
        import base64
        api, x, _ = served_model
        payload = {"codec": "base64",
                   "input": base64.b64encode(x[:2].tobytes()).decode(),
                   "shape": [2, 64]}
        out = _post("http://127.0.0.1:%d/service" % api.port, payload)
        assert np.asarray(out["result"]).shape == (2, 10)

    def test_bad_input_returns_error_json(self, served_model):
        api, _, _ = served_model
        try:
            _post("http://127.0.0.1:%d/service" % api.port,
                  {"input": [[1.0, 2.0]]})
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "error" in json.loads(e.read())

    def test_generate_without_generator_is_an_error(self, served_model):
        api, _, _ = served_model
        try:
            _post("http://127.0.0.1:%d/service" % api.port,
                  {"input": [[1, 2, 3]], "generate": {"max_new": 2}})
            raise AssertionError("expected HTTP 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400

    @pytest.mark.slow
    def test_generate_endpoint_serves_int8_weights(self):
        """The REST generate path decodes through int8 W8A8 serving
        weights and returns the same greedy continuation as the float
        generator (trained model, peaked logits)."""
        from veles_tpu.models import zoo
        from veles_tpu.models.generate import LMGenerator

        prng.seed_all(23)
        r = np.random.RandomState(3)
        n, t, vocab = 128, 12, 11
        toks = ((np.arange(t)[None, :] + r.randint(0, 3, n)[:, None])
                % vocab).astype(np.int32)
        loader = FullBatchLoader(None, data=toks, labels=toks,
                                 minibatch_size=32,
                                 class_lengths=[0, 32, 96])
        wf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=vocab, d_model=16,
                                      n_heads=2, n_layers=1, lr=5e-3,
                                      dropout=0.0),
            loader=loader, loss="lm",
            decision_config={"max_epochs": 8}, name="rest-lm-int8")
        wf.initialize()
        wf.run()
        gen_q = LMGenerator(wf.trainer, max_len=t, weights="int8")
        gen_f = LMGenerator(wf.trainer, max_len=t)
        fwd = wf.forward_fn()
        params = wf.trainer.params
        api = RESTfulAPI(lambda xx: np.asarray(fwd(params, xx)), (t,),
                         port=0, generator=gen_q)
        api.start()
        try:
            out = _post("http://127.0.0.1:%d/service" % api.port,
                        {"input": toks[0, :6].tolist(),
                         "generate": {"max_new": 4}})
            res = np.asarray(out["result"])
            np.testing.assert_array_equal(
                res, gen_f.generate(toks[:1, :6], max_new=4))
        finally:
            api.stop()

    @pytest.mark.slow
    def test_generate_endpoint_serves_lm(self):
        from veles_tpu.models import zoo
        from veles_tpu.models.generate import LMGenerator

        prng.seed_all(23)
        r = np.random.RandomState(3)
        n, t, vocab = 128, 12, 11
        toks = ((np.arange(t)[None, :] + r.randint(0, 3, n)[:, None])
                % vocab).astype(np.int32)
        loader = FullBatchLoader(None, data=toks, labels=toks,
                                 minibatch_size=32,
                                 class_lengths=[0, 32, 96])
        wf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=vocab, d_model=16,
                                      n_heads=2, n_layers=1, lr=5e-3,
                                      dropout=0.0),
            loader=loader, loss="lm",
            decision_config={"max_epochs": 8}, name="rest-lm")
        wf.initialize()
        wf.run()
        fwd = wf.forward_fn()
        params = wf.trainer.params
        api = RESTfulAPI(lambda xx: np.asarray(fwd(params, xx)), (t,),
                         port=0,
                         generator=LMGenerator(wf.trainer, max_len=t))
        api.start()
        try:
            out = _post("http://127.0.0.1:%d/service" % api.port,
                        {"input": toks[0, :6].tolist(),
                         "generate": {"max_new": 4}})
            res = np.asarray(out["result"])
            assert res.shape == (1, 10)
            np.testing.assert_array_equal(res[0, :6], toks[0, :6])
            # the natural-but-wrong shorthand gets a descriptive 400,
            # not an opaque AttributeError
            try:
                _post("http://127.0.0.1:%d/service" % api.port,
                      {"input": toks[0, :6].tolist(), "generate": True})
                raise AssertionError("expected HTTP 400")
            except urllib.error.HTTPError as e:
                assert e.code == 400
                assert "options object" in json.loads(e.read())["error"]
        finally:
            api.stop()


@pytest.mark.slow
class TestGenerateBatching:
    def test_coalesced_requests_match_solo_and_bound_compiles(self):
        """batch_window > 0: concurrent heterogeneous generate requests
        merge into shared device calls, every client gets exactly the
        tokens a solo call would have produced, and compiles stay
        bounded to power-of-two buckets."""
        import threading as th

        from veles_tpu.models import zoo
        from veles_tpu.models.generate import LMGenerator

        prng.seed_all(29)
        r = np.random.RandomState(3)
        n, t, vocab = 128, 12, 11
        toks = ((np.arange(t)[None, :] + r.randint(0, 3, n)[:, None])
                % vocab).astype(np.int32)
        loader = FullBatchLoader(None, data=toks, labels=toks,
                                 minibatch_size=32,
                                 class_lengths=[0, 32, 96])
        wf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=vocab, d_model=16,
                                      n_heads=2, n_layers=1, lr=5e-3,
                                      dropout=0.0),
            loader=loader, loss="lm",
            decision_config={"max_epochs": 8}, name="rest-batch-lm")
        wf.initialize()
        wf.run()
        gen = LMGenerator(wf.trainer, max_len=t)
        solo = LMGenerator(wf.trainer, max_len=t)     # oracle, unbatched
        fwd = wf.forward_fn()
        params = wf.trainer.params
        api = RESTfulAPI(lambda xx: np.asarray(fwd(params, xx)), (t,),
                         port=0, generator=gen, batch_window=0.15)
        api.start()
        try:
            reqs = [
                {"input": toks[0, :6].tolist(),
                 "generate": {"max_new": 4}},
                {"input": toks[1, :4].tolist(),
                 "generate": {"max_new": 5, "temperature": 0.9,
                              "seed": 7}},
                {"input": toks[2, :8].tolist(),
                 "generate": {"max_new": 2, "temperature": 0.7,
                              "top_k": 3, "seed": 2}},
                {"input": toks[3, :5].tolist(),
                 "generate": {"max_new": 6, "temperature": 1.2,
                              "top_p": 0.9, "seed": 5}},
                {"input": toks[4, :7].tolist(),
                 "generate": {"max_new": 3}},
            ]
            results = [None] * len(reqs)

            def client(i):
                results[i] = _post(
                    "http://127.0.0.1:%d/service" % api.port, reqs[i])

            threads = [th.Thread(target=client, args=(i,))
                       for i in range(len(reqs))]
            for thr in threads:
                thr.start()
            for thr in threads:
                thr.join()
            for req, res in zip(reqs, results):
                opts = req["generate"]
                want = solo.generate(
                    np.asarray(req["input"], np.int32)[None],
                    max_new=opts["max_new"],
                    temperature=opts.get("temperature", 0.0),
                    seed=opts.get("seed", 0),
                    top_k=opts.get("top_k", 0),
                    top_p=opts.get("top_p", 1.0))
                np.testing.assert_array_equal(
                    np.asarray(res["result"]), want)
            # power-of-two buckets only — never one compile per size
            assert set(gen._compiled) <= {1, 2, 4, 8}, list(gen._compiled)
        finally:
            api.stop()


class TestWebStatus:
    def test_dashboard_and_apis(self):
        server = WebStatusServer(port=0)
        server.start()
        try:
            base = "http://127.0.0.1:%d" % server.port
            assert b"veles_tpu status" in _get(base + "/")
            status = json.loads(_get(base + "/api/status"))
            assert "workflows" in status
            out = _post(base + "/update", {"node": "r1", "epoch": 3})
            assert out["ok"]
            status = json.loads(_get(base + "/api/status"))
            assert status["remote"][-1]["update"]["epoch"] == 3
            assert isinstance(json.loads(_get(base + "/api/events")), list)
            # sparkline series: per-epoch metric events from the ring
            assert b"sparkline" in _get(base + "/")
            from veles_tpu.logger import events
            for ep, loss in ((1, 0.8), (2, 0.5), (3, 0.3)):
                events.add({"name": "epoch", "cat": "Decision",
                            "type": "single", "time": 0.0, "epoch": ep,
                            "valid_loss": loss})
            series = json.loads(_get(base + "/api/metrics"))
            assert series["valid_loss"] == [[1, 0.8], [2, 0.5], [3, 0.3]]
            # workflow graph + DOT (ref workflow SVG in status POSTs)
            from veles_tpu.plumbing import Repeater
            from veles_tpu.workflow import Workflow
            wf = Workflow(name="gwf")
            rpt = Repeater(wf)
            rpt.link_from(wf.start_point)
            wf.end_point.link_from(rpt)
            server.register(wf)
            g = json.loads(_get(base + "/api/graph"))["gwf"]
            names = {n["name"] for n in g["nodes"]}
            assert "Repeater" in names and len(g["edges"]) >= 2
            assert all({"cls", "runs", "time", "share"} <= set(n)
                       for n in g["nodes"])
            dot = _get(base + "/api/dot").decode()
            assert dot.startswith("digraph") and "Repeater" in dot
            # chrome-trace export: B/E pairs for begin/end, instants
            # for singles, µs timestamps
            from veles_tpu.logger import events as ev_ring
            ev_ring.add({"name": "unit", "cat": "T", "type": "begin",
                         "time": 10.0})
            ev_ring.add({"name": "unit", "cat": "T", "type": "end",
                         "time": 10.5, "n": 3})
            trace = json.loads(_get(base + "/api/trace"))
            recs = [t for t in trace["traceEvents"]
                    if t["name"] == "unit"]
            assert [t["ph"] for t in recs] == ["B", "E"]
            assert recs[1]["ts"] - recs[0]["ts"] == 5e5
            assert recs[1]["args"]["n"] == 3
            page = _get(base + "/")
            assert b"drawGraph" in page and b"drawTimeline" in page
        finally:
            server.stop()


class TestProfilerEndpoint:
    def test_on_demand_capture_serves_chrome_trace(self, tmp_path,
                                                   monkeypatch):
        """POST /api/profile opens a jax.profiler window over the live
        process; /api/profile/trace then serves the decompressed
        chrome-trace JSON (the on-chip step timeline, VERDICT r3 #10).

        Hermetic over a stubbed ``jax.profiler``: the real profiler's
        ``start_trace`` takes ~8 s to initialize in this sandbox (slow
        enough that a short capture window blows any reasonable poll
        deadline — a pre-existing tier-1 failure), and what this test
        owns is the ENDPOINT state machine — the capture slot's
        exclusivity, running→done lifecycle, and the gz trace being
        found and served decompressed — not jax's tracer."""
        import gzip
        import time as _time

        import jax

        calls = {"started": [], "stopped": 0}

        def fake_start(d):
            calls["started"].append(d)

        def fake_stop():
            calls["stopped"] += 1
            d = os.path.join(calls["started"][-1], "plugins",
                             "profile", "20260803")
            os.makedirs(d, exist_ok=True)
            with gzip.open(os.path.join(d, "host.trace.json.gz"),
                           "wb") as f:
                f.write(json.dumps(
                    {"traceEvents": [{"name": "stub"}]}).encode())

        monkeypatch.setattr(jax.profiler, "start_trace", fake_start)
        monkeypatch.setattr(jax.profiler, "stop_trace", fake_stop)

        from veles_tpu.config import root
        prev = root.common.dirs.get("profiles", None)
        root.common.dirs.profiles = str(tmp_path)
        server = WebStatusServer(port=0)
        server.start()
        try:
            base = "http://127.0.0.1:%d" % server.port
            out = _post(base + "/api/profile", {"seconds": 0.3})
            assert out["ok"] and out["dir"].startswith(str(tmp_path))
            # concurrent capture refused while one is running
            refused = _post(base + "/api/profile", {"seconds": 1})
            assert "error" in refused
            deadline = _time.time() + 15
            while _time.time() < deadline:
                state = json.loads(_get(base + "/api/profile"))
                if not state.get("running"):
                    break
                _time.sleep(0.05)
            assert not state.get("running") and "error" not in state
            assert calls["started"] == [out["dir"]]
            assert calls["stopped"] == 1
            trace = json.loads(_get(base + "/api/profile/trace"))
            assert trace["traceEvents"][0]["name"] == "stub"
        finally:
            server.stop()
            if prev is None:
                if "profiles" in root.common.dirs:
                    del root.common.dirs.profiles
            else:
                root.common.dirs.profiles = prev


class TestCrossRunLogBrowser:
    def test_sqlite_store_and_api(self, tmp_path):
        """Log duplication + cross-run browse (the reference's Mongo
        log store + web browser, ref veles/logger.py:292-331,
        web_status.py:113-200 — redesigned onto sqlite)."""
        import logging

        from veles_tpu.config import root
        from veles_tpu.logger import (duplicate_log_to, log_sessions,
                                      search_logs)
        db = str(tmp_path / "logs.sqlite3")
        prev_level = logging.getLogger().level
        logging.getLogger().setLevel(logging.INFO)
        # two "runs" land in one store
        h1 = duplicate_log_to(db, session="run-A", node="n0")
        logging.getLogger("TestUnit").info("alpha %d", 1)
        logging.getLogger("TestUnit").warning("needle in A")
        logging.getLogger().removeHandler(h1)
        h1.close()
        h2 = duplicate_log_to(db, session="run-B", node="n0")
        logging.getLogger("Other").info("needle in B")
        logging.getLogger().removeHandler(h2)
        h2.close()
        logging.getLogger().setLevel(prev_level)

        runs = log_sessions(db)
        assert [r["session"] for r in runs] == ["run-B", "run-A"]
        assert runs[1]["records"] == 2
        hits = search_logs(db, q="needle")
        assert {h["session"] for h in hits} == {"run-A", "run-B"}
        only_a = search_logs(db, session="run-A", q="needle")
        assert len(only_a) == 1 and only_a[0]["level"] == "WARNING"
        assert search_logs(db, level="warning") and \
            not search_logs(db, q="no-such-text")

        prev = root.common.web.get("log_db", None)
        root.common.web.log_db = db
        server = WebStatusServer(port=0)
        server.start()
        try:
            base = "http://127.0.0.1:%d" % server.port
            runs = json.loads(_get(base + "/api/logruns"))["runs"]
            assert len(runs) == 2
            out = json.loads(_get(base + "/api/logs?q=needle&session=run-B"))
            assert [l["session"] for l in out["logs"]] == ["run-B"]
            assert b"log browser" in _get(base + "/")
        finally:
            server.stop()
            root.common.web.log_db = prev


class TestPlotters:
    def test_accumulating_plotter_writes_png(self, tmp_path):
        from veles_tpu.workflow import Workflow
        wf = Workflow(name="plots")
        values = iter([0.5, 0.4, 0.3])
        p = AccumulatingPlotter(wf, source=lambda: next(values),
                                directory=str(tmp_path), ylabel="err")
        p.run()
        p.run()
        assert p.last_file and p.last_file.endswith(".png")
        import os
        assert os.path.getsize(p.last_file) > 500
        assert bus.snapshot()[-1]["kind"] == "curve"

    def test_matrix_plotter(self, tmp_path):
        from veles_tpu.workflow import Workflow
        wf = Workflow(name="plots2")
        m = np.eye(4) * 5
        p = MatrixPlotter(wf, source=lambda: m, directory=str(tmp_path))
        p.run()
        import os
        assert os.path.exists(p.last_file)


@pytest.mark.slow
class TestCLI:
    def test_sample_workflow_via_cli(self, tmp_path):
        result_file = str(tmp_path / "results.json")
        export_file = str(tmp_path / "model.zip")
        from veles_tpu.services.supervisor import run_with_startup_retry
        proc = run_with_startup_retry(
            [sys.executable, "-m", "veles_tpu", "samples/digits_mlp.py",
             "samples/digits_config.py", "--backend", "cpu",
             "--random-seed", "5",
             "--config-list", "root.digits.max_epochs=2",
             "--result-file", result_file, "--export", export_file],
            timeout=300,
            cwd=str(__import__("pathlib").Path(__file__).parent.parent))
        assert proc.returncode == 0, proc.stderr[-2000:]
        results = json.load(open(result_file))
        assert results["epochs"] == 2
        assert results["best_metric"] < 0.5
        from veles_tpu.services.export import import_workflow
        manifest, _ = import_workflow(export_file)
        assert manifest["name"] == "digits-mlp"

    def test_cli_snapshot_resume(self, tmp_path):
        snap_dir = str(tmp_path / "snaps")
        base = [sys.executable, "-m", "veles_tpu", "samples/digits_mlp.py",
                "--backend", "cpu", "--random-seed", "5"]
        cwd = str(__import__("pathlib").Path(__file__).parent.parent)
        from veles_tpu.services.supervisor import run_with_startup_retry
        p1 = run_with_startup_retry(
            base + ["--config-list", "root.digits.max_epochs=2"],
            timeout=300, cwd=cwd)
        assert p1.returncode == 0, p1.stderr[-2000:]


class TestWebFrontendEndpoint:
    def test_frontend_page_served(self):
        import urllib.request
        from veles_tpu.services.web_status import WebStatusServer
        srv = WebStatusServer(port=0)
        srv.start()
        try:
            with urllib.request.urlopen(
                    "http://127.0.0.1:%d/frontend" % srv.port) as r:
                html = r.read().decode()
            assert "command composer" in html and "random_seed" in html
        finally:
            srv.stop()


@pytest.mark.slow
class TestProfileFlag:
    def test_cli_profile_writes_trace(self, tmp_path):
        import os
        import subprocess
        import sys
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        out = str(tmp_path / "trace")
        from veles_tpu.services.supervisor import run_with_startup_retry
        r = run_with_startup_retry(
            [sys.executable, "-m", "veles_tpu", "samples/digits_mlp.py",
             "--backend", "cpu", "--random-seed", "3",
             "--config-list", "root.digits.max_epochs=1",
             "--profile", out],
            cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            timeout=420)
        assert r.returncode == 0, r.stderr[-2000:]
        found = [f for _, _, fs in os.walk(out) for f in fs]
        assert any(f.endswith((".pb", ".json.gz", ".xplane.pb"))
                   for f in found), found


class TestNewPlotters:
    """r2 service tails (VERDICT #9): multi-histogram + min-max envelope
    plotters, checked against golden PNGs (ref veles/tests/res/ golden
    plotter images)."""

    GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "res")

    def _check_golden(self, path, name):
        """Pixel comparison against the committed golden render.  A
        missing golden FAILS (a silently self-created golden would bake
        in whatever the current code draws); regenerate deliberately with
        VELES_REGEN_GOLDEN=1 after a reviewed rendering change."""
        from PIL import Image
        golden = os.path.join(self.GOLDEN, name)
        if os.environ.get("VELES_REGEN_GOLDEN") == "1":
            import shutil
            shutil.copy(path, golden)
        assert os.path.exists(golden), (
            "golden image %s missing — run with VELES_REGEN_GOLDEN=1 and "
            "commit it" % golden)
        got = np.asarray(Image.open(path).convert("RGB"), np.float32)
        want = np.asarray(Image.open(golden).convert("RGB"), np.float32)
        assert got.shape == want.shape
        assert np.abs(got - want).mean() < 1.0

    def test_multi_histogram_golden(self, tmp_path):
        from veles_tpu.services.plotting import MultiHistogramPlotter
        from veles_tpu.workflow import Workflow
        rng = np.random.RandomState(0)
        wf = Workflow(name="mh")
        p = MultiHistogramPlotter(
            wf, sources={"l0_weights": rng.normal(size=400),
                         "l1_weights": rng.uniform(size=300),
                         "l2_bias": rng.normal(2.0, 0.5, 200)},
            directory=str(tmp_path), name="multihist")
        p.run()
        assert bus.snapshot()[-1]["kind"] == "multi_histogram"
        assert len(bus.snapshot()[-1]["histograms"]) == 3
        self._check_golden(p.last_file, "golden_multihist.png")

    def test_minmax_golden(self, tmp_path):
        from veles_tpu.services.plotting import MinMaxPlotter
        from veles_tpu.workflow import Workflow
        rng = np.random.RandomState(1)
        wf = Workflow(name="mm")
        feed = iter(rng.normal(0, s, 100) for s in (1.0, 0.8, 0.5, 0.3))
        p = MinMaxPlotter(wf, source=lambda: next(feed), ylabel="weights",
                          directory=str(tmp_path), name="minmax")
        for _ in range(4):
            p.run()
        payload = bus.snapshot()[-1]
        assert payload["kind"] == "minmax"
        assert len(payload["mean"]) == 4
        assert all(a >= b for a, b in zip(payload["max"], payload["min"]))
        self._check_golden(p.last_file, "golden_minmax.png")


class TestNewPublishingBackends:
    def _workflow(self):
        from veles_tpu.units import TrivialUnit
        from veles_tpu.workflow import Workflow
        wf = Workflow(name="pub2")
        u = TrivialUnit(wf, name="trainer")
        u.run_count = 5
        u.run_time = 1.25
        return wf

    def test_pdf_backend(self, tmp_path):
        from veles_tpu.publishing import Publisher
        pub = Publisher(self._workflow(), backends=("pdf",),
                        directory=str(tmp_path), description="pdf test")
        pub.run()
        pdf = open(pub.written[0], "rb").read()
        assert pdf.startswith(b"%PDF")
        assert len(pdf) > 1000

    def test_confluence_backend(self, tmp_path):
        from veles_tpu.publishing import Publisher
        pub = Publisher(self._workflow(), backends=("confluence",),
                        directory=str(tmp_path))
        pub.run()
        text = open(pub.written[0]).read()
        assert "h1. pub2" in text
        assert "||unit||runs||total s||" in text
        assert "|trainer|5|1.250|" in text


class TestUnitStatsPlotter:
    def test_renders_units_and_memory(self, tmp_path):
        import jax.numpy as jnp

        from veles_tpu.services.plotting import UnitStatsPlotter
        from veles_tpu.units import TrivialUnit
        from veles_tpu.workflow import Workflow
        wf = Workflow(name="stats")
        for i, t in enumerate((0.5, 0.2, 0.9)):
            u = TrivialUnit(wf, name="unit%d" % i)
            u.run_count = i + 1
            u.run_time = t
        keep = jnp.ones((64, 64))   # something live on a device
        p = UnitStatsPlotter(wf, directory=str(tmp_path), name="ustats")
        p.run()
        payload = bus.snapshot()[-1]
        assert payload["kind"] == "unit_stats"
        assert payload["units"][0]["name"] == "unit2"   # sorted by time
        assert os.path.getsize(p.last_file) > 1000
        del keep


@pytest.mark.slow
class TestTracingFlags:
    def test_event_log_and_sync_run(self, tmp_path):
        """--event-log writes a JSONL event timeline; --sync-run runs
        the same training with per-step device sync (ref --sync-run +
        the Mongo event timeline)."""
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        log = str(tmp_path / "events.jsonl")
        from veles_tpu.services.supervisor import run_with_startup_retry
        r = run_with_startup_retry(
            [sys.executable, "-m", "veles_tpu", "samples/digits_mlp.py",
             "--backend", "cpu", "--random-seed", "3",
             "--config-list", "root.digits.max_epochs=1",
             "--event-log", log, "--sync-run"],
            cwd=repo, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            timeout=420)
        assert r.returncode == 0, r.stderr[-2000:]
        lines = [json.loads(ln) for ln in open(log)]
        assert len(lines) > 10
        assert any(e["name"] == "minibatch" for e in lines)
        assert all({"name", "cat", "type", "time"} <= set(e) for e in lines)


@pytest.mark.slow
class TestContinuousServing:
    def test_rest_endpoint_rides_the_continuous_engine(self):
        """continuous_slots>0: concurrent HTTP generate requests join
        the live slot pool and each gets its exact solo continuation
        (the ContinuousEngine REST integration)."""
        import threading as _threading

        from veles_tpu.models import zoo
        from veles_tpu.models.generate import LMGenerator

        prng.seed_all(23)
        r = np.random.RandomState(3)
        n, t, vocab = 128, 12, 11
        toks = ((np.arange(t)[None, :] + r.randint(0, 3, n)[:, None])
                % vocab).astype(np.int32)
        loader = FullBatchLoader(None, data=toks, labels=toks,
                                 minibatch_size=32,
                                 class_lengths=[0, 32, 96])
        wf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=vocab, d_model=16,
                                      n_heads=2, n_layers=1, lr=5e-3,
                                      dropout=0.0),
            loader=loader, loss="lm",
            decision_config={"max_epochs": 8}, name="rest-cont")
        wf.initialize()
        wf.run()
        gen = LMGenerator(wf.trainer, max_len=t)
        fwd = wf.forward_fn()
        params = wf.trainer.params
        api = RESTfulAPI(lambda xx: np.asarray(fwd(params, xx)), (t,),
                         port=0, generator=gen, continuous_slots=3)
        api.start()
        try:
            url = "http://127.0.0.1:%d/service" % api.port
            outs = {}

            def req(i, plen, max_new):
                outs[i] = _post(url, {
                    "input": toks[i, :plen].tolist(),
                    "generate": {"max_new": max_new}})["result"]

            threads = [_threading.Thread(target=req, args=a) for a in
                       ((0, 5, 4), (1, 6, 3), (2, 4, 5), (3, 5, 4))]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=120)
            for i, plen, max_new in ((0, 5, 4), (1, 6, 3), (2, 4, 5),
                                     (3, 5, 4)):
                want = gen.generate(toks[i:i + 1, :plen],
                                    max_new)[0].tolist()
                assert outs[i][0] == want, (i, outs[i][0], want)
        finally:
            api.stop()


    def test_rest_streaming_ndjson(self):
        """{"stream": true}: the response is NDJSON — {"tokens": [...]}
        lines whose concatenation equals the buffered result, then a
        {"done": true, "result": [...]} terminal line matching the
        solo decode.  Ineligible stream requests (beam, two rows, no
        engine) must 400."""
        import urllib.request

        from veles_tpu.models import zoo
        from veles_tpu.models.generate import LMGenerator

        prng.seed_all(23)
        r = np.random.RandomState(3)
        n, t, vocab = 128, 12, 11
        toks = ((np.arange(t)[None, :] + r.randint(0, 3, n)[:, None])
                % vocab).astype(np.int32)
        loader = FullBatchLoader(None, data=toks, labels=toks,
                                 minibatch_size=32,
                                 class_lengths=[0, 32, 96])
        wf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=vocab, d_model=16,
                                      n_heads=2, n_layers=1, lr=5e-3,
                                      dropout=0.0),
            loader=loader, loss="lm",
            decision_config={"max_epochs": 8}, name="rest-stream")
        wf.initialize()
        wf.run()
        gen = LMGenerator(wf.trainer, max_len=t)
        api = RESTfulAPI(lambda xx: xx, (t,), port=0, generator=gen,
                         continuous_slots=2)
        api.start()
        try:
            url = "http://127.0.0.1:%d/service" % api.port
            body = json.dumps({
                "input": toks[0, :5].tolist(),
                "generate": {"max_new": 5, "stream": True}}).encode()
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=300) as resp:
                assert resp.headers["Content-Type"] == \
                    "application/x-ndjson"
                lines = [json.loads(l)
                         for l in resp.read().decode().splitlines()]
            assert lines[-1]["done"] is True
            streamed = [tok for l in lines[:-1] for tok in l["tokens"]]
            want = gen.generate(toks[:1, :5], 5)[0].tolist()
            assert lines[-1]["result"] == want
            assert toks[0, :5].tolist() + streamed == want
            assert len(lines) >= 3        # genuinely incremental
            # ineligible: beam
            bad = json.dumps({
                "input": toks[0, :5].tolist(),
                "generate": {"max_new": 4, "stream": True,
                             "beam": 2}}).encode()
            req = urllib.request.Request(
                url, data=bad,
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=60)
                assert False, "beam stream must 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            api.stop()


@pytest.mark.slow
class TestServingSLO:
    """Serving-plane observability + SLO (r4 verdict #4): N concurrent
    clients against a small ContinuousEngine pool — every request
    completes (no starvation), tail latency is bounded, the metrics
    are truthful, and the /metrics endpoint + dashboard panel see it.
    Slow-tier budget (conftest.SLOW_MODULES note): replaces nothing but
    skips training — the untrained model costs compile-only (~30 s)."""

    T, VOCAB = 24, 11

    def _generator(self):
        from veles_tpu.models import zoo
        from veles_tpu.models.generate import LMGenerator

        prng.seed_all(29)
        toks = np.random.RandomState(5).randint(
            0, self.VOCAB, (8, self.T)).astype(np.int32)
        loader = FullBatchLoader(None, data=toks, labels=toks,
                                 minibatch_size=4,
                                 class_lengths=[0, 4, 4])
        wf = StandardWorkflow(
            layers=zoo.transformer_lm(vocab_size=self.VOCAB, d_model=16,
                                      n_heads=2, n_layers=1,
                                      dropout=0.0),
            loader=loader, loss="lm",
            decision_config={"max_epochs": 1}, name="slo-serve")
        wf.initialize()
        return LMGenerator(wf.trainer, max_len=self.T), toks

    def test_load_no_starvation_bounded_tails_truthful_metrics(self):
        import threading as _threading
        import time as _time

        from veles_tpu.services.restful import ContinuousEngine

        gen, toks = self._generator()
        eng = ContinuousEngine(gen, slots=4)
        try:
            n_req, max_new = 16, 8
            # warmup with the burst's EXACT shape: admission prefill
            # and the tick both compile per shape bucket, and a cold
            # compile mid-burst would stall every queued client
            eng.submit(toks[0, :6].tolist(), max_new)
            eng.reset_metrics()     # compile time must not skew SLOs
            done = [None] * n_req

            def client(i):
                done[i] = eng.submit(toks[i % 8, :6].tolist(), max_new)

            t0 = _time.monotonic()
            threads = [_threading.Thread(target=client, args=(i,))
                       for i in range(n_req)]
            for th in threads:
                th.start()
            for th in threads:
                th.join(timeout=180)
            wall_ms = (_time.monotonic() - t0) * 1e3
            # no starvation: every client completed with its tokens
            assert all(d is not None and len(d) == 6 + max_new
                       for d in done)

            m = eng.metrics()
            assert m["served"] == n_req
            assert m["queued"] == 0 and m["in_flight"] == 0
            assert m["slots"] == 4
            assert m["p50_ms_per_tok"] > 0
            assert m["agg_tokens_per_sec"] > 0
            # tail bounds: p99 queue-wait can't exceed the burst's own
            # wall time, and must be consistent with FIFO over
            # ceil(16/4) waves of ~max_new-token decodes (generous 6x
            # headroom for the 1-core CI box — catches unbounded waits,
            # not jitter)
            assert m["p99_queue_wait_ms"] < wall_ms
            p99_decode_ms = m["p99_ms_per_tok"] * max_new
            waves = -(-n_req // m["slots"])
            assert m["p99_queue_wait_ms"] < 6 * waves * p99_decode_ms, m
            # no straggler streams: worst decode rate within 25x median
            assert m["p99_ms_per_tok"] < 25 * m["p50_ms_per_tok"], m
        finally:
            eng.stop()

    def test_metrics_endpoint_and_dashboard_panel(self):
        gen, toks = self._generator()
        api = RESTfulAPI(lambda xx: xx, (self.T,), port=0,
                         generator=gen, continuous_slots=2)
        api.start()
        web = WebStatusServer(port=0)
        web.register_serving(api)
        try:
            url = "http://127.0.0.1:%d/service" % api.port
            _post(url, {"input": toks[0, :5].tolist(),
                        "generate": {"max_new": 3}})
            with urllib.request.urlopen(url + "/metrics") as r:
                m = json.loads(r.read())
            assert m["paths"]["continuous"] is True
            assert m["continuous"]["served"] == 1
            assert m["continuous"]["p50_tokens_per_sec"] > 0
            # the dashboard's /api/status carries the same snapshot
            s = web.status()
            assert s["serving"]["continuous"]["served"] == 1
        finally:
            api.stop()


class TestSqliteLogJournalMode:
    def test_local_path_uses_wal(self, tmp_path, monkeypatch):
        """A path the detector classifies local gets WAL.  The
        detector is stubbed: the suite must not depend on what
        filesystem the CI sandbox mounts /tmp on (some containers
        genuinely put it on 9p/overlay-over-network, where the real
        detector CORRECTLY disables WAL — the network-path test
        below covers that branch)."""
        import veles_tpu.logger as vl
        monkeypatch.setattr(vl, "_network_fs_type", lambda p: None)
        h = vl.SqliteLogHandler(str(tmp_path / "logs.db"), session="s1")
        mode = h._conn.execute("PRAGMA journal_mode").fetchone()[0]
        h.close()
        assert mode == "wal"

    def test_network_path_falls_back_to_rollback_journal(
            self, tmp_path, monkeypatch):
        """WAL needs a coherent shared-memory file — unsupported on
        network filesystems; a pod-shared log DB must use the rollback
        journal + busy retry instead (ADVICE r4)."""
        import veles_tpu.logger as vl
        monkeypatch.setattr(vl, "_network_fs_type", lambda p: "nfs4")
        h = vl.SqliteLogHandler(str(tmp_path / "logs.db"), session="s2")
        mode = h._conn.execute("PRAGMA journal_mode").fetchone()[0]
        busy = h._conn.execute("PRAGMA busy_timeout").fetchone()[0]
        h.close()
        assert mode == "delete"
        assert busy == 5000

    def test_network_fs_detector_local_and_boundary(self):
        """Detector semantics over a FAKE mounts table — hermetic, so
        the verdicts hold no matter what the CI sandbox really mounts
        (a 9p-backed /tmp used to fail the old real-path assertion
        while the detector was behaving exactly as designed)."""
        import veles_tpu.logger as vl
        real_open = open

        def fake_mounts(path, *a, **k):
            if path == "/proc/mounts":
                import io
                return io.StringIO(
                    "srv /data nfs4 rw 0 0\n"
                    "tmpfs /scratch tmpfs rw 0 0\n"
                    "overlay / overlay rw 0 0\n")
            return real_open(path, *a, **k)

        import builtins
        orig = builtins.open
        builtins.open = fake_mounts
        try:
            # a local-fs path is classified local (WAL stays on) —
            # if this fails, every pod log DB silently loses WAL
            assert vl._network_fs_type("/scratch/logs.db") is None
            assert vl._network_fs_type("/var/logs.db") is None
            assert vl._network_fs_type("/data/logs.db") == "nfs4"
            # component boundary: /data must not claim /database
            assert vl._network_fs_type("/database/logs.db") is None
        finally:
            builtins.open = orig


class TestBenchPanel:
    def test_api_bench_reports_measured_vs_predicted(self, tmp_path):
        """/api/bench joins the bench cache (fetch-synced on-chip
        numbers) with the roofline model's predictions — the
        dashboard's measurement-confirms-model view."""
        from veles_tpu.config import root

        cache = tmp_path / "bench.json"
        cache.write_text(json.dumps({
            "lm_large_mfu": 0.369, "value": 10611.7,
            "measured_at": "2026-08-01 10:30:54"}))
        root.common.web.bench_cache = str(cache)
        server = WebStatusServer(port=0)
        server.start()
        try:
            base = "http://127.0.0.1:%d" % server.port
            rep = json.loads(_get(base + "/api/bench"))
            assert rep["measured"]["lm_large_mfu"] == 0.369
            assert rep["measured_at"] == "2026-08-01 10:30:54"
            # predictions ride along when the model imports
            assert "lm_large_mfu" in rep.get("predicted", {})
            assert b'id="bench"' in _get(base + "/")
        finally:
            server.stop()
            del root.common.web.bench_cache
