"""Replica fleet tier (services.router.FleetRouter + the drain half of
services.lifecycle/restful): session affinity pins a session to one
replica, mid-stream failover splices to a byte-identical result, drain
refuses new work but completes in-flight (then deregisters), backoff
delays respect their bounds, and fleet churn lands in the flight ring
as serve.replica_up/down/failover/drain.  One tiny untrained
transformer is shared module-wide — replicas share the (read-only)
generator and differ only in engine state."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.services.router import FleetRouter
from veles_tpu.telemetry import flight

T, VOCAB = 16, 11
PROMPT = [1, 2, 3, 4, 5]


@pytest.fixture(scope="module")
def gen():
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models import zoo
    from veles_tpu.models.generate import LMGenerator
    from veles_tpu.models.standard_workflow import StandardWorkflow

    prng.seed_all(31)
    toks = np.random.RandomState(5).randint(
        0, VOCAB, (8, T)).astype(np.int32)
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=VOCAB, d_model=16,
                                  n_heads=2, n_layers=1, dropout=0.0),
        loader=FullBatchLoader(None, data=toks, labels=toks,
                               minibatch_size=4,
                               class_lengths=[0, 4, 4]),
        loss="lm", decision_config={"max_epochs": 1},
        name="router-serve")
    wf.initialize()
    return LMGenerator(wf.trainer, max_len=T)


def _post(router, body, timeout=120):
    conn = http.client.HTTPConnection(router.host, router.port,
                                      timeout=timeout)
    conn.request("POST", router.path, json.dumps(body),
                 {"Content-Type": "application/json"})
    return conn.getresponse(), conn


def _flight_count(kind, since=0.0):
    return sum(1 for e in flight.recorder.snapshot()
               if e["kind"] == kind and e["ts"] >= since)


class TestBackoffBounds:
    def test_exponential_with_jitter_and_cap(self):
        router = FleetRouter(backoff_base_ms=20, backoff_max_ms=200,
                             rng_seed=3)
        for attempt in range(8):
            uncapped = 0.020 * (2 ** attempt)
            cap = min(0.200, uncapped)
            for _ in range(50):
                d = router.backoff_delay(attempt)
                # jitter window: [0.5, 1.0) x the capped exponential
                assert 0.5 * cap <= d < cap or d == pytest.approx(
                    0.5 * cap)
        # jitter actually varies (not a constant backoff)
        assert len({round(router.backoff_delay(2), 9)
                    for _ in range(20)}) > 1


class TestRegistryAndHealth:
    def test_unreachable_replica_marked_down_with_event(self):
        t0 = time.time()
        router = FleetRouter(port=0, health_interval_ms=30)
        router.start()
        try:
            rid = router.register("http://127.0.0.1:1/service")
            assert router.replicas()[rid]["state"] == "up"  # optimistic
            deadline = time.monotonic() + 10
            while router.replicas()[rid]["state"] != "down" \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert router.replicas()[rid]["state"] == "down"
            assert _flight_count("serve.replica_up", t0) >= 1
            assert _flight_count("serve.replica_down", t0) >= 1
            # no live replica: routing sheds with Retry-After
            resp, conn = _post(router, {"input": PROMPT,
                                        "generate": {"max_new": 2}})
            assert resp.status == 503
            assert int(resp.headers["Retry-After"]) >= 1
            resp.read()
            conn.close()
            assert router.fleet_health()["state"] == "unavailable"
        finally:
            router.stop()


class TestSessionAffinity:
    def test_session_sticks_to_one_replica(self, gen):
        router = FleetRouter(port=0, health_interval_ms=50,
                             affinity="session")
        rids = router.spawn_local(gen, 2, continuous_slots=2)
        router.start()
        try:
            for _ in range(4):
                resp, conn = _post(router, {
                    "input": PROMPT, "session": "alpha",
                    "generate": {"max_new": 4}})
                assert resp.status == 200
                resp.read()
                conn.close()
            served = [a.engine.metrics()["served"]
                      for a in router._local_apis]
            # every request of the session landed on ONE replica (its
            # prefix cache keeps hitting); the other served nothing
            assert sorted(served) == [0, 4], served
            assert router._sessions["alpha"] in rids
            # sessionless requests round-robin across both
            for _ in range(4):
                resp, conn = _post(router, {
                    "input": PROMPT, "generate": {"max_new": 4}})
                assert resp.status == 200
                resp.read()
                conn.close()
            served = [a.engine.metrics()["served"]
                      for a in router._local_apis]
            assert min(served) >= 2, served
        finally:
            router.stop()


class TestMidStreamFailover:
    def test_splice_is_byte_identical_to_uninterrupted_run(self, gen):
        t0 = time.time()
        router = FleetRouter(port=0, health_interval_ms=10000,
                             affinity="session")
        rids = router.spawn_local(gen, 2, continuous_slots=2)
        router.start()
        try:
            # uninterrupted reference (replicas share weights: greedy
            # decode is identical on either one)
            resp, conn = _post(router, {"input": PROMPT,
                                        "session": "fo",
                                        "generate": {"max_new": 8}})
            assert resp.status == 200
            expected = json.loads(resp.read())["result"][0]
            conn.close()
            # warm BOTH replicas directly (failover must not pay a
            # first-compile mid-splice)
            for a in router._local_apis:
                a.engine.wait(a.engine.submit_async(PROMPT, 8))
            pinned = router._sessions["fo"]
            victim = router._local_apis[rids.index(pinned)]
            orig = victim.engine.cb.tick

            def slow_tick():
                time.sleep(0.05)
                return orig()

            victim.engine.cb.tick = slow_tick
            resp, conn = _post(router, {
                "input": PROMPT, "session": "fo",
                "generate": {"max_new": 8, "stream": True}})
            assert resp.status == 200
            got, result, resumed = list(PROMPT), None, None
            killed = False
            while True:
                raw = resp.fp.readline()
                if not raw:
                    break
                msg = json.loads(raw)
                if "tokens" in msg:
                    got.extend(msg["tokens"])
                    if not killed:
                        # kill the pinned replica's engine mid-stream:
                        # its in-flight streams fail terminally and the
                        # router must splice onto the survivor
                        killed = True
                        threading.Thread(target=victim.engine.stop,
                                         daemon=True).start()
                else:
                    assert msg.get("done"), msg
                    result, resumed = msg["result"], msg.get("resumed")
                    break
            conn.close()
            assert killed, "stream finished before the kill landed"
            assert resumed, "stream was never spliced"
            # the client saw ONE uninterrupted stream whose
            # concatenation equals the uninterrupted run exactly
            assert got == expected
            assert list(result) == expected
            m = router.metrics()["counters"]
            assert m["failovers"] >= 1
            assert m["resumed_streams"] >= 1
            assert _flight_count("serve.failover", t0) >= 1
            assert _flight_count("serve.replica_down", t0) >= 1
            # the session re-pinned onto the survivor
            assert router._sessions["fo"] != pinned
        finally:
            router.stop()


class TestDrain:
    def test_drain_refuses_new_work_completes_inflight_deregisters(
            self, gen):
        t0 = time.time()
        router = FleetRouter(port=0, health_interval_ms=50)
        (rid,) = router.spawn_local(gen, 1, continuous_slots=2)
        router.start()
        try:
            api = router._local_apis[0]
            resp, conn = _post(router, {"input": PROMPT,
                                        "generate": {"max_new": 8}})
            expected = json.loads(resp.read())["result"][0]
            conn.close()
            orig = api.engine.cb.tick

            def slow_tick():
                time.sleep(0.05)
                return orig()

            api.engine.cb.tick = slow_tick
            # in-flight stream, THEN drain
            resp, conn = _post(router, {
                "input": PROMPT,
                "generate": {"max_new": 8, "stream": True}})
            assert resp.status == 200
            first = json.loads(resp.fp.readline())
            assert "tokens" in first
            status, _ = self._admin(router, "/drain", {"replica": rid})
            assert status == 202
            # draining: new work is refused — by the replica (503 +
            # Retry-After) and, it being the only one, by the router
            r2, c2 = _post(router, {"input": PROMPT,
                                    "generate": {"max_new": 2}})
            assert r2.status == 503
            assert int(r2.headers["Retry-After"]) >= 1
            r2.read()
            c2.close()
            # ... but the in-flight stream completes, full result
            got = list(PROMPT) + list(first["tokens"])
            result = None
            while True:
                raw = resp.fp.readline()
                assert raw, "stream truncated by the drain"
                msg = json.loads(raw)
                if "tokens" in msg:
                    got.extend(msg["tokens"])
                else:
                    assert msg.get("done"), msg
                    result = msg["result"]
                    break
            conn.close()
            assert got == expected and list(result) == expected
            # the replica walks draining -> drained; the health loop
            # then deregisters it
            assert api.wait_drained(timeout=30)
            assert api.drain_state.state == "drained"
            deadline = time.monotonic() + 10
            while router.replicas() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert router.replicas() == {}
            assert _flight_count("serve.drain", t0) >= 1
            leaks = api.engine.leak_check()
            assert leaks["slots_busy"] == 0 and leaks["records"] == 0
        finally:
            router.stop()

    @staticmethod
    def _admin(router, endpoint, body):
        conn = http.client.HTTPConnection(router.host, router.port,
                                          timeout=30)
        try:
            conn.request("POST", router.path + endpoint,
                         json.dumps(body),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        finally:
            conn.close()


class TestSigtermDrainHandler:
    def test_handler_drains_then_exits_zero(self, gen, monkeypatch):
        """The standalone-serve SIGTERM path (restful.
        install_sigterm_drain): invoke the registered handler directly
        (sending a real SIGTERM would also exercise it, but the
        os._exit at the end must be intercepted either way)."""
        import signal

        from veles_tpu.services import restful
        from veles_tpu.services.restful import (RESTfulAPI,
                                                install_sigterm_drain)
        exited = []
        monkeypatch.setattr(restful.os, "_exit",
                            lambda code: exited.append(code))
        api = RESTfulAPI(lambda x: x, (T,), port=0, generator=gen,
                         continuous_slots=1)
        api.start()
        prev = signal.getsignal(signal.SIGTERM)
        try:
            install_sigterm_drain(api, grace_s=30)
            api.engine.wait(api.engine.submit_async(PROMPT, 2))
            handler = signal.getsignal(signal.SIGTERM)
            assert handler is not prev
            handler(signal.SIGTERM, None)
            deadline = time.monotonic() + 30
            while not exited and time.monotonic() < deadline:
                time.sleep(0.02)
            assert exited == [0]
            assert api.drain_state.state == "drained"
        finally:
            signal.signal(signal.SIGTERM, prev)
            api.stop()


class TestRequestScopedStreamErrors:
    def test_deadline_error_relays_without_flapping_replica(self, gen):
        """A mid-stream DeadlineExceeded is a REQUEST verdict, not a
        replica failure: the router must relay the error line to the
        client and neither mark the replica down nor resume the dead
        request on a survivor."""
        router = FleetRouter(port=0, health_interval_ms=10000)
        (rid,) = router.spawn_local(gen, 1, continuous_slots=1)
        router.start()
        try:
            api = router._local_apis[0]
            api.engine.wait(api.engine.submit_async(PROMPT, 2))
            orig = api.engine.cb.tick

            def slow_tick():
                time.sleep(0.05)
                return orig()

            api.engine.cb.tick = slow_tick
            blocker = api.engine.submit_async(PROMPT, 10)
            resp, conn = _post(router, {
                "input": PROMPT,
                "generate": {"max_new": 4, "stream": True,
                             "deadline_ms": 1}})
            assert resp.status == 200      # submit is eager, headers
            lines = [json.loads(raw)       # commit before the verdict
                     for raw in resp.fp.readlines() if raw.strip()]
            conn.close()
            api.engine.wait(blocker)
            terminal = lines[-1]
            assert terminal.get("kind") == "DeadlineExceeded", lines
            assert "error" in terminal
            # the replica is still routable; nothing failed over
            assert router.replicas()[rid]["state"] == "up"
            assert router.metrics()["counters"]["failovers"] == 0
        finally:
            router.stop()


class TestShedRouting:
    def test_replica_503_routes_around_then_propagates(self, gen):
        """One shedding replica + one healthy one: the router must
        route around the open valve; with EVERY replica shedding the
        client gets the 503 + the largest Retry-After."""
        router = FleetRouter(port=0, health_interval_ms=10000,
                             affinity="none")
        router.spawn_local(gen, 2, continuous_slots=2)
        router.start()
        try:
            a, b = router._local_apis
            resp, conn = _post(router, {"input": PROMPT,
                                        "generate": {"max_new": 2}})
            assert resp.status == 200
            resp.read()
            conn.close()
            # force replica A's shed valve open (and pin it: the
            # engine's control loop would close a forced valve within
            # one idle iteration)
            a.engine._shed.slo_ms = 100.0
            a.engine._shed._last_measure_ms = 450.0
            a.engine._shed._open = True
            a.engine._shed.update = lambda head_wait_ms=0.0: None
            for _ in range(4):      # round-robin hits A too: routed off
                r, c = _post(router, {"input": PROMPT,
                                      "generate": {"max_new": 2}})
                assert r.status == 200
                r.read()
                c.close()
            # a session pinned to the shedding replica keeps its pin
            # (transient valve blip must not cost the prefix cache) —
            # the request itself routes around to the healthy replica
            a_rid = next(rid for rid, rep in router.replicas().items()
                         if router._local_apis[0].port
                         == int(rep["url"].rsplit(":", 1)[1]
                                .split("/")[0]))
            router._sessions["sticky"] = a_rid
            r, c = _post(router, {"input": PROMPT, "session": "sticky",
                                  "generate": {"max_new": 2}})
            assert r.status == 200
            r.read()
            c.close()
            assert router._sessions["sticky"] == a_rid
            # both shedding: 503 propagates with the scaled hint
            b.engine._shed.slo_ms = 100.0
            b.engine._shed._open = True
            b.engine._shed.update = lambda head_wait_ms=0.0: None
            r, c = _post(router, {"input": PROMPT,
                                  "generate": {"max_new": 2}})
            assert r.status == 503
            # replica A's overshoot-scaled Retry-After (4.5 SLO
            # windows -> ceil to 5) dominates replica B's floor
            assert int(r.headers["Retry-After"]) >= 4
            r.read()
            c.close()
        finally:
            router.stop()


class TestDisaggregatedPrefill:
    """Prefill/decode roles: a long prompt's first leg runs on the
    prefill-role replica, the decode continues on the decode replica
    via the prefix-resume splice — ONE byte-identical client stream,
    and the handoff is observable (counter + serve.prefill_handoff)."""

    def _fleet(self, gen, **kw):
        kw.setdefault("health_interval_ms", 50)
        kw.setdefault("prefill_prompt_min", 8)
        kw.setdefault("prefill_handoff_new", 2)
        router = FleetRouter(port=0, rng_seed=3, **kw)
        router.start()
        router.spawn_local(gen, 2, continuous_slots=2,
                           roles=["prefill", "decode"])
        return router

    def test_stream_handoff_splice_byte_identical(self, gen,
                                                  f32_precision):
        t0 = time.time()
        long_prompt = list(range(1, 11))           # >= prompt_min 8
        expected = gen.generate(
            np.asarray([long_prompt], np.int32), 5)[0].tolist()
        router = self._fleet(gen)
        try:
            resp, conn = _post(router, {
                "input": long_prompt,
                "generate": {"max_new": 5, "stream": True}})
            assert resp.status == 200
            got = list(long_prompt)
            done = None
            while True:
                raw = resp.fp.readline()
                if not raw:
                    break
                msg = json.loads(raw)
                if "tokens" in msg:
                    got.extend(msg["tokens"])
                if msg.get("done"):
                    done = msg
                    break
            conn.close()
            assert got == expected
            assert done is not None and done["result"] == expected
            m = router.metrics()
            assert m["counters"]["prefill_handoffs"] >= 1
            assert _flight_count("serve.prefill_handoff", t0) >= 1
            # both tiers actually served: the prefill replica decoded
            # the handoff tokens, the decode replica the rest
            served = [a.engine.metrics()["served"]
                      for a in router._local_apis]
            assert all(s >= 1 for s in served), served
        finally:
            router.stop()

    def test_buffered_handoff_byte_identical(self, gen,
                                             f32_precision):
        long_prompt = list(range(1, 11))
        expected = gen.generate(
            np.asarray([long_prompt], np.int32), 6)[0].tolist()
        router = self._fleet(gen)
        try:
            resp, conn = _post(router, {
                "input": long_prompt, "generate": {"max_new": 6}})
            assert resp.status == 200
            out = json.loads(resp.read())
            conn.close()
            assert out["result"][0] == expected
            assert router.metrics()["counters"][
                "prefill_handoffs"] >= 1
        finally:
            router.stop()

    def test_short_prompt_skips_the_prefill_tier(self, gen,
                                                 f32_precision):
        router = self._fleet(gen)
        try:
            resp, conn = _post(router, {
                "input": [1, 2, 3], "generate": {"max_new": 4}})
            assert resp.status == 200
            resp.read()
            conn.close()
            served = [a.engine.metrics()["served"]
                      for a in router._local_apis]
            # replica 0 is the prefill tier: a short prompt must not
            # land there while the decode tier is up
            assert served[0] == 0 and served[1] == 1, served
        finally:
            router.stop()
