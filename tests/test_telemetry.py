"""veles_tpu.telemetry: metrics registry (Prometheus rendering, JSONL
sink), span aggregation through the scheduler, step telemetry and the
predicted-vs-measured MFU check from the staged trainer, the Watcher
memory gauges, and the veles-tpu-metrics summarizer."""

import json
import math
import re

import numpy as np
import pytest

from veles_tpu import telemetry
from veles_tpu.telemetry import MetricsRegistry


@pytest.fixture
def reg():
    return MetricsRegistry()


class TestRegistry:
    def test_counter_gauge_histogram_basics(self, reg):
        c = reg.counter("t_total", "a counter", ("kind",))
        c.inc(kind="a")
        c.inc(2.5, kind="a")
        c.inc(kind="b")
        assert c.value(kind="a") == 3.5
        assert c.value(kind="b") == 1.0
        with pytest.raises(ValueError):
            c.inc(-1, kind="a")
        g = reg.gauge("t_gauge")
        g.set(4.0)
        g.inc()
        g.dec(2.0)
        assert g.value() == 3.0
        h = reg.histogram("t_seconds", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        st = h.state()
        assert st["count"] == 3 and st["sum"] == pytest.approx(5.55)
        assert st["counts"] == [1, 1]     # 5.0 lands only in +Inf

    def test_create_or_return_and_type_mismatch(self, reg):
        c1 = reg.counter("same_name", "x", ("l",))
        assert reg.counter("same_name", "x", ("l",)) is c1
        with pytest.raises(ValueError):
            reg.gauge("same_name")
        with pytest.raises(ValueError):
            reg.counter("same_name", "x", ("other",))
        with pytest.raises(ValueError):
            reg.counter("bad name!")
        with pytest.raises(ValueError):
            c1.inc(wrong_label="x")
        h1 = reg.histogram("same_hist", buckets=(1.0, 2.0))
        assert reg.histogram("same_hist") is h1      # "don't care"
        with pytest.raises(ValueError):
            reg.histogram("same_hist", buckets=(0.5,))
        with pytest.raises(ValueError):
            reg.histogram("le_hist", labelnames=("le",))

    def test_prometheus_escaping_and_label_ordering(self, reg):
        g = reg.gauge("esc_gauge", 'help with \\ and\nnewline',
                      ("zeta", "alpha"))
        g.set(1.5, zeta='va"l\\ue\n2', alpha="plain")
        text = reg.render_prometheus()
        assert '# HELP esc_gauge help with \\\\ and\\nnewline' in text
        # label names sorted alphabetically regardless of declaration
        assert ('esc_gauge{alpha="plain",zeta="va\\"l\\\\ue\\n2"} 1.5'
                in text)
        assert "# TYPE esc_gauge gauge" in text

    def test_prometheus_deterministic_sample_order(self, reg):
        c = reg.counter("order_total", "", ("x",))
        for x in ("b", "a", "c"):
            c.inc(x=x)
        lines = [l for l in reg.render_prometheus().splitlines()
                 if l.startswith("order_total{")]
        assert lines == ['order_total{x="a"} 1', 'order_total{x="b"} 1',
                         'order_total{x="c"} 1']

    def test_prometheus_histogram_cumulative(self, reg):
        h = reg.histogram("lat_seconds", "", ("op",),
                          buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.5, 0.7, 20.0):
            h.observe(v, op="get")
        text = reg.render_prometheus()
        assert 'lat_seconds_bucket{op="get",le="0.1"} 1' in text
        assert 'lat_seconds_bucket{op="get",le="1"} 3' in text
        assert 'lat_seconds_bucket{op="get",le="10"} 3' in text
        assert 'lat_seconds_bucket{op="get",le="+Inf"} 4' in text
        assert 'lat_seconds_count{op="get"} 4' in text
        assert 'lat_seconds_sum{op="get"} 21.25' in text
        # every exposition line is name{labels} value or a comment
        for line in text.splitlines():
            assert re.match(
                r"(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*"
                r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? \S+)$", line)

    def test_sink_failure_disables_sink_not_the_run(self, reg,
                                                    tmp_path):
        path = str(tmp_path / "dead.jsonl")
        reg.open_sink(path)
        reg._sink.close()            # simulate ENOSPC/closed-fd
        reg.emit("probe", n=1)       # must not raise
        assert reg.sink_path is None
        reg.emit("probe", n=2)       # sink gone, ring still records
        assert [r["n"] for r in reg.records("probe")] == [1, 2]

    def test_jsonl_sink_emit_and_dump(self, reg, tmp_path):
        path = str(tmp_path / "m.jsonl")
        reg.open_sink(path)
        reg.counter("dump_total").inc(3)
        reg.histogram("dump_seconds", buckets=(1.0,)).observe(0.5)
        reg.emit("custom", answer=42)
        reg.dump_state()
        reg.close_sink()
        recs = [json.loads(l) for l in open(path)]
        kinds = {r["kind"] for r in recs}
        assert {"custom", "counter", "histogram"} <= kinds
        custom = [r for r in recs if r["kind"] == "custom"][0]
        assert custom["answer"] == 42 and "ts" in custom
        hist = [r for r in recs if r["kind"] == "histogram"][0]
        assert hist["count"] == 1 and hist["buckets"] == [[1.0, 1]]


class TestSpans:
    def test_span_context_feeds_aggregate_and_emits(self, reg):
        agg = telemetry.SpanAggregate("unit.run")
        with telemetry.span("unit.run:x", aggregate=agg, emit=True,
                            registry=reg, unit="x"):
            pass
        assert agg.count == 1 and agg.total > 0
        assert agg.min == agg.max == agg.last == agg.total
        recs = reg.records("span")
        assert recs and recs[0]["name"] == "unit.run:x"
        assert recs[0]["dur_s"] >= 0 and recs[0]["unit"] == "x"

    def test_unit_run_compat_properties(self):
        from veles_tpu.units import TrivialUnit
        u = TrivialUnit(None)
        u._run_wrapped()
        u._run_wrapped()
        assert u.run_count == u.span.count == 2
        assert u.run_time == u.span.total > 0
        u.run_count = 7          # legacy writers still work
        u.run_time = 1.25
        assert u.span.count == 7 and u.span.total == 1.25

    def test_workflow_spans_exclude_gated_and_skipped(self, tmp_path):
        from veles_tpu.mutable import Bool
        from veles_tpu.units import TrivialUnit
        from veles_tpu.workflow import Workflow
        wf = Workflow(name="spanwf")
        a = TrivialUnit(wf, name="runner")
        blocked = TrivialUnit(wf, name="blocked")
        skipped = TrivialUnit(wf, name="skipped")
        a.link_from(wf.start_point)
        blocked.link_from(a)
        blocked.gate_block = Bool(True)
        skipped.link_from(a)
        skipped.gate_skip = Bool(True)
        wf.end_point.link_from(a)
        wf.initialize()
        path = str(tmp_path / "spans.jsonl")
        telemetry.registry.open_sink(path)
        try:
            wf.run()
        finally:
            telemetry.registry.close_sink()
        recs = [json.loads(l) for l in open(path)]
        spans = [r for r in recs if r["kind"] == "span"]
        assert any(r["name"] == "workflow.run"
                   and r["workflow"] == "spanwf" for r in spans)
        units = {r["unit"] for r in spans if r["name"] == "unit.run"}
        assert "runner" in units and "EndPoint" in units
        # gated/skipped units never ran: no span record, and the
        # /metrics gauges carry no sample for them either
        assert "blocked" not in units and "skipped" not in units
        g = telemetry.registry.gauge(
            "veles_unit_runs", "unit.run() invocations, per unit "
            "(set at each workflow run end)", ("workflow", "unit"))
        labeled = {l["unit"] for l, _ in g.samples()
                   if l["workflow"] == "spanwf"}
        assert "runner" in labeled and "blocked" not in labeled


def _mnist_shaped_workflow(max_epochs=2):
    """784-100-10 MLP on synthetic data — the MNIST sample's exact
    workflow shape without the dataset mount."""
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.models.zoo import mnist_mlp
    prng.seed_all(11)
    rng = np.random.RandomState(11)
    data = rng.rand(400, 784).astype(np.float32)
    labels = rng.randint(0, 10, 400).astype(np.int32)
    loader = FullBatchLoader(None, data=data, labels=labels,
                             minibatch_size=100,
                             class_lengths=[0, 100, 300])
    return StandardWorkflow(
        layers=mnist_mlp(), loader=loader,
        decision_config={"max_epochs": max_epochs}, name="mnist-shaped")


@pytest.fixture(scope="module")
def mnist_metrics(tmp_path_factory):
    """One trained MNIST-shaped run with the sink open; yields the
    parsed records (the acceptance-criteria artifact, in-process)."""
    from veles_tpu import compile_cache
    compile_cache.install_metrics()
    path = str(tmp_path_factory.mktemp("telemetry") / "mnist.jsonl")
    wf = _mnist_shaped_workflow()
    wf.initialize()
    telemetry.registry.open_sink(path)
    try:
        wf.run()
        telemetry.registry.dump_state()
    finally:
        telemetry.registry.close_sink()
    return [json.loads(l) for l in open(path)]


class TestStagedStepTelemetry:
    def test_jsonl_contains_required_records(self, mnist_metrics):
        """The acceptance-criteria contract: workflow/unit/step spans,
        compile counters, device-memory gauges, and an MFU record with
        both predicted and measured."""
        kinds = {r["kind"] for r in mnist_metrics}
        assert {"span", "step", "mfu", "counter", "gauge"} <= kinds
        spans = [r for r in mnist_metrics if r["kind"] == "span"]
        assert any(r["name"] == "workflow.run" for r in spans)
        assert any(r["name"] == "unit.run"
                   and r.get("cls") == "StagedTrainer" for r in spans)
        names = {r.get("name") for r in mnist_metrics}
        assert "veles_compile_events_total" in names
        assert "veles_compile_seconds_total" in names
        assert "veles_device_live_bytes" in names
        mfu = [r for r in mnist_metrics if r["kind"] == "mfu"]
        assert mfu and "predicted" in mfu[-1] and "measured" in mfu[-1]

    def test_step_records_per_class(self, mnist_metrics):
        steps = [r for r in mnist_metrics if r["kind"] == "step"]
        by_class = {}
        for r in steps:
            by_class.setdefault(r["class"], []).append(r)
        assert set(by_class) == {"train", "validation"}
        train = by_class["train"][-1]
        assert train["steps"] == 3 and train["examples"] == 300
        assert train["wall_s"] > 0
        assert train["examples_per_sec"] == pytest.approx(
            train["examples"] / train["wall_s"])
        assert math.isfinite(train["loss"])

    def test_mfu_predicted_vs_measured_consistent(self, mnist_metrics):
        """MFU math pinned on the MNIST-shaped step: analytic FLOPs for
        784-100-10 at batch 100, measured == flops / (step_time * peak),
        ratio == measured/predicted — all within tolerance."""
        m = [r for r in mnist_metrics if r["kind"] == "mfu"][-1]
        flops = 3 * (2 * 100 * 784 * 100 + 2 * 100 * 100 * 10)
        assert m["flops_per_step"] == pytest.approx(flops)
        assert m["measured"] == pytest.approx(
            flops / (m["measured_step_ms"] / 1e3 * m["peak_flops"]),
            rel=1e-6)
        assert m["ratio"] == pytest.approx(
            m["measured"] / m["predicted"], rel=1e-6)
        assert 0 < m["predicted"] < 1
        assert m["warned"] == (m["ratio"] < m["warn_fraction"])
        # step wall time from the matching sweep agrees with the
        # measured step time the MFU check used (same sync point)
        train = [r for r in mnist_metrics if r["kind"] == "step"
                 and r["class"] == "train"][-1]
        assert m["measured_step_ms"] == pytest.approx(
            train["wall_s"] / train["steps"] * 1e3, rel=0.2) or \
            m["steps"] == train["steps"]

    def test_stop_clears_open_sweep_accumulators(self):
        """A run stopped mid-sweep must not leak its t0 into the next
        run's first sweep (idle-gap wall time → garbage MFU)."""
        wf = _mnist_shaped_workflow(max_epochs=1)
        wf.initialize()
        wf.trainer._note_step(2)
        assert wf.trainer._sweep_
        wf.trainer.stop()
        assert not wf.trainer._sweep_

    def test_price_staged_step_shape(self):
        wf = _mnist_shaped_workflow(max_epochs=1)
        wf.initialize()
        pricing = telemetry.mfu.price_staged_step(wf.trainer)
        assert pricing["param_elems"] == 784 * 100 + 100 * 10 + 110
        assert pricing["predicted_step_s"] > 0
        assert pricing["flops_per_step"] == pytest.approx(
            3 * (2 * 100 * 784 * 100 + 2 * 100 * 100 * 10))
        assert pricing["predicted_mfu"] == pytest.approx(
            pricing["flops_per_step"]
            / (pricing["predicted_step_s"] * pricing["peak_flops"]))


class TestWatcher:
    def test_record_sets_gauges_and_survives_cpu_stats(self, reg):
        import jax.numpy as jnp
        from veles_tpu.benchmark import Watcher
        keep = jnp.ones((128, 128))     # something live to census
        w = Watcher()
        per_device = w.record(reg)
        assert per_device and w.peak > 0
        g = reg.gauge("veles_device_live_bytes",
                      "live jax-array bytes per device "
                      "(per-shard census)", ("device",))
        assert any(v > 0 for _, v in g.samples())
        assert reg.gauge("veles_device_peak_bytes",
                         "census high-water mark across snapshots, "
                         "all devices").value() == w.peak
        # CPU memory_stats() is None/partial: the hbm gauges simply
        # carry no samples — no exception, no prints
        text = reg.render_prometheus()
        assert "veles_device_live_bytes" in text
        del keep


class TestTimeit:
    def test_mixed_pytree_blocks_on_array_leaves_only(self):
        import jax.numpy as jnp
        from veles_tpu.timeit2 import timeit

        def fn():
            return {"arrays": [jnp.ones(8), jnp.zeros(3)],
                    "meta": "not-an-array", "n": 3, "none": None}

        result, seconds = timeit(fn)
        assert seconds > 0
        assert result["meta"] == "not-an-array"

    def test_plain_python_result(self):
        from veles_tpu.timeit2 import timeit
        result, seconds = timeit(lambda: sum(range(10)))
        assert result == 45 and seconds >= 0


class TestMetricsCLI:
    def test_summarizer_text_and_json(self, mnist_metrics, tmp_path,
                                      capsys):
        from veles_tpu.telemetry import cli
        path = str(tmp_path / "sum.jsonl")
        with open(path, "w") as f:
            for r in mnist_metrics:
                f.write(json.dumps(r) + "\n")
        assert cli.main([path]) == 0
        text = capsys.readouterr().out
        assert "MFU vs" in text and "step telemetry" in text
        assert "unit spans" in text
        assert cli.main([path, "--format", "json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert summary["mfu"]["predicted"] > 0
        assert summary["steps"]["train"]["steps"] > 0
        assert any(u["unit"] == "StagedTrainer"
                   for u in summary["units"])
        assert summary["compile"]["events"] > 0

    def test_summarizer_missing_file(self, capsys):
        from veles_tpu.telemetry import cli
        assert cli.main(["/nonexistent/m.jsonl"]) == 2


class TestWebStatusTelemetry:
    def test_metrics_endpoint_and_panel_api(self):
        import urllib.request
        from veles_tpu.services.web_status import WebStatusServer
        telemetry.registry.counter(
            "web_probe_total", "endpoint probe").inc()
        server = WebStatusServer(port=0)
        server.start()
        try:
            base = "http://127.0.0.1:%d" % server.port
            with urllib.request.urlopen(base + "/metrics") as r:
                assert r.status == 200
                assert "text/plain" in r.headers["Content-Type"]
                body = r.read().decode()
            assert "# TYPE web_probe_total counter" in body
            assert re.search(r"^web_probe_total 1$", body, re.M)
            with urllib.request.urlopen(base + "/api/telemetry") as r:
                data = json.loads(r.read())
            assert any(s["name"] == "web_probe_total"
                       for s in data["metrics"])
            with urllib.request.urlopen(base + "/") as r:
                page = r.read().decode()
            assert "/metrics" in page and "telemetry" in page
        finally:
            server.stop()
