"""Pure pod-logic core of the pod survival tier (services.podmaster +
the snapshotter's cross-host agreement) — no subprocesses, no sockets:
checkpoint agreement over mixed/torn manifest sets, incarnation fencing,
hang classification from heartbeat/step inputs, and the pod-scope
crash-loop / deterministic-bug valves.  The end-to-end behavior (real
workers, coordinated restarts, bit-exactness) is gated by
tools/pod_chaos.py."""

import hashlib
import json
import os

import pytest

from veles_tpu.services.podmaster import (IncarnationFence, PodMaster,
                                          PodValves, classify_stall,
                                          merge_config_list,
                                          merge_worker_env)
from veles_tpu.services.snapshotter import (MANIFEST_SUFFIX,
                                            SnapshotReshardError,
                                            _commit_order_key,
                                            agree_commits,
                                            reshard_state,
                                            rollback_to_commit,
                                            scan_commits)
from veles_tpu.services.supervisor import is_startup_flake


# =====================================================================
# manifest scan + cross-host agreement + rollback
# =====================================================================

def _commit(directory, name, payload=b"state-bytes", epoch=None,
            incarnation=None, process_index=None, mtime=None,
            manifest=True, sha=None):
    """Fabricate one committed checkpoint + manifest sidecar the way the
    file snapshotter writes them (file bytes + sidecar with the file
    sha recorded)."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, name)
    with open(path, "wb") as f:
        f.write(payload)
    if manifest:
        man = {"format": 1, "created": mtime or 0.0,
               "file_sha256": sha if sha is not None
               else hashlib.sha256(payload).hexdigest()}
        if epoch is not None:
            man["epoch"] = epoch
        if incarnation is not None:
            man["incarnation"] = incarnation
        if process_index is not None:
            man["process_index"] = process_index
        with open(path + MANIFEST_SUFFIX, "w") as f:
            json.dump(man, f)
    if mtime is not None:
        os.utime(path, (mtime, mtime))
    return path


class TestScanCommits:
    def test_scan_validates_against_manifest_without_unpickling(
            self, tmp_path):
        d = str(tmp_path)
        _commit(d, "wf_1.pickle.gz", b"good", epoch=1, incarnation=0,
                process_index=1, mtime=100.0)
        out = scan_commits(d, "wf")
        assert set(out) == {"wf_1.pickle.gz"}
        e = out["wf_1.pickle.gz"]
        assert e["valid"] is True and e["error"] is None
        assert e["epoch"] == 1 and e["incarnation"] == 0
        assert e["process_index"] == 1 and e["mtime"] == 100.0

    def test_torn_file_scans_invalid(self, tmp_path):
        d = str(tmp_path)
        p = _commit(d, "wf_1.pickle.gz", b"full-payload", epoch=1)
        with open(p, "r+b") as f:       # tear it after the commit
            f.truncate(4)
        e = scan_commits(d, "wf")["wf_1.pickle.gz"]
        assert e["valid"] is False
        assert "mismatch" in e["error"]

    def test_manifestless_commit_is_unverified_not_trusted(
            self, tmp_path):
        d = str(tmp_path)
        _commit(d, "wf_1.pickle.gz", manifest=False)
        e = scan_commits(d, "wf")["wf_1.pickle.gz"]
        assert e["valid"] is None

    def test_scan_skips_noise(self, tmp_path):
        d = str(tmp_path)
        _commit(d, "wf_1.pickle.gz", epoch=1)
        _commit(d, "wf_2.pickle.gz.corrupt", manifest=False)
        _commit(d, "wf_3.pickle.gz.tmp-x", manifest=False)
        _commit(d, "other_1.pickle.gz", manifest=False)
        os.symlink("wf_1.pickle.gz",
                   os.path.join(d, "wf_current"))
        assert set(scan_commits(d, "wf")) == {"wf_1.pickle.gz"}

    def test_unreadable_directory_scans_empty(self, tmp_path):
        assert scan_commits(str(tmp_path / "missing"), "wf") == {}


class TestAgreement:
    def test_newest_commit_valid_everywhere_wins(self, tmp_path):
        for h in (0, 1):
            d = str(tmp_path / ("host%d" % h))
            _commit(d, "wf_1.pickle.gz", epoch=1, mtime=100.0)
            _commit(d, "wf_2.pickle.gz", epoch=2, mtime=200.0)
        reports = {h: scan_commits(str(tmp_path / ("host%d" % h)),
                                   "wf") for h in (0, 1)}
        agreed, detail = agree_commits(reports)
        assert agreed == "wf_2.pickle.gz"
        assert detail["wf_1.pickle.gz"]["rejected"] is None

    def test_commit_torn_on_one_host_rejected_pod_wide(self, tmp_path):
        """The tentpole property: a step-N commit present on host 0 but
        torn on host 1 must be rolled back POD-wide — the pod resumes
        from step N-1 even though host 0's copy of N is pristine."""
        d0, d1 = (str(tmp_path / "host0"), str(tmp_path / "host1"))
        for d in (d0, d1):
            _commit(d, "wf_1.pickle.gz", epoch=1, mtime=100.0)
            _commit(d, "wf_2.pickle.gz", epoch=2, mtime=200.0)
        with open(os.path.join(d1, "wf_2.pickle.gz"), "r+b") as f:
            f.truncate(3)
        agreed, detail = agree_commits(
            {0: scan_commits(d0, "wf"), 1: scan_commits(d1, "wf")})
        assert agreed == "wf_1.pickle.gz"
        assert "invalid" in detail["wf_2.pickle.gz"]["rejected"]
        assert detail["wf_2.pickle.gz"]["valid_on"] == [0]

    def test_commit_absent_on_one_host_rejected(self, tmp_path):
        d0, d1 = (str(tmp_path / "host0"), str(tmp_path / "host1"))
        _commit(d0, "wf_1.pickle.gz", epoch=1, mtime=100.0)
        _commit(d0, "wf_2.pickle.gz", epoch=2, mtime=200.0)
        _commit(d1, "wf_1.pickle.gz", epoch=1, mtime=100.0)
        agreed, detail = agree_commits(
            {0: scan_commits(d0, "wf"), 1: scan_commits(d1, "wf")})
        assert agreed == "wf_1.pickle.gz"
        assert "absent" in detail["wf_2.pickle.gz"]["rejected"]

    def test_manifestless_commits_never_agree(self, tmp_path):
        d0, d1 = (str(tmp_path / "host0"), str(tmp_path / "host1"))
        for d in (d0, d1):
            _commit(d, "wf_1.pickle.gz", manifest=False)
        agreed, detail = agree_commits(
            {0: scan_commits(d0, "wf"), 1: scan_commits(d1, "wf")})
        assert agreed is None
        assert "invalid/unverified" in \
            detail["wf_1.pickle.gz"]["rejected"]

    def test_no_commits_anywhere(self):
        agreed, detail = agree_commits({0: {}, 1: {}})
        assert agreed is None and detail == {}

    def test_epoch_orders_before_mtime(self, tmp_path):
        """A host's clock skew (newer mtime on an OLDER commit) must
        not win the agreement: the SPMD-lockstep epoch recorded in the
        manifest orders first."""
        for h in (0, 1):
            d = str(tmp_path / ("host%d" % h))
            _commit(d, "wf_a.pickle.gz", epoch=5, mtime=900.0)
            _commit(d, "wf_b.pickle.gz", epoch=6, mtime=100.0)
        agreed, _ = agree_commits(
            {h: scan_commits(str(tmp_path / ("host%d" % h)), "wf")
             for h in (0, 1)})
        assert agreed == "wf_b.pickle.gz"

    def test_commit_order_key_shape(self):
        assert _commit_order_key(
            "n", [{"epoch": 3, "mtime": 1.0},
                  {"epoch": 3, "mtime": 2.0}]) == (3, 2.0, "n")
        assert _commit_order_key("n", [{"mtime": 2.0}]) == (-1, 2.0,
                                                           "n")


class TestRollback:
    def test_rollback_quarantines_newer_and_invalid(self, tmp_path):
        d = str(tmp_path)
        _commit(d, "wf_1.pickle.gz", epoch=1, mtime=100.0)
        p2 = _commit(d, "wf_2.pickle.gz", epoch=2, mtime=200.0)
        with open(p2, "r+b") as f:
            f.truncate(2)               # invalid here
        _commit(d, "wf_3.pickle.gz", epoch=3, mtime=300.0)  # newer
        q = rollback_to_commit(d, "wf", "wf_1.pickle.gz")
        assert q == ["wf_2.pickle.gz", "wf_3.pickle.gz"]
        names = sorted(os.listdir(d))
        assert "wf_2.pickle.gz.corrupt" in names
        assert "wf_3.pickle.gz.corrupt" in names
        assert "wf_3.pickle.gz" not in names
        # _current points the respawned worker's --snapshot auto at the
        # pod-agreed state
        cur = os.path.join(d, "wf_current")
        assert os.path.islink(cur)
        assert os.readlink(cur) == "wf_1.pickle.gz"

    def test_rollback_to_none_quarantines_everything(self, tmp_path):
        d = str(tmp_path)
        _commit(d, "wf_1.pickle.gz", epoch=1, mtime=100.0)
        _commit(d, "wf_2.pickle.gz", epoch=2, mtime=200.0)
        q = rollback_to_commit(d, "wf", None)
        assert q == ["wf_1.pickle.gz", "wf_2.pickle.gz"]
        assert not os.path.exists(os.path.join(d, "wf_current"))

    def test_rollback_keeps_older_valid_commits(self, tmp_path):
        d = str(tmp_path)
        _commit(d, "wf_1.pickle.gz", epoch=1, mtime=100.0)
        _commit(d, "wf_2.pickle.gz", epoch=2, mtime=200.0)
        q = rollback_to_commit(d, "wf", "wf_2.pickle.gz")
        assert q == []
        assert os.path.exists(os.path.join(d, "wf_1.pickle.gz"))

    def test_explicit_quarantine_list_overrides_local_ordering(
            self, tmp_path):
        """Same-epoch commits tie-break on mtime and host clocks can
        disagree with the pod-wide ordering — the master's explicit
        list decides, so every host quarantines the SAME set."""
        d = str(tmp_path)
        _commit(d, "wf_1_0.5.pickle.gz", epoch=1, mtime=100.0)
        # locally newer than agreed by mtime, but pod-wide older: stays
        _commit(d, "wf_1_0.6.pickle.gz", epoch=1, mtime=300.0)
        # locally older, but the master says quarantine
        _commit(d, "wf_1_0.7.pickle.gz", epoch=1, mtime=50.0)
        q = rollback_to_commit(d, "wf", "wf_1_0.5.pickle.gz",
                               quarantine=["wf_1_0.7.pickle.gz"])
        assert q == ["wf_1_0.7.pickle.gz"]
        names = sorted(os.listdir(d))
        assert "wf_1_0.6.pickle.gz" in names
        assert "wf_1_0.7.pickle.gz.corrupt" in names

    def test_quarantine_list_still_drops_locally_invalid(
            self, tmp_path):
        d = str(tmp_path)
        _commit(d, "wf_1.pickle.gz", epoch=1, mtime=100.0)
        p = _commit(d, "wf_2.pickle.gz", epoch=2, mtime=50.0)
        with open(p, "r+b") as f:
            f.truncate(2)               # torn here, whatever the list
        q = rollback_to_commit(d, "wf", "wf_1.pickle.gz",
                               quarantine=[])
        assert q == ["wf_2.pickle.gz"]

    def test_provided_scan_skips_the_rescan(self, tmp_path,
                                            monkeypatch):
        """The agent hands rollback the scan it just computed for the
        agreement — the ring must NOT be re-hashed a second time."""
        from veles_tpu.services import snapshotter
        d = str(tmp_path)
        _commit(d, "wf_1.pickle.gz", epoch=1, mtime=100.0)
        _commit(d, "wf_2.pickle.gz", epoch=2, mtime=200.0)
        scan = scan_commits(d, "wf")
        monkeypatch.setattr(
            snapshotter, "scan_commits",
            lambda *a: pytest.fail("rollback re-scanned the ring"))
        q = rollback_to_commit(d, "wf", "wf_1.pickle.gz", scan=scan)
        assert q == ["wf_2.pickle.gz"]
        assert os.readlink(os.path.join(d, "wf_current")) \
            == "wf_1.pickle.gz"


# =====================================================================
# incarnation fencing
# =====================================================================

class TestIncarnationFence:
    def test_current_and_unversioned_admitted(self):
        f = IncarnationFence()
        assert f.admit(0, 0) is None
        assert f.admit(0, None) is None    # fresh agent, no life yet
        f.bump()
        assert f.admit(0, 1) is None

    def test_stale_registration_refused_and_recorded(self):
        f = IncarnationFence()
        f.bump()
        f.bump()
        assert f.admit(1, 0, now=123.0) == "stale-incarnation"
        assert f.refusals == [
            {"host": 1, "incarnation": 0, "current": 2,
             "reason": "stale-incarnation", "ts": 123.0}]

    def test_future_incarnation_refused(self):
        f = IncarnationFence()
        assert f.admit(0, 7) == "future-incarnation"


class TestOrphanFence:
    """The agent-startup zombie fence must verify the pidfile's pid
    still names the SAME process life before SIGKILLing it (a host
    reboot / pid wraparound hands the number to an innocent)."""

    def _agent(self, tmp_path):
        from veles_tpu.services.podmaster import PodAgent
        return PodAgent("127.0.0.1:1", 0, str(tmp_path))

    def _kills(self, monkeypatch):
        calls = []
        monkeypatch.setattr(os, "kill",
                            lambda pid, sig: calls.append((pid, sig)))
        return calls

    def test_proc_start_ticks_identifies_this_process(self):
        from veles_tpu.services.podmaster import _proc_start_ticks
        ticks = _proc_start_ticks(os.getpid())
        if ticks is None:
            pytest.skip("/proc unavailable")
        assert ticks == _proc_start_ticks(os.getpid())
        assert _proc_start_ticks(2 ** 30) is None

    def test_recycled_pid_not_fenced(self, tmp_path, monkeypatch):
        from veles_tpu.services.podmaster import _proc_start_ticks
        ticks = _proc_start_ticks(os.getpid())
        if ticks is None:
            pytest.skip("/proc unavailable")
        agent = self._agent(tmp_path)
        with open(agent.pidfile, "w") as f:
            f.write("%d %d" % (os.getpid(), ticks + 1))
        calls = self._kills(monkeypatch)
        agent._fence_orphan()
        assert (os.getpid(), 9) not in [
            (p, int(s)) for p, s in calls]
        assert not os.path.exists(agent.pidfile)

    def test_same_life_fenced(self, tmp_path, monkeypatch):
        import signal as _signal
        from veles_tpu.services.podmaster import _proc_start_ticks
        ticks = _proc_start_ticks(os.getpid())
        if ticks is None:
            pytest.skip("/proc unavailable")
        agent = self._agent(tmp_path)
        with open(agent.pidfile, "w") as f:
            f.write("%d %d" % (os.getpid(), ticks))
        calls = self._kills(monkeypatch)
        agent._fence_orphan()
        assert (os.getpid(), _signal.SIGKILL) in calls
        assert not os.path.exists(agent.pidfile)


# =====================================================================
# hang classification
# =====================================================================

class TestClassifyStall:
    def _hosts(self, now, progress_age=1.0, hb_age=0.1, alive=True):
        return {h: {"heartbeat_ts": now - hb_age,
                    "progress_ts": now - progress_age,
                    "worker_alive": alive} for h in (0, 1)}

    def test_healthy_pod_is_quiet(self):
        now = 1000.0
        assert classify_stall(now, self._hosts(now), 30.0, 10.0) is None

    def test_empty_view_is_quiet(self):
        assert classify_stall(0.0, {}, 30.0, 10.0) is None

    def test_silent_agent_is_stale_heartbeat(self):
        now = 1000.0
        hosts = self._hosts(now)
        hosts[1]["heartbeat_ts"] = now - 99.0
        out = classify_stall(now, hosts, 30.0, 10.0)
        assert out == {"cause": "stale-heartbeat", "hosts": [1]}

    def test_never_heartbeated_agent_is_stale(self):
        now = 1000.0
        hosts = self._hosts(now)
        hosts[0]["heartbeat_ts"] = None
        assert classify_stall(now, hosts, 30.0, 10.0)["hosts"] == [0]

    def test_pod_wide_flat_progress_latches_collective_hang(self):
        """The signature multi-controller failure: every worker alive
        and heartbeating, zero step/commit progress anywhere — one
        stalled host froze the pod inside a collective."""
        now = 1000.0
        out = classify_stall(now, self._hosts(now, progress_age=60.0),
                             30.0, 10.0)
        assert out == {"cause": "collective-hang", "hosts": [0, 1]}

    def test_one_live_progress_defuses_the_latch(self):
        now = 1000.0
        hosts = self._hosts(now, progress_age=60.0)
        hosts[1]["progress_ts"] = now - 1.0
        assert classify_stall(now, hosts, 30.0, 10.0) is None

    def test_dead_worker_is_not_a_hang(self):
        """A dead worker is the worker-exit trigger's job — the latch
        must not fire for it (double classification would race)."""
        now = 1000.0
        hosts = self._hosts(now, progress_age=60.0)
        hosts[0]["worker_alive"] = False
        assert classify_stall(now, hosts, 30.0, 10.0) is None


# =====================================================================
# pod-scope valves
# =====================================================================

class TestPodValves:
    def test_bounded_restarts_per_window(self):
        v = PodValves(max_restarts=3, window_seconds=100.0,
                      deterministic_limit=99)
        now = 1000.0
        for i in range(3):
            assert v.admit(now + i) == "respawn"
        assert v.admit(now + 3) == "crash-loop"

    def test_window_expiry_resets_the_budget(self):
        v = PodValves(max_restarts=2, window_seconds=10.0,
                      deterministic_limit=99)
        assert v.admit(0.0) == "respawn"
        assert v.admit(1.0) == "respawn"
        assert v.admit(100.0) == "respawn"   # old window expired

    def test_identical_signatures_without_progress_give_up(self):
        v = PodValves(max_restarts=99, window_seconds=600.0,
                      deterministic_limit=3)
        sig = ("0=crash:ValueError:boom",)
        assert v.admit(0.0, sig, progressed=False) == "respawn"
        assert v.admit(1.0, sig, progressed=False) == "respawn"
        assert v.admit(2.0, sig, progressed=False) == \
            "deterministic-bug"

    def test_progress_resets_the_deterministic_counter(self):
        """Same ordering as PR 8's Supervisor: progress resets the
        streak FIRST, then the current crash re-registers as streak 1
        — a pod that keeps committing is working, however it dies."""
        v = PodValves(max_restarts=99, window_seconds=600.0,
                      deterministic_limit=3)
        sig = ("0=crash:X",)
        assert v.admit(0.0, sig, progressed=False) == "respawn"
        assert v.admit(1.0, sig, progressed=False) == "respawn"
        assert v.admit(2.0, sig, progressed=True) == "respawn"
        # without the reset this round would be streak 4 and trip:
        assert v.admit(3.0, sig, progressed=False) == "respawn"
        assert v.admit(4.0, sig, progressed=False) == \
            "deterministic-bug"

    def test_changing_signatures_never_trip_deterministic(self):
        v = PodValves(max_restarts=99, window_seconds=600.0,
                      deterministic_limit=2)
        assert v.admit(0.0, ("0=a",), progressed=False) == "respawn"
        assert v.admit(1.0, ("0=b",), progressed=False) == "respawn"
        assert v.admit(2.0, ("0=a",), progressed=False) == "respawn"

    def test_uncounted_rounds_cost_nothing(self):
        """Graceful preemption / env startup flakes respawn unbounded:
        they must neither consume the window budget nor feed the
        deterministic counter."""
        v = PodValves(max_restarts=1, window_seconds=600.0,
                      deterministic_limit=2)
        for i in range(5):
            assert v.admit(float(i), None, counted=False) == "respawn"
        assert v.admit(10.0) == "respawn"    # budget still intact


# =====================================================================
# master-side policy helpers (constructed master, no sockets)
# =====================================================================

@pytest.fixture
def master(tmp_path):
    return PodMaster(
        ["python", "-m", "veles_tpu", "wf.py", "--snapshot", "auto"],
        n_hosts=2, workdir=str(tmp_path / "pod"), prefix="wf",
        spawn_agents=False, seed=7)


class TestPodMasterPolicy:
    def test_worker_spec_threads_identity_and_per_host_dirs(
            self, master):
        spec = master.worker_spec(1, incarnation=3,
                                  coordinator_port=4321)
        env = spec["env"]
        assert env["VELES_TPU_COORDINATOR"] == "127.0.0.1:4321"
        assert env["VELES_TPU_NUM_PROCESSES"] == "2"
        assert env["VELES_TPU_PROCESS_ID"] == "1"
        assert env["VELES_TPU_INCARNATION"] == "3"
        argv = spec["argv"]
        joined = " ".join(argv)
        assert "root.common.snapshot.per_host=True" in joined
        # agreement runs over file commits — the pod forces the
        # backend so an orbax/db config can't leave every commit
        # unverifiable on the first restart
        assert "root.common.snapshot.backend='file'" in joined
        assert repr(master.host_snapshot_dir(1)) in joined
        # the worker command itself is intact up front
        assert argv[:6] == ["python", "-m", "veles_tpu", "wf.py",
                            "--snapshot", "auto"]

    def test_host_extras_ride_the_config_list(self, tmp_path):
        m = PodMaster(["x", "--config-list", "root.a=1"], n_hosts=2,
                      workdir=str(tmp_path), prefix="wf",
                      host_extras={1: ["root.b=2"]},
                      spawn_agents=False)
        argv0 = m.worker_spec(0, 0, 1)["argv"]
        argv1 = m.worker_spec(1, 0, 1)["argv"]
        assert "root.b=2" not in argv0
        assert "root.b=2" in argv1
        assert "root.a=1" in argv1      # the command's own override
        assert argv1.count("--config-list") == 1

    def test_round_weight_flake_and_preempt_uncounted(self, master):
        master._round_cause = {"cause": "worker-exit"}
        master._round_exits = {0: {"kind": "env-flake"},
                               1: {"kind": "done"}}
        assert master._round_weight() == (False, True)
        master._round_exits = {0: {"kind": "preempt"},
                               1: {"kind": "preempt"}}
        assert master._round_weight() == (False, False)
        master._round_exits = {0: {"kind": "killed:SIGKILL"}}
        assert master._round_weight() == (True, False)
        # a hang/stale trigger is always counted, whatever the
        # (post-kill) exits look like
        master._round_cause = {"cause": "collective-hang"}
        master._round_exits = {0: {"kind": "env-flake"}}
        counted, _flake = master._round_weight()
        assert counted is True

    def test_round_weight_ignores_coordinated_kill_exits(self, master):
        """The survivor's killed:SIGKILL from OUR escalation must not
        turn a flake round into a counted one."""
        master._round_cause = {"cause": "worker-exit"}
        master._round_exits = {
            0: {"kind": "env-flake"},
            1: {"kind": "killed:SIGKILL", "during_kill": True}}
        assert master._round_weight() == (False, True)

    def test_missing_report_falls_back_to_pod_verified(
            self, master, monkeypatch):
        """A host silent through the agreement window is UNKNOWN, not
        empty: the pod resumes from the last checkpoint that was
        pod-verified on EVERY host, never from survivor-only agreement
        — and never quarantines everything off a transient partition."""
        calls = {}
        monkeypatch.setattr(
            master, "_spawn_all",
            lambda agreed, rollback, quarantine=None, hosts=None:
            calls.update(agreed=agreed, rollback=rollback,
                         quarantine=quarantine))
        master._last_agreed = "wf_1.pickle.gz"
        master._last_agreed_key = (1, 100.0, "wf_1.pickle.gz")
        master._round_cause = {"cause": "stale-heartbeat", "hosts": [1]}
        master._round_exits = {}
        master._round_started = 0.0
        master.hosts[0]["manifests"] = {
            "wf_1.pickle.gz": {"epoch": 1, "mtime": 100.0,
                               "valid": True},
            "wf_2.pickle.gz": {"epoch": 2, "mtime": 200.0,
                               "valid": True}}
        # host 1 never reported (61s > the 60s report window)
        master._tick_agreeing(1000.0)
        assert calls["agreed"] == "wf_1.pickle.gz"
        # the survivor's newer (pod-unverifiable) commit goes; the
        # pod-verified one stays everywhere
        assert calls["quarantine"] == ["wf_2.pickle.gz"]
        assert master.history[-1]["verdict"] == "respawn"

    def test_missing_report_without_pod_verified_gives_up(
            self, tmp_path, monkeypatch):
        """No pod-verified fallback + an incomplete view: a NON-elastic
        pod gives up with the data intact instead of quarantining every
        checkpoint (the elastic recycle toward a loss verdict is the
        test below)."""
        master = PodMaster(
            ["python", "-m", "veles_tpu", "wf.py", "--snapshot", "auto"],
            n_hosts=2, workdir=str(tmp_path / "pod"), prefix="wf",
            spawn_agents=False, seed=7, elastic=False)
        spawned = []
        monkeypatch.setattr(master, "_spawn_all",
                            lambda *a, **k: spawned.append(1))
        master._round_cause = {"cause": "worker-exit",
                               "exit": {"kind": "killed:SIGKILL"}}
        master._round_exits = {0: {"kind": "killed:SIGKILL", "rc": -9}}
        master._round_started = 0.0
        master.hosts[0]["manifests"] = {
            "wf_2.pickle.gz": {"epoch": 2, "mtime": 200.0,
                               "valid": True}}
        master._tick_agreeing(1000.0)
        assert master.phase == "giveup"
        assert master.history[-1]["verdict"] == "agreement-incomplete"
        assert not spawned

    def test_elastic_cold_start_recycles_toward_loss_not_giveup(
            self, master, monkeypatch):
        """The same incomplete view on an ELASTIC pod (agent-dead host,
        no pod-verified fallback — the cold-start host death) must NOT
        give up: it recycles the round so the absence strikes can
        accumulate toward the permanent-loss verdict, data intact."""
        spawned, restarted = [], []
        monkeypatch.setattr(master, "_spawn_all",
                            lambda *a, **k: spawned.append(1))
        monkeypatch.setattr(
            master, "_begin_restart",
            lambda trigger, now: restarted.append(trigger))
        master._round_cause = {"cause": "worker-exit",
                               "exit": {"kind": "killed:SIGKILL"}}
        master._round_exits = {0: {"kind": "killed:SIGKILL", "rc": -9}}
        master._round_started = 0.0
        master.hosts[0]["manifests"] = {
            "wf_2.pickle.gz": {"epoch": 2, "mtime": 200.0,
                               "valid": True}}
        master._tick_agreeing(1000.0)
        assert master.phase != "giveup"
        assert not spawned
        assert restarted == [{"cause": "host-absent-retry",
                              "hosts": [1]}]
        assert master.absence_strikes[1] == 1
        # the struck host is not yet lost — one strike short
        assert not master.lost_hosts

    def test_full_reports_fresh_start_quarantines_all(
            self, master, monkeypatch):
        """With EVERY host reporting and no commit valid everywhere,
        the fresh start is legitimate — the master's explicit list
        covers every name."""
        calls = {}
        monkeypatch.setattr(
            master, "_spawn_all",
            lambda agreed, rollback, quarantine=None, hosts=None:
            calls.update(agreed=agreed, quarantine=quarantine))
        master._round_cause = {"cause": "worker-exit",
                               "exit": {"kind": "killed:SIGKILL"}}
        master._round_exits = {0: {"kind": "killed:SIGKILL", "rc": -9}}
        master._round_started = 0.0
        master.hosts[0]["manifests"] = {
            "wf_1.pickle.gz": {"epoch": 1, "mtime": 100.0,
                               "valid": True}}
        master.hosts[1]["manifests"] = {}   # reported: really empty
        master._tick_agreeing(1000.0)
        assert calls["agreed"] is None
        assert calls["quarantine"] == ["wf_1.pickle.gz"]

    def test_unverifiable_ring_gives_up_with_data_intact(
            self, master, monkeypatch):
        """A ring that is unverifiable EVERYWHERE (valid None on every
        host that has it — a manifestless or foreign-backend ring,
        e.g. a workflow hard-coding the orbax snapshotter past the
        forced file backend) is data the agreement cannot judge:
        quarantining it to *.corrupt and resuming from scratch would
        silently destroy the run — give up with the data intact."""
        spawned = []
        monkeypatch.setattr(master, "_spawn_all",
                            lambda *a, **k: spawned.append(1))
        master._round_cause = {"cause": "worker-exit",
                               "exit": {"kind": "killed:SIGKILL"}}
        master._round_exits = {0: {"kind": "killed:SIGKILL", "rc": -9}}
        master._round_started = 0.0
        for h in (0, 1):
            master.hosts[h]["manifests"] = {
                "wf_1.pickle.gz": {"epoch": 1, "mtime": 100.0,
                                   "valid": None}}
        master._tick_agreeing(1000.0)
        assert master.phase == "giveup"
        assert master.history[-1]["verdict"] == "agreement-unverifiable"
        assert not spawned

    def test_flake_streak_and_startup_shaped_log(self, master,
                                                 tmp_path):
        from veles_tpu.services.podmaster import PodAgent
        # a quiet startup log reads as a flake candidate...
        small = tmp_path / "small.log"
        small.write_text("[auto-resume] x\njax.distributed init\n")
        assert PodAgent._startup_shaped_log(str(small))
        # ...a traceback or a big log never does
        tb = tmp_path / "tb.log"
        tb.write_text("banner\nTraceback (most recent call last):\n")
        assert not PodAgent._startup_shaped_log(str(tb))
        big = tmp_path / "big.log"
        big.write_bytes(b"x" * 20000)
        assert not PodAgent._startup_shaped_log(str(big))
        assert not PodAgent._startup_shaped_log(
            str(tmp_path / "missing.log"))
        assert not PodAgent._startup_shaped_log(None)


class TestMergeConfigList:
    def test_appends_fresh_flag(self):
        assert merge_config_list(["a", "b"], ["root.x=1"]) == \
            ["a", "b", "--config-list", "root.x=1"]

    def test_inserts_into_existing_flag(self):
        out = merge_config_list(
            ["a", "--config-list", "root.x=1", "--flag", "v"],
            ["root.y=2"])
        assert out == ["a", "--config-list", "root.x=1", "root.y=2",
                       "--flag", "v"]

    def test_no_statements_is_identity(self):
        argv = ["a", "--config-list", "root.x=1"]
        assert merge_config_list(argv, []) == argv


class TestMergeWorkerEnv:
    def test_appends_to_inherited_xla_flags(self):
        """The pod's device-count flag must not clobber the operator's
        own XLA_FLAGS — appended last, so it wins a conflict."""
        env = merge_worker_env(
            {"XLA_FLAGS": "--xla_dump_to=/tmp/d", "HOME": "/h"},
            {"XLA_FLAGS": "--xla_force_host_platform_device_count=2",
             "VELES_TPU_PROCESS_ID": "1"})
        assert env["XLA_FLAGS"] == ("--xla_dump_to=/tmp/d "
                                    "--xla_force_host_platform_"
                                    "device_count=2")
        assert env["HOME"] == "/h"
        assert env["VELES_TPU_PROCESS_ID"] == "1"

    def test_no_inherited_flags_uses_spec_verbatim(self):
        env = merge_worker_env({}, {"XLA_FLAGS": "--a=1"})
        assert env["XLA_FLAGS"] == "--a=1"

    def test_spec_without_flags_leaves_inherited(self):
        env = merge_worker_env({"XLA_FLAGS": "--a=1"}, {"B": "2"})
        assert env["XLA_FLAGS"] == "--a=1" and env["B"] == "2"


class TestStartupFlakeFingerprint:
    def test_abort_signal_with_zero_output_is_a_flake(self):
        assert is_startup_flake(-11, "", "")          # SIGSEGV
        assert is_startup_flake(-6, "", "")           # SIGABRT
        assert is_startup_flake(134, "", "")          # shell spelling

    def test_abort_after_startup_prints_is_still_a_flake(self):
        """The abort can land just AFTER the first prints — the
        auto-resume banner, glibc's own corruption lines — so the
        fingerprint is startup-shaped output (small, no traceback),
        not zero output."""
        assert is_startup_flake(-6, "", "[auto-resume] no _current — "
                                        "fresh start\ncorrupted "
                                        "double-linked list\n")
        assert is_startup_flake(-6, "", "malloc(): invalid size "
                                        "(unsorted)\n")
        assert is_startup_flake(134, "", "free(): invalid next size "
                                         "(normal)\n")
        assert is_startup_flake(-11, "log line", "")

    def test_traceback_output_or_benign_rc_is_not(self):
        assert not is_startup_flake(
            -6, "", "Traceback (most recent call last):\n  boom\n")
        assert not is_startup_flake(-11, "x" * 20000, "")  # real run
        assert not is_startup_flake(1, "", "")
        assert not is_startup_flake(0, "", "")
        assert not is_startup_flake(-15, "", "")      # SIGTERM: a kill
        assert not is_startup_flake(1, "", "double free or corruption\n")

    def test_uncaptured_streams_never_read_as_flake(self):
        assert not is_startup_flake(-11, None, None)


# =====================================================================
# elastic tier: resize valve bucket, strike -> degrade -> re-expand
# =====================================================================

class TestResizeValveBucket:
    def test_resize_rounds_never_consume_crash_loop_budget(self):
        """A planned topology change (degrade/re-expand) lives in its
        own bucket: ten resizes through a max_restarts=1 valve and the
        crash-loop budget is still intact."""
        v = PodValves(max_restarts=1, window_seconds=600.0,
                      deterministic_limit=2)
        for i in range(10):
            assert v.admit(float(i), resize=True) == "respawn"
        assert v.resize_restarts == 10
        assert v.admit(20.0) == "respawn"     # budget untouched

    def test_resize_rounds_never_feed_the_deterministic_counter(self):
        v = PodValves(max_restarts=99, window_seconds=600.0,
                      deterministic_limit=2)
        sig = ("0=crash:X",)
        assert v.admit(0.0, sig, progressed=False) == "respawn"
        # a resize between two identical crashes must not advance the
        # signature streak (it passes the same signature the round saw)
        assert v.admit(1.0, sig, progressed=False,
                       resize=True) == "respawn"
        assert v.admit(2.0, sig, progressed=False) == \
            "deterministic-bug"     # streak 2 -> trips, not earlier


class TestElasticPolicy:
    def _prime_round(self, master, cause=None):
        master._round_cause = cause or {"cause": "stale-heartbeat",
                                        "hosts": [1]}
        master._round_exits = {}
        master._round_started = 0.0

    def test_strike_limit_classifies_loss_and_degrades(
            self, master, monkeypatch):
        """The final strike degrades the pod: one resize-bucketed
        restart on the survivors from THEIR agreement, the lost host's
        frozen ring no longer voting."""
        calls = {}
        monkeypatch.setattr(
            master, "_spawn_all",
            lambda agreed, rollback, quarantine=None, hosts=None:
            calls.update(agreed=agreed, quarantine=quarantine,
                         hosts=hosts))
        self._prime_round(master)
        master.absence_strikes[1] = master.loss_strikes - 1
        master.hosts[0]["manifests"] = {
            "wf_3.pickle.gz": {"epoch": 3, "mtime": 300.0,
                               "valid": True}}
        master._tick_agreeing(1000.0)
        assert master.lost_hosts == {1}
        assert calls["hosts"] == [0]
        assert calls["agreed"] == "wf_3.pickle.gz"
        rec = master.history[-1]
        assert rec["resize"] == "degrade"
        assert rec["cause"] == "host-loss:1"
        assert rec["counted"] is False
        assert rec["verdict"] == "respawn"
        assert master.valves.resize_restarts == 1
        assert master.status()["degraded"] is True
        assert master.status()["lost_hosts"] == [1]

    def test_last_survivor_is_never_classified_lost(
            self, tmp_path, monkeypatch):
        """With every live host absent there is nowhere to degrade TO:
        that is a master partition, not a host loss — the old
        agreement-incomplete giveup holds, data intact."""
        master = PodMaster(
            ["python", "-m", "veles_tpu", "wf.py"], n_hosts=2,
            workdir=str(tmp_path / "pod"), prefix="wf",
            spawn_agents=False, seed=7)
        spawned = []
        monkeypatch.setattr(master, "_spawn_all",
                            lambda *a, **k: spawned.append(1))
        self._prime_round(master, {"cause": "stale-heartbeat",
                                   "hosts": [0, 1]})
        master.absence_strikes[0] = 99
        master.absence_strikes[1] = 99
        master._tick_agreeing(1000.0)
        assert not master.lost_hosts
        assert master.phase == "giveup"
        assert not spawned

    def test_returning_agent_triggers_capacity_restore(self, master):
        class FakeConn:
            alive = True
        now = 1000.0
        master.lost_hosts = {1}
        master.phase = "running"
        master.hosts[0].update(heartbeat_ts=now, progress_ts=now,
                               worker_alive=True)
        master.hosts[1]["conn"] = FakeConn()
        trig = master._detect_trigger(now)
        assert trig == {"cause": "capacity-restore", "hosts": [1]}
        # a failed re-expansion blocks the trigger until the agent
        # re-registers (agent_up clears the block)
        master._reexpand_blocked = {1}
        assert master._detect_trigger(now) is None

    def test_blocked_reexpand_retries_after_cooldown(self, master):
        """A block whose agent simply STAYS connected never sees a
        fresh agent_up — the timestamped block expires after the
        cooldown so the pod cannot run degraded forever on healthy
        capacity."""
        class FakeConn:
            alive = True
        now = 1000.0
        master.lost_hosts = {1}
        master.phase = "running"
        master.hosts[0].update(heartbeat_ts=now, progress_ts=now,
                               worker_alive=True)
        master.hosts[1]["conn"] = FakeConn()
        master._reexpand_blocked = {1}
        master._reexpand_block_ts = {1: now}
        assert master._detect_trigger(now) is None
        cooldown = max(60.0, master.loss_window_s)
        trig = master._detect_trigger(now + cooldown + 1.0)
        assert trig == {"cause": "capacity-restore", "hosts": [1]}
        assert not master._reexpand_blocked

    def test_reexpand_waits_for_returned_report_then_skips_transfer(
            self, master, monkeypatch):
        """The returned host's manifest report decides whether the
        agreed commit must be shipped: the agreement waits for it
        (window-bounded) instead of replicating off a report still in
        flight — a host that already holds the commit valid (shared
        storage, short absence) re-expands with NO transfer."""
        sent, calls = [], {}
        monkeypatch.setattr(
            master, "_send",
            lambda host, obj: (sent.append((host, obj)), True)[1])
        monkeypatch.setattr(
            master, "_spawn_all",
            lambda agreed, rollback, quarantine=None, hosts=None:
            calls.update(agreed=agreed, hosts=hosts))
        master.lost_hosts = {1}
        self._prime_round(master, {"cause": "capacity-restore",
                                   "hosts": [1]})
        master.hosts[0]["manifests"] = {
            "wf_5.pickle.gz": {"epoch": 5, "mtime": 500.0,
                               "valid": True}}
        # the survivors have all reported; the returned host has not —
        # the round WAITS (window-bounded) instead of deciding `need`
        master._tick_agreeing(1.0)
        assert master.phase != "replicating" and not calls
        # ... the report lands: the host holds the agreed commit VALID,
        # so re-expansion proceeds without any control-plane transfer
        master.hosts[1]["manifests"] = {
            "wf_5.pickle.gz": {"epoch": 5, "mtime": 500.0,
                               "valid": True}}
        master._tick_agreeing(2.0)
        assert not [m for _h, m in sent
                    if m["type"] == "fetch_commit"]
        assert calls["hosts"] == [0, 1]
        assert not master.lost_hosts

    def test_reexpand_replicates_agreed_commit_then_spawns_full(
            self, master, monkeypatch):
        """The re-expand agreement round: survivors vote, the returned
        host's stale ring does not hold the agreed commit, so the
        master ships it source->returning host over the control plane
        and only then spawns the full topology."""
        sent, calls = [], {}
        monkeypatch.setattr(
            master, "_send",
            lambda host, obj: (sent.append((host, obj)), True)[1])
        monkeypatch.setattr(
            master, "_spawn_all",
            lambda agreed, rollback, quarantine=None, hosts=None:
            calls.update(agreed=agreed, hosts=hosts))
        master.lost_hosts = {1}
        self._prime_round(master, {"cause": "capacity-restore",
                                   "hosts": [1]})
        master.hosts[0]["manifests"] = {
            "wf_5.pickle.gz": {"epoch": 5, "mtime": 500.0,
                               "valid": True}}
        master.hosts[1]["manifests"] = {
            "wf_2.pickle.gz": {"epoch": 2, "mtime": 200.0,
                               "valid": True}}   # frozen at the loss
        master._tick_agreeing(1000.0)
        assert master.phase == "replicating"
        assert master.history[-1]["resize"] == "reexpand"
        assert master.valves.resize_restarts == 1
        fetches = [m for _h, m in sent if m["type"] == "fetch_commit"]
        assert len(fetches) == 1 and \
            fetches[0]["name"] == "wf_5.pickle.gz"
        assert not calls    # no spawn before the transfer lands
        # source agent answers with the commit bytes
        master._handle_event("commit_data", 0, {
            "ok": True, "files": {"wf_5.pickle.gz": "QUJD"}})
        master._tick_replicating(1001.0)
        pushes = [(h, m) for h, m in sent
                  if m["type"] == "push_commit"]
        assert [h for h, _m in pushes] == [1]
        # returning host confirms the write -> re-expand completes
        master._handle_event("commit_pushed", 1, {"ok": True})
        master._tick_replicating(1002.0)
        assert not master.lost_hosts
        assert master.absence_strikes[1] == 0
        assert calls["agreed"] == "wf_5.pickle.gz"
        assert calls["hosts"] == [0, 1]
        assert master.status()["degraded"] is False

    def test_failed_replication_stays_degraded_not_down(
            self, master, monkeypatch):
        """A push failure must neither wedge the pod in `replicating`
        nor take it down: it re-spawns the SURVIVORS (still degraded)
        and blocks re-expansion until the agent re-registers."""
        calls = {}
        monkeypatch.setattr(master, "_send",
                            lambda host, obj: True)
        monkeypatch.setattr(
            master, "_spawn_all",
            lambda agreed, rollback, quarantine=None, hosts=None:
            calls.update(hosts=hosts))
        master.lost_hosts = {1}
        master._replication = {
            "source": 0, "need": [1], "returned": [1],
            "agreed": "wf_5.pickle.gz", "quarantine": [],
            "targets": [0, 1], "files": {"wf_5.pickle.gz": "QUJD"},
            "sent": True, "pushed": set(), "failed": [],
            "error": None}
        master.phase = "replicating"
        master._round_started = 0.0
        master._handle_event("commit_pushed", 1,
                             {"ok": False, "error": "disk full"})
        master._tick_replicating(1.0)
        assert master.lost_hosts == {1}          # still degraded
        assert master._reexpand_blocked == {1}
        assert master._reexpand_block_ts == {1: 1.0}   # cooldown armed
        assert calls["hosts"] == [0]             # survivors respawned
        # a fresh registration clears the block for a retry
        master._handle_event("agent_up", 1, {})
        assert not master._reexpand_blocked
        assert not master._reexpand_block_ts

    def test_degraded_worker_spec_remaps_identity_and_surfaces_size(
            self, tmp_path):
        """A degraded incarnation's workers get contiguous process ids
        over the survivor set, a shrunken world size, and the pod-size
        block threaded into config for /api/health."""
        master = PodMaster(
            ["python", "-m", "veles_tpu", "wf.py"], n_hosts=3,
            workdir=str(tmp_path / "pod"), prefix="wf",
            spawn_agents=False, seed=7)
        master.lost_hosts = {1}
        spec = master.worker_spec(2, incarnation=4,
                                  coordinator_port=4321, live=[0, 2])
        env = spec["env"]
        assert env["VELES_TPU_NUM_PROCESSES"] == "2"
        assert env["VELES_TPU_PROCESS_ID"] == "1"   # contiguous remap
        joined = " ".join(spec["argv"])
        assert "root.common.pod.elastic_mesh=True" in joined
        assert "root.common.pod.size=2" in joined
        assert "root.common.pod.total=3" in joined
        assert "root.common.pod.degraded=True" in joined
        assert "root.common.pod.lost_hosts=[1]" in joined

    def test_full_size_worker_spec_is_not_degraded(self, master):
        spec = master.worker_spec(1, incarnation=0,
                                  coordinator_port=4321)
        joined = " ".join(spec["argv"])
        assert env_of(spec)["VELES_TPU_NUM_PROCESSES"] == "2"
        assert "root.common.pod.degraded=False" in joined
        assert "root.common.pod.size=2" in joined


def env_of(spec):
    return spec["env"]


class TestAgentCommitReplication:
    def _agent(self, tmp_path):
        from veles_tpu.services.podmaster import PodAgent
        agent = PodAgent("127.0.0.1:1", 0, str(tmp_path / "agent0"))

        sent = []

        class FakeConn:
            @staticmethod
            def send(obj):
                sent.append(obj)
                return True
        agent._conn = FakeConn()
        return agent, sent

    def test_fetch_push_round_trip_is_byte_exact(self, tmp_path):
        src = str(tmp_path / "src")
        dst = str(tmp_path / "dst")
        payload = os.urandom(2048)
        _commit(src, "wf_5.pickle.gz", payload, epoch=5)
        agent, sent = self._agent(tmp_path)
        agent._fetch_commit({"name": "wf_5.pickle.gz",
                             "snapshot_dir": src, "max_mb": 1})
        reply = sent[-1]
        assert reply["type"] == "commit_data" and reply["ok"]
        assert set(reply["files"]) == {
            "wf_5.pickle.gz", "wf_5.pickle.gz" + MANIFEST_SUFFIX}
        agent._push_commit({"snapshot_dir": dst,
                            "files": reply["files"]})
        assert sent[-1]["type"] == "commit_pushed" and sent[-1]["ok"]
        with open(os.path.join(dst, "wf_5.pickle.gz"), "rb") as f:
            assert f.read() == payload
        # the pushed commit scans VALID against its shipped manifest
        assert scan_commits(dst, "wf")["wf_5.pickle.gz"]["valid"] \
            is True
        # no .tmp leftovers (tmp+rename)
        assert not [n for n in os.listdir(dst) if n.endswith(".tmp")]

    def test_fetch_refuses_past_the_replication_cap(self, tmp_path):
        src = str(tmp_path / "src")
        _commit(src, "wf_5.pickle.gz", os.urandom(4096), epoch=5)
        agent, sent = self._agent(tmp_path)
        agent._fetch_commit({"name": "wf_5.pickle.gz",
                             "snapshot_dir": src,
                             "max_mb": 0.001})     # ~1 KiB cap
        reply = sent[-1]
        assert not reply["ok"] and "cap" in reply["error"]
        assert reply["files"] is None

    def test_push_strips_path_traversal(self, tmp_path):
        dst = str(tmp_path / "dst")
        agent, sent = self._agent(tmp_path)
        agent._push_commit({"snapshot_dir": dst,
                            "files": {"../../evil.bin": "QUJD"}})
        assert sent[-1]["ok"]
        assert os.listdir(dst) == ["evil.bin"]
        assert not os.path.exists(str(tmp_path / "evil.bin"))


# =====================================================================
# reshard-on-restore (snapshotter.reshard_state): the 4->2->4 matrix
# =====================================================================

def _topo(processes, data, fsdp=False, extra_axes=None):
    axes = {"data": data}
    axes.update(extra_axes or {})
    return {"processes": processes, "devices": data,
            "axes": axes, "fsdp": fsdp}


def _state(topology, order=None, mb=64):
    import numpy as np
    rng = np.random.RandomState(7)
    params = {"fc": {"weights": rng.randn(8, 4).astype("float32"),
                     "bias": rng.randn(4).astype("float32")}}
    velocity = {"fc": {"weights": rng.randn(8, 4).astype("float32"),
                       "bias": rng.randn(4).astype("float32")}}
    return {
        "topology": topology,
        "params": params,
        "velocity": velocity,
        "loader": {"epoch_number": 3, "minibatch_offset": 128,
                   "minibatch_size": mb,
                   "order": order, "prng": {"seed": 11, "counter": 5}},
        "prng": {"train": {"seed": 1, "counter": 2},
                 "dropout": {"seed": 3, "counter": 4}},
    }


class TestFitAxesToDevices:
    """parallel.mesh.fit_axes_to_devices — the launcher's elastic-mesh
    refit: only the data axis rescales to the survivors."""

    def test_data_axis_rescales_to_survivors(self):
        from veles_tpu.parallel import fit_axes_to_devices
        assert fit_axes_to_devices({"data": 4}, 2) == {"data": 2}
        assert fit_axes_to_devices({"data": 2}, 8) == {"data": 8}

    def test_fixed_model_axis_is_preserved(self):
        from veles_tpu.parallel import fit_axes_to_devices
        assert fit_axes_to_devices({"data": 4, "model": 2}, 4) == \
            {"data": 2, "model": 2}

    def test_data_wildcard_passes_through(self):
        from veles_tpu.parallel import fit_axes_to_devices
        assert fit_axes_to_devices({"data": -1, "model": 2}, 6) == \
            {"data": -1, "model": 2}
        with pytest.raises(ValueError, match="fixed axes"):
            fit_axes_to_devices({"data": -1, "model": 4}, 6)

    def test_non_data_wildcard_is_refused(self):
        """make_mesh would resolve a model=-1 against the LIVE device
        count — a silent model re-layout at each pod size (2 -> 1 when
        half the devices die).  Refused up front, at FULL size too, so
        the operator learns at first spawn, not at degrade time."""
        from veles_tpu.parallel import fit_axes_to_devices
        with pytest.raises(ValueError, match="non-data"):
            fit_axes_to_devices({"data": 4, "model": -1}, 8)

    def test_illegal_resize_is_an_error_not_a_relayout(self):
        from veles_tpu.parallel import fit_axes_to_devices
        with pytest.raises(ValueError, match="data axis"):
            fit_axes_to_devices({"data": 2, "model": 4}, 6)


class TestReshardState:
    @pytest.mark.parametrize("fsdp", [False, True],
                             ids=["dp", "dp-fsdp"])
    def test_4_2_4_round_trip_is_per_leaf_bit_exact(self, fsdp):
        """The degrade->re-expand ladder of the chaos gate, at the
        state level: 4 hosts -> 2 -> back, dp and dp x fsdp; params,
        optimizer slots, loader words and PRNG words carry bit-exactly
        and the checks prove the data order invariant."""
        import numpy as np
        from veles_tpu.services.snapshotter import iter_state_leaves
        order = np.arange(1024, dtype=np.int64)
        src = _state(_topo(4, 8, fsdp), order=order)
        baseline = {p: leaf.copy() for p, leaf in
                    iter_state_leaves(src) if hasattr(leaf, "copy")}
        for target in (_topo(2, 4, fsdp), _topo(4, 8, fsdp)):
            out, report = reshard_state(src, target)
            assert out is src                  # never copied, never cast
            assert report["changed"] == (target != src["topology"])
            assert any("order invariant" in c
                       for c in report["checks"])
            assert any("prng streams are global words" in c
                       for c in report["checks"])
            assert any("dense on host" in c for c in report["checks"])
        for path, leaf in iter_state_leaves(src):
            if hasattr(leaf, "copy") and path in baseline:
                assert np.array_equal(
                    np.asarray(leaf), np.asarray(baseline[path])), path

    def test_growing_past_the_source_size_is_legal(self):
        out, report = reshard_state(_state(_topo(2, 4)), _topo(8, 16))
        assert report["changed"]

    def test_model_axis_change_is_refused(self):
        src = _state(_topo(4, 8, extra_axes={"model": 2}))
        with pytest.raises(SnapshotReshardError, match="model"):
            reshard_state(src, _topo(2, 4, extra_axes={"model": 4}))

    def test_indivisible_minibatch_is_refused_before_restore(self):
        src = _state(_topo(4, 8), mb=6)
        with pytest.raises(SnapshotReshardError, match="divide"):
            reshard_state(src, _topo(4, 4))

    def test_non_global_prng_words_are_refused(self):
        src = _state(_topo(4, 8))
        src["prng"]["train"] = {"per_host": [1, 2, 3, 4]}
        with pytest.raises(SnapshotReshardError, match="global"):
            reshard_state(src, _topo(2, 4))

    def test_device_pinned_leaf_is_refused(self):
        import jax.numpy as jnp
        src = _state(_topo(4, 8))
        src["params"]["fc"]["weights"] = jnp.ones((8, 4))
        with pytest.raises(SnapshotReshardError, match="host array"):
            reshard_state(src, _topo(2, 4))

    def test_fsdp_flag_change_is_placement_only(self):
        out, report = reshard_state(_state(_topo(4, 8, fsdp=True)),
                                    _topo(4, 8, fsdp=False))
        assert any("placement-only" in c for c in report["checks"])

    def test_legacy_state_without_topology_tag_still_checks(self):
        src = _state(None)
        del src["topology"]
        out, report = reshard_state(src, _topo(2, 4),
                                    minibatch_size=64)
        assert report["from"] is None and not report["changed"]
        assert any("order invariant" in c for c in report["checks"])
