"""Training survival layer (PR 8): crash-consistent snapshotter
hardening (integrity manifests, torn-commit detection + quarantine,
keep-last-N ring, transient-error retry), the respawn supervisor
(classification via crashdumps, backoff, crash-loop + deterministic-bug
valves), the --snapshot auto dangling/corrupt `_current` fallback, and
the scaled-down train-chaos smoke (the CI `train-chaos` job runs the
full gate)."""

import json
import os
import pickle
import subprocess
import sys
import time

import numpy as np
import pytest

from veles_tpu.services.snapshotter import (MANIFEST_SUFFIX,
                                            SnapshotIntegrityError,
                                            SnapshotterBase,
                                            iter_state_leaves,
                                            state_manifest,
                                            validate_state_manifest)
from veles_tpu.services.supervisor import Supervisor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _state(seed=0):
    rng = np.random.RandomState(seed)
    return {"params": {"l0": {"weights": rng.randn(4, 3),
                              "bias": rng.randn(3)}},
            "prng": {"loader": {"seed": 11, "counter": 5 + seed}},
            "epoch": 2, "step_counter": 36,
            "loader": {"epoch_number": 2, "minibatch_offset": 7,
                       "order": np.arange(10, dtype=np.int32)}}


class _StateSnap(SnapshotterBase):
    """File-backend snapshotter over a fixed state dict — exercises the
    commit path without a training workflow."""

    def __init__(self, state, **kwargs):
        super(_StateSnap, self).__init__(None, **kwargs)
        self._state = state

    def collect(self):
        return self._state


# --------------------------------------------------------------------
# integrity manifest + torn-commit detection
# --------------------------------------------------------------------
class TestIntegrityManifest:
    def test_manifest_written_and_validated_roundtrip(self, tmp_path):
        snap = _StateSnap(_state(), directory=str(tmp_path),
                          prefix="m", compression="gz")
        path = snap.export()
        assert os.path.exists(path + MANIFEST_SUFFIX)
        man = json.load(open(path + MANIFEST_SUFFIX))
        assert man["file_sha256"] and man["leaves"]
        # weights leaf records shape+dtype next to its digest
        wl = man["leaves"]["/params/l0/weights"]
        assert wl["shape"] == [4, 3] and "float64" in wl["dtype"]
        loaded = SnapshotterBase.import_(path)
        np.testing.assert_array_equal(
            loaded["params"]["l0"]["weights"],
            _state()["params"]["l0"]["weights"])

    def test_truncated_checkpoint_rejected_before_unpickle(
            self, tmp_path):
        snap = _StateSnap(_state(), directory=str(tmp_path),
                          prefix="t", compression="gz")
        path = snap.export()
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size * 3 // 5)
        with pytest.raises(SnapshotIntegrityError, match="sha256"):
            SnapshotterBase.import_(path)

    def test_leaf_mutation_detected(self):
        st = _state()
        man = state_manifest(st)
        validate_state_manifest(st, man)            # clean passes
        st["params"]["l0"]["weights"][0, 0] += 1.0
        with pytest.raises(SnapshotIntegrityError,
                           match="/params/l0/weights"):
            validate_state_manifest(st, man)
        # scalar leaves are covered too
        st2 = _state()
        st2["step_counter"] = 37
        with pytest.raises(SnapshotIntegrityError,
                           match="step_counter"):
            validate_state_manifest(st2, man)

    def test_legacy_checkpoint_without_manifest_still_loads(
            self, tmp_path):
        snap = _StateSnap(_state(), directory=str(tmp_path),
                          prefix="l", compression="", manifest=False)
        path = snap.export()
        assert not os.path.exists(path + MANIFEST_SUFFIX)
        assert SnapshotterBase.import_(path)["epoch"] == 2

    def test_quarantine_renames_data_and_manifest(self, tmp_path):
        snap = _StateSnap(_state(), directory=str(tmp_path),
                          prefix="q", compression="gz")
        path = snap.export()
        target = SnapshotterBase.quarantine(path)
        assert target == path + ".corrupt"
        assert os.path.exists(target)
        assert os.path.exists(target + MANIFEST_SUFFIX)
        assert not os.path.exists(path)
        assert not os.path.exists(path + MANIFEST_SUFFIX)


# --------------------------------------------------------------------
# keep-last-N ring + commit retry
# --------------------------------------------------------------------
class TestCheckpointRing:
    def _export_n(self, snap, n):
        paths = []
        for i in range(n):
            snap._epoch_counter = i + 1
            paths.append(snap.export())
            # distinct mtimes on coarse-grained filesystems
            t = time.time() + i - n
            os.utime(paths[-1], (t, t))
        return paths

    def test_ring_prunes_beyond_keep_last(self, tmp_path):
        snap = _StateSnap(_state(), directory=str(tmp_path), prefix="r",
                          compression="gz", keep_last=3)
        self._export_n(snap, 6)
        data = [n for n in os.listdir(str(tmp_path))
                if not n.endswith("_current")
                and not n.endswith(MANIFEST_SUFFIX)]
        assert sorted(data) == ["r_4.pickle.gz", "r_5.pickle.gz",
                                "r_6.pickle.gz"]
        # manifests pruned alongside their data files
        manifests = [n for n in os.listdir(str(tmp_path))
                     if n.endswith(MANIFEST_SUFFIX)]
        assert len(manifests) == 3
        # _current still resolves to a loadable checkpoint
        cur = os.path.join(str(tmp_path), "r_current")
        assert SnapshotterBase.import_(cur)["epoch"] == 2

    def test_ring_never_deletes_current_anchor(self, tmp_path):
        snap = _StateSnap(_state(), directory=str(tmp_path), prefix="a",
                          compression="gz", keep_last=2)
        paths = self._export_n(snap, 3)
        # age the CURRENT target far past everything else: mtime says
        # collect it, the anchor rule says never
        cur = os.path.join(str(tmp_path), "a_current")
        anchor = os.path.realpath(cur)
        os.utime(anchor, (1.0, 1.0))
        snap._epoch_counter = 9
        snap.export()
        assert os.path.exists(anchor) or \
            os.path.realpath(cur) != anchor   # re-flipped is fine
        assert SnapshotterBase.import_(cur)["epoch"] == 2
        assert paths  # silence unused

    def test_keep_last_zero_keeps_everything(self, tmp_path):
        snap = _StateSnap(_state(), directory=str(tmp_path), prefix="k",
                          compression="gz", keep_last=0)
        self._export_n(snap, 6)
        data = [n for n in os.listdir(str(tmp_path))
                if not n.endswith("_current")
                and not n.endswith(MANIFEST_SUFFIX)]
        assert len(data) == 6


class TestCommitRetry:
    def test_transient_error_retried_and_recorded(self, tmp_path,
                                                  monkeypatch):
        from veles_tpu.telemetry import flight
        real_replace = os.replace
        fails = {"n": 2}

        def flaky(src, dst):
            if fails["n"] > 0 and dst.endswith(".gz"):
                fails["n"] -= 1
                raise OSError("transient EIO")
            return real_replace(src, dst)

        monkeypatch.setattr(os, "replace", flaky)
        snap = _StateSnap(_state(), directory=str(tmp_path), prefix="f",
                          compression="gz", commit_retries=3,
                          retry_backoff_ms=1)
        path = snap.export()
        assert os.path.exists(path)
        assert fails["n"] == 0
        # filter by THIS test's destination: the bounded ring may have
        # rotated arbitrary events from earlier tests
        retries = [e for e in flight.recorder.snapshot()
                   if e["kind"] == "snapshot.retry"
                   and str(tmp_path) in str(e.get("destination"))]
        assert len(retries) == 2
        assert "transient EIO" in retries[0]["error"]

    def test_exhausted_retries_surface(self, tmp_path, monkeypatch):
        def always(src, dst):
            raise OSError("disk on fire")

        monkeypatch.setattr(os, "replace", always)
        snap = _StateSnap(_state(), directory=str(tmp_path), prefix="x",
                          compression="gz", commit_retries=2,
                          retry_backoff_ms=1)
        with pytest.raises(OSError, match="disk on fire"):
            snap.export()


# --------------------------------------------------------------------
# db backend integrity
# --------------------------------------------------------------------
class TestDBIntegrity:
    def _write_rows(self, dsn, n):
        from veles_tpu.services.snapshotter import DBSnapshotter
        snap = DBSnapshotter(None, dsn=dsn)
        for i in range(n):
            snap._db_write(_state(seed=i), "s%d" % i,
                           "%s#wf_s%d" % (dsn, i))
        return snap

    def test_corrupt_newest_row_falls_back_to_previous(self, tmp_path):
        import sqlite3

        from veles_tpu.services.snapshotter import DBSnapshotter
        dsn = str(tmp_path / "s.sqlite")
        self._write_rows(dsn, 3)
        conn = sqlite3.connect(dsn)
        with conn:
            conn.execute("UPDATE snapshots SET state = ? WHERE id = "
                         "(SELECT MAX(id) FROM snapshots)",
                         (b"torn-garbage",))
        conn.close()
        snap = DBSnapshotter.import_db(dsn)
        # newest (seed=2) skipped; previous valid row (seed=1) loads
        assert snap["prng"]["loader"]["counter"] == 6
        np.testing.assert_array_equal(
            snap["params"]["l0"]["weights"],
            _state(seed=1)["params"]["l0"]["weights"])

    def test_all_rows_corrupt_raises_integrity_error(self, tmp_path):
        import sqlite3
        from veles_tpu.services.snapshotter import DBSnapshotter
        dsn = str(tmp_path / "s.sqlite")
        self._write_rows(dsn, 2)
        conn = sqlite3.connect(dsn)
        with conn:
            conn.execute("UPDATE snapshots SET state = ?",
                         (b"torn-garbage",))
        conn.close()
        with pytest.raises(SnapshotIntegrityError):
            DBSnapshotter.import_db(dsn)

    def test_db_ring_bounded_in_transaction(self, tmp_path):
        import sqlite3
        from veles_tpu.services.snapshotter import DBSnapshotter
        dsn = str(tmp_path / "s.sqlite")
        snap = DBSnapshotter(None, dsn=dsn, keep_last=2)
        for i in range(5):
            snap._db_write(_state(seed=i), "s%d" % i, "d")
        conn = sqlite3.connect(dsn)
        rows = conn.execute(
            "SELECT suffix FROM snapshots ORDER BY id").fetchall()
        conn.close()
        assert [r[0] for r in rows] == ["s3", "s4"]
        assert DBSnapshotter.import_db(dsn)["prng"]["loader"][
            "counter"] == 9


# --------------------------------------------------------------------
# --snapshot auto fallback: torn current, dangling symlink
# --------------------------------------------------------------------
class TestAutoResumeFallback:
    def _commit(self, tmp_path, prefix, suffix, seed):
        snap = _StateSnap(_state(seed=seed), directory=str(tmp_path),
                          prefix=prefix, compression="gz")
        snap._epoch_counter = suffix
        path = snap.export()
        t = time.time() - 100 + suffix
        os.utime(path, (t, t))
        return path

    def test_torn_current_steps_back_and_quarantines(self, tmp_path,
                                                     capsys):
        from veles_tpu.__main__ import Main
        self._commit(tmp_path, "w", 1, seed=1)
        newest = self._commit(tmp_path, "w", 2, seed=2)
        with open(newest, "r+b") as f:
            f.truncate(os.path.getsize(newest) // 2)
        current = os.path.join(str(tmp_path), "w_current")
        try:
            SnapshotterBase.import_(current)
            raise AssertionError("torn checkpoint loaded")
        except SnapshotIntegrityError as e:
            snap, src = Main._auto_snapshot_fallback(current, e)
        assert snap is not None and src.endswith("w_1.pickle.gz")
        assert snap["prng"]["loader"]["counter"] == 6   # seed=1 state
        assert os.path.exists(newest + ".corrupt")
        assert not os.path.exists(newest)
        err = capsys.readouterr().err
        assert "failed to load" in err and "recovered from" in err
        assert "quarantined" in err

    def test_dangling_current_falls_back_with_warning(self, tmp_path,
                                                      capsys):
        import types

        from veles_tpu.__main__ import Main
        self._commit(tmp_path, "d", 1, seed=3)
        current = os.path.join(str(tmp_path), "d_current")
        os.remove(current)
        os.symlink("d_gone.pickle.gz", current)   # dangling
        wf = types.SimpleNamespace(
            name="d", snapshotter=types.SimpleNamespace(
                directory=str(tmp_path), prefix="d"))
        resolved = Main._resolve_auto_snapshot(wf)
        assert resolved == current               # NOT a silent fresh start
        err = capsys.readouterr().err
        assert "dangles" in err
        try:
            SnapshotterBase.import_(resolved)
            raise AssertionError("dangling symlink loaded")
        except Exception as e:   # noqa: BLE001 — any load failure
            snap, src = Main._auto_snapshot_fallback(resolved, e)
        assert snap is not None and src.endswith("d_1.pickle.gz")

    def test_no_candidates_fresh_start(self, tmp_path, capsys):
        from veles_tpu.__main__ import Main
        current = os.path.join(str(tmp_path), "n_current")
        snap, src = Main._auto_snapshot_fallback(
            current, FileNotFoundError("gone"))
        assert snap is None and src is None
        assert "fresh start" in capsys.readouterr().err


# --------------------------------------------------------------------
# the supervisor
# --------------------------------------------------------------------
_CHILD_PREEMPT_THEN_DONE = """\
import os, sys
marker = sys.argv[1]
if not os.path.exists(marker):
    open(marker, "w").write("x")
    sys.exit(75)
sys.exit(0)
"""

_CHILD_ALWAYS_CRASH = """\
import sys
sys.exit(3)
"""

_CHILD_CRASH_WITH_DUMP = """\
import json, os, sys, time
blackbox, kind, marker = sys.argv[1], sys.argv[2], sys.argv[3]
if kind == "fault" and os.path.exists(marker):
    sys.exit(0)                        # second life: drill recovered
d = os.path.join(blackbox, "crashdump-%d" % int(time.time() * 1e6))
os.makedirs(d)
with open(os.path.join(d, "events.jsonl"), "w") as f:
    if kind == "fault":
        f.write(json.dumps({"kind": "fault.injected"}) + "\\n")
meta = {"reason": "test"}
if kind == "error":
    meta["error"] = {"type": "ValueError", "message": "boom"}
with open(os.path.join(d, "meta.json"), "w") as f:
    json.dump(meta, f)
open(marker, "w").write("x")
sys.exit(1)
"""

_CHILD_SLEEP = """\
import time
time.sleep(60)
"""


def _script(tmp_path, body, name="child.py"):
    path = str(tmp_path / name)
    with open(path, "w") as f:
        f.write(body)
    return path


class TestSupervisor:
    def test_backoff_delay_pinned(self):
        sup = Supervisor(["true"], backoff_base_ms=100,
                         backoff_max_ms=800, seed=5,
                         install_signals=False)
        for attempt, ceiling in ((1, 0.1), (2, 0.2), (3, 0.4),
                                 (4, 0.8), (7, 0.8)):
            for _ in range(20):
                d = sup.backoff_delay(attempt)
                assert 0.5 * ceiling <= d < ceiling

    def test_preempt_respawns_immediately_unbounded(self, tmp_path):
        child = _script(tmp_path, _CHILD_PREEMPT_THEN_DONE)
        marker = str(tmp_path / "m")
        sup = Supervisor([sys.executable, child, marker],
                         max_restarts=1, window_seconds=600,
                         blackbox_dir=str(tmp_path / "bb"),
                         install_signals=False)
        assert sup.run() == 0
        kinds = [h["kind"] for h in sup.history]
        assert kinds == ["preempt", "done"]
        assert sup.restarts["preempt"] == 1
        assert sup.spawn_count == 2

    def test_crash_loop_valve_gives_up(self, tmp_path):
        child = _script(tmp_path, _CHILD_ALWAYS_CRASH)
        sup = Supervisor([sys.executable, child],
                         max_restarts=2, window_seconds=600,
                         backoff_base_ms=1, backoff_max_ms=2,
                         blackbox_dir=str(tmp_path / "bb"),
                         deterministic_limit=99,
                         install_signals=False)
        assert sup.run() == 3
        # initial + 2 allowed respawns, then the valve
        assert sup.spawn_count == 3
        assert all(h["kind"] == "crash:rc3" for h in sup.history)

    def test_deterministic_bug_gives_up_early(self, tmp_path):
        bb = str(tmp_path / "bb")
        os.makedirs(bb)
        child = _script(tmp_path, _CHILD_CRASH_WITH_DUMP)
        sup = Supervisor(
            [sys.executable, child, bb, "error",
             str(tmp_path / "m")],
            max_restarts=50, window_seconds=600,
            backoff_base_ms=1, backoff_max_ms=2,
            deterministic_limit=2, blackbox_dir=bb,
            install_signals=False)
        assert sup.run() == 1
        assert sup.spawn_count == 2       # identical signature twice
        assert sup.history[-1]["kind"] == "crash:ValueError"
        assert "boom" in sup.history[-1]["signature"]

    def test_fault_injection_classified_from_crashdump(self, tmp_path):
        bb = str(tmp_path / "bb")
        os.makedirs(bb)
        child = _script(tmp_path, _CHILD_CRASH_WITH_DUMP)
        sup = Supervisor(
            [sys.executable, child, bb, "fault",
             str(tmp_path / "m")],
            max_restarts=5, backoff_base_ms=1, backoff_max_ms=2,
            deterministic_limit=2, blackbox_dir=bb,
            install_signals=False)
        assert sup.run() == 0
        kinds = [h["kind"] for h in sup.history]
        assert kinds == ["fault-injection", "done"]
        assert sup.restarts["fault-injection"] == 1

    def test_sigkill_classified_and_respawned(self, tmp_path):
        import signal as _signal
        import threading
        marker = str(tmp_path / "m")
        child = _script(tmp_path, """\
import os, sys, time
if os.path.exists(%r):
    sys.exit(0)
open(%r, "w").write("x")
time.sleep(60)
""" % (marker, marker))
        sup = Supervisor([sys.executable, child],
                         max_restarts=5, backoff_base_ms=1,
                         backoff_max_ms=2,
                         blackbox_dir=str(tmp_path / "bb"),
                         install_signals=False)

        def killer():
            deadline = time.time() + 30
            while time.time() < deadline:
                if os.path.exists(marker):
                    pid = sup.current_pid()
                    if pid:
                        os.kill(pid, _signal.SIGKILL)
                        return
                time.sleep(0.02)

        t = threading.Thread(target=killer, daemon=True)
        t.start()
        assert sup.run() == 0
        t.join(timeout=10)
        kinds = [h["kind"] for h in sup.history]
        assert kinds == ["killed:SIGKILL", "done"]
        assert sup.restarts["killed"] == 1

    def test_progress_resets_deterministic_counter(self, tmp_path):
        """Crashes WITH checkpoint progress between them never trip the
        deterministic-bug valve: the signature counter resets."""
        bb = str(tmp_path / "bb")
        progress = str(tmp_path / "snap")
        os.makedirs(bb)
        os.makedirs(progress)
        child = _script(tmp_path, """\
import json, os, sys, time
bb, progress, counter = sys.argv[1], sys.argv[2], sys.argv[3]
n = int(open(counter).read()) if os.path.exists(counter) else 0
open(counter, "w").write(str(n + 1))
if n >= 4:
    sys.exit(0)
open(os.path.join(progress, "ckpt-%d" % n), "w").write("x")  # progress
d = os.path.join(bb, "crashdump-%d" % int(time.time() * 1e6))
os.makedirs(d)
open(os.path.join(d, "events.jsonl"), "w").write("")
json.dump({"error": {"type": "ValueError", "message": "same"}},
          open(os.path.join(d, "meta.json"), "w"))
sys.exit(1)
""")
        sup = Supervisor(
            [sys.executable, child, bb, progress,
             str(tmp_path / "n")],
            max_restarts=50, backoff_base_ms=1, backoff_max_ms=2,
            deterministic_limit=2, blackbox_dir=bb,
            progress_paths=[progress], install_signals=False)
        # 4 identical-signature crashes, each WITH progress -> all
        # respawned; a deterministic_limit of 2 would otherwise stop
        # after the second
        assert sup.run() == 0
        assert sup.spawn_count == 5   # 4 crashes + the clean finish

    def test_stop_prevents_respawn(self, tmp_path):
        import threading
        child = _script(tmp_path, _CHILD_SLEEP)
        sup = Supervisor([sys.executable, child],
                         blackbox_dir=str(tmp_path / "bb"),
                         install_signals=False)

        def stopper():
            while sup.current_pid() is None:
                time.sleep(0.01)
            sup.stop()

        t = threading.Thread(target=stopper, daemon=True)
        t.start()
        rc = sup.run()
        t.join(timeout=10)
        assert rc == -15                  # SIGTERM, default disposition
        assert sup.spawn_count == 1       # no respawn after stop()


# --------------------------------------------------------------------
# CLI wiring
# --------------------------------------------------------------------
class TestSuperviseCLI:
    def test_supervise_rejects_explicit_snapshot_path(self):
        from veles_tpu.__main__ import Main
        with pytest.raises(SystemExit, match="snapshot auto"):
            Main(["wf.py", "--supervise",
                  "--snapshot", "/some/file.pickle"]).run()

    def test_supervise_parses_and_composes_with_auto(self):
        from veles_tpu.__main__ import Main
        args = Main(["wf.py", "--supervise", "--snapshot", "auto",
                     "--snapshot-every", "1"]).parse()
        assert args.supervise and args.snapshot == "auto"


# --------------------------------------------------------------------
# scaled-down chaos smoke (the CI train-chaos job runs the full gate)
# --------------------------------------------------------------------
class TestTrainChaosSmoke:
    def test_chaos_gate_scaled_down(self, tmp_path):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        report_file = str(tmp_path / "report.json")
        r = subprocess.run(
            [sys.executable, "tools/train_chaos.py",
             "--epochs", "6", "--kills", "2", "--seed", "23",
             "--workdir", str(tmp_path / "work"),
             "--json", report_file, "--timeout", "240"],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=360)
        assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
        rep = json.load(open(report_file))
        assert rep["gates_failed"] == []
        assert rep["exactness"]["identical"]
        assert rep["exactness"]["n_leaves"] > 20
        sigs = {k["signal"] for k in rep["kills_delivered"]}
        assert sigs == {"SIGKILL", "SIGTERM"}
        assert rep["quarantined"]          # torn commit quarantined
        assert rep["ring_invalid"] == []   # zero torn checkpoints left


def test_iter_state_leaves_shared_flattener():
    """The verifier and the manifest flatten identically (they import
    the same function — pin the contract anyway)."""
    st = {"b": [1, 2], "a": {"x": np.zeros(2)}}
    paths = [p for p, _ in iter_state_leaves(st)]
    assert paths == ["/a/x", "/b[0]", "/b[1]"]
    assert pickle.loads(pickle.dumps(st))  # round-trips
