"""RBM + LSTM/RNN engine tests (ref SURVEY §2.9 'Other documented
engines': RBM numpy engine, RNN/LSTM in-progress — completed here)."""

import numpy as np
import pytest
from sklearn.datasets import load_digits

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.rbm import RBMWorkflow
from veles_tpu.models.standard_workflow import StandardWorkflow


class TestRBM:
    def test_rbm_learns_digits(self):
        prng.seed_all(23)
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        loader = FullBatchLoader(None, data=x, minibatch_size=100,
                                 class_lengths=[0, 0, len(x)])
        wf = RBMWorkflow(loader=loader, n_hidden=48, n_epochs=8,
                         learning_rate=0.3, name="rbm")
        wf.initialize()
        wf.run()
        assert len(wf.rmse_history) == 8
        assert wf.rmse_history[-1] < wf.rmse_history[0]
        assert wf.rmse_history[-1] < 0.25
        # hidden representation separates at least a little: reconstruction
        # of real digits should beat reconstruction of noise
        recon = np.asarray(wf.trainer.reconstruct(x[:200]))
        err_real = np.sqrt(((recon - x[:200]) ** 2).mean())
        noise = np.random.RandomState(0).rand(200, 64).astype(np.float32)
        recon_n = np.asarray(wf.trainer.reconstruct(noise))
        err_noise = np.sqrt(((recon_n - noise) ** 2).mean())
        assert err_real < err_noise

    def test_rbm_reproducible(self):
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)[:400]

        def run():
            prng.seed_all(7)
            loader = FullBatchLoader(None, data=x, minibatch_size=100,
                                     class_lengths=[0, 0, len(x)])
            wf = RBMWorkflow(loader=loader, n_hidden=16, n_epochs=2,
                             name="rbm-r")
            wf.initialize()
            wf.run()
            return np.asarray(wf.trainer.params["weights"])

        np.testing.assert_array_equal(run(), run())


def sequence_dataset(n=1200, t=12, seed=0):
    """Classify whether the sequence sum is positive — requires
    integrating over time."""
    g = np.random.RandomState(seed)
    x = g.normal(0, 1, (n, t, 4)).astype(np.float32)
    y = (x.sum(axis=(1, 2)) > 0).astype(np.int32)
    return x, y


class TestRecurrent:
    @pytest.mark.parametrize("kind", ["lstm", "rnn_tanh"])
    def test_sequence_classification(self, kind):
        prng.seed_all(31)
        x, y = sequence_dataset()
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                                 class_lengths=[0, 200, 1000])
        wf = StandardWorkflow(
            layers=[
                {"type": kind, "output_sample_shape": 16,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
                {"type": "softmax", "output_sample_shape": 2,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
            ],
            loader=loader, decision_config={"max_epochs": 15},
            name="seq-" + kind)
        wf.initialize()
        wf.run()
        assert wf.decision.best_metric < 0.15, wf.decision.best_metric

    def test_return_sequences_stacking(self):
        prng.seed_all(5)
        x, y = sequence_dataset(400)
        loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                                 class_lengths=[0, 100, 300], name="l2")
        wf = StandardWorkflow(
            layers=[
                {"type": "lstm", "output_sample_shape": 8,
                 "return_sequences": True, "learning_rate": 0.05,
                 "gradient_moment": 0.9},
                {"type": "lstm", "output_sample_shape": 8,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
                {"type": "softmax", "output_sample_shape": 2,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
            ],
            loader=loader, decision_config={"max_epochs": 3},
            name="seq-stack")
        wf.initialize()
        wf.run()
        assert wf.trainer.layers[0].output_shape == (12, 8)
        assert wf.decision.best_metric is not None
