"""LMDB-style KV loader (with a fake env) and audio loader tests."""

import pickle
import wave

import numpy as np
import pytest

from veles_tpu.loader import TRAIN, VALID
from veles_tpu.loader.audio import AudioLoader, read_audio, window
from veles_tpu.loader.lmdb import LMDBLoader, decode_record


class FakeTxn:
    def __init__(self, records):
        self.records = records

    def cursor(self):
        return iter(sorted(self.records.items()))

    def get(self, key):
        return self.records.get(key)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class FakeEnv:
    def __init__(self, records):
        self.records = records
        self.closed = False

    def begin(self):
        return FakeTxn(self.records)

    def close(self):
        self.closed = True


class TestLMDBLoader:
    def test_decode_record_variants(self):
        import io
        buf = io.BytesIO()
        np.save(buf, np.arange(4, dtype=np.float32))
        d, l = decode_record(buf.getvalue())
        np.testing.assert_array_equal(d, [0, 1, 2, 3])
        assert l is None
        d, l = decode_record(pickle.dumps((np.ones(3, np.float32), 7)))
        assert l == 7
        d, l = decode_record(np.arange(6, dtype=np.float32).tobytes(),
                             sample_shape=(2, 3))
        assert d.shape == (2, 3)

    def test_loads_classes_from_fake_envs(self):
        rng = np.random.RandomState(0)
        envs = {}

        def factory(path):
            records = {b"%04d" % i: pickle.dumps(
                (rng.rand(4).astype(np.float32), i % 3))
                for i in range(8 if "train" in path else 4)}
            envs[path] = FakeEnv(records)
            return envs[path]

        loader = LMDBLoader(None, dbs={"train": "train.mdb",
                                       "validation": "val.mdb"},
                            env_factory=factory, minibatch_size=4)
        loader.initialize()
        assert loader.class_lengths == [0, 4, 8]
        assert loader.original_data.shape == (12, 4)
        assert loader.original_labels.shape == (12,)
        assert all(env.closed for env in envs.values())
        loader.run()
        assert loader.minibatch_indices.shape[0] == 4
        got = LMDBLoader.gather(loader.data, loader.minibatch_indices)
        assert got.shape == (4, 4)

    def test_missing_lmdb_package_reports_clearly(self, tmp_path):
        loader = LMDBLoader(None, dbs={"train": str(tmp_path)})
        with pytest.raises(ImportError, match="lmdb"):
            loader.initialize()


def _write_wav(path, samples, rate=8000):
    with wave.open(str(path), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(rate)
        w.writeframes((np.clip(samples, -1, 1) * 32767)
                      .astype("<i2").tobytes())


class TestAudioLoader:
    def test_read_audio_roundtrip(self, tmp_path):
        sig = np.sin(np.linspace(0, 40 * np.pi, 4000)).astype(np.float32)
        _write_wav(tmp_path / "t.wav", sig)
        data, rate = read_audio(str(tmp_path / "t.wav"))
        assert rate == 8000
        np.testing.assert_allclose(data, sig, atol=1e-3)

    def test_window(self):
        w = window(np.arange(10, dtype=np.float32), 4, 3)
        assert w.shape == (3, 4)
        np.testing.assert_array_equal(w[1], [3, 4, 5, 6])

    def test_loader_frames_and_labels(self, tmp_path):
        for name in ("a", "b"):
            _write_wav(tmp_path / (name + ".wav"),
                       np.random.RandomState(0).rand(2048) * 2 - 1)
        loader = AudioLoader(
            None,
            files={"train": [str(tmp_path / "a.wav"),
                             (str(tmp_path / "b.wav"), 5)],
                   "validation": [str(tmp_path / "a.wav")]},
            frame_size=512, minibatch_size=2)
        loader.initialize()
        # 2048 samples / 512 = 4 frames per file
        assert loader.class_lengths == [0, 4, 8]
        assert loader.original_data.shape == (12, 512)
        # VALID block comes first in the concatenated layout
        labels = loader.original_labels
        assert list(labels[:4]) == [0, 0, 0, 0]
        # raw label 5 dense-maps to class index 1 (base label analysis)
        assert list(labels[8:]) == [1, 1, 1, 1]
        assert loader.labels_mapping == {0: 0, 5: 1}
        loader.run()
        assert loader.minibatch_indices.shape == (2,)
        got = AudioLoader.gather(loader.data, loader.minibatch_indices)
        assert got.shape == (2, 512)


class TestHDFSTextLoader:
    """The HDFS text loader through pyarrow's LocalFileSystem (file://
    URIs exercise the exact open_fs/read_rows path a real hdfs:// takes,
    minus the libhdfs transport)."""

    def _write(self, path, rows):
        with open(path, "w") as f:
            f.write("# comment line\n\n")
            for r in rows:
                f.write(",".join(str(v) for v in r) + "\n")

    def test_loads_classes_and_trains_shape(self, tmp_path):
        from veles_tpu.loader.hdfs import HDFSTextLoader

        rs = np.random.RandomState(0)
        train = [(i * 0.5, i * 0.25, i % 3) for i in range(20)]
        valid = [(rs.rand(), rs.rand(), i % 3) for i in range(6)]
        self._write(tmp_path / "train.txt", train)
        self._write(tmp_path / "valid.txt", valid)
        loader = HDFSTextLoader(
            None,
            files={"train": "file://%s" % (tmp_path / "train.txt"),
                   "validation": "file://%s" % (tmp_path / "valid.txt")},
            minibatch_size=5)
        loader.initialize()
        assert loader.class_lengths == [0, 6, 20]
        np.testing.assert_allclose(np.asarray(loader.data)[6], [0, 0])
        assert int(np.asarray(loader.labels)[6]) == 0
        loader.run()
        assert loader.minibatch_class == VALID

    def test_separator_and_unlabeled(self, tmp_path):
        from veles_tpu.loader.hdfs import read_rows

        with open(tmp_path / "u.txt", "w") as f:
            f.write("1.0;2.0\n3.0;4.0\n")
        d, l = read_rows("file://%s" % (tmp_path / "u.txt"),
                         separator=";", labeled=False)
        np.testing.assert_allclose(d, [[1, 2], [3, 4]])
        assert l is None

    def test_empty_raises(self, tmp_path):
        from veles_tpu.loader.hdfs import read_rows

        (tmp_path / "e.txt").write_text("# nothing\n")
        with pytest.raises(ValueError, match="no rows"):
            read_rows("file://%s" % (tmp_path / "e.txt"))
