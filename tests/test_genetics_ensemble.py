"""Genetics + ensemble meta-workflow tests (ref SURVEY §2.8; the
reference's genetics tests optimized a synthetic function before touching
real workflows)."""

import numpy as np
from sklearn.datasets import load_digits

from veles_tpu import prng
from veles_tpu.ensemble import EnsembleTester, EnsembleTrainer
from veles_tpu.genetics import GeneticsOptimizer, Range
from veles_tpu.genetics.core import (Chromosome, Population, apply_genes,
                                     extract_ranges)


class TestRanges:
    cfg = {"lr": Range(0.001, 0.1), "layers": {"hidden": Range(10, 100, int)},
           "fixed": "keep"}

    def test_extract(self):
        paths = extract_ranges(self.cfg)
        assert {p for p, _ in paths} == {("lr",), ("layers", "hidden")}

    def test_apply_genes_decodes(self):
        genes = {("lr",): 0.5, ("layers", "hidden"): 1.0}
        out = apply_genes(self.cfg, genes)
        assert abs(out["lr"] - 0.0505) < 1e-9
        assert out["layers"]["hidden"] == 100
        assert out["fixed"] == "keep"

    def test_int_range_rounds(self):
        assert Range(0, 10, int).decode(0.449) == 4


class TestPopulation:
    def test_evolution_improves_sphere(self):
        """Maximize -|x - 0.7|² over 5 genes."""
        prng.seed_all(21)
        pop = Population(24, 5)

        def fitness(c):
            return -float(((c.values - 0.7) ** 2).sum())

        for c in pop.chromosomes:
            c.fitness = fitness(c)
        first_best = pop.best.fitness
        for _ in range(15):
            pop.evolve()
            for c in pop.chromosomes:
                if c.fitness is None:
                    c.fitness = fitness(c)
        assert pop.best.fitness > first_best
        assert pop.best.fitness > -0.05

    def test_selection_modes(self):
        prng.seed_all(3)
        for sel in ("roulette", "tournament"):
            pop = Population(8, 3, selection=sel)
            for i, c in enumerate(pop.chromosomes):
                c.fitness = float(i)
            assert isinstance(pop._select(), Chromosome)

    def test_crossover_modes(self):
        prng.seed_all(4)
        for cx in ("uniform", "single_point", "blend"):
            pop = Population(4, 6, crossover=cx)
            a, b = pop.chromosomes[:2]
            child = pop._cross(a, b)
            assert child.values.shape == (6,)
            assert (child.values >= 0).all() and (child.values <= 1).all()


class TestGeneticsOptimizer:
    def test_optimizes_quadratic_config(self):
        prng.seed_all(5)
        cfg = {"a": Range(-2.0, 2.0), "b": Range(-2.0, 2.0)}
        opt = GeneticsOptimizer(
            cfg, lambda c: -(c["a"] - 1.0) ** 2 - (c["b"] + 0.5) ** 2,
            size=16, generations=12)
        best = opt.run()
        assert abs(best["a"] - 1.0) < 0.4
        assert abs(best["b"] + 0.5) < 0.4
        assert opt.history[-1] >= opt.history[0]


class TestEnsemble:
    def test_ensemble_beats_or_matches_worst_member(self):
        """Tiny logistic members on digits: ensemble averaging should not
        be worse than the worst individual member."""
        prng.seed_all(8)
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        y = d.target.astype(np.int32)
        x_tr, y_tr, x_te, y_te = x[:1400], y[:1400], x[1400:], y[1400:]

        def softmax_fit(xs, ys, epochs=40, lr=0.5, seed=0):
            g = np.random.default_rng(seed)
            w = g.normal(0, 0.01, (64, 10)).astype(np.float32)
            for _ in range(epochs):
                logits = xs @ w
                p = np.exp(logits - logits.max(1, keepdims=True))
                p /= p.sum(1, keepdims=True)
                onehot = np.eye(10, dtype=np.float32)[ys]
                w -= lr * xs.T @ (p - onehot) / len(xs)
            return w

        def build(i, subset):
            w = softmax_fit(x_tr[subset], y_tr[subset], seed=i)
            return w, {"member": i}

        trainer = EnsembleTrainer(build, len(x_tr), n_models=5,
                                  train_ratio=0.6)
        models = trainer.run()
        member_errs = []
        fns = []
        for w in models:
            fn = (lambda w: lambda xs: xs @ w)(w)
            fns.append(fn)
            member_errs.append(
                float((np.asarray(fn(x_te)).argmax(1) != y_te).mean()))
        tester = EnsembleTester(fns)
        ens_err = tester.error_rate(x_te, y_te)
        assert ens_err <= max(member_errs) + 1e-9
        assert ens_err < 0.15


class TestGrayEncoding:
    """r2: the reference's gray-code binary chromosomes
    (ref veles/genetics/core.py gray encoding)."""

    def test_gray_roundtrip(self):
        from veles_tpu.genetics.core import gray_decode, gray_encode
        vals = np.array([0.0, 0.25, 0.5, 0.75, 1.0])
        bits = gray_encode(vals, 16)
        np.testing.assert_allclose(gray_decode(bits), vals, atol=1e-4)

    def test_adjacent_ints_differ_by_one_bit(self):
        from veles_tpu.genetics.core import gray_encode
        nbits = 8
        scale = 2 ** nbits - 1
        prev = gray_encode(np.array([0.0]), nbits)[0]
        for i in range(1, 256):
            cur = gray_encode(np.array([i / scale]), nbits)[0]
            assert int(np.sum(prev != cur)) == 1, i
            prev = cur

    def test_gray_population_optimizes(self):
        from veles_tpu import prng
        from veles_tpu.genetics.core import Range
        from veles_tpu.genetics.optimizer import GeneticsOptimizer
        prng.seed_all(17)
        config = {"x": Range(-5.0, 5.0), "y": Range(-5.0, 5.0)}

        def fitness(cfg):
            return -(cfg["x"] - 1.0) ** 2 - (cfg["y"] + 2.0) ** 2

        opt = GeneticsOptimizer(config, fitness, size=24, generations=25,
                                encoding="gray", nbits=12)
        best = opt.run()
        assert abs(best["x"] - 1.0) < 0.5
        assert abs(best["y"] + 2.0) < 0.5
        assert len(opt.stats_history) == 25
        assert opt.stats_history[-1]["best"] >= opt.stats_history[0]["best"]

    def test_early_stop_on_convergence(self):
        from veles_tpu import prng
        from veles_tpu.genetics.core import Range
        from veles_tpu.genetics.optimizer import GeneticsOptimizer
        prng.seed_all(3)
        config = {"x": Range(0.0, 1.0)}
        opt = GeneticsOptimizer(config, lambda cfg: 7.0, size=6,
                                generations=50, early_stop_eps=1e-9)
        opt.run()
        # constant fitness -> converged after the first generation
        assert len(opt.history) < 50
