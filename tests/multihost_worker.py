"""Worker process for the multi-host SPMD test (the TPU-era equivalent of
the reference's in-process Server+Client network test,
veles/tests/test_network.py:52-120): each process owns a slice of the
devices, `jax.distributed.initialize` forms the job (the DCN control plane
that replaces the reference's Twisted TCP), and one StandardWorkflow
trains data-parallel over the cross-process mesh.

Usage: python multihost_worker.py <coordinator> <num_processes> <process_id>
Prints one line: ``METRICS {json}``.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    coordinator, num_processes, process_id = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
    # remaining args: "--fsdp" (ZeRO-3 over the cross-process data axis)
    # and/or a snapshot dir.  A snapshot dir without --fsdp runs
    # tensor-parallel over a cross-process 'model' axis (proves
    # multi-host checkpointing: params sharded across processes gather
    # via process_allgather; only process 0 writes); with --fsdp the
    # checkpoint gathers ZeRO-3 shards instead.
    rest = sys.argv[4:]
    fsdp = "--fsdp" in rest
    # --orbax: the sharded backend — save is the collective, every
    # process writes its own shards (all_processes_export)
    orbax = "--orbax" in rest
    seq = "--seq" in rest       # ring attention ACROSS processes
    # --preempt: ONLY process 0 raises the preemption flag mid-run (the
    # staggered-SIGTERM race); the snapshotter's per-cycle agreement
    # allgather must stop BOTH processes at the same cycle with a
    # checkpoint — the exact divergence-deadlock scenario the agreement
    # exists for
    preempt = "--preempt" in rest
    dirs = [a for a in rest if not a.startswith("--")]
    snap_dir = dirs[0] if dirs else None
    # 4 local devices per process -> 8 global over 2 processes (overwrite
    # any inherited XLA_FLAGS — the pytest conftest forces 8 per process)
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    from sklearn.datasets import load_digits

    from veles_tpu import prng
    from veles_tpu.launcher import Launcher
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow

    prng.seed_all(1234)
    if seq:
        # sequence parallelism spanning processes: the ring's
        # ppermute steps cross the process boundary at the seams
        # (DCN on real pods)
        from veles_tpu.models.zoo import transformer_classifier
        xs = np.random.RandomState(0).rand(320, 16, 8)\
            .astype(np.float32)
        ys = np.random.RandomState(1).randint(0, 4, 320)\
            .astype(np.int32)
        loader = FullBatchLoader(None, data=xs, labels=ys,
                                 minibatch_size=80,
                                 class_lengths=[0, 80, 240])
        wf = StandardWorkflow(
            layers=transformer_classifier(n_classes=4, d_model=8,
                                          n_heads=4, n_layers=1,
                                          dropout=0.0, impl="ring",
                                          lr=0.01),
            loader=loader, decision_config={"max_epochs": 2},
            name="multihost-seq")
        mesh_axes = {"data": 1, "seq": -1}
    else:
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)[:800]
        y = d.target.astype(np.int32)[:800]
        loader = FullBatchLoader(None, data=x, labels=y,
                                 minibatch_size=80,
                                 class_lengths=[0, 160, 640])
        if preempt:
            # effectively endless run; ONLY the preemption path can
            # write the checkpoint (interval far beyond the epochs)
            decision_cfg = {"max_epochs": 100000}
            snap_cfg = {"interval": 10 ** 6, "directory": snap_dir}
        else:
            decision_cfg = {"max_epochs": 2}
            snap_cfg = (None if snap_dir is None else
                        {"interval": 1, "directory": snap_dir})
            if orbax and snap_cfg is not None:
                snap_cfg["name"] = "orbax"
        wf = StandardWorkflow(
            layers=[{"type": "all2all_tanh", "output_sample_shape": 32,
                     "learning_rate": 0.1},
                    {"type": "softmax", "output_sample_shape": 10,
                     "learning_rate": 0.1}],
            loader=loader, decision_config=decision_cfg,
            snapshotter_config=snap_cfg,
            name="multihost-digits")
        if preempt or fsdp or wf.snapshotter is None:
            mesh_axes = {"data": -1}
        else:
            mesh_axes = {"model": -1}   # params shard ACROSS processes

    launcher = Launcher(workflow=wf, coordinator_address=coordinator,
                        num_processes=num_processes, process_id=process_id,
                        mesh_axes=mesh_axes, fsdp=fsdp)
    launcher.initialize()
    assert launcher.mode == "spmd"
    n_devices = len(jax.devices())
    if preempt and process_id == 0:
        import threading
        threading.Timer(4.0, wf.request_preempt).start()
    launcher.run()

    result = {
        "process_id": process_id,
        "process_count": jax.process_count(),
        "n_global_devices": n_devices,
        "is_master": launcher.is_master,
    }
    if preempt:
        result["preempted"] = wf.preempted_
        result["epochs"] = wf.loader.epoch_number
    else:
        m = wf.decision.epoch_metrics[1]
        result.update(loss=m["loss"], n_errors=m["n_errors"],
                      best_metric=wf.decision.best_metric)
    if wf.snapshotter is not None or fsdp:
        if wf.snapshotter is not None:
            result["snapshot"] = wf.snapshotter.destination
        w = wf.trainer.params[wf.trainer.layers[0].name]["weights"]
        result["weights_addressable"] = bool(w.is_fully_addressable)
        result["weights_spec"] = str(w.sharding.spec)
    print("METRICS " + json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
