"""End-to-end training tests — the round-1 correctness gate
(SURVEY.md §7 step 4: MLP reaches low validation error, bit-reproducible
across runs with a fixed seed).

MNIST itself is not available offline; sklearn's bundled digits dataset
(1797 8×8 images, 10 classes) exercises the identical workflow shape.
Each threshold below is the always-on proxy for a published reference
row gated for real in tests/test_accuracy_gates.py (which runs whenever
the datasets are mounted — ref docs/manualrst_veles_algorithms.rst).

Margin math (round 4): every gate = worst-of-5-seeds × 1.25, measured
by ``tools/proxy_margins.py`` on the CPU-8 test platform, seeds
{1234, 5, 9, 17, 42} — tight enough that a real regression (a broken
layer/GD/loader path costing more than the seed spread + 25% platform
drift allowance) fires the gate, instead of the old generous round
numbers that tolerated 2-4x degradation:

  digits MLP   < 0.065 ~ MNIST 784-100-10 MLP, published 1.48 % error.
                         Measured 0.0370-0.0505 (mean 0.0444);
                         1.25 x 0.0505 = 0.063.
  digits AE    < 0.25  ~ MNIST autoencoder, published val RMSE 0.5478
                         (per-element RMSE here).  Measured
                         0.1988-0.2080 (mean 0.2038); the historical
                         0.25 gate is already TIGHTER than 1.25 x worst
                         (0.260), so it stands at 1.20 x worst.
  digits conv  < 0.055 ~ cifar_caffe conv stack, published 17.21 %
                         (digits conv separates far better than CIFAR —
                         the proxy checks the conv/pool/GD path, not the
                         absolute row).  Measured 0.0236-0.0438 (mean
                         0.0357); 1.25 x 0.0438 = 0.0547.
  conv AE      < 0.57x ~ the relative autoencoder-beats-trivial-zeros
                         gate (no published conv-AE row).  Measured
                         0.437-0.453 x baseline (mean 0.446);
                         1.25 x 0.453 = 0.567."""

import numpy as np
import pytest
from sklearn.datasets import load_digits

from veles_tpu import prng
from veles_tpu.config import root
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow


def digits_data():
    d = load_digits()
    x = (d.data / 16.0).astype(np.float32)
    y = d.target.astype(np.int32)
    return x, y


def make_workflow(max_epochs=25, seed=1234, snapshotter_config=None):
    prng.seed_all(seed)
    x, y = digits_data()
    loader = FullBatchLoader(
        None, data=x, labels=y, minibatch_size=100,
        class_lengths=[0, 297, 1500])
    return StandardWorkflow(
        layers=[
            {"type": "all2all_tanh", "output_sample_shape": 60,
             "learning_rate": 0.1, "gradient_moment": 0.9},
            {"type": "softmax", "output_sample_shape": 10,
             "learning_rate": 0.1, "gradient_moment": 0.9},
        ],
        loader=loader,
        decision_config={"max_epochs": max_epochs},
        snapshotter_config=snapshotter_config,
        name="digits-mlp")


class TestDigitsMLP:
    def test_trains_to_low_validation_error(self):
        wf = make_workflow()
        wf.initialize()
        wf.run()
        val = wf.decision.best_metric
        assert val is not None and val < 0.065, \
            "validation error %.3f not < 6.5%% (margin math in module " \
            "docstring)" % val

    def test_bit_reproducible_with_fixed_seed(self):
        def run():
            wf = make_workflow(max_epochs=3, seed=77)
            wf.initialize()
            wf.run()
            return (wf.decision.best_metric,
                    np.asarray(wf.trainer.params[
                        wf.trainer.layers[0].name]["weights"]))

        m1, w1 = run()
        m2, w2 = run()
        assert m1 == m2
        np.testing.assert_array_equal(w1, w2)

    def test_forward_fn_serves_probabilities(self):
        wf = make_workflow(max_epochs=5)
        wf.initialize()
        wf.run()
        fwd = wf.forward_fn()
        x, y = digits_data()
        probs = np.asarray(fwd(wf.trainer.params, x[:32]))
        assert probs.shape == (32, 10)
        np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)
        acc = (probs.argmax(axis=1) == y[:32]).mean()
        assert acc > 0.8


class TestSnapshotResume:
    def test_snapshot_and_resume_continue_training(self, tmp_path):
        cfg = {"directory": str(tmp_path), "interval": 1, "prefix": "dig"}
        wf = make_workflow(max_epochs=2, snapshotter_config=cfg)
        wf.initialize()
        wf.run()
        snap_path = wf.snapshotter.destination
        assert snap_path is not None

        from veles_tpu.services.snapshotter import SnapshotterBase
        snap = SnapshotterBase.import_(snap_path)
        assert snap["epoch"] == 2

        wf2 = make_workflow(max_epochs=4, snapshotter_config=cfg)
        wf2.initialize()
        wf2.restore(snap)
        assert wf2.loader.epoch_number == 2
        wf2.run()
        assert wf2.loader.epoch_number == 4
        assert wf2.decision.best_metric < 0.2

    def test_warm_start_partial_restore(self, tmp_path):
        """Fine-tuning initializer: matching layers copy over, a
        resized head stays fresh, nothing else (loader/PRNG/moments)
        is touched — and the warm-started model trains on."""
        import numpy as np

        from veles_tpu.loader.fullbatch import FullBatchLoader
        from veles_tpu.services.snapshotter import TrainingSnapshotter

        cfg = {"directory": str(tmp_path), "interval": 1, "prefix": "dig"}
        wf = make_workflow(max_epochs=2, snapshotter_config=cfg)
        wf.initialize()
        wf.run()
        snap = wf.snapshotter.collect()

        # same trunk, DIFFERENT head width: 5 coarse classes
        prng.seed_all(77)
        x, y = digits_data()
        loader = FullBatchLoader(None, data=x, labels=y // 2,
                                 minibatch_size=100,
                                 class_lengths=[0, 297, 1500])
        wf2 = StandardWorkflow(
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 60,
                 "learning_rate": 0.1, "gradient_moment": 0.9},
                {"type": "softmax", "output_sample_shape": 5,
                 "learning_rate": 0.1, "gradient_moment": 0.9},
            ],
            loader=loader, decision_config={"max_epochs": 3},
            name="digits-coarse")
        wf2.initialize()
        head_fresh = np.asarray(
            wf2.trainer.params["l01_softmax"]["weights"]).copy()
        restored, skipped = TrainingSnapshotter.warm_start(wf2, snap)
        assert restored == 2 and skipped == 2    # trunk w+b; head w+b
        np.testing.assert_array_equal(
            np.asarray(wf2.trainer.params["l00_all2all_tanh"]["weights"]),
            np.asarray(snap["params"]["l00_all2all_tanh"]["weights"]))
        np.testing.assert_array_equal(
            np.asarray(wf2.trainer.params["l01_softmax"]["weights"]),
            head_fresh)
        assert wf2.loader.epoch_number == 0      # NOT an exact resume
        wf2.run()
        assert wf2.decision.best_metric < 0.2    # fine-tunes fine

    def test_orbax_backend_snapshot_and_resume(self, tmp_path):
        """The orbax sharded backend (snapshotter_config name="orbax" —
        SURVEY §5's prescribed TPU equivalent: arrays saved as live
        jax.Arrays, no host gather) round-trips through --snapshot-auto
        style import and resumes to the exact uninterrupted metrics."""
        cfg = {"name": "orbax", "directory": str(tmp_path),
               "interval": 1, "prefix": "ox"}
        wf = make_workflow(max_epochs=2, snapshotter_config=cfg)
        wf.initialize()
        wf.run()
        import os as _os
        dest = wf.snapshotter.destination
        assert dest.endswith(".orbax") and _os.path.isdir(dest)

        from veles_tpu.services.snapshotter import SnapshotterBase
        cur = _os.path.join(str(tmp_path), "ox_current")
        snap = SnapshotterBase.import_(cur)     # follows the symlink
        assert snap["epoch"] == 2

        wf2 = make_workflow(max_epochs=4, snapshotter_config=cfg)
        wf2.initialize()
        wf2.restore(snap)
        wf2.run()
        wf3 = make_workflow(max_epochs=4)
        wf3.initialize()
        wf3.run()
        assert wf2.decision.best_metric == wf3.decision.best_metric

    def test_orbax_backend_async_write(self, tmp_path):
        """async_write rides orbax's AsyncCheckpointer; flush() is the
        barrier before reading the checkpoint back."""
        cfg = {"name": "orbax", "directory": str(tmp_path),
               "interval": 1, "prefix": "oxa", "async_write": True}
        wf = make_workflow(max_epochs=2, snapshotter_config=cfg)
        wf.initialize()
        wf.run()
        wf.snapshotter.flush()
        from veles_tpu.services.snapshotter import SnapshotterBase
        snap = SnapshotterBase.import_(wf.snapshotter.destination)
        assert snap["epoch"] == 2 and "params" in snap

    def test_current_symlink(self, tmp_path):
        cfg = {"directory": str(tmp_path), "interval": 1, "prefix": "dig"}
        wf = make_workflow(max_epochs=1, snapshotter_config=cfg)
        wf.initialize()
        wf.run()
        import os
        cur = os.path.join(str(tmp_path), "dig_current")
        assert os.path.islink(cur)
        from veles_tpu.services.snapshotter import SnapshotterBase
        snap = SnapshotterBase.import_(cur)
        assert "params" in snap and "prng" in snap


class TestAutoencoderMSE:
    def test_mse_autoencoder_reduces_rmse(self):
        prng.seed_all(5)
        x, _ = digits_data()
        loader = FullBatchLoader(
            None, data=x, minibatch_size=100,
            class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 16,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
                {"type": "all2all", "output_sample_shape": 64,
                 "learning_rate": 0.05, "gradient_moment": 0.9},
            ],
            loader=loader, loss="mse",
            decision_config={"max_epochs": 20},
            name="digits-ae")
        wf.initialize()
        wf.run()
        # per-element RMSE; gate = 1.20 x worst-of-5-seeds (docstring)
        assert wf.decision.best_metric < 0.25, wf.decision.best_metric


class TestConvWorkflow:
    def test_small_convnet_trains(self):
        prng.seed_all(9)
        x, y = digits_data()
        x_img = x.reshape(-1, 8, 8, 1)
        loader = FullBatchLoader(
            None, data=x_img, labels=y, minibatch_size=100,
            class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=[
                {"type": "conv_strict_relu", "n_kernels": 8, "kx": 3,
                 "ky": 3, "learning_rate": 0.1, "gradient_moment": 0.9},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.1, "gradient_moment": 0.9},
            ],
            loader=loader,
            decision_config={"max_epochs": 25},
            name="digits-conv")
        wf.initialize()
        wf.run()
        assert wf.decision.best_metric < 0.055, wf.decision.best_metric


class TestGroupNormConv:
    def test_modern_conv_stack_with_group_norm_trains(self):
        """conv → group_norm → pool → softmax (the post-LRN conv recipe;
        GroupNorm layer is beyond the reference's registry): trains to
        the same gate as the plain conv proxy."""
        prng.seed_all(13)
        x, y = digits_data()
        x_img = x.reshape(-1, 8, 8, 1)
        loader = FullBatchLoader(
            None, data=x_img, labels=y, minibatch_size=100,
            class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=[
                {"type": "conv_strict_relu", "n_kernels": 8, "kx": 3,
                 "ky": 3, "learning_rate": 0.1, "gradient_moment": 0.9},
                {"type": "group_norm", "groups": 4,
                 "learning_rate": 0.1, "gradient_moment": 0.9},
                {"type": "max_pooling", "kx": 2, "ky": 2},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.1, "gradient_moment": 0.9},
            ],
            loader=loader, decision_config={"max_epochs": 25},
            name="digits-gn-conv")
        wf.initialize()
        wf.run()
        assert wf.decision.best_metric < 0.055, wf.decision.best_metric


class TestResNetGN:
    def test_residual_block_shapes_and_projection(self):
        from veles_tpu.models.layers import make_layer
        blk = make_layer({"type": "conv_residual_block", "n_kernels": 8})
        assert blk.setup((8, 8, 8)) == (8, 8, 8)
        assert not blk.needs_proj
        blk2 = make_layer({"type": "conv_residual_block",
                           "n_kernels": 16, "sliding": (2, 2)})
        assert blk2.setup((8, 8, 8)) == (4, 4, 16)
        assert blk2.needs_proj
        from veles_tpu import prng
        prng.seed_all(1)
        p = blk2.init_params(prng.get("t"))
        assert set(p) == {"gn1", "conv1", "gn2", "conv2", "proj"}
        import jax.numpy as jnp
        x = jnp.ones((2, 8, 8, 8))
        assert blk2.apply(p, x).shape == (2, 4, 4, 16)

    def test_tiny_resnet_trains_on_digits(self):
        """The resnet_gn zoo family (pre-activation residual blocks +
        GroupNorm) trains end-to-end through the standard hot loop.
        Gate = worst-of-4-seeds x 1.25 (same margin method as the
        module docstring): measured 0.0303-0.0606 over seeds
        {21, 7, 42, 5}; 1.25 x 0.0606 = 0.076."""
        from veles_tpu.models.zoo import resnet_gn
        prng.seed_all(21)
        x, y = digits_data()
        x_img = x.reshape(-1, 8, 8, 1)
        loader = FullBatchLoader(
            None, data=x_img, labels=y, minibatch_size=100,
            class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=resnet_gn(n_classes=10, width=8, blocks_per_stage=1,
                             stages=2, pool=4, lr=0.05),
            loader=loader, decision_config={"max_epochs": 25},
            name="digits-resnet")
        wf.initialize()
        wf.run()
        assert wf.decision.best_metric < 0.076, wf.decision.best_metric


class TestConvAutoencoder:
    def test_conv_autoencoder_reduces_rmse(self):
        from veles_tpu.models.zoo import conv_autoencoder
        prng.seed_all(17)
        x, _ = digits_data()
        x_img = x.reshape(-1, 8, 8, 1)
        loader = FullBatchLoader(
            None, data=x_img, minibatch_size=100,
            class_lengths=[0, 297, 1500])
        wf = StandardWorkflow(
            layers=conv_autoencoder(n_kernels=8, lr=0.02),
            loader=loader, loss="mse",
            decision_config={"max_epochs": 15},
            name="digits-conv-ae")
        wf.initialize()
        wf.run()
        # encoder halves the resolution through a 2x2 pool; decoder must
        # reconstruct below the trivial all-zeros baseline RMSE.
        # Gate = 1.25 x worst-of-5-seeds fraction (module docstring)
        baseline = float(np.sqrt((x_img ** 2).mean()))
        assert wf.decision.best_metric < 0.57 * baseline, \
            wf.decision.best_metric / baseline


def test_custom_registered_loss_trains():
    """r2: the evaluator registry seam (ref pluggable evaluator units) —
    a loss registered by name drives training with no trainer changes."""
    import jax.numpy as jnp
    from sklearn.datasets import load_digits

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.ops import losses

    if "scaled_xent_test" not in losses._LOSSES:
        @losses.register_loss("scaled_xent_test", kind="class")
        def scaled_xent(out, lbl, tgt, valid):
            loss_sum, err_sum, n_valid = losses.masked_softmax_xent(
                out, lbl, valid)
            return 2.0 * loss_sum, err_sum, n_valid, 1

    prng.seed_all(5)
    d = load_digits()
    x = (d.data / 16.0).astype("float32")
    y = d.target.astype("int32")
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                             class_lengths=[0, 297, 1500])
    wf = StandardWorkflow(
        layers=[{"type": "all2all_tanh", "output_sample_shape": 32,
                 "learning_rate": 0.05},
                {"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.05}],
        loader=loader, loss="scaled_xent_test",
        decision_config={"max_epochs": 4}, name="custom-loss")
    wf.initialize()
    wf.run()
    assert wf.decision.best_metric < 0.3


def test_unknown_loss_name_raises():
    import pytest as _pytest

    from veles_tpu.ops.losses import get_loss
    with _pytest.raises(KeyError, match="registered"):
        get_loss("nope")


def test_decision_watch_class_option():
    """r2: Decision can watch an explicit split (ref pluggable decision
    configs) instead of validation-else-train."""
    from sklearn.datasets import load_digits

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    prng.seed_all(6)
    d = load_digits()
    x = (d.data / 16.0).astype("float32")
    y = d.target.astype("int32")
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=99,
                             class_lengths=[297, 0, 1500])
    wf = StandardWorkflow(
        layers=[{"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.1}],
        loader=loader,
        decision_config={"max_epochs": 3, "watch": "test"},
        name="watch-test")
    wf.initialize()
    wf.run()
    # best metric derives from the test split stats
    assert wf.decision.best_metric is not None
    assert wf.decision.epoch_metrics[0] is not None

    import pytest as _pytest
    with _pytest.raises(ValueError, match="watch"):
        StandardWorkflow(
            layers=[{"type": "softmax", "output_sample_shape": 10}],
            loader=FullBatchLoader(None, data=x, labels=y,
                                   minibatch_size=99,
                                   class_lengths=[297, 0, 1500]),
            decision_config={"watch": "bogus"}, name="watch-bad")


def test_async_snapshot_write(tmp_path):
    """r2: async checkpoint writer — the train loop pays only the
    device->host gather; the pickle+write happens on a worker thread."""
    from sklearn.datasets import load_digits

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.services.snapshotter import SnapshotterBase
    prng.seed_all(8)
    d = load_digits()
    x = (d.data / 16.0).astype("float32")
    y = d.target.astype("int32")
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                             class_lengths=[0, 297, 1500])
    wf = StandardWorkflow(
        layers=[{"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.1}],
        loader=loader, decision_config={"max_epochs": 2},
        snapshotter_config={"interval": 1, "async_write": True,
                            "directory": str(tmp_path)},
        name="async-snap")
    wf.initialize()
    wf.run()
    wf.snapshotter.flush()
    assert wf.snapshotter.destination is not None
    snap = SnapshotterBase.import_(wf.snapshotter.destination)
    assert snap["epoch"] >= 1
    assert "params" in snap and "prng" in snap
    # the _current link points at a complete, loadable snapshot
    cur = str(tmp_path / "async-snap_current")
    assert SnapshotterBase.import_(cur)["epoch"] == snap["epoch"]


def test_decision_watch_empty_split_rejected():
    from sklearn.datasets import load_digits

    import pytest as _pytest
    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    prng.seed_all(6)
    d = load_digits()
    x = (d.data / 16.0).astype("float32")
    y = d.target.astype("int32")
    wf = StandardWorkflow(
        layers=[{"type": "softmax", "output_sample_shape": 10}],
        loader=FullBatchLoader(None, data=x, labels=y, minibatch_size=99,
                               class_lengths=[0, 297, 1500]),
        decision_config={"watch": "test"}, name="watch-empty")
    with _pytest.raises(ValueError, match="no test samples"):
        wf.initialize()


class TestRestoreExactness:
    """PR 8 satellite: Snapshotter.restore exactness pinned at UNIT
    level — PRNG counter position, loader position/shuffle order, and
    optimizer-slot restoration each independently, plus the mid-sweep
    preemption resume end to end (bit-identical to uninterrupted,
    including the decision's epoch metrics)."""

    def test_prng_counter_position_restored_exactly(self):
        import jax

        prng.seed_all(123)
        g = prng.get("exactness-drill")
        for _ in range(5):
            g.key()
        saved = prng.states()
        expect_keys = [np.asarray(jax.random.key_data(g.key()))
                       for _ in range(3)]
        expect_perm = g.permutation(32)
        # scrub: different base seed AND consumed counters
        prng.seed_all(999)
        g2 = prng.get("exactness-drill")
        g2.key()
        g2.key()
        prng.restore_states(saved)
        g3 = prng.get("exactness-drill")
        assert g3._counter == 5            # counter position, not just seed
        replay_keys = [np.asarray(jax.random.key_data(g3.key()))
                       for _ in range(3)]
        for a, b in zip(expect_keys, replay_keys):
            np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(expect_perm, g3.permutation(32))
        prng.seed_all(1234)                # leave the registry tidy

    def test_loader_position_epoch_and_shuffle_restored_exactly(self):
        def make_loader():
            x, y = digits_data()
            return FullBatchLoader(
                None, data=x, labels=y, minibatch_size=100,
                class_lengths=[0, 297, 1500])

        prng.seed_all(7)
        loader = make_loader()
        loader.initialize()
        for _ in range(25):                # into the train span, epoch 1
            loader.run()
        st = loader.state
        assert st["minibatch_offset"] == loader.minibatch_offset
        assert st["prng"]["counter"] > 0   # self-contained stream words
        golden = []
        for _ in range(40):                # crosses the epoch boundary
            loader.run()
            golden.append((loader.epoch_number, loader.minibatch_class,
                           loader.minibatch_offset,
                           loader.minibatch_indices.copy()))
        # fresh loader under a DIFFERENT global seed: only the captured
        # state may drive the replay (the reshuffle must come from the
        # restored (seed, counter) words, not ambient registry state)
        prng.seed_all(4242)
        loader2 = make_loader()
        loader2.initialize()
        loader2.state = st
        assert loader2.epoch_number == st["epoch_number"]
        assert loader2.minibatch_offset == st["minibatch_offset"]
        for epoch, cls, offset, idx in golden:
            loader2.run()
            assert (loader2.epoch_number, loader2.minibatch_class,
                    loader2.minibatch_offset) == (epoch, cls, offset)
            np.testing.assert_array_equal(loader2.minibatch_indices,
                                          idx)
        prng.seed_all(1234)

    def test_optimizer_slots_restored_exactly(self, tmp_path):
        cfg = {"directory": str(tmp_path), "interval": 1, "prefix": "os"}
        wf = make_workflow(max_epochs=2, snapshotter_config=cfg)
        wf.initialize()
        wf.run()
        snap = wf.snapshotter.collect()
        # momentum slots are real (nonzero) at the capture point
        import jax
        vel_leaves = [np.asarray(v) for v in
                      jax.tree_util.tree_leaves(snap["velocity"])]
        assert any(np.abs(v).max() > 0 for v in vel_leaves)

        wf2 = make_workflow(max_epochs=4, snapshotter_config=cfg)
        wf2.initialize()
        wf2.restore(snap)
        # slot-by-slot bit equality immediately after restore
        import jax
        restored = jax.tree_util.tree_map(np.asarray,
                                          wf2.trainer.velocity)
        for (pa, va), (pb, vb) in zip(
                sorted(jax.tree_util.tree_flatten_with_path(
                    snap["velocity"])[0], key=lambda kv: str(kv[0])),
                sorted(jax.tree_util.tree_flatten_with_path(
                    restored)[0], key=lambda kv: str(kv[0]))):
            assert str(pa) == str(pb)
            np.testing.assert_array_equal(np.asarray(va),
                                          np.asarray(vb))
        # and the continuation equals an uninterrupted run bit-for-bit
        wf2.run()
        wf3 = make_workflow(max_epochs=4)
        wf3.initialize()
        wf3.run()
        np.testing.assert_array_equal(
            np.asarray(wf2.trainer.host_params()[
                wf2.trainer.layers[0].name]["weights"]),
            np.asarray(wf3.trainer.host_params()[
                wf3.trainer.layers[0].name]["weights"]))
        for (pa, va), (pb, vb) in zip(
                sorted(jax.tree_util.tree_flatten_with_path(
                    wf2.trainer.host_velocity())[0],
                    key=lambda kv: str(kv[0])),
                sorted(jax.tree_util.tree_flatten_with_path(
                    wf3.trainer.host_velocity())[0],
                    key=lambda kv: str(kv[0]))):
            np.testing.assert_array_equal(np.asarray(va),
                                          np.asarray(vb))

    def test_midsweep_preempt_resume_bit_identical(self, tmp_path):
        """SIGTERM-style preemption MID-SWEEP: the checkpoint lands at a
        cycle boundary inside an epoch (loader offset > 0), and the
        resumed run's final state — params, velocity, PRNG, loader,
        decision metrics INCLUDING the interrupted epoch's — is
        bit-identical to an uninterrupted golden run."""
        from veles_tpu.services.snapshotter import (SnapshotterBase,
                                                    iter_state_leaves)

        cfg = {"directory": str(tmp_path / "c"), "interval": 1,
               "prefix": "pre"}
        wf = make_workflow(max_epochs=3, snapshotter_config=cfg)
        wf.initialize()
        runs = {"n": 0}
        orig_run = wf.trainer.run

        def hooked():
            orig_run()
            runs["n"] += 1
            if runs["n"] == 25:       # inside epoch 1's train span
                wf.request_preempt()

        wf.trainer.run = hooked
        wf.run()
        assert wf.preempted_
        snap = SnapshotterBase.import_(wf.snapshotter.destination)
        assert snap["loader"]["minibatch_offset"] > 0   # truly mid-sweep
        assert snap["epoch"] == 1
        # the mid-sweep accumulators made it into the checkpoint
        assert "trainer_stats" in snap
        assert snap["decision"]["epoch_metrics"][1] is not None

        wf2 = make_workflow(max_epochs=3, snapshotter_config={
            "directory": str(tmp_path / "r"), "interval": 1,
            "prefix": "pre"})
        wf2.initialize()
        wf2.restore(snap)
        wf2.run()
        golden = make_workflow(max_epochs=3, snapshotter_config={
            "directory": str(tmp_path / "g"), "interval": 1,
            "prefix": "pre"})
        golden.initialize()
        golden.run()
        a = dict(iter_state_leaves(wf2.snapshotter.collect()))
        b = dict(iter_state_leaves(golden.snapshotter.collect()))
        assert set(a) == set(b)
        for path in sorted(a):
            va, vb = a[path], b[path]
            if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
                np.testing.assert_array_equal(
                    np.asarray(va), np.asarray(vb), err_msg=path)
            else:
                assert va == vb, "%s: %r != %r" % (path, va, vb)


def test_db_snapshotter_async(tmp_path):
    from sklearn.datasets import load_digits

    from veles_tpu import prng
    from veles_tpu.loader.fullbatch import FullBatchLoader
    from veles_tpu.models.standard_workflow import StandardWorkflow
    from veles_tpu.services.snapshotter import DBSnapshotter
    prng.seed_all(9)
    d = load_digits()
    x = (d.data / 16.0).astype("float32")
    y = d.target.astype("int32")
    dsn = str(tmp_path / "snaps.sqlite")
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=100,
                             class_lengths=[0, 297, 1500])
    wf = StandardWorkflow(
        layers=[{"type": "softmax", "output_sample_shape": 10,
                 "learning_rate": 0.1}],
        loader=loader, decision_config={"max_epochs": 2},
        snapshotter_config={"name": "db", "dsn": dsn, "interval": 1,
                            "async_write": True},
        name="db-async")
    wf.initialize()
    wf.run()
    wf.snapshotter.flush()
    snap = DBSnapshotter.import_db(dsn)
    assert snap["epoch"] >= 1 and "params" in snap
