"""Autoscaling serving plane — the PURE policy core of
services.podmaster's ServeFleetMaster (no sockets, no subprocesses):
the FleetAutoscaler's measured-feedback decisions (overshoot /
serve.shed → scale-up, sustained idle → scale-down, cooldown, min/max
clamps), the PodValves scale bucket (flap damping that can never
consume the crash-loop budget), the plan_fleet reconciler
(replacement-on-host-death as plain reconciliation, per-host caps,
deterministic placement/drain order), the dead-replica classifier,
the router's staggered health-probe phases (pinned), the shedder's
overshoot surface, and the veles_fleet_* gauges."""

import time

import pytest

from veles_tpu.services.lifecycle import SloShedder
from veles_tpu.services.podmaster import (FleetAutoscaler, PodValves,
                                          ServeFleetMaster,
                                          dead_replica_verdicts,
                                          plan_fleet)
from veles_tpu.services.router import FleetRouter


def _rep(host, state, ready_ts=None, rid=None):
    return {"host": host, "state": state, "rid": rid, "port": None,
            "pid": None, "spawn_ts": 0.0, "ready_ts": ready_ts,
            "exit": None}


# ===================================================================
# FleetAutoscaler — the closed-loop decisions
# ===================================================================

def _sig(overshoot=0.0, shed_total=0, busy=False):
    return {"overshoot": overshoot, "shed_total": shed_total,
            "busy": busy}


class TestFleetAutoscaler:
    def test_overshoot_scales_up(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=30, cooldown_s=5)
        delta, reason = a.decide(0.0, 2, 1, 4, _sig(overshoot=1.5))
        assert delta == +1
        assert "overshoot" in reason

    def test_under_slo_never_scales_up(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=30, cooldown_s=5)
        # busy but UNDER the SLO: capacity is adequate — no decision
        delta, _ = a.decide(0.0, 2, 1, 4,
                            _sig(overshoot=0.9, busy=True))
        assert delta == 0

    def test_fresh_sheds_scale_up(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=30, cooldown_s=5)
        a.decide(0.0, 2, 1, 4, _sig(shed_total=10, busy=True))
        # shed_total is monotonic: only a DELTA means fresh rejections
        delta, _ = a.decide(10.0, 2, 1, 4,
                            _sig(shed_total=10, busy=True))
        assert delta == 0
        delta, reason = a.decide(20.0, 2, 1, 4,
                                 _sig(shed_total=13, busy=True))
        assert delta == +1
        assert "shed_delta=3" in reason

    def test_max_clamp(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=30, cooldown_s=0)
        delta, reason = a.decide(0.0, 4, 1, 4, _sig(overshoot=9.0))
        assert delta == 0
        assert "max" in reason

    def test_sustained_idle_scales_down_after_idle_s(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=10, cooldown_s=0)
        assert a.decide(0.0, 3, 1, 4, _sig())[0] == 0   # idle starts
        assert a.decide(5.0, 3, 1, 4, _sig())[0] == 0   # not yet
        delta, reason = a.decide(10.0, 3, 1, 4, _sig())
        assert delta == -1
        assert "idle" in reason

    def test_busy_resets_the_idle_clock(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=10, cooldown_s=0)
        a.decide(0.0, 3, 1, 4, _sig())
        a.decide(9.0, 3, 1, 4, _sig(busy=True))    # work arrived
        assert a.decide(12.0, 3, 1, 4, _sig())[0] == 0
        assert a.decide(19.0, 3, 1, 4, _sig())[0] == 0
        assert a.decide(22.0, 3, 1, 4, _sig())[0] == -1

    def test_min_clamp(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=5, cooldown_s=0)
        a.decide(0.0, 1, 1, 4, _sig())
        delta, reason = a.decide(10.0, 1, 1, 4, _sig())
        assert delta == 0
        assert "min" in reason

    def test_cooldown_spaces_decisions_but_idle_clock_runs(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=10,
                            cooldown_s=60)
        assert a.decide(0.0, 2, 1, 4, _sig(overshoot=2.0))[0] == +1
        # still overloaded 5s later: cooldown holds the next step
        delta, reason = a.decide(5.0, 3, 1, 4, _sig(overshoot=2.0))
        assert (delta, reason) == (0, "cooldown")
        # load vanished at t=10; idle accrued THROUGH the cooldown,
        # so the first post-cooldown step may already scale down
        a.decide(10.0, 3, 1, 4, _sig())
        assert a.decide(70.0, 3, 1, 4, _sig())[0] == -1

    def test_one_step_at_a_time(self):
        # the controller is closed-loop: a 10x overshoot still adds
        # ONE replica per decision (the effect must be measured
        # before the next step)
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=30, cooldown_s=0)
        assert a.decide(0.0, 1, 1, 8, _sig(overshoot=10.0))[0] == +1


# ===================================================================
# PodValves scale bucket — flap damping, isolated budgets
# ===================================================================

class TestScaleValve:
    def test_flap_damping_window(self):
        v = PodValves(8, 600, 3, scale_max_per_window=2,
                      scale_window_seconds=100.0)
        assert v.admit_scale(0.0) == "scale"
        assert v.admit_scale(10.0) == "scale"
        assert v.admit_scale(20.0) == "damped"     # window full
        assert v.admit_scale(110.1) == "scale"     # window slid
        assert v.scale_events == 3
        assert v.scale_damped == 1

    def test_scale_never_consumes_the_crash_loop_budget(self):
        v = PodValves(2, 600, 3, scale_max_per_window=100,
                      scale_window_seconds=600)
        for t in range(50):
            assert v.admit_scale(float(t)) == "scale"
        # the crash-loop window is untouched: two counted restarts
        # still fit
        assert v.admit(100.0) == "respawn"
        assert v.admit(101.0) == "respawn"
        assert v.admit(102.0) == "crash-loop"

    def test_crashes_never_consume_the_scale_budget(self):
        v = PodValves(100, 600, 99, scale_max_per_window=1,
                      scale_window_seconds=600)
        for t in range(10):
            v.admit(float(t), ("sig",))
        assert v.admit_scale(50.0) == "scale"

    def test_default_construction_unchanged(self):
        # PR 9/10 call sites pass three positionals — must keep working
        v = PodValves(8, 600, 3)
        assert v.admit(0.0) == "respawn"
        assert v.scale_events == 0


# ===================================================================
# plan_fleet — declarative reconciliation
# ===================================================================

class TestPlanFleet:
    def test_initial_spread_least_loaded_first(self):
        spawns, drains = plan_fleet(3, [0, 1], 2, {})
        assert spawns == [0, 1, 0]
        assert drains == []

    def test_per_host_cap(self):
        spawns, _ = plan_fleet(5, [0, 1], 2, {})
        assert len(spawns) == 4                  # 2 hosts x cap 2
        assert sorted(spawns) == [0, 0, 1, 1]

    def test_steady_state_no_actions(self):
        spawns, drains = plan_fleet(2, [0, 1], 2, {0: 0, 1: 1})
        assert (spawns, drains) == ([], [])

    def test_replacement_on_host_death(self):
        # host 1 died: its replica vanishes from the live-host view
        # and reconciliation re-places it on the survivor — the
        # replacement path IS the plain plan, no special case
        spawns, drains = plan_fleet(2, [0], 2, {0: 0, 1: 1})
        assert spawns == [0]
        assert drains == []

    def test_replacement_respects_survivor_cap(self):
        # survivor already full: the spec is unsatisfiable — plan
        # what fits, never overload the survivor
        spawns, _ = plan_fleet(4, [0], 2, {0: 0, 1: 0, 2: 1, 3: 1})
        assert spawns == []

    def test_scale_down_drains_newest_on_most_loaded(self):
        spawns, drains = plan_fleet(
            2, [0, 1], 4, {0: 0, 1: 1, 2: 0, 3: 0})
        assert spawns == []
        assert drains == [3, 2]

    def test_drainable_restriction(self):
        # a replica still SPAWNING is not drainable (it serves
        # nothing to drain); surplus waits for it to become ready
        spawns, drains = plan_fleet(
            1, [0], 4, {0: 0, 1: 0, 2: 0}, drainable=[0, 1])
        assert spawns == []
        assert drains == [1, 0]

    def test_draining_still_occupies_its_slot(self):
        # rep 1 is draining: it neither counts toward desired nor
        # gets drained again, but its host slot stays occupied until
        # it exits
        spawns, drains = plan_fleet(
            2, [0], 2, {0: 0, 1: 0}, draining=[1])
        assert (spawns, drains) == ([], [])


# ===================================================================
# dead_replica_verdicts — host death vs sick process
# ===================================================================

class TestDeadReplicaVerdicts:
    def test_host_death(self):
        reps = {0: {"host": 0, "state": "ready", "rid": 7}}
        assert dead_replica_verdicts(
            reps, {7: "down"}, {0: False}) == [(0, "host-death")]

    def test_sick_process_on_live_host(self):
        reps = {0: {"host": 0, "state": "ready", "rid": 7}}
        assert dead_replica_verdicts(
            reps, {7: "down"}, {0: True}) == [(0, "down")]

    def test_up_and_draining_are_not_dead(self):
        reps = {0: {"host": 0, "state": "ready", "rid": 7},
                1: {"host": 0, "state": "ready", "rid": 8}}
        assert dead_replica_verdicts(
            reps, {7: "up", 8: "draining"}, {0: False}) == []

    def test_only_ready_replicas_classified(self):
        # spawning/draining/dead manager states are someone else's
        # problem (ready-timeout, drain completion)
        reps = {0: {"host": 0, "state": "spawning", "rid": None},
                1: {"host": 0, "state": "draining", "rid": 9}}
        assert dead_replica_verdicts(
            reps, {9: "down"}, {0: False}) == []


# ===================================================================
# ServeFleetMaster death handling (no sockets: unstarted master)
# ===================================================================

def _master(tmp_path, **kw):
    kw.setdefault("spawn_agents", False)
    kw.setdefault("min_uptime_s", 30.0)
    return ServeFleetMaster(["true"], n_hosts=2, fleet_min=1,
                            fleet_max=4, per_host=4,
                            workdir=str(tmp_path), **kw)


class TestReplicaExitPolicy:
    def test_unplanned_clean_exit_loop_trips_the_valve(self, tmp_path):
        # a misconfigured replica command that exits 0 instantly must
        # NOT respawn unbudgeted forever: unplanned "done" counts,
        # with a stable "clean-exit" signature, so the deterministic
        # valve holds replacements
        m = _master(tmp_path, deterministic_limit=3)
        now = time.time()
        for i in range(3):
            m.reps[i] = _rep(0, "ready", ready_ts=now)
            m._handle_replica_exit(
                0, {"rep": i, "rc": 0, "kind": "done"}, now)
        assert m.hold_replace == "deterministic-bug"
        assert [h["verdict"] for h in m.history][-1] \
            == "deterministic-bug"

    def test_long_served_replica_exit_is_progress(self, tmp_path):
        # a replica that served past min_uptime_s resets the
        # deterministic counter — only instant-exit loops latch
        m = _master(tmp_path, deterministic_limit=3, min_uptime_s=10)
        now = time.time()
        for i in range(5):
            m.reps[i] = _rep(0, "ready", ready_ts=now - 60)
            m._handle_replica_exit(
                0, {"rep": i, "rc": 0, "kind": "done"}, now)
        assert m.hold_replace is None
        assert m.replaced_total == 5

    def test_env_flake_uncounted(self, tmp_path):
        m = _master(tmp_path, max_restarts=1, window_seconds=600)
        now = time.time()
        for i in range(6):
            m.reps[i] = _rep(0, "ready", ready_ts=now)
            m._handle_replica_exit(
                0, {"rep": i, "rc": -11, "kind": "env-flake"}, now)
        assert m.hold_replace is None          # never counted
        assert m.replaced_total == 6

    def test_lost_host_reaps_stranded_replicas(self, tmp_path):
        # spawning/dying/draining replicas on a host the strike
        # ladder declared LOST get no exit report ever — they must be
        # reaped (replaced in the resize bucket / recorded as a dirty
        # drain), not hold phantom slots forever
        m = _master(tmp_path)
        now = time.time()
        m.lost_hosts.add(1)
        m.reps[5] = _rep(1, "spawning")
        m.reps[6] = _rep(1, "draining", ready_ts=now - 60)
        m.reps[7] = _rep(0, "spawning")        # live host: untouched
        m._reap_lost_host_replicas(now)
        assert m.reps[5]["state"] == "dead"
        assert m.reps[6]["state"] == "dead"
        assert m.reps[7]["state"] == "spawning"
        replaces = [h for h in m.history
                    if h.get("action") == "replace"]
        assert [(h["rep"], h["cause"], h["counted"])
                for h in replaces] == [(5, "host-death", False)]
        assert m.valves.resize_restarts == 1   # planned recovery
        assert [(d["rep"], d["kind"], d["was_ready"])
                for d in m.drained] == [(6, "host-death", True)]


# ===================================================================
# staggered health probes — the phase function, pinned
# ===================================================================

class TestProbePhase:
    def test_deterministic_and_bounded(self):
        for rid in range(64):
            p = FleetRouter.probe_phase(rid, 0.1)
            assert 0.0 <= p < 0.1
            assert p == FleetRouter.probe_phase(rid, 0.1)

    def test_pinned_spacing(self):
        # golden-ratio spacing, pinned: these exact offsets are the
        # contract (a change here changes every fleet's probe timing)
        assert FleetRouter.probe_phase(0, 1.0) == pytest.approx(
            0.6180339887498949)
        assert FleetRouter.probe_phase(1, 1.0) == pytest.approx(
            0.2360679774997898)
        assert FleetRouter.probe_phase(2, 1.0) == pytest.approx(
            0.8541019662496847)
        assert FleetRouter.probe_phase(3, 1.0) == pytest.approx(
            0.4721359549995796)

    def test_first_probe_never_races_registration(self):
        # strictly positive phase: no replica's FIRST probe fires at
        # the registration instant (the optimistic-up window exists)
        for rid in range(256):
            assert FleetRouter.probe_phase(rid, 0.1) > 0.0

    def test_no_lockstep_at_scale(self):
        # any two of the first 32 replicas are at least interval/64
        # apart — N probes never fire as one synchronized herd
        interval = 0.1
        phases = sorted(FleetRouter.probe_phase(r, interval)
                        for r in range(32))
        gaps = [b - a for a, b in zip(phases, phases[1:])]
        assert min(gaps) > interval / 64

    def test_scales_with_interval(self):
        assert FleetRouter.probe_phase(5, 2.0) == pytest.approx(
            2.0 * ((6 * 0.6180339887498949) % 1.0))


# ===================================================================
# shedder overshoot surface + fleet gauges
# ===================================================================

class TestFleetObservability:
    def test_shedder_overshoot_in_status(self):
        s = SloShedder(slo_ms=100.0)
        s.update(head_wait_ms=250.0)
        assert s.overshoot() == pytest.approx(2.5)
        st = s.status()
        assert st["overshoot"] == pytest.approx(2.5)
        assert st["last_measure_ms"] == pytest.approx(250.0)

    def test_disabled_shedder_overshoot_zero(self):
        s = SloShedder(slo_ms=0)
        assert s.overshoot() == 0.0
        assert s.status()["overshoot"] == 0.0

    def test_fleet_gauges_and_blocks(self):
        from veles_tpu import telemetry
        router = FleetRouter(port=0, rng_seed=3)
        # never started: registry bookkeeping only
        rid = router.register("http://127.0.0.1:1/service")
        router.note_fleet(desired=3, hosts=2, replaced=0)
        router.fleet_event("scale", "up")
        router.fleet_event("replace")
        reg = telemetry.registry
        g = reg.gauge("veles_fleet_replicas",
                      "registered serving replicas",
                      labelnames=("state",))
        assert g.value(state="up") == 1
        assert reg.gauge("veles_fleet_desired", "").value() == 3
        assert reg.counter(
            "veles_fleet_scale_events_total", "",
            labelnames=("direction",)).value(direction="up") >= 1
        assert reg.counter(
            "veles_fleet_replaced_total", "").value() >= 1
        # the fleet block rides /metrics and /health
        assert router.metrics()["fleet"]["desired"] == 3
        assert router.fleet_health()["fleet"]["desired"] == 3
        router.deregister(rid)
        assert g.value(state="up") == 0

    def test_fleet_signals_aggregation(self):
        router = FleetRouter(port=0, rng_seed=3)
        r1 = router.register("http://127.0.0.1:1/service")
        r2 = router.register("http://127.0.0.2:1/service")
        with router._lock:
            router._replicas[r1].last_health = {
                "serving": {"overshoot": 2.5, "shed_total": 4},
                "queued": 0, "in_flight": 0}
            router._replicas[r2].last_health = {
                "serving": {"overshoot": 0.5, "shed_total": 1},
                "queued": 3, "in_flight": 1}
        sig = router.fleet_signals()
        assert sig["overshoot"] == pytest.approx(2.5)   # the WORST
        assert sig["shed_total"] == 5
        assert sig["busy"] is True
        assert sig["live"] == 2


# ===================================================================
# Cost-weighted placement + prefill/decode roles (stall-free serving)
# ===================================================================

class TestRequestCost:
    def test_price_shape(self):
        from veles_tpu.services.costing import RequestCost
        rc = RequestCost(prefill_ms_per_tok=0.01,
                         decode_ms_per_tok=1.0)
        assert rc.price(100, 8) == pytest.approx(100 * 0.01 + 8 * 1.0)
        assert rc.price(0, 0) == 0.0

    def test_calibration_tracks_measured(self):
        from veles_tpu.services.costing import RequestCost
        rc = RequestCost(prefill_ms_per_tok=0.01,
                         decode_ms_per_tok=1.0)
        rc.calibrate(2.0)
        # first sample snaps; prefill rescales by the same drift
        assert rc.decode_ms_per_tok == pytest.approx(2.0)
        assert rc.prefill_ms_per_tok == pytest.approx(0.02)
        assert rc.calibration == pytest.approx(2.0)
        # a measured prefill rate pins the prefill constant directly
        rc.calibrate(2.0, measured_prefill_ms_per_tok=0.5)
        assert rc.prefill_ms_per_tok > 0.02
        assert rc.status()["calibration"] is not None

    def test_zero_measure_ignored(self):
        from veles_tpu.services.costing import RequestCost
        rc = RequestCost(prefill_ms_per_tok=0.01,
                         decode_ms_per_tok=1.0)
        rc.calibrate(0.0)
        assert rc.calibration is None


class TestCostPlacement:
    def _router(self, **kw):
        kw.setdefault("rng_seed", 3)
        kw.setdefault("placement", "cost")
        return FleetRouter(port=0, **kw)

    def test_picks_least_loaded_by_predicted_cost(self):
        router = self._router()
        r1 = router.register("http://127.0.0.1:1/service")
        r2 = router.register("http://127.0.0.2:1/service")
        with router._lock:
            router._replicas[r1].pending_cost_ms = 500.0
            router._replicas[r2].pending_cost_ms = 10.0
        assert router._pick().rid == r2
        with router._lock:
            router._replicas[r2].pending_cost_ms = 900.0
        assert router._pick().rid == r1

    def test_health_backlog_feeds_the_pick(self):
        router = self._router()
        r1 = router.register("http://127.0.0.1:1/service")
        r2 = router.register("http://127.0.0.2:1/service")
        with router._lock:
            # equal router-tracked cost, but r1 reports a big queued
            # prefill backlog on /health — work routed around us
            router._replicas[r1].last_health = {
                "queued_prefill_tokens": 100000}
            router._replicas[r2].last_health = {
                "queued_prefill_tokens": 0}
        assert router._pick().rid == r2

    def test_idle_ties_rotate(self):
        router = self._router()
        r1 = router.register("http://127.0.0.1:1/service")
        r2 = router.register("http://127.0.0.2:1/service")
        picks = {router._pick().rid for _ in range(4)}
        assert picks == {r1, r2}

    def test_round_robin_placement_knob(self):
        router = self._router(placement="round_robin")
        r1 = router.register("http://127.0.0.1:1/service")
        r2 = router.register("http://127.0.0.2:1/service")
        with router._lock:
            router._replicas[r1].pending_cost_ms = 500.0
        picks = [router._pick().rid for _ in range(4)]
        assert sorted(set(picks)) == [r1, r2]

    def test_placement_validated(self):
        with pytest.raises(ValueError):
            FleetRouter(port=0, placement="magic")

    def test_probe_calibrates_cost_model(self):
        router = self._router()
        rid = router.register("http://127.0.0.1:1/service")
        rep = router._replicas[rid]
        rep.last_health = {}
        # feed the probe handler's calibration path directly
        router.cost.calibrate(3.0, 0.25)
        assert router.cost.decode_ms_per_tok == pytest.approx(3.0)
        assert router.metrics()["cost"]["decode_ms_per_tok"] == \
            pytest.approx(3.0)


class TestFleetRoles:
    def _router(self, **kw):
        kw.setdefault("rng_seed", 3)
        kw.setdefault("prefill_prompt_min", 16)
        kw.setdefault("prefill_handoff_new", 4)
        return FleetRouter(port=0, **kw)

    def test_role_validation_and_describe(self):
        router = self._router()
        rid = router.register("http://127.0.0.1:1/service",
                              role="prefill")
        assert router.replicas()[rid]["role"] == "prefill"
        with pytest.raises(ValueError):
            router.register("http://127.0.0.2:1/service", role="bogus")
        # re-registration validates too (a typo'd role must be LOUD,
        # not silently keep the old tier)
        with pytest.raises(ValueError):
            router.register("http://127.0.0.1:1/service", role="bogus")
        # re-registration with a VALID role updates the tier
        router.register("http://127.0.0.1:1/service", role="decode")
        assert router.replicas()[rid]["role"] == "decode"

    def test_pick_prefers_role_tier_and_falls_back(self):
        router = self._router()
        rp = router.register("http://127.0.0.1:1/service",
                             role="prefill")
        rd = router.register("http://127.0.0.2:1/service",
                             role="decode")
        assert router._pick(role="prefill").rid == rp
        # non-prefill picks keep the prefill tier clear
        assert all(router._pick().rid == rd for _ in range(3))
        # tier empty -> falls back to the whole up set (never strand)
        from veles_tpu.services.router import Replica
        with router._lock:
            router._replicas[rp].state = Replica.DOWN
        assert router._pick(role="prefill").rid == rd

    def test_handoff_plan(self):
        router = self._router()
        router.register("http://127.0.0.1:1/service", role="prefill")
        long_req = {"input": [list(range(20))],
                    "generate": {"max_new": 16}}
        role, cap = router._handoff_plan(long_req)
        assert role == "prefill" and cap == 4
        # short prompt: no role routing
        assert router._handoff_plan(
            {"input": [list(range(4))],
             "generate": {"max_new": 16}}) == (None, 0)
        # short DECODE: whole request on the prefill tier, no splice
        role, cap = router._handoff_plan(
            {"input": [list(range(20))], "generate": {"max_new": 3}})
        assert role == "prefill" and cap == 0
        # a resume continuation must never re-enter the plan
        assert router._handoff_plan(
            dict(long_req, resume=True)) == (None, 0)
        # multi-row requests are not handoff-eligible
        assert router._handoff_plan(
            {"input": [list(range(20))] * 2,
             "generate": {"max_new": 16}}) == (None, 0)

    def test_no_prefill_replica_disables_plan(self):
        router = self._router()
        router.register("http://127.0.0.1:1/service", role="decode")
        assert router._handoff_plan(
            {"input": [list(range(20))],
             "generate": {"max_new": 16}}) == (None, 0)


class TestAutoscalerPrefillBacklog:
    def test_backlog_scales_up(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=30, cooldown_s=0,
                            up_prefill_backlog=1024)
        d, reason = a.decide(0.0, 2, 1, 4, dict(
            _sig(), prefill_backlog=2048))
        assert d == +1 and "backlog=2048" in reason

    def test_backlog_below_threshold_ignored(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=30, cooldown_s=0,
                            up_prefill_backlog=1024)
        d, _ = a.decide(0.0, 2, 1, 4, dict(
            _sig(), prefill_backlog=10))
        assert d == 0

    def test_backlog_zero_knob_disables(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=30, cooldown_s=0,
                            up_prefill_backlog=0)
        d, _ = a.decide(0.0, 2, 1, 4, dict(
            _sig(), prefill_backlog=10 ** 9))
        assert d == 0

    def test_backlog_resets_idle_clock(self):
        a = FleetAutoscaler(up_overshoot=1.0, idle_s=10, cooldown_s=0,
                            up_prefill_backlog=0)
        a.decide(0.0, 2, 1, 4, dict(_sig(), prefill_backlog=5))
        # backlog kept the fleet non-idle at t=0; the idle clock only
        # starts at the first backlog-free step (t=12)
        d, _ = a.decide(12.0, 2, 1, 4, dict(_sig(), prefill_backlog=0))
        assert d == 0
        d, _ = a.decide(16.0, 2, 1, 4, dict(_sig(), prefill_backlog=0))
        assert d == 0
        d, _ = a.decide(23.0, 2, 1, 4, dict(_sig(), prefill_backlog=0))
        assert d == -1

    def test_router_signals_carry_backlog(self):
        router = FleetRouter(port=0, rng_seed=3)
        r1 = router.register("http://127.0.0.1:1/service")
        r2 = router.register("http://127.0.0.2:1/service")
        with router._lock:
            router._replicas[r1].last_health = {
                "queued_prefill_tokens": 700}
            router._replicas[r2].last_health = {
                "queued_prefill_tokens": 41}
        assert router.fleet_signals()["prefill_backlog"] == 741


class TestMasterRoles:
    def test_want_role_fills_prefill_tier_first(self, tmp_path):
        m = _master(tmp_path, prefill_replicas=1)
        with m._lock:
            assert m._want_role() == "prefill"
            m.reps[0] = dict(_rep(0, "ready"), role="prefill")
            assert m._want_role() == "decode"
            # a dead prefill replica's replacement inherits the role
            m.reps[0]["state"] = "dead"
            assert m._want_role() == "prefill"

    def test_no_roles_when_disabled(self, tmp_path):
        m = _master(tmp_path)
        with m._lock:
            assert m._want_role() is None
