"""Unit-graph runtime tests (ref: veles/tests/test_units.py,
test_workflow.py:52-312 — graph iteration, linking, gates, loop
semantics)."""

import pytest

from veles_tpu.mutable import Bool
from veles_tpu.plumbing import Repeater
from veles_tpu.units import MissingDemands, TrivialUnit, Unit
from veles_tpu.workflow import Workflow


class Recorder(Unit):
    """Appends its name to a shared trace on each run."""

    def __init__(self, workflow, trace, **kwargs):
        super(Recorder, self).__init__(workflow, **kwargs)
        self.trace = trace

    def run(self):
        self.trace.append(self.name)


def build_linear(n=3):
    wf = Workflow(name="linear")
    trace = []
    units = [Recorder(wf, trace, name="u%d" % i) for i in range(n)]
    units[0].link_from(wf.start_point)
    for a, b in zip(units, units[1:]):
        b.link_from(a)
    wf.end_point.link_from(units[-1])
    return wf, trace, units


class TestControlFlow:
    def test_linear_chain_runs_in_order(self):
        wf, trace, _ = build_linear()
        wf.initialize()
        wf.run()
        assert trace == ["u0", "u1", "u2"]

    def test_diamond_waits_for_all_predecessors(self):
        wf = Workflow(name="diamond")
        trace = []
        a = Recorder(wf, trace, name="a")
        b = Recorder(wf, trace, name="b")
        c = Recorder(wf, trace, name="c")
        d = Recorder(wf, trace, name="d")
        a.link_from(wf.start_point)
        b.link_from(a)
        c.link_from(a)
        d.link_from(b, c)
        wf.end_point.link_from(d)
        wf.initialize()
        wf.run()
        assert trace[0] == "a" and trace[-1] == "d"
        assert set(trace[1:3]) == {"b", "c"}
        assert trace.count("d") == 1

    def test_gate_block_stops_propagation(self):
        wf, trace, units = build_linear()
        units[1].gate_block <<= True
        wf.initialize()
        wf.run()
        assert trace == ["u0"]
        assert not bool(wf.stopped)  # blocked path never reached end_point

    def test_gate_skip_propagates_without_running(self):
        wf, trace, units = build_linear()
        units[1].gate_skip <<= True
        wf.initialize()
        wf.run()
        assert trace == ["u0", "u2"]

    def test_repeater_loop_until_decision(self):
        """The canonical hot loop: repeater -> body -> decision; decision
        blocks the loop and opens end_point after N iterations
        (ref workflow run loop, SURVEY §3.1)."""
        wf = Workflow(name="loop")
        trace = []
        rpt = Repeater(wf)
        body = Recorder(wf, trace, name="body")
        complete = Bool(False)

        class Decision(Unit):
            def run(self):
                if len(trace) >= 5:
                    complete.set(True)

        dec = Decision(wf)
        rpt.link_from(wf.start_point)
        body.link_from(rpt)
        dec.link_from(body)
        rpt.link_from(dec)
        rpt.gate_block = complete
        wf.end_point.link_from(dec)
        wf.end_point.gate_block = ~complete
        wf.initialize()
        wf.run()
        assert trace == ["body"] * 5
        assert bool(wf.stopped)

    def test_external_stop(self):
        wf = Workflow(name="stoppable")
        trace = []
        rpt = Repeater(wf)

        class Stopper(Unit):
            def run(self):
                trace.append("x")
                if len(trace) >= 3:
                    self.workflow.stop()

        s = Stopper(wf)
        rpt.link_from(wf.start_point)
        s.link_from(rpt)
        rpt.link_from(s)
        wf.initialize()
        wf.run()
        assert len(trace) == 3


class TestDataLinks:
    def test_link_attrs_forwarding(self):
        wf = Workflow(name="attrs")
        src = TrivialUnit(wf, name="src")
        dst = TrivialUnit(wf, name="dst")
        src.output = 42
        dst.link_attrs(src, ("input", "output"))
        assert dst.input == 42
        src.output = 43
        assert dst.input == 43

    def test_link_attrs_one_way_write_raises(self):
        wf = Workflow(name="attrs")
        src = TrivialUnit(wf, name="src")
        dst = TrivialUnit(wf, name="dst")
        src.v = 1
        dst.link_attrs(src, "v")
        with pytest.raises(AttributeError):
            dst.v = 9

    def test_link_attrs_two_way(self):
        wf = Workflow(name="attrs")
        src = TrivialUnit(wf, name="src")
        dst = TrivialUnit(wf, name="dst")
        src.v = 1
        dst.link_attrs(src, "v", two_way=True)
        dst.v = 9
        assert src.v == 9


class TestDemand:
    def test_demand_satisfied_after_linking(self):
        wf = Workflow(name="demand")

        class Consumer(Unit):
            def __init__(self, workflow, **kw):
                super(Consumer, self).__init__(workflow, **kw)
                self.demand("minibatch")

        src = TrivialUnit(wf, name="src")
        con = Consumer(wf, name="con")
        con.link_from(src)
        src.link_from(wf.start_point)
        with pytest.raises(MissingDemands):
            con.verify_demands()
        src.out = 5
        con.link_attrs(src, ("minibatch", "out"))
        wf.end_point.link_from(con)
        wf.initialize()  # no raise

    def test_initialize_requeues_until_producer_sets_attr(self):
        """Producer initialize() sets the attribute consumer demands; consumer
        appears earlier in insertion order — requeue must resolve it
        (ref workflow.py partial re-init queue)."""
        wf = Workflow(name="requeue")

        class Producer(Unit):
            def initialize(self, **kwargs):
                self.out = 123

        class Consumer(Unit):
            def __init__(self, workflow, **kw):
                super(Consumer, self).__init__(workflow, **kw)
                self.demand("inp")

        con = Consumer(wf, name="con")
        pro = Producer(wf, name="pro")
        con.link_attrs(pro, ("inp", "out"))
        pro.link_from(wf.start_point)
        con.link_from(pro)
        wf.end_point.link_from(con)
        wf.initialize()
        assert con.inp == 123


class TestWorkflowContainer:
    def test_getitem_by_name_and_index(self):
        wf, _, units = build_linear()
        assert wf["u1"] is units[1]
        assert wf[wf.units.index(units[2])] is units[2]

    def test_stats_and_graph(self):
        wf, _, _ = build_linear()
        wf.initialize()
        wf.run()
        dot = wf.generate_graph()
        assert "digraph" in dot and "u1" in dot
        rows = wf.print_stats()
        assert rows

    def test_gather_results(self):
        wf, _, units = build_linear()

        class Metric(TrivialUnit):
            def get_metric_values(self):
                return {"acc": 0.9}

        Metric(wf, name="m")
        assert wf.gather_results() == {"acc": 0.9}


class TestWorkflowChecksum:
    def test_stable_and_hex(self):
        """r2: the reference's per-file version checksum
        (veles/workflow.py:847) — identical workflows agree."""
        from veles_tpu.units import TrivialUnit
        from veles_tpu.workflow import Workflow

        def build():
            wf = Workflow(name="cs")
            TrivialUnit(wf, name="a")
            return wf

        c1, c2 = build().checksum(), build().checksum()
        assert c1 == c2
        assert len(c1) == 40 and int(c1, 16) >= 0

    def test_changes_with_unit_code(self, tmp_path):
        import importlib.util
        import sys

        from veles_tpu.units import TrivialUnit
        from veles_tpu.workflow import Workflow

        def custom_unit(body):
            mod_path = tmp_path / "cs_mod.py"
            mod_path.write_text(
                "from veles_tpu.units import TrivialUnit\n"
                "class Custom(TrivialUnit):\n    %s\n" % body)
            spec = importlib.util.spec_from_file_location("cs_mod",
                                                          str(mod_path))
            mod = importlib.util.module_from_spec(spec)
            sys.modules["cs_mod"] = mod
            spec.loader.exec_module(mod)
            return mod.Custom

        def digest(body):
            wf = Workflow(name="cs2")
            custom_unit(body)(wf, name="c")
            return wf.checksum()

        assert digest("x = 1") != digest("x = 2")


class TestTimingsAndStats:
    def test_per_call_timings_flag(self, caplog):
        """timings=True (or root.common.timings) prints per-call
        durations (ref units.py:144-149)."""
        import logging

        from veles_tpu.config import root
        from veles_tpu.units import TrivialUnit
        from veles_tpu.workflow import Workflow
        wf = Workflow(name="tw")
        u = TrivialUnit(wf, name="timed", timings=True)
        with caplog.at_level(logging.DEBUG, logger="TrivialUnit"):
            u._run_wrapped()
        assert any("run #1" in r.getMessage()
                   for r in caplog.records)
        # global config default reaches new units
        root.common.timings = True
        try:
            assert TrivialUnit(wf, name="t2").timings
        finally:
            root.common.timings = False
        assert not TrivialUnit(wf, name="t3").timings

    def test_print_stats_reports_efficiency_and_rss(self, caplog):
        """print_stats: top-N table + scheduler efficiency η + peak RSS
        (ref workflow.py:763-821, __main__.py:791-797)."""
        import logging

        from veles_tpu.plumbing import Repeater
        from veles_tpu.workflow import Workflow
        wf = Workflow(name="sw")
        rpt = Repeater(wf)
        rpt.link_from(wf.start_point)
        wf.end_point.link_from(rpt)
        wf.initialize()
        wf.run()
        with caplog.at_level(logging.INFO, logger="Workflow"):
            wf.print_stats()
        text = " ".join(r.getMessage() for r in caplog.records)
        assert "peak RSS" in text and "η" in text
        import re
        m = re.search(r"peak RSS ([0-9.]+) MiB", text)
        assert m and float(m.group(1)) > 10.0   # a real process RSS
