"""Control-plane contract auditors (ISSUE 17): the VW9xx wire-protocol
lint and the VC95x config/telemetry contract audit.

PR 16 test pattern: per-rule seeded-hazard fixtures where each rule
fires exactly once, clean sweeps over the real tree (both lints ship at
zero findings), the suppression contract, the generated
docs/config_reference.md pin, and the CLI gates in-process."""

import os
import textwrap

import pytest

from veles_tpu.analysis import config_audit, protocol_audit

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return [f.rule for f in findings]


# --------------------------------------------------------------------------
# VW9xx — seeded hazards, each rule fires exactly once
# --------------------------------------------------------------------------

VW_SEEDS = {
    "VW900": """
        class Master:
            def announce(self, conn):
                conn.send({"type": "orphan", "host": "h"})
        """,
    "VW901": """
        class Peer:
            def send_hello(self, conn):
                conn.send({"type": "hello"})

            def handle(self, msg):
                if msg.get("type") == "hello":
                    return msg["nonce"]
        """,
    "VW902": """
        class Registry:
            def handle(self, msg):
                if msg.get("type") == "fetch_slices":
                    self.slices = msg.get("want")
        """,
    "VW903": """
        class Master:
            def __init__(self):
                self.fence = IncarnationFence()
                self.hosts = {}

            def handle(self, msg):
                if msg.get("type") == "attach":
                    self.hosts["h"] = msg.get("incarnation")
        """,
    "VW904": """
        def attach(sock):
            sock.settimeout(None)
        """,
    "VW905": """
        import json

        def pump(sock):
            line = sock.recv(65536)
            return json.loads(line)
        """,
}


def _protocol(tmp_path, *sources):
    paths = []
    for i, src in enumerate(sources):
        p = tmp_path / ("mod%d.py" % i)
        p.write_text(textwrap.dedent(src))
        paths.append(str(p))
    return protocol_audit.lint_protocol(paths=paths)


class TestSeededVW:
    @pytest.mark.parametrize("rule", sorted(VW_SEEDS))
    def test_rule_fires_exactly_once(self, rule, tmp_path):
        findings = _protocol(tmp_path, VW_SEEDS[rule])
        assert _rules(findings) == [rule], findings

    def test_all_vw_rules_covered(self):
        assert tuple(sorted(VW_SEEDS)) == protocol_audit.RULES

    def test_vw900_handler_in_other_module_clears(self, tmp_path):
        """The scanned files are ONE protocol universe — a kind sent
        here and handled there is matched across modules."""
        handler = """
            class Agent:
                def handle(self, msg):
                    if msg.get("type") == "orphan":
                        return msg.get("host")
            """
        findings = _protocol(tmp_path, VW_SEEDS["VW900"], handler)
        assert findings == [], findings

    def test_vw901_sender_setting_the_field_clears(self, tmp_path):
        findings = _protocol(tmp_path, """
            class Peer:
                def send_hello(self, conn):
                    conn.send({"type": "hello", "nonce": 7})

                def handle(self, msg):
                    if msg.get("type") == "hello":
                        return msg["nonce"]
            """)
        assert findings == [], findings

    def test_vw902_response_through_closure(self, tmp_path):
        """The handler branch closes over same-class methods the
        message flows into — a reply sent there counts."""
        findings = _protocol(tmp_path, """
            class Registry:
                def handle(self, msg):
                    if msg.get("type") == "fetch_slices":
                        self._reply(msg)

                def _reply(self, msg):
                    self.conn.send({"type": "slices", "data": []})

                def pump(self, msg):
                    if msg.get("type") == "slices":
                        return msg.get("data")
            """)
        assert findings == [], findings

    def test_vw903_fence_consult_clears(self, tmp_path):
        findings = _protocol(tmp_path, """
            class Master:
                def __init__(self):
                    self.fence = IncarnationFence()
                    self.hosts = {}

                def handle(self, msg):
                    if msg.get("type") == "attach":
                        if msg.get("incarnation") != self.fence.current:
                            return
                        self.hosts["h"] = msg.get("incarnation")
            """)
        assert findings == [], findings

    def test_vw903_guard_idiom_branch(self, tmp_path):
        """`if msg.get("type") != "attach": ... return` — the REST of
        the block is the handler branch."""
        findings = _protocol(tmp_path, """
            class Master:
                def __init__(self):
                    self.fence = IncarnationFence()
                    self.hosts = {}

                def run(self, msg):
                    if msg.get("type") != "attach":
                        return
                    self.hosts["h"] = msg.get("incarnation")
            """)
        assert _rules(findings) == ["VW903"], findings

    def test_vw905_guarded_callers_clear(self, tmp_path):
        """An unguarded helper is fine when every call site sits in a
        try/except ValueError (one-level caller propagation)."""
        findings = _protocol(tmp_path, """
            import json

            def parse(sock):
                return json.loads(sock.recv(65536))

            def pump(sock):
                try:
                    return parse(sock)
                except ValueError:
                    return None
            """)
        assert findings == [], findings

    def test_get_default_registers_kind(self, tmp_path):
        """msg.get("type", "garbage") is the inbox pump's torn-line
        classification — "garbage" becomes a handled kind."""
        findings = _protocol(tmp_path, """
            def classify(msg):
                return msg.get("type", "garbage")

            def synthesize(conn):
                conn.send({"type": "garbage"})
            """)
        assert findings == [], findings


# --------------------------------------------------------------------------
# VC95x — seeded hazards, each rule fires exactly once
# --------------------------------------------------------------------------

VC_SEEDS = {
    "VC950": {
        "config": """
            root.common.update({
                "pod": {"heartbeat_ms": 500},
            })
            """,
        "code": """
            from veles_tpu.config import root

            def tick():
                return root.common.pod.get("heartbeat_ms", 500)

            def poll():
                return root.common.pod.get("heartbeat_mss", 500)
            """,
    },
    "VC951": {
        "config": """
            root.common.update({
                "pod": {"alive": True, "dead": 7},
            })
            """,
        "code": """
            from veles_tpu.config import root

            def tick():
                return root.common.pod.get("alive", True)
            """,
    },
    "VC952": {
        "config": """
            root.common.update({
                "pod": {"retry_ms": 100},
            })
            """,
        "code": """
            from veles_tpu.config import root

            def fast():
                return root.common.pod.get("retry_ms", 100)

            def slow():
                return root.common.pod.get("retry_ms", 250)
            """,
    },
    "VC953": {
        "config": """
            root.common.update({
                "pod": {"alive": True},
            })
            """,
        "code": """
            from veles_tpu.config import root

            def tick():
                return root.common.pod.get("alive", True)

            def probe():
                return root.common.pod.get("brand_new_knob", 8)
            """,
    },
    "VC954": {
        "config": """
            root.common.update({})
            """,
        "code": """
            def boot(flight):
                flight.record("pod.spawn", host="h")
            """,
        "test": """
            def test_gate(count):
                assert count("pod.spawn") >= 1
                assert count("pod.fence") == 0
            """,
    },
}


def _config_registry(tmp_path, seed):
    cfg = tmp_path / "config.py"
    cfg.write_text(textwrap.dedent(seed["config"]))
    code = tmp_path / "code.py"
    code.write_text(textwrap.dedent(seed["code"]))
    tst = tmp_path / "test_seed.py"
    tst.write_text(textwrap.dedent(seed.get("test", "")))
    doc = tmp_path / "doc.md"
    doc.write_text(seed.get("docs", ""))
    return config_audit.build_registry(
        code_paths=[str(code)], config_path=str(cfg),
        doc_paths=[str(doc)], test_paths=[str(tst)],
        root=str(tmp_path))


class TestSeededVC:
    @pytest.mark.parametrize("rule", sorted(VC_SEEDS))
    def test_rule_fires_exactly_once(self, rule, tmp_path):
        reg = _config_registry(tmp_path, VC_SEEDS[rule])
        findings = config_audit.lint_config(registry=reg)
        assert _rules(findings) == [rule], findings

    def test_all_vc_rules_covered(self):
        assert tuple(sorted(VC_SEEDS)) == config_audit.RULES

    def test_vc954_forward_needs_a_surface(self, tmp_path):
        """An emitted event on no test/tool/docs surface is the
        forward warning; putting it in the generated reference (any
        docs page) clears it."""
        seed = dict(VC_SEEDS["VC954"], test="")
        reg = _config_registry(tmp_path, seed)
        findings = config_audit.lint_config(registry=reg)
        assert _rules(findings) == ["VC954"], findings
        assert findings[0].severity == "warning"
        seed = dict(seed, docs="the `pod.spawn` flight event\n")
        reg = _config_registry(tmp_path, seed)
        assert config_audit.lint_config(registry=reg) == []

    def test_knob_helper_reads_resolve(self, tmp_path):
        """The `def knob(value, key, default): return
        root.common.pod.get(key, default)` idiom resolves at call
        sites — declared keys read only through it are not dead."""
        reg = _config_registry(tmp_path, {
            "config": """
                root.common.update({
                    "pod": {"alive": True},
                })
                """,
            "code": """
                from veles_tpu.config import root

                def tune(value):
                    def knob(key, default):
                        return root.common.pod.get(key, default)
                    return knob("alive", True)
                """,
        })
        assert config_audit.lint_config(registry=reg) == []

    def test_dynamic_key_read_covers_the_node(self, tmp_path):
        """root.common.pod.get(var) makes the whole node dynamic — its
        declared children are neither dead nor undeclared."""
        reg = _config_registry(tmp_path, {
            "config": """
                root.common.update({
                    "pod": {"alive": True, "spare": 1},
                })
                """,
            "code": """
                from veles_tpu.config import root

                def probe(which):
                    return root.common.pod.get(which)
                """,
        })
        assert config_audit.lint_config(registry=reg) == []

    def test_write_string_threads_the_key(self, tmp_path):
        """A config-list thread string ("root.common.pod.size=%d")
        registers the write — the key is neither a typo nor dead."""
        reg = _config_registry(tmp_path, {
            "config": """
                root.common.update({})
                """,
            "code": """
                from veles_tpu.config import root

                def spawn(n):
                    arg = "root.common.pod.size=%d" % n
                    return root.common.pod.get("size", 0), arg
                """,
        })
        assert config_audit.lint_config(registry=reg) == []

    def test_stale_doc_key_is_vc951(self, tmp_path):
        reg = _config_registry(tmp_path, {
            "config": """
                root.common.update({})
                """,
            "code": "",
            "docs": "set `root.common.pod.vanished` to tune it\n",
        })
        findings = config_audit.lint_config(registry=reg)
        assert _rules(findings) == ["VC951"], findings


# --------------------------------------------------------------------------
# suppression — the lint-ok contract, shared with VT8xx
# --------------------------------------------------------------------------

class TestSuppression:
    def test_rationale_suppresses_vw(self, tmp_path):
        findings = _protocol(tmp_path, """
            def attach(sock):
                # lint-ok: VW904 — EOF is the liveness signal here
                sock.settimeout(None)
            """)
        assert findings == [], findings

    def test_bare_lint_ok_suppresses_nothing(self, tmp_path):
        findings = _protocol(tmp_path, """
            def attach(sock):
                # lint-ok:
                sock.settimeout(None)
            """)
        assert _rules(findings) == ["VW904"], findings

    def test_rationale_suppresses_vc(self, tmp_path):
        seed = VC_SEEDS["VC953"]
        reg = _config_registry(tmp_path, {
            "config": seed["config"],
            "code": """
                from veles_tpu.config import root

                def tick():
                    return root.common.pod.get("alive", True)

                def probe():
                    # lint-ok: VC953 — staged knob, declared next PR
                    return root.common.pod.get("brand_new_knob", 8)
                """,
        })
        assert config_audit.lint_config(registry=reg) == []


# --------------------------------------------------------------------------
# the shipped tree — both contracts hold at zero findings
# --------------------------------------------------------------------------

class TestRealTree:
    def test_services_protocol_is_clean(self):
        findings = protocol_audit.lint_protocol()
        assert findings == [], findings

    def test_config_contract_is_clean(self):
        findings = config_audit.lint_config(root=REPO)
        assert findings == [], findings

    def test_reference_doc_is_fresh(self):
        """docs/config_reference.md is generated — regenerating it
        must reproduce the checked-in file byte for byte (the CI
        staleness gate)."""
        with open(os.path.join(REPO, "docs",
                               "config_reference.md")) as fh:
            checked_in = fh.read()
        assert config_audit.build_reference(root=REPO) == checked_in

    def test_reference_is_deterministic(self):
        reg = config_audit.build_registry(root=REPO)
        assert config_audit.build_reference(registry=reg) == \
            config_audit.build_reference(registry=reg)

    def test_lints_never_import_services(self):
        """Pure AST: auditing the control plane must not execute it."""
        import subprocess
        import sys
        code = (
            "import sys\n"
            "from veles_tpu.analysis import protocol_audit, "
            "config_audit\n"
            "protocol_audit.lint_protocol()\n"
            "config_audit.lint_config()\n"
            "poisoned = [m for m in sys.modules\n"
            "            if m.startswith('veles_tpu.services')]\n"
            "print('POISONED', poisoned)\n")
        out = subprocess.run(
            [sys.executable, "-c", code], cwd=REPO,
            capture_output=True, text=True, check=True)
        assert "POISONED []" in out.stdout, out.stdout + out.stderr


# --------------------------------------------------------------------------
# CLI — exit codes 0/1/2 through the shared findings gate
# --------------------------------------------------------------------------

class TestCLI:
    def test_protocol_and_config_audit_clean(self, capsys):
        from veles_tpu.analysis.cli import main
        rc = main(["--protocol", "--config-audit"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "no findings" in out

    def test_markdown_prints_the_reference(self, capsys):
        from veles_tpu.analysis.cli import main
        rc = main(["--config-audit", "--format", "markdown"])
        out = capsys.readouterr().out
        assert rc == 0
        assert out.startswith("# Config & telemetry contract")

    def test_markdown_pairs_with_config_audit_alone(self, capsys):
        from veles_tpu.analysis.cli import main
        with pytest.raises(SystemExit) as e:
            main(["--protocol", "--format", "markdown"])
        assert e.value.code == 2

    def test_workflow_required_without_ast_lints(self):
        from veles_tpu.analysis.cli import main
        with pytest.raises(SystemExit) as e:
            main([])
        assert e.value.code == 2

    def test_fail_on_unifies_contract_findings(self, capsys,
                                               monkeypatch):
        """A VC954 forward warning flips the exit only under
        --fail-on warning — threshold_reached is the one gate."""
        import veles_tpu.analysis as analysis
        from veles_tpu.analysis.cli import main
        from veles_tpu.analysis.findings import WARNING, Finding
        monkeypatch.setattr(
            analysis, "lint_config",
            lambda registry=None, root=None: [Finding(
                "VC954", WARNING, "x.py:1", "seeded")])
        assert main(["--config-audit"]) == 0
        assert main(["--config-audit", "--fail-on", "warning"]) == 1
        out = capsys.readouterr().out
        assert "VC954" in out
