"""Static-analysis suite: one minimal failing workflow per linter rule,
clean passes over real samples, the Bool structural metadata the rules
see through, and the CLI surfaces (`veles-tpu-lint`, `--lint`).

Rule catalog: docs/static_analysis.md."""

import pytest

from veles_tpu.analysis import (ERROR, audit_step, format_findings,
                                has_errors, lint_workflow)
from veles_tpu.mutable import Bool
from veles_tpu.plumbing import Repeater
from veles_tpu.units import TrivialUnit, Unit
from veles_tpu.workflow import Workflow


def rules(findings):
    return {f.rule for f in findings}


def errors(findings):
    return [f for f in findings if f.severity == ERROR]


# classes used by the source-scanning rules MUST be file-backed (defined
# at module level) so inspect.getsource works
class OneWayWriter(Unit):
    def run(self):
        self.v = 9  # linked one-way in the test below: runtime raise


class NeedyUnit(Unit):
    def __init__(self, workflow, **kw):
        super(NeedyUnit, self).__init__(workflow, **kw)
        self.demand("never_set")


class ProvidingProducer(Unit):
    def initialize(self, **kwargs):
        self.made_value = 123


class AnnotatedProducer(Unit):
    def initialize(self, **kwargs):
        self.made_value: int = 123   # AnnAssign form must count too


class NeedsProduced(Unit):
    def __init__(self, workflow, **kw):
        super(NeedsProduced, self).__init__(workflow, **kw)
        self.demand("made_value")


class GateController(Unit):
    """Runtime gate surgery: opens another unit's gate from run()."""

    def run(self):
        self.worker.gate_block <<= False


class ProvidingWorkflow(Workflow):
    """The workflow's own initialize() provides a unit's demand."""

    def initialize(self, **kwargs):
        self["con"].made_value = 7
        super(ProvidingWorkflow, self).initialize(**kwargs)


class TestBoolStructure:
    def test_derived_bool_exposes_operands_and_op(self):
        a, b = Bool(True), Bool(False)
        g = a & ~b
        assert g.derived and g.op == "&"
        assert g.operands[0] is a
        assert g.operands[1].op == "~"
        assert set(map(id, g.leaves())) == {id(a), id(b)}

    def test_expression_and_repr(self):
        a, b = Bool(True), Bool(False)
        g = a & ~b
        assert g.expression() == "(True & ~False)"
        assert repr(g) == "<Bool (True & ~False) = True>"
        assert repr(a) == "<Bool value = True>"
        a <<= False
        assert g.expression() == "(False & ~False)"  # live, not a snapshot

    def test_value_bool_is_its_own_leaf(self):
        a = Bool(True)
        assert a.leaves() == [a]
        assert not a.derived and a.op is None and a.operands == ()

    def test_shared_leaf_counted_once(self):
        a = Bool(False)
        assert (a | ~a).leaves() == [a]

    def test_bare_expr_bool_renders_without_crash(self):
        """A derived Bool built directly with _expr and no operands (the
        pre-metadata form) must still repr, whatever its op tag."""
        assert Bool(_expr=lambda: True, _name="~").expression() == "<~>"
        assert "derived" not in repr(Bool(_expr=lambda: True, _name="&"))

    def test_tautology_over_shared_leaf_is_constant_true(self):
        """a | ~a is true under every assignment of a — the gate-deadlock
        rule must fire even though the leaf itself is flippable."""
        wf = Workflow(name="taut")
        u = TrivialUnit(wf, name="blocked")
        u.link_from(wf.start_point)
        u.flag = Bool(False)              # named attr: flippable leaf
        u.gate_block = u.flag | ~u.flag   # ...but the expression is a
        wf.end_point.link_from(u)         # tautology
        fs = lint_workflow(wf)
        assert any(f.rule == "VG003" and f.unit == "blocked" for f in fs)


class TestCycleRule:
    def build(self, closer):
        wf = Workflow(name="cyc")
        a = TrivialUnit(wf, name="a")
        b = TrivialUnit(wf, name="b")
        a.link_from(wf.start_point)
        b.link_from(a)
        if closer:
            rpt = Repeater(wf)
            rpt.link_from(b)
            a.link_from(rpt)
        else:
            a.link_from(b)
        wf.end_point.link_from(b)
        return wf

    def test_cycle_without_repeater_fires_vg001(self):
        fs = lint_workflow(self.build(closer=False))
        assert "VG001" in rules(errors(fs))

    def test_repeater_closed_cycle_is_clean(self):
        fs = lint_workflow(self.build(closer=True))
        assert "VG001" not in rules(fs)


class TestReachabilityRule:
    def test_unreachable_linked_unit_warns(self):
        wf = Workflow(name="unr")
        a = TrivialUnit(wf, name="a")
        orphan = TrivialUnit(wf, name="orphan")
        sink = TrivialUnit(wf, name="sink")
        a.link_from(wf.start_point)
        sink.link_from(orphan)      # orphan has links but no path from start
        wf.end_point.link_from(a)
        fs = lint_workflow(wf)
        hits = [f for f in fs if f.rule == "VG002" and f.unit == "orphan"]
        assert hits and hits[0].severity == "warning"

    def test_passive_unit_is_info_only(self):
        wf = Workflow(name="pas")
        a = TrivialUnit(wf, name="a")
        TrivialUnit(wf, name="handle")   # no links at all
        a.link_from(wf.start_point)
        wf.end_point.link_from(a)
        fs = lint_workflow(wf)
        hits = [f for f in fs if f.rule == "VG002" and f.unit == "handle"]
        assert hits and hits[0].severity == "info"
        assert not has_errors(fs)


class TestGateDeadlockRule:
    def test_unreachable_predecessor_fires_vg003(self):
        wf = Workflow(name="gd")
        a = TrivialUnit(wf, name="a")
        stranded = TrivialUnit(wf, name="stranded")
        c = TrivialUnit(wf, name="c")
        a.link_from(wf.start_point)
        c.link_from(a, stranded)     # c waits on a unit that never fires
        wf.end_point.link_from(c)
        fs = lint_workflow(wf)
        hits = [f for f in fs if f.rule == "VG003" and f.unit == "c"]
        assert hits and hits[0].severity == ERROR

    def test_constant_true_gate_block_fires_vg003(self):
        wf = Workflow(name="cg")
        u = TrivialUnit(wf, name="blocked")
        u.link_from(wf.start_point)
        u.gate_block = Bool(True)    # anonymous: nothing can ever flip it
        wf.end_point.link_from(u)
        fs = lint_workflow(wf)
        hits = [f for f in fs if f.rule == "VG003" and f.unit == "blocked"]
        assert hits and "constant-true" in hits[0].message

    def test_runtime_gate_write_suppresses_constant_true(self):
        """A unit whose run() writes another unit's gate slot
        (`x.gate_block <<= False`) proves the program manipulates gates
        at runtime — the constant-true rule must stay silent."""
        wf = Workflow(name="rg")
        ctl = GateController(wf, name="ctl")
        worker = TrivialUnit(wf, name="worker")
        ctl.worker = worker
        worker.gate_block = Bool(True)     # opened by ctl at runtime
        ctl.link_from(wf.start_point)
        worker.link_from(ctl)
        wf.end_point.link_from(worker)
        assert "VG003" not in rules(lint_workflow(wf))

    def test_canonical_loop_with_closure_flag_is_clean(self):
        """The test_units_workflow repeater idiom: the completion flag is
        a closure var the Decision flips — the linter must see the flip
        site through the method's closure cells and NOT flag the
        ~complete end_point gate."""
        wf = Workflow(name="loop")
        rpt = Repeater(wf)
        body = TrivialUnit(wf, name="body")
        complete = Bool(False)

        class Decision(Unit):
            def run(self):
                complete.set(True)

        dec = Decision(wf)
        rpt.link_from(wf.start_point)
        body.link_from(rpt)
        dec.link_from(body)
        rpt.link_from(dec)
        rpt.gate_block = complete
        wf.end_point.link_from(dec)
        wf.end_point.gate_block = ~complete
        fs = lint_workflow(wf)
        assert "VG003" not in rules(fs)
        assert "VG001" not in rules(fs)  # repeater closes the cycle
        assert not has_errors(fs)


class TestDanglingLinkRule:
    def build_linked_pair(self):
        wf = Workflow(name="dl")
        src = TrivialUnit(wf, name="src")
        dst = TrivialUnit(wf, name="dst")
        src.out = 1
        dst.link_attrs(src, ("inp", "out"))
        dst.link_from(wf.start_point)
        wf.end_point.link_from(dst)
        return wf, src, dst

    def test_del_refd_source_fires_vg004(self):
        wf, src, dst = self.build_linked_pair()
        src.unlink_all()
        wf.del_ref(src)
        fs = lint_workflow(wf)
        hits = [f for f in fs if f.rule == "VG004"]
        assert hits and hits[0].unit == "dst" and "inp" in hits[0].message

    def test_live_link_is_clean(self):
        wf, _, _ = self.build_linked_pair()
        assert "VG004" not in rules(lint_workflow(wf))

    def test_del_ref_drops_empty_by_name_bucket(self):
        """Linter ground truth (and container hygiene): removing the last
        unit of a name must remove the name itself."""
        wf, src, _ = self.build_linked_pair()
        assert "src" in wf._by_name
        wf.del_ref(src)
        assert "src" not in wf._by_name
        with pytest.raises(KeyError):
            wf["src"]

    def test_unlink_all_clears_one_sided_entries(self):
        wf = Workflow(name="ua")
        a = TrivialUnit(wf, name="a")
        b = TrivialUnit(wf, name="b")
        b.link_from(a)
        b.links_to.add(a)            # simulate sloppy direct graph surgery
        b.unlink_all()
        assert not b.links_from and not b.links_to
        assert b not in a.links_to and b not in a.links_from

    def test_unlink_attrs_inverse_of_link_attrs(self):
        wf = Workflow(name="ul")
        src = TrivialUnit(wf, name="src")
        dst = TrivialUnit(wf, name="dst")
        src.out = 7
        dst.link_attrs(src, ("inp", "out"))
        assert dst.linked_attrs == {"inp": (src, "out", False)}
        dst.unlink_attrs("inp")
        assert dst.linked_attrs == {}


class TestOneWayWriteRule:
    def test_run_method_write_to_one_way_link_fires_vg005(self):
        wf = Workflow(name="ow")
        src = TrivialUnit(wf, name="src")
        src.v = 1
        w = OneWayWriter(wf, name="w")
        w.link_attrs(src, "v")
        w.link_from(wf.start_point)
        wf.end_point.link_from(w)
        fs = lint_workflow(wf)
        hits = [f for f in fs if f.rule == "VG005"]
        assert hits and hits[0].unit == "w"
        assert "ONE-WAY" in hits[0].message

    def test_two_way_link_is_clean(self):
        wf = Workflow(name="ow2")
        src = TrivialUnit(wf, name="src")
        src.v = 1
        w = OneWayWriter(wf, name="w")
        w.link_attrs(src, "v", two_way=True)
        w.link_from(wf.start_point)
        wf.end_point.link_from(w)
        assert "VG005" not in rules(lint_workflow(wf))


class TestDemandRule:
    def test_unsatisfiable_demand_fires_vg006(self):
        wf = Workflow(name="dm")
        n = NeedyUnit(wf, name="needy")
        n.link_from(wf.start_point)
        wf.end_point.link_from(n)
        fs = lint_workflow(wf)
        hits = [f for f in fs if f.rule == "VG006"]
        assert hits and "never_set" in hits[0].message

    def test_demand_satisfied_by_data_link_is_clean(self):
        wf = Workflow(name="dm2")
        src = TrivialUnit(wf, name="src")
        src.out = 5
        n = NeedyUnit(wf, name="needy")
        n.link_attrs(src, ("never_set", "out"))
        n.link_from(wf.start_point)
        wf.end_point.link_from(n)
        assert "VG006" not in rules(lint_workflow(wf))

    def test_demand_satisfied_by_workflow_initialize_is_clean(self):
        """The workflow is a Unit too: its own initialize() assigning the
        demanded attribute must count as a provider."""
        wf = ProvidingWorkflow(name="dm4")
        con = NeedsProduced(wf, name="con")
        con.link_from(wf.start_point)
        wf.end_point.link_from(con)
        assert "VG006" not in rules(lint_workflow(wf))

    def test_demand_satisfied_by_producer_initialize_is_clean(self):
        """The requeue pattern: the producer's initialize() assigns the
        attribute — statically visible, so no finding."""
        wf = Workflow(name="dm3")
        pro = ProvidingProducer(wf, name="pro")
        con = NeedsProduced(wf, name="con")
        con.link_attrs(pro, "made_value")
        pro.link_from(wf.start_point)
        con.link_from(pro)
        wf.end_point.link_from(con)
        assert "VG006" not in rules(lint_workflow(wf))

    def test_annotated_assignment_counts_as_provider(self):
        """`self.x: int = 123` (AnnAssign) must register as an
        assignment — no false-positive VG006."""
        wf = Workflow(name="dm5")
        pro = AnnotatedProducer(wf, name="pro")
        con = NeedsProduced(wf, name="con")
        con.link_attrs(pro, "made_value")
        pro.link_from(wf.start_point)
        con.link_from(pro)
        wf.end_point.link_from(con)
        assert "VG006" not in rules(lint_workflow(wf))


class TestStagingAuditor:
    def test_host_callback_in_step_fires_vj101(self):
        import jax
        import jax.numpy as jnp

        def step(x):
            jax.debug.print("x={}", x)
            return x

        fs = audit_step(step, (jnp.zeros((3,), jnp.float32),))
        assert "VJ101" in rules(errors(fs))

    def test_weak_typed_input_fires_vj102(self):
        import jax.numpy as jnp
        fs = audit_step(lambda x, s: x * s, (jnp.zeros((3,)), 2.0))
        hits = [f for f in fs if f.rule == "VJ102"]
        assert hits and hits[0].severity == "warning"

    def test_carry_dtype_drift_fires_vj103(self):
        import jax
        import jax.numpy as jnp
        fs = audit_step(lambda x: x * 1.0,
                        (jax.ShapeDtypeStruct((3,), jnp.int32),),
                        carry_argnums=(0,))
        hits = [f for f in fs if f.rule == "VJ103"]
        assert hits and "recompiles" in hits[0].message

    def test_clean_step_has_no_findings(self):
        import jax.numpy as jnp

        def step(params, x):
            return params + x.sum()

        fs = audit_step(step, (jnp.zeros(()), jnp.zeros((4,))),
                        carry_argnums=(0,))
        assert fs == []

    def test_untraceable_step_fires_vj100(self):
        import jax.numpy as jnp

        def step(x):
            if float(x.sum()) > 0:   # concretizes a tracer: untraceable
                return x
            return -x

        fs = audit_step(step, (jnp.ones((2,)),))
        assert "VJ100" in rules(errors(fs))

    def test_iter_primitives_recurses_into_dict_params(self):
        """Satellite: a nested jaxpr stashed in a DICT-valued eqn.params
        (keyed branch/function tables) must not hide from VJ101."""
        import jax
        import jax.numpy as jnp
        from types import SimpleNamespace

        from veles_tpu.analysis.staging import iter_primitives

        def leaky(x):
            jax.debug.print("x={}", x)
            return x

        inner = jax.make_jaxpr(leaky)(jnp.zeros(()))
        fake_eqn = SimpleNamespace(
            primitive=SimpleNamespace(name="fake_call"),
            params={"funs": {"branch_a": inner}})
        fake_jaxpr = SimpleNamespace(eqns=[fake_eqn])
        names = {n for n, _ in iter_primitives(fake_jaxpr)}
        assert "debug_callback" in names

    def test_iter_primitives_recurses_into_cond_branch_lists(self):
        """Satellite: jaxprs nested in LIST/TUPLE-valued eqn.params —
        cond/switch carry their branches as a tuple of ClosedJaxprs —
        must not be skipped by any auditor built on iter_primitives."""
        import jax
        import jax.numpy as jnp

        from veles_tpu.analysis.staging import iter_primitives

        def leaky_branch(x):
            jax.debug.print("x={}", x)
            return x * 2.0

        def cond_fn(p, x):
            return jax.lax.cond(p, leaky_branch, lambda x: x, x)

        closed = jax.make_jaxpr(cond_fn)(True, jnp.zeros(()))
        names = {n for n, _ in iter_primitives(closed.jaxpr)}
        assert "cond" in names
        assert "debug_callback" in names     # inside a branch list

        def switch_fn(i, x):
            return jax.lax.switch(
                i, [lambda x: x, leaky_branch, lambda x: -x], x)

        closed = jax.make_jaxpr(switch_fn)(0, jnp.zeros(()))
        names = {n for n, _ in iter_primitives(closed.jaxpr)}
        assert "debug_callback" in names

    def test_nested_containers_in_params_recurse(self):
        """Dicts of lists of jaxprs (and vice versa) all unwrap."""
        import jax
        import jax.numpy as jnp
        from types import SimpleNamespace

        from veles_tpu.analysis.staging import iter_primitives

        def leaky(x):
            jax.debug.print("x={}", x)
            return x

        inner = jax.make_jaxpr(leaky)(jnp.zeros(()))
        fake_eqn = SimpleNamespace(
            primitive=SimpleNamespace(name="fake_call"),
            params={"table": {"a": [inner], "b": ([inner],)}})
        fake_jaxpr = SimpleNamespace(eqns=[fake_eqn])
        names = {n for n, _ in iter_primitives(fake_jaxpr)}
        assert "debug_callback" in names

    def test_lint_workflow_consumes_staging_hook(self):
        """lint_workflow pulls a unit's lint_staging_spec() and audits the
        staged step it describes (StagedTrainer exposes the same hook
        once initialized)."""
        import jax
        import jax.numpy as jnp

        class Staged(TrivialUnit):
            def lint_staging_spec(self):
                def step(acc):
                    jax.debug.print("acc={}", acc)
                    return acc
                return {"fn": step,
                        "args": (jax.ShapeDtypeStruct((), jnp.float32),),
                        "carry_argnums": (0,), "name": "staged.step"}

        wf = Workflow(name="hook")
        s = Staged(wf, name="staged")
        s.link_from(wf.start_point)
        wf.end_point.link_from(s)
        fs = lint_workflow(wf)
        assert any(f.rule == "VJ101" and f.unit == "staged.step"
                   for f in fs)
        assert "VJ101" not in rules(lint_workflow(wf, staging=False))


class TestFindingSurface:
    def test_text_and_json_formats(self):
        wf = Workflow(name="fmt")
        u = TrivialUnit(wf, name="blocked")
        u.link_from(wf.start_point)
        u.gate_block = Bool(True)
        wf.end_point.link_from(u)
        fs = lint_workflow(wf)
        text = format_findings(fs)
        assert "VG003" in text and "hint:" in text
        import json
        data = json.loads(format_findings(fs, "json"))
        assert any(d["rule"] == "VG003" for d in data)
        assert {"rule", "severity", "unit", "message", "hint"} <= set(
            data[0])

    def test_sorted_most_severe_first(self):
        wf = Workflow(name="sort")
        u = TrivialUnit(wf, name="blocked")
        u.link_from(wf.start_point)
        u.gate_block = Bool(True)
        TrivialUnit(wf, name="handle")      # info finding
        wf.end_point.link_from(u)
        fs = lint_workflow(wf)
        sev = [f.severity for f in fs]
        assert sev == sorted(sev, key=("error", "warning", "info").index)


CYCLIC_WF = '''
from veles_tpu.units import TrivialUnit
from veles_tpu.workflow import Workflow

def run(load, main):
    wf = load(Workflow, name="cyclic")
    a = TrivialUnit(wf, name="a")
    b = TrivialUnit(wf, name="b")
    a.link_from(wf.start_point)
    b.link_from(a)
    a.link_from(b)          # control cycle, no Repeater
    wf.end_point.link_from(b)
    main()
'''


class TestCLI:
    def test_lint_flag_exits_nonzero_on_cycle_without_dispatch(self,
                                                               tmp_path,
                                                               capsys,
                                                               monkeypatch):
        """`--lint` on a cyclic workflow: non-zero exit, and the workflow
        is never initialized — so no param init, no XLA dispatch."""
        # Main.run() enables the persistent compile cache; in-process
        # that would latch process-global jax cache state onto the repo
        # .xla_cache dir — use the module's env kill switch instead
        monkeypatch.setenv("VELES_COMPILE_CACHE", "off")
        from veles_tpu.__main__ import Main
        wf_file = tmp_path / "cyclic_wf.py"
        wf_file.write_text(CYCLIC_WF)
        m = Main(argv=[str(wf_file), "--lint"])
        rc = m.run()
        assert rc != 0
        assert m.workflow is not None
        assert not m.workflow._initialized   # nothing ran, nothing staged
        assert "VG001" in capsys.readouterr().out

    def test_lint_runs_even_if_workflow_file_skips_main(self, tmp_path,
                                                        capsys,
                                                        monkeypatch):
        """A workflow file that builds via load() but never calls main()
        must still be linted — not silently exit 0."""
        monkeypatch.setenv("VELES_COMPILE_CACHE", "off")
        from veles_tpu.__main__ import Main
        wf_file = tmp_path / "no_main_wf.py"
        wf_file.write_text(CYCLIC_WF.replace("    main()\n", ""))
        assert Main(argv=[str(wf_file), "--lint"]).run() != 0
        assert "VG001" in capsys.readouterr().out

    def test_lint_skips_snapshot_import(self, tmp_path, capsys,
                                         monkeypatch):
        """--lint must not unpickle a checkpoint: snapshot restore is
        heavy, side-effectful I/O the lint contract excludes."""
        monkeypatch.setenv("VELES_COMPILE_CACHE", "off")
        from veles_tpu.__main__ import Main
        wf_file = tmp_path / "cyclic_wf.py"
        wf_file.write_text(CYCLIC_WF)
        snap = tmp_path / "ckpt.pkl"
        snap.write_bytes(b"not a pickle at all")   # import_ would raise
        m = Main(argv=[str(wf_file), "--snapshot", str(snap), "--lint"])
        assert m.run() != 0                        # lint verdict, no raise
        assert "VG001" in capsys.readouterr().out

    def test_lint_console_script_main(self, tmp_path, capsys):
        from veles_tpu.analysis.cli import main
        wf_file = tmp_path / "cyclic_wf.py"
        wf_file.write_text(CYCLIC_WF)
        assert main([str(wf_file)]) == 1
        assert "VG001" in capsys.readouterr().out

    def test_fail_on_warning_threshold(self, tmp_path, capsys):
        """Satellite: --fail-on warning exits non-zero on warning-only
        findings (the CI gate knob); the default (error) stays 0."""
        from veles_tpu.analysis.cli import main
        wf_file = tmp_path / "warn_wf.py"
        # an unreachable-but-linked unit: VG002 warning, no errors
        wf_file.write_text('''
from veles_tpu.units import TrivialUnit
from veles_tpu.workflow import Workflow

def run(load, main):
    wf = load(Workflow, name="warny")
    a = TrivialUnit(wf, name="a")
    orphan = TrivialUnit(wf, name="orphan")
    sink = TrivialUnit(wf, name="sink")
    a.link_from(wf.start_point)
    sink.link_from(orphan)
    wf.end_point.link_from(a)
    main()
''')
        assert main([str(wf_file)]) == 0
        assert main([str(wf_file), "--fail-on", "warning"]) == 1
        assert main([str(wf_file), "--strict"]) == 1   # legacy alias
        assert "VG002" in capsys.readouterr().out

    def test_lint_clean_sample_digits_mlp(self, capsys):
        """Acceptance gate: `veles-tpu-lint samples/digits_mlp.py` exits 0
        with no error findings."""
        pytest.importorskip("sklearn")
        import os
        from veles_tpu.analysis.cli import main
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        rc = main([os.path.join(repo, "samples", "digits_mlp.py"),
                   os.path.join(repo, "samples", "digits_config.py")])
        assert rc == 0

    def test_initialized_trainer_staging_spec_is_clean(self):
        """StagedTrainer's own hook: after initialize() the real jitted
        eval step traces abstractly with no staging findings."""
        pytest.importorskip("sklearn")
        import os
        from veles_tpu.analysis.cli import build_workflow
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        wf = build_workflow(
            os.path.join(repo, "samples", "digits_mlp.py"),
            os.path.join(repo, "samples", "digits_config.py"))
        wf.initialize()
        spec = wf.trainer.lint_staging_spec()
        assert spec is not None and spec["carry_argnums"] == (1,)
        fs = lint_workflow(wf)
        assert not [f for f in fs if f.rule.startswith("VJ")]
        assert not has_errors(fs)


class TestHotLoopHygiene:
    def test_no_per_iteration_imports_in_run_loop(self):
        """Satellite: the fault-injection imports must live at module
        scope, not inside Workflow.run's per-unit loop."""
        import ast
        import inspect
        import textwrap

        from veles_tpu import workflow as wf_mod
        src = textwrap.dedent(inspect.getsource(wf_mod.Workflow.run))
        assert not [n for n in ast.walk(ast.parse(src))
                    if isinstance(n, (ast.Import, ast.ImportFrom))]
        assert wf_mod.os is not None and wf_mod.random is not None
