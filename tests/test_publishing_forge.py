"""Publisher report backends + Forge model-zoo client/server round trip."""

import json
import os
import zipfile

import numpy as np
import pytest

from veles_tpu.forge import ForgeClient, ForgeServer
from veles_tpu.publishing import Publisher
from veles_tpu.units import TrivialUnit
from veles_tpu.workflow import Workflow


def _small_workflow():
    wf = Workflow(name="pubtest")
    u = TrivialUnit(wf, name="worker")
    u.run_count = 3
    u.run_time = 0.5
    return wf


class TestPublisher:
    def test_markdown_html_json_reports(self, tmp_path):
        wf = _small_workflow()
        pub = Publisher(wf, backends=("markdown", "html", "json"),
                        directory=str(tmp_path),
                        description="desc here")
        pub.run()
        assert len(pub.written) == 3
        md = open(os.path.join(str(tmp_path), "pubtest.md")).read()
        assert "# pubtest" in md and "worker" in md and "desc here" in md
        html = open(os.path.join(str(tmp_path), "pubtest.html")).read()
        assert "<h1>pubtest</h1>" in html and "worker" in html
        rep = json.load(open(os.path.join(str(tmp_path), "pubtest.json")))
        assert rep["name"] == "pubtest"
        worker = [u for u in rep["units"] if u["name"] == "worker"][0]
        assert worker["runs"] == 3

    def test_markdown_includes_metrics_and_plots(self, tmp_path):
        wf = _small_workflow()
        plotter = TrivialUnit(wf, name="plots")
        plotter.written_files = [str(tmp_path / "loss.png")]
        wf.results_hook = None
        pub = Publisher(wf, backends=("markdown",), directory=str(tmp_path))
        report = pub.gather()
        assert str(tmp_path / "loss.png") in report["plots"]


def _make_package(path):
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("contents.json", json.dumps({"units": []}))
        zf.writestr("w.npy", np.zeros(4, np.float32).tobytes())
    return path


class TestForge:
    @pytest.fixture
    def server(self, tmp_path):
        srv = ForgeServer(str(tmp_path / "store")).start()
        yield srv
        srv.stop()

    def test_upload_list_fetch_roundtrip(self, server, tmp_path):
        pkg = _make_package(str(tmp_path / "model.zip"))
        client = ForgeClient(server.url)
        manifest = client.upload(pkg, "mnist", "1.0", description="first")
        assert manifest["latest"] == "1.0"
        client.upload(pkg, "mnist", "1.1")
        listing = client.list()
        assert len(listing) == 1
        assert listing[0]["latest"] == "1.1"
        assert set(listing[0]["versions"]) == {"1.0", "1.1"}
        details = client.details("mnist")
        assert details["versions"]["1.0"]["description"] == "first"
        dest, version = client.fetch("mnist", str(tmp_path / "got.zip"))
        assert version == "1.1"
        assert open(dest, "rb").read() == open(pkg, "rb").read()
        dest, version = client.fetch("mnist", str(tmp_path / "got10.zip"),
                                     version="1.0")
        assert version == "1.0"

    def test_fetch_missing_model_404(self, server, tmp_path):
        import urllib.error
        client = ForgeClient(server.url)
        with pytest.raises(urllib.error.HTTPError):
            client.fetch("nope", str(tmp_path / "x.zip"))

    def test_bad_names_rejected(self, server, tmp_path):
        import urllib.error
        pkg = _make_package(str(tmp_path / "m.zip"))
        client = ForgeClient(server.url)
        with pytest.raises(urllib.error.HTTPError):
            client.upload(pkg, "../evil", "1.0")


def _make_export_package(path):
    """A real export-format package (contents.json + npy) so thumbnails
    can render from its weights."""
    import io
    rng = np.random.RandomState(0)
    w = rng.normal(size=(16, 16)).astype(np.float32)
    buf = io.BytesIO()
    np.save(buf, w)
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("contents.json", json.dumps({
            "name": "m", "units": [
                {"name": "l00_dense", "type": "all2all", "config": {},
                 "input_shape": [16], "output_shape": [16],
                 "arrays": {"weights": "w.npy"}}]}))
        zf.writestr("w.npy", buf.getvalue())
    return path


class TestForgeThumbnailsHistory:
    """r2 (VERDICT #9): thumbnails + version lineage (ref git-based
    versioning and model thumbnails, forge_server.py:462)."""

    @pytest.fixture
    def server(self, tmp_path):
        srv = ForgeServer(str(tmp_path / "store")).start()
        yield srv
        srv.stop()

    def test_upload_attaches_thumbnail(self, server, tmp_path):
        pkg = _make_export_package(str(tmp_path / "m.zip"))
        client = ForgeClient(server.url)
        manifest = client.upload(pkg, "mnist", "1.0")
        assert manifest["versions"]["1.0"]["thumbnail"] is True
        dest = client.fetch_thumbnail("mnist", str(tmp_path / "t.png"))
        data = open(dest, "rb").read()
        assert data.startswith(b"\x89PNG")
        from PIL import Image
        import io as _io
        img = Image.open(_io.BytesIO(data))
        assert img.size == (128, 128)

    def test_history_walks_parent_chain(self, server, tmp_path):
        pkg = _make_export_package(str(tmp_path / "m.zip"))
        client = ForgeClient(server.url)
        for v in ("1.0", "1.1", "2.0"):
            client.upload(pkg, "mnist", v, thumbnail=False)
        hist = client.history("mnist")
        assert [h["version"] for h in hist] == ["2.0", "1.1", "1.0"]
        assert hist[0]["parent"] == "1.1"
        assert hist[-1]["parent"] is None
        assert all("created" in h for h in hist)

    def test_thumbnail_missing_404(self, server, tmp_path):
        import urllib.error
        pkg = _make_package(str(tmp_path / "bare.zip"))
        client = ForgeClient(server.url)
        client.upload(pkg, "bare", "1.0")   # no arrays -> no thumbnail
        with pytest.raises(urllib.error.HTTPError):
            client.fetch_thumbnail("bare", str(tmp_path / "x.png"))


class TestForgeWebIndex:
    def test_index_lists_models(self, tmp_path):
        srv = ForgeServer(str(tmp_path / "store")).start()
        try:
            pkg = _make_export_package(str(tmp_path / "m.zip"))
            client = ForgeClient(srv.url)
            client.upload(pkg, "mnist", "1.0", description="hello <x>")
            import urllib.request
            with urllib.request.urlopen(srv.url + "/") as r:
                page = r.read().decode()
            assert "veles_tpu model forge" in page
            assert "mnist" in page
            assert "hello &lt;x&gt;" in page          # escaped
            assert "/thumbnail?name=mnist" in page
        finally:
            srv.stop()
