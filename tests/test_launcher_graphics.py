"""Launcher lifecycle, LR adjuster schedules, and ZMQ graphics pub/sub."""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from veles_tpu import prng  # noqa: E402
from veles_tpu.launcher import Launcher, filter_argv  # noqa: E402
from veles_tpu.loader.fullbatch import FullBatchLoader  # noqa: E402
from veles_tpu.models.lr_adjuster import LRAdjuster, POLICIES  # noqa: E402
from veles_tpu.models.standard_workflow import StandardWorkflow  # noqa: E402
from veles_tpu.services import plotting  # noqa: E402
from veles_tpu.services.graphics import (GraphicsClient,  # noqa: E402
                                         GraphicsServer)


def _mnistish_workflow(**kw):
    prng.seed_all(21)
    n = 32
    x = np.random.RandomState(0).rand(2 * n, 6, 6, 1).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 3, 2 * n).astype(np.int32)
    loader = FullBatchLoader(None, data=x, labels=y, minibatch_size=16,
                             class_lengths=[0, n, n])
    return StandardWorkflow(
        layers=[{"type": "softmax", "output_sample_shape": 3,
                 "learning_rate": 0.05, "gradient_moment": 0.9}],
        loader=loader, decision_config={"max_epochs": 3}, **kw)


class TestFilterArgv:
    def test_drops_flag_and_value(self):
        argv = ["prog", "-l", "host:1", "--keep", "x", "--drop=5", "tail"]
        assert filter_argv(argv, "-l=", "--drop=") == \
            ["prog", "--keep", "x", "tail"]

    def test_bare_flag_keeps_following_arg(self):
        assert filter_argv(["prog", "-v", "train.py"], "-v") == \
            ["prog", "train.py"]


class TestLauncher:
    def test_standalone_boot(self):
        wf = _mnistish_workflow(name="launch-test")
        launcher = Launcher(workflow=wf)
        assert launcher.is_standalone and launcher.is_master
        launcher.boot()
        assert wf.gather_results()["epochs"] == 3

    def test_mode_detection_spmd(self):
        launcher = Launcher(coordinator_address="10.0.0.1:1234",
                            num_processes=4, process_id=2)
        assert launcher.mode == "spmd"
        assert launcher.num_processes == 4

    def test_mesh_axes_build(self):
        wf = _mnistish_workflow(name="launch-mesh")
        launcher = Launcher(workflow=wf, mesh_axes={"data": 1})
        launcher.initialize()
        assert launcher.mesh_config is not None
        launcher.run()

    def test_web_status_service(self):
        import urllib.request
        wf = _mnistish_workflow(name="launch-web")
        launcher = Launcher(workflow=wf, web_status_port=0)
        launcher.initialize()
        port = launcher.web_server.port
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/api/status" % port) as r:
            assert b"launch-web" in r.read()
        launcher.run()   # stops services afterwards (idempotent stop
        assert launcher.web_server is None   # clears the reference)


class TestLRAdjuster:
    def test_policies(self):
        assert POLICIES["exp"](2, base=0.5) == 0.25
        assert POLICIES["step_exp"](25, base=0.1, step=10) == \
            pytest.approx(0.01)
        assert POLICIES["inv"](0) == 1.0
        assert POLICIES["arbitrary_step"](
            7, steps=[(0, 1.0), (5, 0.3), (10, 0.1)]) == 0.3
        # warmup_cosine: linear ramp, peak after warmup, floor at total
        wc = POLICIES["warmup_cosine"]
        assert wc(0, warmup=4, total=20) == pytest.approx(0.25)
        assert wc(3, warmup=4, total=20) == pytest.approx(1.0)
        assert wc(4, warmup=4, total=20) == pytest.approx(1.0)
        assert wc(20, warmup=4, total=20, floor=0.1) == pytest.approx(0.1)
        assert 0.4 < wc(12, warmup=4, total=20) < 0.6
        # the integration path: kwargs must survive the unit's whitelist
        adj = LRAdjuster(None, policy="warmup_cosine", warmup=4,
                         total=20, floor=0.1)
        assert adj.scale_for(0) == pytest.approx(0.25)
        assert adj.scale_for(20) == pytest.approx(0.1)

    def test_adjuster_in_workflow(self):
        wf = _mnistish_workflow(
            name="lr-test",
            lr_adjuster_config={"policy": "exp", "base": 0.5})
        wf.initialize()
        wf.run()
        # after 3 epochs the last applied scale reflects the schedule
        assert wf.trainer.lr_scale == pytest.approx(
            0.5 ** wf.loader.epoch_number)

    def test_training_still_converges_with_schedule(self):
        wf = _mnistish_workflow(
            name="lr-conv",
            lr_adjuster_config={"policy": "inv", "gamma": 0.1,
                                "power": 0.5})
        wf.initialize()
        wf.run()
        res = wf.gather_results()
        assert res["epochs"] == 3 and res["best_metric"] is not None


class TestGraphics:
    def test_pub_sub_roundtrip(self):
        local_bus = plotting.PlotBus()
        srv = GraphicsServer(bus=local_bus).start()
        client = GraphicsClient(srv.endpoint).start()
        time.sleep(0.3)   # SUB connect (slow-joiner)
        for i in range(3):
            local_bus.publish({"name": "loss", "kind": "curve",
                               "values": list(range(i + 1)),
                               "ylabel": "loss"})
            time.sleep(0.05)
        deadline = time.time() + 5
        while client.received < 3 and time.time() < deadline:
            time.sleep(0.05)
        assert client.received >= 1
        assert client.latest["loss"]["kind"] == "curve"
        srv.stop()
        client.stop()

    def test_multicast_binds_degrade_gracefully(self):
        """With a multicast group configured the server attempts an
        epgm:// bind per non-blacklisted interface (ref LAN plot
        broadcast, graphics_server.py:100-133) and the tcp endpoint
        keeps working whether or not libzmq was built with PGM."""
        local_bus = plotting.PlotBus()
        srv = GraphicsServer(bus=local_bus, multicast="239.192.1.1",
                             ifaces=["lo", "fake0"],
                             multicast_port=15555)
        # blacklist filtering happens before any bind attempt
        srv._blacklist = {"fake0"}
        assert srv._multicast_ifaces() == ["lo"]
        srv.start()
        try:
            assert srv.endpoints["tcp"].startswith("tcp://")
            import zmq
            if zmq.has("pgm"):
                assert srv.endpoints["epgm"] == [
                    "epgm://lo;239.192.1.1:15555"]
            else:
                assert srv.endpoints["epgm"] == []   # warned, not raised
            # the tcp path still round-trips
            client = GraphicsClient(srv.endpoint).start()
            time.sleep(0.3)
            local_bus.publish({"name": "mc", "kind": "curve",
                               "values": [1], "ylabel": "x"})
            deadline = time.time() + 5
            while client.received < 1 and time.time() < deadline:
                time.sleep(0.05)
            assert client.received >= 1
            client.stop()
        finally:
            srv.stop()

    def test_client_renders_png(self, tmp_path):
        client = GraphicsClient("tcp://127.0.0.1:1", str(tmp_path))
        client.latest = {"loss": {"name": "loss", "kind": "curve",
                                  "values": [3.0, 2.0, 1.0],
                                  "ylabel": "loss"}}
        written = client.render_all()
        assert len(written) == 1
        assert written[0].endswith("loss.png")
        import os
        assert os.path.getsize(written[0]) > 0

    def test_client_pdf_export_and_signal(self, tmp_path):
        """r2: the reference's SIGUSR2 PDF export
        (veles/graphics_client.py)."""
        import os
        import signal
        client = GraphicsClient("tcp://127.0.0.1:1", str(tmp_path))
        client.latest = {"w": {"name": "w", "kind": "minmax",
                               "min": [0.0, -1.0], "mean": [1.0, 0.5],
                               "max": [2.0, 2.5], "ylabel": "w"}}
        written = client.render_all(fmt="pdf")
        assert written[0].endswith("w.pdf")
        assert open(written[0], "rb").read(4) == b"%PDF"
        os.remove(written[0])
        client.install_pdf_signal()
        os.kill(os.getpid(), signal.SIGUSR2)
        assert open(os.path.join(str(tmp_path), "w.pdf"),
                    "rb").read(4) == b"%PDF"
        signal.signal(signal.SIGUSR2, signal.SIG_DFL)

    def test_plotter_feeds_subscribers(self):
        seen = []
        plotting.bus.subscribe(seen.append)
        try:
            plotting.bus.publish({"name": "x", "kind": "curve"})
            assert seen and seen[0]["name"] == "x"
        finally:
            plotting.bus.unsubscribe(seen.append)
