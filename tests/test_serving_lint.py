"""Serving-plane static analysis (ISSUE 16): the VD7xx decode-path
auditor and the VT8xx concurrency lint.

PR 4 test pattern: per-rule seeded-hazard fixtures where each rule
fires exactly once, a clean sweep over the real engine configs
(bf16/int8/w4a8 x paged/dense x spec on/off) and the full services
tree, a purity pin (zero dispatch, zero device arrays), and the CLI
gates in-process."""

import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from veles_tpu import prng
from veles_tpu.analysis import concurrency_lint, decode_audit
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models import zoo
from veles_tpu.models.generate import (ContinuousBatcher, LMGenerator,
                                       PagedContinuousBatcher)
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.ops import quant


@pytest.fixture(scope="module")
def lm_wf():
    prng.seed_all(31)
    r = np.random.RandomState(5)
    toks = ((np.arange(16)[None, :] * 2
             + r.randint(0, 4, 192)[:, None]) % 13).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=48,
                             class_lengths=[0, 48, 144])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=13, d_model=32,
                                  n_heads=4, n_layers=2, lr=5e-3,
                                  dropout=0.0),
        loader=loader, loss="lm",
        decision_config={"max_epochs": 1},
        name="serving-lint-lm")
    wf.initialize()
    return wf


@pytest.fixture(scope="module")
def lm_wf48():
    """Longer position table (t=48) so a pool block can sit above the
    bf16 sublane minimum yet off its tile (the VD705 seed needs
    block=24 to divide max_len)."""
    prng.seed_all(33)
    r = np.random.RandomState(7)
    toks = ((np.arange(48)[None, :] * 2
             + r.randint(0, 4, 96)[:, None]) % 13).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=24,
                             class_lengths=[0, 24, 72])
    wf = StandardWorkflow(
        layers=zoo.transformer_lm(vocab_size=13, d_model=32,
                                  n_heads=4, n_layers=2, lr=5e-3,
                                  dropout=0.0),
        loader=loader, loss="lm",
        decision_config={"max_epochs": 1},
        name="serving-lint-lm48")
    wf.initialize()
    return wf


def _rules(findings, rule):
    return [f for f in findings if f.rule == rule]


# --------------------------------------------------------------------------
# VD7xx — seeded hazards, each rule fires exactly once
# --------------------------------------------------------------------------

class TestSeededVD:
    def test_vd700_payload_dequant_outside_dot(self, lm_wf):
        """A payload-sized int8->float convert whose result feeds a
        reduction (not a dot) — the hoistable dense-dequant bug class
        PR 14 erased, now a rule."""
        gen = LMGenerator(lm_wf.trainer, max_len=16, weights="int8")
        cb = ContinuousBatcher(gen, slots=2)
        qws = [l for l in jax.tree_util.tree_leaves(
                   gen.params, is_leaf=quant.is_quant)
               if isinstance(l, quant.QuantWeight)]
        assert qws
        body = cb._tick_body()

        def bad_body(params, st, aids):
            st = body(params, st, aids)
            qw = [l for l in jax.tree_util.tree_leaves(
                      params, is_leaf=quant.is_quant)
                  if isinstance(l, quant.QuantWeight)][0]
            dense = qw.q.astype(jnp.float32)             # BAD: no dot
            return (st[0] + dense.sum().astype(st[0].dtype),) + st[1:]

        cb._tick_body = lambda: bad_body
        findings = decode_audit.audit_decode_tick(cb)
        assert len(_rules(findings, "VD700")) == 1, findings

    def test_vd701_donation_miss(self, lm_wf):
        """A dispatch wrapper that forgets donate_argnums re-allocates
        every state leaf (KV caches included) per tick."""
        gen = LMGenerator(lm_wf.trainer, max_len=16)
        cb = ContinuousBatcher(gen, slots=2)
        cb._jit_ticks = lambda fn: jax.jit(fn)   # donation dropped
        findings = decode_audit.audit_decode_tick(cb)
        vd701 = _rules(findings, "VD701")
        assert len(vd701) == 1, findings
        assert "0 of" in vd701[0].message

    def test_vd702_host_callback_in_tick(self, lm_wf):
        gen = LMGenerator(lm_wf.trainer, max_len=16)
        cb = ContinuousBatcher(gen, slots=2)
        body = cb._tick_body()

        def chatty(params, st, aids):
            st = body(params, st, aids)
            jax.debug.print("tick {}", st[1].sum())   # BAD: host sync
            return st

        cb._tick_body = lambda: chatty
        findings = decode_audit.audit_decode_tick(cb)
        assert len(_rules(findings, "VD702")) == 1, findings

    def test_vd702_trace_failure_is_the_finding(self, lm_wf):
        """Data-dependent python control flow inside the tick cannot
        trace abstractly — the failure itself is the VD702."""
        gen = LMGenerator(lm_wf.trainer, max_len=16)
        cb = ContinuousBatcher(gen, slots=2)
        body = cb._tick_body()

        def host_branch(params, st, aids):
            if bool(st[4].sum() > 0):            # BAD: host decision
                return body(params, st, aids)
            return st

        cb._tick_body = lambda: host_branch
        findings = decode_audit.audit_decode_tick(cb)
        vd702 = _rules(findings, "VD702")
        assert len(vd702) == 1, findings
        assert "failed to trace" in vd702[0].message

    def test_vd703_weak_scalar_in_signature(self, lm_wf):
        """A python scalar leaking into the tick signature retraces
        per distinct value (the PR 3 compile counters count it at
        runtime; the rule catches it before)."""
        gen = LMGenerator(lm_wf.trainer, max_len=16)
        cb = ContinuousBatcher(gen, slots=2)
        body = cb._tick_body()
        state0 = cb._state
        cb._state = lambda: state0() + (0.25,)   # BAD: host float

        def leaky(params, st, aids):
            out = body(params, st[:-1], aids)
            return out + (st[-1] * 1.0,)

        cb._tick_body = lambda: leaky
        findings = decode_audit.audit_decode_tick(cb)
        assert len(_rules(findings, "VD703")) == 1, findings

    def test_vd704_collective_bound_tick(self, lm_wf, monkeypatch):
        """Under a model-axis mesh, per-tick collective bytes priced
        above the tick's KV reads flag an ICI-bound decode."""
        from veles_tpu.parallel import MeshConfig, make_mesh
        mc = MeshConfig(make_mesh({"data": 1, "model": 2}))
        gen = LMGenerator(lm_wf.trainer, max_len=16, mesh_cfg=mc)
        cb = ContinuousBatcher(gen, slots=2)
        from veles_tpu.analysis import sharding_audit
        monkeypatch.setattr(
            sharding_audit, "collective_stats",
            lambda text: {"all-gather": {"count": 4,
                                         "bytes": 1 << 30}})
        findings = decode_audit.audit_decode_tick(cb)
        vd704 = _rules(findings, "VD704")
        assert len(vd704) == 1, findings
        assert "ICI-bound" in vd704[0].message

    def test_vd704_silent_without_model_axis(self, lm_wf, monkeypatch):
        """No mesh — the rule must not even lower for collectives."""
        gen = LMGenerator(lm_wf.trainer, max_len=16)
        cb = ContinuousBatcher(gen, slots=2)
        from veles_tpu.analysis import sharding_audit
        monkeypatch.setattr(
            sharding_audit, "collective_stats",
            lambda text: {"all-gather": {"count": 4,
                                         "bytes": 1 << 30}})
        findings = decode_audit.audit_decode_tick(cb)
        assert not _rules(findings, "VD704"), findings

    def test_vd705_bad_pool_block_geometry(self, lm_wf48):
        """A pinned pool block above the sublane minimum but off the
        native tile (12 % 8 != 0 for the f32 pool this CPU build
        makes) fails the VP6xx audit at exactly the geometry the
        engine resolved."""
        gen = LMGenerator(lm_wf48.trainer, max_len=48)
        cb = PagedContinuousBatcher(gen, slots=2, block=12,
                                    pool_tokens=96)
        assert cb.fused and cb.block == 12
        findings = decode_audit.audit_decode_tick(cb)
        vd705 = _rules(findings, "VD705")
        assert len(vd705) == 1, findings
        assert "block=12" in vd705[0].message
        assert "VP600" in vd705[0].message

    def test_vd705_silent_below_sublane_fallback(self, lm_wf):
        """A block below the sublane minimum never launches the fused
        kernel on hardware (the engine's own mosaic_ok fallback) — no
        geometry to audit, no finding."""
        gen = LMGenerator(lm_wf.trainer, max_len=16,
                          weights="int8", cache_dtype="int8")
        cb = PagedContinuousBatcher(gen, slots=2, block=16,
                                    pool_tokens=64)
        findings = decode_audit.audit_decode_tick(cb)
        assert not _rules(findings, "VD705"), findings

    def test_all_vd_rules_fire_exactly_once_on_seeds(self, lm_wf,
                                                     lm_wf48,
                                                     monkeypatch):
        """The aggregated PR 4 pin: every VD7xx rule has a seeded
        hazard on which it fires exactly once."""
        counts = {}
        for rule, seed in [
                ("VD700", self.test_vd700_payload_dequant_outside_dot),
                ("VD701", self.test_vd701_donation_miss),
                ("VD702", self.test_vd702_host_callback_in_tick),
                ("VD703", self.test_vd703_weak_scalar_in_signature)]:
            seed(lm_wf)
            counts[rule] = 1
        self.test_vd704_collective_bound_tick(lm_wf, monkeypatch)
        counts["VD704"] = 1
        self.test_vd705_bad_pool_block_geometry(lm_wf48)
        counts["VD705"] = 1
        assert counts == {r: 1 for r in decode_audit.RULES}


# --------------------------------------------------------------------------
# VD7xx — clean sweep over the real engine configs
# --------------------------------------------------------------------------

VARIANTS = [
    ("bf16-dense", dict(), dict()),
    ("bf16-spec4", dict(), dict(speculative_k=4)),
    ("bf16-paged", dict(), dict(paged=True)),
    ("int8-dense", dict(weights="int8"), dict()),
    ("int8-paged-q8", dict(weights="int8", cache_dtype="int8"),
     dict(paged=True)),
    ("w4a8-dense", dict(weights="w4a8"), dict()),
]


class TestCleanSweep:
    @pytest.mark.parametrize("tag,gen_kw,cb_kw",
                             VARIANTS, ids=[v[0] for v in VARIANTS])
    def test_real_decode_tick_is_clean(self, lm_wf, tag, gen_kw,
                                       cb_kw):
        """Acceptance: the real decode path passes for every
        quantization/pool/speculative variant."""
        cb_kw = dict(cb_kw)
        gen = LMGenerator(lm_wf.trainer, max_len=16, **gen_kw)
        if cb_kw.pop("paged", False):
            cb = PagedContinuousBatcher(gen, slots=2, pool_tokens=64,
                                        **cb_kw)
        else:
            cb = ContinuousBatcher(gen, slots=2, **cb_kw)
        findings = decode_audit.audit_decode_tick(cb)
        assert not findings, findings

    @pytest.mark.parametrize("scheme", [None, "int8", "w4a8"])
    def test_real_prefill_pass_is_clean(self, lm_wf, scheme):
        gen = LMGenerator(lm_wf.trainer, max_len=16, weights=scheme)
        findings = decode_audit.audit_prefill_pass(gen, segment=8)
        assert not findings, findings

    def test_lint_serving_sweeps_all_variants_clean(self, lm_wf):
        findings = decode_audit.lint_serving(lm_wf.trainer, max_len=16)
        assert not findings, findings

    def test_services_tree_is_clean(self):
        """Acceptance: the whole threaded control plane passes the
        VT8xx lint (genuine findings were fixed or carry an inline
        ``# lint-ok`` rationale)."""
        findings = concurrency_lint.lint_concurrency()
        assert not findings, findings


# --------------------------------------------------------------------------
# purity: zero dispatch, zero device arrays
# --------------------------------------------------------------------------

class TestPurity:
    def test_decode_audit_allocates_nothing(self, lm_wf):
        """The audit traces and lowers abstractly: not one device
        array may outlive it (construction happens OUTSIDE the
        measured region — building a quantized generator does
        allocate, exactly like serving itself would)."""
        import gc
        gen = LMGenerator(lm_wf.trainer, max_len=16, weights="int8")
        cb = ContinuousBatcher(gen, slots=2)
        gc.collect()
        before = len(jax.live_arrays())
        findings = decode_audit.audit_decode_tick(cb)
        findings += decode_audit.audit_prefill_pass(gen, segment=8)
        gc.collect()
        assert len(jax.live_arrays()) <= before
        assert not findings, findings

    def test_concurrency_lint_never_imports_services(self):
        """The VT lint is AST-only: linting a file with a poisoned
        import proves nothing runs."""
        import sys
        poisoned = [m for m in ("veles_tpu.services.podmaster",)
                    if m in sys.modules]
        findings = concurrency_lint.lint_concurrency()
        assert isinstance(findings, list)
        for m in ("veles_tpu.services.podmaster",):
            if m not in poisoned:
                assert m not in sys.modules


# --------------------------------------------------------------------------
# VT8xx — seeded hazards, each rule fires exactly once
# --------------------------------------------------------------------------

VT_SEEDS = {
    "VT800": """
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.counter = 0

            def start(self):
                threading.Thread(target=self._pump,
                                 daemon=True).start()
                threading.Thread(target=self._drain,
                                 daemon=True).start()

            def _pump(self):
                self.counter += 1

            def _drain(self):
                self.counter = 0
        """,
    "VT801": """
        import threading

        class Inverted:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """,
    "VT802": """
        import signal
        import threading

        class SigLock:
            def __init__(self):
                self._lock = threading.Lock()
                signal.signal(signal.SIGUSR1, self._on_sig)

            def _on_sig(self, signum, frame):
                self._note()

            def _note(self):
                with self._lock:
                    pass
        """,
    "VT803": """
        import threading

        def spawn(fn):
            threading.Thread(target=fn).start()
        """,
    "VT804": """
        import queue

        def make_channel():
            return queue.Queue()
        """,
}


class TestSeededVT:
    @pytest.mark.parametrize("rule", sorted(VT_SEEDS))
    def test_rule_fires_exactly_once(self, rule, tmp_path):
        path = tmp_path / ("%s.py" % rule.lower())
        path.write_text(textwrap.dedent(VT_SEEDS[rule]))
        findings = concurrency_lint.lint_module(str(path))
        assert [f.rule for f in findings] == [rule], findings

    def test_all_vt_rules_covered(self):
        assert tuple(sorted(VT_SEEDS)) == concurrency_lint.RULES

    def test_vt802_closure_handler(self, tmp_path):
        """A handler defined as a local closure (the graphics.py
        SIGUSR2 idiom) is followed through the registering method."""
        path = tmp_path / "closure.py"
        path.write_text(textwrap.dedent("""
            import signal
            import threading

            class ClosureSig:
                def __init__(self):
                    self._lock = threading.Lock()

                def install(self):
                    def handler(signum, frame):
                        self.flush()
                    signal.signal(signal.SIGUSR2, handler)

                def flush(self):
                    with self._lock:
                        pass
            """))
        findings = concurrency_lint.lint_module(str(path))
        assert [f.rule for f in findings] == ["VT802"], findings

    def test_rlock_quiets_vt802(self, tmp_path):
        path = tmp_path / "rlock.py"
        path.write_text(textwrap.dedent(VT_SEEDS["VT802"]).replace(
            "threading.Lock()", "threading.RLock()"))
        findings = concurrency_lint.lint_module(str(path))
        assert not findings, findings

    def test_common_lock_quiets_vt800(self, tmp_path):
        path = tmp_path / "locked.py"
        path.write_text(textwrap.dedent("""
            import threading

            class Shared:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.counter = 0

                def start(self):
                    threading.Thread(target=self._pump,
                                     daemon=True).start()
                    threading.Thread(target=self._drain,
                                     daemon=True).start()

                def _pump(self):
                    with self._lock:
                        self.counter += 1

                def _drain(self):
                    with self._lock:
                        self.counter = 0
            """))
        findings = concurrency_lint.lint_module(str(path))
        assert not findings, findings

    def test_bounded_queue_and_daemon_thread_pass(self, tmp_path):
        path = tmp_path / "clean.py"
        path.write_text(textwrap.dedent("""
            import queue
            import threading

            def make():
                q = queue.Queue(maxsize=64)
                t = threading.Thread(target=q.get, daemon=True)
                t.start()
                return q
            """))
        assert not concurrency_lint.lint_module(str(path))

    def test_joined_thread_passes(self, tmp_path):
        path = tmp_path / "joined.py"
        path.write_text(textwrap.dedent("""
            import threading

            def run(fn):
                t = threading.Thread(target=fn)
                t.start()
                t.join()
            """))
        assert not concurrency_lint.lint_module(str(path))

    def test_inline_suppression_with_rationale(self, tmp_path):
        path = tmp_path / "ok.py"
        path.write_text(textwrap.dedent("""
            import queue

            def make_channel():
                # lint-ok: VT804 — drained every cycle, producers
                # bounded by pod size, events must not drop
                return queue.Queue()
            """))
        assert not concurrency_lint.lint_module(str(path))

    def test_bare_lint_ok_suppresses_nothing(self, tmp_path):
        path = tmp_path / "bare.py"
        path.write_text(textwrap.dedent("""
            import queue

            def make_channel():
                # lint-ok: because reasons
                return queue.Queue()
            """))
        findings = concurrency_lint.lint_module(str(path))
        assert [f.rule for f in findings] == ["VT804"], findings


# --------------------------------------------------------------------------
# CLI — the unified gate
# --------------------------------------------------------------------------

WF_TEMPLATE = """
import numpy as np
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.zoo import transformer_lm


def run(load, main):
    r = np.random.RandomState(5)
    toks = ((np.arange(16)[None, :] * 2
             + r.randint(0, 4, 96)[:, None]) % 13).astype(np.int32)
    loader = FullBatchLoader(None, data=toks, labels=toks,
                             minibatch_size=48,
                             class_lengths=[0, 24, 72])
    load(StandardWorkflow,
         layers=transformer_lm(vocab_size=13, d_model=32, n_heads=4,
                               n_layers=2, lr=5e-3, dropout=0.0),
         loader=loader, loss="lm",
         decision_config={"max_epochs": 1}, name="cli-serve-lm")
    main()
"""


class TestCLI:
    def test_serve_and_concurrency_clean(self, tmp_path, capsys):
        from veles_tpu.analysis.cli import main
        wf = tmp_path / "wf.py"
        wf.write_text(WF_TEMPLATE)
        rc = main([str(wf), "--serve", "--concurrency",
                   "--fail-on", "error"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "VD7" not in out and "VT8" not in out

    def test_concurrency_alone_needs_no_workflow(self, capsys):
        from veles_tpu.analysis.cli import main
        rc = main(["--concurrency"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "no findings" in out

    def test_workflow_required_without_concurrency(self, capsys):
        from veles_tpu.analysis.cli import main
        with pytest.raises(SystemExit) as e:
            main([])
        assert e.value.code == 2

    def test_fail_on_unifies_vt_findings(self, tmp_path, capsys,
                                         monkeypatch):
        """--fail-on {error,warning} gates the new families through
        findings.threshold_reached — a VT warning flips the exit only
        under --fail-on warning."""
        import veles_tpu.analysis as analysis
        from veles_tpu.analysis.cli import main
        from veles_tpu.analysis.findings import WARNING, Finding
        monkeypatch.setattr(
            analysis, "lint_concurrency",
            lambda paths=None, root=None: [Finding(
                "VT804", WARNING, "x.py:1", "seeded")])
        assert main(["--concurrency"]) == 0
        assert main(["--concurrency", "--fail-on", "warning"]) == 1
        out = capsys.readouterr().out
        assert "VT804" in out
