"""Bit-exactness cross-check matrix (r4 verdict #5): for every zoo
family, export RANDOM-weight models (no dataset needed — the published
accuracy rows stay gated on real data, tests/test_accuracy_gates.py)
and assert the three forward paths agree on identical inputs:

    jax forward  ==  StableHLO artifact  ==  native C++ runtime

to 1e-6 under f32 compute.  The StableHLO leg is jax.export round-trip
(exact by construction — same XLA program); the native leg is an
independent C++ reimplementation, so agreement there validates every
operator's math, not just the serialization.  Configs the native
runtime deliberately rejects (MoE experts) assert the
jax==StableHLO leg plus the loud unsupported-type load error.

Smoke-tier by design: random weights, tiny shapes, no training.
(Ref parity: libVeles's GoogleTest suite loads real exported packages,
SURVEY.md §4 — this matrix is that contract swept across the zoo.)"""

import shutil

import numpy as np
import pytest

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models import zoo
from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.services.export import (export_stablehlo, export_workflow,
                                       load_stablehlo)

HAS_GXX = shutil.which("g++") is not None

#: (family, layer-spec factory, input sample shape, loss, native?)
FAMILIES = [
    ("mnist_mlp", lambda: zoo.mnist_mlp(), (784,), None, True),
    ("mnist_autoencoder", lambda: zoo.mnist_autoencoder(), (784,),
     "mse", True),
    # 16x16 is the smallest input whose three stride-2 pools stay
    # non-empty (16 -> 7 -> 3 -> 1)
    ("cifar_conv", lambda: zoo.cifar_conv(), (16, 16, 3), None, True),
    ("conv_autoencoder", lambda: zoo.conv_autoencoder(), (8, 8, 1),
     "mse", True),
    ("resnet_gn", lambda: zoo.resnet_gn(n_classes=10, width=8,
                                        blocks_per_stage=1, stages=2,
                                        pool=4), (8, 8, 1), None, True),
    ("transformer_classifier",
     lambda: zoo.transformer_classifier(n_classes=4, d_model=16,
                                        n_heads=2, n_layers=1,
                                        dropout=0.0), (6, 5), None,
     True),
    ("transformer_lm",
     lambda: zoo.transformer_lm(vocab_size=17, d_model=16, n_heads=2,
                                n_layers=1, dropout=0.0, pos="rope"),
     (8,), "lm", True),
    # learned positional table: the native runtime must read the
    # exported "pos" array instead of synthesizing the sinusoid
    ("transformer_lm_learnedpos",
     lambda: zoo.transformer_lm(vocab_size=17, d_model=16, n_heads=2,
                                n_layers=1, dropout=0.0,
                                pos="learned"),
     (8,), "lm", True),
    # MoE: the StableHLO leg runs (symbolic-batch capacity math,
    # ops/moe.py) — the native C++ leg stays a loud load rejection
    ("transformer_moe_rejected",
     lambda: zoo.transformer_lm(vocab_size=17, d_model=16, n_heads=2,
                                n_layers=1, dropout=0.0,
                                n_experts=2),
     (8,), "lm", False),
    # the hard serving combo: grouped-query attention, sliding window,
    # tied embedding head — exercises the native runtime's GQA kv
    # mapping, the windowed causal mask, and cross-unit tie resolution
    ("transformer_lm_gqa_win",
     lambda: zoo.transformer_lm(vocab_size=17, d_model=16, n_heads=4,
                                n_kv_heads=2, n_layers=2, dropout=0.0,
                                pos="rope", window=3,
                                tie_embeddings=True),
     (8,), "lm", True),
]


def _build(name, layers, in_shape, loss):
    """Random-weight workflow: initialize() seeds params from the PRNG;
    the loader carries synthetic data purely to fix shapes/dtypes."""
    prng.seed_all(101)
    n = 8
    r = np.random.RandomState(7)
    if loss == "lm":
        data = r.randint(0, 17, (n,) + in_shape).astype(np.int32)
        labels = data
    else:
        data = r.rand(n, *in_shape).astype(np.float32)
        labels = (data.reshape(n, -1)
                  if loss == "mse" else
                  r.randint(0, 4, n).astype(np.int32))
    loader = FullBatchLoader(None, data=data, labels=labels,
                             minibatch_size=n,
                             class_lengths=[0, 0, n])
    wf = StandardWorkflow(layers=layers, loader=loader,
                          loss=loss or "softmax",
                          decision_config={"max_epochs": 1},
                          name="exact-" + name)
    wf.initialize()
    return wf, data


_IDS = [f[0] for f in FAMILIES]


@pytest.mark.parametrize("name,factory,in_shape,loss,native_ok",
                         FAMILIES, ids=_IDS)
def test_stablehlo_leg_exact(name, factory, in_shape, loss, native_ok,
                             tmp_path, f32_precision):

    """Leg 1, every family: StableHLO artifact == live forward to 1e-6
    (reports independently of the C++ toolchain's presence)."""
    wf, x = _build(name, factory(), in_shape, loss)
    want = np.asarray(wf.forward_fn()(wf.trainer.params, x))
    sp = str(tmp_path / (name + ".stablehlo.zip"))
    export_stablehlo(wf, sp, platforms=("cpu",))
    fn, _meta = load_stablehlo(sp)
    np.testing.assert_allclose(np.asarray(fn(x)), want,
                               rtol=1e-6, atol=1e-6,
                               err_msg="stablehlo leg: " + name)


@pytest.mark.skipif(not HAS_GXX, reason="no g++ toolchain")
@pytest.mark.parametrize("name,factory,in_shape,loss,native_ok",
                         FAMILIES, ids=_IDS)
def test_native_leg_exact(name, factory, in_shape, loss, native_ok,
                          tmp_path, f32_precision):
    """Leg 2: native C++ runtime == live forward for supported
    families; deliberately-unsupported configs (MoE) assert the loud
    load error instead."""
    from veles_tpu.services.native import NativeWorkflow

    wf, x = _build(name, factory(), in_shape, loss)
    want = np.asarray(wf.forward_fn()(wf.trainer.params, x))
    pp = str(tmp_path / (name + ".zip"))
    export_workflow(wf, pp)
    if native_ok:
        native = NativeWorkflow(pp)
        got = native(np.ascontiguousarray(x.reshape(len(x), -1)))
        native.close()
        # the native runtime emits flat rows; compare values not layout
        np.testing.assert_allclose(got.reshape(want.shape), want,
                                   rtol=1e-5, atol=1e-6,
                                   err_msg="native leg: " + name)
    else:
        with pytest.raises(Exception,
                           match="not supported|unsupported"):
            NativeWorkflow(pp)


def test_int8_transformer_package_through_native(tmp_path,
                                                 f32_precision):
    """int8 transformer package → native runtime: the per-channel
    scale folding covers the block's named sub-arrays (mha/wq,
    w1/w2, embedding table) — outputs match the f32 forward within
    quantization error, and the argmax token survives for most
    positions."""
    from veles_tpu.services.native import NativeWorkflow

    name, factory, in_shape, loss, _ = [
        f for f in FAMILIES if f[0] == "transformer_lm_gqa_win"][0]
    wf, x = _build(name, factory(), in_shape, loss)
    want = np.asarray(wf.forward_fn()(wf.trainer.params, x))
    pp = str(tmp_path / "tlm8.zip")
    export_workflow(wf, pp, dtype="int8")
    native = NativeWorkflow(pp)
    got = native(np.ascontiguousarray(
        x.reshape(len(x), -1))).reshape(want.shape)
    native.close()
    # int8 tolerance: probabilities, so absolute error is meaningful
    np.testing.assert_allclose(got, want, atol=0.08)
    agree = (got.argmax(-1) == want.argmax(-1)).mean()
    assert agree > 0.9, agree


@pytest.mark.parametrize("family", ["transformer_lm",
                                    "transformer_lm_gqa_win"])
def test_native_greedy_generate_matches_python(family, tmp_path,
                                               f32_precision):
    """C++ greedy decode == LMGenerator greedy, token for token (int
    equality).  Both sides decode through k/v caches (the C++ one
    streams positions through per-block caches, O(T) per token) —
    agreeing integers prove the block math, the GQA/windowed cache
    bookkeeping, and the rope position handling on both sides."""
    import jax.numpy as jnp

    from veles_tpu.models.generate import LMGenerator
    from veles_tpu.services.native import NativeWorkflow

    name, factory, in_shape, loss, _ = [
        f for f in FAMILIES if f[0] == family][0]
    wf, x = _build(name, factory(), in_shape, loss)
    # a few training steps so greedy argmax is decisive, not tie-noise
    for _ in range(30):
        wf.loader.run()
        wf.trainer.run()
    wf.trainer.flush()
    pp = str(tmp_path / "gen.zip")
    export_workflow(wf, pp)

    gen = LMGenerator(wf.trainer, max_len=in_shape[0],
                      cache_dtype=jnp.float32)
    prompt = np.asarray(x[0, :3])
    want = np.asarray(gen.generate(prompt[None], max_new=5))[0]

    native = NativeWorkflow(pp)
    got = native.generate(prompt, max_new=5)
    native.close()
    np.testing.assert_array_equal(got, want[:len(got)],
                                  err_msg="native greedy diverged")
    assert len(got) == len(prompt) + 5


def test_native_sampled_generate(tmp_path, f32_precision):
    """Sampling plumbing: top_k=1 collapses to greedy exactly; a
    temperature>0 run is deterministic per seed, varies across seeds,
    and stays in-vocab."""
    from veles_tpu.services.native import NativeWorkflow

    name, factory, in_shape, loss, _ = [
        f for f in FAMILIES if f[0] == "transformer_lm"][0]
    wf, x = _build(name, factory(), in_shape, loss)
    pp = str(tmp_path / "s.zip")
    export_workflow(wf, pp)
    native = NativeWorkflow(pp)
    try:
        prompt = np.asarray(x[0, :3])
        greedy = native.generate(prompt, max_new=5)
        topk1 = native.generate(prompt, max_new=5, temperature=0.7,
                                top_k=1, seed=9)
        np.testing.assert_array_equal(topk1, greedy)
        s1 = native.generate(prompt, max_new=5, temperature=1.5,
                             seed=1)
        s1b = native.generate(prompt, max_new=5, temperature=1.5,
                              seed=1)
        np.testing.assert_array_equal(s1, s1b)   # seed-deterministic
        assert ((0 <= s1) & (s1 < 17)).all()
        draws = {tuple(native.generate(prompt, max_new=5,
                                       temperature=1.5, seed=sd))
                 for sd in range(1, 7)}
        assert len(draws) > 1      # different seeds explore
    finally:
        native.close()


def test_native_generate_from_int8_package(tmp_path, f32_precision):
    """Generation from a quantized package: the dequantized-on-load
    weights drive the same KV-cached decode; on a trained model the
    token stream stays overwhelmingly equal to the f32 package's."""
    from veles_tpu.services.native import NativeWorkflow

    name, factory, in_shape, loss, _ = [
        f for f in FAMILIES if f[0] == "transformer_lm"][0]
    wf, x = _build(name, factory(), in_shape, loss)
    for _ in range(30):       # decisive argmax, not tie noise
        wf.loader.run()
        wf.trainer.run()
    wf.trainer.flush()
    p32 = str(tmp_path / "g32.zip")
    p8 = str(tmp_path / "g8.zip")
    export_workflow(wf, p32)
    export_workflow(wf, p8, dtype="int8")
    prompt = np.asarray(x[0, :3])
    n32 = NativeWorkflow(p32)
    want = n32.generate(prompt, max_new=5)
    n32.close()
    n8 = NativeWorkflow(p8)
    got = n8.generate(prompt, max_new=5)
    n8.close()
    agree = (got == want).mean()
    assert agree >= 0.75, (got, want)
