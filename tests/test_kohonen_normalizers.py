"""Kohonen SOM + normalizer registry tests (ref SOM algorithm docs and
veles/normalization.py behavior)."""

import numpy as np
import pytest
from sklearn.datasets import load_digits

from veles_tpu import prng
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.loader.normalization import (NormalizerBase, make_normalizer)
from veles_tpu.models.kohonen import KohonenWorkflow, grid_coords, winners


class TestNormalizers:
    data = (np.arange(12, dtype=np.float32).reshape(3, 4) * 20)

    def test_registry_complete(self):
        for name in ("none", "linear", "range_linear", "exp", "mean_disp",
                     "external_mean", "pointwise"):
            assert name in NormalizerBase.mapping, name

    def test_linear_per_sample_range(self):
        out = make_normalizer("linear").normalize(self.data)
        np.testing.assert_allclose(out.min(axis=1), -1.0)
        np.testing.assert_allclose(out.max(axis=1), 1.0)

    def test_range_linear_roundtrip(self):
        n = make_normalizer("range_linear", source_range=(0, 255),
                            target_range=(-1, 1))
        x = np.array([0.0, 127.5, 255.0], np.float32)
        out = n.normalize(x)
        np.testing.assert_allclose(out, [-1.0, 0.0, 1.0])
        np.testing.assert_allclose(n.denormalize(out), x, atol=1e-5)

    def test_mean_disp(self):
        n = make_normalizer("mean_disp")
        n.analyze(self.data)
        out = n.normalize(self.data)
        np.testing.assert_allclose(out.mean(axis=0), 0.0, atol=1e-5)
        assert np.abs(out).max() <= 1.0 + 1e-5

    def test_pointwise_spans_unit_interval(self):
        n = make_normalizer("pointwise")
        n.analyze(self.data)
        out = n.normalize(self.data)
        np.testing.assert_allclose(out.min(axis=0), -1.0, atol=1e-6)
        np.testing.assert_allclose(out.max(axis=0), 1.0, atol=1e-6)

    def test_external_mean(self):
        mean = np.full((4,), 10.0, np.float32)
        n = make_normalizer("external_mean", mean_source=mean)
        out = n.normalize(self.data)
        np.testing.assert_allclose(out, self.data - 10.0)

    def test_state_pickles(self):
        import pickle
        n = make_normalizer("pointwise")
        n.analyze(self.data)
        st = pickle.dumps(n.state)
        n2 = make_normalizer("pointwise")
        n2.state = pickle.loads(st)
        np.testing.assert_array_equal(n2.normalize(self.data),
                                      n.normalize(self.data))


class TestKohonen:
    def test_winner_search_matches_bruteforce(self):
        rng = np.random.default_rng(0)
        w = rng.normal(size=(25, 8)).astype(np.float32)
        x = rng.normal(size=(10, 8)).astype(np.float32)
        got = np.asarray(winners(w, x))
        want = np.argmin(((x[:, None, :] - w[None]) ** 2).sum(-1), axis=1)
        np.testing.assert_array_equal(got, want)

    def test_grid_coords(self):
        c = np.asarray(grid_coords(3, 2))
        assert c.shape == (6, 2)
        np.testing.assert_array_equal(c[0], [0, 0])
        np.testing.assert_array_equal(c[-1], [2, 1])

    def test_som_organizes_digits(self):
        """Train an 6x6 SOM on digits; quantization error must drop
        substantially and the map must use many distinct neurons."""
        prng.seed_all(11)
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)
        loader = FullBatchLoader(None, data=x, minibatch_size=100,
                                 class_lengths=[0, 0, len(x)],
                                 name="som-loader")
        wf = KohonenWorkflow(loader=loader, sx=6, sy=6, n_epochs=8,
                             name="som")
        wf.initialize()
        qe0 = wf.trainer.quantization_error(x)
        wf.run()
        qe1 = wf.trainer.quantization_error(x)
        assert qe1 < 0.6 * qe0, (qe0, qe1)
        used = len(set(np.asarray(wf.trainer.assign(x)).tolist()))
        assert used >= 18   # at least half the 36 neurons in use

    def test_batch_som_matches_online_quality(self):
        """The batched (MXU) SOM step must reach the same quantization
        error as the exact per-sample online scan (VERDICT r1 weak #3)."""
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)

        def train(algorithm):
            prng.seed_all(7)
            loader = FullBatchLoader(None, data=x, minibatch_size=100,
                                     class_lengths=[0, 0, len(x)])
            wf = KohonenWorkflow(loader=loader, sx=6, sy=6, n_epochs=8,
                                 algorithm=algorithm, name="som-" + algorithm)
            wf.initialize()
            wf.run()
            return wf.trainer.quantization_error(x)

        qe_batch = train("batch")
        qe_online = train("online")
        # equal quality: within 10% of the online rule's error
        assert qe_batch <= qe_online * 1.10, (qe_batch, qe_online)

    def test_benchmark_som_runs(self):
        from veles_tpu.models.kohonen import benchmark_som
        res = benchmark_som(n_samples=256, n_features=32, sx=4, sy=4,
                            minibatch_size=64, steps=3)
        assert res["ms_per_step"] > 0 and res["scan_ms_per_step"] > 0
        assert res["quantization_error"] > 0
        # the fused sweep is the same math: identical final map
        assert (res["sweep_quantization_error"]
                == pytest.approx(res["quantization_error"], rel=1e-5))

    def test_fused_dispatch_matches_per_step(self):
        """steps_per_dispatch: the indexed sweep must produce the same
        map as per-step dispatch (same ops, same order)."""
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)

        def train(k):
            prng.seed_all(7)
            loader = FullBatchLoader(None, data=x, minibatch_size=100,
                                     class_lengths=[0, 0, len(x)])
            wf = KohonenWorkflow(loader=loader, sx=5, sy=5, n_epochs=4,
                                 steps_per_dispatch=k, name="som-k%d" % k)
            wf.initialize()
            wf.run()
            assert not wf.trainer._pending
            return wf.trainer.host_weights()

        np.testing.assert_allclose(train(1), train(4), rtol=2e-5,
                                   atol=2e-6)

    def test_som_reproducible(self):
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)[:500]

        def run():
            prng.seed_all(3)
            loader = FullBatchLoader(None, data=x, minibatch_size=100,
                                     class_lengths=[0, 0, len(x)])
            wf = KohonenWorkflow(loader=loader, sx=4, sy=4, n_epochs=3,
                                 name="som-r")
            wf.initialize()
            wf.run()
            return wf.trainer.host_weights()

        np.testing.assert_array_equal(run(), run())


class TestSOMPlotter:
    def test_hits_and_umatrix(self, tmp_path):
        from veles_tpu.models.kohonen import SOMPlotter
        prng.seed_all(12)
        d = load_digits()
        x = (d.data / 16.0).astype(np.float32)[:600]
        loader = FullBatchLoader(None, data=x, minibatch_size=100,
                                 class_lengths=[0, 0, len(x)])
        wf = KohonenWorkflow(loader=loader, sx=5, sy=4, n_epochs=3,
                             name="som-plot")
        wf.initialize()
        wf.run()
        path = str(tmp_path / "som.png")
        payload = SOMPlotter.plot(wf.trainer, x, path)
        hits = np.asarray(payload["hits"])
        um = np.asarray(payload["umatrix"])
        assert hits.shape == (4, 5) and um.shape == (4, 5)
        assert hits.sum() == len(x)          # every sample lands somewhere
        assert (um >= 0).all()
        import os
        assert os.path.getsize(path) > 1000
