// Native inference runtime: loads a veles_tpu package (ZIP of
// contents.json + .npy arrays, ref Workflow.package_export
// veles/workflow.py:864-971) and executes the forward pass on CPU.
// Plays the role of the reference's libVeles engine (SURVEY.md §2.10):
// package loader, unit factory, topological execute, arena memory
// optimizer, C API for embedding.
//
// Build: make -C native   (produces libveles_native.so)

#include <algorithm>
#include <cmath>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "json.h"
#include "memory_optimizer.h"
#include "package.h"

namespace veles_native {

struct Shape3 {  // H, W, C (or 1,1,F for flat)
  int h = 1, w = 1, c = 1;
  size_t elems() const {
    return static_cast<size_t>(h) * w * c;
  }
};

static Shape3 ToShape(const Json& arr) {
  Shape3 s;
  const auto& v = arr.arr_v;
  if (v.size() == 1) { s.h = 1; s.w = 1; s.c = v[0].integer(); }
  else if (v.size() == 2) { s.h = 1; s.w = v[0].integer(); s.c = v[1].integer(); }
  else if (v.size() == 3) {
    s.h = v[0].integer(); s.w = v[1].integer(); s.c = v[2].integer();
  } else if (!v.empty()) {
    throw std::runtime_error("unsupported shape rank");
  }
  return s;
}

// ------------------------------------------------------------ activations
enum class Act { kLinear, kTanh, kSigmoid, kRelu, kStrictRelu, kLog };

static Act ActOf(const std::string& type) {
  auto ends = [&](const char* suf) {
    size_t n = std::strlen(suf);
    return type.size() >= n && type.compare(type.size() - n, n, suf) == 0;
  };
  if (ends("strict_relu")) return Act::kStrictRelu;
  if (ends("relu")) return Act::kRelu;
  if (ends("tanh")) return Act::kTanh;
  if (ends("sigmoid")) return Act::kSigmoid;
  if (ends("_log")) return Act::kLog;
  return Act::kLinear;
}

static inline float Activate(float v, Act a) {
  switch (a) {
    case Act::kTanh: return 1.7159f * std::tanh(0.6666f * v);
    case Act::kSigmoid: return 1.0f / (1.0f + std::exp(-v));
    case Act::kRelu:  // Veles RELU = softplus
      return v > 20.f ? v : std::log1p(std::exp(v));
    case Act::kStrictRelu: return v > 0.f ? v : 0.f;
    case Act::kLog: return std::asinh(v);
    default: return v;
  }
}

// ------------------------------------------------------------------ unit
struct Unit {
  std::string name, type;
  Shape3 in, out;
  Act act = Act::kLinear;
  NpyArray weights, bias;
  bool has_weights = false, has_bias = false;
  // composite layers (conv_residual_block) and norm affines keep their
  // arrays by semantic name ("gn1/gamma"); int8 scales already folded
  std::map<std::string, NpyArray> extra;
  // layer-specific config
  int kx = 0, ky = 0, sx = 1, sy = 1;
  int pad_t = 0, pad_l = 0, pad_b = 0, pad_r = 0;
  float alpha = 1e-4f, beta = 0.75f, knorm = 2.0f;
  int nwin = 15;
  int off_y = 0, off_x = 0;
  int groups = 32;
  // composite scratch, reused across calls (resize is a no-op at
  // steady batch — no per-inference heap churn).  Same thread-safety
  // contract as the workflow's shared arena: one infer at a time.
  mutable std::vector<float> scratch_[4];

  void Execute(const float* x, float* y, int batch) const;
};

static bool StartsWith(const std::string& s, const char* pre) {
  return s.rfind(pre, 0) == 0;
}

// keep in sync with the branches of Unit::Execute — the loader rejects
// anything else AT LOAD TIME so "unsupported type" surfaces with the
// type name, not as a generic failure at first inference
static bool TypeSupported(const std::string& t) {
  return StartsWith(t, "all2all") || t == "softmax" ||
         t == "conv_residual_block" || t == "group_norm" ||
         StartsWith(t, "conv") || StartsWith(t, "deconv") ||
         t == "depooling" || t == "max_pooling" ||
         t == "avg_pooling" || t == "maxabs_pooling" || t == "norm" ||
         t == "cutter" || t == "dropout" ||
         StartsWith(t, "zerofiller") || StartsWith(t, "activation_");
}

// shared by the conv/deconv unit types and the residual composite
static void Conv2D(const NpyArray& weights, const NpyArray* bias,
                   const float* x, float* y, const Shape3& in,
                   const Shape3& out, int kx, int ky, int sx, int sy,
                   int pad_t, int pad_l, int batch, Act act) {
  int ci = in.c, co = out.c;
  for (int b = 0; b < batch; ++b) {
    const float* xb = x + static_cast<size_t>(b) * in.elems();
    float* yb = y + static_cast<size_t>(b) * out.elems();
    for (int oy = 0; oy < out.h; ++oy)
      for (int ox = 0; ox < out.w; ++ox)
        for (int oc = 0; oc < co; ++oc) {
          float acc = bias ? bias->data[oc] : 0.f;
          for (int fy = 0; fy < ky; ++fy) {
            int iy = oy * sy + fy - pad_t;
            if (iy < 0 || iy >= in.h) continue;
            for (int fx = 0; fx < kx; ++fx) {
              int ix = ox * sx + fx - pad_l;
              if (ix < 0 || ix >= in.w) continue;
              const float* xp =
                  xb + (static_cast<size_t>(iy) * in.w + ix) * ci;
              const float* wp = &weights.data[
                  ((static_cast<size_t>(fy) * kx + fx) * ci) * co + oc];
              for (int icc = 0; icc < ci; ++icc)
                acc += xp[icc] * wp[static_cast<size_t>(icc) * co];
            }
          }
          yb[(static_cast<size_t>(oy) * out.w + ox) * co + oc] =
              Activate(acc, act);
        }
  }
}

// group normalization over [H, W, C]: per-(sample, group) statistics
// across spatial + intra-group channels; effective group count is the
// largest divisor of C <= groups (matches veles_tpu.ops.norm.group_norm,
// biased variance, eps 1e-5)
static void GroupNormForward(const float* x, float* y, const Shape3& s,
                             const NpyArray* gamma, const NpyArray* beta,
                             int groups, int batch) {
  int c = s.c;
  int g = std::max(1, std::min(groups, c));
  while (c % g) --g;
  int cg = c / g;
  size_t hw = static_cast<size_t>(s.h) * s.w;
  for (int b = 0; b < batch; ++b) {
    const float* xb = x + static_cast<size_t>(b) * s.elems();
    float* yb = y + static_cast<size_t>(b) * s.elems();
    for (int gi = 0; gi < g; ++gi) {
      double sum = 0.0, sq = 0.0;
      for (size_t p = 0; p < hw; ++p)
        for (int ic = 0; ic < cg; ++ic) {
          float v = xb[p * c + gi * cg + ic];
          sum += v;
          sq += static_cast<double>(v) * v;
        }
      double n = static_cast<double>(hw) * cg;
      float mean = static_cast<float>(sum / n);
      float var = static_cast<float>(sq / n - (sum / n) * (sum / n));
      float inv = 1.f / std::sqrt(var + 1e-5f);
      for (size_t p = 0; p < hw; ++p)
        for (int ic = 0; ic < cg; ++ic) {
          int ch = gi * cg + ic;
          float v = (xb[p * c + ch] - mean) * inv;
          if (gamma) v *= gamma->data[ch];
          if (beta) v += beta->data[ch];
          yb[p * c + ch] = v;
        }
    }
  }
}

void Unit::Execute(const float* x, float* y, int batch) const {
  if (StartsWith(type, "all2all") || type == "softmax") {
    int ni = static_cast<int>(in.elems()), no = static_cast<int>(out.elems());
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * ni;
      float* yb = y + static_cast<size_t>(b) * no;
      for (int o = 0; o < no; ++o)
        yb[o] = has_bias ? bias.data[o] : 0.f;
      for (int i = 0; i < ni; ++i) {      // i-major: streams W row-wise
        float xv = xb[i];
        const float* wrow = &weights.data[static_cast<size_t>(i) * no];
        for (int o = 0; o < no; ++o) yb[o] += xv * wrow[o];
      }
      for (int o = 0; o < no; ++o) yb[o] = Activate(yb[o], act);
    }
  } else if (type == "conv_residual_block") {
    // pre-activation He v2 residual composite (matches
    // models.layers.ConvResidualBlock): gn→relu→conv3×3(stride) →
    // gn→relu→conv3×3 + skip (1×1 strided projection on shape change).
    // Scratch is local — the arena only plans inter-unit buffers.
    const NpyArray* g1g = &extra.at("gn1/gamma");
    const NpyArray* g1b = &extra.at("gn1/beta");
    const NpyArray* g2g = &extra.at("gn2/gamma");
    const NpyArray* g2b = &extra.at("gn2/beta");
    size_t n_in = in.elems() * batch, n_out = out.elems() * batch;
    std::vector<float>& h1 = scratch_[0];
    std::vector<float>& h2 = scratch_[1];
    std::vector<float>& h3 = scratch_[2];
    h1.resize(n_in);
    h2.resize(n_out);
    h3.resize(n_out);
    GroupNormForward(x, h1.data(), in, g1g, g1b, groups, batch);
    for (size_t i = 0; i < n_in; ++i)
      h1[i] = Activate(h1[i], Act::kStrictRelu);
    auto bias_of = [this](const char* name) -> const NpyArray* {
      auto it = extra.find(name);
      return it == extra.end() ? nullptr : &it->second;
    };
    Conv2D(extra.at("conv1/weights"), bias_of("conv1/bias"), h1.data(),
           h2.data(), in, out, 3, 3, sx, sy, 1, 1, batch,
           Act::kLinear);
    GroupNormForward(h2.data(), h3.data(), out, g2g, g2b, groups,
                     batch);
    for (size_t i = 0; i < n_out; ++i)
      h3[i] = Activate(h3[i], Act::kStrictRelu);
    Conv2D(extra.at("conv2/weights"), bias_of("conv2/bias"), h3.data(),
           y, out, out, 3, 3, 1, 1, 1, 1, batch, Act::kLinear);
    auto proj = extra.find("proj/weights");
    if (proj != extra.end()) {
      std::vector<float>& sk = scratch_[3];
      sk.resize(n_out);
      Conv2D(proj->second, nullptr, x, sk.data(), in, out, 1, 1, sx,
             sy, 0, 0, batch, Act::kLinear);
      for (size_t i = 0; i < n_out; ++i) y[i] += sk[i];
    } else {
      for (size_t i = 0; i < n_out; ++i) y[i] += x[i];
    }
  } else if (type == "group_norm") {
    auto aff = [this](const char* name) -> const NpyArray* {
      auto it = extra.find(name);
      return it == extra.end() ? nullptr : &it->second;
    };
    GroupNormForward(x, y, in, aff("gamma"), aff("beta"), groups,
                     batch);
  } else if (StartsWith(type, "conv")) {
    Conv2D(weights, has_bias ? &bias : nullptr, x, y, in, out, kx, ky,
           sx, sy, pad_t, pad_l, batch, act);
  } else if (StartsWith(type, "deconv")) {
    // transposed conv, gather form over the stride-dilated input
    // (matches lax.conv_transpose VALID: out = (in-1)*s + k)
    int ci = in.c, co = out.c;
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int oy = 0; oy < out.h; ++oy)
        for (int ox = 0; ox < out.w; ++ox)
          for (int oc = 0; oc < co; ++oc) {
            float acc = has_bias ? bias.data[oc] : 0.f;
            for (int fy = 0; fy < ky; ++fy) {
              int ay = oy + fy - (ky - 1);
              if (ay < 0 || ay % sy) continue;
              int iy = ay / sy;
              if (iy >= in.h) continue;
              for (int fx = 0; fx < kx; ++fx) {
                int ax = ox + fx - (kx - 1);
                if (ax < 0 || ax % sx) continue;
                int ix = ax / sx;
                if (ix >= in.w) continue;
                const float* xp =
                    xb + (static_cast<size_t>(iy) * in.w + ix) * ci;
                const float* wp = &weights.data[
                    ((static_cast<size_t>(fy) * kx + fx) * ci) * co + oc];
                for (int icc = 0; icc < ci; ++icc)
                  acc += xp[icc] * wp[static_cast<size_t>(icc) * co];
              }
            }
            yb[(static_cast<size_t>(oy) * out.w + ox) * co + oc] =
                Activate(acc, act);
          }
    }
  } else if (type == "depooling") {
    // nearest-neighbor upsample by the window (decoder half of pooled
    // autoencoders)
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int oy = 0; oy < out.h; ++oy)
        for (int ox = 0; ox < out.w; ++ox)
          std::memcpy(
              yb + (static_cast<size_t>(oy) * out.w + ox) * in.c,
              xb + (static_cast<size_t>(oy / ky) * in.w + ox / kx) * in.c,
              sizeof(float) * in.c);
    }
  } else if (type == "max_pooling" || type == "avg_pooling" ||
             type == "maxabs_pooling") {
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int oy = 0; oy < out.h; ++oy)
        for (int ox = 0; ox < out.w; ++ox)
          for (int cc = 0; cc < in.c; ++cc) {
            float best = 0.f, sum = 0.f;
            bool first = true;
            int cnt = 0;
            for (int fy = 0; fy < ky; ++fy) {
              int iy = oy * sy + fy;
              if (iy >= in.h) continue;
              for (int fx = 0; fx < kx; ++fx) {
                int ix = ox * sx + fx;
                if (ix >= in.w) continue;
                float v = xb[(static_cast<size_t>(iy) * in.w + ix) *
                             in.c + cc];
                sum += v;
                ++cnt;
                if (type == "max_pooling") {
                  if (first || v > best) best = v;
                } else {  // maxabs_pooling
                  if (first || std::fabs(v) > std::fabs(best)) best = v;
                }
                first = false;
              }
            }
            float r = type[0] == 'a' ? (cnt ? sum / cnt : 0.f) : best;
            yb[(static_cast<size_t>(oy) * out.w + ox) * in.c + cc] = r;
          }
    }
  } else if (type == "norm") {  // LRN across channels
    int half = nwin / 2;
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int p = 0; p < in.h * in.w; ++p) {
        const float* xp = xb + static_cast<size_t>(p) * in.c;
        float* yp = yb + static_cast<size_t>(p) * in.c;
        for (int cc = 0; cc < in.c; ++cc) {
          float ssum = 0.f;
          for (int j = std::max(0, cc - half);
               j <= std::min(in.c - 1, cc + half); ++j)
            ssum += xp[j] * xp[j];
          yp[cc] = xp[cc] * std::pow(knorm + alpha * ssum, -beta);
        }
      }
    }
  } else if (type == "cutter") {
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int oy = 0; oy < out.h; ++oy)
        for (int ox = 0; ox < out.w; ++ox)
          std::memcpy(
              yb + (static_cast<size_t>(oy) * out.w + ox) * in.c,
              xb + (static_cast<size_t>(oy + off_y) * in.w + ox + off_x) *
                       in.c,
              sizeof(float) * in.c);
    }
  } else if (type == "dropout" || StartsWith(type, "zerofiller")) {
    std::memcpy(y, x, sizeof(float) * in.elems() * batch);  // inference no-op
  } else if (StartsWith(type, "activation_")) {
    Act a = ActOf(type);
    size_t n = in.elems() * batch;
    for (size_t i = 0; i < n; ++i) y[i] = Activate(x[i], a);
  } else {
    throw std::runtime_error("native runtime: unsupported unit type " +
                             type);
  }
}

// -------------------------------------------------------------- workflow
class Workflow {
 public:
  explicit Workflow(const std::string& path) {
    ZipReader zip(path);
    Json manifest = Json::Parse(zip.read("contents.json"));
    name_ = manifest.at("name").str();
    softmax_output_ = manifest.at("loss").str() == "softmax";
    for (const Json& ju : manifest.at("units").arr_v) {
      Unit u;
      u.name = ju.at("name").str();
      u.type = ju.at("type").str();
      if (!TypeSupported(u.type))
        throw std::runtime_error(
            "native runtime: unsupported unit type " + u.type +
            " (unit " + u.name + ") — package not loadable by the C++ "
            "engine; use the StableHLO export for this model");
      u.in = ToShape(ju.at("input_shape"));
      u.out = ToShape(ju.at("output_shape"));
      u.act = ActOf(u.type);
      const Json& cfg = ju.at("config");
      auto geti = [&](const char* k, int dflt) {
        return cfg.has(k) ? cfg.at(k).integer() : dflt;
      };
      u.kx = geti("kx", 0);
      u.ky = geti("ky", 0);
      if (cfg.has("sliding")) {
        u.sy = cfg.at("sliding").arr_v[0].integer();
        u.sx = cfg.at("sliding").arr_v[1].integer();
      } else if (u.type.find("pooling") != std::string::npos) {
        u.sy = u.ky; u.sx = u.kx;  // pooling stride defaults to the window
      }
      if (cfg.has("padding")) {
        const auto& p = cfg.at("padding").arr_v;
        u.pad_t = p[0].integer(); u.pad_l = p[1].integer();
        u.pad_b = p[2].integer(); u.pad_r = p[3].integer();
      }
      if (cfg.has("alpha")) u.alpha = static_cast<float>(cfg.at("alpha").num());
      if (cfg.has("beta")) u.beta = static_cast<float>(cfg.at("beta").num());
      if (cfg.has("k")) u.knorm = static_cast<float>(cfg.at("k").num());
      if (cfg.has("n")) u.nwin = cfg.at("n").integer();
      if (cfg.has("offset")) {
        u.off_y = cfg.at("offset").arr_v[0].integer();
        u.off_x = cfg.at("offset").arr_v[1].integer();
      }
      if (cfg.has("groups")) u.groups = cfg.at("groups").integer();
      const Json& arrays = ju.at("arrays");
      if (arrays.has("weights")) {
        u.weights = ParseNpy(zip.read(arrays.at("weights").str()));
        if (arrays.has("weights__scales"))   // int8 package: widen
          ApplyChannelScales(
              u.weights,
              ParseNpy(zip.read(arrays.at("weights__scales").str())));
        u.has_weights = true;
      }
      if (arrays.has("bias")) {
        u.bias = ParseNpy(zip.read(arrays.at("bias").str()));
        // forward-compat only: today's exporter keeps 1-D biases f32,
        // so this branch is unexercised until the format quantizes them
        if (arrays.has("bias__scales"))
          ApplyChannelScales(
              u.bias,
              ParseNpy(zip.read(arrays.at("bias__scales").str())));
        u.has_bias = true;
      }
      // everything else (composite sub-arrays like "gn1/gamma", norm
      // affines) lands in the named map, int8 scales folded in
      for (const auto& kv : arrays.obj_v) {
        const std::string& an = kv.first;
        if (an == "weights" || an == "bias") continue;
        if (an.size() >= 8 &&
            an.compare(an.size() - 8, 8, "__scales") == 0)
          continue;
        NpyArray a = ParseNpy(zip.read(kv.second.str()));
        if (arrays.has(an + "__scales"))
          ApplyChannelScales(
              a, ParseNpy(zip.read(arrays.at(an + "__scales").str())));
        u.extra[an] = std::move(a);
      }
      units_.push_back(std::move(u));
    }
    if (units_.empty()) throw std::runtime_error("empty workflow");
  }

  size_t input_elems() const { return units_.front().in.elems(); }
  size_t output_elems() const { return units_.back().out.elems(); }
  size_t arena_bytes() const { return arena_bytes_; }
  const std::vector<Unit>& units() const { return units_; }
  const std::string& name() const { return name_; }

  // Plan the arena for a given batch size (ref MemoryOptimizer::Optimize).
  void Plan(int batch) {
    if (batch == planned_batch_) return;
    blocks_.clear();
    // block i = output buffer of unit i, live from producer i to consumer
    // i+1; block for the network input is the caller's buffer.
    for (size_t i = 0; i < units_.size(); ++i) {
      MemoryBlock blk;
      blk.first_use = static_cast<int>(i);
      blk.last_use = static_cast<int>(i + 1);
      blk.size = units_[i].out.elems() * batch * sizeof(float);
      blocks_.push_back(blk);
    }
    arena_bytes_ = MemoryOptimizer::Optimize(&blocks_);
    arena_.resize(arena_bytes_ / sizeof(float) + 1);
    planned_batch_ = batch;
  }

  void Infer(const float* input, int batch, float* output) {
    Plan(batch);
    const float* x = input;
    for (size_t i = 0; i < units_.size(); ++i) {
      float* y = arena_.data() + blocks_[i].offset / sizeof(float);
      units_[i].Execute(x, y, batch);
      x = y;
    }
    size_t no = output_elems();
    std::memcpy(output, x, sizeof(float) * no * batch);
    if (softmax_output_) {
      for (int b = 0; b < batch; ++b) {
        float* ob = output + static_cast<size_t>(b) * no;
        float mx = ob[0];
        for (size_t j = 1; j < no; ++j) mx = std::max(mx, ob[j]);
        float sum = 0.f;
        for (size_t j = 0; j < no; ++j) {
          ob[j] = std::exp(ob[j] - mx);
          sum += ob[j];
        }
        for (size_t j = 0; j < no; ++j) ob[j] /= sum;
      }
    }
  }

 private:
  std::string name_;
  bool softmax_output_ = false;
  std::vector<Unit> units_;
  std::vector<MemoryBlock> blocks_;
  std::vector<float> arena_;
  size_t arena_bytes_ = 0;
  int planned_batch_ = -1;
};

}  // namespace veles_native

// ------------------------------------------------------------------ C API
extern "C" {

void* veles_native_load(const char* path, char* err, int errlen) {
  try {
    return new veles_native::Workflow(path);
  } catch (const std::exception& e) {
    if (err && errlen > 0) {
      std::strncpy(err, e.what(), errlen - 1);
      err[errlen - 1] = '\0';
    }
    return nullptr;
  }
}

int veles_native_input_size(void* h) {
  return static_cast<int>(
      static_cast<veles_native::Workflow*>(h)->input_elems());
}

int veles_native_output_size(void* h) {
  return static_cast<int>(
      static_cast<veles_native::Workflow*>(h)->output_elems());
}

int veles_native_num_units(void* h) {
  return static_cast<int>(
      static_cast<veles_native::Workflow*>(h)->units().size());
}

const char* veles_native_unit_name(void* h, int i) {
  const auto& units = static_cast<veles_native::Workflow*>(h)->units();
  if (i < 0 || i >= static_cast<int>(units.size())) return "";
  return units[i].name.c_str();
}

long veles_native_arena_bytes(void* h, int batch) {
  auto* wf = static_cast<veles_native::Workflow*>(h);
  wf->Plan(batch);
  return static_cast<long>(wf->arena_bytes());
}

int veles_native_infer(void* h, const float* input, int batch,
                       float* output) {
  try {
    static_cast<veles_native::Workflow*>(h)->Infer(input, batch, output);
    return 0;
  } catch (const std::exception&) {
    return -1;
  }
}

void veles_native_free(void* h) {
  delete static_cast<veles_native::Workflow*>(h);
}

}  // extern "C"
