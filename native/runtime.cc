// Native inference runtime: loads a veles_tpu package (ZIP of
// contents.json + .npy arrays, ref Workflow.package_export
// veles/workflow.py:864-971) and executes the forward pass on CPU.
// Plays the role of the reference's libVeles engine (SURVEY.md §2.10):
// package loader, unit factory, topological execute, arena memory
// optimizer, C API for embedding.
//
// Build: make -C native   (produces libveles_native.so)

#include <algorithm>
#include <cmath>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

#include "json.h"
#include "memory_optimizer.h"
#include "package.h"

namespace veles_native {

struct Shape3 {  // H, W, C (or 1,1,F for flat)
  int h = 1, w = 1, c = 1;
  size_t elems() const {
    return static_cast<size_t>(h) * w * c;
  }
};

static Shape3 ToShape(const Json& arr) {
  Shape3 s;
  const auto& v = arr.arr_v;
  if (v.size() == 1) { s.h = 1; s.w = 1; s.c = v[0].integer(); }
  else if (v.size() == 2) { s.h = 1; s.w = v[0].integer(); s.c = v[1].integer(); }
  else if (v.size() == 3) {
    s.h = v[0].integer(); s.w = v[1].integer(); s.c = v[2].integer();
  } else if (!v.empty()) {
    throw std::runtime_error("unsupported shape rank");
  }
  return s;
}

// ------------------------------------------------------------ activations
enum class Act { kLinear, kTanh, kSigmoid, kRelu, kStrictRelu, kLog };

static Act ActOf(const std::string& type) {
  auto ends = [&](const char* suf) {
    size_t n = std::strlen(suf);
    return type.size() >= n && type.compare(type.size() - n, n, suf) == 0;
  };
  if (ends("strict_relu")) return Act::kStrictRelu;
  if (ends("relu")) return Act::kRelu;
  if (ends("tanh")) return Act::kTanh;
  if (ends("sigmoid")) return Act::kSigmoid;
  if (ends("_log")) return Act::kLog;
  return Act::kLinear;
}

static inline float Activate(float v, Act a) {
  switch (a) {
    case Act::kTanh: return 1.7159f * std::tanh(0.6666f * v);
    case Act::kSigmoid: return 1.0f / (1.0f + std::exp(-v));
    case Act::kRelu:  // Veles RELU = softplus
      return v > 20.f ? v : std::log1p(std::exp(v));
    case Act::kStrictRelu: return v > 0.f ? v : 0.f;
    case Act::kLog: return std::asinh(v);
    default: return v;
  }
}

// ------------------------------------------------------------------ unit
struct Unit {
  std::string name, type;
  Shape3 in, out;
  Act act = Act::kLinear;
  NpyArray weights, bias;
  bool has_weights = false, has_bias = false;
  // composite layers (conv_residual_block) and norm affines keep their
  // arrays by semantic name ("gn1/gamma"); int8 scales already folded
  std::map<std::string, NpyArray> extra;
  // layer-specific config
  int kx = 0, ky = 0, sx = 1, sy = 1;
  int pad_t = 0, pad_l = 0, pad_b = 0, pad_r = 0;
  float alpha = 1e-4f, beta = 0.75f, knorm = 2.0f;
  int nwin = 15;
  int off_y = 0, off_x = 0;
  int groups = 32;
  // transformer family
  int n_heads = 0, n_kv_heads = 0, window = 0;
  bool causal = false, use_rope = false;
  std::string tie_to, pool_mode = "mean";
  const NpyArray* tied_table = nullptr;   // resolved after load
  // composite scratch, reused across calls (resize is a no-op at
  // steady batch — no per-inference heap churn).  Same thread-safety
  // contract as the workflow's shared arena: one infer at a time.
  mutable std::vector<float> scratch_[8];

  void Execute(const float* x, float* y, int batch) const;
  void StepDecode(const float* x_row, float* y_row, float* ck,
                  float* cv, int pos) const;
};

static bool StartsWith(const std::string& s, const char* pre) {
  return s.rfind(pre, 0) == 0;
}

// keep in sync with the branches of Unit::Execute — the loader rejects
// anything else AT LOAD TIME so "unsupported type" surfaces with the
// type name, not as a generic failure at first inference
static bool TypeSupported(const std::string& t) {
  return StartsWith(t, "all2all") || t == "softmax" ||
         t == "conv_residual_block" || t == "group_norm" ||
         StartsWith(t, "conv") || StartsWith(t, "deconv") ||
         t == "depooling" || t == "max_pooling" ||
         t == "avg_pooling" || t == "maxabs_pooling" || t == "norm" ||
         t == "cutter" || t == "dropout" ||
         StartsWith(t, "zerofiller") || StartsWith(t, "activation_") ||
         // transformer family (matches models/layers.py +
         // ops/attention.py math; lora/moe configs are rejected at
         // load with their own messages)
         t == "embedding" || t == "positional_encoding" ||
         t == "transformer_block" || t == "layer_norm" ||
         t == "tied_lm_head" || t == "seq_pool" ||
         StartsWith(t, "timestep_dense");
}

// shared by the conv/deconv unit types and the residual composite
static void Conv2D(const NpyArray& weights, const NpyArray* bias,
                   const float* x, float* y, const Shape3& in,
                   const Shape3& out, int kx, int ky, int sx, int sy,
                   int pad_t, int pad_l, int batch, Act act) {
  int ci = in.c, co = out.c;
  for (int b = 0; b < batch; ++b) {
    const float* xb = x + static_cast<size_t>(b) * in.elems();
    float* yb = y + static_cast<size_t>(b) * out.elems();
    for (int oy = 0; oy < out.h; ++oy)
      for (int ox = 0; ox < out.w; ++ox)
        for (int oc = 0; oc < co; ++oc) {
          float acc = bias ? bias->data[oc] : 0.f;
          for (int fy = 0; fy < ky; ++fy) {
            int iy = oy * sy + fy - pad_t;
            if (iy < 0 || iy >= in.h) continue;
            for (int fx = 0; fx < kx; ++fx) {
              int ix = ox * sx + fx - pad_l;
              if (ix < 0 || ix >= in.w) continue;
              const float* xp =
                  xb + (static_cast<size_t>(iy) * in.w + ix) * ci;
              const float* wp = &weights.data[
                  ((static_cast<size_t>(fy) * kx + fx) * ci) * co + oc];
              for (int icc = 0; icc < ci; ++icc)
                acc += xp[icc] * wp[static_cast<size_t>(icc) * co];
            }
          }
          yb[(static_cast<size_t>(oy) * out.w + ox) * co + oc] =
              Activate(acc, act);
        }
  }
}

// group normalization over [H, W, C]: per-(sample, group) statistics
// across spatial + intra-group channels; effective group count is the
// largest divisor of C <= groups (matches veles_tpu.ops.norm.group_norm,
// biased variance, eps 1e-5)
static void GroupNormForward(const float* x, float* y, const Shape3& s,
                             const NpyArray* gamma, const NpyArray* beta,
                             int groups, int batch) {
  int c = s.c;
  int g = std::max(1, std::min(groups, c));
  while (c % g) --g;
  int cg = c / g;
  size_t hw = static_cast<size_t>(s.h) * s.w;
  for (int b = 0; b < batch; ++b) {
    const float* xb = x + static_cast<size_t>(b) * s.elems();
    float* yb = y + static_cast<size_t>(b) * s.elems();
    for (int gi = 0; gi < g; ++gi) {
      double sum = 0.0, sq = 0.0;
      for (size_t p = 0; p < hw; ++p)
        for (int ic = 0; ic < cg; ++ic) {
          float v = xb[p * c + gi * cg + ic];
          sum += v;
          sq += static_cast<double>(v) * v;
        }
      double n = static_cast<double>(hw) * cg;
      float mean = static_cast<float>(sum / n);
      float var = static_cast<float>(sq / n - (sum / n) * (sum / n));
      float inv = 1.f / std::sqrt(var + 1e-5f);
      for (size_t p = 0; p < hw; ++p)
        for (int ic = 0; ic < cg; ++ic) {
          int ch = gi * cg + ic;
          float v = (xb[p * c + ch] - mean) * inv;
          if (gamma) v *= gamma->data[ch];
          if (beta) v += beta->data[ch];
          yb[p * c + ch] = v;
        }
    }
  }
}

// ------------------------------------------------- transformer helpers
// math mirrors the jit path exactly: ops/norm.py layer_norm (eps 1e-6,
// biased variance), ops/attention.py rope/attention (scale d^-0.5,
// f32 softmax), jax.nn.gelu approximate=True (tanh form).

static void LayerNormRows(const float* x, float* y, int t, int d,
                          const NpyArray* gamma, const NpyArray* beta) {
  for (int r = 0; r < t; ++r) {
    const float* xr = x + static_cast<size_t>(r) * d;
    float* yr = y + static_cast<size_t>(r) * d;
    double sum = 0.0, sq = 0.0;
    for (int i = 0; i < d; ++i) {
      sum += xr[i];
      sq += static_cast<double>(xr[i]) * xr[i];
    }
    float mean = static_cast<float>(sum / d);
    float var = static_cast<float>(sq / d - (sum / d) * (sum / d));
    float inv = 1.f / std::sqrt(var + 1e-6f);
    for (int i = 0; i < d; ++i) {
      float v = (xr[i] - mean) * inv;
      if (gamma) v *= gamma->data[i];
      if (beta) v += beta->data[i];
      yr[i] = v;
    }
  }
}

// [t, din] @ [din, dout] + bias -> [t, dout] (npy row-major weights)
static void DenseRows(const float* x, float* y, int t, int din, int dout,
                      const NpyArray& w, const NpyArray* b) {
  for (int r = 0; r < t; ++r) {
    const float* xr = x + static_cast<size_t>(r) * din;
    float* yr = y + static_cast<size_t>(r) * dout;
    for (int o = 0; o < dout; ++o) yr[o] = b ? b->data[o] : 0.f;
    for (int i = 0; i < din; ++i) {
      float xv = xr[i];
      const float* wrow = &w.data[static_cast<size_t>(i) * dout];
      for (int o = 0; o < dout; ++o) yr[o] += xv * wrow[o];
    }
  }
}

// rope angle table [t, half] interleaved (cos, sin) — the angles
// depend only on (position, i), so compute the transcendentals once
// per Execute instead of per (row, head)
static void RopeTable(std::vector<float>& tab, int t, int dh) {
  int half = dh / 2;
  tab.resize(static_cast<size_t>(t) * half * 2);
  for (int i = 0; i < half; ++i) {
    float freq = std::pow(10000.f, -static_cast<float>(i) / half);
    for (int pos = 0; pos < t; ++pos) {
      float ang = static_cast<float>(pos) * freq;
      tab[(static_cast<size_t>(pos) * half + i) * 2] = std::cos(ang);
      tab[(static_cast<size_t>(pos) * half + i) * 2 + 1] =
          std::sin(ang);
    }
  }
}

// rotate one head-row in place: consecutive (even, odd) pairs
static void RopeRow(float* v, const float* tab_row, int dh) {
  int half = dh / 2;
  for (int i = 0; i < half; ++i) {
    float c = tab_row[2 * i], s = tab_row[2 * i + 1];
    float e = v[2 * i], o = v[2 * i + 1];
    v[2 * i] = e * c - o * s;
    v[2 * i + 1] = e * s + o * c;
  }
}

static inline float GeluTanh(float v) {
  return 0.5f * v *
         (1.f + std::tanh(0.7978845608028654f *
                          (v + 0.044715f * v * v * v)));
}

// One decode step of a causal transformer_block: x_row [d] at
// ``pos``, external k/v cache [t_max, d_kv] rows filled for [0, pos).
// Bit-identical to the full forward restricted to this position: every
// helper iterates rows independently in the same order.
void Unit::StepDecode(const float* x_row, float* y_row, float* ck,
                      float* cv, int pos) const {
  int d = in.c;
  int dh = d / n_heads;
  int d_kv = dh * n_kv_heads;
  int rep = n_heads / n_kv_heads;
  int d_ff = static_cast<int>(extra.at("w1").data.size()) / d;
  auto arr = [this](const char* n) -> const NpyArray& {
    return extra.at(n);
  };
  std::vector<float>& h = scratch_[0];
  std::vector<float>& q = scratch_[1];
  std::vector<float>& att = scratch_[4];
  std::vector<float>& prob = scratch_[5];
  std::vector<float>& ff = scratch_[6];
  h.resize(d);
  q.resize(d);
  att.resize(d);
  prob.resize(pos + 1);
  ff.resize(d_ff);
  float* krow = ck + static_cast<size_t>(pos) * d_kv;
  float* vrow = cv + static_cast<size_t>(pos) * d_kv;
  float scale = 1.f / std::sqrt(static_cast<float>(dh));

  LayerNormRows(x_row, h.data(), 1, d, &arr("ln1/gamma"),
                &arr("ln1/beta"));
  DenseRows(h.data(), q.data(), 1, d, d, arr("mha/wq"), &arr("mha/bq"));
  DenseRows(h.data(), krow, 1, d, d_kv, arr("mha/wk"), &arr("mha/bk"));
  DenseRows(h.data(), vrow, 1, d, d_kv, arr("mha/wv"), &arr("mha/bv"));
  if (use_rope) {
    std::vector<float>& rtab = scratch_[7];
    if (rtab.empty()) RopeTable(rtab, static_cast<int>(out.w), dh);
    const float* trow = &rtab[static_cast<size_t>(pos) * dh];
    for (int hh = 0; hh < n_heads; ++hh)
      RopeRow(&q[static_cast<size_t>(hh) * dh], trow, dh);
    for (int hh = 0; hh < n_kv_heads; ++hh)
      RopeRow(krow + static_cast<size_t>(hh) * dh, trow, dh);
  }
  int lo = 0, hi = pos + 1;
  if (window > 0) lo = std::max(0, pos - window + 1);
  for (int hh = 0; hh < n_heads; ++hh) {
    int kv = hh / rep;
    const float* qr = &q[static_cast<size_t>(hh) * dh];
    float mx = -1e30f;
    for (int c2 = lo; c2 < hi; ++c2) {
      const float* kr = ck + static_cast<size_t>(c2) * d_kv + kv * dh;
      float s = 0.f;
      for (int i = 0; i < dh; ++i) s += qr[i] * kr[i];
      s *= scale;
      prob[c2] = s;
      mx = std::max(mx, s);
    }
    double denom = 0.0;
    for (int c2 = lo; c2 < hi; ++c2) {
      prob[c2] = std::exp(prob[c2] - mx);
      denom += prob[c2];
    }
    float* ar = &att[static_cast<size_t>(hh) * dh];
    for (int i = 0; i < dh; ++i) ar[i] = 0.f;
    for (int c2 = lo; c2 < hi; ++c2) {
      float p = static_cast<float>(prob[c2] / denom);
      const float* vr = cv + static_cast<size_t>(c2) * d_kv + kv * dh;
      for (int i = 0; i < dh; ++i) ar[i] += p * vr[i];
    }
  }
  DenseRows(att.data(), h.data(), 1, d, d, arr("mha/wo"),
            &arr("mha/bo"));
  for (int i = 0; i < d; ++i) h[i] += x_row[i];
  LayerNormRows(h.data(), att.data(), 1, d, &arr("ln2/gamma"),
                &arr("ln2/beta"));
  DenseRows(att.data(), ff.data(), 1, d, d_ff, arr("w1"), &arr("b1"));
  for (int i = 0; i < d_ff; ++i) ff[i] = GeluTanh(ff[i]);
  DenseRows(ff.data(), y_row, 1, d_ff, d, arr("w2"), &arr("b2"));
  for (int i = 0; i < d; ++i) y_row[i] += h[i];
}

void Unit::Execute(const float* x, float* y, int batch) const {
  if (StartsWith(type, "all2all") || type == "softmax") {
    int ni = static_cast<int>(in.elems()), no = static_cast<int>(out.elems());
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * ni;
      float* yb = y + static_cast<size_t>(b) * no;
      for (int o = 0; o < no; ++o)
        yb[o] = has_bias ? bias.data[o] : 0.f;
      for (int i = 0; i < ni; ++i) {      // i-major: streams W row-wise
        float xv = xb[i];
        const float* wrow = &weights.data[static_cast<size_t>(i) * no];
        for (int o = 0; o < no; ++o) yb[o] += xv * wrow[o];
      }
      for (int o = 0; o < no; ++o) yb[o] = Activate(yb[o], act);
    }
  } else if (type == "conv_residual_block") {
    // pre-activation He v2 residual composite (matches
    // models.layers.ConvResidualBlock): gn→relu→conv3×3(stride) →
    // gn→relu→conv3×3 + skip (1×1 strided projection on shape change).
    // Scratch is local — the arena only plans inter-unit buffers.
    const NpyArray* g1g = &extra.at("gn1/gamma");
    const NpyArray* g1b = &extra.at("gn1/beta");
    const NpyArray* g2g = &extra.at("gn2/gamma");
    const NpyArray* g2b = &extra.at("gn2/beta");
    size_t n_in = in.elems() * batch, n_out = out.elems() * batch;
    std::vector<float>& h1 = scratch_[0];
    std::vector<float>& h2 = scratch_[1];
    std::vector<float>& h3 = scratch_[2];
    h1.resize(n_in);
    h2.resize(n_out);
    h3.resize(n_out);
    GroupNormForward(x, h1.data(), in, g1g, g1b, groups, batch);
    for (size_t i = 0; i < n_in; ++i)
      h1[i] = Activate(h1[i], Act::kStrictRelu);
    auto bias_of = [this](const char* name) -> const NpyArray* {
      auto it = extra.find(name);
      return it == extra.end() ? nullptr : &it->second;
    };
    Conv2D(extra.at("conv1/weights"), bias_of("conv1/bias"), h1.data(),
           h2.data(), in, out, 3, 3, sx, sy, 1, 1, batch,
           Act::kLinear);
    GroupNormForward(h2.data(), h3.data(), out, g2g, g2b, groups,
                     batch);
    for (size_t i = 0; i < n_out; ++i)
      h3[i] = Activate(h3[i], Act::kStrictRelu);
    Conv2D(extra.at("conv2/weights"), bias_of("conv2/bias"), h3.data(),
           y, out, out, 3, 3, 1, 1, 1, 1, batch, Act::kLinear);
    auto proj = extra.find("proj/weights");
    if (proj != extra.end()) {
      std::vector<float>& sk = scratch_[3];
      sk.resize(n_out);
      Conv2D(proj->second, nullptr, x, sk.data(), in, out, 1, 1, sx,
             sy, 0, 0, batch, Act::kLinear);
      for (size_t i = 0; i < n_out; ++i) y[i] += sk[i];
    } else {
      for (size_t i = 0; i < n_out; ++i) y[i] += x[i];
    }
  } else if (type == "group_norm") {
    auto aff = [this](const char* name) -> const NpyArray* {
      auto it = extra.find(name);
      return it == extra.end() ? nullptr : &it->second;
    };
    GroupNormForward(x, y, in, aff("gamma"), aff("beta"), groups,
                     batch);
  } else if (StartsWith(type, "conv")) {
    Conv2D(weights, has_bias ? &bias : nullptr, x, y, in, out, kx, ky,
           sx, sy, pad_t, pad_l, batch, act);
  } else if (StartsWith(type, "deconv")) {
    // transposed conv, gather form over the stride-dilated input
    // (matches lax.conv_transpose VALID: out = (in-1)*s + k)
    int ci = in.c, co = out.c;
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int oy = 0; oy < out.h; ++oy)
        for (int ox = 0; ox < out.w; ++ox)
          for (int oc = 0; oc < co; ++oc) {
            float acc = has_bias ? bias.data[oc] : 0.f;
            for (int fy = 0; fy < ky; ++fy) {
              int ay = oy + fy - (ky - 1);
              if (ay < 0 || ay % sy) continue;
              int iy = ay / sy;
              if (iy >= in.h) continue;
              for (int fx = 0; fx < kx; ++fx) {
                int ax = ox + fx - (kx - 1);
                if (ax < 0 || ax % sx) continue;
                int ix = ax / sx;
                if (ix >= in.w) continue;
                const float* xp =
                    xb + (static_cast<size_t>(iy) * in.w + ix) * ci;
                const float* wp = &weights.data[
                    ((static_cast<size_t>(fy) * kx + fx) * ci) * co + oc];
                for (int icc = 0; icc < ci; ++icc)
                  acc += xp[icc] * wp[static_cast<size_t>(icc) * co];
              }
            }
            yb[(static_cast<size_t>(oy) * out.w + ox) * co + oc] =
                Activate(acc, act);
          }
    }
  } else if (type == "depooling") {
    // nearest-neighbor upsample by the window (decoder half of pooled
    // autoencoders)
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int oy = 0; oy < out.h; ++oy)
        for (int ox = 0; ox < out.w; ++ox)
          std::memcpy(
              yb + (static_cast<size_t>(oy) * out.w + ox) * in.c,
              xb + (static_cast<size_t>(oy / ky) * in.w + ox / kx) * in.c,
              sizeof(float) * in.c);
    }
  } else if (type == "max_pooling" || type == "avg_pooling" ||
             type == "maxabs_pooling") {
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int oy = 0; oy < out.h; ++oy)
        for (int ox = 0; ox < out.w; ++ox)
          for (int cc = 0; cc < in.c; ++cc) {
            float best = 0.f, sum = 0.f;
            bool first = true;
            int cnt = 0;
            for (int fy = 0; fy < ky; ++fy) {
              int iy = oy * sy + fy;
              if (iy >= in.h) continue;
              for (int fx = 0; fx < kx; ++fx) {
                int ix = ox * sx + fx;
                if (ix >= in.w) continue;
                float v = xb[(static_cast<size_t>(iy) * in.w + ix) *
                             in.c + cc];
                sum += v;
                ++cnt;
                if (type == "max_pooling") {
                  if (first || v > best) best = v;
                } else {  // maxabs_pooling
                  if (first || std::fabs(v) > std::fabs(best)) best = v;
                }
                first = false;
              }
            }
            float r = type[0] == 'a' ? (cnt ? sum / cnt : 0.f) : best;
            yb[(static_cast<size_t>(oy) * out.w + ox) * in.c + cc] = r;
          }
    }
  } else if (type == "norm") {  // LRN across channels
    int half = nwin / 2;
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int p = 0; p < in.h * in.w; ++p) {
        const float* xp = xb + static_cast<size_t>(p) * in.c;
        float* yp = yb + static_cast<size_t>(p) * in.c;
        for (int cc = 0; cc < in.c; ++cc) {
          float ssum = 0.f;
          for (int j = std::max(0, cc - half);
               j <= std::min(in.c - 1, cc + half); ++j)
            ssum += xp[j] * xp[j];
          yp[cc] = xp[cc] * std::pow(knorm + alpha * ssum, -beta);
        }
      }
    }
  } else if (type == "cutter") {
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int oy = 0; oy < out.h; ++oy)
        for (int ox = 0; ox < out.w; ++ox)
          std::memcpy(
              yb + (static_cast<size_t>(oy) * out.w + ox) * in.c,
              xb + (static_cast<size_t>(oy + off_y) * in.w + ox + off_x) *
                       in.c,
              sizeof(float) * in.c);
    }
  } else if (type == "dropout" || StartsWith(type, "zerofiller")) {
    std::memcpy(y, x, sizeof(float) * in.elems() * batch);  // inference no-op
  } else if (StartsWith(type, "activation_")) {
    Act a = ActOf(type);
    size_t n = in.elems() * batch;
    for (size_t i = 0; i < n; ++i) y[i] = Activate(x[i], a);
  } else if (type == "embedding") {
    // int tokens arrive as f32 values through the C ABI: round to index
    const NpyArray& table = extra.at("table");
    int t = static_cast<int>(in.elems()), d = out.c;
    int vocab = static_cast<int>(table.data.size()) / d;
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * t;
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int r = 0; r < t; ++r) {
        long tok = std::lround(xb[r]);
        if (tok < 0 || tok >= vocab)
          throw std::runtime_error("embedding: token out of range");
        std::memcpy(yb + static_cast<size_t>(r) * d,
                    &table.data[static_cast<size_t>(tok) * d],
                    sizeof(float) * d);
      }
    }
  } else if (type == "positional_encoding") {
    int t = in.w, d = in.c;
    auto learned = extra.find("pos");
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * in.elems();
      for (int r = 0; r < t; ++r)
        for (int i = 0; i < d; ++i) {
          float pe;
          if (learned != extra.end()) {
            pe = learned->second.data[static_cast<size_t>(r) * d + i];
          } else {                      // fixed sinusoid (layers.py)
            float ang = r / std::pow(
                10000.f, static_cast<float>(2 * (i / 2)) / d);
            pe = (i % 2 == 0) ? std::sin(ang) : std::cos(ang);
          }
          yb[static_cast<size_t>(r) * d + i] =
              xb[static_cast<size_t>(r) * d + i] + pe;
        }
    }
  } else if (type == "layer_norm") {
    auto aff = [this](const char* n) -> const NpyArray* {
      auto it = extra.find(n);
      return it == extra.end() ? nullptr : &it->second;
    };
    for (int b = 0; b < batch; ++b)
      LayerNormRows(x + static_cast<size_t>(b) * in.elems(),
                    y + static_cast<size_t>(b) * in.elems(),
                    in.w, in.c, aff("gamma"), aff("beta"));
  } else if (StartsWith(type, "timestep_dense")) {
    for (int b = 0; b < batch; ++b) {
      float* yb = y + static_cast<size_t>(b) * out.elems();
      DenseRows(x + static_cast<size_t>(b) * in.elems(), yb, in.w,
                in.c, out.c, weights, has_bias ? &bias : nullptr);
      for (size_t i = 0; i < out.elems(); ++i)
        yb[i] = Activate(yb[i], act);
    }
  } else if (type == "seq_pool") {
    int t = in.w, d = in.c;
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * d;
      for (int i = 0; i < d; ++i) {
        if (pool_mode == "mean") {
          double s = 0.0;
          for (int r = 0; r < t; ++r)
            s += xb[static_cast<size_t>(r) * d + i];
          yb[i] = static_cast<float>(s / t);
        } else if (pool_mode == "max") {
          float m = xb[i];
          for (int r = 1; r < t; ++r)
            m = std::max(m, xb[static_cast<size_t>(r) * d + i]);
          yb[i] = m;
        } else {        // layers.py SeqPool: everything else = last
          yb[i] = xb[static_cast<size_t>(t - 1) * d + i];
        }
      }
    }
  } else if (type == "tied_lm_head") {
    // logits = h @ tableᵀ (layers.py TiedLMHead; table resolved to the
    // tie_to unit's embedding array at load)
    const NpyArray& table = *tied_table;
    int t = in.w, d = in.c, vocab = out.c;
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * out.elems();
      for (int r = 0; r < t; ++r) {
        const float* hr = xb + static_cast<size_t>(r) * d;
        for (int v = 0; v < vocab; ++v) {
          const float* tv = &table.data[static_cast<size_t>(v) * d];
          float acc = 0.f;
          for (int i = 0; i < d; ++i) acc += hr[i] * tv[i];
          yb[static_cast<size_t>(r) * vocab + v] = acc;
        }
      }
    }
  } else if (type == "transformer_block") {
    // pre-LN block (layers.py TransformerBlock.apply):
    // LN→MHA(+rope, causal/window, GQA)→residual, LN→gelu-MLP→residual
    int t = in.w, d = in.c;
    int dh = d / n_heads;
    int d_kv = dh * n_kv_heads;
    int rep = n_heads / n_kv_heads;
    int d_ff = static_cast<int>(extra.at("w1").data.size()) / d;
    auto arr = [this](const char* n) -> const NpyArray& {
      return extra.at(n);
    };
    std::vector<float>& h = scratch_[0];    // normed input [t, d]
    std::vector<float>& q = scratch_[1];    // [t, d]
    std::vector<float>& k = scratch_[2];    // [t, d_kv]
    std::vector<float>& v = scratch_[3];    // [t, d_kv]
    std::vector<float>& att = scratch_[4];  // merged attn out [t, d]
    std::vector<float>& prob = scratch_[5]; // one score row [t]
    std::vector<float>& ff = scratch_[6];   // [t, d_ff]
    h.resize(static_cast<size_t>(t) * d);
    q.resize(static_cast<size_t>(t) * d);
    k.resize(static_cast<size_t>(t) * d_kv);
    v.resize(static_cast<size_t>(t) * d_kv);
    att.resize(static_cast<size_t>(t) * d);
    prob.resize(t);
    ff.resize(static_cast<size_t>(t) * d_ff);
    float scale = 1.f / std::sqrt(static_cast<float>(dh));
    for (int b = 0; b < batch; ++b) {
      const float* xb = x + static_cast<size_t>(b) * in.elems();
      float* yb = y + static_cast<size_t>(b) * in.elems();
      LayerNormRows(xb, h.data(), t, d, &arr("ln1/gamma"),
                    &arr("ln1/beta"));
      DenseRows(h.data(), q.data(), t, d, d, arr("mha/wq"),
                &arr("mha/bq"));
      DenseRows(h.data(), k.data(), t, d, d_kv, arr("mha/wk"),
                &arr("mha/bk"));
      DenseRows(h.data(), v.data(), t, d, d_kv, arr("mha/wv"),
                &arr("mha/bv"));
      if (use_rope) {
        std::vector<float>& rtab = scratch_[7];
        if (rtab.empty()) RopeTable(rtab, t, dh);
        for (int r = 0; r < t; ++r) {
          const float* row = &rtab[static_cast<size_t>(r) * dh];
          for (int hh = 0; hh < n_heads; ++hh)
            RopeRow(&q[static_cast<size_t>(r) * d + hh * dh], row, dh);
          for (int hh = 0; hh < n_kv_heads; ++hh)
            RopeRow(&k[static_cast<size_t>(r) * d_kv + hh * dh], row,
                    dh);
        }
      }
      // per query head: scores → f32 softmax → weighted V
      for (int hh = 0; hh < n_heads; ++hh) {
        int kv = hh / rep;
        for (int r = 0; r < t; ++r) {
          const float* qr = &q[static_cast<size_t>(r) * d + hh * dh];
          int lo = 0, hi = t;                 // attended key range
          if (causal) hi = r + 1;
          if (window > 0 && causal) lo = std::max(0, r - window + 1);
          float mx = -1e30f;
          for (int c2 = lo; c2 < hi; ++c2) {
            const float* kr =
                &k[static_cast<size_t>(c2) * d_kv + kv * dh];
            float s = 0.f;
            for (int i = 0; i < dh; ++i) s += qr[i] * kr[i];
            s *= scale;
            prob[c2] = s;
            mx = std::max(mx, s);
          }
          double denom = 0.0;
          for (int c2 = lo; c2 < hi; ++c2) {
            prob[c2] = std::exp(prob[c2] - mx);
            denom += prob[c2];
          }
          float* ar = &att[static_cast<size_t>(r) * d + hh * dh];
          for (int i = 0; i < dh; ++i) ar[i] = 0.f;
          for (int c2 = lo; c2 < hi; ++c2) {
            float p = static_cast<float>(prob[c2] / denom);
            const float* vr =
                &v[static_cast<size_t>(c2) * d_kv + kv * dh];
            for (int i = 0; i < dh; ++i) ar[i] += p * vr[i];
          }
        }
      }
      // wo projection + residual (reuse h as the o-proj output)
      DenseRows(att.data(), h.data(), t, d, d, arr("mha/wo"),
                &arr("mha/bo"));
      for (size_t i = 0; i < static_cast<size_t>(t) * d; ++i)
        h[i] += xb[i];
      // MLP branch on the residual stream (att reused as ln2 output)
      LayerNormRows(h.data(), att.data(), t, d, &arr("ln2/gamma"),
                    &arr("ln2/beta"));
      DenseRows(att.data(), ff.data(), t, d, d_ff, arr("w1"),
                &arr("b1"));
      for (size_t i = 0; i < static_cast<size_t>(t) * d_ff; ++i)
        ff[i] = GeluTanh(ff[i]);
      DenseRows(ff.data(), yb, t, d_ff, d, arr("w2"), &arr("b2"));
      for (size_t i = 0; i < static_cast<size_t>(t) * d; ++i)
        yb[i] += h[i];
    }
  } else {
    throw std::runtime_error("native runtime: unsupported unit type " +
                             type);
  }
}

// -------------------------------------------------------------- workflow
class Workflow {
 public:
  explicit Workflow(const std::string& path) {
    ZipReader zip(path);
    Json manifest = Json::Parse(zip.read("contents.json"));
    name_ = manifest.at("name").str();
    // class-kind losses serve PROBABILITIES (trainer.forward_fn
    // applies softmax over the last axis — ops/losses.py kind="class";
    // regression losses like mse serve raw outputs).  New packages
    // carry the kind explicitly; the name allowlist keeps old
    // packages loading.
    if (manifest.has("loss_kind")) {
      softmax_output_ = manifest.at("loss_kind").str() == "class";
    } else {
      const std::string& loss = manifest.at("loss").str();
      softmax_output_ = loss == "softmax" || loss == "lm";
    }
    for (const Json& ju : manifest.at("units").arr_v) {
      Unit u;
      u.name = ju.at("name").str();
      u.type = ju.at("type").str();
      if (!TypeSupported(u.type))
        throw std::runtime_error(
            "native runtime: unsupported unit type " + u.type +
            " (unit " + u.name + ") — package not loadable by the C++ "
            "engine; use the StableHLO export for this model");
      u.in = ToShape(ju.at("input_shape"));
      u.out = ToShape(ju.at("output_shape"));
      u.act = ActOf(u.type);
      const Json& cfg = ju.at("config");
      auto geti = [&](const char* k, int dflt) {
        return cfg.has(k) ? cfg.at(k).integer() : dflt;
      };
      u.kx = geti("kx", 0);
      u.ky = geti("ky", 0);
      if (cfg.has("sliding")) {
        u.sy = cfg.at("sliding").arr_v[0].integer();
        u.sx = cfg.at("sliding").arr_v[1].integer();
      } else if (u.type.find("pooling") != std::string::npos) {
        u.sy = u.ky; u.sx = u.kx;  // pooling stride defaults to the window
      }
      if (cfg.has("padding")) {
        const auto& p = cfg.at("padding").arr_v;
        u.pad_t = p[0].integer(); u.pad_l = p[1].integer();
        u.pad_b = p[2].integer(); u.pad_r = p[3].integer();
      }
      if (cfg.has("alpha")) u.alpha = static_cast<float>(cfg.at("alpha").num());
      if (cfg.has("beta")) u.beta = static_cast<float>(cfg.at("beta").num());
      if (cfg.has("k")) u.knorm = static_cast<float>(cfg.at("k").num());
      if (cfg.has("n")) u.nwin = cfg.at("n").integer();
      if (cfg.has("offset")) {
        u.off_y = cfg.at("offset").arr_v[0].integer();
        u.off_x = cfg.at("offset").arr_v[1].integer();
      }
      if (cfg.has("groups")) u.groups = cfg.at("groups").integer();
      // transformer family config
      if (cfg.has("n_heads")) u.n_heads = cfg.at("n_heads").integer();
      u.n_kv_heads = cfg.has("n_kv_heads")
                         ? cfg.at("n_kv_heads").integer() : u.n_heads;
      if (cfg.has("causal")) u.causal = cfg.at("causal").bool_v;
      if (cfg.has("rope")) u.use_rope = cfg.at("rope").bool_v;
      if (cfg.has("window") && cfg.at("window").type == Json::kNumber)
        u.window = cfg.at("window").integer();
      if (cfg.has("tie_to")) u.tie_to = cfg.at("tie_to").str();
      if (cfg.has("mode")) u.pool_mode = cfg.at("mode").str();
      if (u.type == "transformer_block") {
        if (u.n_heads <= 0) u.n_heads = 8;     // layers.py default
        if (u.n_kv_heads <= 0) u.n_kv_heads = u.n_heads;
        if (u.in.c % u.n_heads || u.n_heads % u.n_kv_heads)
          throw std::runtime_error(
              "native runtime: bad head config for unit " + u.name);
        if (cfg.has("n_experts") && cfg.at("n_experts").integer() > 0)
          throw std::runtime_error(
              "native runtime: transformer_block with MoE experts is "
              "not supported (unit " + u.name + ") — use the StableHLO "
              "export for this model");
        for (const auto& kv : ju.at("arrays").obj_v)
          if (kv.first.rfind("mha/lora", 0) == 0)
            throw std::runtime_error(
                "native runtime: un-merged LoRA adapters are not "
                "supported (unit " + u.name + ") — merge adapters at "
                "export or use the StableHLO export");
      }
      const Json& arrays = ju.at("arrays");
      if (arrays.has("weights")) {
        u.weights = ParseNpy(zip.read(arrays.at("weights").str()));
        if (arrays.has("weights__scales"))   // int8 package: widen
          ApplyChannelScales(
              u.weights,
              ParseNpy(zip.read(arrays.at("weights__scales").str())));
        u.has_weights = true;
      }
      if (arrays.has("bias")) {
        u.bias = ParseNpy(zip.read(arrays.at("bias").str()));
        // forward-compat only: today's exporter keeps 1-D biases f32,
        // so this branch is unexercised until the format quantizes them
        if (arrays.has("bias__scales"))
          ApplyChannelScales(
              u.bias,
              ParseNpy(zip.read(arrays.at("bias__scales").str())));
        u.has_bias = true;
      }
      // everything else (composite sub-arrays like "gn1/gamma", norm
      // affines) lands in the named map, int8 scales folded in
      for (const auto& kv : arrays.obj_v) {
        const std::string& an = kv.first;
        if (an == "weights" || an == "bias") continue;
        if (an.size() >= 8 &&
            an.compare(an.size() - 8, 8, "__scales") == 0)
          continue;
        NpyArray a = ParseNpy(zip.read(kv.second.str()));
        if (arrays.has(an + "__scales"))
          ApplyChannelScales(
              a, ParseNpy(zip.read(arrays.at(an + "__scales").str())));
        u.extra[an] = std::move(a);
      }
      units_.push_back(std::move(u));
    }
    if (units_.empty()) throw std::runtime_error("empty workflow");
    // resolve tied heads to their source unit's table (addresses into
    // extra maps stay stable once the vector stops growing)
    for (Unit& tu : units_) {
      if (tu.tie_to.empty()) continue;
      for (const Unit& src : units_)
        if (src.name == tu.tie_to) {
          auto it = src.extra.find("table");
          if (it == src.extra.end())
            throw std::runtime_error(
                "tied_lm_head: tie_to unit " + tu.tie_to +
                " carries no table");
          if (it->second.data.size() !=
              static_cast<size_t>(tu.out.c) * tu.in.c)
            throw std::runtime_error(
                "tied_lm_head: table shape does not match head "
                "(unit " + tu.name + ")");
          tu.tied_table = &it->second;
          break;
        }
      if (!tu.tied_table)
        throw std::runtime_error(
            "tied_lm_head: tie_to unit not found: " + tu.tie_to);
    }
  }

  size_t input_elems() const { return units_.front().in.elems(); }
  size_t output_elems() const { return units_.back().out.elems(); }
  size_t arena_bytes() const { return arena_bytes_; }
  const std::vector<Unit>& units() const { return units_; }
  const std::string& name() const { return name_; }

  // Plan the arena for a given batch size (ref MemoryOptimizer::Optimize).
  void Plan(int batch) {
    if (batch == planned_batch_) return;
    blocks_.clear();
    // block i = output buffer of unit i, live from producer i to consumer
    // i+1; block for the network input is the caller's buffer.
    for (size_t i = 0; i < units_.size(); ++i) {
      MemoryBlock blk;
      blk.first_use = static_cast<int>(i);
      blk.last_use = static_cast<int>(i + 1);
      blk.size = units_[i].out.elems() * batch * sizeof(float);
      blocks_.push_back(blk);
    }
    arena_bytes_ = MemoryOptimizer::Optimize(&blocks_);
    arena_.resize(arena_bytes_ / sizeof(float) + 1);
    planned_batch_ = batch;
  }

  void Infer(const float* input, int batch, float* output) {
    Plan(batch);
    const float* x = input;
    for (size_t i = 0; i < units_.size(); ++i) {
      float* y = arena_.data() + blocks_[i].offset / sizeof(float);
      units_[i].Execute(x, y, batch);
      x = y;
    }
    size_t no = output_elems();
    std::memcpy(output, x, sizeof(float) * no * batch);
    if (softmax_output_) {
      // softmax over the LAST axis of the final unit ([V] classifier
      // row = one group; [T, V] per-position LM logits = T groups)
      size_t width = static_cast<size_t>(units_.back().out.c);
      for (int b = 0; b < batch; ++b) {
        float* ob = output + static_cast<size_t>(b) * no;
        for (size_t r = 0; r < no; r += width) {
          float* row = ob + r;
          float mx = row[0];
          for (size_t j = 1; j < width; ++j) mx = std::max(mx, row[j]);
          float sum = 0.f;
          for (size_t j = 0; j < width; ++j) {
            row[j] = std::exp(row[j] - mx);
            sum += row[j];
          }
          for (size_t j = 0; j < width; ++j) row[j] /= sum;
        }
      }
    }
  }

  // Greedy decode without Python: tokens <- argmax of the last live
  // position, re-running the full forward per step.  EXACT because
  // every attention block is causal — the zero-padded tail beyond
  // ``cur`` cannot influence positions <= cur — at O(T^2) compute per
  // token (the package's shapes are baked at export, so T is the
  // context ceiling; a KV-cached step function is future work).
  // Returns the total token count written to ``out``
  // (prompt + generated, capped at the exported T).
  int Generate(const int* prompt, int n_prompt, int max_new, int* out) {
    return GenerateSampled(prompt, n_prompt, max_new, 0.f, 0, 0, out);
  }

  // temperature <= 0: greedy argmax (the token-exact parity path vs
  // the Python decoder).  temperature > 0: softmax sampling at that
  // temperature, optionally truncated to the top_k best tokens, with
  // a xorshift64* stream seeded from ``seed`` — deliberately NOT
  // jax's threefry, so sampled streams differ from the Python
  // sampler by design (documented; top_k=1 collapses to greedy and
  // is cross-checked against it in the tests).
  int GenerateSampled(const int* prompt, int n_prompt, int max_new,
                      float temperature, int top_k,
                      unsigned long long seed, int* out) {
    rng_ = seed ? seed : 0x9E3779B97F4A7C15ULL;
    int t_max = static_cast<int>(input_elems());
    if (n_prompt < 1 || n_prompt > t_max)
      throw std::runtime_error("generate: bad prompt length");
    if (max_new < 0)
      throw std::runtime_error("generate: max_new must be >= 0");
    if (units_.front().type != "embedding")
      throw std::runtime_error(
          "generate: package must start with an embedding unit");
    // the pad-tail-is-inert invariant holds only for causal attention
    // plus strictly PER-POSITION units — whitelist, don't blacklist
    // (a group_norm or conv would mix the time axis and silently
    // corrupt the decode)
    for (const Unit& u : units_) {
      bool ok = u.type == "embedding" ||
                u.type == "positional_encoding" ||
                u.type == "layer_norm" || u.type == "tied_lm_head" ||
                u.type == "dropout" ||
                StartsWith(u.type, "timestep_dense") ||
                StartsWith(u.type, "zerofiller") ||
                StartsWith(u.type, "activation_") ||
                (u.type == "transformer_block" && u.causal);
      if (!ok)
        throw std::runtime_error(
            "generate: unit " + u.name + " (" + u.type +
            ") is not per-position/causal — the padded-tail decode "
            "would be wrong");
    }
    int total = std::min(t_max, n_prompt + max_new);
    int vocab = units_.back().out.c;
    if (output_elems() != static_cast<size_t>(t_max) * vocab)
      throw std::runtime_error(
          "generate: package head is not per-position [T, V] logits");
    // O(T) per token: every unit in the whitelist is per-position, so
    // positions stream through once with per-block k/v caches — the
    // helpers iterate rows independently in the same order as the full
    // forward, so the decode is bit-identical to re-running it.
    std::vector<std::vector<float>> cks(units_.size()),
        cvs(units_.size());
    for (size_t i = 0; i < units_.size(); ++i)
      if (units_[i].type == "transformer_block") {
        const Unit& u = units_[i];
        int d_kv = (u.in.c / u.n_heads) * u.n_kv_heads;
        cks[i].assign(static_cast<size_t>(t_max) * d_kv, 0.f);
        cvs[i].assign(static_cast<size_t>(t_max) * d_kv, 0.f);
      }
    const NpyArray& table = units_.front().extra.at("table");
    int d0 = units_.front().out.c;
    int vocab_in = static_cast<int>(table.data.size()) / d0;
    std::vector<float> a, b;
    for (int i = 0; i < n_prompt; ++i) out[i] = prompt[i];
    for (int pos = 0; pos < total; ++pos) {
      int tok = out[pos];
      if (tok < 0 || tok >= vocab_in)
        throw std::runtime_error("generate: token out of range");
      a.assign(&table.data[static_cast<size_t>(tok) * d0],
               &table.data[static_cast<size_t>(tok) * d0] + d0);
      for (size_t i = 1; i < units_.size(); ++i) {
        const Unit& u = units_[i];
        if (u.type == "transformer_block") {
          b.resize(u.in.c);
          u.StepDecode(a.data(), b.data(), cks[i].data(),
                       cvs[i].data(), pos);
          a.swap(b);
        } else if (u.type == "positional_encoding") {
          int d = u.in.c;
          auto learned = u.extra.find("pos");
          for (int j = 0; j < d; ++j) {
            float pe;
            if (learned != u.extra.end()) {
              pe = learned->second.data[
                  static_cast<size_t>(pos) * d + j];
            } else {
              float ang = pos / std::pow(
                  10000.f, static_cast<float>(2 * (j / 2)) / d);
              pe = (j % 2 == 0) ? std::sin(ang) : std::cos(ang);
            }
            a[j] += pe;
          }
        } else if (u.type == "layer_norm") {
          auto aff = [&u](const char* n) -> const NpyArray* {
            auto it = u.extra.find(n);
            return it == u.extra.end() ? nullptr : &it->second;
          };
          b.resize(u.in.c);
          LayerNormRows(a.data(), b.data(), 1, u.in.c,
                        aff("gamma"), aff("beta"));
          a.swap(b);
        } else if (StartsWith(u.type, "timestep_dense")) {
          b.resize(u.out.c);
          DenseRows(a.data(), b.data(), 1, u.in.c, u.out.c,
                    u.weights, u.has_bias ? &u.bias : nullptr);
          for (int j = 0; j < u.out.c; ++j)
            b[j] = Activate(b[j], u.act);
          a.swap(b);
        } else if (u.type == "tied_lm_head") {
          int d = u.in.c;
          b.resize(vocab);
          for (int v = 0; v < vocab; ++v) {
            const float* tv =
                &u.tied_table->data[static_cast<size_t>(v) * d];
            float acc = 0.f;
            for (int j = 0; j < d; ++j) acc += a[j] * tv[j];
            b[v] = acc;
          }
          a.swap(b);
        } else if (StartsWith(u.type, "activation_")) {
          for (float& v : a) v = Activate(v, u.act);
        }
        // dropout / zerofiller: inference no-ops, row passes through
      }
      int next = pos + 1;
      if (next >= n_prompt && next < total) {
        int pick;
        if (temperature <= 0.f || top_k == 1) {
          pick = 0;        // argmax over raw logits == over softmax
          for (int v = 1; v < vocab; ++v)
            if (a[v] > a[pick]) pick = v;
        } else {
          // softmax(logits / temperature), optionally top-k-truncated
          std::vector<float> p(a.begin(), a.begin() + vocab);
          if (top_k > 0 && top_k < vocab) {
            std::vector<float> sorted(p);
            std::nth_element(sorted.begin(),
                             sorted.begin() + (top_k - 1),
                             sorted.end(), std::greater<float>());
            float cut = sorted[top_k - 1];
            for (float& v : p)
              if (v < cut) v = -1e30f;
          }
          float mx = *std::max_element(p.begin(), p.end());
          double denom = 0.0;
          for (float& v : p) {
            v = std::exp((v - mx) / temperature);
            denom += v;
          }
          // xorshift64* advance (never zero-seeded)
          rng_ ^= rng_ << 13;
          rng_ ^= rng_ >> 7;
          rng_ ^= rng_ << 17;
          double u = static_cast<double>(
              rng_ * 2685821657736338717ULL >> 11) /
              static_cast<double>(1ULL << 53);
          double acc = 0.0;
          pick = vocab - 1;
          for (int v = 0; v < vocab; ++v) {
            acc += p[v] / denom;
            if (u < acc) { pick = v; break; }
          }
        }
        out[next] = pick;
      }
    }
    return total;
  }

 private:
  std::string name_;
  bool softmax_output_ = false;
  unsigned long long rng_ = 0x9E3779B97F4A7C15ULL;
  std::vector<Unit> units_;
  std::vector<MemoryBlock> blocks_;
  std::vector<float> arena_;
  size_t arena_bytes_ = 0;
  int planned_batch_ = -1;
};

}  // namespace veles_native

// ------------------------------------------------------------------ C API
extern "C" {

void* veles_native_load(const char* path, char* err, int errlen) {
  try {
    return new veles_native::Workflow(path);
  } catch (const std::exception& e) {
    if (err && errlen > 0) {
      std::strncpy(err, e.what(), errlen - 1);
      err[errlen - 1] = '\0';
    }
    return nullptr;
  }
}

int veles_native_input_size(void* h) {
  return static_cast<int>(
      static_cast<veles_native::Workflow*>(h)->input_elems());
}

int veles_native_output_size(void* h) {
  return static_cast<int>(
      static_cast<veles_native::Workflow*>(h)->output_elems());
}

int veles_native_num_units(void* h) {
  return static_cast<int>(
      static_cast<veles_native::Workflow*>(h)->units().size());
}

const char* veles_native_unit_name(void* h, int i) {
  const auto& units = static_cast<veles_native::Workflow*>(h)->units();
  if (i < 0 || i >= static_cast<int>(units.size())) return "";
  return units[i].name.c_str();
}

long veles_native_arena_bytes(void* h, int batch) {
  auto* wf = static_cast<veles_native::Workflow*>(h);
  wf->Plan(batch);
  return static_cast<long>(wf->arena_bytes());
}

int veles_native_infer(void* h, const float* input, int batch,
                       float* output) {
  try {
    static_cast<veles_native::Workflow*>(h)->Infer(input, batch, output);
    return 0;
  } catch (const std::exception&) {
    return -1;
  }
}

// greedy decode (causal LM packages): returns total tokens written
// (prompt + generated, capped at the exported context T), or -1 with
// the reason in ``err``
int veles_native_generate(void* h, const int* prompt, int n_prompt,
                          int max_new, int* out, char* err,
                          int errlen) {
  try {
    return static_cast<veles_native::Workflow*>(h)->Generate(
        prompt, n_prompt, max_new, out);
  } catch (const std::exception& e) {
    if (err && errlen > 0) {
      std::strncpy(err, e.what(), errlen - 1);
      err[errlen - 1] = '\0';
    }
    return -1;
  }
}

// sampled decode: temperature > 0 draws from softmax(logits/T)
// (optionally top_k-truncated) with a seeded xorshift64* stream —
// NOT bit-matched to the Python sampler's threefry; temperature <= 0
// or top_k == 1 is exact greedy
int veles_native_generate_sampled(void* h, const int* prompt,
                                  int n_prompt, int max_new,
                                  float temperature, int top_k,
                                  unsigned long long seed, int* out,
                                  char* err, int errlen) {
  try {
    return static_cast<veles_native::Workflow*>(h)->GenerateSampled(
        prompt, n_prompt, max_new, temperature, top_k, seed, out);
  } catch (const std::exception& e) {
    if (err && errlen > 0) {
      std::strncpy(err, e.what(), errlen - 1);
      err[errlen - 1] = '\0';
    }
    return -1;
  }
}

void veles_native_free(void* h) {
  delete static_cast<veles_native::Workflow*>(h);
}

}  // extern "C"
