// Minimal JSON parser for contents.json manifests.
// (Plays the role of the bundled rapidjson submodule in the reference's
// libVeles, SURVEY.md §2.10 — parses the package main file,
// ref src/main_file_loader.cc.)
#pragma once

#include <cctype>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace veles_native {

class Json {
 public:
  enum Type { kNull, kBool, kNumber, kString, kArray, kObject };

  Type type = kNull;
  bool bool_v = false;
  double num_v = 0;
  std::string str_v;
  std::vector<Json> arr_v;
  std::map<std::string, Json> obj_v;

  static Json Parse(const std::string& text) {
    size_t pos = 0;
    Json v = ParseValue(text, &pos);
    SkipWs(text, &pos);
    if (pos != text.size()) throw std::runtime_error("json: trailing data");
    return v;
  }

  const Json& at(const std::string& key) const {
    auto it = obj_v.find(key);
    if (it == obj_v.end())
      throw std::runtime_error("json: missing key " + key);
    return it->second;
  }
  bool has(const std::string& key) const { return obj_v.count(key) > 0; }
  const std::string& str() const { return str_v; }
  double num() const { return num_v; }
  int integer() const { return static_cast<int>(num_v); }

 private:
  static void SkipWs(const std::string& s, size_t* p) {
    while (*p < s.size() && std::isspace(static_cast<unsigned char>(s[*p])))
      ++*p;
  }

  static Json ParseValue(const std::string& s, size_t* p) {
    SkipWs(s, p);
    if (*p >= s.size()) throw std::runtime_error("json: eof");
    char c = s[*p];
    if (c == '{') return ParseObject(s, p);
    if (c == '[') return ParseArray(s, p);
    if (c == '"') return ParseString(s, p);
    if (c == 't' || c == 'f') return ParseBool(s, p);
    if (c == 'n') { Expect(s, p, "null"); return Json(); }
    return ParseNumber(s, p);
  }

  static void Expect(const std::string& s, size_t* p, const char* lit) {
    for (const char* q = lit; *q; ++q, ++*p)
      if (*p >= s.size() || s[*p] != *q)
        throw std::runtime_error(std::string("json: expected ") + lit);
  }

  static Json ParseBool(const std::string& s, size_t* p) {
    Json v;
    v.type = kBool;
    if (s[*p] == 't') { Expect(s, p, "true"); v.bool_v = true; }
    else { Expect(s, p, "false"); v.bool_v = false; }
    return v;
  }

  static Json ParseNumber(const std::string& s, size_t* p) {
    size_t end = *p;
    while (end < s.size() &&
           (std::isdigit(static_cast<unsigned char>(s[end])) ||
            strchr("+-.eE", s[end])))
      ++end;
    Json v;
    v.type = kNumber;
    v.num_v = std::stod(s.substr(*p, end - *p));
    *p = end;
    return v;
  }

  static Json ParseString(const std::string& s, size_t* p) {
    Json v;
    v.type = kString;
    ++*p;  // opening quote
    while (*p < s.size() && s[*p] != '"') {
      char c = s[(*p)++];
      if (c == '\\' && *p < s.size()) {
        char e = s[(*p)++];
        switch (e) {
          case 'n': c = '\n'; break;
          case 't': c = '\t'; break;
          case 'r': c = '\r'; break;
          case 'b': c = '\b'; break;
          case 'f': c = '\f'; break;
          case 'u': {  // \uXXXX -> keep ASCII subset, else '?'
            if (*p + 4 > s.size())
              throw std::runtime_error("json: bad \\u");
            int code = std::stoi(s.substr(*p, 4), nullptr, 16);
            *p += 4;
            c = code < 128 ? static_cast<char>(code) : '?';
            break;
          }
          default: c = e;
        }
      }
      v.str_v.push_back(c);
    }
    if (*p >= s.size()) throw std::runtime_error("json: unterminated string");
    ++*p;  // closing quote
    return v;
  }

  static Json ParseArray(const std::string& s, size_t* p) {
    Json v;
    v.type = kArray;
    ++*p;
    SkipWs(s, p);
    if (*p < s.size() && s[*p] == ']') { ++*p; return v; }
    while (true) {
      v.arr_v.push_back(ParseValue(s, p));
      SkipWs(s, p);
      if (*p >= s.size()) throw std::runtime_error("json: eof in array");
      if (s[*p] == ',') { ++*p; continue; }
      if (s[*p] == ']') { ++*p; break; }
      throw std::runtime_error("json: bad array");
    }
    return v;
  }

  static Json ParseObject(const std::string& s, size_t* p) {
    Json v;
    v.type = kObject;
    ++*p;
    SkipWs(s, p);
    if (*p < s.size() && s[*p] == '}') { ++*p; return v; }
    while (true) {
      SkipWs(s, p);
      Json key = ParseString(s, p);
      SkipWs(s, p);
      if (*p >= s.size() || s[*p] != ':')
        throw std::runtime_error("json: missing ':'");
      ++*p;
      v.obj_v[key.str_v] = ParseValue(s, p);
      SkipWs(s, p);
      if (*p >= s.size()) throw std::runtime_error("json: eof in object");
      if (s[*p] == ',') { ++*p; continue; }
      if (s[*p] == '}') { ++*p; break; }
      throw std::runtime_error("json: bad object");
    }
    return v;
  }
};

}  // namespace veles_native
