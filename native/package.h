// Package reading: STORED-entry ZIP archive + .npy array parsing.
// (Plays the roles of libarchive + NumpyArrayLoader in the reference's
// libVeles — ref src/workflow_archive.cc, src/numpy_array_loader.cc.
// Export writes ZIP_STORED so no inflate implementation is needed.)
#pragma once

#include <cstdint>
#include <cstring>
#include <fstream>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

namespace veles_native {

// ---------------------------------------------------------------- zip ----
class ZipReader {
 public:
  explicit ZipReader(const std::string& path) {
    std::ifstream f(path, std::ios::binary);
    if (!f) throw std::runtime_error("cannot open " + path);
    data_.assign(std::istreambuf_iterator<char>(f),
                 std::istreambuf_iterator<char>());
    ParseCentralDirectory();
  }

  bool has(const std::string& name) const { return entries_.count(name); }

  std::string read(const std::string& name) const {
    auto it = entries_.find(name);
    if (it == entries_.end())
      throw std::runtime_error("zip: no entry " + name);
    size_t local = it->second.local_offset;
    if (local + 30 > data_.size())
      throw std::runtime_error("zip: bad local header");
    if (U16(local + 8) != 0)
      throw std::runtime_error("zip: only STORED entries supported");
    uint16_t nlen = U16(local + 26), elen = U16(local + 28);
    size_t start = local + 30 + nlen + elen;
    if (start + it->second.size > data_.size())
      throw std::runtime_error("zip: truncated entry " + name);
    return std::string(data_.data() + start, it->second.size);
  }

  std::vector<std::string> names() const {
    std::vector<std::string> out;
    for (auto& kv : entries_) out.push_back(kv.first);
    return out;
  }

 private:
  struct Entry { size_t local_offset; size_t size; };

  uint16_t U16(size_t p) const {
    return static_cast<uint8_t>(data_[p]) |
           (static_cast<uint8_t>(data_[p + 1]) << 8);
  }
  uint32_t U32(size_t p) const {
    return static_cast<uint32_t>(U16(p)) |
           (static_cast<uint32_t>(U16(p + 2)) << 16);
  }

  void ParseCentralDirectory() {
    // find End Of Central Directory record (signature 0x06054b50)
    if (data_.size() < 22) throw std::runtime_error("zip: too small");
    size_t eocd = std::string::npos;
    for (size_t i = data_.size() - 22; ; --i) {
      if (U32(i) == 0x06054b50) { eocd = i; break; }
      if (i == 0 || data_.size() - i > 22 + 65535) break;
    }
    if (eocd == std::string::npos)
      throw std::runtime_error("zip: no EOCD");
    uint16_t count = U16(eocd + 10);
    size_t pos = U32(eocd + 16);
    for (uint16_t i = 0; i < count; ++i) {
      if (U32(pos) != 0x02014b50)
        throw std::runtime_error("zip: bad central entry");
      uint32_t size = U32(pos + 24);
      uint16_t nlen = U16(pos + 28), elen = U16(pos + 30),
               clen = U16(pos + 32);
      uint32_t local = U32(pos + 42);
      std::string name(data_.data() + pos + 46, nlen);
      entries_[name] = Entry{local, size};
      pos += 46 + nlen + elen + clen;
    }
  }

  std::vector<char> data_;
  std::map<std::string, Entry> entries_;
};

// ---------------------------------------------------------------- npy ----
struct NpyArray {
  std::vector<int> shape;
  std::vector<float> data;

  size_t elements() const {
    size_t n = 1;
    for (int d : shape) n *= static_cast<size_t>(d);
    return n;
  }
};

// IEEE binary16 -> float (the reference's optional fp16->fp32 load
// transform, libVeles numpy_array_loader.cc).
inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (static_cast<uint32_t>(h) & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1Fu;
  uint32_t man = h & 0x3FFu;
  uint32_t bits;
  if (exp == 0) {
    if (man == 0) {
      bits = sign;                       // +-0
    } else {                             // subnormal: renormalize
      // value = man * 2^-24; after s left-shifts the leading bit is
      // implicit and the exponent is 2^(-14 - s) -> biased 113 - s
      int shift = 0;
      while ((man & 0x400u) == 0) { man <<= 1; ++shift; }
      man &= 0x3FFu;
      bits = sign | ((113 - shift) << 23) | (man << 13);
    }
  } else if (exp == 31) {
    bits = sign | 0x7F800000u | (man << 13);   // inf / nan
  } else {
    bits = sign | ((exp - 15 + 127) << 23) | (man << 13);
  }
  float out;
  std::memcpy(&out, &bits, 4);
  return out;
}

// Parses NPY format v1/v2, little-endian <f2, <f4 or <f8, C order.
inline NpyArray ParseNpy(const std::string& bytes) {
  if (bytes.size() < 10 || std::memcmp(bytes.data(), "\x93NUMPY", 6) != 0)
    throw std::runtime_error("npy: bad magic");
  uint8_t major = bytes[6];
  size_t hlen, hstart;
  if (major == 1) {
    hlen = static_cast<uint8_t>(bytes[8]) |
           (static_cast<uint8_t>(bytes[9]) << 8);
    hstart = 10;
  } else {
    if (bytes.size() < 12) throw std::runtime_error("npy: truncated");
    hlen = static_cast<uint8_t>(bytes[8]) |
           (static_cast<uint8_t>(bytes[9]) << 8) |
           (static_cast<uint8_t>(bytes[10]) << 16) |
           (static_cast<uint8_t>(bytes[11]) << 24);
    hstart = 12;
  }
  std::string header = bytes.substr(hstart, hlen);
  if (header.find("'fortran_order': True") != std::string::npos)
    throw std::runtime_error("npy: fortran order unsupported");
  bool f8 = header.find("<f8") != std::string::npos;
  bool f2 = header.find("<f2") != std::string::npos;
  bool i1 = header.find("|i1") != std::string::npos ||
            header.find("<i1") != std::string::npos;
  if (!f8 && !f2 && !i1 && header.find("<f4") == std::string::npos)
    throw std::runtime_error("npy: dtype must be <f2, <f4, <f8 or i1");
  NpyArray arr;
  size_t sp = header.find("'shape':");
  size_t lp = header.find('(', sp), rp = header.find(')', lp);
  std::string dims = header.substr(lp + 1, rp - lp - 1);
  size_t p = 0;
  while (p < dims.size()) {
    while (p < dims.size() &&
           !std::isdigit(static_cast<unsigned char>(dims[p])))
      ++p;
    if (p >= dims.size()) break;
    size_t e = p;
    while (e < dims.size() &&
           std::isdigit(static_cast<unsigned char>(dims[e])))
      ++e;
    arr.shape.push_back(std::stoi(dims.substr(p, e - p)));
    p = e;
  }
  size_t n = arr.elements();
  size_t dstart = hstart + hlen;
  size_t esize = f8 ? 8 : (f2 ? 2 : (i1 ? 1 : 4));
  if (bytes.size() < dstart + n * esize)
    throw std::runtime_error("npy: truncated data");
  arr.data.resize(n);
  if (f8) {
    const double* src =
        reinterpret_cast<const double*>(bytes.data() + dstart);
    for (size_t i = 0; i < n; ++i)
      arr.data[i] = static_cast<float>(src[i]);
  } else if (f2) {
    const uint16_t* src =
        reinterpret_cast<const uint16_t*>(bytes.data() + dstart);
    for (size_t i = 0; i < n; ++i) arr.data[i] = HalfToFloat(src[i]);
  } else if (i1) {
    const int8_t* src =
        reinterpret_cast<const int8_t*>(bytes.data() + dstart);
    for (size_t i = 0; i < n; ++i)
      arr.data[i] = static_cast<float>(src[i]);
  } else {
    std::memcpy(arr.data.data(), bytes.data() + dstart, n * 4);
  }
  return arr;
}

// Fold per-output-channel scales (export dtype="int8": one <f4 scale
// per last-dim column) back into a widened int8 array.
inline void ApplyChannelScales(NpyArray& w, const NpyArray& scales) {
  if (w.shape.empty())
    throw std::runtime_error("scales: scalar weights unsupported");
  size_t cols = w.shape.back();
  if (scales.elements() != cols)
    throw std::runtime_error("scales: length != output channels");
  for (size_t i = 0; i < w.data.size(); ++i)
    w.data[i] *= scales.data[i % cols];
}

}  // namespace veles_native
