// Arena memory optimizer: bin-packs unit input/output buffers into one
// arena by lifetime — the reference's standout native idea ("sliding
// blocks to minimal height", ref libVeles src/memory_optimizer.cc,
// src/memory_node.h; SURVEY.md §2.10).
//
// Each block has a [first_use, last_use] interval in execution order and a
// byte size.  Blocks whose intervals overlap must not overlap in the
// arena.  Greedy first-fit over size-descending blocks approximates the
// minimal arena height.
#pragma once

#include <algorithm>
#include <cstddef>
#include <vector>

namespace veles_native {

struct MemoryBlock {
  int first_use = 0;   // unit index producing/first reading the buffer
  int last_use = 0;    // last unit index reading it
  size_t size = 0;     // bytes
  size_t offset = 0;   // assigned arena offset (output)
};

class MemoryOptimizer {
 public:
  // Assigns offsets; returns total arena height in bytes.
  static size_t Optimize(std::vector<MemoryBlock>* blocks) {
    std::vector<size_t> order(blocks->size());
    for (size_t i = 0; i < order.size(); ++i) order[i] = i;
    std::sort(order.begin(), order.end(), [&](size_t a, size_t b) {
      return (*blocks)[a].size > (*blocks)[b].size;
    });
    size_t height = 0;
    std::vector<size_t> placed;
    for (size_t oi : order) {
      MemoryBlock& blk = (*blocks)[oi];
      // candidate offsets: 0 and the top of every conflicting block
      std::vector<std::pair<size_t, size_t>> conflicts;  // [off, end)
      for (size_t pj : placed) {
        const MemoryBlock& other = (*blocks)[pj];
        bool live_overlap = !(blk.last_use < other.first_use ||
                              other.last_use < blk.first_use);
        if (live_overlap)
          conflicts.emplace_back(other.offset, other.offset + other.size);
      }
      std::sort(conflicts.begin(), conflicts.end());
      size_t off = 0;
      for (auto& c : conflicts) {
        if (off + blk.size <= c.first) break;  // fits in the gap
        off = std::max(off, c.second);
      }
      blk.offset = off;
      height = std::max(height, off + blk.size);
      placed.push_back(oi);
    }
    return height;
  }
};

}  // namespace veles_native
