"""Numpy helpers (ref veles/numpy_ext.py): ``roundup``, ``interleave``,
and the ``NumDiff`` numeric-diff used by golden kernel-vs-reference tests
(ref numpy_ext.py:116, SURVEY.md §4)."""

import numpy as np


def roundup(value, align):
    """Round ``value`` up to a multiple of ``align`` (ref numpy_ext.roundup;
    on TPU the natural aligns are 8/128 sublane/lane tiles)."""
    rem = value % align
    return value if rem == 0 else value + align - rem


def interleave(arr):
    """Interleave the first two axes: (2, N, ...) -> (2N, ...) with
    alternating rows (ref numpy_ext.interleave)."""
    a = np.asarray(arr)
    if a.shape[0] != 2:
        raise ValueError("interleave expects leading axis of 2")
    out = np.empty((2 * a.shape[1],) + a.shape[2:], dtype=a.dtype)
    out[0::2] = a[0]
    out[1::2] = a[1]
    return out


class NumDiff(object):
    """Accumulating numeric diff between two arrays (ref NumDiff
    numpy_ext.py:116): feeds golden tests with max-abs-diff plus the
    offending index, tolerant of bf16 quantization via ``threshold``."""

    def __init__(self, threshold=1e-5):
        self.threshold = threshold
        self.max_diff = 0.0
        self.max_index = None
        self.count = 0
        self.checked = 0

    def check(self, a, b):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        if a.shape != b.shape:
            raise ValueError("shape mismatch: %s vs %s" % (a.shape, b.shape))
        d = np.abs(a - b)
        idx = np.unravel_index(np.argmax(d), d.shape) if d.size else None
        if d.size and d[idx] > self.max_diff:
            self.max_diff = float(d[idx])
            self.max_index = idx
        self.count += int((d > self.threshold).sum())
        self.checked += d.size
        return self

    @property
    def ok(self):
        return self.count == 0

    def report(self):
        return ("NumDiff: %d/%d elements over %.1e (max %.3e at %s)"
                % (self.count, self.checked, self.threshold,
                   self.max_diff, self.max_index))

    def assert_ok(self):
        assert self.ok, self.report()
