"""Publisher unit (ref veles/publishing/publisher.py:57): at the end of a
run, gathers workflow identity, metrics (IResultProvider aggregation),
per-unit run stats, config, and any plot files emitted by plotters, and
renders them through the selected backends."""

import datetime
import os

from veles_tpu.config import root
from veles_tpu.publishing.backends import ReportBackend
from veles_tpu.units import Unit


class Publisher(Unit):
    def __init__(self, workflow, backends=("markdown",), directory=None,
                 description=None, **kwargs):
        super(Publisher, self).__init__(workflow, **kwargs)
        self.backends = list(backends)
        self.directory = directory or root.common.dirs.get("reports",
                                                           "reports")
        self.description = description
        self.written = []

    def gather(self):
        wf = self.workflow
        report = {
            "name": getattr(wf, "name", "workflow"),
            "date": datetime.datetime.now().isoformat(timespec="seconds"),
            "description": self.description,
            "metrics": wf.gather_results() if wf is not None else {},
            "units": [], "plots": [], "config": None,
        }
        if wf is not None:
            for u in wf.units:
                report["units"].append({"name": u.name, "runs": u.run_count,
                                        "time": u.run_time})
                for attr in ("written_files", "saved_paths"):
                    for p in getattr(u, attr, ()) or ():
                        if str(p).endswith((".png", ".pdf", ".svg")):
                            report["plots"].append(str(p))
            cfg = getattr(wf, "config", None)
            if cfg is not None:
                report["config"] = (cfg.as_dict()
                                    if hasattr(cfg, "as_dict") else cfg)
        return report

    def run(self):
        report = self.gather()
        os.makedirs(self.directory, exist_ok=True)
        stem = report["name"].replace(" ", "_").replace("/", "_")
        for name in self.backends:
            backend = ReportBackend.mapping[name]()
            path = os.path.join(self.directory, stem + backend.EXT)
            rendered = backend.render(report)
            with open(path, "wb" if backend.BINARY else "w") as f:
                f.write(rendered)
            self.written.append(path)
            self.info("published %s report: %s", name, path)
