"""Report rendering backends (ref veles/publishing/registry.py + the
markdown/jinja2/pdf/confluence backends).  Each backend renders the
Publisher's report dict to text and declares a file extension; the jinja2
backend upgrades the HTML output when jinja2 is importable (it is in this
image), with a string-template fallback so the backend never disappears."""

import json

from veles_tpu.json_encoders import NumpyJSONEncoder
from veles_tpu.registry import MappedRegistry


class _ReportEncoder(NumpyJSONEncoder):
    """Numpy/jax values as numbers; anything else stringifies rather than
    failing the report."""

    def default(self, o):
        try:
            return super(_ReportEncoder, self).default(o)
        except TypeError:
            return str(o)


class BackendRegistry(MappedRegistry):
    """MAPPING name → backend class."""


class ReportBackend(object, metaclass=BackendRegistry):
    EXT = ".txt"

    def render(self, report):
        raise NotImplementedError


def _fmt_value(v):
    if isinstance(v, float):
        return "%.6g" % v
    return str(v)


class MarkdownBackend(ReportBackend):
    MAPPING = "markdown"
    EXT = ".md"

    def render(self, report):
        lines = ["# %s" % report.get("name", "workflow"),
                 "", "*Generated %s*" % report.get("date", ""), ""]
        if report.get("description"):
            lines += [report["description"], ""]
        metrics = report.get("metrics") or {}
        if metrics:
            lines += ["## Metrics", "", "| metric | value |", "|---|---|"]
            lines += ["| %s | %s |" % (k, _fmt_value(v))
                      for k, v in sorted(metrics.items())]
            lines.append("")
        units = report.get("units") or []
        if units:
            lines += ["## Units", "",
                      "| unit | runs | total s |", "|---|---|---|"]
            lines += ["| %s | %d | %.3f |" % (u["name"], u["runs"], u["time"])
                      for u in units]
            lines.append("")
        plots = report.get("plots") or []
        if plots:
            lines += ["## Plots", ""]
            lines += ["![%s](%s)" % (p, p) for p in plots]
            lines.append("")
        config = report.get("config")
        if config:
            lines += ["## Configuration", "", "```json",
                      json.dumps(config, indent=2, default=str), "```", ""]
        return "\n".join(lines)


_HTML_TEMPLATE = """<!doctype html><html><head><meta charset="utf-8">
<title>{{ name }}</title></head><body>
<h1>{{ name }}</h1><p><em>Generated {{ date }}</em></p>
{% if metrics %}<h2>Metrics</h2><table border="1">
{% for k, v in metrics %}<tr><td>{{ k }}</td><td>{{ v }}</td></tr>{% endfor %}
</table>{% endif %}
{% if units %}<h2>Units</h2><table border="1">
<tr><th>unit</th><th>runs</th><th>total s</th></tr>
{% for u in units %}<tr><td>{{ u.name }}</td><td>{{ u.runs }}</td>
<td>{{ '%.3f' % u.time }}</td></tr>{% endfor %}</table>{% endif %}
{% for p in plots %}<img src="{{ p }}" alt="{{ p }}">{% endfor %}
</body></html>"""


class HTMLBackend(ReportBackend):
    MAPPING = "html"
    EXT = ".html"

    def render(self, report):
        metrics = sorted((k, _fmt_value(v))
                         for k, v in (report.get("metrics") or {}).items())
        ctx = dict(name=report.get("name", "workflow"),
                   date=report.get("date", ""), metrics=metrics,
                   units=report.get("units") or [],
                   plots=report.get("plots") or [])
        try:
            import jinja2
            return jinja2.Template(_HTML_TEMPLATE).render(**ctx)
        except ImportError:
            rows = "".join("<tr><td>%s</td><td>%s</td></tr>" % kv
                           for kv in metrics)
            return ("<!doctype html><html><body><h1>%s</h1>"
                    "<p><em>%s</em></p><table border=\"1\">%s</table>"
                    "</body></html>"
                    % (ctx["name"], ctx["date"], rows))


class JSONBackend(ReportBackend):
    MAPPING = "json"
    EXT = ".json"

    def render(self, report):
        return json.dumps(report, indent=2, cls=_ReportEncoder,
                          sort_keys=True)
