"""Report rendering backends (ref veles/publishing/registry.py + the
markdown/jinja2/pdf/confluence backends).  Each backend renders the
Publisher's report dict to text and declares a file extension; the jinja2
backend upgrades the HTML output when jinja2 is importable (it is in this
image), with a string-template fallback so the backend never disappears."""

import json

from veles_tpu.json_encoders import NumpyJSONEncoder
from veles_tpu.registry import MappedRegistry


class _ReportEncoder(NumpyJSONEncoder):
    """Numpy/jax values as numbers; anything else stringifies rather than
    failing the report."""

    def default(self, o):
        try:
            return super(_ReportEncoder, self).default(o)
        except TypeError:
            return str(o)


class BackendRegistry(MappedRegistry):
    """MAPPING name → backend class."""


class ReportBackend(object, metaclass=BackendRegistry):
    EXT = ".txt"
    BINARY = False   # render() returns str; True → bytes

    def render(self, report):
        raise NotImplementedError


def _fmt_value(v):
    if isinstance(v, float):
        return "%.6g" % v
    return str(v)


class MarkdownBackend(ReportBackend):
    MAPPING = "markdown"
    EXT = ".md"

    def render(self, report):
        lines = ["# %s" % report.get("name", "workflow"),
                 "", "*Generated %s*" % report.get("date", ""), ""]
        if report.get("description"):
            lines += [report["description"], ""]
        metrics = report.get("metrics") or {}
        if metrics:
            lines += ["## Metrics", "", "| metric | value |", "|---|---|"]
            lines += ["| %s | %s |" % (k, _fmt_value(v))
                      for k, v in sorted(metrics.items())]
            lines.append("")
        units = report.get("units") or []
        if units:
            lines += ["## Units", "",
                      "| unit | runs | total s |", "|---|---|---|"]
            lines += ["| %s | %d | %.3f |" % (u["name"], u["runs"], u["time"])
                      for u in units]
            lines.append("")
        plots = report.get("plots") or []
        if plots:
            lines += ["## Plots", ""]
            lines += ["![%s](%s)" % (p, p) for p in plots]
            lines.append("")
        config = report.get("config")
        if config:
            lines += ["## Configuration", "", "```json",
                      json.dumps(config, indent=2, default=str), "```", ""]
        return "\n".join(lines)


_HTML_TEMPLATE = """<!doctype html><html><head><meta charset="utf-8">
<title>{{ name }}</title></head><body>
<h1>{{ name }}</h1><p><em>Generated {{ date }}</em></p>
{% if metrics %}<h2>Metrics</h2><table border="1">
{% for k, v in metrics %}<tr><td>{{ k }}</td><td>{{ v }}</td></tr>{% endfor %}
</table>{% endif %}
{% if units %}<h2>Units</h2><table border="1">
<tr><th>unit</th><th>runs</th><th>total s</th></tr>
{% for u in units %}<tr><td>{{ u.name }}</td><td>{{ u.runs }}</td>
<td>{{ '%.3f' % u.time }}</td></tr>{% endfor %}</table>{% endif %}
{% for p in plots %}<img src="{{ p }}" alt="{{ p }}">{% endfor %}
</body></html>"""


class HTMLBackend(ReportBackend):
    MAPPING = "html"
    EXT = ".html"

    def render(self, report):
        metrics = sorted((k, _fmt_value(v))
                         for k, v in (report.get("metrics") or {}).items())
        ctx = dict(name=report.get("name", "workflow"),
                   date=report.get("date", ""), metrics=metrics,
                   units=report.get("units") or [],
                   plots=report.get("plots") or [])
        try:
            import jinja2
            return jinja2.Template(_HTML_TEMPLATE).render(**ctx)
        except ImportError:
            rows = "".join("<tr><td>%s</td><td>%s</td></tr>" % kv
                           for kv in metrics)
            return ("<!doctype html><html><body><h1>%s</h1>"
                    "<p><em>%s</em></p><table border=\"1\">%s</table>"
                    "</body></html>"
                    % (ctx["name"], ctx["date"], rows))


class JSONBackend(ReportBackend):
    MAPPING = "json"
    EXT = ".json"

    def render(self, report):
        return json.dumps(report, indent=2, cls=_ReportEncoder,
                          sort_keys=True)


class PDFBackend(ReportBackend):
    """PDF report via matplotlib's PdfPages (ref the pdf backend,
    veles/publishing/) — page 1: title + metrics table + unit stats;
    then one page per plot image."""

    MAPPING = "pdf"
    EXT = ".pdf"
    BINARY = True

    def render(self, report):
        import io
        import os

        from veles_tpu.services.plotting import _matplotlib
        plt = _matplotlib()   # pins the Agg backend before pdf imports
        from matplotlib.backends.backend_pdf import PdfPages

        buf = io.BytesIO()
        with PdfPages(buf) as pdf:
            fig = plt.figure(figsize=(8.27, 11.69))     # A4
            fig.text(0.5, 0.95, report.get("name", "workflow"),
                     ha="center", fontsize=18, weight="bold")
            fig.text(0.5, 0.92, "Generated %s" % report.get("date", ""),
                     ha="center", fontsize=9, style="italic")
            y = 0.86
            if report.get("description"):
                fig.text(0.1, y, report["description"], fontsize=10,
                         wrap=True)
                y -= 0.06
            metrics = report.get("metrics") or {}
            if metrics:
                fig.text(0.1, y, "Metrics", fontsize=13, weight="bold")
                y -= 0.03
                for k, v in sorted(metrics.items()):
                    fig.text(0.12, y, str(k), fontsize=9)
                    fig.text(0.55, y, _fmt_value(v)[:60], fontsize=9)
                    y -= 0.022
                    if y < 0.1:
                        break
            units = report.get("units") or []
            if units and y > 0.2:
                y -= 0.03
                fig.text(0.1, y, "Units", fontsize=13, weight="bold")
                y -= 0.03
                for u in units:
                    fig.text(0.12, y, u["name"], fontsize=9)
                    fig.text(0.55, y, "%d runs, %.3f s"
                             % (u["runs"], u["time"]), fontsize=9)
                    y -= 0.022
                    if y < 0.08:
                        break
            pdf.savefig(fig)
            plt.close(fig)
            for p in report.get("plots") or []:
                if not os.path.exists(p):
                    continue
                img = plt.imread(p)
                fig = plt.figure(figsize=(8.27, 11.69))
                ax = fig.add_axes([0.05, 0.2, 0.9, 0.7])
                ax.imshow(img)
                ax.axis("off")
                ax.set_title(os.path.basename(p))
                pdf.savefig(fig)
                plt.close(fig)
        return buf.getvalue()


class ConfluenceBackend(ReportBackend):
    """Confluence wiki-markup report (ref the confluence backend,
    veles/publishing/).  Renders the storage markup offline; posting to a
    server is the caller's transport concern (zero-egress friendly)."""

    MAPPING = "confluence"
    EXT = ".confluence"

    def render(self, report):
        lines = ["h1. %s" % report.get("name", "workflow"),
                 "_Generated %s_" % report.get("date", ""), ""]
        if report.get("description"):
            lines += [report["description"], ""]
        metrics = report.get("metrics") or {}
        if metrics:
            lines += ["h2. Metrics", "||metric||value||"]
            lines += ["|%s|%s|" % (k, _fmt_value(v))
                      for k, v in sorted(metrics.items())]
            lines.append("")
        units = report.get("units") or []
        if units:
            lines += ["h2. Units", "||unit||runs||total s||"]
            lines += ["|%s|%d|%.3f|" % (u["name"], u["runs"], u["time"])
                      for u in units]
            lines.append("")
        for p in report.get("plots") or []:
            lines.append("!%s!" % p)
        return "\n".join(lines)
