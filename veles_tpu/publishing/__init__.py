"""Post-training report publishing (ref veles/publishing/ — Publisher unit
gathering metrics + plots, with pluggable output backends
publisher.py:57, registry.py)."""

from veles_tpu.publishing.backends import (BackendRegistry, JSONBackend,
                                           HTMLBackend, MarkdownBackend,
                                           ReportBackend)
from veles_tpu.publishing.publisher import Publisher

__all__ = ["Publisher", "ReportBackend", "BackendRegistry",
           "MarkdownBackend", "HTMLBackend", "JSONBackend"]
