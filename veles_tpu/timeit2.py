"""timeit helper (ref veles/timeit2.py): ``timeit(fn, *args)`` →
``(result, seconds)``; on jax outputs it blocks until ready so the number
means device time, not dispatch time."""

import time


def timeit(fn, *args, **kwargs):
    t0 = time.perf_counter()
    result = fn(*args, **kwargs)
    try:
        import jax
        jax.block_until_ready(result)
    except (ImportError, TypeError):
        pass
    return result, time.perf_counter() - t0
