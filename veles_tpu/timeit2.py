"""timeit helper (ref veles/timeit2.py): ``timeit(fn, *args)`` →
``(result, seconds)``; on jax outputs it blocks until ready so the number
means device time, not dispatch time."""

import time

try:   # resolved at import time — never inside the timed window
    import jax as _jax
except ImportError:   # pragma: no cover — jax is bundled in this image
    _jax = None


def timeit(fn, *args, **kwargs):
    t0 = time.perf_counter()
    result = fn(*args, **kwargs)
    if _jax is not None:
        # flatten and block ONLY on array leaves: a mixed pytree (arrays
        # next to strings/None/ints) must still report device time — the
        # old blanket block_until_ready raised TypeError on the first
        # non-array leaf and a wholesale `except TypeError` silently
        # timed dispatch instead of compute
        leaves = [x for x in _jax.tree_util.tree_leaves(result)
                  if isinstance(x, _jax.Array)]
        if leaves:
            _jax.block_until_ready(leaves)
    return result, time.perf_counter() - t0
