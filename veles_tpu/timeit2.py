"""timeit helper (ref veles/timeit2.py): ``timeit(fn, *args)`` →
``(result, seconds)``; on jax outputs it blocks until ready so the number
means device time, not dispatch time."""

import time

try:   # resolved at import time — never inside the timed window
    import jax as _jax
except ImportError:   # pragma: no cover — jax is bundled in this image
    _jax = None


def timeit(fn, *args, **kwargs):
    t0 = time.perf_counter()
    result = fn(*args, **kwargs)
    if _jax is not None:
        try:
            _jax.block_until_ready(result)
        except TypeError:
            pass
    return result, time.perf_counter() - t0
