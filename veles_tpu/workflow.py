"""Workflow — a Unit container and the host-side scheduler (ref: veles/workflow.py).

Keeps the reference's semantics — units, control links, gates, dependency-
ordered initialization with partial re-init requeue (ref workflow.py:299-345),
Repeater-closed hot loop, EndPoint → ``on_workflow_finished`` (ref :347-365),
per-unit run statistics (ref :763-821), result gathering (ref :823-845) —
on a single-threaded queue scheduler instead of a Twisted thread pool.

The TPU performance story does NOT come from this graph walk: subclasses
(e.g. :class:`veles_tpu.models.standard_workflow.StandardWorkflow`) *stage*
the repeater cycle's compute into one jitted step function, so one scheduler
iteration costs one XLA dispatch regardless of how many logical units the
loop contains."""

import collections
import json
import os
import random
import time

from veles_tpu import telemetry
from veles_tpu.logger import Logger
from veles_tpu.mutable import Bool
from veles_tpu.telemetry import flight, health
from veles_tpu.plumbing import EndPoint, StartPoint
from veles_tpu.units import Container, MissingDemands, Unit


class NoMoreJobs(Exception):
    """Ref workflow.py:78."""


class Workflow(Container):
    hide_from_registry = True

    def __init__(self, workflow=None, **kwargs):
        super(Workflow, self).__init__(workflow, **kwargs)
        self._units = []
        self._by_name = collections.defaultdict(list)
        self.stopped = Bool(False)
        self.start_point = StartPoint(self)
        self.end_point = EndPoint(self)
        self._run_time_ = 0.0
        self.result_file = kwargs.get("result_file")
        #: graceful-preemption flags.  ``preempt_requested`` is a gate
        #: Bool raised by a SIGTERM handler — StandardWorkflow composes
        #: it into the snapshotter's gate_skip so the checkpoint happens
        #: at the NEXT CYCLE, not the next epoch end.  The snapshotter
        #: unit (or the run loop, when there is none) answers it and
        #: raises ``preempted_`` once handled — the CLI turns that into
        #: exit code 75 (EX_TEMPFAIL) so a supervisor restarts the
        #: identical command and --snapshot auto resumes.
        self.preempt_requested = Bool(False)
        self.preempted_ = False
        #: fault injection (ref --slave-death-probability,
        #: client.py:303-307: randomly crash to prove the recovery
        #: path).  Per UNIT RUN probability of a sudden, checkpoint-less
        #: process death (os._exit(1)) — pair with --snapshot-every /
        #: --snapshot auto and a restarting supervisor to drill
        #: checkpoint-restart elasticity end to end.  Uses stdlib
        #: random, NOT the framework PRNG streams, so injection never
        #: perturbs training reproducibility.
        self.death_probability = float(
            kwargs.get("death_probability", 0.0))

    # --------------------------------------------------------------- container
    def add_ref(self, unit):
        """Register a child unit (ref workflow.py:398)."""
        if unit is self:
            return
        self._units.append(unit)
        self._by_name[unit.name].append(unit)

    def del_ref(self, unit):
        if unit in self._units:
            self._units.remove(unit)
            bucket = self._by_name.get(unit.name)
            if bucket is not None and unit in bucket:
                bucket.remove(unit)
                if not bucket:
                    # defaultdict: an empty leftover bucket would keep
                    # the name visible to iteration/membership and make
                    # the analyzer's dangling-link rule lie about what
                    # is still in the workflow
                    del self._by_name[unit.name]

    @property
    def units(self):
        return list(self._units)

    def __iter__(self):
        return iter(self._units)

    def __len__(self):
        return len(self._units)

    def __getitem__(self, key):
        if isinstance(key, int):
            return self._units[key]
        hits = self._by_name.get(key, [])
        if not hits:
            raise KeyError(key)
        return hits[0] if len(hits) == 1 else hits

    # ------------------------------------------------------------- initialize
    def initialize(self, **kwargs):
        """Initialize all units in control-dependency order, requeueing units
        whose ``demand()``-ed attributes are not linked yet
        (ref workflow.py:299-345)."""
        order = self._dependency_order()
        pending = collections.deque(order)
        passes_without_progress = 0
        while pending:
            if passes_without_progress > len(pending):
                unit = pending[0]
                unit.verify_demands()  # raises the informative MissingDemands
                raise RuntimeError("initialize() deadlock at %s" % unit)
            unit = pending.popleft()
            try:
                unit._initialize_wrapped(**kwargs)
                passes_without_progress = 0
            except MissingDemands:
                pending.append(unit)
                passes_without_progress += 1
        self._initialized = True

    def control_reachable(self, start=None):
        """Units reachable from ``start`` (default ``start_point``) over
        control links, in BFS order.  Introspection hook shared by the
        scheduler's dependency ordering and the static analyzer
        (veles_tpu.analysis.graph_lint)."""
        seen = []
        seen_set = set()
        queue = collections.deque(
            [start if start is not None else self.start_point])
        while queue:
            unit = queue.popleft()
            if unit in seen_set:
                continue
            seen.append(unit)
            seen_set.add(unit)
            for dst in unit.links_to:
                if dst not in seen_set:
                    queue.append(dst)
        return seen

    def _dependency_order(self):
        """BFS from start_point over control links, then any unreached units
        in insertion order."""
        seen = self.control_reachable()
        seen_set = set(seen)
        for unit in self._units:
            if unit not in seen_set:
                seen.append(unit)
                seen_set.add(unit)
        return seen

    # -------------------------------------------------------------------- run
    def run(self):
        """Drive the control graph from start_point until EndPoint fires or
        ``stopped`` is raised externally (ref workflow.py:347-365)."""
        if not self._initialized:
            raise RuntimeError("run() before initialize()")
        self.stopped <<= False
        for unit in self._units:
            unit.reset_gate()  # clear stale pulses from a stopped prior run
        t0 = time.perf_counter()
        self.event("workflow", "begin")
        flight.record("workflow.start", workflow=self.name)
        with telemetry.span("workflow.run:%s" % self.name):
            self._drive()
        wall = time.perf_counter() - t0
        self._run_time_ += wall
        self.event("workflow", "end")
        flight.record("workflow.stop", workflow=self.name, dur_s=wall,
                      preempted=self.preempted_)
        # span export: the workflow.run record plus aggregated per-unit
        # spans (units that never ran — gate-blocked/skipped throughout —
        # are excluded) into the JSONL sink and the /metrics gauges.
        # Guarded: a telemetry failure here must not skip the unit
        # stop() cleanup or the result file below
        try:
            telemetry.emit_workflow_spans(self, wall)
        except Exception as e:   # noqa: BLE001 — observe, never abort
            self.warning("workflow span export failed (%s: %s)",
                         type(e).__name__, e)
        for unit in self._units:
            unit.stop()
        if self.result_file:
            self.write_results(self.result_file)

    def _drive(self):
        """The scheduler loop proper: walk the control graph from
        start_point until the queue drains or ``stopped`` rises."""
        queue = collections.deque([self.start_point])
        queued = {self.start_point}
        can_break = None      # no-snapshotter fallback, decided once
        # hot-loop hoists: one attribute lookup per run, not per unit
        fl_record = flight.record
        note_progress = health.note_progress
        # chaos knob (tools/train_chaos.py): a per-unit-run sleep that
        # stretches the scheduler so external kills reliably land
        # mid-sweep.  Zero (the default) costs one config read per run()
        # — and with chaos.unit_delay_file set the sleep is further
        # gated on that file EXISTING, so a harness can switch a
        # long stall on mid-run (tools/pod_chaos.py freezes one host's
        # scheduler this way to forge a collective hang) and disarm it
        # again for the respawn
        from veles_tpu.config import root as _root
        unit_delay = float(
            _root.common.chaos.get("unit_delay_ms", 0)) / 1e3
        delay_file = _root.common.chaos.get("unit_delay_file", None)
        while queue and not bool(self.stopped):
            if bool(self.preempt_requested) and not self.preempted_:
                if can_break is None:
                    can_break = (not self._graph_has_snapshotter()
                                 and self._preempt_break_safe())
                if can_break:
                    # no snapshotter in the graph: nothing to save — stop
                    # at this unit boundary; the supervisor restart will
                    # resume from whatever snapshot exists (or fresh)
                    self.warning("preemption requested with no "
                                 "snapshotter unit — stopping without a "
                                 "checkpoint")
                    self.preempted_ = True
                    break
            unit = queue.popleft()
            queued.discard(unit)
            if self.death_probability:
                if random.random() < self.death_probability:
                    self.warning("fault injection: simulated crash "
                                 "(death_probability=%.3f)",
                                 self.death_probability)
                    # leave a black box behind: the simulated crash is
                    # exactly the sudden-death case the flight recorder
                    # exists for, and it doubles as the end-to-end
                    # exercise of the crashdump path
                    fl_record("fault.injected", unit=unit.name,
                              workflow=self.name,
                              death_probability=self.death_probability)
                    flight.dump(reason="fault-injection")
                    os._exit(1)
            if bool(unit.gate_block):
                unit.reset_gate()
                continue
            if not bool(unit.gate_skip):
                if unit_delay and (delay_file is None
                                   or os.path.exists(delay_file)):
                    time.sleep(unit_delay)
                fl_record("unit.start", unit=unit.name)
                dt = unit._run_wrapped()
                fl_record("unit.stop", unit=unit.name, dur_s=dt)
                note_progress()
            unit.reset_gate()
            if bool(self.stopped):
                break
            for dst in unit.links_to:
                if dst.open_gate(unit) and dst not in queued:
                    queue.append(dst)
                    queued.add(dst)

    def on_workflow_finished(self):
        """EndPoint callback (ref workflow.py:373)."""
        self.stopped <<= True

    def stop(self):
        self.stopped <<= True

    def request_preempt(self):
        """Ask for a graceful preemption stop: checkpoint at the next
        consistent cycle boundary, then stop.  Signal-handler safe (one
        Bool flip + an O(1) flight append, both reentrancy-proof); the
        TPU-era mapping of the reference's slave drop/respawn
        elasticity (server.py:637-655) onto checkpoint-restart."""
        self.preempt_requested.set(True)
        # the flag flip comes FIRST — forensics must never delay it
        flight.record("preempt.requested", workflow=self.name)

    def _graph_has_snapshotter(self):
        """A snapshotter anywhere in the unit graph — not just the
        StandardWorkflow ``self.snapshotter`` convention — answers
        preemption itself (its gate composes ``preempt_requested``)."""
        from veles_tpu.services.snapshotter import SnapshotterBase
        return any(isinstance(u, SnapshotterBase) for u in self._units)

    def _preempt_break_safe(self):
        """Unilaterally breaking the run loop is only safe single-host:
        under multi-host the SIGTERMs race unit boundaries, and a process
        that stops while a peer is inside a collective strands the peer
        until the DCN timeout.  With no snapshotter unit there is no
        agreed cycle point to rendezvous on, so multi-host falls back to
        the scheduler's hard kill + interval-snapshot restart."""
        import jax
        if jax.process_count() == 1:
            return True
        if not getattr(self, "_preempt_multihost_warned_", False):
            self._preempt_multihost_warned_ = True
            self.warning(
                "preemption requested, but a multi-host workflow without "
                "a snapshotter unit cannot stop at an agreed point — "
                "continuing until the scheduler's hard kill (add a "
                "snapshotter for graceful preemption)")
        return False

    # ------------------------------------------------------------------ stats
    def print_stats(self, top=5):
        """Top-N unit run-time table + scheduler efficiency η
        (unit-time / wall) + peak RSS (ref workflow.py:763-821 and the
        exit-time RSS report, ref __main__.py:791-797)."""
        rows = sorted(((u.run_time, u.run_count, u.name) for u in self._units),
                      reverse=True)[:top]
        total = sum(u.run_time for u in self._units)
        try:
            import resource
            import sys as _sys
            # ru_maxrss: KiB on linux, BYTES on darwin
            div = 1024.0 * 1024.0 if _sys.platform == "darwin" else 1024.0
            rss_mib = resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss / div
        except (ImportError, ValueError):
            rss_mib = 0.0
        self.info("---- unit run-time stats (total %.3fs, wall %.3fs, "
                  "η %.2f, peak RSS %.1f MiB) ----",
                  total, self._run_time_,
                  total / max(self._run_time_, 1e-9), rss_mib)
        for rt, rc, name in rows:
            if rc:
                self.info("%-30s %8d runs %10.3fs (%6.2f%%)",
                          name, rc, rt, 100.0 * rt / max(total, 1e-9))
        return rows

    # ---------------------------------------------------------------- results
    def change_unit(self, old, new):
        """Graph surgery: splice ``new`` into ``old``'s place — control
        links in and out move over, gates transfer (ref Workflow.change_unit
        workflow.py:973, used to swap units in restored/derived
        workflows)."""
        for pred in list(old.links_from):
            new.link_from(pred)
            old.unlink_from(pred)
        for succ in list(old.links_to):
            succ.link_from(new)
            succ.unlink_from(old)
        new.gate_block = old.gate_block
        new.gate_skip = old.gate_skip
        new.ignores_gate = old.ignores_gate
        self.del_ref(old)       # fully orphan it: no init/stats/graph
        old.workflow = None
        return new

    def computing_power(self):
        """Benchmarked device throughput, re-measured at most every 120 s
        (ref AcceleratedWorkflow.computing_power,
        accelerated_units.py:843-858 — the number the reference's master
        used for load balancing; here it feeds observability).  A method,
        not a property: the first call blocks on a jit compile, which must
        never hide behind attribute access."""
        import time as _time
        now = _time.time()
        cached = getattr(self, "_power_cache_", None)
        if cached is not None and now - cached[0] < 120.0:
            return cached[1]
        from veles_tpu.benchmark import DeviceBenchmark
        bench = DeviceBenchmark(None, size=512, repeats=1)
        bench.run()
        self._power_cache_ = (now, bench.computing_power)
        return bench.computing_power

    def checksum(self):
        """SHA1 over the source files defining this workflow's unit
        classes (ref workflow.py:847 — the per-file checksum that guarded
        master/slave version match; the Launcher compares it across
        processes before a multi-host run)."""
        import hashlib
        import inspect
        files = set()
        for u in self._units:
            try:
                f = inspect.getsourcefile(type(u))
            except TypeError:
                f = None
            if f:
                files.add(f)
        digests = []
        for path in files:
            try:
                with open(path, "rb") as f:
                    digests.append(hashlib.sha1(f.read()).hexdigest())
            except OSError:
                pass
        # combine SORTED per-file digests: path-independent, so hosts
        # with different install prefixes but identical bytes agree
        h = hashlib.sha1()
        for d in sorted(digests):
            h.update(d.encode())
        return h.hexdigest()

    def gather_results(self):
        """Collect metrics from every unit exposing ``get_metric_values()``
        (IResultProvider, ref workflow.py:823-845)."""
        results = {}
        for unit in self._units:
            getter = getattr(unit, "get_metric_values", None)
            if getter is not None:
                results.update(getter())
        return results

    def write_results(self, path):
        with open(path, "w") as f:
            json.dump(self.gather_results(), f, indent=2, default=str)

    # ------------------------------------------------------------------ graph
    def generate_graph(self):
        """DOT text of the control graph (ref workflow.py:624)."""
        lines = ["digraph %s {" % self.name.replace(" ", "_")]
        ids = {u: "u%d" % i for i, u in enumerate(self._units)}
        for u, uid in ids.items():
            lines.append('  %s [label="%s"];' % (uid, u.name))
        for u in self._units:
            for dst in u.links_to:
                if dst in ids:
                    lines.append("  %s -> %s;" % (ids[u], ids[dst]))
        lines.append("}")
        return "\n".join(lines)
