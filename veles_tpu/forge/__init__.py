"""Forge — model-zoo for workflow packages (ref veles/forge/: upload /
fetch versioned packages with a manifest; forge_client.py:91,
forge_server.py:462).  The transport is plain HTTP (stdlib http.server /
urllib), storage is a versioned directory tree with a JSON manifest per
model — the reference's git-backed store swapped for content hashes."""

from veles_tpu.forge.client import ForgeClient
from veles_tpu.forge.server import ForgeServer

__all__ = ["ForgeClient", "ForgeServer"]
