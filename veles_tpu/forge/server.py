"""Forge server (ref veles/forge/forge_server.py:462).

Endpoints (all JSON unless noted):
  GET  /service?query=list                  → [{name, versions, …}]
  GET  /service?query=details&name=N        → manifest of one model
  GET  /fetch?name=N[&version=V]            → package bytes (zip)
  POST /upload?name=N&version=V[&description=…]  body = package bytes
Storage: <root>/<name>/<version>/package.zip + <root>/<name>/manifest.json
"""

import hashlib
import json
import os
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from veles_tpu.logger import Logger

_NAME_RE = re.compile(r"^[A-Za-z0-9_.-]{1,64}$")


class ForgeStore(object):
    """Versioned on-disk package store with per-model manifest."""

    def __init__(self, directory):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        # RLock: upload() holds it across manifest read-modify-write;
        # manifest()/fetch() take it too so concurrent HTTP threads never
        # see a torn manifest.json
        self._lock = threading.RLock()

    def _manifest_path(self, name):
        return os.path.join(self.directory, name, "manifest.json")

    def _check_name(self, name):
        if not name or not _NAME_RE.match(name):
            raise ValueError("bad model/version name %r" % (name,))

    def manifest(self, name):
        self._check_name(name)
        with self._lock:
            try:
                with open(self._manifest_path(name)) as f:
                    return json.load(f)
            except FileNotFoundError:
                return None

    def list(self):
        out = []
        for name in sorted(os.listdir(self.directory)):
            if not _NAME_RE.match(name) or \
                    not os.path.isdir(os.path.join(self.directory, name)):
                continue   # stray entry in the store root — not a model
            m = self.manifest(name)
            if m is not None:
                out.append(m)
        return out

    def upload(self, name, version, data, description=None):
        import time
        self._check_name(name)
        self._check_name(version)
        with self._lock:
            vdir = os.path.join(self.directory, name, version)
            os.makedirs(vdir, exist_ok=True)
            with open(os.path.join(vdir, "package.zip"), "wb") as f:
                f.write(data)
            m = self.manifest(name) or {"name": name, "versions": {},
                                        "latest": None}
            # lineage: each version records its parent (the latest at
            # upload time) — the linear history the reference kept in git
            # (ref forge_server.py:462 git-based versioning)
            m["versions"][version] = {
                "description": description,
                "sha1": hashlib.sha1(data).hexdigest(),
                "size": len(data),
                "created": time.time(),
                "parent": m["latest"],
            }
            m["latest"] = version
            with open(self._manifest_path(name), "w") as f:
                json.dump(m, f, indent=2)
            return m

    def put_thumbnail(self, name, version, data):
        self._check_name(name)
        self._check_name(version)
        with self._lock:
            m = self.manifest(name)
            if m is None or version not in m["versions"]:
                raise KeyError("no version %r of %r" % (version, name))
            vdir = os.path.join(self.directory, name, version)
            with open(os.path.join(vdir, "thumbnail.png"), "wb") as f:
                f.write(data)
            m["versions"][version]["thumbnail"] = True
            with open(self._manifest_path(name), "w") as f:
                json.dump(m, f, indent=2)
            return m

    def thumbnail(self, name, version=None):
        with self._lock:
            m = self.manifest(name)
            if m is None:
                raise KeyError("no such model %r" % name)
            version = version or m["latest"]
            self._check_name(version)
            path = os.path.join(self.directory, name, version,
                                "thumbnail.png")
            if not os.path.exists(path):
                raise KeyError("no thumbnail for %s:%s" % (name, version))
            with open(path, "rb") as f:
                return f.read(), version

    def history(self, name):
        """Version lineage, newest first (walks parent links)."""
        m = self.manifest(name)
        if m is None:
            raise KeyError("no such model %r" % name)
        out, version = [], m["latest"]
        seen = set()
        while version is not None and version not in seen:
            seen.add(version)
            entry = dict(m["versions"][version], version=version)
            out.append(entry)
            version = entry.get("parent")
        return out

    def fetch(self, name, version=None):
        with self._lock:
            m = self.manifest(name)
            if m is None:
                raise KeyError("no such model %r" % name)
            version = version or m["latest"]
            self._check_name(version)
            if version not in m["versions"]:
                raise KeyError("no version %r of %r" % (version, name))
            with open(os.path.join(self.directory, name, version,
                                   "package.zip"), "rb") as f:
                return f.read(), version


class _Handler(BaseHTTPRequestHandler):
    store = None   # set by ForgeServer

    def log_message(self, fmt, *args):   # keep test output quiet
        import logging
        logging.getLogger("ForgeServer").debug("http: " + fmt % args)

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code, message):
        self._json({"error": message}, code)

    def _html_index(self):
        """Minimal forge site (ref the node.js forge web UI,
        web/build.sh + forge_server.py:462): model table with versions,
        descriptions and thumbnails."""
        import html
        rows = []
        for m in self.store.list():
            name = html.escape(m["name"])
            latest = m.get("latest") or ""
            v = m["versions"].get(latest, {})
            thumb = ("<img src='/thumbnail?name=%s' width='48'>" % name
                     if v.get("thumbnail") else "")
            rows.append(
                "<tr><td>%s</td><td>%s</td><td>%d</td><td>%s</td>"
                "<td>%s</td><td><a href='/fetch?name=%s'>zip</a> "
                "<a href='/service?query=history&amp;name=%s'>history</a>"
                "</td></tr>"
                % (thumb, name, len(m["versions"]),
                   html.escape(str(latest)),
                   html.escape(str(v.get("description") or "")),
                   name, name))
        body = ("<!doctype html><html><head><meta charset='utf-8'>"
                "<title>veles_tpu forge</title></head><body>"
                "<h1>veles_tpu model forge</h1>"
                "<table border='1' cellpadding='4'>"
                "<tr><th></th><th>model</th><th>versions</th>"
                "<th>latest</th><th>description</th><th></th></tr>"
                "%s</table></body></html>" % "".join(rows)).encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        url = urllib.parse.urlparse(self.path)
        q = dict(urllib.parse.parse_qsl(url.query))
        try:
            if url.path in ("/", "/index.html"):
                return self._html_index()
            if url.path == "/service":
                query = q.get("query", "list")
                if query == "list":
                    return self._json(self.store.list())
                if query == "details":
                    m = self.store.manifest(q["name"])
                    if m is None:
                        return self._error(404, "no such model")
                    return self._json(m)
                if query == "history":
                    return self._json(self.store.history(q["name"]))
                return self._error(400, "unknown query %r" % query)
            if url.path == "/thumbnail":
                data, version = self.store.thumbnail(q["name"],
                                                     q.get("version"))
                self.send_response(200)
                self.send_header("Content-Type", "image/png")
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-Forge-Version", version)
                self.end_headers()
                self.wfile.write(data)
                return
            if url.path == "/fetch":
                data, version = self.store.fetch(q["name"], q.get("version"))
                self.send_response(200)
                self.send_header("Content-Type", "application/zip")
                self.send_header("Content-Length", str(len(data)))
                self.send_header("X-Forge-Version", version)
                self.end_headers()
                self.wfile.write(data)
                return
            return self._error(404, "unknown path")
        except (KeyError, ValueError) as e:
            return self._error(404 if isinstance(e, KeyError) else 400,
                               str(e))

    def do_POST(self):
        url = urllib.parse.urlparse(self.path)
        q = dict(urllib.parse.parse_qsl(url.query))
        if url.path not in ("/upload", "/thumbnail"):
            return self._error(404, "unknown path")
        try:
            length = int(self.headers.get("Content-Length", 0))
            data = self.rfile.read(length)
            if url.path == "/upload":
                m = self.store.upload(q["name"], q["version"], data,
                                      q.get("description"))
            else:
                m = self.store.put_thumbnail(q["name"], q["version"], data)
            return self._json(m)
        except (KeyError, ValueError) as e:
            return self._error(400, str(e))


class ForgeServer(Logger):
    def __init__(self, directory, host="127.0.0.1", port=0, **kwargs):
        super(ForgeServer, self).__init__(**kwargs)
        self.store = ForgeStore(directory)
        handler = type("BoundHandler", (_Handler,), {"store": self.store})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread = None

    @property
    def url(self):
        return "http://%s:%d" % (self.httpd.server_address[0], self.port)

    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self.info("forge server at %s", self.url)
        return self

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
