"""Forge client (ref veles/forge/forge_client.py:91): list / details /
upload / fetch of workflow packages against a ForgeServer over HTTP."""

import json
import urllib.parse
import urllib.request

from veles_tpu.logger import Logger


def make_thumbnail(package_path, size=128):
    """Render a PNG preview of a model package: the first weight tensor
    reshaped to a square grayscale tile (what the reference's forge site
    showed per model).  Returns PNG bytes, or None when the package has
    no arrays."""
    import io

    import numpy as np
    from PIL import Image

    from veles_tpu.services.export import import_workflow

    try:
        manifest, arrays = import_workflow(package_path)
    except Exception:   # not an export package — upload proceeds bare
        return None
    for unit in manifest["units"]:
        fname = unit["arrays"].get("weights")
        if fname is None:
            continue
        w = np.asarray(arrays[fname], np.float32)
        flat = w.ravel()
        side = int(np.floor(np.sqrt(flat.size)))
        if side < 2:
            continue
        tile = flat[:side * side].reshape(side, side)
        lo, hi = float(tile.min()), float(tile.max())
        tile = (tile - lo) / (hi - lo) if hi > lo else tile * 0
        img = Image.fromarray((tile * 255).astype(np.uint8), "L")
        img = img.resize((size, size), Image.NEAREST)
        buf = io.BytesIO()
        img.save(buf, "PNG")
        return buf.getvalue()
    return None


class ForgeClient(Logger):
    def __init__(self, base_url, **kwargs):
        super(ForgeClient, self).__init__(**kwargs)
        self.base_url = base_url.rstrip("/")

    def _get_json(self, path, **params):
        url = "%s%s?%s" % (self.base_url, path,
                           urllib.parse.urlencode(params))
        with urllib.request.urlopen(url) as resp:
            return json.loads(resp.read().decode())

    def list(self):
        return self._get_json("/service", query="list")

    def details(self, name):
        return self._get_json("/service", query="details", name=name)

    def upload(self, package_path, name, version, description=None,
               thumbnail=True):
        """Upload a package; with ``thumbnail=True`` a PNG rendered from
        the package's first weight tensor is attached (ref forge
        thumbnails, forge_server.py:462).  ``thumbnail`` may also be a
        path to a ready-made PNG."""
        with open(package_path, "rb") as f:
            data = f.read()
        params = {"name": name, "version": version}
        if description:
            params["description"] = description
        url = "%s/upload?%s" % (self.base_url, urllib.parse.urlencode(params))
        req = urllib.request.Request(
            url, data=data, method="POST",
            headers={"Content-Type": "application/zip"})
        with urllib.request.urlopen(req) as resp:
            manifest = json.loads(resp.read().decode())
        self.info("uploaded %s:%s (%d bytes)", name, version, len(data))
        png = None
        if thumbnail is True:
            png = make_thumbnail(package_path)
        elif thumbnail:
            with open(thumbnail, "rb") as f:
                png = f.read()
        if png:
            # best-effort: the package upload already succeeded — a forge
            # server without the thumbnail endpoint must not fail it
            try:
                turl = "%s/thumbnail?%s" % (
                    self.base_url, urllib.parse.urlencode(
                        {"name": name, "version": version}))
                treq = urllib.request.Request(
                    turl, data=png, method="POST",
                    headers={"Content-Type": "image/png"})
                with urllib.request.urlopen(treq) as resp:
                    manifest = json.loads(resp.read().decode())
            except Exception as e:   # noqa: BLE001 — old server/network
                self.warning("thumbnail upload skipped: %s", e)
        return manifest

    def history(self, name):
        """Version lineage newest-first (the reference kept this in git;
        here it is the manifest's parent chain)."""
        return self._get_json("/service", query="history", name=name)

    def fetch_thumbnail(self, name, dest_path, version=None):
        params = {"name": name}
        if version:
            params["version"] = version
        url = "%s/thumbnail?%s" % (self.base_url,
                                   urllib.parse.urlencode(params))
        with urllib.request.urlopen(url) as resp:
            data = resp.read()
        with open(dest_path, "wb") as f:
            f.write(data)
        return dest_path

    def fetch(self, name, dest_path, version=None):
        params = {"name": name}
        if version:
            params["version"] = version
        url = "%s/fetch?%s" % (self.base_url, urllib.parse.urlencode(params))
        with urllib.request.urlopen(url) as resp:
            data = resp.read()
            got_version = resp.headers.get("X-Forge-Version")
        with open(dest_path, "wb") as f:
            f.write(data)
        self.info("fetched %s:%s → %s", name, got_version, dest_path)
        return dest_path, got_version


def main(argv=None):
    """``python -m veles_tpu.forge.client`` — the `veles forge` subcommand
    surface (ref __main__.py:230-241): list / details / upload / fetch,
    plus `serve` to run a store."""
    import argparse
    p = argparse.ArgumentParser(description="veles_tpu model forge")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("list", "details", "history", "upload", "fetch",
                 "thumbnail"):
        sp = sub.add_parser(name)
        sp.add_argument("--url", required=True, help="forge server URL")
        if name in ("details", "history", "upload", "fetch", "thumbnail"):
            sp.add_argument("name")
        if name == "upload":
            sp.add_argument("package")
            sp.add_argument("version")
            sp.add_argument("--description")
            sp.add_argument("--no-thumbnail", action="store_true")
        if name in ("fetch", "thumbnail"):
            sp.add_argument("dest")
            sp.add_argument("--version")
    ps = sub.add_parser("serve")
    ps.add_argument("directory")
    ps.add_argument("--port", type=int, default=8088)
    a = p.parse_args(argv)
    import json as _json
    if a.cmd == "serve":
        from veles_tpu.forge.server import ForgeServer
        srv = ForgeServer(a.directory, port=a.port).start()
        print("forge server at %s (Ctrl-C to stop)" % srv.url)
        try:
            import time
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            srv.stop()
        return 0
    client = ForgeClient(a.url)
    if a.cmd == "list":
        print(_json.dumps(client.list(), indent=2))
    elif a.cmd == "details":
        print(_json.dumps(client.details(a.name), indent=2))
    elif a.cmd == "history":
        print(_json.dumps(client.history(a.name), indent=2))
    elif a.cmd == "upload":
        client.upload(a.package, a.name, a.version, a.description,
                      thumbnail=not a.no_thumbnail)
    elif a.cmd == "fetch":
        dest, ver = client.fetch(a.name, a.dest, a.version)
        print("%s (version %s)" % (dest, ver))
    elif a.cmd == "thumbnail":
        print(client.fetch_thumbnail(a.name, a.dest, a.version))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
