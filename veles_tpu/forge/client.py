"""Forge client (ref veles/forge/forge_client.py:91): list / details /
upload / fetch of workflow packages against a ForgeServer over HTTP."""

import json
import urllib.parse
import urllib.request

from veles_tpu.logger import Logger


class ForgeClient(Logger):
    def __init__(self, base_url, **kwargs):
        super(ForgeClient, self).__init__(**kwargs)
        self.base_url = base_url.rstrip("/")

    def _get_json(self, path, **params):
        url = "%s%s?%s" % (self.base_url, path,
                           urllib.parse.urlencode(params))
        with urllib.request.urlopen(url) as resp:
            return json.loads(resp.read().decode())

    def list(self):
        return self._get_json("/service", query="list")

    def details(self, name):
        return self._get_json("/service", query="details", name=name)

    def upload(self, package_path, name, version, description=None):
        with open(package_path, "rb") as f:
            data = f.read()
        params = {"name": name, "version": version}
        if description:
            params["description"] = description
        url = "%s/upload?%s" % (self.base_url, urllib.parse.urlencode(params))
        req = urllib.request.Request(
            url, data=data, method="POST",
            headers={"Content-Type": "application/zip"})
        with urllib.request.urlopen(req) as resp:
            manifest = json.loads(resp.read().decode())
        self.info("uploaded %s:%s (%d bytes)", name, version, len(data))
        return manifest

    def fetch(self, name, dest_path, version=None):
        params = {"name": name}
        if version:
            params["version"] = version
        url = "%s/fetch?%s" % (self.base_url, urllib.parse.urlencode(params))
        with urllib.request.urlopen(url) as resp:
            data = resp.read()
            got_version = resp.headers.get("X-Forge-Version")
        with open(dest_path, "wb") as f:
            f.write(data)
        self.info("fetched %s:%s → %s", name, got_version, dest_path)
        return dest_path, got_version
