"""Forge client (ref veles/forge/forge_client.py:91): list / details /
upload / fetch of workflow packages against a ForgeServer over HTTP."""

import json
import urllib.parse
import urllib.request

from veles_tpu.logger import Logger


class ForgeClient(Logger):
    def __init__(self, base_url, **kwargs):
        super(ForgeClient, self).__init__(**kwargs)
        self.base_url = base_url.rstrip("/")

    def _get_json(self, path, **params):
        url = "%s%s?%s" % (self.base_url, path,
                           urllib.parse.urlencode(params))
        with urllib.request.urlopen(url) as resp:
            return json.loads(resp.read().decode())

    def list(self):
        return self._get_json("/service", query="list")

    def details(self, name):
        return self._get_json("/service", query="details", name=name)

    def upload(self, package_path, name, version, description=None):
        with open(package_path, "rb") as f:
            data = f.read()
        params = {"name": name, "version": version}
        if description:
            params["description"] = description
        url = "%s/upload?%s" % (self.base_url, urllib.parse.urlencode(params))
        req = urllib.request.Request(
            url, data=data, method="POST",
            headers={"Content-Type": "application/zip"})
        with urllib.request.urlopen(req) as resp:
            manifest = json.loads(resp.read().decode())
        self.info("uploaded %s:%s (%d bytes)", name, version, len(data))
        return manifest

    def fetch(self, name, dest_path, version=None):
        params = {"name": name}
        if version:
            params["version"] = version
        url = "%s/fetch?%s" % (self.base_url, urllib.parse.urlencode(params))
        with urllib.request.urlopen(url) as resp:
            data = resp.read()
            got_version = resp.headers.get("X-Forge-Version")
        with open(dest_path, "wb") as f:
            f.write(data)
        self.info("fetched %s:%s → %s", name, got_version, dest_path)
        return dest_path, got_version


def main(argv=None):
    """``python -m veles_tpu.forge.client`` — the `veles forge` subcommand
    surface (ref __main__.py:230-241): list / details / upload / fetch,
    plus `serve` to run a store."""
    import argparse
    p = argparse.ArgumentParser(description="veles_tpu model forge")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("list", "details", "upload", "fetch"):
        sp = sub.add_parser(name)
        sp.add_argument("--url", required=True, help="forge server URL")
        if name in ("details", "upload", "fetch"):
            sp.add_argument("name")
        if name == "upload":
            sp.add_argument("package")
            sp.add_argument("version")
            sp.add_argument("--description")
        if name == "fetch":
            sp.add_argument("dest")
            sp.add_argument("--version")
    ps = sub.add_parser("serve")
    ps.add_argument("directory")
    ps.add_argument("--port", type=int, default=8088)
    a = p.parse_args(argv)
    import json as _json
    if a.cmd == "serve":
        from veles_tpu.forge.server import ForgeServer
        srv = ForgeServer(a.directory, port=a.port).start()
        print("forge server at %s (Ctrl-C to stop)" % srv.url)
        try:
            import time
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            srv.stop()
        return 0
    client = ForgeClient(a.url)
    if a.cmd == "list":
        print(_json.dumps(client.list(), indent=2))
    elif a.cmd == "details":
        print(_json.dumps(client.details(a.name), indent=2))
    elif a.cmd == "upload":
        client.upload(a.package, a.name, a.version, a.description)
    elif a.cmd == "fetch":
        dest, ver = client.fetch(a.name, a.dest, a.version)
        print("%s (version %s)" % (dest, ver))
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
