"""Layer descriptors — the Znicz layer-type registry
(ref docs/source/manualrst_veles_workflow_creation.rst:107-150 and the unit
inventory in manualrst_veles_workflow_parameters.rst:467-504).

A layer descriptor is pure configuration + three pure functions:
``setup(input_shape)`` infers the static output shape, ``init_params(rng)``
builds the parameter pytree, ``apply(params, x, train, key)`` is the traced
forward.  StandardWorkflow composes them into one jitted step — layers are
*not* units; the per-layer Forward units exist only as introspection
handles.

Config dicts accept both the reference's flat style
(``{"type": "all2all_tanh", "output_sample_shape": 100, "learning_rate":
0.1}``) and its newer split style (``{"type": ..., "->": {forward params},
"<-": {gd params}}``)."""

import math

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu.ops import activations, conv, dropout, linear, lrn, misc, pooling
from veles_tpu.ops.policy import default_policy


def _flatten_config(cfg):
    out = dict(cfg)
    for split_key in ("->", "<-"):
        sub = out.pop(split_key, None)
        if sub:
            out.update(sub)
    return out


class Layer(object):
    """Base descriptor.  Subclasses set TYPES = {registry names}."""

    TYPES = ()
    needs_rng = False      # dropout / stochastic pooling want a key
    has_params = False
    #: apply() receives the WHOLE param tree instead of its own slice —
    #: the seam weight tying uses (TiedLMHead reads the embedding table)
    needs_full_params = False

    def __init__(self, cfg):
        cfg = _flatten_config(cfg)
        self.type = cfg["type"]
        self.cfg = cfg
        self.name = cfg.get("name", self.type)
        # per-layer GD hyperparameters (ref Znicz GD unit kwargs); None
        # falls back to workflow-level defaults in the optimizer.  The
        # key set IS optimizer.DEFAULTS (which includes the *_bias
        # variants) so a new solver knob can never be silently dropped
        # by a stale hand-maintained whitelist.
        from veles_tpu.models import optimizer as _opt
        self.gd = {k: cfg[k] for k in _opt.DEFAULTS if k in cfg}
        self.input_shape = None
        self.output_shape = None
        self.policy = default_policy()

    def setup(self, input_shape):
        self.input_shape = tuple(input_shape)
        self.output_shape = self._infer(self.input_shape)
        return self.output_shape

    def _infer(self, input_shape):
        return input_shape

    def init_params(self, rng):
        return {}

    def param_partition_specs(self, mesh_shape):
        """Optional override of the default (model-axis) parameter
        sharding rule: return a PartitionSpec applied to every param
        leaf, or a partial dict mirroring init_params' structure.  None =
        default rule (parallel.sharding.param_spec)."""
        return None

    def apply(self, params, x, train=False, key=None):
        raise NotImplementedError

    def _activation(self):
        # longest suffix first: "_strict_relu" must not match "_relu"
        for suffix in sorted(activations.ACTIVATIONS, key=len, reverse=True):
            if self.type.endswith("_" + suffix):
                return activations.ACTIVATIONS[suffix]
        return activations.ACTIVATIONS["linear"]


class All2All(Layer):
    """Dense family (ref Znicz All2All*, SURVEY §2.9 "Dense").  ``softmax``
    maps here too: it emits logits; the softmax lives in the evaluator and
    in the serve-time head."""

    TYPES = ("all2all", "all2all_tanh", "all2all_sigmoid", "all2all_relu",
             "all2all_strict_relu", "softmax")
    has_params = True

    def _infer(self, input_shape):
        oss = self.cfg["output_sample_shape"]
        self.n_in = int(math.prod(input_shape))
        if isinstance(oss, int):
            return (oss,)
        return tuple(oss)

    def init_params(self, rng):
        n_out = int(math.prod(self.output_shape))
        params = linear.init_params(
            rng, self.n_in, n_out, bias=self.cfg.get("include_bias", True),
            weights_stddev=self.cfg.get("weights_stddev"),
            dtype=self.policy.param)
        r = int(self.cfg.get("lora_rank", 0))
        if r > 0:
            # LoRA: base W/b freeze (ops.linear stop_gradients them);
            # B = 0 makes the adapted layer exactly the base at init —
            # pair with --warm-start to fine-tune a pretrained model
            # training only these rank-r factors
            params["lora_a"] = jnp.asarray(
                rng.normal(0.0, self.n_in ** -0.5, (self.n_in, r)),
                self.policy.param)
            params["lora_b"] = jnp.zeros((r, n_out), self.policy.param)
        return params

    def apply(self, params, x, train=False, key=None):
        y = linear.forward(params, x, self.policy)
        y = self._activation()(y)
        return y.reshape((x.shape[0],) + self.output_shape)


class Conv(Layer):
    """Conv family (ref Znicz Conv*).  NHWC; ``sliding``=(sy, sx) stride;
    ``padding``=(top, left, bottom, right) explicit pixels."""

    TYPES = ("conv", "conv_tanh", "conv_sigmoid", "conv_relu",
             "conv_strict_relu")
    has_params = True

    def _infer(self, input_shape):
        h, w, c = input_shape
        self.kx = int(self.cfg["kx"])
        self.ky = int(self.cfg["ky"])
        self.n_kernels = int(self.cfg["n_kernels"])
        self.stride = tuple(self.cfg.get("sliding", (1, 1)))
        self.padding = tuple(self.cfg.get("padding", (0, 0, 0, 0)))
        pt, pl, pb, pr = self.padding
        ho = (h + pt + pb - self.ky) // self.stride[0] + 1
        wo = (w + pl + pr - self.kx) // self.stride[1] + 1
        self.n_channels = c
        return (ho, wo, self.n_kernels)

    def init_params(self, rng):
        return conv.init_params(
            rng, self.kx, self.ky, self.n_channels, self.n_kernels,
            bias=self.cfg.get("include_bias", True),
            weights_stddev=self.cfg.get("weights_stddev"),
            dtype=self.policy.param)

    def apply(self, params, x, train=False, key=None):
        y = conv.forward(params, x, self.stride, self.padding, self.policy)
        return self._activation()(y)


class Deconv(Layer):
    """Transposed conv (ref Znicz Deconv — conv-autoencoder decoder)."""

    TYPES = ("deconv", "deconv_tanh", "deconv_sigmoid", "deconv_relu")
    has_params = True

    def _infer(self, input_shape):
        h, w, c = input_shape
        self.kx = int(self.cfg["kx"])
        self.ky = int(self.cfg["ky"])
        self.n_kernels = int(self.cfg["n_kernels"])
        self.stride = tuple(self.cfg.get("sliding", (1, 1)))
        self.n_channels = c
        ho = (h - 1) * self.stride[0] + self.ky
        wo = (w - 1) * self.stride[1] + self.kx
        return (ho, wo, self.n_kernels)

    def init_params(self, rng):
        return conv.init_params(
            rng, self.kx, self.ky, self.n_channels, self.n_kernels,
            bias=self.cfg.get("include_bias", True),
            weights_stddev=self.cfg.get("weights_stddev"),
            dtype=self.policy.param)

    def apply(self, params, x, train=False, key=None):
        y = conv.deconv_forward(params, x, self.stride, "VALID", self.policy)
        return self._activation()(y)


class Pooling(Layer):
    TYPES = ("max_pooling", "avg_pooling", "maxabs_pooling",
             "stochastic_pooling", "stochastic_abs_pooling")

    @property
    def needs_rng(self):
        return self.type.startswith("stochastic")

    def _infer(self, input_shape):
        h, w, c = input_shape
        self.kx = int(self.cfg["kx"])
        self.ky = int(self.cfg["ky"])
        self.stride = tuple(self.cfg.get("sliding", (self.ky, self.kx)))
        ho = (h - self.ky) // self.stride[0] + 1
        wo = (w - self.kx) // self.stride[1] + 1
        return (ho, wo, c)

    def apply(self, params, x, train=False, key=None):
        if self.type == "max_pooling":
            return pooling.max_pool(x, self.ky, self.kx, self.stride)
        if self.type == "avg_pooling":
            return pooling.avg_pool(x, self.ky, self.kx, self.stride)
        if self.type == "maxabs_pooling":
            return pooling.max_abs_pool(x, self.ky, self.kx, self.stride)
        absolute = self.type == "stochastic_abs_pooling"
        if train:
            return pooling.stochastic_pool(x, self.ky, self.kx, key,
                                           self.stride, absolute)
        return pooling.stochastic_pool_infer(x, self.ky, self.kx,
                                             self.stride, absolute)


class Depooling(Layer):
    TYPES = ("depooling",)

    def _infer(self, input_shape):
        h, w, c = input_shape
        self.kx = int(self.cfg["kx"])
        self.ky = int(self.cfg["ky"])
        return (h * self.ky, w * self.kx, c)

    def apply(self, params, x, train=False, key=None):
        return pooling.depool(x, self.ky, self.kx)


class StochasticPoolDepool(Layer):
    """Fused stochastic pooling + depooling (ref Znicz
    StochasticPoolingDepooling) — keeps one sampled element per window in
    place, zeroes the rest; shape-preserving."""

    TYPES = ("stochastic_pooling_depooling", "stochastic_abs_pooling_depooling")
    needs_rng = True

    def _infer(self, input_shape):
        self.kx = int(self.cfg["kx"])
        self.ky = int(self.cfg["ky"])
        return input_shape

    def apply(self, params, x, train=False, key=None):
        if not train:
            return x
        absolute = "abs" in self.type
        return pooling.stochastic_pool_depool(x, self.ky, self.kx, key,
                                              absolute)


class ChannelSplitter(Layer):
    """ChannelSplitter (ref Znicz): (H, W, C) samples become (C, H, W, 1) —
    channels move to a leading per-sample axis so downstream per-channel
    branches can vmap/slice; ChannelMerger inverts it."""

    TYPES = ("channel_splitter",)

    def _infer(self, input_shape):
        h, w, c = input_shape
        return (c, h, w, 1)

    def apply(self, params, x, train=False, key=None):
        return jnp.transpose(x, (0, 3, 1, 2))[..., None]


class ChannelMerger(Layer):
    """Inverse of ChannelSplitter: (C, H, W, 1) -> (H, W, C)."""

    TYPES = ("channel_merger",)

    def _infer(self, input_shape):
        c, h, w, _ = input_shape
        return (h, w, c)

    def apply(self, params, x, train=False, key=None):
        return jnp.transpose(x[..., 0], (0, 2, 3, 1))


class ResizableAll2All(All2All):
    """All2All whose output width can change between training stages (ref
    Znicz ResizableAll2All, used when growing autoencoder bottlenecks).
    ``resize(params, new_output, rng)`` returns an updated parameter dict
    preserving the overlapping weight slice; call it *between* jitted
    stages (it changes shapes, so the next stage recompiles)."""

    TYPES = ("resizable_all2all",)

    def resize(self, params, new_output, rng):
        new_out = (int(new_output) if isinstance(new_output, int)
                   else int(math.prod(new_output)))
        self.output_shape = ((new_output,) if isinstance(new_output, int)
                             else tuple(new_output))
        # keep cfg in sync so a later setup()/_infer re-derives this shape
        self.cfg["output_sample_shape"] = new_output
        fresh = linear.init_params(
            rng, self.n_in, new_out, bias="bias" in params,
            weights_stddev=self.cfg.get("weights_stddev"),
            dtype=self.policy.param)
        keep = min(new_out, params["weights"].shape[1])
        w = np.array(fresh["weights"])
        w[:, :keep] = np.asarray(params["weights"])[:, :keep]
        fresh["weights"] = jnp.asarray(w)
        if "bias" in params:
            b = np.array(fresh["bias"])
            b[:keep] = np.asarray(params["bias"])[:keep]
            fresh["bias"] = jnp.asarray(b)
        return fresh


class LRN(Layer):
    """Local response normalization, the "norm" layer type."""

    TYPES = ("norm",)

    def apply(self, params, x, train=False, key=None):
        return lrn.forward(x, self.cfg.get("alpha", 1e-4),
                           self.cfg.get("beta", 0.75),
                           self.cfg.get("n", 15), self.cfg.get("k", 2.0))


class Dropout(Layer):
    TYPES = ("dropout",)
    needs_rng = True

    def apply(self, params, x, train=False, key=None):
        if not train:
            return x
        return dropout.forward(x, key, self.cfg.get("dropout_ratio", 0.5))


class Activation(Layer):
    """Standalone activation units (ref Znicz activation.*)."""

    TYPES = tuple("activation_" + n for n in activations.ACTIVATIONS)

    def apply(self, params, x, train=False, key=None):
        name = self.type[len("activation_"):]
        return activations.ACTIVATIONS[name](x)


class Cutter(Layer):
    TYPES = ("cutter",)

    def _infer(self, input_shape):
        self.oy, self.ox = self.cfg.get("offset", (0, 0))
        self.h, self.w = self.cfg["size"]
        return (self.h, self.w, input_shape[2])

    def apply(self, params, x, train=False, key=None):
        return misc.cut(x, self.oy, self.ox, self.h, self.w)


class LSTM(Layer):
    """LSTM layer over [T, F] samples (ref Veles RNN/LSTM engines).
    ``output_sample_shape`` = hidden units; ``return_sequences`` keeps the
    whole [T, H] output for stacking."""

    TYPES = ("lstm", "rnn_tanh")
    has_params = True

    def _infer(self, input_shape):
        if len(input_shape) != 2:
            raise ValueError("%s wants [T, F] samples, got %s"
                             % (self.type, input_shape))
        self.n_hidden = int(self.cfg["output_sample_shape"])
        self.return_sequences = bool(self.cfg.get("return_sequences",
                                                  False))
        t, f = input_shape
        self.n_in = f
        return ((t, self.n_hidden) if self.return_sequences
                else (self.n_hidden,))

    def init_params(self, rng):
        from veles_tpu.ops import recurrent
        if self.type == "lstm":
            return recurrent.lstm_init(rng, self.n_in, self.n_hidden,
                                       self.policy.param)
        return recurrent.rnn_init(rng, self.n_in, self.n_hidden,
                                  self.policy.param)

    def apply(self, params, x, train=False, key=None):
        from veles_tpu.ops import recurrent
        fn = (recurrent.lstm_forward if self.type == "lstm"
              else recurrent.rnn_forward)
        return fn(params, x, self.policy, self.return_sequences)


class LayerNorm(Layer):
    """Layer normalization over the feature axis (ops.norm)."""

    TYPES = ("layer_norm",)
    has_params = True

    def init_params(self, rng):
        from veles_tpu.ops import norm
        return norm.layer_norm_init((self.input_shape[-1],))

    def apply(self, params, x, train=False, key=None):
        from veles_tpu.ops import norm
        return norm.layer_norm(x, params["gamma"], params["beta"])


class GroupNorm(Layer):
    """Group normalization (Wu & He 2018) over the channel axis —
    batch-size independent, no running statistics, so it fits the
    stateless functional layer contract where batch norm's mutable
    running mean/var cannot.  The modern conv-stack normalizer
    (capability beyond the reference's LRN-era registry).  The
    effective group count is the largest divisor of C <= ``groups``
    (default 32)."""

    TYPES = ("group_norm",)
    has_params = True

    def init_params(self, rng):
        from veles_tpu.ops import norm
        return norm.layer_norm_init((self.input_shape[-1],))

    def apply(self, params, x, train=False, key=None):
        from veles_tpu.ops import norm
        return norm.group_norm(x, params["gamma"], params["beta"],
                               groups=self.cfg.get("groups", 32))


class ConvResidualBlock(Layer):
    """Pre-activation residual conv block (He et al. 2016 "identity
    mappings" v2, with GroupNorm standing in for batch norm so the
    block stays stateless): gn→relu→conv3×3 → gn→relu→conv3×3, added
    to the skip path.  ``n_kernels`` sets the output channels (default:
    keep input channels); ``sliding`` strides the FIRST conv, and a
    stride or channel change routes the skip through a 1×1 projection.
    Composite like TransformerBlock — residual conv families (ResNet)
    are capability beyond the reference's 2015-era registry."""

    TYPES = ("conv_residual_block",)
    has_params = True

    def _infer(self, input_shape):
        h, w, c = input_shape
        self.n_kernels = int(self.cfg.get("n_kernels", c))
        self.stride = tuple(self.cfg.get("sliding", (1, 1)))
        # same default as the standalone group_norm layer; the op
        # degrades to the largest divisor of C automatically
        self.groups = int(self.cfg.get("groups", 32))
        self.n_channels = c
        sy, sx = self.stride
        # both convs are 3x3 SAME (padding 1); only the first strides
        ho = (h + 2 - 3) // sy + 1
        wo = (w + 2 - 3) // sx + 1
        self.needs_proj = self.stride != (1, 1) or self.n_kernels != c
        return (ho, wo, self.n_kernels)

    def init_params(self, rng):
        from veles_tpu.ops import norm
        c, k = self.n_channels, self.n_kernels
        params = {
            "gn1": norm.layer_norm_init((c,)),
            "conv1": conv.init_params(rng, 3, 3, c, k,
                                      dtype=self.policy.param),
            "gn2": norm.layer_norm_init((k,)),
            "conv2": conv.init_params(rng, 3, 3, k, k,
                                      dtype=self.policy.param),
        }
        if self.needs_proj:
            params["proj"] = conv.init_params(
                rng, 1, 1, c, k, bias=False, dtype=self.policy.param)
        return params

    def apply(self, params, x, train=False, key=None):
        from veles_tpu.ops import activations, norm
        relu = activations.ACTIVATIONS["strict_relu"]
        h = relu(norm.group_norm(x, params["gn1"]["gamma"],
                                 params["gn1"]["beta"],
                                 groups=self.groups))
        h = conv.forward(params["conv1"], h, self.stride, (1, 1, 1, 1),
                         self.policy)
        h = relu(norm.group_norm(h, params["gn2"]["gamma"],
                                 params["gn2"]["beta"],
                                 groups=self.groups))
        h = conv.forward(params["conv2"], h, (1, 1), (1, 1, 1, 1),
                         self.policy)
        skip = x
        if self.needs_proj:
            # 1x1 strided projection aligns shape AND resolution
            skip = conv.forward(params["proj"], x, self.stride,
                                (0, 0, 0, 0), self.policy)
        return h + skip


class Embedding(Layer):
    """Token embedding: int ids [T] → [T, d_model]."""

    TYPES = ("embedding",)
    has_params = True

    def _infer(self, input_shape):
        self.vocab = int(self.cfg["vocab_size"])
        self.d_model = int(self.cfg["d_model"])
        return tuple(input_shape) + (self.d_model,)

    def init_params(self, rng):
        import jax.numpy as jnp
        std = self.cfg.get("weights_stddev")
        if std is None:
            std = self.d_model ** -0.5
        table = rng.normal(0.0, std, (self.vocab, self.d_model))
        return {"table": jnp.asarray(table, self.policy.param)}

    def apply(self, params, x, train=False, key=None):
        return jnp.take(params["table"], x.astype(jnp.int32), axis=0)


class PositionalEncoding(Layer):
    """Add position information to [T, F] activations: ``learned`` table
    or fixed sinusoidal (default) — without this a pooled transformer is
    permutation-invariant over time."""

    TYPES = ("positional_encoding",)

    def _infer(self, input_shape):
        self.learned = bool(self.cfg.get("learned", False))
        return tuple(input_shape)

    @property
    def has_params(self):
        return self.learned

    def init_params(self, rng):
        if not self.learned:
            return {}
        t, f = self.input_shape
        return {"pos": jnp.asarray(rng.normal(0.0, 0.02, (t, f)),
                                   self.policy.param)}

    def _sinusoid(self):
        import numpy as np
        t, f = self.input_shape
        pos = np.arange(t)[:, None]
        i = np.arange(f)[None, :]
        angle = pos / np.power(10000.0, (2 * (i // 2)) / f)
        pe = np.where(i % 2 == 0, np.sin(angle), np.cos(angle))
        return jnp.asarray(pe, jnp.float32)

    def apply(self, params, x, train=False, key=None):
        pe = params["pos"] if self.learned else self._sinusoid()
        return x + pe.astype(x.dtype)


def _seq_parallel_attn_fn(layer):
    """impl="ring"/"ulysses": core attention runs sequence-parallel over
    the mesh's ``seq`` axis (parallel.ring — ring attention rotates k/v
    shards over ICI; Ulysses all-to-alls to head sharding).  The trainer
    injects ``layer.mesh`` when its mesh has a ``seq`` axis."""
    impl = layer.cfg.get("impl", "blockwise")
    if impl not in ("ring", "ulysses"):
        return None
    if getattr(layer, "mesh", None) is None or \
            "seq" not in layer.mesh.shape:
        raise ValueError(
            "impl=%r needs sequence parallelism: pass the trainer a "
            "mesh_config whose mesh has a 'seq' axis" % impl)
    from veles_tpu.parallel import ring as seqpar
    fn = (seqpar.ring_attention_sharded if impl == "ring"
          else seqpar.ulysses_attention_sharded)
    mesh = layer.mesh

    def attn(q, k, v, causal=False):
        return fn(q, k, v, mesh, causal=causal)
    return attn


class MultiHeadAttention(Layer):
    """Self-attention over [T, F] samples (ops.attention).  ``impl``
    selects naive / blockwise / flash (Pallas) / ring / ulysses (the
    sequence-parallel paths); causal via ``causal``."""

    TYPES = ("multihead_attention",)
    has_params = True
    mesh = None   # injected by the trainer for impl=ring/ulysses

    def _infer(self, input_shape):
        t, f = input_shape
        self.n_heads = int(self.cfg.get("n_heads", 8))
        self.n_kv_heads = int(self.cfg.get("n_kv_heads", self.n_heads))
        if f % self.n_heads:
            raise ValueError("d_model %d %% n_heads %d != 0"
                             % (f, self.n_heads))
        return (t, f)

    def init_params(self, rng):
        from veles_tpu.ops import attention
        return attention.mha_init(rng, self.input_shape[-1], self.n_heads,
                                  self.policy.param,
                                  n_kv_heads=self.n_kv_heads)

    def apply(self, params, x, train=False, key=None):
        from veles_tpu.ops import attention
        return attention.mha_forward(
            params, x, self.n_heads,
            causal=bool(self.cfg.get("causal", False)),
            impl=self.cfg.get("impl", "blockwise"),
            attn_fn=_seq_parallel_attn_fn(self), policy=self.policy,
            n_kv_heads=self.n_kv_heads,
            use_rope=bool(self.cfg.get("rope", False)),
            window=self.cfg.get("window"))


class MoE(Layer):
    """Position-wise mixture-of-experts feed-forward over [T, D] samples
    (ops.moe — GShard/Switch dense-dispatch MoE).  With a mesh carrying an
    ``expert`` axis (trainer-injected), experts run expert-parallel via
    all_to_all; otherwise all experts compute locally.  The router's
    load-balancing loss lands in ``last_aux`` and is added to the
    training loss scaled by ``aux_weight``."""

    TYPES = ("moe",)
    has_params = True
    mesh = None   # injected by the trainer when the mesh has 'expert'

    def _infer(self, input_shape):
        t, f = input_shape
        self.n_experts = int(self.cfg.get("n_experts", 8))
        self.d_ff = int(self.cfg.get("d_ff", 4 * f))
        self.top_k = int(self.cfg.get("top_k", 2))
        self.capacity_factor = float(self.cfg.get("capacity_factor", 2.0))
        self.last_aux = None
        return (t, f)

    def init_params(self, rng):
        from veles_tpu.ops import moe as moe_ops
        return moe_ops.moe_init(rng, self.input_shape[-1], self.d_ff,
                                self.n_experts, self.policy.param)

    def param_partition_specs(self, mesh_shape):
        if "expert" not in mesh_shape:
            return None
        from jax.sharding import PartitionSpec as P
        e = P("expert")
        return {"router": P(), "w1": e, "b1": e, "w2": e, "b2": e}

    def apply(self, params, x, train=False, key=None):
        from veles_tpu.ops import moe as moe_ops
        if self.mesh is not None and "expert" in self.mesh.shape:
            y, aux = moe_ops.moe_forward_sharded(
                params, x, self.mesh, top_k=self.top_k,
                capacity_factor=self.capacity_factor, policy=self.policy)
        else:
            y, aux = moe_ops.moe_forward(
                params, x, top_k=self.top_k,
                capacity_factor=self.capacity_factor, policy=self.policy)
        self.last_aux = aux
        return y


class TransformerBlock(Layer):
    """Pre-LN transformer block: LN→MHA→residual, LN→MLP(gelu)→residual.
    ``impl`` as in MultiHeadAttention; optional dropout on both branches.
    ``n_experts`` > 0 swaps the dense MLP for a mixture-of-experts FFN
    (ops.moe), expert-parallel when the mesh has an ``expert`` axis."""

    TYPES = ("transformer_block",)
    has_params = True
    mesh = None   # injected by the trainer for impl=ring/ulysses / moe

    @property
    def needs_rng(self):
        return self.cfg.get("dropout_ratio", 0.0) > 0.0

    def _infer(self, input_shape):
        t, f = input_shape
        self.n_heads = int(self.cfg.get("n_heads", 8))
        self.n_kv_heads = int(self.cfg.get("n_kv_heads", self.n_heads))
        self.d_ff = int(self.cfg.get("d_ff", 4 * f))
        self.n_experts = int(self.cfg.get("n_experts", 0))
        self.last_aux = None
        if self.n_experts:
            # the FFN is a full MoE layer instance — one implementation of
            # the dispatch/fallback logic, shared with the standalone type
            self._moe = MoE({"type": "moe", "n_experts": self.n_experts,
                             "d_ff": self.d_ff,
                             "top_k": self.cfg.get("top_k", 2),
                             "capacity_factor":
                                 self.cfg.get("capacity_factor", 2.0)})
            self._moe.setup(input_shape)
        return (t, f)

    def param_partition_specs(self, mesh_shape):
        if not self.n_experts:
            return None
        sub = self._moe.param_partition_specs(mesh_shape)
        return None if sub is None else {"moe": sub}

    def init_params(self, rng):
        from veles_tpu.ops import attention, norm
        f = self.input_shape[-1]
        std = f ** -0.5
        params = {
            "ln1": norm.layer_norm_init((f,)),
            "mha": attention.mha_init(rng, f, self.n_heads,
                                      self.policy.param,
                                      n_kv_heads=self.n_kv_heads),
            "ln2": norm.layer_norm_init((f,)),
        }
        if self.n_experts:
            params["moe"] = self._moe.init_params(rng)
        else:
            params.update({
                "w1": jnp.asarray(rng.normal(0.0, std, (f, self.d_ff)),
                                  self.policy.param),
                "b1": jnp.zeros((self.d_ff,), self.policy.param),
                "w2": jnp.asarray(rng.normal(0.0, self.d_ff ** -0.5,
                                             (self.d_ff, f)),
                                  self.policy.param),
                "b2": jnp.zeros((f,), self.policy.param),
            })
        r = int(self.cfg.get("lora_rank", 0))
        if r > 0:
            # LoRA q/v adapters (Hu et al. 2021): rank-r factors added
            # to the attention's q and v projections; qb/vb start at
            # ZERO so the adapted block computes exactly the base.
            # At train time apply() freezes every base leaf — pair
            # with --warm-start to fine-tune a pretrained checkpoint
            # updating only ~2·2·f·r params per block.
            d_kv = (f // self.n_heads) * self.n_kv_heads
            params["mha"]["lora"] = {
                "qa": jnp.asarray(rng.normal(0.0, std, (f, r)),
                                  self.policy.param),
                "qb": jnp.zeros((r, f), self.policy.param),
                "va": jnp.asarray(rng.normal(0.0, std, (f, r)),
                                  self.policy.param),
                "vb": jnp.zeros((r, d_kv), self.policy.param),
            }
        return params

    @staticmethod
    def _lora_freeze(params):
        """stop_gradient every base leaf, keeping only the lora subtree
        trainable (the standard LoRA contract)."""
        lora = params["mha"]["lora"]
        base = {k: ({mk: mv for mk, mv in v.items() if mk != "lora"}
                    if k == "mha" else v)
                for k, v in params.items()}
        frozen = jax.tree_util.tree_map(jax.lax.stop_gradient, base)
        frozen["mha"]["lora"] = lora
        return frozen

    def apply(self, params, x, train=False, key=None):
        from veles_tpu.ops import attention, norm
        if train and "lora" in params.get("mha", {}):
            params = self._lora_freeze(params)
        ratio = self.cfg.get("dropout_ratio", 0.0)
        k1 = k2 = None
        if train and ratio > 0.0 and key is not None:
            k1, k2 = jax.random.split(key)
        h = norm.layer_norm(x, params["ln1"]["gamma"], params["ln1"]["beta"])
        h = attention.mha_forward(
            params["mha"], h, self.n_heads,
            causal=bool(self.cfg.get("causal", False)),
            impl=self.cfg.get("impl", "blockwise"),
            attn_fn=_seq_parallel_attn_fn(self), policy=self.policy,
            n_kv_heads=self.n_kv_heads,
            use_rope=bool(self.cfg.get("rope", False)),
            window=self.cfg.get("window"))
        if k1 is not None:
            h = dropout.forward(h, k1, ratio)
        x = x + h
        h = norm.layer_norm(x, params["ln2"]["gamma"], params["ln2"]["beta"])
        h = self._ffn(params, h, train)
        if k2 is not None:
            h = dropout.forward(h, k2, ratio)
        return x + h

    def _ffn(self, params, h, train):
        """The post-LN branch, shared by apply() and step() so training
        and incremental decoding can never diverge.  MoE: the router aux
        loss lands in self.last_aux unconditionally — eval loss includes
        it, same as the standalone ``moe`` layer type."""
        if self.n_experts:
            self._moe.mesh = self.mesh
            h = self._moe.apply(params["moe"], h, train=train)
            self.last_aux = self._moe.last_aux
            self._moe.last_aux = None
            return h
        h = jax.nn.gelu(linear.matmul(h, params["w1"], self.policy)
                        + params["b1"])
        return linear.matmul(h, params["w2"], self.policy) + params["b2"]

    def _cached_attn_block(self, params, x, attn_call):
        """Shared serve-time block body (step + prefill — they must
        never diverge): LN → cached attention → residual, LN → FFN →
        residual.  ``attn_call(h) -> (h, cache_k, cache_v)``."""
        from veles_tpu.ops import norm
        h = norm.layer_norm(x, params["ln1"]["gamma"],
                            params["ln1"]["beta"])
        h, cache_k, cache_v = attn_call(h)
        x = x + h
        h = norm.layer_norm(x, params["ln2"]["gamma"],
                            params["ln2"]["beta"])
        return x + self._ffn(params, h, train=False), cache_k, cache_v

    def step(self, params, x, cache_k, cache_v, pos):
        """Incremental-decoding step: x [B, 1, F] at position ``pos``
        against the block's KV cache (models.generate).  Dropout off
        (serve time); MoE FFN works unchanged on the single position."""
        from veles_tpu.ops import attention
        return self._cached_attn_block(
            params, x,
            lambda h: attention.mha_step(
                params["mha"], h, cache_k, cache_v, pos, self.n_heads,
                n_kv_heads=self.n_kv_heads, policy=self.policy,
                use_rope=bool(self.cfg.get("rope", False)),
                window=self.cfg.get("window")))

    def step_paged(self, params, x, pool_k, pool_v, table, pos):
        """Incremental-decoding step against a PAGED KV pool: x
        [B, 1, F], every row at its own position ``pos[b]`` (the
        continuous batcher's fused path — attention.mha_step_paged
        reads the shared block pool through the table instead of a
        gathered dense view).  Same block body as step() via
        _cached_attn_block, so the two can never diverge."""
        from veles_tpu.ops import attention
        if self.cfg.get("window"):
            raise ValueError("step_paged does not support sliding-"
                             "window attention (rolling caches are "
                             "not pageable)")
        return self._cached_attn_block(
            params, x,
            lambda h: attention.mha_step_paged(
                params["mha"], h, pool_k, pool_v, table, pos,
                self.n_heads, n_kv_heads=self.n_kv_heads,
                policy=self.policy,
                use_rope=bool(self.cfg.get("rope", False))))

    def prefill(self, params, x, cache_k, cache_v):
        """Chunked prefill: the whole prompt chunk x [B, Tp, F] in one
        parallel pass, k/v written into cache positions [0, Tp) —
        equivalent to Tp step() calls at full-forward cost
        (models.generate's serving prefill)."""
        from veles_tpu.ops import attention
        return self._cached_attn_block(
            params, x,
            lambda h: attention.mha_prefill(
                params["mha"], h, cache_k, cache_v, self.n_heads,
                n_kv_heads=self.n_kv_heads, policy=self.policy,
                use_rope=bool(self.cfg.get("rope", False)),
                window=self.cfg.get("window")))

    def chunk_step(self, params, x, cache_k, cache_v, start):
        """K positions [start, start+K) in one parallel pass against
        the existing cache — the speculative-decoding verify step
        (equivalent to K step() calls)."""
        from veles_tpu.ops import attention
        return self._cached_attn_block(
            params, x,
            lambda h: attention.mha_chunk_step(
                params["mha"], h, cache_k, cache_v, start, self.n_heads,
                n_kv_heads=self.n_kv_heads, policy=self.policy,
                use_rope=bool(self.cfg.get("rope", False)),
                window=self.cfg.get("window")))


class PipelinedTransformer(Layer):
    """N identical transformer blocks run as pipeline stages
    (parallel.pipeline — GPipe microbatch schedule over the mesh's
    ``pipe`` axis; sequential ``lax.scan`` over stages without one).
    Stage params stack on a leading [n_blocks, ...] axis so the pipe
    sharding is one PartitionSpec.  Dropout inside pipelined stages is
    unsupported (keys would need per-stage plumbing); keep it in
    surrounding layers."""

    TYPES = ("pipelined_transformer",)
    has_params = True
    mesh = None   # injected by the trainer when the mesh has 'pipe'

    def _infer(self, input_shape):
        t, f = input_shape
        self.n_blocks = int(self.cfg.get("n_blocks", 2))
        self.n_microbatches = int(self.cfg.get("n_microbatches", 4))
        # forward EVERY TransformerBlock option the caller set (a
        # hand-maintained whitelist silently dropped rope/window/
        # n_kv_heads in past revisions); only the pipeline's own keys
        # and the unsupported dropout are withheld.  Options the
        # pipelined wrapper genuinely cannot honor must FAIL, not
        # silently degrade:
        if int(self.cfg.get("n_experts", 0)):
            raise ValueError(
                "pipelined_transformer does not support MoE stages (the "
                "router aux loss cannot cross the stage scan) — use "
                "transformer_block layers with an 'expert' mesh axis")
        if self.cfg.get("impl") in ("ring", "ulysses"):
            raise ValueError(
                "pipelined_transformer does not support sequence-"
                "parallel attention inside stages — shard the sequence "
                "with plain transformer_block layers instead")
        own = {"type", "n_blocks", "n_microbatches", "dropout_ratio",
               "name"}
        block_cfg = {k: v for k, v in self.cfg.items() if k not in own}
        block_cfg.update({"type": "transformer_block",
                          "dropout_ratio": 0.0})
        # per-stage remat rides the whole pipelined layer: set
        # {"remat": true} on THIS layer and the trainer checkpoints the
        # full stage scan (stages recompute during the backward sweep)
        self._block = TransformerBlock(block_cfg)
        self._block.setup(input_shape)
        return (t, f)

    def init_params(self, rng):
        stages = [self._block.init_params(rng)
                  for _ in range(self.n_blocks)]
        return {"stages": jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *stages)}

    def param_partition_specs(self, mesh_shape):
        if "pipe" not in mesh_shape:
            return None
        from jax.sharding import PartitionSpec as P
        return P("pipe")   # every stacked [S, ...] leaf shards its stage

    def apply(self, params, x, train=False, key=None):
        block = self._block

        def fn(p, h):
            return block.apply(p, h, train=False, key=None)

        if self.mesh is not None and "pipe" in self.mesh.shape:
            from veles_tpu.parallel import pipeline
            # combined data x pipe mesh: keep each data slice's batch
            # rows local to its own pipeline instance
            batch_axis = ("data" if self.mesh.shape.get("data", 1) > 1
                          else None)
            return pipeline.pipeline_apply_sharded(
                fn, params["stages"], x, self.mesh,
                n_microbatches=self.n_microbatches,
                batch_axis=batch_axis)
        h, _ = jax.lax.scan(lambda h, p: (fn(p, h), None), x,
                            params["stages"])
        return h


class TiedLMHead(Layer):
    """LM head that reuses the embedding table transposed
    (``tie_to`` = the embedding layer's name): logits = x @ tableᵀ.
    Weight tying saves vocab×d_model params and regularizes; gradients
    flow to the table through both uses."""

    TYPES = ("tied_lm_head",)
    needs_full_params = True

    def _infer(self, input_shape):
        t, f = input_shape
        self.tie_to = self.cfg["tie_to"]
        self.n_in = f
        self.n_out = int(self.cfg["vocab_size"])
        return (t, self.n_out)

    def apply(self, params, x, train=False, key=None):
        # ``params`` is the FULL tree (needs_full_params)
        table = params[self.tie_to]["table"]        # [vocab, d_model]
        from veles_tpu.ops.quant import (QuantWeight4, is_quant,
                                         quant_matmul_t)
        if isinstance(table, QuantWeight4):
            # nibble-packed: the payload's packed axis is d/2, so the
            # logical shape is (vocab, table.n)
            shape = (table.q.shape[0], table.n)
        elif is_quant(table):
            shape = table.q.shape
        else:
            shape = table.shape
        if shape != (self.n_out, self.n_in):
            raise ValueError("tied table %s does not match head (%d, %d)"
                             % (shape, self.n_out, self.n_in))
        if is_quant(table):
            # quantized serving: the per-ROW table scales are exactly
            # the head's per-output-channel scales (ops.quant)
            return quant_matmul_t(x, table)
        return linear.matmul(x, table.T, self.policy)


class TimestepDense(Layer):
    """Per-timestep dense over [T, F] samples: [B, T, F] → [B, T, out]
    (the transformer projection / LM head; weight shared across time)."""

    TYPES = ("timestep_dense", "timestep_dense_tanh", "timestep_dense_relu")
    has_params = True

    def _infer(self, input_shape):
        t, f = input_shape
        self.n_in = f
        self.n_out = int(self.cfg["output_sample_shape"])
        return (t, self.n_out)

    def init_params(self, rng):
        return linear.init_params(
            rng, self.n_in, self.n_out,
            bias=self.cfg.get("include_bias", True),
            weights_stddev=self.cfg.get("weights_stddev"),
            dtype=self.policy.param)

    def apply(self, params, x, train=False, key=None):
        y = linear.matmul(x, params["weights"], self.policy)
        if "bias" in params:
            y = y + params["bias"].astype(y.dtype)
        return self._activation()(y)


class SeqPool(Layer):
    """Collapse the time axis: mean / max / last (classifier head input)."""

    TYPES = ("seq_pool",)

    def _infer(self, input_shape):
        self.mode = self.cfg.get("mode", "mean")
        return tuple(input_shape[1:])

    def apply(self, params, x, train=False, key=None):
        if self.mode == "mean":
            return jnp.mean(x, axis=1)
        if self.mode == "max":
            return jnp.max(x, axis=1)
        return x[:, -1]


class ZeroFiller(Layer):
    """Weight-mask regularizer: masks the *previous* parametric layer's
    weights after every update (ref Znicz ZeroFiller).  Carries no forward
    compute."""

    TYPES = ("zerofiller",)

    def apply(self, params, x, train=False, key=None):
        return x


LAYER_TYPES = {}
for _cls in (All2All, ResizableAll2All, Conv, Deconv, Pooling, Depooling,
             StochasticPoolDepool, ChannelSplitter, ChannelMerger, LRN,
             Dropout, Activation, Cutter, LSTM, ZeroFiller, LayerNorm,
             GroupNorm, ConvResidualBlock,
             Embedding, PositionalEncoding, MultiHeadAttention, MoE,
             TransformerBlock, PipelinedTransformer, TimestepDense,
             TiedLMHead, SeqPool):
    for _t in _cls.TYPES:
        LAYER_TYPES[_t] = _cls


def make_layer(cfg):
    cfg_flat = _flatten_config(cfg)
    t = cfg_flat["type"]
    if t not in LAYER_TYPES:
        raise KeyError("unknown layer type %r (known: %s)"
                       % (t, ", ".join(sorted(LAYER_TYPES))))
    return LAYER_TYPES[t](cfg)
