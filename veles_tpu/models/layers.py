"""Layer descriptors — the Znicz layer-type registry
(ref docs/source/manualrst_veles_workflow_creation.rst:107-150 and the unit
inventory in manualrst_veles_workflow_parameters.rst:467-504).

A layer descriptor is pure configuration + three pure functions:
``setup(input_shape)`` infers the static output shape, ``init_params(rng)``
builds the parameter pytree, ``apply(params, x, train, key)`` is the traced
forward.  StandardWorkflow composes them into one jitted step — layers are
*not* units; the per-layer Forward units exist only as introspection
handles.

Config dicts accept both the reference's flat style
(``{"type": "all2all_tanh", "output_sample_shape": 100, "learning_rate":
0.1}``) and its newer split style (``{"type": ..., "->": {forward params},
"<-": {gd params}}``)."""

import math

import jax
import jax.numpy as jnp

from veles_tpu.ops import activations, conv, dropout, linear, lrn, misc, pooling
from veles_tpu.ops.policy import default_policy


def _flatten_config(cfg):
    out = dict(cfg)
    for split_key in ("->", "<-"):
        sub = out.pop(split_key, None)
        if sub:
            out.update(sub)
    return out


class Layer(object):
    """Base descriptor.  Subclasses set TYPES = {registry names}."""

    TYPES = ()
    needs_rng = False      # dropout / stochastic pooling want a key
    has_params = False

    def __init__(self, cfg):
        cfg = _flatten_config(cfg)
        self.type = cfg["type"]
        self.cfg = cfg
        self.name = cfg.get("name", self.type)
        # per-layer GD hyperparameters (ref Znicz GD unit kwargs); None
        # falls back to workflow-level defaults in the optimizer
        self.gd = {k: cfg[k] for k in
                   ("learning_rate", "learning_rate_bias", "weights_decay",
                    "weights_decay_bias", "l1_vs_l2", "gradient_moment",
                    "gradient_moment_bias") if k in cfg}
        self.input_shape = None
        self.output_shape = None
        self.policy = default_policy()

    def setup(self, input_shape):
        self.input_shape = tuple(input_shape)
        self.output_shape = self._infer(self.input_shape)
        return self.output_shape

    def _infer(self, input_shape):
        return input_shape

    def init_params(self, rng):
        return {}

    def apply(self, params, x, train=False, key=None):
        raise NotImplementedError

    def _activation(self):
        # longest suffix first: "_strict_relu" must not match "_relu"
        for suffix in sorted(activations.ACTIVATIONS, key=len, reverse=True):
            if self.type.endswith("_" + suffix):
                return activations.ACTIVATIONS[suffix]
        return activations.ACTIVATIONS["linear"]


class All2All(Layer):
    """Dense family (ref Znicz All2All*, SURVEY §2.9 "Dense").  ``softmax``
    maps here too: it emits logits; the softmax lives in the evaluator and
    in the serve-time head."""

    TYPES = ("all2all", "all2all_tanh", "all2all_sigmoid", "all2all_relu",
             "all2all_strict_relu", "softmax")
    has_params = True

    def _infer(self, input_shape):
        oss = self.cfg["output_sample_shape"]
        self.n_in = int(math.prod(input_shape))
        if isinstance(oss, int):
            return (oss,)
        return tuple(oss)

    def init_params(self, rng):
        n_out = int(math.prod(self.output_shape))
        return linear.init_params(
            rng, self.n_in, n_out, bias=self.cfg.get("include_bias", True),
            weights_stddev=self.cfg.get("weights_stddev"),
            dtype=self.policy.param)

    def apply(self, params, x, train=False, key=None):
        y = linear.forward(params, x, self.policy)
        y = self._activation()(y)
        return y.reshape((x.shape[0],) + self.output_shape)


class Conv(Layer):
    """Conv family (ref Znicz Conv*).  NHWC; ``sliding``=(sy, sx) stride;
    ``padding``=(top, left, bottom, right) explicit pixels."""

    TYPES = ("conv", "conv_tanh", "conv_sigmoid", "conv_relu",
             "conv_strict_relu")
    has_params = True

    def _infer(self, input_shape):
        h, w, c = input_shape
        self.kx = int(self.cfg["kx"])
        self.ky = int(self.cfg["ky"])
        self.n_kernels = int(self.cfg["n_kernels"])
        self.stride = tuple(self.cfg.get("sliding", (1, 1)))
        self.padding = tuple(self.cfg.get("padding", (0, 0, 0, 0)))
        pt, pl, pb, pr = self.padding
        ho = (h + pt + pb - self.ky) // self.stride[0] + 1
        wo = (w + pl + pr - self.kx) // self.stride[1] + 1
        self.n_channels = c
        return (ho, wo, self.n_kernels)

    def init_params(self, rng):
        return conv.init_params(
            rng, self.kx, self.ky, self.n_channels, self.n_kernels,
            bias=self.cfg.get("include_bias", True),
            weights_stddev=self.cfg.get("weights_stddev"),
            dtype=self.policy.param)

    def apply(self, params, x, train=False, key=None):
        y = conv.forward(params, x, self.stride, self.padding, self.policy)
        return self._activation()(y)


class Deconv(Layer):
    """Transposed conv (ref Znicz Deconv — conv-autoencoder decoder)."""

    TYPES = ("deconv", "deconv_tanh", "deconv_sigmoid", "deconv_relu")
    has_params = True

    def _infer(self, input_shape):
        h, w, c = input_shape
        self.kx = int(self.cfg["kx"])
        self.ky = int(self.cfg["ky"])
        self.n_kernels = int(self.cfg["n_kernels"])
        self.stride = tuple(self.cfg.get("sliding", (1, 1)))
        self.n_channels = c
        ho = (h - 1) * self.stride[0] + self.ky
        wo = (w - 1) * self.stride[1] + self.kx
        return (ho, wo, self.n_kernels)

    def init_params(self, rng):
        return conv.init_params(
            rng, self.kx, self.ky, self.n_channels, self.n_kernels,
            bias=self.cfg.get("include_bias", True),
            weights_stddev=self.cfg.get("weights_stddev"),
            dtype=self.policy.param)

    def apply(self, params, x, train=False, key=None):
        y = conv.deconv_forward(params, x, self.stride, "VALID", self.policy)
        return self._activation()(y)


class Pooling(Layer):
    TYPES = ("max_pooling", "avg_pooling", "maxabs_pooling",
             "stochastic_pooling", "stochastic_abs_pooling")

    @property
    def needs_rng(self):
        return self.type.startswith("stochastic")

    def _infer(self, input_shape):
        h, w, c = input_shape
        self.kx = int(self.cfg["kx"])
        self.ky = int(self.cfg["ky"])
        self.stride = tuple(self.cfg.get("sliding", (self.ky, self.kx)))
        ho = (h - self.ky) // self.stride[0] + 1
        wo = (w - self.kx) // self.stride[1] + 1
        return (ho, wo, c)

    def apply(self, params, x, train=False, key=None):
        if self.type == "max_pooling":
            return pooling.max_pool(x, self.ky, self.kx, self.stride)
        if self.type == "avg_pooling":
            return pooling.avg_pool(x, self.ky, self.kx, self.stride)
        if self.type == "maxabs_pooling":
            return pooling.max_abs_pool(x, self.ky, self.kx, self.stride)
        absolute = self.type == "stochastic_abs_pooling"
        if train:
            return pooling.stochastic_pool(x, self.ky, self.kx, key,
                                           self.stride, absolute)
        return pooling.stochastic_pool_infer(x, self.ky, self.kx,
                                             self.stride, absolute)


class Depooling(Layer):
    TYPES = ("depooling",)

    def _infer(self, input_shape):
        h, w, c = input_shape
        self.kx = int(self.cfg["kx"])
        self.ky = int(self.cfg["ky"])
        return (h * self.ky, w * self.kx, c)

    def apply(self, params, x, train=False, key=None):
        return pooling.depool(x, self.ky, self.kx)


class LRN(Layer):
    """Local response normalization, the "norm" layer type."""

    TYPES = ("norm",)

    def apply(self, params, x, train=False, key=None):
        return lrn.forward(x, self.cfg.get("alpha", 1e-4),
                           self.cfg.get("beta", 0.75),
                           self.cfg.get("n", 15), self.cfg.get("k", 2.0))


class Dropout(Layer):
    TYPES = ("dropout",)
    needs_rng = True

    def apply(self, params, x, train=False, key=None):
        if not train:
            return x
        return dropout.forward(x, key, self.cfg.get("dropout_ratio", 0.5))


class Activation(Layer):
    """Standalone activation units (ref Znicz activation.*)."""

    TYPES = tuple("activation_" + n for n in activations.ACTIVATIONS)

    def apply(self, params, x, train=False, key=None):
        name = self.type[len("activation_"):]
        return activations.ACTIVATIONS[name](x)


class Cutter(Layer):
    TYPES = ("cutter",)

    def _infer(self, input_shape):
        self.oy, self.ox = self.cfg.get("offset", (0, 0))
        self.h, self.w = self.cfg["size"]
        return (self.h, self.w, input_shape[2])

    def apply(self, params, x, train=False, key=None):
        return misc.cut(x, self.oy, self.ox, self.h, self.w)


class LSTM(Layer):
    """LSTM layer over [T, F] samples (ref Veles RNN/LSTM engines).
    ``output_sample_shape`` = hidden units; ``return_sequences`` keeps the
    whole [T, H] output for stacking."""

    TYPES = ("lstm", "rnn_tanh")
    has_params = True

    def _infer(self, input_shape):
        if len(input_shape) != 2:
            raise ValueError("%s wants [T, F] samples, got %s"
                             % (self.type, input_shape))
        self.n_hidden = int(self.cfg["output_sample_shape"])
        self.return_sequences = bool(self.cfg.get("return_sequences",
                                                  False))
        t, f = input_shape
        self.n_in = f
        return ((t, self.n_hidden) if self.return_sequences
                else (self.n_hidden,))

    def init_params(self, rng):
        from veles_tpu.ops import recurrent
        if self.type == "lstm":
            return recurrent.lstm_init(rng, self.n_in, self.n_hidden,
                                       self.policy.param)
        return recurrent.rnn_init(rng, self.n_in, self.n_hidden,
                                  self.policy.param)

    def apply(self, params, x, train=False, key=None):
        from veles_tpu.ops import recurrent
        fn = (recurrent.lstm_forward if self.type == "lstm"
              else recurrent.rnn_forward)
        return fn(params, x, self.policy, self.return_sequences)


class ZeroFiller(Layer):
    """Weight-mask regularizer: masks the *previous* parametric layer's
    weights after every update (ref Znicz ZeroFiller).  Carries no forward
    compute."""

    TYPES = ("zerofiller",)

    def apply(self, params, x, train=False, key=None):
        return x


LAYER_TYPES = {}
for _cls in (All2All, Conv, Deconv, Pooling, Depooling, LRN, Dropout,
             Activation, Cutter, LSTM, ZeroFiller):
    for _t in _cls.TYPES:
        LAYER_TYPES[_t] = _cls


def make_layer(cfg):
    cfg_flat = _flatten_config(cfg)
    t = cfg_flat["type"]
    if t not in LAYER_TYPES:
        raise KeyError("unknown layer type %r (known: %s)"
                       % (t, ", ".join(sorted(LAYER_TYPES))))
    return LAYER_TYPES[t](cfg)
