"""Autoregressive LM generation with a KV cache (incremental decoding).

Serve-time counterpart of the ``transformer_lm`` zoo stack (embedding →
[positional_encoding] → transformer_block* → layer_norm →
timestep_dense | tied_lm_head).
Each step feeds ONE token through the stack against per-block KV caches
([B, n_kv_heads, T_max, head_dim] — GQA stores only the kv heads, so its
smaller KV state is realized here), inside a single jitted ``lax.scan``
over positions: prefill and generation are the same loop, with the
prompt teacher-forcing the first ``prompt_len`` positions.

The reference served forward passes over REST (restful_api.py:112-217);
generation is the transformer-era equivalent and beyond-parity."""


import collections
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu.ops import attention, norm, quant

#: compiled-executable cache capacity per generator.  Batch size (number
#: of prompt rows) and beam width are both client-controlled on the REST
#: serving path; each distinct value compiles an executable, so the cache
#: must be an LRU, not a grow-forever dict.
COMPILE_CACHE_SIZE = 12

#: shortest prompt length (tokens) at which the chunked-prefill decode
#: path kicks in — below this the one-executable full scan wins on
#: compile count and is cheap anyway
PREFILL_MIN = 32


def _truncate(logits, top_k, top_p):
    """top-k/top-p truncation with TRACED per-row parameters (lax.top_k
    would need a static k) over a sorted-descending view."""
    sl = jnp.sort(logits, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        sl, jnp.clip(top_k - 1, 0, sl.shape[-1] - 1)[:, None], axis=-1)
    k_thresh = jnp.where(top_k[:, None] > 0, kth, -jnp.inf)
    # nucleus: keep the smallest prefix of the distribution whose mass
    # reaches top_p
    ps = jax.nn.softmax(sl, axis=-1)
    keep = (jnp.cumsum(ps, axis=-1) - ps) < top_p[:, None]
    p_thresh = jnp.min(jnp.where(keep, sl, jnp.inf), axis=-1,
                       keepdims=True)
    # per-row escapes: a top_p=1.0 row must behave EXACTLY as if it
    # skipped truncation (f32 cumsum can reach 1.0 early and mask real
    # tail tokens), or coalescing would not be bit-identical to the
    # solo run — mirrors the top_k==0 guard
    p_thresh = jnp.where(top_p[:, None] < 1.0, p_thresh, -jnp.inf)
    return jnp.where((logits >= k_thresh) & (logits >= p_thresh),
                     logits, -1e30)


def _sample(logits, pos, keys, top_k, top_p, inv_temp):
    """Per-row categorical draw keyed on (row seed, position) ONLY — a
    row's randomness never depends on what it was batched with."""
    lg = logits * inv_temp[:, None]
    # plain temperature sampling skips the O(V log V) sort when NO row
    # asks for truncation
    lg = jax.lax.cond(
        jnp.any(top_k > 0) | jnp.any(top_p < 1.0),
        lambda l: _truncate(l, top_k, top_p),
        lambda l: l, lg)
    subs = jax.vmap(jax.random.fold_in)(
        keys, jnp.broadcast_to(pos, (lg.shape[0],)))
    return jax.vmap(jax.random.categorical)(subs, lg).astype(jnp.int32)


def _ngram_draft(row, cursor, kk, ll):
    """Draft ``kk`` candidate tokens for positions cursor+1..cursor+kk:
    copy the continuation of the most recent EARLIER occurrence of the
    last known bigram (row[cursor-1], row[cursor]); fallback = repeat
    from ``cursor``.  Shared by the solo speculative decode
    (LMGenerator._spec_fn, whose loop cursor ``cur`` equals cursor+1)
    and the batcher's speculative tick core — draft quality only
    affects how many positions verify, never which tokens come out,
    but the rule must not silently drift between the two."""
    j = jnp.arange(ll - 1)
    last2 = jax.lax.dynamic_slice(row, (jnp.maximum(cursor - 1, 0),),
                                  (2,))
    match = ((row[:-1] == last2[0]) & (row[1:] == last2[1])
             & (j + 1 < cursor))
    cand = jnp.max(jnp.where(match, j, -1))
    src = jnp.clip(jnp.where(cand >= 0, cand + 2, cursor), 0, ll - kk)
    return jax.lax.dynamic_slice(row, (src,), (kk,))


class LMGenerator:
    """Build from a trained ``transformer_lm`` workflow/trainer:

        gen = LMGenerator(wf.trainer, max_len=128)
        out = gen.generate(prompt_tokens, max_new=32)        # greedy
        out = gen.generate(prompt, max_new=32, temperature=0.8, seed=1)
    """

    def __init__(self, trainer, max_len, cache_dtype=None,
                 mesh_cfg="auto", weights=None, use_ema=False):
        #: ``use_ema=True`` decodes with the trainer's Polyak/EMA weight
        #: average (gd_defaults["ema_decay"]) instead of the live params.
        #: The duck-typed fallback only applies when EMA was NOT asked
        #: for — a use_ema request on a trainer without the API must
        #: fail loudly, never silently serve un-averaged weights.
        self.params = (trainer.serve_params(use_ema)
                       if use_ema or hasattr(trainer, "serve_params")
                       else trainer.params)
        #: ``weights="int8"`` quantizes the serving copy of the params
        #: (ops.quant W8A8-dynamic): attention/FFN/head matrices become
        #: int8 + per-channel scales, the embedding table int8 + per-row
        #: scales — halving decode-time weight HBM traffic vs bf16.
        #: Training params are untouched.
        self.weight_dtype = weights
        self.max_len = int(max_len)
        #: KV-cache storage dtype; default follows the params.  bfloat16
        #: halves serve-time cache memory (keys/values are MXU inputs
        #: anyway; softmax stays f32); "int8" quarters it vs f32 via
        #: per-position symmetric quantization (ops.attention.QuantCache)
        self.cache_dtype = cache_dtype
        self._compiled = collections.OrderedDict()
        self._cache_lock = threading.Lock()
        #: tensor-parallel decode: when the trainer ran under a mesh
        #: (``mesh_cfg="auto"``) or one is passed explicitly, the decode
        #: scan runs against the training shardings — column-parallel
        #: projections, KV caches sharded over the kv-head dim on the
        #: model axis, GSPMD inserting the collectives.  A model trained
        #: with TP/FSDP serves at the size it was trained.  (The
        #: reference only ever served single-process forward passes,
        #: restful_api.py:112-217.)
        if mesh_cfg == "auto":
            mesh_cfg = getattr(trainer, "mesh_config", None)
        self.mesh_cfg = mesh_cfg
        #: per-instance prefill threshold (module default PREFILL_MIN);
        #: tests pin it to force one path or the other
        self.prefill_min = PREFILL_MIN
        layers = trainer.layers
        by_type = {}
        self._blocks = []
        for layer in layers:
            if layer.type == "transformer_block":
                self._blocks.append(layer)
            else:
                by_type.setdefault(layer.type, layer)
        for need in ("embedding", "layer_norm"):
            if need not in by_type:
                raise ValueError(
                    "LMGenerator needs a transformer_lm-shaped stack "
                    "(missing %r; got %s)" % (need,
                                              [l.type for l in layers]))
        if not self._blocks:
            raise ValueError("no transformer_block layers to decode with")
        self._embed = by_type["embedding"]
        self._posenc = by_type.get("positional_encoding")
        self._ln = by_type["layer_norm"]
        self._head = by_type.get("timestep_dense",
                                 by_type.get("tied_lm_head"))
        if self._head is None:
            raise ValueError("LMGenerator needs a timestep_dense or "
                             "tied_lm_head LM head")
        if self._posenc is not None and self.max_len > \
                self._posenc.input_shape[0]:
            raise ValueError(
                "max_len %d exceeds the position table length %d"
                % (self.max_len, self._posenc.input_shape[0]))
        b0 = self._blocks[0]
        self._head_dim = b0.input_shape[-1] // b0.n_heads
        #: sliding-window blocks with window < max_len get a ROLLING
        #: ring-buffer cache of exactly ``window`` slots — serve-time
        #: KV memory is O(window) regardless of context length
        self._rolling = any(
            (layer.cfg.get("window") or self.max_len) < self.max_len
            for layer in self._blocks)
        if self.mesh_cfg is not None and self.mesh_cfg.model_size > 1:
            m = self.mesh_cfg.model_size
            for layer in self._blocks:
                # the KV cache shards its head dim over the model axis,
                # so every block's kv heads must divide the axis size
                if layer.n_kv_heads % m:
                    raise ValueError(
                        "tensor-parallel decode needs n_kv_heads (%d) "
                        "divisible by the model axis size (%d)"
                        % (layer.n_kv_heads, m))
        if self.weight_dtype is not None:
            if self.weight_dtype not in ("bf16", "int8", "w4a8"):
                raise ValueError("weights must be None, 'bf16', 'int8' "
                                 "or 'w4a8', got %r"
                                 % (self.weight_dtype,))
            # weight compression must never shift cache/compute
            # precision — that stays an explicit cache_dtype opt-in
            self._float_dtype = \
                self.params[self._embed.name]["table"].dtype
            if self.weight_dtype == "bf16":
                # training params are often f32; the float decode path
                # already streams a hoisted bf16 cast per step, so this
                # mainly halves RESIDENT param memory (no duplicate
                # f32 input + hoisted bf16 copy) — int8 is what cuts
                # the per-step traffic
                self.params = jax.tree_util.tree_map(
                    lambda a: (a.astype(jnp.bfloat16)
                               if hasattr(a, "dtype")
                               and jnp.issubdtype(a.dtype, jnp.floating)
                               else a), self.params)
            else:                       # int8 / w4a8
                if any(layer.cfg.get("n_experts")
                       for layer in self._blocks):
                    raise ValueError(
                        "%s serving weights do not cover MoE experts "
                        "yet" % self.weight_dtype)
                if self.weight_dtype == "w4a8" and \
                        self.mesh_cfg is not None and \
                        self.mesh_cfg.model_size > 1:
                    # the nibble-packed payload halves the contraction
                    # axis, so the training partition specs no longer
                    # describe it — int8 carries the shardings, w4a8
                    # stays single-device for now
                    raise ValueError(
                        "w4a8 serving weights are single-device for "
                        "now — serve int8 under a model-axis mesh, or "
                        "drop the mesh")
                orig = self.params
                self.params = quant.quantize_lm_params(
                    self.params, embed_name=self._embed.name,
                    scheme=self.weight_dtype)
                if self.mesh_cfg is not None and \
                        self.mesh_cfg.model_size > 1:
                    # tensor-parallel int8: re-place every quantized
                    # leaf explicitly — the int8 payload sharded like
                    # the float weight it replaces (the eager
                    # quantization already computed under that
                    # sharding), the per-channel scales replicated so
                    # the rescale never inserts a collective
                    self.params = self._shard_quant_params(orig,
                                                           self.params)

    # ------------------------------------------------------------------
    def _shard_quant_params(self, orig, qparams):
        """Re-place quantized leaves under the tensor-parallel mesh:
        the payload gets the ORIGINAL weight's sharding (so the int8
        bytes stream exactly where the bf16 bytes did), the scales are
        replicated.  Walks the quantized tree against the pre-quant
        tree — a QuantWeight node's partner is the array it replaced."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        repl = NamedSharding(self.mesh_cfg.mesh, P())

        def place(qv, ov):
            # int8 only: the w4a8 constructor path refuses a model-axis
            # mesh outright (the packed contraction axis invalidates
            # the training specs), so QuantWeight4 never reaches here
            if not isinstance(qv, quant.QuantWeight):
                return qv
            sh = getattr(ov, "sharding", None)
            payload = (jax.device_put(qv.q, sh) if sh is not None
                       else qv.q)
            return quant.QuantWeight(payload,
                                     jax.device_put(qv.scale, repl))

        return jax.tree_util.tree_map(place, qparams, orig,
                                      is_leaf=quant.is_quant)

    def _embed_rows(self, params, idx):
        """Embedding lookup — quantized serving tables gather payload
        rows and dequantize only those (ops.quant.take_rows)."""
        table = params[self._embed.name]["table"]
        if quant.is_quant(table):
            return quant.take_rows(table, idx.astype(jnp.int32))
        return jnp.take(table, idx.astype(jnp.int32), axis=0)

    def _model_dtype(self):
        """Cache/init dtype: the embedding table's pre-compression
        dtype — weights="bf16"/"int8" must not silently shift cache
        precision (the user opts into cache compression via
        cache_dtype)."""
        if self.weight_dtype is not None:
            return self._float_dtype
        return self.params[self._embed.name]["table"].dtype

    def _pos_table(self, params):
        """The position table (learned weights or the sinusoid buffer);
        None when the stack has no positional-encoding layer (rope)."""
        if self._posenc is None:
            return None
        if self._posenc.learned:
            return params[self._posenc.name]["pos"]
        return self._posenc._sinusoid()

    def _pos_row(self, params, pos):
        table = self._pos_table(params)
        if table is None:
            return 0.0
        return jax.lax.dynamic_index_in_dim(table, pos, keepdims=False)

    def _step(self, params, caches, tok, pos):
        """tok [B] int32 at position ``pos`` → (logits [B, V], caches)."""
        x = self._embed_rows(params, tok)[:, None, :]
        x = x + self._pos_row(params, pos)
        new_caches = []
        for layer, (ck, cv) in zip(self._blocks, caches):
            x, ck, cv = layer.step(params[layer.name], x, ck, cv, pos)
            new_caches.append((ck, cv))
        logits = self._ln_head(params, x)
        return logits[:, 0].astype(jnp.float32), new_caches

    def load_adapter_bank(self, adapters):
        """Multi-LoRA serving (S-LoRA idea, Sheng et al. 2023): stack N
        fine-tuned adapters into per-layer banks so ONE slot pool
        serves base + any adapter, routed per request.

        ``adapters`` — list of host param trees from LoRA fine-tunes of
        THIS base model (each carries ``<layer>.mha.lora`` subtrees).
        Bank slot 0 is the identity adapter (zeros — the b-factors
        zero out the delta), so adapter id 0 == the base model and ids
        1..N follow ``adapters``' order.  Returns N.

        Banks live in ``params[layer]["mha"]["lora_bank"]``; the
        serving tick gathers a request's adapter into the live
        ``"lora"`` subtree (``_graft_adapters``) — the gathered leaves
        keep a leading row dim under the batched paged step, which
        ``_qkv_proj``'s jnp.matmul chain broadcasts natively.  Banks
        are a serving-path artifact: training, solo generate() and
        beam ignore them."""
        if not adapters:
            raise ValueError("adapters must be a non-empty list")
        # validate + build EVERY layer's bank before touching
        # self.params: a mid-list error (missing subtree, rank
        # mismatch breaking the stack) must leave the generator
        # exactly as it was, never half-banked
        banks = {}
        for layer in self._blocks:
            lp = self.params.get(layer.name, {})
            if "lora" in lp.get("mha", {}):
                raise ValueError(
                    "params already carry a single 'lora' subtree on "
                    "%s — serve it as a bank member instead"
                    % layer.name)
            subs = []
            for i, tree in enumerate(adapters):
                sub = tree.get(layer.name, {}).get("mha", {}).get(
                    "lora")
                if sub is None:
                    raise ValueError(
                        "adapter %d has no lora subtree on layer %s"
                        % (i, layer.name))
                subs.append(sub)
            try:
                banks[layer.name] = jax.tree_util.tree_map(
                    lambda *leaves: jnp.stack(
                        (jnp.zeros_like(jnp.asarray(leaves[0])),)
                        + tuple(jnp.asarray(l) for l in leaves)),
                    *subs)
            except (ValueError, TypeError) as e:
                raise ValueError(
                    "adapters disagree on layer %s (rank/shape "
                    "mismatch?): %s" % (layer.name, e)) from e
        if not banks:
            raise ValueError("model has no transformer blocks to bank")
        # rebind a shallow copy: self.params may BE the trainer's live
        # params dict (shared with training and other generators) —
        # banks belong to THIS generator only
        self.params = dict(self.params)
        for name, bank in banks.items():
            lp = self.params[name]
            mha = dict(lp["mha"])
            mha["lora_bank"] = bank
            self.params[name] = dict(lp, mha=mha)
        self._n_adapters = len(adapters)
        return self._n_adapters

    def _graft_adapters(self, params, aid):
        """``params`` with each banked layer's adapters gathered at
        ``aid`` (scalar for one row, [B] vector for the batched paged
        step) into the live ``"lora"`` subtree ``_qkv_proj`` reads.
        Identity (returns ``params`` itself) when no banks exist, so
        bank-free models trace the exact same program as before."""
        out = None
        for layer in self._blocks:
            lp = params.get(layer.name, {})
            bank = lp.get("mha", {}).get("lora_bank")
            if bank is None:
                continue
            if out is None:
                out = dict(params)
            mha = {k: v for k, v in lp["mha"].items()
                   if k != "lora_bank"}
            mha["lora"] = jax.tree_util.tree_map(
                lambda b_: b_[aid], bank)
            out[layer.name] = dict(lp, mha=mha)
        return params if out is None else out

    def _step_paged(self, params, pool, tables, tok, pos):
        """One decode step against the PAGED KV pool, batched over rows
        at PER-ROW positions: tok [B] int32, pos [B] int32 →
        (logits [B, V], pool).  The paged continuous batcher's fused
        path — unlike _step (scalar pos, dense caches, vmappable per
        row), the pool is SHARED across rows, so the whole step runs
        batched and each layer scatters/reads through the block table
        (layers.TransformerBlock.step_paged)."""
        x = self._embed_rows(params, tok)[:, None, :]
        ptab = self._pos_table(params)
        if ptab is not None:
            x = x + jnp.take(ptab, pos.astype(jnp.int32),
                             axis=0)[:, None, :]
        new_pool = []
        for layer, (pk, pv) in zip(self._blocks, pool):
            x, pk, pv = layer.step_paged(params[layer.name], x, pk, pv,
                                         tables, pos)
            new_pool.append((pk, pv))
        logits = self._ln_head(params, x)
        return logits[:, 0].astype(jnp.float32), new_pool

    def _ln_head(self, params, x):
        """Final LN + LM head (shared by every decode path — the
        needs_full_params head protocol lives in exactly one place)."""
        lp = params[self._ln.name]
        x = norm.layer_norm(x, lp["gamma"], lp["beta"])
        head_p = (params if getattr(self._head, "needs_full_params",
                                    False) else params[self._head.name])
        return self._head.apply(head_p, x)

    def _cache_constraint(self, c):
        """Pin a KV cache's head dim to the model axis under a mesh —
        the annotation GSPMD propagates through the whole decode scan.
        Applied leaf-wise (a QuantCache carries data + scales, both
        [B, Hkv, T, ...])."""
        if self.mesh_cfg is None or self.mesh_cfg.model_size <= 1:
            return c
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh = NamedSharding(self.mesh_cfg.mesh,
                           P(None, self.mesh_cfg.model_axis))
        return jax.tree_util.tree_map(
            lambda a: jax.lax.with_sharding_constraint(a, sh), c)

    def _init_caches(self, batch, dtype):
        dtype = self.cache_dtype or dtype

        def one(layer):
            t_cache = min(self.max_len,
                          layer.cfg.get("window") or self.max_len)
            shape = (batch, layer.n_kv_heads, t_cache, self._head_dim)
            if jnp.dtype(dtype) == jnp.int8:
                # int8 KV cache: quarter the serve-time cache memory
                # (ops.attention.QuantCache; scales for unwritten
                # positions are never read — decode writes before use)
                return attention.QuantCache(
                    jnp.zeros(shape, jnp.int8),
                    jnp.ones(shape[:3] + (1,), jnp.float32))
            return jnp.zeros(shape, dtype)

        return [tuple(self._cache_constraint(one(layer))
                      for _ in range(2))
                for layer in self._blocks]

    def _scan_fn(self, batch):
        """ONE compile per batch size: the scan always runs to
        max_len - 1, and prompt_len / seed / top_k / top_p / inv_temp /
        greedy are all TRACED per-row [B] vectors (a REST server sees
        arbitrary prompt lengths and client-chosen sampling configs —
        shape- or value-specializing on any of them would recompile per
        request and cache executables forever; per-ROW parameters are
        what lets the serving batcher coalesce heterogeneous requests
        into one device call).  Each row's draws depend only on its own
        (seed, position), so a request's output is invariant to which
        batch it was coalesced into.  Cached per-instance (NOT
        lru_cache: a class-level cache keyed on self would immortalize
        every generator and its params)."""
        cached = self._cache_get(batch)
        if cached is not None:
            return cached

        def run(params, tokens, prompt_len, seeds, top_k, top_p,
                inv_temp, greedy):
            caches = self._init_caches(batch, self._model_dtype())
            keys = jax.vmap(jax.random.key)(seeds)
            body = self._decode_body(params, prompt_len, keys, top_k,
                                     top_p, inv_temp, greedy, batch)
            (tokens, _), logits = jax.lax.scan(
                body, (tokens, caches),
                jnp.arange(self.max_len - 1))
            return tokens, logits

        return self._cache_put(batch, jax.jit(run))

    def _decode_body(self, params, prompt_len, keys, top_k, top_p,
                     inv_temp, greedy, batch):
        """The per-position decode body shared by the full scan and the
        prefilled generation scan (they must never diverge)."""
        def body(carry, pos):
            tokens, caches = carry
            logits, caches = self._step(params, caches,
                                        tokens[:, pos], pos)
            # an all-greedy batch (the serving default) skips the
            # whole-vocab gumbel draw — jnp.where alone would pay it
            smp = jax.lax.cond(
                jnp.any(~greedy),
                lambda: _sample(logits, pos, keys, top_k, top_p,
                                inv_temp),
                lambda: jnp.zeros((batch,), jnp.int32))
            nxt = jnp.where(
                greedy,
                jnp.argmax(logits, axis=-1).astype(jnp.int32), smp)
            keep = pos + 1 < prompt_len       # teacher-force prompt
            nxt = jnp.where(keep, tokens[:, pos + 1], nxt)
            tokens = jax.lax.dynamic_update_slice(
                tokens, nxt[:, None], (0, pos + 1))
            return (tokens, caches), logits

        return body

    def _pos_rows(self, params, tp):
        table = self._pos_table(params)
        return 0.0 if table is None else table[:tp]

    def _prefill_fn(self, batch, tp):
        """ONE compile per (batch, prompt bucket): run the prompt chunk
        [B, tp] through every block's parallel prefill, returning the
        filled KV caches.  Replaces tp sequential scan steps with one
        MXU-fed forward — the serving prefill."""
        cached = self._cache_get(("pre", batch, tp))
        if cached is not None:
            return cached

        def run(params, toks):
            x = self._embed_rows(params, toks)
            x = x + self._pos_rows(params, tp)
            caches = self._init_caches(batch, self._model_dtype())
            out = []
            for layer, (ck, cv) in zip(self._blocks, caches):
                x, ck, cv = layer.prefill(params[layer.name], x, ck, cv)
                out.append((ck, cv))
            return out

        return self._cache_put(("pre", batch, tp), jax.jit(run))

    def _gen_fn(self, batch, length):
        """ONE compile per (batch, generation-length bucket): the decode
        scan over ``length`` positions starting at traced ``start``
        (prefilled caches in, final tokens out).  Positions past
        max_len - 2 clamp — the body is idempotent at a repeated
        position (same inputs -> same token), so overshoot from the
        power-of-two bucket is harmless."""
        cached = self._cache_get(("gen", batch, length))
        if cached is not None:
            return cached

        def run(params, caches, tokens, start, prompt_len, seeds,
                top_k, top_p, inv_temp, greedy):
            keys = jax.vmap(jax.random.key)(seeds)
            body = self._decode_body(params, prompt_len, keys, top_k,
                                     top_p, inv_temp, greedy, batch)

            def body2(carry, i):
                pos = jnp.minimum(start + i, self.max_len - 2)
                return body(carry, pos)

            (tokens, _), _ = jax.lax.scan(body2, (tokens, caches),
                                          jnp.arange(length))
            return tokens

        return self._cache_put(("gen", batch, length), jax.jit(run))

    @staticmethod
    def _bucket(n, cap):
        return min(1 << max(0, n - 1).bit_length(), cap)

    def _prefill_dispatch(self, min_len, max_total):
        """(prompt bucket, scan start, scan length) for the chunked-
        prefill paths (greedy/sampled AND beam — one copy of the
        invariant): validate_request caps max_total <= max_len, so the
        pow2 length bucket, clamped to the remaining positions, always
        covers the needed steps — and overshoot positions are frozen/
        idempotent.

        ROLLING caches round the prompt chunk DOWN (largest pow2 <=
        min_len): a ring slot must always hold the latest position <=
        the scan cursor, so the prefill may never write a position past
        its own start — padding rows would poison the slot->position
        mapping.  Linear caches round UP (padding is overwritten before
        it can be read)."""
        if self._rolling:
            tp = max(1, min(1 << (min_len.bit_length() - 1),
                            self.max_len))
            start = tp - 1
        else:
            tp = self._bucket(min_len, self.max_len)
            start = min_len - 1
        need = max(1, max_total - 1 - start)
        length = self._bucket(need, max(1, self.max_len - 1 - start))
        return tp, start, length

    def _decode_rows(self, tokens_np, lens, totals, greedy, seeds,
                     top_k, top_p, inv_temp):
        """Shared decode orchestrator (generate / generate_batch): pick
        chunked-prefill + short generation scan when the shortest
        prompt is long enough, else the single full scan.  Correctness
        of padded prefill: the decode body overwrites cache row ``pos``
        BEFORE attending to it, so prefill garbage beyond a row's
        prompt (padding, or rows whose prompt is longer than the
        common prefix) is rewritten before it can ever be read."""
        b = tokens_np.shape[0]
        pad = self.max_len - tokens_np.shape[1]
        if pad:
            tokens_np = np.concatenate(
                [tokens_np, np.zeros((b, pad), np.int32)], axis=1)

        def row(x, dtype):
            return jnp.broadcast_to(jnp.asarray(x, dtype), (b,))

        min_len, max_total = int(min(lens)), int(max(totals))
        if min_len < self.prefill_min:
            out, _ = self._run(self.params, tokens_np, lens, greedy,
                               seeds, top_k, top_p, inv_temp)
            return np.asarray(out)
        tp, start, length = self._prefill_dispatch(min_len, max_total)
        caches = self._prefill_fn(b, tp)(
            self.params, jnp.asarray(tokens_np[:, :tp]))
        out = self._gen_fn(b, length)(
            self.params, caches, jnp.asarray(tokens_np),
            jnp.int32(start), row(lens, jnp.int32),
            row(seeds, jnp.int32), row(top_k, jnp.int32),
            row(top_p, jnp.float32), row(inv_temp, jnp.float32),
            row(greedy, jnp.bool_))
        return np.asarray(out)

    def _chunk_forward(self, params, caches, toks, start):
        """toks [1, K] at positions [start, start+K) through every
        block's chunk_step against an existing cache → (x, caches).
        THE one chunk-positioning contract — the speculative verify
        (_chunk_logits) and the prefix-cache prefill resume
        (_prefill_resume_fn) must never diverge on it."""
        x = self._embed_rows(params, toks)
        ptab = self._pos_table(params)
        if ptab is not None:
            x = x + jax.lax.dynamic_slice(
                ptab, (start, 0), (toks.shape[1], ptab.shape[1]))
        new_caches = []
        for layer, (ck, cv) in zip(self._blocks, caches):
            x, ck, cv = layer.chunk_step(params[layer.name], x, ck, cv,
                                         start)
            new_caches.append((ck, cv))
        return x, new_caches

    def _chunk_logits(self, params, caches, toks, start):
        """toks [1, K] at positions [start, start+K) → (logits [K, V]
        f32, caches) — the speculative verify forward."""
        x, new_caches = self._chunk_forward(params, caches, toks,
                                            start)
        return (self._ln_head(params, x)[0].astype(jnp.float32),
                new_caches)

    def _prefill_resume_fn(self, kb):
        """ONE compile per resume-chunk bucket: positions
        [start, start+kb) of a prompt run through every block's
        chunk_step against an EXISTING cache (valid for [0, start)) —
        chunked prefill that RESUMES from a prefix another request
        already computed (the paged batcher's prefix-cache compute
        skip).  Identical K/V math to a full prefill of the same
        positions (chunk_step == K step() calls, the same contract the
        speculative verify rides)."""
        cached = self._cache_get(("presume", kb))
        if cached is not None:
            return cached

        def run(params, caches, toks, start):
            return self._chunk_forward(params, caches, toks, start)[1]

        return self._cache_put(("presume", kb), jax.jit(run))

    def _spec_fn(self, draft_k):
        """ONE compile per draft width: the whole speculative greedy
        decode — n-gram draft, K-wide verify chunk, acceptance — inside
        a single jitted lax.while_loop (no host round trips).  Each
        round advances >= 1 position; drafts that copy a continuation
        of the last bigram from earlier context verify several
        positions per model pass."""
        cached = self._cache_get(("spec", draft_k))
        if cached is not None:
            return cached
        kk = draft_k
        ll = self.max_len

        def run(params, caches, tokens, cur0, prompt_len, total):
            # tokens [1, max_len]; cache valid for [0, cur0)
            idx = jnp.arange(kk)

            def cond(state):
                return state[2] < total

            def body(state):
                tokens, caches, cur = state
                row = tokens[0]
                draft = _ngram_draft(row, cur - 1, kk, ll)
                # prompt positions teacher-force their own tokens
                in_prompt = (cur + idx) < prompt_len
                cur_slice = jax.lax.dynamic_slice(row, (cur,), (kk,))
                draft = jnp.where(in_prompt, cur_slice, draft)
                # verify: inputs are [token at cur-1, draft[:-1]]
                prev = jax.lax.dynamic_slice(row, (cur - 1,), (1,))
                chunk = jnp.concatenate([prev, draft[:-1]])[None]
                logits, caches = self._chunk_logits(
                    params, caches, chunk, cur - 1)
                g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                ok = (draft == g) | in_prompt
                # first rejection = number accepted; cap at kk-1 so the
                # "bonus" write is always a position we HAVE logits for
                # (if draft[kk-1] was accepted, g[kk-1] equals it)
                a = jnp.minimum(
                    jnp.argmin(jnp.concatenate(
                        [ok, jnp.zeros((1,), bool)])), kk - 1)
                # the bonus position must NEVER overwrite a
                # teacher-forced prompt token (a lands inside the
                # prompt tail when the whole chunk was in-prompt)
                bonus = jnp.where(jnp.take(in_prompt, a),
                                  jnp.take(cur_slice, a),
                                  jnp.take(g, a))
                newvec = jnp.where(
                    idx < a, draft,
                    jnp.where(idx == a, bonus, cur_slice))
                tokens = jax.lax.dynamic_update_slice(
                    tokens, newvec[None], (0, cur))
                return (tokens, caches, cur + a + 1)

            tokens, _, _ = jax.lax.while_loop(
                cond, body, (tokens, caches, cur0))
            return tokens

        return self._cache_put(("spec", draft_k), jax.jit(run))

    def generate_speculative(self, prompt, max_new, draft_k=8):
        """Greedy decode with in-jit n-gram speculation: repetitive or
        self-similar continuations verify up to ``draft_k`` positions
        per model pass instead of one.  Exact greedy semantics — the
        accepted tokens ARE the verify pass's own argmax.  Falls back
        to generate() when speculation can't apply (batch > 1, short
        prompts, rolling-window caches, no headroom for the draft
        overshoot)."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        b, t0 = prompt.shape
        draft_k = int(draft_k)
        if not 2 <= draft_k <= 64:
            raise ValueError("draft_k must be in [2, 64], got %r"
                             % (draft_k,))
        t0v, total, _, _, _, _ = self.validate_request(
            t0, {"max_new": max_new, "temperature": 0.0})
        if (b != 1 or self._rolling or t0 < max(4, self.prefill_min)
                or total + draft_k >= self.max_len):
            return self.generate(prompt, max_new)
        # prefill rounds DOWN: every cache row < cur0 must hold a REAL
        # prompt token (the verify chunk attends them before any
        # rewrite — round-up padding would poison later chunks)
        tp = max(2, min(1 << (t0.bit_length() - 1), self.max_len))
        caches = self._prefill_fn(1, tp)(
            self.params, jnp.asarray(prompt[:, :tp]))   # tp <= t0
        tokens = np.zeros((1, self.max_len), np.int32)
        tokens[0, :t0] = prompt[0]
        out = self._spec_fn(draft_k)(
            self.params, caches, jnp.asarray(tokens), jnp.int32(tp),
            jnp.int32(t0), jnp.int32(total))
        return np.asarray(out)[:, :total]

    def _cache_get(self, key):
        # the REST server is threaded and shares one generator: the
        # get/move_to_end pair must not race a concurrent eviction
        with self._cache_lock:
            fn = self._compiled.get(key)
            if fn is not None:
                self._compiled.move_to_end(key)
            return fn

    def _cache_put(self, key, fn):
        with self._cache_lock:
            self._compiled[key] = fn
            while len(self._compiled) > COMPILE_CACHE_SIZE:
                self._compiled.popitem(last=False)
        return fn

    def _run(self, params, tokens_np, prompt_len, greedy, seeds=0,
             top_k=0, top_p=1.0, inv_temp=1.0):
        """All per-row knobs accept a scalar (broadcast) or a [B]
        vector — the serving batcher passes vectors."""
        b = tokens_np.shape[0]
        pad = self.max_len - tokens_np.shape[1]
        if pad:
            tokens_np = np.concatenate(
                [tokens_np, np.zeros((b, pad), np.int32)], axis=1)

        def row(x, dtype):
            return jnp.broadcast_to(jnp.asarray(x, dtype), (b,))

        return self._scan_fn(b)(
            params, jnp.asarray(tokens_np), row(prompt_len, jnp.int32),
            row(seeds, jnp.int32), row(top_k, jnp.int32),
            row(top_p, jnp.float32), row(inv_temp, jnp.float32),
            row(greedy, jnp.bool_))

    # ------------------------------------------------------------------
    def generate(self, prompt, max_new, temperature=0.0, seed=0,
                 top_k=0, top_p=1.0):
        """prompt [B, T0] int tokens → [B, T0 + max_new].  temperature 0
        = greedy argmax; otherwise softmax sampling at that temperature,
        optionally truncated to the ``top_k`` best tokens and/or the
        ``top_p`` nucleus (smallest set reaching that probability
        mass)."""
        prompt = np.asarray(prompt, np.int32)
        b, t0 = prompt.shape
        t0, total, temperature, top_k, top_p, seed = \
            self.validate_request(
                t0, {"max_new": max_new, "temperature": temperature,
                     "seed": seed, "top_k": top_k, "top_p": top_p})
        greedy = temperature == 0.0
        out = self._decode_rows(
            prompt, [t0] * b, [total] * b, greedy, seed, top_k, top_p,
            1.0 if greedy else 1.0 / temperature)
        return out[:, :total]

    def validate_request(self, prompt_len, opts):
        """Validate ONE generate request's options against this model —
        raises ValueError; returns (t0, total, temperature, top_k,
        top_p, seed).  The serving batcher calls this BEFORE enqueueing
        so one bad request can never fail the batch it would have
        coalesced into."""
        t0 = int(prompt_len)
        max_new = int(opts.get("max_new", 16))
        if max_new < 0:
            raise ValueError("max_new must be >= 0, got %r" % (max_new,))
        total = t0 + max_new
        if total > self.max_len:
            raise ValueError("prompt + max_new = %d exceeds max_len %d"
                             % (total, self.max_len))
        temp = float(opts.get("temperature", 0.0))
        if temp < 0.0:
            raise ValueError("temperature must be >= 0, got %r"
                             % (temp,))
        top_p = float(opts.get("top_p", 1.0))
        top_k = int(opts.get("top_k", 0))
        if not 0.0 < top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1], got %r"
                             % (top_p,))
        if not 0 <= top_k <= self._head.n_out:
            raise ValueError("top_k must be in [0, %d], got %r"
                             % (self._head.n_out, top_k))
        return t0, total, temp, top_k, top_p, int(opts.get("seed", 0))

    def generate_batch(self, prompts, opts_list):
        """Coalesce heterogeneous generate requests into ONE device
        call: ``prompts`` is a list of 1-D token sequences (any
        lengths), ``opts_list`` a parallel list of per-request dicts
        (max_new, temperature, seed, top_k, top_p).  Returns a list of
        1-D outputs, each trimmed to its request's prompt + max_new.
        Per-row traced parameters + per-(seed, position) sampling keys
        make each row's RANDOM DRAWS independent of what it was batched
        with; outputs equal the solo generate() call whenever the
        forward itself is batch-size-deterministic (exact on CPU — on
        TPU a different batch size can tile f32 reductions differently,
        so a near-tied argmax may flip on rare positions)."""
        if len(prompts) != len(opts_list):
            raise ValueError("prompts and opts_list lengths differ")
        b = len(prompts)
        lens, totals = [], []
        tk, tp, it, gr, sd = [], [], [], [], []
        for prompt, opts in zip(prompts, opts_list):
            t0, total, temp, top_k, top_p, seed = self.validate_request(
                len(prompt), opts)
            lens.append(t0)
            totals.append(total)
            tk.append(top_k)
            tp.append(top_p)
            it.append(1.0 if temp == 0.0 else 1.0 / temp)
            gr.append(temp == 0.0)
            sd.append(seed)
        t_max = max(lens)
        tokens = np.zeros((b, t_max), np.int32)
        for i, prompt in enumerate(prompts):
            tokens[i, :lens[i]] = np.asarray(prompt, np.int32)
        out = self._decode_rows(
            tokens, lens, totals, np.asarray(gr), np.asarray(sd),
            np.asarray(tk), np.asarray(tp, np.float32),
            np.asarray(it, np.float32))
        return [out[i, :totals[i]] for i in range(b)]

    def _beam_fn(self, batch, beam):
        """ONE compile per (batch, beam): scan over all max_len - 1
        positions; the prompt prefix teacher-forces every beam
        identically (scores pinned to 0 so beams only diverge after the
        prompt), then each step expands beam×V continuations and keeps
        the ``beam`` best, gathering the KV caches of the surviving
        parents."""
        cached = self._cache_get(("beam", batch, beam))
        if cached is not None:
            return cached
        bb = batch * beam

        def run(params, tokens, prompt_len, gen_end):
            # tokens: [batch, beam, max_len]
            caches = self._init_caches(bb, self._model_dtype())
            scores = self._beam_init_scores(batch, beam)
            body = self._beam_body(params, prompt_len, gen_end, batch,
                                   beam)
            (tokens, _, scores), _ = jax.lax.scan(
                body, (tokens, caches, scores),
                jnp.arange(self.max_len - 1))
            return tokens, scores

        return self._cache_put(("beam", batch, beam), jax.jit(run))

    @staticmethod
    def _beam_init_scores(batch, beam):
        # before any divergence only beam 0 may survive expansion,
        # or the result would be `beam` copies of one continuation
        scores = jnp.zeros((batch, beam), jnp.float32)
        return scores.at[:, 1:].set(-1e30)

    def _beam_body(self, params, prompt_len, gen_end, batch, beam):
        """Per-position beam-expansion body shared by the full scan and
        the prefilled beam scan.  Frozen steps (inside the prompt, past
        ``gen_end``, or a clamped overshoot position) keep an identity
        parent, so repeating them is a no-op — what makes power-of-two
        length buckets safe."""
        bb = batch * beam

        def body(carry, pos):
            tokens, caches, scores = carry
            logits, caches = self._step(
                params, caches, tokens.reshape(bb, -1)[:, pos], pos)
            logp = jax.nn.log_softmax(logits)        # [bb, V]
            v = logp.shape[-1]
            in_prompt = pos + 1 < prompt_len
            # beams freeze inside the prompt AND once max_new tokens
            # are out — scores must not accumulate past the horizon
            frozen = in_prompt | (pos + 1 >= gen_end)

            # candidate scores for every (beam, token) continuation
            cand = scores[:, :, None] + logp.reshape(batch, beam, v)
            flat = cand.reshape(batch, beam * v)
            top_s, top_i = jax.lax.top_k(flat, beam)
            parent = top_i // v                      # [batch, beam]
            tok = (top_i % v).astype(jnp.int32)

            # teacher forcing / frozen tail: every beam keeps its own
            # row and the already-present token, at no score cost
            keep_parent = jnp.broadcast_to(
                jnp.arange(beam)[None], (batch, beam))
            parent = jnp.where(frozen, keep_parent, parent)
            tok = jnp.where(frozen, tokens[:, :, pos + 1], tok)
            new_scores = jnp.where(frozen, scores, top_s)

            flat_parent = (parent
                           + jnp.arange(batch)[:, None] * beam
                           ).reshape(bb)
            tokens = jnp.take(tokens.reshape(bb, -1), flat_parent,
                              axis=0).reshape(batch, beam, -1)
            tokens = jax.lax.dynamic_update_slice(
                tokens, tok[:, :, None], (0, 0, pos + 1))
            # physical cache reorder: every step gathers the FULL
            # [B·beam, H, T_max, D] cache along the parent rows —
            # O(T·beam·H·D) HBM write traffic per position, so
            # O(T²·beam·H·D) per decode: fine at beam<=8 / T<=4k
            # (bench.py phase_beam records the T=4096 beam=8 rate);
            # a lazy ancestry-index reorder (gather at attention
            # time) would cut writes to O(1) per step but needs the
            # block step API to take per-position row indices —
            # revisit if long-context beam serving becomes hot
            caches = jax.tree_util.tree_map(
                lambda c: jnp.take(c, flat_parent, axis=0), caches)
            return (tokens, caches, new_scores), None

        return body

    def _beam_gen_fn(self, batch, beam, length):
        """ONE compile per (batch, beam, length bucket): beam expansion
        over ``length`` positions from traced ``start``, against
        prefilled BATCH caches tiled across the beams inside the jit
        (beam rows are identical during the prompt, so one batch-wide
        prefill serves all of them — the old path recomputed the prompt
        beam× through the serial scan)."""
        cached = self._cache_get(("beamgen", batch, beam, length))
        if cached is not None:
            return cached

        def run(params, caches, tokens, start, prompt_len, gen_end):
            caches = jax.tree_util.tree_map(
                lambda c: jnp.repeat(c, beam, axis=0), caches)
            scores = self._beam_init_scores(batch, beam)
            body = self._beam_body(params, prompt_len, gen_end, batch,
                                   beam)

            def body2(carry, i):
                pos = jnp.minimum(start + i, self.max_len - 2)
                return body(carry, pos)

            (tokens, _, scores), _ = jax.lax.scan(
                body2, (tokens, caches, scores), jnp.arange(length))
            return tokens, scores

        return self._cache_put(("beamgen", batch, beam, length),
                               jax.jit(run))

    def beam_search(self, prompt, max_new, beam=4):
        """Beam-search decode: prompt [B, T0] → (tokens [B, T0+max_new],
        log-probability of the returned best beam, [B]).

        Short prompts (< prefill_min) run the single full scan, which
        teacher-forces all ``beam`` rows identically — ONE executable
        per (batch, beam) regardless of prompt length.  Long prompts
        take the chunked-prefill path: ONE batch-wide prefill tiled
        across the beams plus a short expansion scan, compiling per
        ('pre', batch, prompt-bucket) and ('beamgen', batch, beam,
        length-bucket) — all LRU-bounded."""
        prompt = np.asarray(prompt, np.int32)
        b, t0 = prompt.shape
        total = t0 + int(max_new)
        if total > self.max_len:
            raise ValueError("prompt + max_new = %d exceeds max_len %d"
                             % (total, self.max_len))
        if not 1 <= int(beam) <= 64:
            # bounded like top_k: beam is client-controlled over REST,
            # and each distinct value compiles (and caches) an
            # executable whose cache memory scales with batch*beam
            raise ValueError("beam must be in [1, 64], got %r" % (beam,))
        tokens = np.zeros((b, beam, self.max_len), np.int32)
        tokens[:, :, :t0] = prompt[:, None, :]
        if t0 >= self.prefill_min:
            # batch-wide prefill, tiled to the beams in-jit: the prompt
            # is computed ONCE instead of beam x position-by-position
            tp, start, length = self._prefill_dispatch(t0, total)
            caches = self._prefill_fn(b, tp)(
                self.params, jnp.asarray(tokens[:, 0, :tp]))
            out, scores = self._beam_gen_fn(b, int(beam), length)(
                self.params, caches, jnp.asarray(tokens),
                jnp.int32(start), jnp.int32(t0), jnp.int32(total))
        else:
            out, scores = self._beam_fn(b, int(beam))(
                self.params, jnp.asarray(tokens), jnp.int32(t0),
                jnp.int32(total))
        best = np.asarray(jnp.argmax(scores, axis=1))
        out = np.asarray(out)[np.arange(b), best, :total]
        return out, np.asarray(scores)[np.arange(b), best]

    def score(self, tokens):
        """Per-position next-token logits from the incremental path
        (teacher forcing) — [B, T-1, V]; the equivalence oracle for the
        tests and a perplexity scorer."""
        tokens = np.asarray(tokens, np.int32)
        b, t = tokens.shape
        if t > self.max_len:
            raise ValueError("sequence %d exceeds max_len %d"
                             % (t, self.max_len))
        _, logits = self._run(self.params, tokens, t, True)
        return np.asarray(logits).transpose(1, 0, 2)[:, :t - 1]


class ContinuousBatcher:
    """In-flight (continuous) batching over a fixed pool of decode
    slots: requests JOIN and LEAVE the batched decode at any step
    instead of waiting for a whole batch to finish together — the
    modern serving-engine admission model (capability beyond both the
    reference and this repo's coalescing ``GenerateBatcher``, which
    merges only same-phase requests).

    Design: one jitted per-tick step, ``jax.vmap`` of the generator's
    single-row incremental step with PER-ROW positions (each slot sits
    at its own depth in its own KV cache; the vmapped
    dynamic_update_slice becomes a scatter).  Admission chunk-prefills
    by default: the new prompt fills its slot's cache in one parallel
    pass and the row starts at the standard scan cursor
    (``chunked_prefill=False`` falls back to forcing the prompt
    token-by-token through the shared tick — the tick's prompt-forcing
    also finishes whatever a rolling-window prefill chunk leaves).
    Inactive slots tick too
    (uniform shapes beat recompiles); their writes stay inside their
    own slot so they cannot disturb live rows.

    Greedy and per-row temperature sampling; each row's draws depend
    only on its own (seed, position), so outputs are invariant to
    which slots or neighbors a request shared the pool with — the same
    contract GenerateBatcher proves for coalescing.

        cb = ContinuousBatcher(gen, slots=8)
        rid = cb.submit([1, 2, 3], max_new=16)
        while not cb.idle():
            cb.tick()
        tokens = cb.result(rid)
    """

    def __init__(self, gen, slots=8, ticks_per_dispatch=1,
                 chunked_prefill=True, speculative_k=0,
                 prefill_segment=0, prefill_tick_budget=0):
        self.gen = gen
        self.slots = int(slots)
        #: speculative_k > 0: n-gram speculative ticks — every active
        #: row verifies up to k drafted tokens per tick instead of
        #: decoding one (_make_core_spec; exact decode semantics).
        #: Dense pools, linear caches only.
        self.speculative_k = int(speculative_k)
        if self.speculative_k:
            if not 2 <= self.speculative_k <= 64:
                raise ValueError("speculative_k must be in [2, 64], "
                                 "got %d" % self.speculative_k)
            if self.speculative_k + 2 > gen.max_len:
                raise ValueError(
                    "speculative_k %d leaves no room for any request "
                    "at max_len %d (prompt+max_new+k must fit)"
                    % (self.speculative_k, gen.max_len))
            if gen._rolling:
                raise ValueError("speculative ticks need linear KV "
                                 "caches (rolling windows cannot "
                                 "absorb the rejected-draft tail)")
        #: fuse K engine ticks into ONE device dispatch (lax.scan over
        #: the tick body) — the same host→device amortization as the
        #: trainer's fused sweep.  Admission then happens at K-token
        #: boundaries; rows that hit their budget mid-scan freeze
        #: in-jit, so outputs stay EXACTLY the solo continuation at any
        #: K.  K=1 is pure per-token admission; remote/tunnel devices
        #: want K ~ 8-32.
        self.ticks_per_dispatch = max(1, int(ticks_per_dispatch))
        #: chunked-prefill admission: a new request's prompt fills its
        #: slot's KV cache in ONE parallel pass (TransformerBlock.
        #:prefill via _prefill_fn) and the row starts at the scan
        #: cursor _prefill_dispatch prescribes — instead of consuming
        #: one pool tick per prompt token.  The tick's prompt-forcing
        #: still covers whatever the prefill chunk didn't (rolling
        #: windows round the chunk DOWN).
        self.chunked_prefill = bool(chunked_prefill)
        #: segmented prefill admission (docs/services.md "Disaggregated
        #: prefill"): prefill_segment > 0 splits a long prompt's
        #: admission prefill into bounded chunk passes of at most
        #: ``prefill_segment`` tokens each, INTERLEAVED with decode
        #: ticks — one long admission can no longer stall every
        #: in-flight decode stream for its whole prompt.  The staged
        #: passes run _prefill_resume_fn's resume-from-cursor math
        #: (the prefix-cache resume contract: chunk_step == K step()
        #: calls), so the finished cache row and pos0 = plen - 1 are
        #: byte-identical to the unsegmented admission.  Per tick, at
        #: most ``prefill_tick_budget`` prefill tokens advance across
        #: ALL staged admissions (0 = one segment's worth; chunk
        #: passes are pow2-bucketed, so a tick may overshoot the
        #: budget by < 2x, never by a whole prompt).  0 = off.
        self.prefill_segment = max(0, int(prefill_segment or 0))
        self.prefill_tick_budget = max(0, int(prefill_tick_budget or 0))
        #: slot -> staged-admission record (a reserved slot whose
        #: prompt is still prefilling in segments; its row stays
        #: inactive so decode ticks skip it)
        self._staging = {}
        #: optional callable({"kind": "begin"|"segment"|"admit", ...})
        #: the serving engine hooks to surface serve.prefill flight
        #: events and gauges; runs on the tick() caller's thread
        self.prefill_observer = None
        B, L = self.slots, gen.max_len
        self._tokens = jnp.zeros((B, L), jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._plen = jnp.ones((B,), jnp.int32)
        self._total = jnp.ones((B,), jnp.int32)   # plen + max_new
        self._active = jnp.zeros((B,), jnp.bool_)
        self._seeds = jnp.zeros((B,), jnp.int32)
        self._inv_temp = jnp.zeros((B,), jnp.float32)  # 0 = greedy
        #: per-slot adapter id (multi-LoRA routing; 0 = base).  Host-
        #: managed: changes only at admission, so it rides the tick as
        #: a separate non-donated argument instead of growing the
        #: state tuple every admit body must rebuild.
        self._aids = jnp.zeros((B,), jnp.int32)
        self._caches = self._init_slot_caches()
        self._slot_req = [None] * B               # slot -> request id
        self._queue = collections.deque()
        self._results = {}
        #: rid -> monotonic timestamp of the request's FIRST decode
        #: tick (admission prefill complete, row active) — the
        #: prefill/decode boundary of the serving plane's per-request
        #: phase decomposition.  Survives slot release so the engine
        #: can read it at completion; pop_decode_start releases it.
        self._decode_start = {}
        #: opt-in per-tick partial-token snapshots (token streaming);
        #: costs one [B, max_len] host fetch per dispatch when on
        self.stream_partials = False
        self._partials = {}
        self._next_id = 0
        self._tick_fn = None
        self._admit_fn = None

    # ------------------------------------------------------------ public
    def submit(self, prompt, max_new, temperature=0.0, seed=0,
               adapter=0):
        """Queue a request; returns a request id.  The request enters
        the pool at the next tick with a free slot.  ``adapter``:
        multi-LoRA routing — 0 = base model, 1..N = the bank loaded by
        ``LMGenerator.load_adapter_bank``."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if int(max_new) < 1:
            raise ValueError("max_new must be >= 1, got %d"
                             % int(max_new))
        if len(prompt) + int(max_new) > self.gen.max_len:
            raise ValueError("prompt+max_new %d exceeds max_len %d"
                             % (len(prompt) + int(max_new),
                                self.gen.max_len))
        if self.speculative_k and (len(prompt) + int(max_new)
                                   + self.speculative_k
                                   > self.gen.max_len):
            raise ValueError(
                "speculative ticks draft %d positions past the "
                "cursor: prompt+max_new+k %d exceeds max_len %d"
                % (self.speculative_k,
                   len(prompt) + int(max_new) + self.speculative_k,
                   self.gen.max_len))
        n_bank = getattr(self.gen, "_n_adapters", 0)
        if not 0 <= int(adapter) <= n_bank:
            raise ValueError("adapter %d outside the loaded bank "
                             "(0..%d)" % (int(adapter), n_bank))
        rid = self._next_id
        self._next_id += 1
        self._queue.append((rid, prompt, int(max_new),
                            float(temperature), int(seed),
                            int(adapter)))
        return rid

    def idle(self):
        return not self._queue and not any(
            r is not None for r in self._slot_req)

    def active_requests(self):
        """Request ids currently holding a decode slot (admitted but
        not yet finished) — the serving plane's admission signal."""
        return {r for r in self._slot_req if r is not None}

    def result(self, rid):
        """Completed token list (prompt + continuation), or None while
        the request is still queued/decoding."""
        return self._results.get(rid)

    def pop_result(self, rid):
        """Like ``result`` but releases the stored tokens — long-running
        servers must not accumulate every completed request."""
        return self._results.pop(rid, None)

    def pop_decode_start(self, rid):
        """Monotonic timestamp of the request's first decode tick (the
        admit→decode phase boundary), releasing it — or None if the
        request never reached decode.  The serving engine reads it at
        completion to split queue/prefill/decode latency."""
        return self._decode_start.pop(rid, None)

    def cancel(self, rid):
        """Abort a request mid-flight: drop it from the queue, or —
        if already admitted — deactivate its row and free its slot
        (paged pools also free its KV blocks) WITHOUT waiting for the
        decode to finish.  The serving engine's cancellation path
        (client disconnect, deadline expiry, shutdown); single-caller
        contract like ``tick`` — only the engine thread may call it.
        Returns True if the request was queued or active; False if
        unknown or already finished (a finished result is released
        either way, so a cancelled request can never leak its
        tokens)."""
        for i, item in enumerate(self._queue):
            if item[0] == rid:
                del self._queue[i]
                return True
        if rid in self._slot_req:
            b = self._slot_req.index(rid)
            # the in-jit freeze flag: an inactive row neither writes
            # tokens nor advances, so a fused multi-tick scan stops
            # paying for it immediately; admission overwrites the
            # whole slot (incl. caches) for the next occupant
            self._active = self._active.at[b].set(False)
            self._partials.pop(rid, None)
            self._decode_start.pop(rid, None)
            self._release_slot(b)
            return True
        self._partials.pop(rid, None)
        self._results.pop(rid, None)
        self._decode_start.pop(rid, None)
        return False

    def reset_pool(self):
        """Hard reset after an engine fault: drop every queued and
        active request and rebuild the device-side state from scratch.
        A tick that raised mid-dispatch may have invalidated its
        DONATED buffers (state is donated into ``_jit_ticks``), so the
        arrays cannot be trusted — only their shapes/dtypes can.
        Compiled tick/admit executables survive; callers own waking
        any waiters for the dropped requests."""
        self._queue.clear()
        self._results.clear()
        self._partials.clear()
        self._decode_start.clear()
        self._staging = {}
        self._slot_req = [None] * self.slots
        B, L = self.slots, self.gen.max_len
        self._tokens = jnp.zeros((B, L), jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._plen = jnp.ones((B,), jnp.int32)
        self._total = jnp.ones((B,), jnp.int32)
        self._active = jnp.zeros((B,), jnp.bool_)
        self._seeds = jnp.zeros((B,), jnp.int32)
        self._inv_temp = jnp.zeros((B,), jnp.float32)
        self._aids = jnp.zeros((B,), jnp.int32)
        self._caches = self._init_slot_caches()

    def tick(self):
        """One engine step: admit queued requests into free slots
        (long prompts under segmented prefill only RESERVE their slot
        and stage — their prefill advances in bounded chunk passes
        below, never in one whole-prompt pass), advance staged
        prefills within the per-tick budget, then advance EVERY slot
        one token; emit and free finished rows.  Returns the number of
        active slots after the tick."""
        while self._can_admit():
            b = self._slot_req.index(None)
            if self._will_segment(len(self._queue[0][1])):
                self._begin_staged(b)
            else:
                self._admit(b)
        if self._staging:
            self._advance_staged(
                self.prefill_tick_budget or self.prefill_segment)
        # decode-start stamps: a slot that is occupied and NOT staging
        # is about to take its first decode step this tick (staged
        # admissions land here the tick their last segment finishes)
        now = time.monotonic()
        for b, rid in enumerate(self._slot_req):
            if rid is not None and b not in self._staging \
                    and rid not in self._decode_start:
                self._decode_start[rid] = now
        self._set_state(self._tick(self._state()))
        # emission: completion is re-derived from slot OCCUPANCY + pos
        # (the in-jit freeze already cleared ``active`` for rows that
        # hit their budget mid-scan, possibly several per fused
        # dispatch).  Staged slots are reserved but not yet decoding —
        # their device-side pos/total still belong to the previous
        # occupant, so they must not look done.
        pos = np.asarray(self._pos)
        total = np.asarray(self._total)
        occupied = np.array([r is not None and b not in self._staging
                             for b, r in enumerate(self._slot_req)])
        done = occupied & (pos + 1 >= total)
        stream = self.stream_partials and occupied.any()
        # ONE [B, L] host fetch serves both the partial snapshots and
        # the completion emission; non-streaming servers with nothing
        # done still pay nothing
        toks = (np.asarray(self._tokens)
                if stream or done.any() else None)
        if stream:
            # per-tick partial snapshot for token streaming: tokens
            # through index pos[b] are final (the tick wrote pos, then
            # advanced)
            for b in np.nonzero(occupied)[0]:
                self._partials[self._slot_req[b]] = toks[
                    b, :min(pos[b] + 1, total[b])].tolist()
        if done.any():
            for b in np.nonzero(done)[0]:
                rid = self._slot_req[b]
                self._results[rid] = toks[b, :total[b]].tolist()
                self._partials.pop(rid, None)
                self._release_slot(int(b))
        return int((np.asarray(self._active)).sum())

    def partial(self, rid):
        """Tokens decoded so far (prompt included) for an in-flight
        request, or None before admission / after completion.  Only
        populated while ``stream_partials`` is True; granularity is one
        dispatch (``ticks_per_dispatch`` tokens per update)."""
        return self._partials.get(rid)

    # --- subclass hooks (the paged batcher reshapes the cache state) ---
    def _init_slot_caches(self):
        """Dense slot-major KV allocation; the paged subclass returns
        None and allocates its (smaller) pool instead — it must never
        pay a dense-sized startup spike."""
        return self.gen._init_caches(self.slots, self.gen._model_dtype())

    def _can_admit(self):
        return bool(self._queue) and None in self._slot_req

    def _release_slot(self, b):
        self._slot_req[b] = None
        # a cancelled staged admission drops its partial prefill row
        # (paged: the subclass's block free path runs either way)
        self._staging.pop(b, None)

    def _state(self):
        return (self._tokens, self._pos, self._plen, self._total,
                self._active, self._seeds, self._inv_temp, self._caches)

    def _set_state(self, st):
        (self._tokens, self._pos, self._plen, self._total,
         self._active, self._seeds, self._inv_temp, self._caches) = st

    def run_all(self):
        """Drive until every submitted request completed."""
        while not self.idle():
            self.tick()
        return self._results

    # ----------------------------------------------------------- internal
    def _will_chunk(self, plen):
        """Whether admission chunk-prefills this prompt — THE predicate
        _prefill_row, _shareable_blocks, and the paged admit's
        resume-vs-full decision all share (a drifted hand-copy would
        let blocks register as shareable that the tick-by-tick path
        fills progressively)."""
        return self.chunked_prefill and plen >= 2

    def _prefill_row(self, prompt, plen, max_new, adapter=0):
        """Chunked-prefill admission: one parallel pass fills a [1, ...]
        cache row with the prompt and returns (cache_row, start_pos);
        the tick's prompt-forcing covers whatever the chunk didn't
        (rolling windows prefill a smaller chunk).  (None, 0) when the
        request prefills token-by-token through the shared tick.
        ``adapter``: the prompt's K/V must be computed under the SAME
        adapter the decode will run (grafted params; id 0 = base)."""
        gen = self.gen
        if self._will_chunk(plen):
            tp, start, _ = gen._prefill_dispatch(plen, plen + max_new)
            chunk = np.zeros((tp,), np.int32)
            chunk[:min(plen, tp)] = prompt[:tp]
            params = gen._graft_adapters(gen.params,
                                         jnp.int32(adapter))
            return gen._prefill_fn(1, tp)(
                params, jnp.asarray(chunk[None])), start
        return None, 0

    # ------------------------------------------- segmented admission
    def _will_segment(self, plen):
        """Whether admission STAGES this prompt (segmented prefill):
        the knob is on, the prompt chunk-prefills at all, the cache is
        linear (a rolling ring must round its one prefill chunk DOWN —
        generate._prefill_dispatch — so it keeps the unsegmented
        path), and the prefill work [0, plen-1) exceeds one segment
        (otherwise one pass IS the bound)."""
        return (self.prefill_segment > 0 and self._will_chunk(plen)
                and not self.gen._rolling
                and plen - 1 > self.prefill_segment)

    def _staged_setup(self, b, prompt, plen, max_new, adapter):
        """Subclass hook: reserve admission resources and return the
        (cache_row, cursor, extras) a staged prefill starts from.
        Dense pools start from a fresh [1, ...] row at cursor 0; the
        paged subclass claims KV blocks and may resume mid-prompt
        from a matched prefix."""
        return (self.gen._init_caches(1, self.gen._model_dtype()), 0,
                {})

    def _begin_staged(self, b):
        """Reserve slot ``b`` for the queue head and stage its
        segmented prefill — cheap (allocation only): the chunk passes
        run in _advance_staged under the per-tick budget, so beginning
        never stalls the tick and the requests queued behind a long
        prompt admit without waiting for its prefill."""
        (rid, prompt, max_new, temperature, seed,
         adapter) = self._queue.popleft()
        plen = len(prompt)
        self._aids = self._aids.at[b].set(adapter)
        caches, cursor, extras = self._staged_setup(
            b, prompt, plen, max_new, adapter)
        rec = {"rid": rid, "prompt": prompt, "plen": plen,
               "max_new": int(max_new), "temperature": temperature,
               "seed": seed, "adapter": adapter, "caches": caches,
               # the adapter graft is fixed for the whole admission:
               # build it ONCE here, not once per segment pass
               "params": self.gen._graft_adapters(
                   self.gen.params, jnp.int32(adapter)),
               "cursor": int(cursor)}
        rec.update(extras)
        self._slot_req[b] = rid
        self._staging[b] = rec
        if self.prefill_observer is not None:
            self.prefill_observer({"kind": "begin", "rid": rid,
                                   "slot": b, "plen": plen,
                                   "cursor": rec["cursor"]})

    def _advance_staged(self, budget):
        """Advance staged prefills by bounded chunk passes, spending
        at most ``budget`` prompt tokens this tick (pow2 bucketing may
        overshoot by < 2x); an admission whose cursor reaches
        plen - 1 finishes into its reserved slot — with the exact
        cache row and start position the unsegmented admission hands
        over.  Returns the budget left."""
        gen = self.gen
        for b in sorted(self._staging):
            rec = self._staging[b]
            while budget > 0 and rec["cursor"] < rec["plen"] - 1:
                start = rec["cursor"]
                want = min(self.prefill_segment,
                           rec["plen"] - 1 - start, budget)
                kb = gen._bucket(max(1, want), gen.max_len - start)
                chunk = np.zeros((kb,), np.int32)
                n_real = min(rec["plen"] - start, kb)
                chunk[:n_real] = rec["prompt"][start:start + n_real]
                t0 = time.perf_counter()
                rec["caches"] = gen._prefill_resume_fn(kb)(
                    rec["params"], rec["caches"],
                    jnp.asarray(chunk[None]), jnp.int32(start))
                # block: the per-tick stall bound is only honest if
                # the segment's device work is DONE before the decode
                # dispatch below (one device queue serializes them
                # anyway) — and it makes the observer's seconds a real
                # prefill-rate measurement, not a dispatch time
                jax.block_until_ready(
                    jax.tree_util.tree_leaves(rec["caches"])[0])
                dt = time.perf_counter() - t0
                rec["cursor"] = min(start + kb, rec["plen"] - 1)
                budget -= kb
                if self.prefill_observer is not None:
                    self.prefill_observer(
                        {"kind": "segment", "rid": rec["rid"],
                         "slot": b, "start": start, "tokens": kb,
                         "cursor": rec["cursor"], "plen": rec["plen"],
                         "seconds": dt})
            if rec["cursor"] >= rec["plen"] - 1:
                del self._staging[b]
                self._finish_staged(b, rec)
                if self.prefill_observer is not None:
                    self.prefill_observer(
                        {"kind": "admit", "rid": rec["rid"],
                         "slot": b, "plen": rec["plen"]})
        return budget

    def _finish_staged(self, b, rec):
        """Staged prefill complete: run the normal admission scatter
        with the accumulated cache row at pos0 = plen - 1 (the same
        cursor _prefill_row's full chunk hands over at)."""
        self._ensure_admit_fns()
        st = self._admit_fn(*self._admit_args(b, rec),
                            jnp.int32(rec["plen"] - 1), rec["caches"])
        self._set_state(st)

    def _admit_args(self, b, rec):
        """The shared positional prefix of _admit_fn/_admit_fresh_fn
        (state + scalar slot writes) for one request record."""
        prow = np.zeros((self.gen.max_len,), np.int32)
        prow[:rec["plen"]] = rec["prompt"]
        return (self._state(), jnp.int32(b), jnp.asarray(prow),
                jnp.int32(rec["plen"]),
                jnp.int32(rec["plen"] + rec["max_new"]),
                jnp.int32(rec["seed"]),
                jnp.float32(0.0 if rec["temperature"] == 0.0
                            else 1.0 / rec["temperature"]))

    def prefill_backlog_tokens(self):
        """Queued-but-unprefilled prompt tokens: whole prompts still
        in the queue plus the unprefilled remainder of staged
        admissions — the serving plane's prefill-backlog gauge (the
        fleet autoscaler's early scale-up signal)."""
        queued = sum(len(item[1]) for item in self._queue)
        staged = sum(max(0, rec["plen"] - 1 - rec["cursor"])
                     for rec in self._staging.values())
        return queued + staged

    def staging_slots(self):
        """Slots currently mid-staged-prefill (reserved, not yet
        decoding)."""
        return len(self._staging)

    def _ensure_admit_fns(self):
        if self._admit_fn is not None:
            return
        gen = self.gen

        def admit_body(st, b, prow, plen, total, seed, inv_temp,
                       pos0, cache_row):
            (tokens, pos, plens, totals, active, seeds, its,
             caches) = st
            tokens = jax.lax.dynamic_update_slice(
                tokens, prow[None], (b, 0))
            pos = pos.at[b].set(pos0)
            plens = plens.at[b].set(plen)
            totals = totals.at[b].set(total)
            active = active.at[b].set(True)
            seeds = seeds.at[b].set(seed)
            its = its.at[b].set(inv_temp)
            # the [1, ...] row replaces the slot's ENTIRE cache —
            # either freshly initialized (stale K/V from the
            # previous occupant must not leak) or chunk-prefilled
            # with the new prompt
            caches = jax.tree_util.tree_map(
                lambda pool, one: jax.lax.dynamic_update_slice(
                    pool, one.astype(pool.dtype),
                    (b,) + (0,) * (pool.ndim - 1)),
                caches, cache_row)
            return (tokens, pos, plens, totals, active, seeds, its,
                    caches)

        def admit_fresh(st, b, prow, plen, total, seed, inv_temp):
            # fresh values built INSIDE the jit (zeros, QuantCache
            # scale ones) — the non-prefill path pays no extra
            # dispatch and no host-built zero tree
            return admit_body(st, b, prow, plen, total, seed,
                              inv_temp, jnp.int32(0),
                              gen._init_caches(1,
                                               gen._model_dtype()))

        self._admit_fn = jax.jit(admit_body, donate_argnums=(0,))
        self._admit_fresh_fn = jax.jit(admit_fresh,
                                       donate_argnums=(0,))

    def _admit(self, b):
        (rid, prompt, max_new, temperature, seed,
         adapter) = self._queue.popleft()
        plen = len(prompt)
        self._aids = self._aids.at[b].set(adapter)
        self._ensure_admit_fns()
        cache_row, pos0 = self._prefill_row(prompt, plen, max_new,
                                            adapter)
        rec = {"prompt": prompt, "plen": plen, "max_new": int(max_new),
               "temperature": temperature, "seed": seed}
        args = self._admit_args(b, rec)
        if cache_row is None:
            st = self._admit_fresh_fn(*args)
        else:
            st = self._admit_fn(*args, jnp.int32(pos0), cache_row)
        self._set_state(st)
        self._slot_req[b] = rid

    def _make_core(self, step_all=None):
        """The per-tick body over the 8-tuple state — shared verbatim
        by the dense tick and BOTH paged ticks (gather and fused), so
        the admission models can never diverge on decode semantics.

        ``step_all(params, cache_state, cur, pos) -> (logits,
        cache_state)`` abstracts how a tick runs the stack: the dense
        default vmaps gen._step per row over slot-major caches; the
        paged FUSED path substitutes the pool-batched gen._step_paged
        (the pool is shared across rows, so it cannot vmap).  Token
        selection, sampling, prompt forcing, and the freeze logic stay
        this one function either way."""
        gen = self.gen

        if step_all is None:
            def row_step(params, caches, tok, pos, aid):
                # single-row view: add the batch dim the stack expects;
                # under vmap the per-row ``pos`` scatter-writes each
                # slot at its own depth.  Adapter grafting happens per
                # row (scalar aid) — identity without banks.
                c1 = jax.tree_util.tree_map(lambda a: a[None], caches)
                logits, c1 = gen._step(
                    gen._graft_adapters(params, aid), c1, tok[None],
                    pos)
                return logits[0], jax.tree_util.tree_map(
                    lambda a: a[0], c1)

            def step_all(params, caches, cur, pos, aids):
                return jax.vmap(row_step, in_axes=(None, 0, 0, 0, 0))(
                    params, caches, cur, pos, aids)

        def core(params, st, aids):
            (tokens, pos, plen, total, active, seeds, inv_temp,
             caches) = st
            B = tokens.shape[0]
            rows = jnp.arange(B)
            cur = tokens[rows, pos]
            logits, caches = step_all(params, caches, cur, pos, aids)
            greedy_tok = jnp.argmax(logits, axis=-1).astype(
                jnp.int32)

            def draw(_):
                keys = jax.vmap(
                    lambda s, p: jax.random.fold_in(
                        jax.random.key(s), p))(seeds, pos)
                sampled = jax.vmap(
                    lambda lg, k, it: jax.random.categorical(
                        k, lg * it))(logits, keys,
                                     inv_temp).astype(jnp.int32)
                return jnp.where(inv_temp > 0.0, sampled,
                                 greedy_tok)

            # all-greedy pools (the serving default) skip the
            # whole-vocab gumbel draw entirely — same guard as
            # _decode_body's lax.cond
            nxt = jax.lax.cond(jnp.any(inv_temp > 0.0), draw,
                               lambda _: greedy_tok, None)
            # prefilling rows force their own next prompt token
            in_prompt = pos + 1 < plen
            forced = tokens[rows, jnp.minimum(pos + 1,
                                              tokens.shape[1] - 1)]
            nxt = jnp.where(in_prompt, forced, nxt)
            write = active & (pos + 1 < tokens.shape[1])
            tokens = tokens.at[rows, jnp.minimum(
                pos + 1, tokens.shape[1] - 1)].set(
                jnp.where(write, nxt, tokens[rows, jnp.minimum(
                    pos + 1, tokens.shape[1] - 1)]))
            pos = jnp.where(active, pos + 1, pos)
            # rows that just hit their budget freeze IN-JIT, so a
            # fused multi-tick scan can't overshoot max_new (the
            # host re-derives completion from slot occupancy)
            active = active & (pos + 1 < total)
            return (tokens, pos, plen, total, active, seeds,
                    inv_temp, caches)

        return core

    def _make_core_spec(self, draft_k):
        """Speculative tick core (``speculative_k`` > 0, dense slot
        pools): every active row drafts ``draft_k`` candidate tokens
        from its own history (the n-gram rule of LMGenerator._spec_fn)
        and verifies them in ONE chunk pass per tick, advancing by
        1 + accepted instead of 1.

        EXACT decode semantics, PER ROW:
        * greedy rows accept exactly the prefix of drafts that equal
          the verify pass's own argmax — the accepted tokens ARE the
          argmax chain, so outputs match the 1-token core token for
          token;
        * prompt positions auto-accept their own forced tokens (a
          prefilling row fast-forwards through its prompt — same
          tokens and cache writes, fewer ticks);
        * sampled rows accept only forced prompt positions, then draw
          their ONE new token from the chunk's logits at that position
          with the identical (seed, position) key the 1-token core
          would have used — bit-equal streams.

        Routing is PER ROW: the draft/verify/acceptance math runs
        identically for every row regardless of what it shares the
        pool with, and each row's ``sampled = inv_temp > 0`` flag
        selects its own token in a ``where``.  The only pool-wide
        ``lax.cond`` left gates the PRICE of the gumbel draws (the
        1-token core's own all-greedy guard) — never the speculation
        semantics, so one sampled request cannot strip speculation
        from (or perturb by one bit) the greedy rows around it.  The
        old pool-wide branch between a sampled and a greedy step
        function — the `serve.spec_degraded` cliff — is gone.

        The chunk writes draft-conditioned K/V up to ``draft_k``
        positions past a row's cursor; rejected-tail entries are
        rewritten by a later chunk before any mask lets them be
        attended (mha_chunk_step's contract).  submit() therefore
        requires plen + max_new + draft_k <= max_len."""
        gen = self.gen
        kk = int(draft_k)
        ll = gen.max_len
        idx = jnp.arange(kk)

        def row_verify(params, caches, row, pos, aid, inv_temp, plen,
                       total):
            """Per-row draft + K-wide verify + acceptance count — NO
            sampling in here; the draw routes per row outside the
            vmap, so the verify math is one program for every pool
            mix."""
            params = gen._graft_adapters(params, aid)
            c1 = jax.tree_util.tree_map(lambda a: a[None], caches)
            draft = _ngram_draft(row, pos, kk, ll)
            # candidate positions are pos+1 .. pos+kk; submit()'s
            # total + kk <= max_len bound keeps every slice in range
            # (no clamping, so read/write windows always align)
            in_prompt = (pos + 1 + idx) < plen
            old = jax.lax.dynamic_slice(row, (pos + 1,), (kk,))
            draft = jnp.where(in_prompt, old, draft)
            cur_tok = jax.lax.dynamic_slice(row, (pos,), (1,))
            chunk = jnp.concatenate([cur_tok, draft[:-1]])[None]
            logits, c1 = gen._chunk_logits(params, c1, chunk, pos)
            g = jnp.argmax(logits, axis=-1).astype(jnp.int32)

            sampled = inv_temp > 0.0
            ok = in_prompt | (~sampled & (draft == g))
            # first rejection = acceptance count; cap so the bonus
            # position always has its own logits AND the row never
            # writes past total - 1
            a = jnp.minimum(jnp.argmin(jnp.concatenate(
                [ok, jnp.zeros((1,), bool)])), kk - 1)
            a = jnp.minimum(a, jnp.maximum(total - 2 - pos, 0))
            return (jax.tree_util.tree_map(lambda x: x[0], c1),
                    draft, old, in_prompt, a, jnp.take(g, a),
                    logits[a])

        verify_all = jax.vmap(row_verify,
                              in_axes=(None, 0, 0, 0, 0, 0, 0, 0))

        def core(params, st, aids):
            (tokens, pos, plen, total, active, seeds, inv_temp,
             caches) = st
            (caches, draft, old, in_prompt, a, g_a, logits_a) = \
                verify_all(params, caches, tokens, pos, aids,
                           inv_temp, plen, total)
            sampled = inv_temp > 0.0

            def draw(_):
                keys = jax.vmap(
                    lambda s, p: jax.random.fold_in(
                        jax.random.key(s), p))(seeds, pos + a)
                smp = jax.vmap(
                    lambda lg, k, it: jax.random.categorical(
                        k, lg * it))(logits_a, keys,
                                     inv_temp).astype(jnp.int32)
                return jnp.where(sampled, smp, g_a)

            # all-greedy pools (the serving default) skip the
            # whole-vocab gumbel draws entirely — same cost guard as
            # the 1-token core's lax.cond; greedy rows select g_a on
            # BOTH sides of it, so the branch can never change a
            # greedy row's bytes
            gen_tok = jax.lax.cond(jnp.any(sampled), draw,
                                   lambda _: g_a, None)
            old_a = jnp.take_along_axis(old, a[:, None], 1)[:, 0]
            prompt_a = jnp.take_along_axis(in_prompt, a[:, None],
                                           1)[:, 0]
            # the bonus position must never overwrite a teacher-forced
            # prompt token
            bonus = jnp.where(prompt_a, old_a, gen_tok)
            newvec = jnp.where(idx[None, :] < a[:, None], draft,
                               jnp.where(idx[None, :] == a[:, None],
                                         bonus[:, None], old))
            # frozen rows write their own old values back (idempotent)
            newvec = jnp.where(active[:, None]
                               & (idx[None, :] <= a[:, None]),
                               newvec, old)
            tokens = jax.vmap(
                lambda r, nv, p: jax.lax.dynamic_update_slice(
                    r, nv, (p + 1,)))(tokens, newvec, pos)
            pos = pos + jnp.where(active, a + 1, 0)
            active = active & (pos + 1 < total)
            return (tokens, pos, plen, total, active, seeds,
                    inv_temp, caches)

        return core

    def _jit_ticks(self, tick_fn):
        """ticks_per_dispatch engine ticks fused into ONE jitted
        dispatch (lax.scan over ``tick_fn(params, state) -> state``),
        state donated: without aliasing, every per-token tick would
        copy the whole slots×layers KV-cache pool.  One helper shared
        by the dense tick and both paged flavors so the dispatch-fusion
        contract can never diverge between them."""
        def fused(params, st, aids):
            def body(carry, _):
                return tick_fn(params, carry, aids), None
            return jax.lax.scan(body, st, None,
                                length=self.ticks_per_dispatch)[0]

        return jax.jit(fused, donate_argnums=(1,))

    def _tick_body(self):
        """The un-jitted tick body ``fn(params, state, aids) -> state``
        this batcher dispatches (through :meth:`_jit_ticks`).  ONE
        construction point shared by the engine and the decode-path
        auditor (``analysis.decode_audit``), which abstractly traces
        exactly this function — so the lint can never audit a different
        tick than serving runs."""
        return (self._make_core_spec(self.speculative_k)
                if self.speculative_k else self._make_core())

    def _tick(self, st):
        if self._tick_fn is None:
            self._tick_fn = self._jit_ticks(self._tick_body())
        return self._tick_fn(self.gen.params, st, self._aids)


def parse_paged_block(value):
    """The ``serve.paged_block`` grammar, shared by the engine and the
    CLI: ``0``/``''``/``None``/``"off"`` → dense slot pool; a positive
    int → paged KV with that pool block; ``"auto"``/``-1`` → paged KV
    with the block resolved at admission through config > the kernel
    autotuner > default (``PagedContinuousBatcher(block=None)``, see
    ops.pallas.paged.preferred_pool_block).  Returns
    ``(paged, block_or_None)``."""
    if value in (None, "", 0, "0", False, "off"):
        return False, None
    if value in ("auto", -1, "-1"):
        return True, None
    n = int(value)
    if n <= 0:
        return False, None
    return True, n


class PagedContinuousBatcher(ContinuousBatcher):
    """Paged-KV continuous batching: slot caches live in a SHARED block
    pool addressed through per-slot block tables, so KV memory scales
    with the pool budget (sum of active request lengths, rounded up to
    blocks) instead of ``slots x max_len`` — the vLLM block-table idea
    (Kwon et al. 2023) recast for XLA's static shapes.

    Layout: every dense cache leaf [B, H, T, *] becomes a pool leaf
    [P, H, block, *] plus one shared int32 table [B, T/block]; block 0
    is a reserved dummy all unallocated table entries point at.  A
    request's block count is KNOWN at admission (prompt + max_new), so
    allocation is a host-side free-list pop at admit and a push at
    completion — no in-decode growth, and ADMISSION BACKPRESSURES on
    pool exhaustion exactly like on slot exhaustion (a queued request
    waits until both a slot and enough blocks free up).

    Two tick flavors share the dense batcher's decode core
    (sampling/forcing/freeze logic — _make_core):

    * ``fused=True`` (default): attention reads the pool THROUGH the
      block table inside a scalar-prefetch Pallas kernel
      (ops.pallas.paged), and each layer scatters its new k/v straight
      into its pool block — no dense re-materialization at all, and
      reads stop at each row's own length instead of max_len.
      QuantCache pools auto-fall back to the gather tick (the kernel
      reads plain-dtype pools only).
    * gather (``fused=False``): gather each row's blocks into a dense
      [B, H, T, *] view, run the dense core verbatim, scatter the
      newly written position back (~2x cache traffic — the classic
      paged-attention overhead the fused path erases).  Outputs are
      EXACTLY the dense batcher's: same core, same per-row positions,
      same seeds.  The fused path differs from dense only at the
      last-ulp level (online softmax + pool-dtype MXU inputs, same as
      flash vs naive).

        cb = PagedContinuousBatcher(gen, slots=8, block=16,
                                    pool_tokens=512)
    """

    def __init__(self, gen, slots=8, ticks_per_dispatch=1,
                 chunked_prefill=True, block=None, pool_tokens=None,
                 fused=True, prefix_cache=False, speculative_k=0,
                 prefill_segment=0, prefill_tick_budget=0):
        if int(speculative_k):
            raise ValueError(
                "speculative ticks are dense-pool only (the chunk "
                "verify would write draft K/V through the block "
                "table) — use ContinuousBatcher(speculative_k=...)")
        super(PagedContinuousBatcher, self).__init__(
            gen, slots=slots, ticks_per_dispatch=ticks_per_dispatch,
            chunked_prefill=chunked_prefill,
            prefill_segment=prefill_segment,
            prefill_tick_budget=prefill_tick_budget)
        L = gen.max_len
        # shapes WITHOUT allocating the dense caches (eval_shape): the
        # whole point of paging is that dense slots x max_len may not
        # fit, so construction must never spike to dense + pool; ONE
        # abstract trace serves both the auto-block probe below and
        # the pool layout/pageability checks
        cache_shapes = jax.eval_shape(
            lambda: gen._init_caches(slots, gen._model_dtype()))
        if block is None:
            # unpinned pool block: config > tuned paged.decode winner >
            # 16 (ops.pallas.paged.preferred_pool_block) — the pool
            # layout is THE launch geometry of the fused decode kernel,
            # and admission is the only point it can be chosen
            from veles_tpu.ops.pallas import paged as _paged
            try:
                leaf = next(s for s in
                            jax.tree_util.tree_leaves(cache_shapes)
                            if len(s.shape) == 4)
                hkv, hd = leaf.shape[1], leaf.shape[-1]
                g = max(1, int(getattr(gen._blocks[0], "n_heads", hkv))
                        // int(hkv))
                block = _paged.preferred_pool_block(hd, g, leaf.dtype)
            except Exception:  # noqa: BLE001 — odd cache pytrees
                block = 16
            # a tuned block must still divide max_len; config/explicit
            # blocks keep the hard error below instead
            if L % int(block):
                block = 16
        if L % int(block):
            raise ValueError("max_len %d %% block %d != 0"
                             % (L, int(block)))
        self.block = int(block)
        self.max_blocks = L // self.block
        pool_tokens = int(pool_tokens or slots * L)
        self.pool_blocks = max(1, pool_tokens // self.block)
        for leaf in jax.tree_util.tree_leaves(cache_shapes):
            if leaf.shape[2] != L:
                raise ValueError(
                    "paged KV needs full-length caches; a rolling-"
                    "window layer (cache T=%d < max_len %d) is not "
                    "pageable" % (leaf.shape[2], L))

        def to_pool(leaf):
            # [B, H, T, *] -> [1 + P, H, block, *]; block 0 = dummy
            shape = ((1 + self.pool_blocks, leaf.shape[1], self.block)
                     + leaf.shape[3:])
            return jnp.zeros(shape, leaf.dtype)

        # zero-filled pool is safe for every leaf kind: QuantCache
        # scales for unwritten positions are never read (decode writes
        # before use, _init_caches' own invariant), and the dummy
        # block 0 is never read at all
        self._pool = jax.tree_util.tree_map(to_pool, cache_shapes)
        self._tables = jnp.zeros((slots, self.max_blocks), jnp.int32)
        self._free = list(range(1, 1 + self.pool_blocks))
        self._slot_blocks = {}               # slot -> [block ids]
        #: prefix caching (copy-on-write block sharing): concurrent
        #: requests whose prompts share a prefix share the pool blocks
        #: that hold it — the system-prompt serving case pays for the
        #: prefix ONCE in KV memory.  Sharing is CORRECT because a
        #: block's K/V is a deterministic function of (params, token
        #: prefix, absolute positions): only blocks fully covered by
        #: the prompt AND fully written at admission (chunked prefill
        #: ran) are registered, later sharers skip the admit scatter
        #: for matched blocks (diverted to the dummy block) so an
        #: in-flight sharer's K/V is never rewritten with anything but
        #: identical bytes, and generation never writes into a
        #: registered block (those end before the first generated
        #: position).  Blocks free when their last owner releases.
        self.prefix_cache = bool(prefix_cache)
        self._prefix_reg = {}                # token-prefix -> block id
        self._prefix_ref = {}                # block id -> owner count
        self._block_key = {}                 # block id -> its reg key
        self._resume_gather_fn = None        # jitted row gather (lazy)
        #: fused tick: attention reads the pool through the block table
        #: (ops.pallas.paged scalar-prefetch kernel) — no per-tick
        #: dense gather/scatter.  QuantCache pools run the kernel's
        #: quantized variant (int8 K/V streamed from HBM, dequantized
        #: in VMEM with f32 accumulation — the int8 payload stays
        #: narrow all the way into the decode dots).  Auto-fallback to
        #: the gather tick only for window >= max_len models (linear
        #: cache, so they pass the pageability check, but the kernel
        #: has no window mask — the gather tick served them before and
        #: still does).
        windowed = any(getattr(l, "cfg", {}).get("window")
                       for l in gen._blocks)
        # Mosaic sublane bound: a pool block is the fused kernel's K/V
        # tile, so when the kernel would actually be Mosaic-compiled
        # (a real TPU backend — interpret mode takes any size), blocks
        # below the dtype's sublane minimum (32 rows for int8 pools)
        # fall back to the gather tick exactly like window pools do,
        # instead of failing compilation at the first tick.
        from veles_tpu.ops import pallas as _pallas
        pool_dtype = jax.tree_util.tree_leaves(cache_shapes)[0].dtype
        mosaic_ok = (_pallas.autodetect_interpret(None)
                     or self.block
                     >= _pallas.mosaic_sublane_min(pool_dtype))
        self.fused = (bool(fused) and not windowed and mosaic_ok)

    def _init_slot_caches(self):
        return None                          # the pool replaces them

    # ------------------------------------------------------------ hooks
    def _blocks_needed(self, plen, max_new):
        total = plen + max_new
        return -(-total // self.block)

    def submit(self, prompt, max_new, temperature=0.0, seed=0,
               adapter=0):
        """Reject a request larger than the ENTIRE pool up front — it
        could never be admitted, and a forever-queued request would
        deadlock run_all()/the serving engine."""
        nb = self._blocks_needed(len(prompt), int(max_new))
        if nb > self.pool_blocks:
            raise ValueError(
                "request needs %d KV blocks (prompt %d + max_new %d, "
                "block %d) but the pool only has %d — raise "
                "pool_tokens or shorten the request"
                % (nb, len(prompt), int(max_new), self.block,
                   self.pool_blocks))
        return super(PagedContinuousBatcher, self).submit(
            prompt, max_new, temperature=temperature, seed=seed,
            adapter=adapter)

    def _shareable_blocks(self, plen):
        """Blocks of an admitted request that decode NEVER writes:
        chunked-prefill admission starts ticking at pos0 = plen - 1
        (the last prompt token re-enters the step), so only blocks
        strictly before the one holding position plen - 1 are safe to
        share — on BOTH sides (registration by the first owner, and
        matching by later sharers, whose own writes start at their own
        plen - 1).  The tick-by-tick admission path writes every
        position from 0 and can share nothing."""
        if not self._will_chunk(plen):
            return 0
        return (plen - 1) // self.block

    def _match_prefix(self, prompt, adapter=0):
        """Longest run of registered blocks covering this prompt's
        prefix, from block 0 — the block ids a new sharer reuses.
        Keys chain per block — (parent block id, adapter id, that
        block's own tokens) — so matching is one O(plen) walk and
        registry memory is O(plen), not O(plen^2) full-prefix tuples.
        The adapter id is part of every link: adapters change the
        prefix's K/V, so sharing is only valid within one adapter."""
        if not self.prefix_cache:
            return []
        out, parent = [], 0
        for i in range(self._shareable_blocks(len(prompt))):
            blk = self._prefix_reg.get(
                (parent, int(adapter),
                 tuple(prompt[i * self.block:(i + 1) * self.block])))
            if blk is None:
                break
            out.append(blk)
            parent = blk
        return out

    def _can_admit(self):
        if not self._queue or None not in self._slot_req:
            return False
        _, prompt, max_new, _, _, adapter = self._queue[0]
        need = self._blocks_needed(len(prompt), max_new) \
            - len(self._match_prefix(prompt, adapter))
        return need <= len(self._free)

    def free_blocks(self):
        """Unallocated pool blocks — the serving plane's memory gauge."""
        return len(self._free)

    def prefix_stats(self):
        """(registered shared blocks, total owner refs) — the prefix-
        cache gauge; refs > blocks means live sharing.  Public
        accessor: the engine reads gauges only through methods."""
        return len(self._prefix_ref), sum(self._prefix_ref.values())

    def _release_slot(self, b):
        super(PagedContinuousBatcher, self)._release_slot(b)
        for blk in self._slot_blocks.pop(b, ()):
            if blk in self._prefix_ref:
                self._prefix_ref[blk] -= 1
                if self._prefix_ref[blk] == 0:
                    del self._prefix_ref[blk]
                    del self._prefix_reg[self._block_key.pop(blk)]
                    self._free.append(blk)
            else:
                self._free.append(blk)
        self._tables = self._tables.at[b].set(0)

    def reset_pool(self):
        """Fault reset, paged flavor: also rebuild the block pool, the
        tables, the free list, and the prefix-cache registries —
        every block returns to the free list (``cancel``/release paths
        already keep per-request accounting exact; this is the big
        hammer for a corrupted-pool fault)."""
        ContinuousBatcher.reset_pool(self)
        self._pool = jax.tree_util.tree_map(
            lambda leaf: jnp.zeros(leaf.shape, leaf.dtype), self._pool)
        self._tables = jnp.zeros((self.slots, self.max_blocks),
                                 jnp.int32)
        self._free = list(range(1, 1 + self.pool_blocks))
        self._slot_blocks = {}
        self._prefix_reg = {}
        self._prefix_ref = {}
        self._block_key = {}

    def _state(self):
        return (self._tokens, self._pos, self._plen, self._total,
                self._active, self._seeds, self._inv_temp,
                self._pool, self._tables)

    def _set_state(self, st):
        (self._tokens, self._pos, self._plen, self._total,
         self._active, self._seeds, self._inv_temp,
         self._pool, self._tables) = st

    # -------------------------------------------------------- admission
    def _claim_blocks(self, b, prompt, max_new, adapter,
                      register=True):
        """Allocate slot ``b``'s KV blocks (reusing matched prefix
        blocks, ref-counted) and return ``(matched, will_chunk,
        table_row, srow)``.  ``register=False`` defers prefix-cache
        REGISTRATION: a staged (segmented) admission's new blocks hold
        no K/V until the finish scatter runs, so they must not be
        matchable by another admission in between —
        _register_staged_blocks publishes them at finish instead."""
        plen = len(prompt)
        nb = self._blocks_needed(plen, max_new)
        will_chunk = self._will_chunk(plen)
        matched = self._match_prefix(prompt, adapter)
        # registerable = blocks the chunk prefill writes COMPLETELY at
        # admit and that decode never touches (_shareable_blocks); the
        # tick-by-tick path fills blocks progressively — a later
        # sharer could attend positions nobody has written
        registerable = self._shareable_blocks(plen) if will_chunk \
            else 0
        ids, scatter_row, parent = [], [], 0
        for i in range(nb):
            if i < len(matched):
                blk = matched[i]
                self._prefix_ref[blk] += 1
                # skip the admit scatter for matched blocks (divert to
                # the dummy block): they already hold the prefix K/V,
                # and a fresh-init scatter would zero them under an
                # in-flight sharer
                scatter_row.append(0)
            else:
                blk = self._free.pop()
                if register and self.prefix_cache \
                        and i < registerable:
                    key = (parent, int(adapter), tuple(
                        prompt[i * self.block:(i + 1) * self.block]))
                    self._prefix_reg[key] = blk
                    self._prefix_ref[blk] = 1
                    self._block_key[blk] = key
                scatter_row.append(blk)
            parent = blk
            ids.append(blk)
        self._slot_blocks[b] = ids
        table_row = np.zeros((self.max_blocks,), np.int32)
        table_row[:nb] = ids
        srow = np.zeros((self.max_blocks,), np.int32)
        srow[:nb] = scatter_row
        return matched, will_chunk, table_row, srow

    def _register_staged_blocks(self, prompt, adapter, ids,
                                registerable, matched):
        """Publish a finished staged admission's shareable blocks in
        the prefix registry (deferred from _claim_blocks: their K/V
        exists only after the finish scatter).  A key another request
        registered meanwhile keeps ITS block — ours stays a private
        allocation and frees normally on release."""
        if not self.prefix_cache:
            return
        parent = 0
        for i, blk in enumerate(ids):
            if i >= registerable:
                break
            if i < len(matched):
                parent = blk
                continue
            key = (parent, int(adapter), tuple(
                prompt[i * self.block:(i + 1) * self.block]))
            if key not in self._prefix_reg \
                    and blk not in self._prefix_ref:
                self._prefix_reg[key] = blk
                self._prefix_ref[blk] = 1
                self._block_key[blk] = key
            parent = blk

    def _staged_setup(self, b, prompt, plen, max_new, adapter):
        """Paged staging: claim the blocks now (admission
        backpressure accounting stays exact — _can_admit already
        checked them against the free list) and start the cache row
        from the matched prefix when there is one."""
        matched, will_chunk, table_row, srow = self._claim_blocks(
            b, prompt, max_new, adapter, register=False)
        extras = {"trow": table_row, "srow": srow, "matched": matched,
                  "registerable": (self._shareable_blocks(plen)
                                   if will_chunk else 0)}
        if matched:
            # resume from the shared prefix blocks: gather this row's
            # table view (real K/V for [0, start), dummy elsewhere)
            caches = self._gather_row_view(table_row)
            cursor = len(matched) * self.block
        else:
            caches = self.gen._init_caches(1, self.gen._model_dtype())
            cursor = 0
        return caches, cursor, extras

    def _finish_staged(self, b, rec):
        self._ensure_admit_fns()
        self._register_staged_blocks(
            rec["prompt"], rec["adapter"], self._slot_blocks.get(b, ()),
            rec["registerable"], rec["matched"])
        st = self._admit_fn(*self._admit_args(b, rec),
                            jnp.asarray(rec["trow"]),
                            jnp.asarray(rec["srow"]),
                            jnp.int32(rec["plen"] - 1), rec["caches"])
        self._set_state(st)

    def _admit(self, b):
        (rid, prompt, max_new, temperature, seed,
         adapter) = self._queue.popleft()
        plen = len(prompt)
        self._aids = self._aids.at[b].set(adapter)
        matched, will_chunk, table_row, srow = self._claim_blocks(
            b, prompt, max_new, adapter)
        if matched and will_chunk:
            # prefix-cache COMPUTE skip: the matched blocks already
            # hold positions [0, start) — resume the chunk prefill
            # from there instead of re-running the whole prompt
            # forward (the dominant admission cost for long shared
            # system prompts).  The resume row gathers this row's
            # table view (real prefix + dummies), chunk-steps
            # [start, start+kb), and the admit scatter then stores
            # only the NEW blocks (srow already diverts matched ones).
            cache_row, pos0 = self._resume_row(prompt, plen, matched,
                                               table_row, adapter)
        else:
            cache_row, pos0 = self._prefill_row(prompt, plen, max_new,
                                                adapter)
        self._ensure_admit_fns()
        rec = {"prompt": prompt, "plen": plen, "max_new": int(max_new),
               "temperature": temperature, "seed": seed}
        args = self._admit_args(b, rec) + (jnp.asarray(table_row),
                                           jnp.asarray(srow))
        if cache_row is None:
            st = self._admit_fresh_fn(*args)
        else:
            st = self._admit_fn(*args, jnp.int32(pos0), cache_row)
        self._set_state(st)
        self._slot_req[b] = rid

    def _ensure_admit_fns(self):
        if self._admit_fn is not None:
            return
        gen = self.gen
        bs, nbm = self.block, self.max_blocks

        def admit_body(st, b, prow, plen_, total, seed_, inv_temp,
                       trow, srow, pos0_, crow):
            # ONE fused dispatch, mirroring the dense admit_body
            # (same scalar writes) + the table row and the prompt
            # cache blocks scattered into the pool.  Dummy table
            # entries (0) scatter into the dummy block — harmless,
            # never read.  ``srow`` is ``trow`` with prefix-shared
            # blocks diverted to the dummy block: their K/V already
            # lives in the pool and must not be rewritten under an
            # in-flight sharer.
            (tokens, pos, plens, totals, active, seeds, its,
             pool, tables) = st
            tokens = jax.lax.dynamic_update_slice(
                tokens, prow[None], (b, 0))
            pos = pos.at[b].set(pos0_)
            plens = plens.at[b].set(plen_)
            totals = totals.at[b].set(total)
            active = active.at[b].set(True)
            seeds = seeds.at[b].set(seed_)
            its = its.at[b].set(inv_temp)
            tables = jax.lax.dynamic_update_slice(
                tables, trow[None], (b, 0))

            def one(pl, rw):
                blocks = jnp.moveaxis(
                    rw[0].reshape((rw.shape[1], nbm, bs)
                                  + rw.shape[3:]), 1, 0)
                return pl.at[srow].set(blocks.astype(pl.dtype))

            pool = jax.tree_util.tree_map(one, pool, crow)
            return (tokens, pos, plens, totals, active, seeds,
                    its, pool, tables)

        def admit_fresh(st, b, prow, plen_, total, seed_,
                        inv_temp, trow, srow):
            return admit_body(st, b, prow, plen_, total, seed_,
                              inv_temp, trow, srow, jnp.int32(0),
                              gen._init_caches(
                                  1, gen._model_dtype()))

        self._admit_fn = jax.jit(admit_body, donate_argnums=(0,))
        self._admit_fresh_fn = jax.jit(admit_fresh,
                                       donate_argnums=(0,))

    def _gather_row_view(self, table_row):
        """Gather ONE slot's table view from the pool into a dense
        [1, ...] cache row: real K/V for every allocated block, dummy-
        block content elsewhere (rewritten or masked before any read —
        the round-up-prefill argument).  Shared by the prefix-resume
        admission and segmented staging."""
        bs, nbm = self.block, self.max_blocks
        if self._resume_gather_fn is None:
            def gather_row(pool, trow):
                def one(pl):
                    v = pl[trow]                 # [nbm, H, bs, *]
                    v = jnp.moveaxis(v, 1, 0)    # [H, nbm, bs, *]
                    return v.reshape(
                        (1, v.shape[0], nbm * bs) + v.shape[3:])
                return [tuple(jax.tree_util.tree_map(one, c)
                              for c in layer)
                        for layer in pool]
            self._resume_gather_fn = jax.jit(gather_row)
        return self._resume_gather_fn(self._pool,
                                      jnp.asarray(table_row))

    def _resume_row(self, prompt, plen, matched, table_row, adapter):
        """Build an admission cache row by RESUMING from the matched
        prefix blocks: gather this row's table view into a dense
        [1, ...] row (real K/V for positions [0, start), dummy-block
        content elsewhere — rewritten below or masked until decode
        overwrites it, the round-up-prefill argument), then chunk-step
        positions [start, start+kb) under the request's adapter.
        Returns (cache_row, plen - 1) — the same cursor the full
        chunk prefill hands over at."""
        gen = self.gen
        start = len(matched) * self.block
        kb = gen._bucket(plen - start, gen.max_len - start)
        caches = self._gather_row_view(table_row)
        chunk = np.zeros((kb,), np.int32)
        chunk[:min(plen - start, kb)] = prompt[start:start + kb]
        params = gen._graft_adapters(gen.params, jnp.int32(adapter))
        return gen._prefill_resume_fn(kb)(
            params, caches, jnp.asarray(chunk[None]),
            jnp.int32(start)), plen - 1

    # ------------------------------------------------------------- tick
    def _tick_body(self):
        if self.fused:
            gen = self.gen

            def paged_step_all(params, cache_state, cur, pos,
                               aids):
                pool, tables = cache_state
                # vector-aid graft: gathered lora leaves carry a
                # leading [B] dim that _qkv_proj's matmul broadcasts
                logits, pool = gen._step_paged(
                    gen._graft_adapters(params, aids), pool, tables,
                    cur, pos)
                return logits, (pool, tables)

            core = self._make_core(step_all=paged_step_all)

            def fused_tick(params, st, aids):
                (tokens, pos, plen, total, active, seeds, inv_temp,
                 pool, tables) = st
                (tokens, pos, plen, total, active, seeds, inv_temp,
                 (pool, tables)) = core(
                     params, (tokens, pos, plen, total, active, seeds,
                              inv_temp, (pool, tables)), aids)
                return (tokens, pos, plen, total, active, seeds,
                        inv_temp, pool, tables)

            return fused_tick
        core = self._make_core()
        bs, nbm = self.block, self.max_blocks

        def gather(pool, tables):
            def one(pl):
                v = pl[tables]               # [B, nb, H, bs, *]
                v = jnp.moveaxis(v, 2, 1)    # [B, H, nb, bs, *]
                return v.reshape(v.shape[:2] + (nbm * bs,)
                                 + v.shape[4:])
            return jax.tree_util.tree_map(one, pool)

        def paged_tick(params, st, aids):
            (tokens, pos, plen, total, active, seeds, inv_temp,
             pool, tables) = st
            views = gather(pool, tables)
            pos0 = pos                       # write position
            (tokens, pos, plen, total, active, seeds, inv_temp,
             views) = core(params, (tokens, pos, plen, total,
                                    active, seeds, inv_temp,
                                    views), aids)
            rows = jnp.arange(tokens.shape[0])
            blk = tables[rows, pos0 // bs]
            off = pos0 % bs

            def write_back(pl, vw):
                vals = jax.vmap(lambda v, p: v[:, p])(vw, pos0)
                return pl.at[blk, :, off].set(vals.astype(pl.dtype))

            pool = jax.tree_util.tree_map(write_back, pool, views)
            return (tokens, pos, plen, total, active, seeds,
                    inv_temp, pool, tables)

        return paged_tick
