"""Update rules (ref Znicz GradientDescent family + RPropAll2All,
SURVEY.md §2.9 — GD/GDTanh/GDSoftmax etc. collapse into ``jax.grad`` over
the staged loss; what remains of them is the *update rule* with the
reference's hyperparameter surface: per-layer learning_rate / weights_decay
/ l1_vs_l2 mixing / gradient_moment (momentum), with separate bias values).

Solvers, selectable per layer via ``solver``:

- ``gd``      Veles GD semantics:
                  reg = (1 - l1_vs_l2) * w + l1_vs_l2 * sign(w)
                  v   = gradient_moment * v - lr * (grad + weights_decay*reg)
                  w  += v
- ``adam``    bias-corrected Adam (new capability — transformers don't
              train well under momentum-SGD)
- ``adamw``   Adam with DECOUPLED weight decay (Loshchilov & Hutter):
              weights_decay acts directly on w, outside the adaptive
              rescaling — the standard transformer-LM recipe.  Biases /
              norm shifts are not decayed unless weights_decay_bias is
              set explicitly, and l1_vs_l2 does not apply (decoupled
              decay is inherently L2-shaped)
- ``adagrad`` accumulated squared gradients
- ``rprop``   sign-based resilient propagation (ref RPropAll2All):
              per-weight step grows ×1.2 on agreeing signs, shrinks ×0.5
              on sign flips
- ``adafactor`` Shazeer & Stern 2018: the second moment of an [n, m]
              weight is stored FACTORED — one row vector [n] and one
              column vector [m] instead of the full [n, m] matrix — so
              optimizer memory for the big matrices drops from 2x the
              params (adam m+v) to ~zero.  Momentum-free; the update is
              RMS-clipped (``adafactor_clip``) instead of bias-corrected;
              decay follows the paper's increasing schedule
              β₂ₜ = 1 − t^−0.8 (``adafactor_decay_exponent``; set it to 0
              to use the fixed ``adafactor_decay`` instead); weight decay
              decoupled like adamw.  1-D leaves (biases, norms) fall back
              to adam.
              State must be built by ``init_state(params, hypers=...)``
              so the factored slots get their shapes.
- ``muon``    momentum orthogonalized by a Newton–Schulz iteration
              (Jordan et al. 2024) — five matmuls per matrix per step,
              MXU-native.  Applies to >=2-D weight matrices (conv
              kernels flatten to [fan_in, fan_out]); embedding/position
              tables, biases and other 1-D leaves fall back to the
              adamw rule, per the Muon recipe.  ``muon_momentum``
              (0.95), ``muon_ns_steps`` (5), ``muon_nesterov`` (True);
              weight decay is decoupled like adamw.

State is {"slot1": tree, "slot2": tree, "step": scalar}: slot1 = momentum
velocity / Adam m / RProp previous gradient; slot2 = Adam v / AdaGrad
accumulator / RProp per-weight step."""

import math

import jax
import jax.numpy as jnp

DEFAULTS = {
    "solver": "gd",
    "learning_rate": 0.01,
    "learning_rate_bias": None,      # None -> same as learning_rate
    "weights_decay": 0.0,
    "weights_decay_bias": None,
    "l1_vs_l2": 0.0,                 # 0 = pure L2, 1 = pure L1
    "gradient_moment": 0.0,
    "gradient_moment_bias": None,
    "adam_beta1": 0.9,
    "adam_beta2": 0.999,
    "epsilon": 1e-8,
    "rprop_inc": 1.2,
    "rprop_dec": 0.5,
    "rprop_min": 1e-8,
    "rprop_max": 1.0,
    "muon_momentum": 0.95,
    "muon_ns_steps": 5,
    "muon_nesterov": True,
    "adafactor_decay": 0.999,
    "adafactor_decay_exponent": 0.8,
    "adafactor_clip": 1.0,
}


def resolve_hyper(layer_gd, workflow_gd=None, layer_type=None):
    """Merge per-layer GD kwargs over workflow defaults over DEFAULTS, and
    resolve the *_bias fallbacks.  ``layer_type`` (the registry type
    string) rides along so solver rules that depend on the layer's ROLE
    (Muon's hidden-matrices-only orthogonalization) match exactly."""
    h = dict(DEFAULTS)
    if layer_type is not None:
        h["_layer_type"] = layer_type
    if workflow_gd:
        h.update({k: v for k, v in workflow_gd.items() if k in DEFAULTS})
    h.update({k: v for k, v in layer_gd.items() if k in DEFAULTS})
    if h["solver"] not in ("gd", "adam", "adamw", "adagrad", "rprop",
                           "muon", "adafactor"):
        raise ValueError(
            "unknown solver %r (gd|adam|adamw|adagrad|rprop|muon|"
            "adafactor)" % (h["solver"],))
    for k in ("learning_rate", "weights_decay", "gradient_moment"):
        if h[k + "_bias"] is None:
            # adamw/muon convention: biases / norm shifts are NOT
            # decayed unless weights_decay_bias is given explicitly
            h[k + "_bias"] = (0.0 if (k == "weights_decay" and
                                      h["solver"] in ("adamw", "muon",
                                                      "adafactor"))
                              else h[k])
    return h


def newton_schulz(g, steps=5, eps=1e-7):
    """Quintic Newton–Schulz orthogonalization (Muon): drives the
    singular values of ``g`` (flattened to [fan_in-ish, fan_out]) toward
    1 with five matmuls per iteration — all MXU work, no SVD.  Runs in
    f32 regardless of input dtype."""
    a, b, c = 3.4445, -4.7750, 2.0315
    shape = g.shape
    x = g.reshape(-1, shape[-1]).astype(jnp.float32)
    transposed = x.shape[0] > x.shape[1]
    if transposed:                       # iterate on the smaller gram
        x = x.T
    x = x / (jnp.linalg.norm(x) + eps)
    for _ in range(steps):
        gram = x @ x.T
        x = a * x + (b * gram + c * gram @ gram) @ x
    if transposed:
        x = x.T
    return x.reshape(shape)


def _factored(w):
    """Adafactor slot shapes for one leaf: >=2-D weights store row+col
    second-moment vectors packed into ONE [rows+cols] array (slot2) and
    no momentum (empty slot1) — the memory win; smaller leaves keep the
    dense adam slots (they fall back to adam)."""
    if w.ndim < 2:
        return jnp.zeros_like(w), jnp.zeros_like(w)
    rows = math.prod(w.shape[:-1])
    return (jnp.zeros((0,), jnp.float32),
            jnp.zeros((rows + w.shape[-1],), jnp.float32))


def init_state(params, grad_accum=1, ema_decay=None, hypers=None):
    """``hypers`` ({layer: resolved hyper dict}) lets per-layer solvers
    pick their slot SHAPES — adafactor's factored second moments need
    it; without it every slot is dense zeros_like."""
    def layer_zeros(lname, sub, idx):
        solver = (hypers or {}).get(lname, {}).get("solver")
        if solver == "adafactor":
            return jax.tree_util.tree_map(
                lambda w: _factored(w)[idx], sub)
        return jax.tree_util.tree_map(jnp.zeros_like, sub)

    def zeros(idx):
        return {ln: layer_zeros(ln, sub, idx)
                for ln, sub in params.items()}

    state = {"slot1": zeros(0), "slot2": zeros(1),
             "step": jnp.zeros((), jnp.int32)}
    if grad_accum > 1:
        # gradient accumulation: running microbatch-gradient sum (ALWAYS
        # dense — it accumulates gradients) + a microstep counter;
        # ``step`` keeps counting real updates only (adam bias
        # correction depends on it)
        state["gacc"] = jax.tree_util.tree_map(jnp.zeros_like, params)
        state["micro"] = jnp.zeros((), jnp.int32)
    if ema_decay:
        # Polyak/EMA weight averaging: seeded with the initial params
        # (no zero-bias warmup needed), advanced on every real update.
        # Kept in f32 ALWAYS: with bf16 master params the per-step
        # increment (1-d)·(p-e) sits below the bf16 mantissa and the
        # average would freeze at its seed.
        # jnp.array COPIES (asarray would alias f32 params, and the
        # train step donates both trees — same-buffer-donated-twice)
        state["ema"] = jax.tree_util.tree_map(
            lambda p: jnp.array(p, jnp.float32), params)
    return state


def _update_leaf(solver, w, g, s1, s2, step, lr, wd, l1, moment, h,
                 orthogonalize=False):
    reg = (1.0 - l1) * w + l1 * jnp.sign(w)
    if solver == "muon":
        if orthogonalize:
            mu = h["muon_momentum"]
            m = mu * s1 + g
            u_in = mu * m + g if h["muon_nesterov"] else m
            u = newton_schulz(u_in, steps=int(h["muon_ns_steps"]))
            # match adamw's per-element update RMS across shapes
            # (Jordan et al.: scale by sqrt(max(1, fan_out/fan_in)))
            flat_rows = math.prod(w.shape[:-1])
            u = u * max(1.0, w.shape[-1] / flat_rows) ** 0.5
            return (w - lr * u.astype(w.dtype) - lr * wd * w, m, s2)
        # tables / biases / 1-D leaves: the adamw rule (Muon recipe)
        solver = "adamw"
    if solver == "adafactor":
        if w.ndim >= 2:
            rows = math.prod(w.shape[:-1])
            cols = w.shape[-1]
            if s2.shape != (rows + cols,):
                raise ValueError(
                    "adafactor state has shape %s, expected (%d,) — "
                    "build it with init_state(params, hypers=...)"
                    % (s2.shape, rows + cols))
            c = h["adafactor_decay_exponent"]
            if c:
                # Shazeer & Stern §7.2: increasing decay β₂ₜ = 1 − t^−c
                # (c = 0.8).  Early steps weight fresh gradients heavily,
                # which debiases the zero-initialized factored moments
                # without Adam-style correction terms.
                b2 = 1.0 - step.astype(jnp.float32) ** jnp.float32(-c)
            else:
                b2 = h["adafactor_decay"]     # fixed decay (exponent = 0)
            g2 = jnp.square(g.astype(jnp.float32)).reshape(rows, cols) \
                + 1e-30
            r = b2 * s2[:rows] + (1.0 - b2) * jnp.mean(g2, axis=1)
            c = b2 * s2[rows:] + (1.0 - b2) * jnp.mean(g2, axis=0)
            # rank-1 reconstruction V = r·cᵀ / mean(r)  (Shazeer & Stern
            # eq. 4: the row/col means over-count the total by mean(r))
            v = jnp.outer(r, c) / jnp.maximum(jnp.mean(r), 1e-30)
            u = g.astype(jnp.float32).reshape(rows, cols) \
                / jnp.sqrt(v + h["epsilon"])
            # update clipping replaces bias correction: cap RMS(u) at
            # adafactor_clip so cold second moments can't blow the step
            rms = jnp.sqrt(jnp.mean(jnp.square(u)))
            u = u / jnp.maximum(1.0, rms / h["adafactor_clip"])
            u = u.reshape(w.shape).astype(w.dtype)
            return (w - lr * u - lr * wd * w, s1,
                    jnp.concatenate([r, c]))
        solver = "adam"      # biases / 1-D leaves: dense adam below
    if solver in ("adam", "adamw"):
        b1, b2, eps = h["adam_beta1"], h["adam_beta2"], h["epsilon"]
        m = b1 * s1 + (1.0 - b1) * g
        v = b2 * s2 + (1.0 - b2) * g * g
        t = step.astype(jnp.float32)
        mhat = m / (1.0 - b1 ** t)
        vhat = v / (1.0 - b2 ** t)
        upd = mhat / (jnp.sqrt(vhat) + eps)
        if solver == "adamw":
            # decoupled weight decay (Loshchilov & Hutter): decay acts
            # on the weight directly, outside the adaptive rescaling
            return (w - lr * upd - lr * wd * w, m, v)
        return (w - lr * (upd + wd * reg), m, v)
    if solver == "adagrad":
        v = s2 + g * g
        return (w - lr * (g / (jnp.sqrt(v) + h["epsilon"]) + wd * reg),
                s1, v)
    if solver == "rprop":
        # s1 = previous gradient, s2 = per-weight step (0 → lr on first use)
        delta = jnp.where(s2 == 0.0, lr, s2)
        agree = jnp.sign(g) * jnp.sign(s1)
        delta = jnp.clip(
            jnp.where(agree > 0, delta * h["rprop_inc"],
                      jnp.where(agree < 0, delta * h["rprop_dec"], delta)),
            h["rprop_min"], h["rprop_max"])
        # on sign flip: skip the step and forget the gradient (iRprop-)
        g_eff = jnp.where(agree < 0, 0.0, g)
        return (w - jnp.sign(g_eff) * delta, g_eff, delta)
    # plain GD + momentum
    v_new = moment * s1 - lr * (g + wd * reg)
    return w + v_new, v_new, s2


_BIAS_KEYS = frozenset(
    {"bias", "beta", "b1", "b2", "bq", "bk", "bv", "bo"})


def _is_bias(path):
    """A leaf follows the *_bias hyperparameters when its dict key names a
    known bias/shift vector (explicit allowlist — a prefix heuristic would
    silently misclassify future params like 'base' or 'block_scale')."""
    return str(getattr(path[-1], "key", "")) in _BIAS_KEYS


#: layer TYPES whose parameters take Muon's adamw fallback even when
#: 2-D: embeddings, position tables, and the LM/classifier head — the
#: Muon recipe orthogonalizes HIDDEN matrices only
_MUON_FALLBACK_TYPES = frozenset(
    {"embedding", "positional_encoding", "timestep_dense",
     "tied_lm_head", "softmax"})


def update_layer(params, grads, s1, s2, step, hyper, lr_scale=1.0,
                 layer_name=""):
    """Apply the update rule to one layer's param pytree (flat
    {'weights', 'bias'} or nested transformer-style dicts)."""
    solver = hyper.get("solver", "gd")
    ltype = hyper.get("_layer_type")
    if ltype is not None:               # exact registry-type match
        muon_fallback_layer = ltype in _MUON_FALLBACK_TYPES
    else:                               # direct callers: name heuristic
        muon_fallback_layer = any(m in layer_name
                                  for m in _MUON_FALLBACK_TYPES)

    def upd(path, w, g, a, b):
        bias = _is_bias(path)
        ortho = (solver == "muon" and not bias and w.ndim >= 2
                 and not muon_fallback_layer
                 and str(getattr(path[-1], "key", ""))
                 not in ("table", "pos"))
        return _update_leaf(
            solver, w, g.astype(w.dtype), a, b, step,
            lr_scale * (hyper["learning_rate_bias"] if bias
                        else hyper["learning_rate"]),
            hyper["weights_decay_bias"] if bias else hyper["weights_decay"],
            hyper["l1_vs_l2"],
            hyper["gradient_moment_bias"] if bias
            else hyper["gradient_moment"], hyper, orthogonalize=ortho)

    triples = jax.tree_util.tree_map_with_path(upd, params, grads, s1, s2)
    is_t = lambda x: isinstance(x, tuple)  # noqa: E731
    pick = lambda i: jax.tree_util.tree_map(  # noqa: E731
        lambda t: t[i], triples, is_leaf=is_t)
    return pick(0), pick(1), pick(2)


def clip_by_global_norm(grads, max_norm):
    """Scale the whole gradient pytree so its global L2 norm is at most
    ``max_norm`` (the standard transformer stabilizer).  Traced-safe."""
    leaves = jax.tree_util.tree_leaves(grads)
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                         for g in leaves))
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(gnorm, 1e-12))
    return jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)


def _apply(params, grads, state, hypers, lr_scale, clip_norm,
           ema_decay=None):
    """One real optimizer update (clip → per-layer rules → EMA track)."""
    if clip_norm:
        grads = clip_by_global_norm(grads, float(clip_norm))
    step = state["step"] + 1
    new_p, new_s1, new_s2 = {}, {}, {}
    for lname in params:
        new_p[lname], new_s1[lname], new_s2[lname] = update_layer(
            params[lname], grads[lname], state["slot1"][lname],
            state["slot2"][lname], step, hypers[lname], lr_scale,
            layer_name=lname)
    new_s = {"slot1": new_s1, "slot2": new_s2, "step": step}
    if ema_decay:
        d = float(ema_decay)
        # f32 accumulator (see init_state) — never rounded to the param
        # dtype, or sub-resolution increments would vanish
        new_s["ema"] = jax.tree_util.tree_map(
            lambda e, p: d * e + (1.0 - d) * p.astype(jnp.float32),
            state["ema"], new_p)
    elif "ema" in state:
        # decay off this call but the tree tracks an EMA slot: carry it
        # unchanged so the returned pytree structure matches the input
        # (a structure change would break a lax.scan carry / jit cache)
        new_s["ema"] = state["ema"]
    return new_p, new_s


def update(params, grads, state, hypers, lr_scale=1.0, clip_norm=None,
           grad_accum=1, ema_decay=None):
    """Whole-model update.  ``params`` is {layer_name: {param: array}};
    ``hypers`` is {layer_name: resolved hyper dict}.  ``clip_norm``
    rescales the FULL gradient tree to that global L2 norm first
    (None or 0 = disabled — 0 would freeze training).

    ``grad_accum=k`` > 1 turns each call into a MICROBATCH step: the
    gradient joins a running sum and only every k-th call applies one
    optimizer update with the mean — k× the effective batch without k×
    the activation memory.  The mean-of-microbatch-gradients equals the
    full-batch gradient for mean-reduced losses, so k steps at batch B
    match one step at batch k·B exactly (clipping included: the norm is
    taken on the mean, not per microbatch).

    ``ema_decay=d`` maintains a Polyak/EMA average of the params in
    ``state["ema"]`` (``ema ← d·ema + (1-d)·params`` per real update) —
    the serve/eval-time weights that average out minibatch noise."""
    if clip_norm and clip_norm < 0:
        raise ValueError("clip_norm must be positive, got %r"
                         % (clip_norm,))
    if grad_accum <= 1:
        return _apply(params, grads, state, hypers, lr_scale, clip_norm,
                      ema_decay)

    gacc = jax.tree_util.tree_map(jnp.add, state["gacc"], grads)
    micro = state["micro"] + 1
    base = {k: state[k] for k in ("slot1", "slot2", "step", "ema")
            if k in state}

    def do_update(_):
        mean = jax.tree_util.tree_map(lambda g: g / grad_accum, gacc)
        new_p, new_s = _apply(params, mean, base, hypers, lr_scale,
                              clip_norm, ema_decay)
        new_s["gacc"] = jax.tree_util.tree_map(jnp.zeros_like, gacc)
        new_s["micro"] = micro
        return new_p, new_s

    def skip(_):
        return params, dict(base, gacc=gacc, micro=micro)

    return jax.lax.cond(micro % grad_accum == 0, do_update, skip, None)
