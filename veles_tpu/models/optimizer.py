"""Gradient-descent update rules (ref Znicz GradientDescent family,
SURVEY.md §2.9 — GD/GDTanh/GDSoftmax etc. collapse into ``jax.grad`` over
the staged loss; what remains of them is the *update rule* with the
reference's hyperparameter surface: per-layer learning_rate / weights_decay
/ l1_vs_l2 mixing / gradient_moment (momentum), with separate bias values).

The update matches Veles GD semantics:
    reg     = (1 - l1_vs_l2) * w + l1_vs_l2 * sign(w)
    v       = gradient_moment * v - lr * (grad + weights_decay * reg)
    w      += v
"""

import jax
import jax.numpy as jnp

DEFAULTS = {
    "learning_rate": 0.01,
    "learning_rate_bias": None,      # None -> same as learning_rate
    "weights_decay": 0.0,
    "weights_decay_bias": None,
    "l1_vs_l2": 0.0,                 # 0 = pure L2, 1 = pure L1
    "gradient_moment": 0.0,
    "gradient_moment_bias": None,
}


def resolve_hyper(layer_gd, workflow_gd=None):
    """Merge per-layer GD kwargs over workflow defaults over DEFAULTS, and
    resolve the *_bias fallbacks."""
    h = dict(DEFAULTS)
    if workflow_gd:
        h.update({k: v for k, v in workflow_gd.items() if k in DEFAULTS})
    h.update({k: v for k, v in layer_gd.items() if k in DEFAULTS})
    for k in ("learning_rate", "weights_decay", "gradient_moment"):
        if h[k + "_bias"] is None:
            h[k + "_bias"] = h[k]
    return h


def init_state(params):
    """Momentum velocity pytree, zeros like params."""
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def _update_leaf(w, g, v, lr, wd, l1, moment):
    reg = (1.0 - l1) * w + l1 * jnp.sign(w)
    v_new = moment * v - lr * (g + wd * reg)
    return w + v_new, v_new


def update_layer(params, grads, velocity, hyper, lr_scale=1.0):
    """Apply the GD rule to one layer's param dict ({'weights', 'bias'?})."""
    new_p, new_v = {}, {}
    for name in params:
        bias = name == "bias"
        w, g, v = params[name], grads[name], velocity[name]
        p2, v2 = _update_leaf(
            w, g.astype(w.dtype), v,
            lr_scale * (hyper["learning_rate_bias"] if bias
                        else hyper["learning_rate"]),
            hyper["weights_decay_bias"] if bias else hyper["weights_decay"],
            hyper["l1_vs_l2"],
            hyper["gradient_moment_bias"] if bias
            else hyper["gradient_moment"])
        new_p[name], new_v[name] = p2, v2
    return new_p, new_v


def update(params, grads, velocity, hypers, lr_scale=1.0):
    """Whole-model update.  ``params`` is {layer_name: {param: array}};
    ``hypers`` is {layer_name: resolved hyper dict}."""
    new_params, new_vel = {}, {}
    for lname in params:
        new_params[lname], new_vel[lname] = update_layer(
            params[lname], grads[lname], velocity[lname], hypers[lname],
            lr_scale)
    return new_params, new_vel
