"""Learning-rate adjusters (ref Znicz lr_adjust — the "LR adjusters"
infrastructure units, SURVEY.md §2.9).

An :class:`LRAdjuster` recomputes ``trainer.lr_scale`` — a *traced*
multiplier on every layer's learning rate — at each epoch boundary, so
schedule changes never trigger an XLA recompile.  Policies mirror the
reference's (Caffe-style) set: exp, step_exp, inv, plus arbitrary
callables."""

import math

from veles_tpu.units import Unit

POLICIES = {}


def policy(name):
    def deco(fn):
        POLICIES[name] = fn
        return fn
    return deco


@policy("fixed")
def fixed(epoch, **kw):
    return 1.0


@policy("exp")
def exp(epoch, base=0.9, **kw):
    """scale = base^epoch."""
    return base ** epoch


@policy("step_exp")
def step_exp(epoch, base=0.1, step=10, **kw):
    """Drop by ``base`` every ``step`` epochs (Caffe "step")."""
    return base ** (epoch // step)


@policy("inv")
def inv(epoch, gamma=0.1, power=0.75, **kw):
    """scale = (1 + gamma·epoch)^-power (Caffe "inv")."""
    return (1.0 + gamma * epoch) ** -power


@policy("warmup_cosine")
def warmup_cosine(epoch, warmup=5, total=100, floor=0.0, **kw):
    """Linear warmup over ``warmup`` epochs then cosine decay to
    ``floor`` at ``total`` — the standard transformer-LM schedule."""
    if epoch < warmup:
        return (epoch + 1) / max(warmup, 1)
    t = min((epoch - warmup) / max(total - warmup, 1), 1.0)
    return floor + (1.0 - floor) * 0.5 * (1.0 + math.cos(math.pi * t))


@policy("arbitrary_step")
def arbitrary_step(epoch, steps=(), **kw):
    """``steps`` = [(epoch_threshold, scale), ...]; the scale of the last
    threshold ≤ epoch wins (ref lr_adjust ArbitraryStep)."""
    scale = 1.0
    for threshold, s in sorted(steps):
        if epoch >= threshold:
            scale = s
    return scale


class LRAdjuster(Unit):
    """Sets ``trainer.lr_scale`` from the schedule each time it runs; wire
    it at the epoch boundary (StandardWorkflow gates it on epoch_ended).

    ``policy`` is a name from POLICIES or a callable ``f(epoch) -> scale``.
    """

    def __init__(self, workflow, policy="fixed", **kwargs):
        self._policy_kwargs = {k: kwargs.pop(k) for k in
                               ("base", "step", "gamma", "power", "steps",
                                "warmup", "total", "floor")
                               if k in kwargs}
        super(LRAdjuster, self).__init__(workflow, **kwargs)
        self.policy = policy
        self.demand("trainer", "loader")
        self.trainer = None
        self.loader = None

    def scale_for(self, epoch):
        if callable(self.policy):
            return float(self.policy(epoch))
        return float(POLICIES[self.policy](epoch, **self._policy_kwargs))

    def run(self):
        scale = self.scale_for(self.loader.epoch_number)
        if scale != self.trainer.lr_scale:
            self.info("lr_scale -> %.6g (epoch %d)", scale,
                      self.loader.epoch_number)
        self.trainer.lr_scale = scale
