"""Model zoo — layer configs for the reference's baseline workflows
(BASELINE.md: MNIST MLP, CIFAR-10 conv, ImageNet AlexNet; ref Znicz sample
workflows documented in manualrst_veles_algorithms.rst)."""


def mnist_mlp(hidden=100, lr=0.03, moment=0.9):
    """MnistSimple: 784-<hidden>-10 softmax net
    (ref docs/source/manualrst_veles_algorithms.rst:26-33; BASELINE
    'MNIST 784-100-10 fully-connected')."""
    return [
        {"type": "all2all_tanh", "output_sample_shape": hidden,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "softmax", "output_sample_shape": 10,
         "learning_rate": lr, "gradient_moment": moment},
    ]


def resnet_gn(n_classes=10, width=16, blocks_per_stage=2, stages=3,
              pool=8, lr=0.05, moment=0.9, wd=1e-4):
    """Small pre-activation ResNet with GroupNorm (He et al. v2 blocks
    via the conv_residual_block composite; residual conv families are
    beyond the reference's 2015-era registry).  Defaults fit 32×32
    inputs: stem conv, ``stages`` stages of ``blocks_per_stage`` blocks
    (channel double + stride-2 transition between stages), global
    ``pool``×``pool`` average pool, softmax head."""
    gd = {"learning_rate": lr, "gradient_moment": moment,
          "weights_decay": wd}
    layers = [dict({"type": "conv", "n_kernels": width, "kx": 3,
                    "ky": 3, "padding": (1, 1, 1, 1)}, **gd)]
    ch = width
    for stage in range(stages):
        for b in range(blocks_per_stage):
            cfg = {"type": "conv_residual_block", "n_kernels": ch}
            if stage > 0 and b == 0:
                cfg["sliding"] = (2, 2)     # transition: downsample
            layers.append(dict(cfg, **gd))
        ch *= 2
    layers += [
        # He v2: pre-activation blocks emit a raw residual sum — one
        # final norm+relu bounds the feature scale before the head
        dict({"type": "group_norm"}, **gd),
        {"type": "activation_strict_relu"},
        {"type": "avg_pooling", "kx": pool, "ky": pool},
        dict({"type": "softmax", "output_sample_shape": n_classes},
             **gd),
    ]
    return layers


def cifar_conv(lr=0.001, moment=0.9, wd=0.004):
    """cifar_caffe-style quick net for 32×32×3 inputs
    (ref manualrst_veles_algorithms.rst:45-52: 17.21% validation error)."""
    return [
        {"type": "conv", "n_kernels": 32, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr,
         "gradient_moment": moment, "weights_decay": wd},
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "activation_strict_relu"},
        {"type": "conv_strict_relu", "n_kernels": 32, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr,
         "gradient_moment": moment, "weights_decay": wd},
        {"type": "avg_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "conv_strict_relu", "n_kernels": 64, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr,
         "gradient_moment": moment, "weights_decay": wd},
        {"type": "avg_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "all2all", "output_sample_shape": 64,
         "learning_rate": lr, "gradient_moment": moment,
         "weights_decay": wd},
        {"type": "softmax", "output_sample_shape": 10,
         "learning_rate": lr, "gradient_moment": moment,
         "weights_decay": wd},
    ]


def alexnet(n_classes=1000, lr=0.01, moment=0.9, wd=5e-4):
    """AlexNet for 227×227×3 ImageNet (ref BASELINE 'ImageNet AlexNet';
    Znicz imagenet workflow).  Single-tower (no grouped convs)."""
    def conv(k, kx, pad, stride=(1, 1), **kw):
        c = {"type": "conv_strict_relu", "n_kernels": k, "kx": kx, "ky": kx,
             "padding": (pad,) * 4, "sliding": stride, "learning_rate": lr,
             "gradient_moment": moment, "weights_decay": wd}
        c.update(kw)
        return c

    return [
        conv(96, 11, 0, stride=(4, 4)),
        {"type": "norm", "alpha": 1e-4, "beta": 0.75, "n": 5, "k": 2.0},
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        conv(256, 5, 2),
        {"type": "norm", "alpha": 1e-4, "beta": 0.75, "n": 5, "k": 2.0},
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        conv(384, 3, 1),
        conv(384, 3, 1),
        conv(256, 3, 1),
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "all2all_strict_relu", "output_sample_shape": 4096,
         "learning_rate": lr, "gradient_moment": moment,
         "weights_decay": wd},
        {"type": "dropout", "dropout_ratio": 0.5},
        {"type": "all2all_strict_relu", "output_sample_shape": 4096,
         "learning_rate": lr, "gradient_moment": moment,
         "weights_decay": wd},
        {"type": "dropout", "dropout_ratio": 0.5},
        {"type": "softmax", "output_sample_shape": n_classes,
         "learning_rate": lr, "gradient_moment": moment,
         "weights_decay": wd},
    ]


def transformer_classifier(n_classes=10, d_model=64, n_heads=4, n_layers=2,
                           d_ff=None, lr=0.001, moment=0.9, causal=False,
                           dropout=0.1, impl="blockwise", solver="adam",
                           n_experts=0, n_kv_heads=None, remat=False):
    """Transformer encoder classifier over [T, F] sequence samples — new
    capability beyond the reference (its RNN/LSTM support was 'in
    progress', manualrst_veles_algorithms.rst:105-112; attention postdates
    it).  ``impl`` picks the attention path: blockwise / flash (Pallas) /
    ring / ulysses (sequence-parallel over a mesh 'seq' axis)."""
    gd = {"learning_rate": lr, "gradient_moment": moment, "solver": solver}
    layers = [dict({"type": "timestep_dense", "output_sample_shape": d_model},
                   **gd),
              {"type": "positional_encoding"}]
    for _ in range(n_layers):
        layers.append(dict({"type": "transformer_block",
                            "n_heads": n_heads,
                            "n_kv_heads": n_kv_heads or n_heads,
                            "d_ff": d_ff or 4 * d_model,
                            "causal": causal, "dropout_ratio": dropout,
                            "impl": impl, "n_experts": n_experts,
                            "remat": remat}, **gd))
    layers.append(dict({"type": "layer_norm"}, **gd))
    layers.append({"type": "seq_pool", "mode": "mean"})
    layers.append(dict({"type": "softmax", "output_sample_shape": n_classes},
                       **gd))
    return layers


def transformer_lm(vocab_size=256, d_model=64, n_heads=4, n_layers=2,
                   d_ff=None, lr=0.001, moment=0.9, dropout=0.0,
                   impl="blockwise", solver="adam", n_experts=0,
                   n_kv_heads=None, remat=False, pos="learned",
                   window=None, tie_embeddings=False, lora_rank=0):
    """Decoder-only causal LM over int token samples [T].
    ``n_kv_heads`` < n_heads = grouped-query attention; ``remat=True``
    rematerializes each block's activations in the backward pass
    (jax.checkpoint — long-context memory for FLOPs), ``remat="dots"``
    keeps matmul outputs and recomputes only elementwise ops
    (dots_saveable — near-no-remat step time, far less memory); ``pos`` =
    "learned" | "sinusoid" position table, or "rope" (rotary q/k in
    every block, no table — extrapolates past the train length);
    ``tie_embeddings`` reuses the embedding table as the LM head
    (saves vocab×d_model params); ``lora_rank`` > 0 = parameter-
    efficient fine-tuning: every block gains rank-r q/v adapters, the
    blocks' base weights freeze via stop_gradient, and the
    embedding/position/norm/head layers freeze via learning_rate 0 —
    pair with ``--warm-start base_snapshot`` so only the adapters
    train (Hu et al. 2021)."""
    if pos not in ("learned", "sinusoid", "rope"):
        raise ValueError("pos must be learned|sinusoid|rope")
    gd = {"learning_rate": lr, "gradient_moment": moment, "solver": solver}
    # resolve_hyper falls learning_rate_bias back to learning_rate, so
    # zeroing the one lr freezes weights AND biases of the outer layers
    outer = dict(gd, learning_rate=0.0) if lora_rank else gd
    layers = [dict({"type": "embedding", "vocab_size": vocab_size,
                    "d_model": d_model}, **outer)]
    if pos != "rope":
        layers.append(dict({"type": "positional_encoding",
                            "learned": pos == "learned"}, **outer))
    for _ in range(n_layers):
        layers.append(dict({"type": "transformer_block",
                            "n_heads": n_heads,
                            "n_kv_heads": n_kv_heads or n_heads,
                            "d_ff": d_ff or 4 * d_model,
                            "causal": True, "dropout_ratio": dropout,
                            "impl": impl, "n_experts": n_experts,
                            "remat": remat, "rope": pos == "rope",
                            "lora_rank": lora_rank,
                            "window": window},
                           **gd))
    layers.append(dict({"type": "layer_norm"}, **outer))
    if tie_embeddings:
        # tie_to by TYPE — the trainer resolves it to the layer's
        # assigned name at initialize
        layers.append({"type": "tied_lm_head", "vocab_size": vocab_size,
                       "tie_to": "embedding"})
    else:
        layers.append(dict({"type": "timestep_dense",
                            "output_sample_shape": vocab_size}, **outer))
    return layers


def mnist_autoencoder(bottleneck=16, lr=0.01, moment=0.9):
    """MNIST-style autoencoder (ref manualrst_veles_algorithms.rst:55-70,
    validation RMSE 0.5478)."""
    return [
        {"type": "all2all_tanh", "output_sample_shape": bottleneck,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "all2all", "output_sample_shape": 784,
         "learning_rate": lr, "gradient_moment": moment},
    ]


def conv_autoencoder(n_kernels=8, kx=3, ky=3, lr=0.01, moment=0.9,
                     out_channels=1):
    """Convolutional autoencoder (ref manualrst_veles_algorithms.rst:86-94
    "convolutional autoencoder"): conv+pool encoder, depool+deconv decoder,
    trained with loss="mse" reconstructing the input."""
    gd = {"learning_rate": lr, "gradient_moment": moment}
    return [
        dict({"type": "conv_relu", "n_kernels": n_kernels, "kx": kx,
              "ky": ky}, **gd),
        {"type": "max_pooling", "kx": 2, "ky": 2},
        {"type": "depooling", "kx": 2, "ky": 2},
        dict({"type": "deconv", "n_kernels": out_channels, "kx": kx,
              "ky": ky}, **gd),
    ]
