"""Model zoo — layer configs for the reference's baseline workflows
(BASELINE.md: MNIST MLP, CIFAR-10 conv, ImageNet AlexNet; ref Znicz sample
workflows documented in manualrst_veles_algorithms.rst)."""


def mnist_mlp(hidden=100, lr=0.03, moment=0.9):
    """MnistSimple: 784-<hidden>-10 softmax net
    (ref docs/source/manualrst_veles_algorithms.rst:26-33; BASELINE
    'MNIST 784-100-10 fully-connected')."""
    return [
        {"type": "all2all_tanh", "output_sample_shape": hidden,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "softmax", "output_sample_shape": 10,
         "learning_rate": lr, "gradient_moment": moment},
    ]


def cifar_conv(lr=0.001, moment=0.9, wd=0.004):
    """cifar_caffe-style quick net for 32×32×3 inputs
    (ref manualrst_veles_algorithms.rst:45-52: 17.21% validation error)."""
    return [
        {"type": "conv", "n_kernels": 32, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr,
         "gradient_moment": moment, "weights_decay": wd},
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "activation_strict_relu"},
        {"type": "conv_strict_relu", "n_kernels": 32, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr,
         "gradient_moment": moment, "weights_decay": wd},
        {"type": "avg_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "conv_strict_relu", "n_kernels": 64, "kx": 5, "ky": 5,
         "padding": (2, 2, 2, 2), "learning_rate": lr,
         "gradient_moment": moment, "weights_decay": wd},
        {"type": "avg_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "all2all", "output_sample_shape": 64,
         "learning_rate": lr, "gradient_moment": moment,
         "weights_decay": wd},
        {"type": "softmax", "output_sample_shape": 10,
         "learning_rate": lr, "gradient_moment": moment,
         "weights_decay": wd},
    ]


def alexnet(n_classes=1000, lr=0.01, moment=0.9, wd=5e-4):
    """AlexNet for 227×227×3 ImageNet (ref BASELINE 'ImageNet AlexNet';
    Znicz imagenet workflow).  Single-tower (no grouped convs)."""
    def conv(k, kx, pad, stride=(1, 1), **kw):
        c = {"type": "conv_strict_relu", "n_kernels": k, "kx": kx, "ky": kx,
             "padding": (pad,) * 4, "sliding": stride, "learning_rate": lr,
             "gradient_moment": moment, "weights_decay": wd}
        c.update(kw)
        return c

    return [
        conv(96, 11, 0, stride=(4, 4)),
        {"type": "norm", "alpha": 1e-4, "beta": 0.75, "n": 5, "k": 2.0},
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        conv(256, 5, 2),
        {"type": "norm", "alpha": 1e-4, "beta": 0.75, "n": 5, "k": 2.0},
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        conv(384, 3, 1),
        conv(384, 3, 1),
        conv(256, 3, 1),
        {"type": "max_pooling", "kx": 3, "ky": 3, "sliding": (2, 2)},
        {"type": "all2all_strict_relu", "output_sample_shape": 4096,
         "learning_rate": lr, "gradient_moment": moment,
         "weights_decay": wd},
        {"type": "dropout", "dropout_ratio": 0.5},
        {"type": "all2all_strict_relu", "output_sample_shape": 4096,
         "learning_rate": lr, "gradient_moment": moment,
         "weights_decay": wd},
        {"type": "dropout", "dropout_ratio": 0.5},
        {"type": "softmax", "output_sample_shape": n_classes,
         "learning_rate": lr, "gradient_moment": moment,
         "weights_decay": wd},
    ]


def mnist_autoencoder(bottleneck=16, lr=0.01, moment=0.9):
    """MNIST-style autoencoder (ref manualrst_veles_algorithms.rst:55-70,
    validation RMSE 0.5478)."""
    return [
        {"type": "all2all_tanh", "output_sample_shape": bottleneck,
         "learning_rate": lr, "gradient_moment": moment},
        {"type": "all2all", "output_sample_shape": 784,
         "learning_rate": lr, "gradient_moment": moment},
    ]
