"""Restricted Boltzmann Machine (ref: Veles RBM engine, numpy-based —
manualrst_veles_algorithms.rst:96-103).

Bernoulli-Bernoulli RBM trained with contrastive divergence (CD-k), the
whole minibatch update staged as one jitted step: sampling uses
counter-derived keys so training is bit-reproducible.  Metric:
per-element reconstruction RMSE (matches the autoencoder metric)."""

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu import prng
from veles_tpu.loader.base import TRAIN
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.mutable import Bool
from veles_tpu.plumbing import Repeater
from veles_tpu.units import Unit
from veles_tpu.workflow import Workflow


def cd_step(params, x, valid, key, lr, k=1):
    """One CD-k minibatch update.  x in [0,1]."""
    w, vb, hb = params["weights"], params["vbias"], params["hbias"]

    def sample(p, key):
        return jax.random.bernoulli(key, p).astype(jnp.float32)

    h0_p = jax.nn.sigmoid(x @ w + hb)
    keys = jax.random.split(key, 2 * k + 1)
    h = sample(h0_p, keys[0])
    v = x
    for i in range(k):
        v_p = jax.nn.sigmoid(h @ w.T + vb)
        v = sample(v_p, keys[2 * i + 1])
        h_p = jax.nn.sigmoid(v @ w + hb)
        h = sample(h_p, keys[2 * i + 2])
    n = jnp.maximum(valid.sum(), 1.0)
    vm = valid[:, None]
    pos = (x * vm).T @ h0_p
    neg = (v * vm).T @ h_p
    new = {
        "weights": w + lr * (pos - neg) / n,
        "vbias": vb + lr * jnp.sum((x - v) * vm, axis=0) / n,
        "hbias": hb + lr * jnp.sum((h0_p - h_p) * vm, axis=0) / n,
    }
    recon = jax.nn.sigmoid(h0_p @ w.T + vb)
    se = jnp.sum(((x - recon) ** 2) * vm)
    return new, se, valid.sum()


class RBMTrainer(Unit):
    def __init__(self, workflow, n_hidden=64, learning_rate=0.1, cd_k=1,
                 **kwargs):
        super(RBMTrainer, self).__init__(workflow, **kwargs)
        self.n_hidden = n_hidden
        self.learning_rate = learning_rate
        self.cd_k = cd_k
        self.demand("loader")
        self.params = None
        self._step_counter = 0
        self._se_sum = 0.0
        self._count = 0.0

    def initialize(self, **kwargs):
        loader = self.loader
        if loader.carries_data:
            raise ValueError("RBMTrainer needs an index loader with an "
                             "HBM-resident dataset")
        n_visible = int(np.prod(loader.data.shape[1:]))
        rng = prng.get("rbm-weights")
        self.params = {
            "weights": jnp.asarray(
                rng.fill_normal((n_visible, self.n_hidden), 0.01)),
            "vbias": jnp.zeros((n_visible,)),
            "hbias": jnp.zeros((self.n_hidden,)),
        }
        self._base_key = jax.random.key(int(prng.get("rbm")._seed))
        self._jit_step = jax.jit(
            lambda p, x, v, s: cd_step(
                p, x, v, jax.random.fold_in(self._base_key, s),
                self.learning_rate, self.cd_k))

    def run(self):
        loader = self.loader
        if loader.minibatch_class != TRAIN:
            return
        x = FullBatchLoader.gather(
            loader.data, jnp.asarray(loader.minibatch_indices))
        x = x.reshape(x.shape[0], -1)
        valid = jnp.asarray(loader.minibatch_valid)
        self._step_counter += 1
        self.params, se, cnt = self._jit_step(self.params, x, valid,
                                              self._step_counter)
        self._se_sum += float(se)
        self._count += float(cnt)

    def epoch_rmse(self):
        n_visible = self.params["weights"].shape[0]
        if not self._count:
            return None
        rmse = float(np.sqrt(self._se_sum / (self._count * n_visible)))
        self._se_sum = 0.0
        self._count = 0.0
        return rmse

    # serving: hidden representation + reconstruction
    def transform(self, x):
        x = jnp.asarray(x.reshape(len(x), -1))
        return jax.nn.sigmoid(x @ self.params["weights"] +
                              self.params["hbias"])

    def reconstruct(self, x):
        h = self.transform(x)
        return jax.nn.sigmoid(h @ self.params["weights"].T +
                              self.params["vbias"])

    def get_metric_values(self):
        return {"rbm_hidden": self.n_hidden}


class RBMWorkflow(Workflow):
    def __init__(self, workflow=None, loader=None, n_hidden=64,
                 n_epochs=10, learning_rate=0.1, cd_k=1, **kwargs):
        super(RBMWorkflow, self).__init__(workflow, **kwargs)
        self.repeater = Repeater(self)
        self.loader = loader
        if loader.workflow is not self:
            self.add_ref(loader)
            loader.workflow = self
        self.trainer = RBMTrainer(self, n_hidden=n_hidden,
                                  learning_rate=learning_rate, cd_k=cd_k)
        self.trainer.loader = loader
        self.n_epochs = n_epochs
        self.complete = Bool(False)
        self.rmse_history = []
        wf = self

        class RBMDecision(Unit):
            def run(self):
                loader_ = wf.loader
                if not bool(loader_.epoch_ended):
                    return
                rmse = wf.trainer.epoch_rmse()
                if rmse is not None:
                    wf.rmse_history.append(rmse)
                    wf.trainer.info("epoch %d: reconstruction rmse %.4f",
                                    loader_.epoch_number, rmse)
                if loader_.epoch_number >= wf.n_epochs:
                    wf.complete <<= True

        self.decision = RBMDecision(self)
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        self.trainer.link_from(self.loader)
        self.decision.link_from(self.trainer)
        self.repeater.link_from(self.decision)
        self.repeater.gate_block = self.complete
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.complete
