"""StandardWorkflow — the declarative model builder
(ref: docs/source/manualrst_veles_workflow_creation.rst:107-150; Znicz
StandardWorkflow with its link_repeater/link_loader/link_forwards/
link_evaluator/link_decision/link_snapshotter/link_gds/link_loop steps).

Given ``layers=[{...}]`` and a loader, it wires the canonical hot loop

    start → repeater → loader → trainer → decision → [snapshotter] → repeater
                                             └→ end_point (gated on complete)

where ``trainer`` is the :class:`~veles_tpu.models.nn_units.StagedTrainer`
holding the whole forward/backward/update chain as jitted XLA steps — the
reference's per-layer forward and GD units appear as introspection
``Forward`` handles only."""

from veles_tpu.loader.base import Loader
from veles_tpu.models.decision import DecisionGD, DecisionMSE
from veles_tpu.models.layers import make_layer
from veles_tpu.models.nn_units import Forward, StagedTrainer
from veles_tpu.plumbing import Repeater
from veles_tpu.services.snapshotter import TrainingSnapshotter
from veles_tpu.workflow import Workflow


class StandardWorkflow(Workflow):
    def __init__(self, workflow=None, layers=None, loader=None,
                 loss="softmax", decision_config=None, snapshotter_config=None,
                 gd_defaults=None, mesh_config=None, lr_adjuster_config=None,
                 dataset_placement="shard", steps_per_dispatch=None,
                 sentinel_config=None, **kwargs):
        super(StandardWorkflow, self).__init__(workflow, **kwargs)
        if not layers:
            raise ValueError("StandardWorkflow needs layers=[{...}, ...]")
        self.layer_configs = layers
        self.loss = loss

        self.repeater = Repeater(self)
        self.loader = self._make_loader(loader)
        if (mesh_config is not None and dataset_placement == "shard"
                and mesh_config.data_size > 1
                and getattr(self.loader, "on_device", None) is True):
            # the trainer will row-shard the dataset over the data axis;
            # a single-device replica must never be materialized first
            self.loader.on_device = "defer"
        if steps_per_dispatch is None:
            # workflow files usually leave this to the CLI / config layer
            # (--steps-per-dispatch → root.common.engine.steps_per_dispatch)
            from veles_tpu.config import root
            steps_per_dispatch = root.common.engine.get(
                "steps_per_dispatch", 1)
        self.trainer = StagedTrainer(self, [make_layer(c) for c in layers],
                                     loss=loss, gd_defaults=gd_defaults,
                                     mesh_config=mesh_config,
                                     dataset_placement=dataset_placement,
                                     steps_per_dispatch=steps_per_dispatch)
        self.trainer.loader = self.loader
        self.forwards = [Forward(self, lay, self.trainer)
                         for lay in self.trainer.layers]

        from veles_tpu.ops.losses import get_loss
        decision_cls = (DecisionGD if get_loss(loss)[1] == "class"
                        else DecisionMSE)
        self.decision = decision_cls(self, **(decision_config or {}))
        self.decision.loader = self.loader
        self.decision.trainer = self.trainer

        # control graph (ref link_* steps)
        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        self.trainer.link_from(self.loader)
        self.decision.link_from(self.trainer)
        tail = self.decision
        if lr_adjuster_config is not None:
            from veles_tpu.models.lr_adjuster import LRAdjuster
            self.lr_adjuster = LRAdjuster(self, **lr_adjuster_config)
            self.lr_adjuster.trainer = self.trainer
            self.lr_adjuster.loader = self.loader
            self.lr_adjuster.link_from(tail)
            self.lr_adjuster.gate_skip = ~self.loader.epoch_ended
            tail = self.lr_adjuster
        else:
            self.lr_adjuster = None
        if snapshotter_config is not None:
            cfg = dict(snapshotter_config)
            # registry routing like the loader dict; the config-tree
            # default makes the backend CLI-selectable, e.g.
            # --config-list "root.common.snapshot.backend='orbax'"
            from veles_tpu.config import root as _root
            kind = cfg.pop("name",
                           _root.common.snapshot.get("backend", None))
            if kind is not None:
                from veles_tpu.services.snapshotter import SnapshotterBase
                snap_cls = SnapshotterBase.mapping[kind]
            else:
                snap_cls = TrainingSnapshotter
            self.snapshotter = snap_cls(self, **cfg)
            self.snapshotter.trainer = self.trainer
            self.snapshotter.loader = self.loader
            self.snapshotter.decision = self.decision
            self.snapshotter.link_from(self.decision)
            # the unit runs EVERY cycle; epoch-end/interval gating and
            # the preemption answer happen inside run() (``when``), so
            # the multi-host preemption agreement executes on all
            # processes each cycle — a gate_skip on per-process state
            # would desynchronize it.  Preemption therefore checkpoints
            # at the NEXT CYCLE, mid-epoch (loader offset/order, step
            # counter and PRNG are all captured).
            self.snapshotter.when = self.loader.epoch_ended
            tail = self.snapshotter
        else:
            self.snapshotter = None
        # the numeric-fault sentinel (services.sentinel): strike
        # accounting at the trainer's sync point, rollback-and-replay
        # after the snapshotter's commit (so the poisoned epoch's
        # commit exists — stamped unhealthy — before the rollback
        # decision quarantines it), escalation under a numerics:<kind>
        # crash class.  Linked at the tail; disabled per-run with
        # root.common.sentinel.enabled=False (the in-jit probes follow
        # the same switch inside the trainer).
        from veles_tpu.config import root as _root
        if _root.common.sentinel.get("enabled", True):
            from veles_tpu.services.sentinel import HealthSentinel
            self.sentinel = HealthSentinel(self,
                                           **(sentinel_config or {}))
            self.sentinel.trainer = self.trainer
            self.sentinel.loader = self.loader
            self.sentinel.snapshotter = self.snapshotter
            self.trainer.sentinel = self.sentinel
            self.sentinel.link_from(tail)
            tail = self.sentinel
        else:
            self.sentinel = None
        self.repeater.link_from(tail)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(tail)
        self.end_point.gate_block = ~self.decision.complete

    def _make_loader(self, loader):
        if isinstance(loader, Loader):
            if loader.workflow is not self:
                self.add_ref(loader)
                loader.workflow = self
            return loader
        if isinstance(loader, dict):
            cfg = dict(loader)
            name = cfg.pop("name")
            return Loader.mapping[name](self, **cfg)
        raise TypeError("loader must be a Loader instance or "
                        "{'name': ..., **kwargs} dict")

    # ---------------------------------------------------------- evaluation
    def evaluate(self, use_ema=False):
        """One full eval-only pass over every non-empty class — the
        ``--test`` mode (ref `veles --test` reusing a trained snapshot for
        inference, SURVEY §3.5).  Returns {class_name: stats}.

        ``use_ema=True`` evaluates the Polyak/EMA weight average
        (gd_defaults["ema_decay"]) — the params swap is transient and
        safe because no update runs in eval-only mode."""
        from veles_tpu.loader.base import CLASS_NAMES
        # queued fused-dispatch TRAIN steps must apply as TRAINING
        # before eval mode flips, or their updates would be silently
        # dropped (replayed through the eval sweep)
        self.trainer.flush()
        saved = self.trainer.train_only_classes
        live = self.trainer.params
        try:
            if use_ema:
                self.trainer.params = self.trainer.serve_params(
                    use_ema=True)
            self.trainer.train_only_classes = ()
            self.trainer.reset_epoch_stats()
            loader = self.loader
            start = loader.epoch_number
            while loader.epoch_number == start:
                loader.run()
                self.trainer.run()
            stats = {CLASS_NAMES[c]: self.trainer.read_class_stats(c)
                     for c in range(3) if loader.class_lengths[c]}
        finally:
            self.trainer.params = live
            self.trainer.train_only_classes = saved
        self.test_results = stats
        return stats

    def get_metric_values(self):
        if getattr(self, "test_results", None) is not None:
            return {"test": self.test_results}
        return {}

    # ------------------------------------------------------------- serving
    def forward_fn(self):
        """Jitted inference function (params, x) -> probabilities/output."""
        return self.trainer.forward_fn()

    def restore(self, snapshot):
        TrainingSnapshotter.restore(self, snapshot)

    def warm_start(self, snapshot):
        """Params-only fine-tuning initializer (see
        TrainingSnapshotter.warm_start)."""
        return TrainingSnapshotter.warm_start(self, snapshot)
