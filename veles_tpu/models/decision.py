"""Decision units — stop conditions and per-epoch bookkeeping
(ref Znicz DecisionGD / DecisionMSE, SURVEY.md §2.9 "Infrastructure").

Reads the trainer's *device-resident* epoch accumulators only when the
loader signals ``last_minibatch`` (one host sync per class sweep, not per
step), tracks the best validation metric, and raises ``complete`` when
training should stop: ``fail_iterations`` epochs without improvement, or
``max_epochs`` reached."""

import math

import numpy as np

from veles_tpu.loader.base import CLASS_NAMES, TRAIN, VALID
from veles_tpu.mutable import Bool
from veles_tpu.units import Unit


class DecisionBase(Unit):
    def __init__(self, workflow, **kwargs):
        super(DecisionBase, self).__init__(workflow, **kwargs)
        self.fail_iterations = kwargs.get("fail_iterations", 100)
        self.max_epochs = kwargs.get("max_epochs", None)
        #: which class's metric drives improvement/stopping: "test",
        #: "validation", "train", or None = validation-else-train (the
        #: reference default).  The seam for workflows that eval on the
        #: test split (ref pluggable decision configs).
        watch = kwargs.get("watch")
        if watch is not None and watch not in CLASS_NAMES:
            raise ValueError("watch must be one of %s" % (CLASS_NAMES,))
        self.watch = watch
        self.complete = Bool(False)
        self.improved = Bool(False)
        self.demand("loader", "trainer")
        self.epoch_metrics = [None, None, None]   # per class
        self.best_metric = None
        self.best_epoch = -1
        self.best_params = None
        self.epochs_since_improvement = 0

    # metric = "smaller is better" scalar; subclasses extract it
    def extract_metric(self, stats):
        raise NotImplementedError

    def initialize(self, **kwargs):
        if self.watch is not None:
            cls = CLASS_NAMES.index(self.watch)
            if not self.loader.class_lengths[cls]:
                raise ValueError(
                    "decision watches the %r split but the loader has no "
                    "%s samples (class_lengths=%s)"
                    % (self.watch, self.watch, self.loader.class_lengths))

    def run(self):
        loader = self.loader
        if not bool(loader.class_ended):
            return
        cls = loader.minibatch_class
        stats = self.trainer.read_class_stats(cls)   # host sync point
        self.epoch_metrics[cls] = stats
        if not bool(loader.epoch_ended):
            return
        # epoch boundary: decide on the watched class's metric
        # (default: validation, falling back to train)
        if self.watch is not None:
            watch_cls = CLASS_NAMES.index(self.watch)
        else:
            watch_cls = VALID if loader.class_lengths[VALID] else TRAIN
        watched = self.epoch_metrics[watch_cls]
        metric = self.extract_metric(watched) if watched else None
        self.improved <<= (metric is not None and
                           (self.best_metric is None or
                            metric < self.best_metric))
        if bool(self.improved):
            self.best_metric = metric
            self.best_epoch = loader.epoch_number
            self.epochs_since_improvement = 0
            self.on_improved()
        else:
            self.epochs_since_improvement += 1
        self._log_epoch(loader)
        if self.epochs_since_improvement >= self.fail_iterations:
            self.complete <<= True
        if (self.max_epochs is not None and
                loader.epoch_number >= self.max_epochs):
            self.complete <<= True
        self.trainer.reset_epoch_stats()

    def on_improved(self):
        """Hook: e.g. remember best params for the snapshotter."""
        self.best_params = self.trainer.host_params()

    def _log_epoch(self, loader):
        parts = []
        payload = {"epoch": int(loader.epoch_number)}
        for cls in (TRAIN, VALID):
            st = self.epoch_metrics[cls]
            if st:
                parts.append("%s %s" % (CLASS_NAMES[cls],
                                        self.format_stats(st)))
                for k, v in st.items():
                    try:   # numeric scalars feed the dashboard series
                        fv = float(v)
                    except (TypeError, ValueError):
                        continue
                    if math.isfinite(fv):   # NaN would poison the JSON
                        payload[CLASS_NAMES[cls] + "_" + k] = fv
        # structured per-epoch metric event: the web dashboard's
        # /api/metrics sparklines read these from the event ring (ref
        # the node.js status app's live charts, web/)
        self.event("epoch", "single", **payload)
        self.info("epoch %d: %s%s", loader.epoch_number, "; ".join(parts),
                  " *" if bool(self.improved) else "")

    def format_stats(self, stats):
        return str(stats)

    def get_metric_values(self):
        return {"best_metric": self.best_metric,
                "best_epoch": self.best_epoch,
                "epoch_metrics": {
                    CLASS_NAMES[c]: self.epoch_metrics[c]
                    for c in range(3) if self.epoch_metrics[c]}}


class DecisionGD(DecisionBase):
    """Classification: watches validation error % (ref DecisionGD)."""

    def extract_metric(self, stats):
        return stats["n_errors"] / max(stats["count"], 1)

    def format_stats(self, stats):
        return "err %.2f%% (%d/%d) loss %.4f" % (
            100.0 * stats["n_errors"] / max(stats["count"], 1),
            stats["n_errors"], stats["count"], stats["loss"])


class DecisionMSE(DecisionBase):
    """Regression/autoencoder: watches validation per-element RMSE
    (ref DecisionMSE)."""

    def _rmse(self, stats):
        n_feat = getattr(self.trainer, "output_features", 1)
        return float(np.sqrt(stats["loss"] /
                             (max(stats["count"], 1) * n_feat)))

    def extract_metric(self, stats):
        return self._rmse(stats)

    def format_stats(self, stats):
        return "rmse %.4f" % self._rmse(stats)
