"""Staged NN units (replaces the reference's per-unit kernel dispatch).

In the reference every forward/GD unit launched its own kernel per
iteration (AcceleratedUnit.execute_kernel, SURVEY.md §3.3).  Here
:class:`StagedTrainer` *stages* the whole forward → loss → backward →
update chain into two jitted functions (train step, eval step) built once
at initialize.  Per iteration the host moves only a [minibatch_size] index
vector to the device; metrics accumulate in device-resident per-class
accumulators, read back exactly once per class sweep by the Decision unit —
the hot loop never blocks on device→host sync.

Per-layer ``Forward`` units still exist as introspection/export handles
(weights live in the trainer's param pytree; they expose views), keeping the
reference's unit-graph UX without its dispatch cost."""

import time

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu import prng, telemetry
from veles_tpu.config import root
from veles_tpu.loader.base import CLASS_NAMES, TRAIN
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.models import optimizer
from veles_tpu.ops import losses
from veles_tpu.units import Unit


class Forward(Unit):
    """Introspection handle for one layer (ref Znicz forward units).  Its
    run() is a no-op — compute happens inside the staged step."""

    def __init__(self, workflow, layer, trainer, **kwargs):
        kwargs.setdefault("name", layer.name)
        super(Forward, self).__init__(workflow, **kwargs)
        self.layer = layer
        self._trainer = trainer
        self.view_group = "WORKER"

    @property
    def weights(self):
        p = self._trainer.params.get(self.layer.name)
        return None if p is None else p.get("weights")

    @property
    def bias(self):
        p = self._trainer.params.get(self.layer.name)
        return None if p is None else p.get("bias")

    @property
    def output_shape(self):
        return self.layer.output_shape


class StagedTrainer(Unit):
    """Runs the staged train/eval step for the current minibatch.

    Demands (data links from the loader): ``minibatch_indices``,
    ``minibatch_valid``, ``minibatch_class``."""

    def __init__(self, workflow, layers, loss="softmax", gd_defaults=None,
                 mesh_config=None, dataset_placement="shard",
                 steps_per_dispatch=1, **kwargs):
        super(StagedTrainer, self).__init__(workflow, **kwargs)
        self.layers = layers
        self.loss = loss
        self.gd_defaults = dict(gd_defaults or {})   # caller's dict stays
        #: global gradient-norm clip applied to the WHOLE grad tree
        #: before the per-layer updates (gd_defaults["clip_norm"]; a
        #: workflow-level knob — per-layer clipping would change the
        #: norm's meaning)
        self.clip_norm = self.gd_defaults.pop("clip_norm", None)
        #: gradient accumulation (gd_defaults["grad_accum_steps"]): every
        #: step's gradient joins a running sum; one optimizer update per
        #: k microbatches with the mean — k× the effective batch without
        #: k× the activation memory.  Composes with steps_per_dispatch
        #: (the scan body carries the accumulator like any other state).
        self.grad_accum = int(self.gd_defaults.pop("grad_accum_steps", 1))
        if self.grad_accum < 1:
            raise ValueError("grad_accum_steps must be >= 1")
        #: Polyak/EMA weight averaging (gd_defaults["ema_decay"], e.g.
        #: 0.999): a decayed average of the params advances on every
        #: real update; ``ema_params`` serves/evaluates with it
        self.ema_decay = self.gd_defaults.pop("ema_decay", None)
        if self.ema_decay is not None and not 0.0 < self.ema_decay < 1.0:
            raise ValueError("ema_decay must be in (0, 1), got %r"
                             % (self.ema_decay,))
        #: fuse this many minibatch steps into ONE device dispatch
        #: (lax.scan inside the jitted sweep).  Amortizes host→device
        #: dispatch latency — the dominant cost for small models and for
        #: remote/tunneled TPUs — exactly k× fewer dispatches; numerics
        #: are the same per-step ops in the same order.  Index-mode
        #: loaders only (data-carrying loaders stream host tensors, so
        #: the host must intervene every step anyway).
        self.steps_per_dispatch = int(steps_per_dispatch)
        if self.steps_per_dispatch < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        self._pending = []          # queued (idx, valid, step, lr) rows
        self._pending_cls = None
        #: parallel.MeshConfig or None (single device).  With a mesh, params
        #: shard over the model axis (tp) and the minibatch over the data
        #: axis (dp) — XLA inserts the gradient psum over ICI.
        self.mesh_config = mesh_config
        #: 'shard' (default): the HBM dataset rows shard over the data axis
        #: — each device holds 1/D of the dataset, the in-step gather rides
        #: a psum_scatter (one minibatch of ICI traffic).  'replicate':
        #: r1 behavior, every device holds a full copy (fastest when the
        #: dataset is small).
        if dataset_placement not in ("shard", "replicate"):
            raise ValueError("dataset_placement must be 'shard' or "
                             "'replicate', got %r" % (dataset_placement,))
        self.dataset_placement = dataset_placement
        self.demand("loader")
        self.params = {}
        self.velocity = {}
        self.class_stats = [None, None, None]  # device accumulators
        self._step_counter = 0
        #: multiplier on every layer's learning rate, set per epoch by an
        #: LRAdjuster unit (ref Znicz lr_adjust); traced, so changing it
        #: does NOT recompile the step
        self.lr_scale = 1.0
        self.train_only_classes = (TRAIN,)
        self.view_group = "TRAINER"
        #: the numeric-fault sentinel (services.sentinel): build-time
        #: probe knobs, the device-resident health accumulator carried
        #: through every train step, the traced replay skip list, and
        #: the HealthSentinel unit observing the sync point (set by
        #: StandardWorkflow wiring; None = probes report to nobody)
        from veles_tpu.services import sentinel as _sentinel
        self._sentinel_cfg = _sentinel.probe_config()
        self.sentinel = None
        self.health = None
        self._health_host = None
        self._health_committed = {}
        self._skip_steps = _sentinel.skip_steps_array(
            self._sentinel_cfg["force_skip_steps"],
            self._sentinel_cfg["max_skip_steps"])
        self._skip_dev = None
        #: step telemetry: per-class sweep accumulators
        #: {cls: [t0, steps]} — opened by the first staged step of a
        #: class sweep, closed (and emitted) at the read_class_stats
        #: sync point, so sweep wall time includes the device work the
        #: async dispatches deferred
        self._sweep_ = {}
        self._mem_watcher = None

    # ------------------------------------------------------------ building
    def initialize(self, **kwargs):
        loader = self.loader
        sample_shape = (tuple(loader.sample_shape) if loader.carries_data
                        else tuple(loader.data.shape[1:]))
        shape = sample_shape
        rng = prng.get("weights")
        hypers = {}
        for i, layer in enumerate(self.layers):
            layer.name = "l%02d_%s" % (i, layer.type)
            shape = layer.setup(shape)
            if layer.has_params:
                self.params[layer.name] = jax.tree_util.tree_map(
                    jnp.asarray, layer.init_params(rng))
                hypers[layer.name] = optimizer.resolve_hyper(
                    layer.gd, self.gd_defaults, layer_type=layer.type)
                if int(layer.cfg.get("lora_rank", 0)) > 0:
                    # LoRA freeze is stop_gradient on the base leaves —
                    # but weight DECAY applies outside the gradient
                    # (adamw's decoupled w - lr*wd*w especially), so a
                    # configured weights_decay would silently shrink
                    # the "frozen" base matrices every step.  Adapted
                    # layers therefore decay nothing.
                    hypers[layer.name] = dict(
                        hypers[layer.name], weights_decay=0.0,
                        weights_decay_bias=0.0)
        self.velocity = optimizer.init_state(self.params,
                                             grad_accum=self.grad_accum,
                                             ema_decay=self.ema_decay,
                                             hypers=hypers)
        self._hypers = hypers
        # resolve weight-tying references now that layers are named:
        # tie_to may be a layer NAME or a layer TYPE (e.g. "embedding");
        # a bad reference must fail here, not as a KeyError mid-trace
        by_type = {}
        for layer in self.layers:
            by_type.setdefault(layer.type, layer.name)
        for layer in self.layers:
            tie = layer.cfg.get("tie_to")
            if not tie:
                continue
            if tie not in self.params:
                resolved = by_type.get(tie)
                if resolved is None or resolved not in self.params:
                    raise ValueError(
                        "%s: tie_to=%r matches no parameterized layer "
                        "(names: %s)" % (layer.name, tie,
                                         sorted(self.params)))
                layer.cfg["tie_to"] = resolved
                if hasattr(layer, "tie_to"):
                    layer.tie_to = resolved
        self.output_features = int(np.prod(shape))
        self._base_key = jax.random.key(
            int(prng.get("trainer")._seed))
        if self.mesh_config is not None:
            from veles_tpu.parallel import sharding
            mc = self.mesh_config
            if {"seq", "expert", "pipe"} & set(mc.mesh.shape):
                # sequence-parallel attention (impl=ring/ulysses),
                # expert-parallel MoE, and pipelined stages need the mesh
                # to build their shard_map
                for layer in self.layers:
                    if hasattr(type(layer), "mesh"):
                        layer.mesh = mc.mesh
            if loader.minibatch_size % mc.data_size:
                raise ValueError(
                    "minibatch_size %d not divisible by data axis %d"
                    % (loader.minibatch_size, mc.data_size))
            self._param_overrides = {
                layer.name: ov for layer in self.layers if layer.has_params
                for ov in [layer.param_partition_specs(
                    dict(mc.mesh.shape))] if ov is not None}
            self.params = sharding.shard_params(self.params, mc,
                                                self._param_overrides)
            self.velocity = sharding.shard_params(self.velocity, mc,
                                                  self._param_overrides)
        self.reset_epoch_stats()
        from veles_tpu.services import sentinel as _sentinel
        self.health = _sentinel.init_health()
        self._skip_dev = jnp.asarray(self._skip_steps)
        self._build_steps()

    # ----------------------------------------------------- numeric fault
    def add_skip_steps(self, steps):
        """Arm the replay skip list (services.sentinel rung 2): these
        staged-step counters' updates are gated off inside the jitted
        step.  Values change without a recompile (the list's CAPACITY
        is the static shape); overflowing the capacity raises — a
        replay that cannot represent its skip set is not exact."""
        from veles_tpu.services import sentinel as _sentinel
        cap = self._sentinel_cfg["max_skip_steps"]
        merged = sorted(
            {int(s) for s in self._skip_steps if int(s) >= 0}
            | {int(s) for s in steps})
        if len(merged) > cap:
            raise ValueError(
                "skip list overflow: %d poisoned steps exceed "
                "root.common.sentinel.max_skip_steps=%d — the replay "
                "could not stay exact" % (len(merged), cap))
        self._skip_steps = _sentinel.skip_steps_array(merged, cap)
        self._skip_dev = jnp.asarray(self._skip_steps)

    def reset_health_marks(self):
        """Clear the per-incident first/last-bad-step marks (the
        sentinel calls this after latching an incident, so the NEXT
        sweep's marks identify freshly poisoned steps instead of
        re-reporting the all-time minimum).  Host-side leaf swap, no
        device sync; the counters stay cumulative."""
        if self.health is None:
            return
        from veles_tpu.services import sentinel as _sentinel
        self.health = dict(
            self.health,
            first_bad_step=jnp.full((), _sentinel.NO_BAD_STEP,
                                    jnp.int32),
            last_bad_step=jnp.full((), -1, jnp.int32))

    def _chaos_poison(self, grads, step):
        """The numerics-chaos injection hooks
        (``root.common.chaos.nan_grads_step`` / ``nan_grads_from``,
        tools/numerics_chaos.py): poison the whole gradient tree with
        NaN at the configured staged step(s).  A build-time gate —
        identity (zero ops traced) when unarmed."""
        from veles_tpu.config import root as _root
        nan_step = _root.common.chaos.get("nan_grads_step", None)
        nan_from = _root.common.chaos.get("nan_grads_from", None)
        if nan_step is None and nan_from is None:
            return grads
        hit = jnp.zeros((), bool)
        if nan_step is not None:
            hit = hit | (step == jnp.int32(int(nan_step)))
        if nan_from is not None:
            hit = hit | (step >= jnp.int32(int(nan_from)))
        return jax.tree_util.tree_map(
            lambda g: jnp.where(hit, jnp.full_like(g, jnp.nan), g),
            grads)

    def _sentinel_gate(self, params, velocity, new_params, new_velocity,
                       health, loss, grads, step, skip_steps):
        """In-jit rung 1 (services.sentinel): run the health probes and
        select the pre-step params/velocity when the step is poisoned
        or policy-skipped — a ``where`` with a scalar predicate, so the
        applied branch is bit-exact either way.  Disabled sentinel
        passes everything through untouched (same traced signature, no
        extra ops)."""
        if not self._sentinel_cfg["enabled"]:
            return new_params, new_velocity, health
        from veles_tpu.services import sentinel as _sentinel
        health, ok = _sentinel.apply_probes(
            health, loss, grads, new_params, params, step, skip_steps,
            self._sentinel_cfg)

        def sel(new, old):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(ok, a, b), new, old)

        return sel(new_params, params), sel(new_velocity, velocity), \
            health

    def health_verdict(self):
        """Commit-time health stamp for the snapshotter: ``"healthy"``
        when no anomaly landed since the previous verdict,
        ``"unhealthy:<kind>"`` otherwise (consumes the delta).  Reads
        the device accumulator directly — the commit path already
        gathers the whole model, one more scalar fetch is noise."""
        if self.health is None:
            return None
        from veles_tpu.services import sentinel as _sentinel
        h = jax.device_get(self.health)
        keys = _sentinel.ANOMALY_KINDS + ("anomalies",)
        deltas = {}
        for k in keys:
            cur = float(h.get(k, 0.0))
            deltas[k] = cur - self._health_committed.get(k, 0.0)
            self._health_committed[k] = cur
        if deltas.get("anomalies", 0) > 0:
            kind = _sentinel.dominant_kind(deltas) or "unknown"
            return "unhealthy:%s" % kind
        return "healthy"

    def _forward(self, params, x, train, key):
        for i, layer in enumerate(self.layers):
            lkey = (jax.random.fold_in(key, i)
                    if (train and layer.needs_rng) else None)
            if getattr(layer, "needs_full_params", False):
                # weight tying (TiedLMHead): the layer reads another
                # layer's params; remat would checkpoint the whole tree
                # for no gain, so tied heads run un-remat'd
                x = layer.apply(params, x, train=train, key=lkey)
                continue
            if train and layer.cfg.get("remat"):
                # rematerialize this layer's activations in the backward
                # pass (jax.checkpoint) — memory for FLOPs, the standard
                # long-context trade.  Aux values (MoE router loss) must
                # cross the remat boundary as outputs, not side effects.
                #
                # remat=True recomputes EVERYTHING (max memory savings,
                # but the recompute FLOPs don't count toward MFU);
                # remat="dots" keeps matmul outputs and recomputes only
                # the cheap elementwise ops (jax dots_saveable policy) —
                # near-no-remat step time at a fraction of the activation
                # memory, usually the right default for MXU-bound
                # transformer training.
                policy = (jax.checkpoint_policies.dots_saveable
                          if layer.cfg.get("remat") == "dots" else None)

                def fn(p, xx, kk, layer=layer):
                    y = layer.apply(p, xx, train=True, key=kk)
                    return y, getattr(layer, "last_aux", None)
                # prevent_cse=False: we are always under jit (and often
                # inside the fused sweep's lax.scan), where the CSE
                # barriers the default inserts only cost fusion
                x, aux = jax.checkpoint(fn, prevent_cse=False,
                                        policy=policy)(
                    params.get(layer.name), x, lkey)
                if aux is not None:
                    layer.last_aux = aux
            else:
                x = layer.apply(params.get(layer.name), x, train=train,
                                key=lkey)
        return x

    def _loss_and_stats(self, params, data, labels, targets, idx, valid,
                        train, key):
        """Index mode: gather the minibatch from HBM-resident arrays
        (``_gather`` is the plain jnp.take on one device, or the
        psum_scatter collective gather when the dataset is row-sharded)."""
        tgt = (self._gather(targets, idx)
               if losses.get_loss(self.loss)[1] == "regression" else None)
        return self._loss_from_batch(
            params, self._gather(data, idx),
            self._gather(labels, idx), tgt, valid, train, key)

    def _loss_from_batch(self, params, x, lbl, tgt, valid, train, key):
        out = self._forward(params, x, train, key)
        # router auxiliary losses (MoE load balancing): layers stash the
        # traced value during _forward; read it back inside the same trace
        aux_total = 0.0
        for layer in self.layers:
            la = getattr(layer, "last_aux", None)
            if la is not None:
                aux_total = aux_total + float(
                    layer.cfg.get("aux_weight", 0.01)) * la
                layer.last_aux = None
        loss_fn, _ = losses.get_loss(self.loss)
        loss_sum, err_sum, n_valid, n_features = loss_fn(out, lbl, tgt,
                                                         valid)
        # optimized loss is per-element mean (keeps lr scale comparable
        # across output widths); stats carry the raw sum for epoch metrics
        denom = jnp.maximum(n_valid, 1.0) * n_features
        return loss_sum / denom + aux_total, {"loss": loss_sum,
                                              "n_errors": err_sum,
                                              "count": n_valid}

    def _build_steps(self):
        if self.loader.carries_data:
            self._build_steps_direct()
            return
        loader = self.loader
        labels = (loader.labels if loader.labels is not None
                  else jnp.zeros((loader.total_samples,), jnp.int32))
        targets = loader.targets
        if losses.get_loss(self.loss)[1] == "regression" and targets is None:
            targets = loader.data   # autoencoder: reconstruct the input
        hypers = self._hypers

        def train_step(params, velocity, acc, health, data, labels,
                       targets, idx, valid, step, lr_scale, skip_steps):
            key = jax.random.fold_in(self._base_key, step)

            def loss_fn(p):
                loss, stats = self._loss_and_stats(
                    p, data, labels, targets, idx, valid, True, key)
                return loss, stats

            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = self._chaos_poison(grads, step)
            new_params, new_velocity = optimizer.update(
                params, grads, velocity, hypers, lr_scale=lr_scale,
                clip_norm=self.clip_norm, grad_accum=self.grad_accum,
                ema_decay=self.ema_decay)
            params, velocity, health = self._sentinel_gate(
                params, velocity, new_params, new_velocity, health,
                loss, grads, step, skip_steps)
            acc = jax.tree_util.tree_map(jnp.add, acc, stats)
            return params, velocity, acc, health

        def eval_step(params, acc, data, labels, targets, idx, valid):
            _, stats = self._loss_and_stats(
                params, data, labels, targets, idx, valid, False,
                jax.random.key(0))
            return jax.tree_util.tree_map(jnp.add, acc, stats)

        self._jit_steps(train_step, eval_step)
        self._build_sweeps(train_step, eval_step)
        self._gather = FullBatchLoader.gather
        if self.mesh_config is not None:
            from veles_tpu.parallel import sharding
            mc = self.mesh_config
            if self.dataset_placement == "shard" and mc.data_size > 1:
                self._gather = sharding.make_sharded_gather(mc)
                place = lambda x: sharding.shard_dataset(np.asarray(x), mc)
            else:
                place = lambda x: sharding.replicate(x, mc)
            labels = place(labels)
            self._data_dev = place(loader.data)
            if targets is loader.data:
                targets = self._data_dev  # autoencoder: don't copy twice
            elif targets is not None:
                targets = place(targets)
        else:
            self._data_dev = loader.data
        self._labels_dev = labels
        self._targets_dev = (targets if targets is not None
                             else jnp.zeros((1,), jnp.float32))

    def _build_sweeps(self, train_step, eval_step):
        """k-step fused dispatch (steps_per_dispatch > 1, index mode):
        one jitted lax.scan advances k minibatches per host→device round
        trip.  The scan body IS train_step / eval_step — the exact
        functions the per-step path jits — so the two paths cannot
        diverge; partial groups (class change, epoch end) fall back to
        the per-step functions, so nothing ever recompiles on a ragged
        tail."""
        self._sweeps = None
        if self.steps_per_dispatch <= 1:
            return

        def train_sweep(params, velocity, acc, health, data, labels,
                        targets, idxs, valids, steps, lr_scales,
                        skip_steps):
            def body(carry, inp):
                idx, valid, step, lr_s = inp
                return train_step(*carry, data, labels, targets, idx,
                                  valid, step, lr_s, skip_steps), None

            (params, velocity, acc, health), _ = jax.lax.scan(
                body, (params, velocity, acc, health),
                (idxs, valids, steps, lr_scales))
            return params, velocity, acc, health

        def eval_sweep(params, acc, data, labels, targets, idxs, valids):
            def body(a, inp):
                idx, valid = inp
                return eval_step(params, a, data, labels, targets, idx,
                                 valid), None

            return jax.lax.scan(body, acc, (idxs, valids))[0]

        pins = self._shard_pins()
        if pins is None:
            self._sweeps = (
                jax.jit(train_sweep, donate_argnums=(0, 1, 2, 3)),
                jax.jit(eval_sweep, donate_argnums=(1,)))
            return
        p_sh, v_sh, acc_sh, health_sh = pins
        self._sweeps = (
            jax.jit(train_sweep, donate_argnums=(0, 1, 2, 3),
                    out_shardings=(p_sh, v_sh, acc_sh, health_sh)),
            jax.jit(eval_sweep, donate_argnums=(1,),
                    out_shardings=acc_sh))

    def _shard_pins(self):
        """(params, velocity, acc, health) output shardings under a
        mesh (params/velocity per the partition rules, stat and
        sentinel-health accumulators replicated); None on a single
        device."""
        if self.mesh_config is None:
            return None
        from veles_tpu.parallel import sharding
        mc = self.mesh_config
        repl = sharding.replicated_sharding(mc)
        overrides = getattr(self, "_param_overrides", None)
        from veles_tpu.services import sentinel as _sentinel
        health_struct = (self.health if self.health is not None
                         else _sentinel.init_health())
        return (sharding.param_shardings(self.params, mc, overrides),
                sharding.param_shardings(self.velocity, mc, overrides),
                jax.tree_util.tree_map(lambda _: repl,
                                       self._zero_stats()),
                jax.tree_util.tree_map(lambda _: repl, health_struct))

    def _jit_steps(self, train_step, eval_step):
        """jit the pair with donation; under a mesh, pin the output
        shardings — shared by the index and data-carrying builders (and
        the fused sweeps) so the paths cannot diverge."""
        pins = self._shard_pins()
        if pins is None:
            self._train_step = jax.jit(train_step,
                                       donate_argnums=(0, 1, 2, 3))
            self._eval_step = jax.jit(eval_step, donate_argnums=(1,))
            return
        p_sh, v_sh, acc_sh, health_sh = pins
        self._train_step = jax.jit(
            train_step, donate_argnums=(0, 1, 2, 3),
            out_shardings=(p_sh, v_sh, acc_sh, health_sh))
        self._eval_step = jax.jit(eval_step, donate_argnums=(1,),
                                  out_shardings=acc_sh)

    def _build_steps_direct(self):
        """Steps for data-carrying loaders (streaming/replay/host-fallback):
        the minibatch tensor arrives from the host each step.  Under a mesh
        the arriving batch shards over the data axis (host-streaming SPMD —
        lifts the r1 restriction); because every dispatch is async, the
        host-side production of batch t+1 naturally overlaps the device
        compute of step t (double buffering for free — nothing below blocks
        until Decision reads the epoch stats).  mse uses the loader's
        minibatch_targets when present, else reconstructs the input."""
        hypers = self._hypers

        def train_step(params, velocity, acc, health, x, lbl, tgt,
                       valid, step, lr_scale, skip_steps):
            key = jax.random.fold_in(self._base_key, step)

            def loss_fn(p):
                return self._loss_from_batch(p, x, lbl, tgt, valid, True,
                                             key)

            (loss, stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = self._chaos_poison(grads, step)
            new_params, new_velocity = optimizer.update(
                params, grads, velocity, hypers, lr_scale=lr_scale,
                clip_norm=self.clip_norm, grad_accum=self.grad_accum,
                ema_decay=self.ema_decay)
            params, velocity, health = self._sentinel_gate(
                params, velocity, new_params, new_velocity, health,
                loss, grads, step, skip_steps)
            acc = jax.tree_util.tree_map(jnp.add, acc, stats)
            return params, velocity, acc, health

        def eval_step(params, acc, x, lbl, tgt, valid):
            _, stats = self._loss_from_batch(params, x, lbl, tgt, valid,
                                             False, jax.random.key(0))
            return jax.tree_util.tree_map(jnp.add, acc, stats)

        self._sweeps = None     # fused sweeps are index-mode only
        self._jit_steps(train_step, eval_step)

    def _direct_batch(self, loader):
        x = np.asarray(loader.minibatch_data)
        lbl = (np.asarray(loader.minibatch_labels)
               if getattr(loader, "minibatch_labels", None) is not None
               else np.zeros((x.shape[0],), np.int32))
        tgt = (np.asarray(loader.minibatch_targets)
               if getattr(loader, "minibatch_targets", None) is not None
               else None)   # None → reuse x's device copy (one transfer)
        if self.mesh_config is not None:
            from veles_tpu.parallel import sharding
            mc = self.mesh_config
            x_dev = sharding.shard_batch(x, mc)
            return (x_dev, sharding.shard_batch(lbl, mc),
                    x_dev if tgt is None else sharding.shard_batch(tgt, mc))
        x_dev = jnp.asarray(x)
        return (x_dev, jnp.asarray(lbl),
                x_dev if tgt is None else jnp.asarray(tgt))

    # ------------------------------------------------------------- hot loop
    def run(self):
        # free when no trace is active; under --profile each step shows up
        # as a named region in the xplane timeline (ref per-unit timing,
        # units.py:805-817 → SURVEY §5 "TPU equivalent: jax profiler")
        with jax.profiler.StepTraceAnnotation("veles_step",
                                              step_num=self._step_counter):
            self._run_step()
        if root.common.engine.get("sync_run"):
            # honest per-unit wall time: charge the device work to THIS
            # unit instead of the next host sync (ref --sync-run,
            # accelerated_units.py:186-193); queued sweep steps must
            # dispatch now or their device time would land on whichever
            # step finally flushes
            self.flush()
            jax.block_until_ready(self.class_stats)

    def _run_step(self):
        loader = self.loader
        self._note_step(loader.minibatch_class)
        if loader.carries_data:
            cls = loader.minibatch_class
            x, lbl, tgt = self._direct_batch(loader)
            if self.mesh_config is not None:
                from veles_tpu.parallel import sharding
                valid = sharding.shard_batch(
                    np.asarray(loader.minibatch_valid), self.mesh_config)
            else:
                valid = jnp.asarray(loader.minibatch_valid)
            if cls in self.train_only_classes:
                self._step_counter += 1
                (self.params, self.velocity, self.class_stats[cls],
                 self.health) = self._train_step(
                    self.params, self.velocity, self.class_stats[cls],
                    self.health, x, lbl, tgt, valid, self._step_counter,
                    jnp.float32(self.lr_scale), self._skip_dev)
            else:
                self.class_stats[cls] = self._eval_step(
                    self.params, self.class_stats[cls], x, lbl, tgt, valid)
            return
        cls = loader.minibatch_class
        if self._sweeps is not None:
            if self._pending and self._pending_cls != cls:
                self.flush()
            train = cls in self.train_only_classes
            if train:
                self._step_counter += 1
            self._pending_cls = cls
            self._pending.append((
                np.array(loader.minibatch_indices),
                np.array(loader.minibatch_valid, np.float32),
                self._step_counter, float(self.lr_scale)))
            if len(self._pending) >= self.steps_per_dispatch:
                self.flush()
            return
        if self.mesh_config is not None:
            from veles_tpu.parallel import sharding
            idx = sharding.shard_batch(
                jnp.asarray(loader.minibatch_indices), self.mesh_config)
            valid = sharding.shard_batch(
                jnp.asarray(loader.minibatch_valid), self.mesh_config)
        else:
            idx = jnp.asarray(loader.minibatch_indices)
            valid = jnp.asarray(loader.minibatch_valid)
        if cls in self.train_only_classes:
            self._step_counter += 1
            (self.params, self.velocity, self.class_stats[cls],
             self.health) = self._train_step(
                self.params, self.velocity, self.class_stats[cls],
                self.health, self._data_dev, self._labels_dev,
                self._targets_dev, idx, valid, self._step_counter,
                jnp.float32(self.lr_scale), self._skip_dev)
        else:
            self.class_stats[cls] = self._eval_step(
                self.params, self.class_stats[cls], self._data_dev,
                self._labels_dev, self._targets_dev, idx, valid)

    # ---------------------------------------------------------- fused sweep
    def _place_stack(self, x):
        """Device placement for a [k, B] stacked index/valid matrix: one
        transfer per flush instead of one per step."""
        if self.mesh_config is None:
            return jnp.asarray(x)
        from veles_tpu.parallel import sharding
        return sharding.shard_batch_stack(x, self.mesh_config)

    def flush(self):
        """Dispatch any queued minibatches (steps_per_dispatch > 1).  Full
        k-groups ride the fused sweep; the ragged tail (class change or
        epoch end) rides the per-step functions — both compiled once."""
        if not self._pending:
            return
        # the fused dispatch is its own device-trace span: in an xplane
        # capture the k-step scan shows up under the same name the host
        # telemetry uses
        ann = telemetry.trace_annotation()
        if ann is None:
            return self._flush_pending()
        with ann("trainer.dispatch:%s" % self.name):
            return self._flush_pending()

    def _flush_pending(self):
        cls = self._pending_cls
        pending, self._pending = self._pending, []
        self._pending_cls = None
        train = cls in self.train_only_classes
        train_sweep, eval_sweep = self._sweeps
        k = self.steps_per_dispatch
        i = 0
        while len(pending) - i >= k:
            group = pending[i:i + k]
            i += k
            idxs = self._place_stack(np.stack([g[0] for g in group]))
            valids = self._place_stack(np.stack([g[1] for g in group]))
            if train:
                steps = jnp.asarray([g[2] for g in group], jnp.int32)
                lrs = jnp.asarray([g[3] for g in group], jnp.float32)
                (self.params, self.velocity, self.class_stats[cls],
                 self.health) = train_sweep(
                    self.params, self.velocity, self.class_stats[cls],
                    self.health, self._data_dev, self._labels_dev,
                    self._targets_dev, idxs, valids, steps, lrs,
                    self._skip_dev)
            else:
                self.class_stats[cls] = eval_sweep(
                    self.params, self.class_stats[cls], self._data_dev,
                    self._labels_dev, self._targets_dev, idxs, valids)
        for idx, valid, step, lr in pending[i:]:
            if self.mesh_config is not None:
                from veles_tpu.parallel import sharding
                idx = sharding.shard_batch(jnp.asarray(idx),
                                           self.mesh_config)
                valid = sharding.shard_batch(jnp.asarray(valid),
                                             self.mesh_config)
            else:
                idx, valid = jnp.asarray(idx), jnp.asarray(valid)
            if train:
                (self.params, self.velocity, self.class_stats[cls],
                 self.health) = self._train_step(
                    self.params, self.velocity, self.class_stats[cls],
                    self.health, self._data_dev, self._labels_dev,
                    self._targets_dev, idx, valid, step,
                    jnp.float32(lr), self._skip_dev)
            else:
                self.class_stats[cls] = self._eval_step(
                    self.params, self.class_stats[cls], self._data_dev,
                    self._labels_dev, self._targets_dev, idx, valid)

    def stop(self):
        # a run stopped mid-sweep leaves an open accumulator whose t0
        # would poison the NEXT run's first sweep (wall time spanning
        # the idle gap → garbage examples/s and a spurious MFU
        # shortfall); Workflow.run calls stop() on every unit at run
        # end, so drop any un-emitted accumulator here
        self._sweep_.clear()

    # ------------------------------------------------------------- metrics
    def _note_step(self, cls):
        """Open/advance the class sweep accumulator (host-side only —
        no device sync; the wall clock closes at read_class_stats)."""
        sw = self._sweep_.get(cls)
        if sw is None:
            self._sweep_[cls] = sw = [time.perf_counter(), 0]
        sw[1] += 1

    def _emit_step_telemetry(self, cls, stats):
        """Close the class sweep at the read_class_stats sync point:
        step counters, loss/examples-per-second gauges, the JSONL step
        record, device-memory gauges, and (train classes) the
        predicted-vs-measured MFU check.  Never raises — telemetry must
        not kill the training loop it instruments."""
        sw = self._sweep_.pop(cls, None)
        if not sw or not sw[1]:
            return
        # the multi-host heartbeat runs FIRST, outside the fail-soft
        # guard below: sweep open/close is SPMD-lockstep on every host,
        # but the guarded telemetry body can fail on host-LOCAL state
        # (disk full, backend memory stats) — if that skipped the
        # heartbeat's allgather on one host only, every later collective
        # would be off by one and the pod would hang.  Only the
        # collective itself rides this path; its reporting (gauges,
        # desync dump) is exception-guarded inside multihost_check.
        telemetry.health.multihost_check(
            self._step_counter, time.perf_counter() - sw[0],
            registry=telemetry.registry)
        try:
            self._emit_step_telemetry_inner(cls, stats, sw)
        except Exception as e:   # noqa: BLE001 — observe, never abort
            if not self.__dict__.get("_telemetry_error_warned_"):
                self.__dict__["_telemetry_error_warned_"] = True
                self.warning("step telemetry failed (%s: %s) — "
                             "training continues, further telemetry "
                             "errors are silenced", type(e).__name__, e)

    def _emit_step_telemetry_inner(self, cls, stats, sw):
        wall = time.perf_counter() - sw[0]
        steps = sw[1]
        name = CLASS_NAMES[cls]
        examples = int(stats["count"])
        loss_mean = stats["loss"] / max(examples, 1)
        reg = telemetry.registry
        lbl = {"class": name}
        reg.counter("veles_steps_total", "staged steps dispatched",
                    ("class",)).inc(steps, **lbl)
        reg.counter("veles_examples_total", "examples processed",
                    ("class",)).inc(examples, **lbl)
        if wall > 0:
            reg.gauge("veles_examples_per_sec",
                      "examples/s over the last class sweep",
                      ("class",)).set(examples / wall, **lbl)
            reg.histogram("veles_step_wall_seconds",
                          "mean per-step wall time per sweep "
                          "(host dispatch + device, sync-point "
                          "amortized)", ("class",)).observe(
                wall / steps, **lbl)
        reg.gauge("veles_loss", "mean per-example loss of the last "
                  "class sweep", ("class",)).set(loss_mean, **lbl)
        reg.emit("step", steps=steps, examples=examples, wall_s=wall,
                 examples_per_sec=examples / wall if wall > 0 else 0.0,
                 step_ms=wall / steps * 1e3, loss=loss_mean,
                 loss_sum=stats["loss"], n_errors=stats["n_errors"],
                 **lbl)
        # black-box surface: the sweep is the staged loop's one honest
        # sync point, so this is where the flight record learns the
        # step counter and the watchdog learns the run is alive (the
        # spmd heartbeat allgather runs in _emit_step_telemetry, before
        # this fail-soft body)
        telemetry.flight.record(
            "step", step=self._step_counter, steps=steps,
            examples=examples, wall_s=wall, loss=loss_mean, **lbl)
        if wall > 0:
            # bank the sweep throughput in the performance ledger
            # (telemetry.ledger, fail-soft): per-class history the
            # regression sentinel bands — the train-class step_ms /
            # MFU rows ride the MFU check below
            telemetry.ledger.record_value(
                "sweep_examples_per_sec", examples / wall,
                workload="%s/%s" % (self.name, name), unit="ex/s",
                better="higher", source="trainer.sweep", steps=steps)
        telemetry.health.note_progress(step=self._step_counter)
        if self._health_host is not None:
            # sentinel health (services.sentinel), read off the SAME
            # device_get as the class stats — cumulative counters as
            # gauges (the anomaly/rollback counters live in the
            # sentinel unit; these are the raw in-jit probe tallies)
            reg.gauge("veles_sentinel_skipped_updates",
                      "cumulative staged updates zeroed by the in-jit "
                      "sentinel (anomaly skips)").set(
                float(self._health_host.get("skipped", 0.0)))
            reg.gauge("veles_sentinel_policy_skips",
                      "cumulative policy-skipped updates (replay skip "
                      "list / force_skip_steps)").set(
                float(self._health_host.get("policy_skips", 0.0)))
        # the live-array census is the one per-sweep cost that scales
        # with model size (O(arrays x shards) host walk): pay it only
        # when something consumes it — an open --metrics-out sink or a
        # started web-status /metrics scrape surface.  The MFU check
        # stays unconditional: its pricing is computed once and cached,
        # the per-sweep cost is a handful of float ops, and the
        # shortfall warning is a log surface that must work bare.
        if telemetry.collection_enabled():
            if self._mem_watcher is None:
                from veles_tpu.benchmark import Watcher
                self._mem_watcher = Watcher()
            self._mem_watcher.record(reg)
        if cls in self.train_only_classes:
            telemetry.mfu.check_step(self, steps, wall, registry=reg)

    def _zero_stats(self):
        return {"loss": jnp.zeros(()), "n_errors": jnp.zeros(()),
                "count": jnp.zeros(())}

    def reset_epoch_stats(self):
        self.class_stats = [self._zero_stats() for _ in range(3)]

    def read_class_stats(self, cls):
        """Device→host sync — called once per class sweep by Decision.
        The sentinel's health accumulator rides the SAME device_get as
        the class stats: the probe results cost zero extra sync points
        (the PR 3 telemetry budget the numerics-chaos gate pins)."""
        self.flush()
        st, health = jax.device_get((self.class_stats[cls],
                                     self.health))
        self._health_host = health
        stats = {"loss": float(st["loss"]),
                 "n_errors": int(st["n_errors"]),
                 "count": int(st["count"])}
        if self.sentinel is not None and health is not None:
            # strike accounting is CONTROL, not telemetry — it runs
            # outside the fail-soft guard (the ladder acts at the
            # sentinel unit's own slot in the cycle, never mid-read)
            self.sentinel.observe_sweep(cls, stats, health)
        # the sweep's wall clock closes HERE, after the device_get that
        # drains every async dispatch — the only honest step-time sample
        # the staged hot loop offers without adding sync points
        self._emit_step_telemetry(cls, stats)
        return stats

    # ---------------------------------------------------------- inspection
    def lint_staging_spec(self):
        """Staging spec for the jit auditor (veles_tpu.analysis.staging):
        the jitted eval step traced over abstract ShapeDtypeStruct inputs
        — no device compute, no allocation.  None before initialize()
        has built the steps (the graph linter still runs construction-
        time), and None under a mesh (the pjit sharding constraints
        don't trace over bare abstract values)."""
        step = getattr(self, "_eval_step", None)
        if step is None or self.mesh_config is not None \
                or self.loader.carries_data:
            return None

        def abstract(tree):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(jnp.shape(a),
                                               jnp.result_type(a)), tree)

        mb = self.loader.minibatch_size
        args = (abstract(self.params), abstract(self.class_stats[0]),
                abstract(self._data_dev), abstract(self._labels_dev),
                abstract(self._targets_dev),
                jax.ShapeDtypeStruct((mb,), jnp.int32),
                jax.ShapeDtypeStruct((mb,), jnp.float32))
        # the accumulator (argnum 1) is the step's carry: its output
        # avals must match or every scheduler iteration recompiles
        return {"fn": step, "args": args, "carry_argnums": (1,),
                "name": "%s.eval_step" % self.name}

    def lint_numerics_spec(self):
        """Numerics/determinism spec for the VN4xx/VR5xx auditor
        (veles_tpu.analysis.numerics_audit): the REAL jitted train step
        — the one with the grad, the loss reductions, and the per-step
        fold_in — over abstract ``ShapeDtypeStruct`` mirrors.  Under a
        mesh it reuses the sharding spec's mirrors (make_jaxpr accepts
        them unchanged); single-device it mirrors the step's true
        signature.  None before initialize() or for data-carrying
        loaders (their minibatch never lives in the staged state)."""
        step = getattr(self, "_train_step", None)
        if step is None or self.loader.carries_data:
            return None
        loss_fn, _ = losses.get_loss(self.loss)
        suppress = tuple(getattr(loss_fn, "numerics_suppress", ()))
        # the staged step fn is framework code — the user's host calls
        # (VR502's numpy.random scan) live in its callees: the loss
        # evaluator and any layer defined outside veles_tpu
        host_scan = [loss_fn]
        for layer in self.layers:
            mod = type(layer).__module__ or ""
            if not mod.startswith("veles_tpu"):
                host_scan.append(layer.apply)

        #: sentinel-health leaves that are nonnegative by construction
        #: (counters, the EWM variance, the +inf-seeded first-bad-step)
        _health_nonneg = frozenset(
            ("ewma_var", "obs", "first_bad_step", "anomalies",
             "skipped", "policy_skips", "nonfinite_loss",
             "nonfinite_grad", "update_explosion", "loss_spike"))

        def step_leaf_flags(args):
            # vouch for the counters the auditor cannot see: the step
            # arg (argnum 9) increments BEFORE dispatch (_run_step), so
            # it is >= 1 inside the step, the optimizer's step/micro
            # slots (velocity tree) only ever count up from 0 — that is
            # what proves adam's 1 - beta**t bias correction positive —
            # and the sentinel health accumulator (argnum 3) carries
            # nonnegative counters/variance
            flags, idx = {}, 0
            for ai, a in enumerate(args):
                for path, _leaf in \
                        jax.tree_util.tree_flatten_with_path(a)[0]:
                    key = (getattr(path[-1], "key", None)
                           if path else None)
                    if ai == 9:
                        flags[idx] = ("pos", "nonneg")
                    elif key in ("step", "micro"):
                        flags[idx] = ("nonneg",)
                    elif ai == 3 and key in _health_nonneg:
                        flags[idx] = ("nonneg",)
                    idx += 1
            return flags

        if self.mesh_config is not None:
            spec = self.lint_sharding_spec()
            if spec is None:
                return None
            return {"fn": spec["fn"], "args": spec["args"],
                    "suppress": suppress, "host_scan": tuple(host_scan),
                    "input_flags": step_leaf_flags(spec["args"]),
                    "name": "%s.train_step" % self.name}

        def abstract(tree):
            return jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(jnp.shape(a),
                                               jnp.result_type(a)), tree)

        mb = self.loader.minibatch_size
        args = (abstract(self.params), abstract(self.velocity),
                abstract(self.class_stats[0]), abstract(self.health),
                abstract(self._data_dev), abstract(self._labels_dev),
                abstract(self._targets_dev),
                jax.ShapeDtypeStruct((mb,), jnp.int32),
                jax.ShapeDtypeStruct((mb,), jnp.float32),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct(self._skip_steps.shape, jnp.int32))
        return {"fn": step, "args": args, "suppress": suppress,
                "host_scan": tuple(host_scan),
                "input_flags": step_leaf_flags(args),
                "name": "%s.train_step" % self.name}

    def lint_sharding_spec(self):
        """Sharding/memory spec for the VS2xx/VM3xx auditor
        (veles_tpu.analysis.sharding_audit): the REAL jitted train step
        plus abstract ``ShapeDtypeStruct`` mirrors of its arguments,
        each carrying the argument's live NamedSharding — the auditor
        lowers and compiles for the mesh without touching data or
        dispatching anything.  None before initialize(), without a mesh
        (nothing to audit), or for data-carrying loaders (the minibatch
        arrives from the host each step, so there is no HBM-resident
        step state beyond the params the staging audit already
        covers)."""
        step = getattr(self, "_train_step", None)
        if step is None or self.mesh_config is None \
                or self.loader.carries_data:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        mc = self.mesh_config
        repl = NamedSharding(mc.mesh, P())
        batch_sh = (NamedSharding(mc.mesh, P(mc.data_axis))
                    if mc.data_axis in mc.mesh.shape else repl)

        memo = {}   # one mirror per PHYSICAL buffer: the autoencoder's
        # targets ARE its data, and VM300 must not count that twice

        def abstract(x):
            if id(x) in memo:
                return memo[id(x)]
            sh = getattr(x, "sharding", None)
            if not isinstance(sh, NamedSharding):
                sh = repl   # uncommitted single-device array: the step
                # receives it replicated over the mesh at dispatch time
            memo[id(x)] = jax.ShapeDtypeStruct(
                tuple(jnp.shape(x)), jnp.result_type(x), sharding=sh)
            return memo[id(x)]

        tree_abs = lambda t: jax.tree_util.tree_map(abstract, t)  # noqa: E731
        mb = self.loader.minibatch_size
        args = (tree_abs(self.params), tree_abs(self.velocity),
                tree_abs(self.class_stats[0]), tree_abs(self.health),
                tree_abs(self._data_dev), tree_abs(self._labels_dev),
                tree_abs(self._targets_dev),
                jax.ShapeDtypeStruct((mb,), jnp.int32,
                                     sharding=batch_sh),
                jax.ShapeDtypeStruct((mb,), jnp.float32,
                                     sharding=batch_sh),
                jax.ShapeDtypeStruct((), jnp.int32, sharding=repl),
                jax.ShapeDtypeStruct((), jnp.float32, sharding=repl),
                jax.ShapeDtypeStruct(self._skip_steps.shape, jnp.int32,
                                     sharding=repl))
        # bytes one minibatch moves per step: mb gathered samples (+
        # labels + the f32 valid/int32 index vectors)
        sample_bytes = int(np.prod(self._data_dev.shape[1:])
                           * self._data_dev.dtype.itemsize)
        mb_bytes = mb * (sample_bytes + self._labels_dev.dtype.itemsize
                         + 8)
        return {"fn": step, "args": args,
                "mesh_config": mc,
                "donate_argnums": (0, 1, 2, 3),
                "carry_argnums": (0, 1, 2, 3),
                "params_argnums": (0,), "opt_argnums": (1,),
                "minibatch_bytes": int(mb_bytes),
                "name": "%s.train_step" % self.name}

    def host_params(self):
        """Full parameter pytree on the host.  Multi-host safe: tensors
        sharded across processes (non-addressable shards) are gathered
        with a process_allgather collective — EVERY process must call
        this together (the snapshotter does; ref only-master-writes,
        snapshotter.py:160)."""
        self.flush()
        return self.host_tree(self.params)

    def host_velocity(self):
        self.flush()
        return self.host_tree(self.velocity)

    @staticmethod
    def host_tree(tree):
        def get(x):
            if isinstance(x, jax.Array) and not x.is_fully_addressable \
                    and not x.is_fully_replicated:
                from jax.experimental import multihost_utils
                return np.asarray(
                    multihost_utils.process_allgather(x, tiled=True))
            return np.asarray(jax.device_get(x))
        return jax.tree_util.tree_map(get, tree)

    def load_params(self, host_params, host_velocity=None):
        # queued steps would otherwise apply to the restored params
        self._pending, self._pending_cls = [], None
        self.params = jax.tree_util.tree_map(jnp.asarray, host_params)
        if host_velocity is not None:
            self.velocity = jax.tree_util.tree_map(jnp.asarray,
                                                   host_velocity)
            # reconcile accumulation state across config changes: a
            # snapshot from a grad_accum=1 run resumes into an
            # accumulating one with fresh (zero) accumulators, and vice
            # versa the stale accumulator is dropped — not a KeyError
            # mid-trace
            if self.grad_accum > 1 and "gacc" not in self.velocity:
                self.velocity["gacc"] = jax.tree_util.tree_map(
                    jnp.zeros_like, self.params)
                self.velocity["micro"] = jnp.zeros((), jnp.int32)
            elif self.grad_accum == 1:
                self.velocity.pop("gacc", None)
                self.velocity.pop("micro", None)
            # abstract (no allocation): only the slot SHAPES matter
            spec = jax.eval_shape(
                lambda: optimizer.init_state(self.params,
                                             hypers=self._hypers))

            def _shapes(t):
                return jax.tree_util.tree_map(lambda a: a.shape, t)

            if any(_shapes(self.velocity.get(s)) != _shapes(spec[s])
                   for s in ("slot1", "slot2")):
                # solver family changed since the snapshot (e.g.
                # adam -> adafactor): slot shapes are incompatible —
                # restart the moments (and the update count their bias
                # correction depends on) rather than crash mid-trace
                self.warning(
                    "restored optimizer state does not match the "
                    "configured solver's slot shapes — reinitializing "
                    "moments and step count")
                fresh = optimizer.init_state(self.params,
                                             hypers=self._hypers)
                for k in ("slot1", "slot2", "step"):
                    self.velocity[k] = fresh[k]
            if self.ema_decay and "ema" not in self.velocity:
                # fresh f32 average seeded from the restored params
                # (jnp.array copies — no aliasing with donated params)
                self.velocity["ema"] = jax.tree_util.tree_map(
                    lambda p: jnp.array(p, jnp.float32), self.params)
            elif not self.ema_decay:
                self.velocity.pop("ema", None)
        if self.mesh_config is not None:
            # re-establish the parallel placement initialize() set up
            from veles_tpu.parallel import sharding
            overrides = getattr(self, "_param_overrides", None)
            self.params = sharding.shard_params(self.params,
                                                self.mesh_config, overrides)
            self.velocity = sharding.shard_params(self.velocity,
                                                  self.mesh_config,
                                                  overrides)

    @property
    def ema_params(self):
        """The Polyak/EMA weight average (gd_defaults["ema_decay"]), or
        None when EMA tracking is off."""
        return self.velocity.get("ema")

    def serve_params(self, use_ema=False):
        """The params a serve/export path should read: the live tree, or
        the EMA average when asked (a loud error beats silently serving
        un-averaged weights the user thought were smoothed)."""
        if not use_ema:
            return self.params
        ema = self.ema_params
        if ema is None:
            raise ValueError(
                "use_ema requested but EMA tracking is off — train with "
                "gd_defaults={'ema_decay': 0.999}")
        return ema

    def forward_fn(self):
        """Jitted serve-time forward (softmax applied for classifiers)."""
        def fwd(params, x):
            out = self._forward(params, x, False, jax.random.key(0))
            if losses.get_loss(self.loss)[1] == "class":
                # every classification loss serves probabilities (the
                # ensemble vote and REST clients rely on it)
                out = jax.nn.softmax(out.astype(jnp.float32), axis=-1)
            return out
        return jax.jit(fwd)
