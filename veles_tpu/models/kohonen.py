"""Kohonen self-organizing map (ref: znicz.kohonen per BASELINE.json config
'Kohonen self-organizing map (znicz.kohonen kernels → Pallas)'; algorithm
docs manualrst_veles_algorithms.rst:72-84).

TPU formulation: the winner search is a matmul — ``argmin ||x-w||² =
argmin (|w|² - 2 x·wᵀ)`` — so it rides the MXU; the neighborhood update is
a ``lax.scan`` over the minibatch (SOM updates are inherently sequential
per sample) with a Gaussian neighborhood over the 2-D neuron grid whose
radius decays with the epoch.  The whole minibatch update is ONE jitted
step; no per-sample host dispatch."""

import jax
import jax.numpy as jnp
import numpy as np

from veles_tpu import prng
from veles_tpu.loader.base import TRAIN
from veles_tpu.loader.fullbatch import FullBatchLoader
from veles_tpu.mutable import Bool
from veles_tpu.plumbing import Repeater
from veles_tpu.units import Unit
from veles_tpu.workflow import Workflow


def grid_coords(sx, sy):
    """Neuron grid coordinates [n, 2] (hexagonal offset like classic SOM
    visualizations is cosmetic; Euclidean rectangular grid here)."""
    yy, xx = np.mgrid[0:sy, 0:sx]
    return jnp.asarray(
        np.stack([xx.ravel(), yy.ravel()], axis=1).astype(np.float32))


def winners(weights, x):
    """Batch winner search: [B] argmin indices.  |w|²-2x·wᵀ via MXU."""
    w_sq = jnp.sum(weights * weights, axis=1)
    scores = w_sq[None, :] - 2.0 * jnp.dot(
        x, weights.T, preferred_element_type=jnp.float32)
    return jnp.argmin(scores, axis=1)


def som_minibatch_step(weights, coords, x, valid, lr, radius):
    """Sequential SOM updates over one minibatch, staged as lax.scan
    (exact online-SOM semantics: each sample sees the previous updates)."""

    def body(w, inp):
        xi, vi = inp
        w_sq = jnp.sum(w * w, axis=1)
        win = jnp.argmin(w_sq - 2.0 * jnp.dot(w, xi))
        d2 = jnp.sum((coords - coords[win]) ** 2, axis=1)
        h = jnp.exp(-d2 / (2.0 * radius * radius))
        w = w + (vi * lr) * h[:, None] * (xi[None, :] - w)
        return w, win

    return jax.lax.scan(body, weights, (x, valid))


def som_batch_step(weights, coords, x, valid, lr, radius):
    """Minibatch (batch-SOM) update: all winners in one MXU matmul, then
    one neighborhood-weighted aggregation — no per-sample sequencing.

    Kohonen's batch algorithm smoothed by ``lr``:
        h[i,j] = exp(-|c(win_i)-c_j|^2 / 2r^2) * valid_i
        w_j   += lr * (sum_i h[i,j] x_i - sum_i h[i,j] w_j) / max(sum_i h, eps)
    i.e. each neuron moves toward the h-weighted mean of the samples it
    (or its grid neighbors) won.  Converges to the same map as the online
    rule for the usual decaying (lr, radius) schedules, and is ~2 matmuls
    per minibatch instead of a B-long scan (ref kernels: znicz.kohonen
    OpenCL per-sample update; BASELINE config 4 'kernels → Pallas')."""
    win = winners(weights, x)
    # [N, N] pairwise grid distances (tiny, loop-invariant), then one row
    # gather — avoids materializing a [B, N, 2] intermediate
    d2_all = jnp.sum((coords[:, None, :] - coords[None, :, :]) ** 2,
                     axis=-1)
    h = jnp.exp(-d2_all[win] / (2.0 * radius * radius)) * valid[:, None]
    num = jnp.dot(h.T, x, preferred_element_type=jnp.float32)   # [N, F]
    den = jnp.sum(h, axis=0)                                    # [N]
    delta = num - den[:, None] * weights
    return weights + lr * delta / jnp.maximum(den, 1e-6)[:, None], win


def som_sweep(weights, coords, xs, valids, lr, radius):
    """k minibatch batch-SOM steps fused into ONE dispatch (lax.scan over
    a [k, B, F] stack) — amortizes host→device dispatch latency exactly
    like StagedTrainer's steps_per_dispatch."""

    def body(w, inp):
        x, v = inp
        w, _ = som_batch_step(w, coords, x, v, lr, radius)
        return w, None

    return jax.lax.scan(body, weights, (xs, valids))[0]


def som_sweep_indexed(weights, coords, data, idxs, valids, lr, radius):
    """Fused k-step sweep gathering each minibatch from the HBM-resident
    dataset by a [k, B] index matrix — the KohonenTrainer hot path under
    steps_per_dispatch > 1 (one host→device round trip per k steps)."""

    def body(w, inp):
        idx, v = inp
        from veles_tpu.loader.fullbatch import FullBatchLoader
        x = FullBatchLoader.gather(data, idx)      # pad-index safe
        w, _ = som_batch_step(w, coords, x.reshape(idx.shape[0], -1),
                              v, lr, radius)
        return w, None

    return jax.lax.scan(body, weights, (idxs, valids))[0]


def benchmark_som(n_samples=1024, n_features=64, sx=8, sy=8,
                  minibatch_size=128, steps=20, seed=0):
    """Timing comparison of the per-sample scan (online) vs batched SOM
    step vs the fused multi-step sweep on synthetic data.  Returns ms/step
    for each and the speedups — used by bench.py's kohonen phase
    (VERDICT r1 weak #3: ≥10× the scan path at equal quantization
    error)."""
    import time

    rs = np.random.RandomState(seed)
    x_all = jnp.asarray(rs.rand(n_samples, n_features).astype(np.float32))
    w0 = jnp.asarray(rs.rand(sx * sy, n_features).astype(np.float32))
    coords = grid_coords(sx, sy)
    valid = jnp.ones((minibatch_size,), jnp.float32)
    scan_step = jax.jit(som_minibatch_step)
    batch_step = jax.jit(som_batch_step)
    batches = [x_all[i:i + minibatch_size]
               for i in range(0, n_samples - minibatch_size + 1,
                              minibatch_size)]

    def run(step_fn):
        w = w0
        w, _ = step_fn(w, coords, batches[0], valid, 0.5, 3.0)  # compile
        jax.block_until_ready(w)
        w = w0
        t0 = time.perf_counter()
        for i in range(steps):
            w, _ = step_fn(w, coords, batches[i % len(batches)], valid,
                           0.5, 3.0)
        jax.block_until_ready(w)
        return (time.perf_counter() - t0) / steps * 1e3, w

    scan_ms, _ = run(scan_step)
    batch_ms, w_batch = run(batch_step)

    # fused sweep: all `steps` minibatches in one dispatch
    xs = jnp.stack([batches[i % len(batches)] for i in range(steps)])
    vs = jnp.broadcast_to(valid, (steps,) + valid.shape)
    sweep = jax.jit(som_sweep)
    jax.block_until_ready(sweep(w0, coords, xs, vs, 0.5, 3.0))  # compile
    t0 = time.perf_counter()
    w_sweep = sweep(w0, coords, xs, vs, 0.5, 3.0)
    jax.block_until_ready(w_sweep)
    sweep_ms = (time.perf_counter() - t0) / steps * 1e3

    qe = float(jnp.mean(jnp.linalg.norm(
        x_all - w_batch[winners(w_batch, x_all)], axis=1)))
    qe_sweep = float(jnp.mean(jnp.linalg.norm(
        x_all - w_sweep[winners(w_sweep, x_all)], axis=1)))
    return {"ms_per_step": batch_ms, "scan_ms_per_step": scan_ms,
            "sweep_ms_per_step": sweep_ms,
            "speedup": scan_ms / batch_ms if batch_ms else 0.0,
            "sweep_speedup": scan_ms / sweep_ms if sweep_ms else 0.0,
            "impl": "batch", "quantization_error": qe,
            "sweep_quantization_error": qe_sweep}


class KohonenTrainer(Unit):
    """SOM trainer unit: owns the weight grid and the jitted minibatch step
    (plays the role of the reference's KohonenTrainer + its OpenCL kernels).

    Epoch schedule: learning rate and neighborhood radius decay
    exponentially from their initial values to ``final`` fractions over
    ``n_epochs``."""

    def __init__(self, workflow, sx=8, sy=8, n_epochs=20,
                 learning_rate=0.5, final_learning_rate=0.01,
                 radius=None, final_radius=1.0, algorithm="batch",
                 steps_per_dispatch=None, **kwargs):
        super(KohonenTrainer, self).__init__(workflow, **kwargs)
        if algorithm not in ("batch", "online"):
            raise ValueError("algorithm must be 'batch' or 'online'")
        #: 'batch' = minibatch batch-SOM (MXU matmuls, the TPU-native
        #: formulation); 'online' = per-sample lax.scan (exact reference
        #: online-SOM semantics, much slower)
        self.algorithm = algorithm
        if steps_per_dispatch is None:
            from veles_tpu.config import root
            steps_per_dispatch = root.common.engine.get(
                "steps_per_dispatch", 1)
        #: fuse k minibatch updates into one dispatch (batch algorithm
        #: only — the online scan is already one dispatch per minibatch
        #: and its whole point is exact per-sample sequencing)
        self.steps_per_dispatch = int(steps_per_dispatch)
        if self.steps_per_dispatch < 1:
            raise ValueError("steps_per_dispatch must be >= 1")
        self._pending = []          # queued (idx, valid) host rows
        self._pending_sched = None  # (lr, radius) the queue was built at
        self.sx, self.sy = sx, sy
        self.n_neurons = sx * sy
        self.n_epochs = n_epochs
        self.lr0 = learning_rate
        self.lr1 = final_learning_rate
        self.r0 = radius if radius is not None else max(sx, sy) / 2.0
        self.r1 = final_radius
        self.demand("loader")
        self.weights = None
        self.view_group = "TRAINER"

    def initialize(self, **kwargs):
        loader = self.loader
        if loader.carries_data:
            raise ValueError("KohonenTrainer needs an index loader with an "
                             "HBM-resident dataset")
        n_features = int(np.prod(loader.data.shape[1:]))
        rng = prng.get("kohonen-weights")
        self.weights = jnp.asarray(
            rng.fill_uniform((self.n_neurons, n_features), 0.5))
        self._coords = grid_coords(self.sx, self.sy)
        self._step = jax.jit(som_batch_step if self.algorithm == "batch"
                             else som_minibatch_step)
        self._sweep = (jax.jit(som_sweep_indexed)
                       if (self.algorithm == "batch"
                           and self.steps_per_dispatch > 1) else None)
        self._data_flat = None
        self._winners = jax.jit(winners)

    def _schedule(self):
        t = min(self.loader.epoch_number / max(self.n_epochs - 1, 1), 1.0)
        lr = self.lr0 * (self.lr1 / self.lr0) ** t
        radius = self.r0 * (self.r1 / self.r0) ** t
        return lr, radius

    def run(self):
        loader = self.loader
        if loader.minibatch_class != TRAIN:
            return
        sched = self._schedule()
        if self._sweep is not None:
            # queued fused dispatch; the schedule is constant within an
            # epoch, so a mid-queue change (new epoch) forces a flush
            if self._pending and self._pending_sched != sched:
                self.flush()
            self._pending_sched = sched
            self._pending.append((
                np.array(loader.minibatch_indices),
                np.array(loader.minibatch_valid, np.float32)))
            if len(self._pending) >= self.steps_per_dispatch:
                self.flush()
            return
        x = FullBatchLoader.gather(
            loader.data, jnp.asarray(loader.minibatch_indices))
        x = x.reshape(x.shape[0], -1)
        valid = jnp.asarray(loader.minibatch_valid)
        lr, radius = sched
        self.weights, _ = self._step(self.weights, self._coords, x, valid,
                                     lr, radius)

    def flush(self):
        """Dispatch queued minibatches (steps_per_dispatch > 1): full and
        partial groups both ride the indexed sweep — scan length varies
        only on ragged tails, so at most two compiled variants exist."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        lr, radius = self._pending_sched
        self._pending_sched = None
        if self._data_flat is None:
            self._data_flat = jnp.asarray(self.loader.data).reshape(
                self.loader.data.shape[0], -1)
        k = self.steps_per_dispatch
        for i in range(0, len(pending), k):
            group = pending[i:i + k]
            idxs = jnp.asarray(np.stack([g[0] for g in group]))
            valids = jnp.asarray(np.stack([g[1] for g in group]))
            self.weights = self._sweep(self.weights, self._coords,
                                       self._data_flat, idxs, valids,
                                       lr, radius)

    # -- inspection / serving -------------------------------------------------
    def assign(self, x):
        """Winner neuron index for each sample (KohonenForward)."""
        self.flush()
        return self._winners(self.weights, jnp.asarray(
            x.reshape(len(x), -1)))

    def quantization_error(self, x):
        self.flush()
        x = jnp.asarray(x.reshape(len(x), -1))
        win = self._winners(self.weights, x)
        return float(jnp.mean(jnp.linalg.norm(x - self.weights[win],
                                              axis=1)))

    def host_weights(self):
        self.flush()
        return np.asarray(self.weights).reshape(self.sy, self.sx, -1)

    def get_metric_values(self):
        return {"som_grid": (self.sx, self.sy)}


class SOMPlotter(object):
    """SOM visualizations (ref Kohonen plotters in the Znicz docs): the
    hit histogram (winners per neuron) and the U-matrix (mean distance
    of each neuron's weights to its grid neighbors — cluster boundaries
    show as ridges).  Implemented as a payload/render pair compatible
    with services.plotting.PlotterBase."""

    @staticmethod
    def payload(trainer, x):
        win = np.asarray(trainer.assign(np.asarray(x)))
        hits = np.bincount(win, minlength=trainer.n_neurons).reshape(
            trainer.sy, trainer.sx)
        w = np.asarray(trainer.weights).reshape(trainer.sy, trainer.sx, -1)
        um = np.zeros((trainer.sy, trainer.sx))
        counts = np.zeros((trainer.sy, trainer.sx))
        for dy, dx in ((0, 1), (1, 0), (0, -1), (-1, 0)):
            shifted = np.roll(w, (dy, dx), axis=(0, 1))
            d = np.linalg.norm(w - shifted, axis=-1)
            valid = np.ones_like(d)
            # roll wraps around; drop the wrapped edge contribution
            if dy == 1:
                valid[0, :] = 0
            elif dy == -1:
                valid[-1, :] = 0
            if dx == 1:
                valid[:, 0] = 0
            elif dx == -1:
                valid[:, -1] = 0
            um += d * valid
            counts += valid
        # true mean over each neuron's REAL neighbors (edges have 3,
        # corners 2 — dividing by 4 would fade border ridges)
        um /= np.maximum(counts, 1)
        return {"kind": "som", "hits": hits.tolist(),
                "umatrix": um.tolist()}

    @staticmethod
    def render(payload, path):
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        fig, (a1, a2) = plt.subplots(1, 2, figsize=(8, 4))
        im1 = a1.imshow(np.asarray(payload["hits"]), cmap="viridis")
        a1.set_title("hits")
        fig.colorbar(im1, ax=a1, shrink=0.8)
        im2 = a2.imshow(np.asarray(payload["umatrix"]), cmap="bone")
        a2.set_title("U-matrix")
        fig.colorbar(im2, ax=a2, shrink=0.8)
        fig.tight_layout()
        fig.savefig(path, dpi=80)
        plt.close(fig)

    @classmethod
    def plot(cls, trainer, x, path):
        payload = cls.payload(trainer, x)
        cls.render(payload, path)
        return payload


class KohonenDecision(Unit):
    """Fixed-epoch stop + quantization-error logging."""

    def __init__(self, workflow, n_epochs=20, **kwargs):
        super(KohonenDecision, self).__init__(workflow, **kwargs)
        self.n_epochs = n_epochs
        self.complete = Bool(False)
        self.demand("loader", "trainer")
        self.qe_history = []

    def run(self):
        loader = self.loader
        if not bool(loader.epoch_ended):
            return
        qe = self.trainer.quantization_error(loader.data)
        self.qe_history.append(qe)
        self.info("epoch %d: quantization error %.4f",
                  loader.epoch_number, qe)
        if loader.epoch_number >= self.n_epochs:
            self.complete <<= True

    def get_metric_values(self):
        return {"quantization_error":
                self.qe_history[-1] if self.qe_history else None}


class KohonenWorkflow(Workflow):
    """start → repeater → loader → trainer → decision → loop/end."""

    def __init__(self, workflow=None, loader=None, sx=8, sy=8, n_epochs=20,
                 **kwargs):
        super(KohonenWorkflow, self).__init__(workflow, **kwargs)
        self.repeater = Repeater(self)
        self.loader = loader
        if loader.workflow is not self:
            self.add_ref(loader)
            loader.workflow = self
        self.trainer = KohonenTrainer(self, sx=sx, sy=sy, n_epochs=n_epochs,
                                      **{k: v for k, v in kwargs.items()
                                         if k in ("learning_rate", "radius",
                                                  "final_learning_rate",
                                                  "final_radius",
                                                  "algorithm",
                                                  "steps_per_dispatch")})
        self.trainer.loader = loader
        self.decision = KohonenDecision(self, n_epochs=n_epochs)
        self.decision.loader = loader
        self.decision.trainer = self.trainer

        self.repeater.link_from(self.start_point)
        self.loader.link_from(self.repeater)
        self.trainer.link_from(self.loader)
        self.decision.link_from(self.trainer)
        self.repeater.link_from(self.decision)
        self.repeater.gate_block = self.decision.complete
        self.end_point.link_from(self.decision)
        self.end_point.gate_block = ~self.decision.complete
