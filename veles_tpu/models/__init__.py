"""Model layer (ref: the Znicz plugin, SURVEY.md §2.9).

``StandardWorkflow`` is the declarative builder (``layers=[{...}]``) that
stages forward + evaluator + GD into jitted train/eval steps; ``layers``
holds the layer-type registry; ``optimizer`` the GD update rules;
``decision`` the stop-condition unit."""

from veles_tpu.models.standard_workflow import StandardWorkflow
from veles_tpu.models.layers import LAYER_TYPES, make_layer

__all__ = ["StandardWorkflow", "LAYER_TYPES", "make_layer"]
