"""Avatar — mirrors attributes of another unit/workflow onto itself
(ref veles/avatar.py:22: used to bridge nested workflows, e.g. expose an
inner loader's minibatch stream to an outer workflow's units)."""

import copy

from veles_tpu.units import Unit


class Avatar(Unit):
    """Clones the listed attributes from ``source`` every run.

    ``deep=True`` copies values (safe mutation isolation, the reference's
    behavior for numpy arrays); the default forwards references, which is
    the right thing for immutable jax Arrays.
    """

    def __init__(self, workflow, source=None, attrs=(), deep=False, **kwargs):
        super(Avatar, self).__init__(workflow, **kwargs)
        self.source = source
        self.attrs = list(attrs)
        self.deep = deep

    def clone_attrs(self, *names):
        self.attrs.extend(names)
        return self

    def initialize(self, **kwargs):
        if self.source is None:
            raise ValueError("Avatar needs source=")
        self.run()   # make attrs visible to dependency-ordered init

    def run(self):
        for name in self.attrs:
            value = getattr(self.source, name)
            setattr(self, name, copy.deepcopy(value) if self.deep else value)
