"""Reproducible randomness as named key streams (ref: veles/prng/).

The reference enforces bit-reproducibility by globally intercepting
``numpy.random`` and snapshotting generator state around every draw
(random_generator.py:49-61).  The TPU-idiomatic equivalent is *counter-based
key derivation*: each named stream owns a master ``jax.random`` key; every
draw folds in a monotonically increasing counter, so a stream's entire future
is determined by ``(seed, counter)`` — two words that pickle into snapshots
and restore mid-epoch (ref Pickleable RandomGenerator, SURVEY §5 checkpoint
notes).  Host-side shuffling gets a numpy ``Generator`` seeded from the same
words.

Usage::

    g = prng.get("loader")        # global registry, like ref prng.get(key)
    k = g.key()                   # fresh jax key, advances the counter
    perm = g.numpy().permutation(n)  # host-side draw, advances the counter
"""

import hashlib
import logging

import jax
import numpy as np

from veles_tpu.config import root

#: derived seed -> stream name, across every stream that auto-derived its
#: seed.  The sha1 offset is 31 bits after the sign mask, so two names CAN
#: collide (and genuinely do at the birthday rate, ~1% at 10k streams) —
#: a collision means two "independent" streams replay each other draw for
#: draw.  Detected here at derivation time and rehashed away
#: deterministically; explicit seeds are the user's to collide (the
#: VR501 numerics-audit rule reports those, analysis/numerics_audit.py).
_derived_seeds = {}


def _derive_seed(name, base):
    """Per-name seed from the shared base: sha1 offset, then
    deterministic rehash past any seed another name already derived.
    Deterministic in (name, base, set of earlier derivations) — the
    registry is populated in program order, which reproducible runs
    replay exactly."""
    salt = b""
    while True:
        h = int(hashlib.sha1(name.encode() + salt).hexdigest()[:8], 16)
        seed = (int(base) ^ h) & 0x7FFFFFFF
        owner = _derived_seeds.get(seed)
        if owner is None or owner == name:
            _derived_seeds[seed] = name
            return seed
        logging.getLogger("prng").warning(
            "prng stream %r: derived seed %d collides with stream %r — "
            "rehashing deterministically", name, seed, owner)
        salt += b"#"


class RandomGenerator(object):
    """One named reproducible stream (ref prng/random_generator.py:64)."""

    def __init__(self, name, seed=None):
        self.name = name
        self.seed(seed)

    def seed(self, seed=None):
        if seed is None:
            base = root.common.get("random_seed", 1234)
            # stable per-name offset so streams differ but derive from
            # one seed; collisions after the 31-bit mask rehash away
            seed = _derive_seed(self.name, base)
        self._seed = int(seed)
        self._counter = 0

    # -- state (pickled into snapshots) --------------------------------------
    @property
    def state(self):
        return {"seed": self._seed, "counter": self._counter}

    @state.setter
    def state(self, value):
        self._seed = int(value["seed"])
        self._counter = int(value["counter"])

    def __getstate__(self):
        return {"name": self.name, "state": self.state}

    def __setstate__(self, d):
        self.name = d["name"]
        self.state = d["state"]

    # -- draws ----------------------------------------------------------------
    def key(self):
        """Next jax PRNG key; deterministic in (seed, counter)."""
        self._counter += 1
        return jax.random.fold_in(jax.random.key(self._seed), self._counter)

    def numpy(self):
        """A numpy Generator for the next host-side draw.  Each call returns
        a *fresh* generator keyed by the advanced counter, so host draws are
        replayable from (seed, counter) exactly like device draws."""
        self._counter += 1
        return np.random.default_rng((self._seed, self._counter))

    def permutation(self, n):
        return self.numpy().permutation(n)

    def randint(self, low, high, size=None):
        return self.numpy().integers(low, high, size=size)

    def normal(self, loc=0.0, scale=1.0, size=None):
        return self.numpy().normal(loc, scale, size)

    def uniform(self, low=0.0, high=1.0, size=None):
        return self.numpy().uniform(low, high, size)

    def fill_normal(self, shape, scale, dtype=np.float32):
        return self.numpy().normal(0.0, scale, shape).astype(dtype)

    def fill_uniform(self, shape, amp, dtype=np.float32):
        return self.numpy().uniform(-amp, amp, shape).astype(dtype)


_streams = {}


def get(name="default"):
    """Global stream registry (ref prng/random_generator.py ``get(key)``)."""
    g = _streams.get(name)
    if g is None:
        g = _streams[name] = RandomGenerator(name)
    return g


def seed_all(seed):
    """Reset the base seed and re-seed every existing stream — the CLI
    ``--random-seed`` entry point (ref __main__.py:483 _seed_random).
    The derivation registry resets first so the rehash outcome is a
    pure function of (base, stream creation order) — identical to a
    fresh process that created the same streams."""
    root.common.random_seed = int(seed)
    _derived_seeds.clear()
    for g in _streams.values():
        g.seed()


def seed_collisions():
    """Streams in the registry whose *effective* seeds collide, as
    ``[(names, seed)]`` — the VR501 determinism rule's input
    (analysis/numerics_audit.py).  Auto-derived seeds are rehashed
    apart at creation, so anything here came from explicit seeding:
    two streams with equal (seed, counter) words replay each other."""
    by_seed = {}
    for name, g in _streams.items():
        by_seed.setdefault(g._seed, []).append(name)
    return [(tuple(sorted(names)), seed)
            for seed, names in sorted(by_seed.items())
            if len(names) > 1]


def states():
    """Snapshot all stream states (for the Snapshotter)."""
    return {name: g.state for name, g in _streams.items()}


def restore_states(saved):
    for name, st in saved.items():
        get(name).state = st
