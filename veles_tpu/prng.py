"""Reproducible randomness as named key streams (ref: veles/prng/).

The reference enforces bit-reproducibility by globally intercepting
``numpy.random`` and snapshotting generator state around every draw
(random_generator.py:49-61).  The TPU-idiomatic equivalent is *counter-based
key derivation*: each named stream owns a master ``jax.random`` key; every
draw folds in a monotonically increasing counter, so a stream's entire future
is determined by ``(seed, counter)`` — two words that pickle into snapshots
and restore mid-epoch (ref Pickleable RandomGenerator, SURVEY §5 checkpoint
notes).  Host-side shuffling gets a numpy ``Generator`` seeded from the same
words.

Usage::

    g = prng.get("loader")        # global registry, like ref prng.get(key)
    k = g.key()                   # fresh jax key, advances the counter
    perm = g.numpy().permutation(n)  # host-side draw, advances the counter
"""

import hashlib

import jax
import numpy as np

from veles_tpu.config import root


class RandomGenerator(object):
    """One named reproducible stream (ref prng/random_generator.py:64)."""

    def __init__(self, name, seed=None):
        self.name = name
        self.seed(seed)

    def seed(self, seed=None):
        if seed is None:
            base = root.common.get("random_seed", 1234)
            # stable per-name offset so streams differ but derive from one seed
            h = int(hashlib.sha1(self.name.encode()).hexdigest()[:8], 16)
            seed = (int(base) ^ h) & 0x7FFFFFFF
        self._seed = int(seed)
        self._counter = 0

    # -- state (pickled into snapshots) --------------------------------------
    @property
    def state(self):
        return {"seed": self._seed, "counter": self._counter}

    @state.setter
    def state(self, value):
        self._seed = int(value["seed"])
        self._counter = int(value["counter"])

    def __getstate__(self):
        return {"name": self.name, "state": self.state}

    def __setstate__(self, d):
        self.name = d["name"]
        self.state = d["state"]

    # -- draws ----------------------------------------------------------------
    def key(self):
        """Next jax PRNG key; deterministic in (seed, counter)."""
        self._counter += 1
        return jax.random.fold_in(jax.random.key(self._seed), self._counter)

    def numpy(self):
        """A numpy Generator for the next host-side draw.  Each call returns
        a *fresh* generator keyed by the advanced counter, so host draws are
        replayable from (seed, counter) exactly like device draws."""
        self._counter += 1
        return np.random.default_rng((self._seed, self._counter))

    def permutation(self, n):
        return self.numpy().permutation(n)

    def randint(self, low, high, size=None):
        return self.numpy().integers(low, high, size=size)

    def normal(self, loc=0.0, scale=1.0, size=None):
        return self.numpy().normal(loc, scale, size)

    def uniform(self, low=0.0, high=1.0, size=None):
        return self.numpy().uniform(low, high, size)

    def fill_normal(self, shape, scale, dtype=np.float32):
        return self.numpy().normal(0.0, scale, shape).astype(dtype)

    def fill_uniform(self, shape, amp, dtype=np.float32):
        return self.numpy().uniform(-amp, amp, shape).astype(dtype)


_streams = {}


def get(name="default"):
    """Global stream registry (ref prng/random_generator.py ``get(key)``)."""
    g = _streams.get(name)
    if g is None:
        g = _streams[name] = RandomGenerator(name)
    return g


def seed_all(seed):
    """Reset the base seed and re-seed every existing stream — the CLI
    ``--random-seed`` entry point (ref __main__.py:483 _seed_random)."""
    root.common.random_seed = int(seed)
    for g in _streams.values():
        g.seed()


def states():
    """Snapshot all stream states (for the Snapshotter)."""
    return {name: g.state for name, g in _streams.items()}


def restore_states(saved):
    for name, st in saved.items():
        get(name).state = st
