"""Predicted-vs-measured MFU / roofline check for the staged step.

TVM's central lesson (PAPERS.md) applied to the training loop: a cost
model is only trustworthy when fed measured runtimes.  PR 2's static
model (``tools/cost_model.py``) prices bench phases offline; this module
prices the *actual configured* staged step — the layers the trainer
built, the minibatch the loader feeds — and, at every train-class sweep,
compares the utilization the chip actually delivered against that
prediction.  A measured/predicted ratio below a configurable fraction
(``root.common.telemetry.mfu_warn_fraction``, default 0.5) raises a
warning metric: the "your step is leaving the roofline" tripwire a
production fleet scrapes.

FLOP counting follows the repo's analytic conventions
(:mod:`veles_tpu.ops.flops`: fwd+bwd = 3x fwd matmul FLOPs, no padding
in the numerator); the *time* prediction pads to the MXU grid and uses
the calibrated device constants from ``tools/cost_model.py`` when that
module is importable (repo checkouts), else the baked-in v5e defaults —
same numbers, so predictions agree either way."""

import math

#: v5e fallback constants — MUST mirror tools/cost_model.py (which is
#: preferred at runtime when importable; this copy only covers installed
#: packages without the repo's tools/ directory)
_FALLBACK = {
    "name": "tpu-v5e", "peak_flops": 197e12, "eff_mxu": 0.440,
    "hbm_bw": 819e9, "eff_bw": 0.8, "t_kernel": 4.3e-6,
    "h_step": 67e-6, "t_dispatch": 4.09e-3,
}


def device_model():
    """Calibrated device constants: ``tools.cost_model.device_constants()``
    when the repo's tools/ is importable, else the baked-in v5e table."""
    try:
        from tools.cost_model import device_constants
        return device_constants()
    except Exception:   # noqa: BLE001 — installed without tools/
        return dict(_FALLBACK)


def _pad(x, m=128):
    return int(math.ceil(x / m)) * m


def _tree_elems(tree):
    n = 0
    stack = [tree]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            stack.extend(node.values())
        elif node is not None:
            size = getattr(node, "size", None)
            if size is not None:
                n += int(size)
    return n


def _layer_matmuls(layer, batch):
    """[(m, k, n)] for the forward matmuls of one layer, or None when
    the layer has no recognized matmul shape."""
    if hasattr(layer, "n_in") and layer.output_shape:   # dense family
        n_out = 1
        for d in layer.output_shape:
            n_out *= int(d)
        return [(batch, int(layer.n_in), n_out)]
    if hasattr(layer, "kx") and hasattr(layer, "n_kernels") \
            and layer.output_shape and len(layer.output_shape) == 3:
        ho, wo, _ = layer.output_shape        # conv via im2col mapping
        k = int(layer.n_channels) * int(layer.kx) * int(layer.ky)
        return [(batch * int(ho) * int(wo), k, int(layer.n_kernels))]
    return None


def price_staged_step(trainer):
    """Roofline pricing of ONE train step of ``trainer``'s staged chain:
    analytic FLOPs (numerator), padded-MXU compute time, optimizer HBM
    traffic, kernel/dispatch/host floors — the per-workflow analogue of
    ``tools/cost_model.predict_mlp``."""
    dm = device_model()
    batch = int(trainer.loader.minibatch_size)
    flops_fwd = 0.0          # analytic (MFU numerator convention)
    padded_fwd = 0.0         # what the MXU actually grinds through
    param_elems = 0
    n_param_layers = 0
    for layer in trainer.layers:
        if getattr(layer, "has_params", False):
            n_param_layers += 1
            param_elems += _tree_elems(trainer.params.get(layer.name))
        mms = _layer_matmuls(layer, batch)
        if mms is None:
            if getattr(layer, "has_params", False):
                # unrecognized parameterized layer: dense-equivalent
                # floor — every param participates in one MAC per sample
                n = _tree_elems(trainer.params.get(layer.name))
                flops_fwd += 2.0 * batch * n
                padded_fwd += 2.0 * batch * n
            continue
        for m, k, n in mms:
            flops_fwd += 2.0 * m * k * n
            padded_fwd += 2.0 * _pad(m) * _pad(k) * _pad(n)
    flops_step = 3.0 * flops_fwd            # fwd + bwd = 3x fwd
    # optimizer traffic, f32 sgd-momentum floor: w rd/wr, m rd/wr,
    # grad rd = 5 passes (adam adds 2 more; a floor, not a ceiling)
    hbm_bytes = param_elems * 4 * 5
    t_compute = 3.0 * padded_fwd / (dm["peak_flops"] * dm["eff_mxu"])
    t_hbm = hbm_bytes / (dm["hbm_bw"] * dm["eff_bw"])
    # fused-kernel floor: ~7 kernels per parameterized layer (fwd 2 +
    # bwd 3 + update 2) + ~8 for loss/stats (cost_model.predict_mlp)
    kernels = 7 * n_param_layers + 8
    spd = max(int(getattr(trainer, "steps_per_dispatch", 1)), 1)
    predicted = (max(t_compute, t_hbm) + kernels * dm["t_kernel"]
                 + dm["h_step"] + dm["t_dispatch"] / spd)
    return {
        "device": dm["name"],
        "peak_flops": dm["peak_flops"],
        "flops_per_step": flops_step,
        "hbm_bytes_per_step": hbm_bytes,
        "param_elems": param_elems,
        "predicted_step_s": predicted,
        "predicted_mfu": flops_step / (predicted * dm["peak_flops"]),
    }


def check_step(trainer, steps, wall_s, registry=None):
    """Compare a finished train-class sweep (``steps`` staged steps in
    ``wall_s`` wall seconds) against :func:`price_staged_step`.  Updates
    the ``veles_mfu_*`` gauges, emits a ``kind="mfu"`` record carrying
    BOTH ``predicted`` and ``measured``, banks predicted & measured in
    the performance ledger (telemetry.ledger) with the step-anatomy
    decomposition attached, and fires the shortfall warning.  The
    one-shot warning routes through the sentinel's drift band: with
    ledger history, "shortfall" means the measured MFU fell outside
    its own MAD noise band on the worse side (noise-aware); only a
    history-less first run falls back to the bare
    ``mfu_warn_fraction`` compare."""
    if registry is None:
        from veles_tpu.telemetry import registry
    if not steps or wall_s <= 0.0:
        return None
    pricing = trainer.__dict__.get("_mfu_pricing_")
    if pricing is None:
        pricing = price_staged_step(trainer)
        trainer.__dict__["_mfu_pricing_"] = pricing
    measured_step_s = wall_s / steps
    measured_mfu = (pricing["flops_per_step"]
                    / (measured_step_s * pricing["peak_flops"]))
    predicted_mfu = pricing["predicted_mfu"]
    ratio = measured_mfu / predicted_mfu if predicted_mfu else 0.0
    from veles_tpu.config import root
    frac = float(root.common.telemetry.get("mfu_warn_fraction", 0.5))
    # bank the sweep: measured MFU (with the anatomy components) and
    # the step time, each assessed against their ledger history — the
    # drift band below reads the returned verdict
    from veles_tpu.telemetry import anatomy, ledger
    comps = anatomy.step_components(trainer, steps, wall_s, registry)
    wl = str(getattr(trainer, "name", "trainer"))
    banked = ledger.record_value(
        "train_mfu", measured_mfu, workload=wl, unit="MFU",
        better="higher", source="mfu.check_step",
        predicted=predicted_mfu, ratio=ratio)
    ledger.record_value(
        "train_step_ms", measured_step_s * 1e3, workload=wl,
        unit="ms", source="mfu.check_step", components=comps,
        predicted=pricing["predicted_step_s"] * 1e3)
    verdict = (banked or {}).get("verdict") or {}
    if verdict.get("status") in ("regression", "improved", "ok"):
        # history exists: the band verdict IS the shortfall call
        warned = verdict["status"] == "regression"
    else:
        warned = ratio < frac
    registry.gauge("veles_mfu_predicted",
                   "roofline-predicted MFU of the staged step").set(
        predicted_mfu)
    registry.gauge("veles_mfu_measured",
                   "measured MFU of the staged step").set(measured_mfu)
    registry.gauge("veles_mfu_ratio",
                   "measured/predicted MFU").set(ratio)
    if warned:
        registry.counter(
            "veles_mfu_shortfall_total",
            "train sweeps whose measured MFU fell below "
            "mfu_warn_fraction of the prediction").inc()
        if not trainer.__dict__.get("_mfu_warned_"):
            trainer.__dict__["_mfu_warned_"] = True
            if verdict.get("status") == "regression":
                trainer.warning(
                    "measured MFU %.3g fell %.1f%% below its ledger "
                    "history median %.3g — outside the MAD noise band "
                    "(%s roofline predicted %.3g; "
                    "root.common.perf.band_mads tunes the band)",
                    measured_mfu,
                    -100.0 * (verdict.get("drift") or 0.0),
                    verdict.get("median") or 0.0, pricing["device"],
                    predicted_mfu)
            else:
                trainer.warning(
                    "measured MFU %.3g is %.2fx the %s roofline "
                    "prediction %.3g (threshold %.2f) — the step is "
                    "off the modeled roofline "
                    "(root.common.telemetry.mfu_warn_fraction tunes "
                    "this tripwire)",
                    measured_mfu, ratio, pricing["device"],
                    predicted_mfu, frac)
    return registry.emit(
        "mfu", predicted=predicted_mfu, measured=measured_mfu,
        ratio=ratio, warned=warned, warn_fraction=frac,
        device=pricing["device"], peak_flops=pricing["peak_flops"],
        flops_per_step=pricing["flops_per_step"],
        predicted_step_ms=pricing["predicted_step_s"] * 1e3,
        measured_step_ms=measured_step_s * 1e3, steps=steps)
