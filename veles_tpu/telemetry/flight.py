"""Flight recorder — the run's black box (ref: the reference's operable
master/slave story, docs/source/manualrst_veles_distributed_training.rst:
a distributed run you can watch, diagnose, and resume).

PR 3's telemetry plane covers the *happy* path: metrics and spans exist
while the process is alive and something scrapes them.  This module is
the *unhappy*-path complement: a bounded, thread-safe ring buffer of
structured events (unit runs, staged steps, compiles, snapshot commits,
serving admissions, fault injections, signals) whose ``append`` is O(1)
and cheap enough for the scheduler hot loop (~0.76 µs measured on the
CI box, budgeted < 2 µs — see docs/services.md "Black box"), plus a
``dump()`` that serializes the last N events together with the config
tree, mesh topology, a live-array census, the PR 3 metrics snapshot and
all-thread stack traces into an **atomic** crashdump directory::

    artifacts/crashdump-<ts>-p<proc>/
        events.jsonl    last N flight events (+ meta header with the
                        dropped-count)
        stacks.txt      every thread's python stack
        config.json     root.as_dict()
        metrics.json    MetricsRegistry snapshot + recent records
        meta.json       reason, pid, argv, process/mesh topology,
                        live-array census

Everything here is stdlib-only; jax is consulted only when it is
already imported (``sys.modules``), so recording and dumping work from
conftest-pinned CLIs and jax-free tools alike.  ``dump()`` never
raises and is re-entrant-safe: a crash *inside* a dump (or a watchdog
firing while an excepthook dump is mid-write) degrades to a no-op
instead of recursing.  Read dumps with ``veles-tpu-blackbox``
(:mod:`veles_tpu.telemetry.blackbox`), which also merges per-process
dumps into one cross-host timeline."""

import collections
import json
import os
import sys
import threading
import time

#: default ring capacity (events); root.common.blackbox.capacity
#: overrides at first use
DEFAULT_CAPACITY = 4096


def _process_index():
    """This process's index in the job — jax's answer when jax is
    already awake (never import it: flight recording must not wake a
    backend), else the launcher env, else 0."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return jax.process_index()
        except Exception:   # noqa: BLE001 — backend not initialized
            pass
    try:
        return int(os.environ.get("VELES_TPU_PROCESS_ID", "0"))
    except ValueError:
        return 0


class FlightRecorder(object):
    """Bounded, thread-safe event ring with atomic post-mortem dumps."""

    def __init__(self, capacity=None):
        if capacity is None:
            from veles_tpu.config import root
            capacity = int(root.common.blackbox.get(
                "capacity", DEFAULT_CAPACITY))
        self.capacity = int(capacity)
        self._ring = collections.deque(maxlen=self.capacity)
        # RLock, not Lock: the SIGTERM/SIGABRT handlers (telemetry.health)
        # record+dump from the main thread, and the signal can land while
        # the interrupted frame is INSIDE record()'s critical section — a
        # non-reentrant lock would deadlock the handler against its own
        # thread (same reasoning as MetricsRegistry's RLock)
        self._lock = threading.RLock()
        self._appended = 0
        #: re-entrancy/concurrency guard for dump(): non-blocking, so a
        #: crash inside a dump (excepthook firing mid-write) or a
        #: watchdog racing an excepthook degrades to a no-op dump
        self._dump_lock = threading.Lock()
        self.dump_count = 0
        self.last_dump = None

    # ---------------------------------------------------------- recording
    def record(self, kind, **fields):
        """O(1) append of one structured event.  The hot-loop surface:
        one dict build + one locked deque append, no I/O, no jax."""
        ev = {"ts": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._ring.append(ev)
            self._appended += 1
        return ev

    def snapshot(self):
        """The ring's current events, oldest first."""
        with self._lock:
            return list(self._ring)

    def __len__(self):
        """Events currently in the ring — O(1), no copy (the health
        endpoint polls this on every probe)."""
        with self._lock:
            return len(self._ring)

    @property
    def appended(self):
        with self._lock:
            return self._appended

    @property
    def dropped(self):
        """Events the bounded ring has already forgotten."""
        with self._lock:
            return self._appended - len(self._ring)

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._appended = 0

    def set_capacity(self, capacity):
        """Re-bound the ring (config applied after import — the module
        singleton is built before CLI config files run).  Keeps the
        newest events when shrinking."""
        capacity = int(capacity)
        with self._lock:
            if capacity == self.capacity:
                return
            self.capacity = capacity
            self._ring = collections.deque(self._ring, maxlen=capacity)

    # ------------------------------------------------------------- dumping
    def dump(self, directory=None, reason="manual", error=None):
        """Write an atomic ``crashdump-<ts>-p<proc>/`` directory and
        return its path, or None when a dump is already in progress
        (re-entrancy guard) or the write failed (a black box must never
        crash the process it is recording)."""
        if not self._dump_lock.acquire(blocking=False):
            return None
        try:
            return self._dump_locked(directory, reason, error)
        except Exception:   # noqa: BLE001 — forensics are best-effort
            return None
        finally:
            self._dump_lock.release()

    def _dump_locked(self, directory, reason, error):
        if directory is None:
            from veles_tpu.config import root
            directory = root.common.blackbox.get("dir", "artifacts")
        proc = _process_index()
        stamp = time.strftime("%Y%m%d_%H%M%S")
        final = os.path.join(
            directory, "crashdump-%s-p%d" % (stamp, proc))
        n = 1
        while os.path.exists(final):      # same-second dumps: suffix
            final = os.path.join(
                directory, "crashdump-%s-p%d.%d" % (stamp, proc, n))
            n += 1
        # atomicity: everything lands in a tmp dir first; the rename is
        # the commit, so a reader never sees a half-written dump and a
        # crash mid-dump leaves only an ignorable *.tmp-<pid>
        tmp = final + ".tmp-%d" % os.getpid()
        os.makedirs(tmp, exist_ok=True)
        events = self.snapshot()
        with open(os.path.join(tmp, "events.jsonl"), "w") as f:
            header = {"kind": "flight.meta", "ts": time.time(),
                      "events": len(events), "dropped": self.dropped,
                      "appended": self.appended,
                      "capacity": self.capacity}
            f.write(json.dumps(header, default=str) + "\n")
            for ev in events:
                f.write(json.dumps(ev, default=str) + "\n")
        with open(os.path.join(tmp, "stacks.txt"), "w") as f:
            f.write(format_all_stacks())
        self._write_json(os.path.join(tmp, "config.json"),
                         self._config_state)
        self._write_json(os.path.join(tmp, "metrics.json"),
                         self._metrics_state)
        self._write_json(
            os.path.join(tmp, "meta.json"),
            lambda: self._meta_state(reason, error, proc))
        os.rename(tmp, final)
        self.dump_count += 1
        self.last_dump = final
        return final

    @staticmethod
    def _write_json(path, producer):
        """One forensic section; a failing producer writes its error
        instead of aborting the whole dump."""
        try:
            payload = producer()
        except Exception as e:   # noqa: BLE001
            payload = {"error": "%s: %s" % (type(e).__name__, e)}
        with open(path, "w") as f:
            json.dump(payload, f, indent=1, default=str)

    @staticmethod
    def _config_state():
        from veles_tpu.config import root
        return root.as_dict()

    @staticmethod
    def _metrics_state():
        from veles_tpu import telemetry
        return {"metrics": telemetry.registry.snapshot(),
                "records": telemetry.registry.records()}

    @staticmethod
    def _meta_state(reason, error, proc):
        meta = {"reason": reason, "ts": time.time(), "pid": os.getpid(),
                "process_index": proc,
                # lint-ok: VK1000 — forensic payload: the exact command
                # line is what operators reproduce a crash with; it is
                # rendered raw from meta.json, never read back by code
                "argv": list(sys.argv)}
        if error is not None:
            meta["error"] = {"type": type(error).__name__,
                             "message": str(error)}
        jax = sys.modules.get("jax")
        if jax is not None:
            # never wake a backend from a dump: topology and the
            # live-array census only when jax already initialized one
            try:
                # lint-ok: VK1000 — forensic payload: pod size at the
                # moment of death, rendered raw by operators
                meta["process_count"] = jax.process_count()
                devs = jax.devices()
                # lint-ok: VK1000 — forensic payload: accelerator
                # census at the moment of death, rendered raw
                meta["devices"] = {
                    "count": len(devs),
                    "platform": devs[0].platform if devs else None}
            except Exception as e:   # noqa: BLE001
                meta["devices"] = {"error": str(e)}
            try:
                meta["live_arrays"] = _live_array_census(jax)
            except Exception as e:   # noqa: BLE001
                meta["live_arrays"] = {"error": str(e)}
        return meta


def _live_array_census(jax):
    """Count/bytes of live jax arrays + the top tenants by size — the
    "what was resident when it died" HBM view."""
    arrays = jax.live_arrays()
    total = 0
    top = []
    for a in arrays:
        try:
            nbytes = int(a.size) * a.dtype.itemsize
        except Exception:   # noqa: BLE001 — deleted/donated buffers
            continue
        total += nbytes
        top.append((nbytes, str(a.shape), str(a.dtype)))
    top.sort(reverse=True)
    return {"count": len(arrays), "total_bytes": total,
            "top": [{"bytes": b, "shape": s, "dtype": d}
                    for b, s, d in top[:20]]}


def format_all_stacks():
    """Every thread's python stack, named — the dump's stacks.txt and
    the watchdog's hang report."""
    import traceback
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sorted(sys._current_frames().items()):
        out.append("Thread %s (%s):" % (tid, names.get(tid, "?")))
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


#: the process-global flight recorder (one black box per process, like
#: the PR 3 metrics registry); ``record``/``dump`` below are the
#: framework-facing surface
recorder = FlightRecorder()


def record(kind, **fields):
    """Append one event to the process flight ring.  Never raises —
    instrumentation must not kill the loop it observes."""
    try:
        return recorder.record(kind, **fields)
    except Exception:   # noqa: BLE001
        return None


def dump(directory=None, reason="manual", error=None):
    """Write a crashdump from the process recorder (see
    :meth:`FlightRecorder.dump`)."""
    return recorder.dump(directory=directory, reason=reason, error=error)
