"""veles_tpu.telemetry — unified metrics registry, span tracing, and
predicted-vs-measured MFU for every workflow run.

The reference platform's operational story (master/slave status server,
per-unit timing prints, device-memory accounting) lands here as one
subsystem:

* :mod:`~veles_tpu.telemetry.registry` — the process-global
  :class:`MetricsRegistry` (counters/gauges/histograms, JSON-lines sink,
  Prometheus text rendering; ``--metrics-out`` and the dashboard's
  ``/metrics`` both read it);
* :mod:`~veles_tpu.telemetry.spans` — host spans doubling as
  ``jax.profiler.TraceAnnotation`` regions, with per-unit aggregation
  replacing the ad-hoc ``Unit.run_time`` bookkeeping;
* :mod:`~veles_tpu.telemetry.mfu` — roofline pricing of the staged step
  (``tools/cost_model.py`` constants + ``ops/flops.py`` conventions) and
  the measured-utilization tripwire;
* :mod:`~veles_tpu.telemetry.cli` — the ``veles-tpu-metrics`` JSONL
  summarizer;
* :mod:`~veles_tpu.telemetry.flight` — the bounded flight-recorder
  ring + atomic ``crashdump-*`` post-mortem dumps (the unhappy-path
  black box; read with ``veles-tpu-blackbox``);
* :mod:`~veles_tpu.telemetry.health` — crash-forensics hooks
  (excepthook/faulthandler/SIGTERM/SIGABRT), the hang watchdog, and
  the multi-host heartbeat/desync layer;
* :mod:`~veles_tpu.telemetry.ledger` — the persistent performance
  ledger (append-only JSONL keyed the tuner's way), the pre-registered
  target registry, and the median/MAD regression sentinel (read with
  ``veles-tpu-perf``);
* :mod:`~veles_tpu.telemetry.anatomy` — step-anatomy attribution:
  compile/host/dispatch/collective/compute decomposition of the
  training step, priced against ``tools/cost_model``.

Import cost is stdlib-only; jax is touched lazily (first span under a
live trace annotation), so platform pinning still works."""

from veles_tpu.telemetry import (anatomy, flight, health,  # noqa: F401
                                 ledger, mfu)
from veles_tpu.telemetry.registry import (  # noqa: F401
    DEFAULT_BUCKETS, Counter, Gauge, Histogram, MetricsRegistry)
from veles_tpu.telemetry.spans import (  # noqa: F401
    SpanAggregate, emit_workflow_spans, span, trace_annotation)

#: the process-global registry (the reference used one status-server
#: session per run); everything instrument-shaped in the framework
#: lands here unless an explicit registry is passed
registry = MetricsRegistry()


def get_registry():
    return registry


_collection = False


def enable_collection():
    """Mark that something will actually consume expensive collections
    (the web-status ``/metrics`` scrape surface calls this on start;
    an open JSONL sink implies it).  Cheap instruments update
    regardless; only the costly sweeps — the ``Watcher`` live-array
    census — key off this."""
    global _collection
    _collection = True


def collection_enabled():
    return _collection or registry.sink_path is not None
