"""Request tracing — one gapless cross-process timeline per serving
request (ref: the reference platform's master/slave web status story:
cross-node visibility is a platform capability, not an add-on).

PR 5's flight recorder answers "what happened in THIS process"; a
serving request now lives across processes — router -> prefill replica
-> handoff splice -> decode replica -> failover survivor — and this
module is the cross-process complement.  A **trace context**
(``trace_id`` + parent span id) is minted at the serving edge (the
fleet router, or a bare replica), travels on the ``X-Veles-Trace``
HTTP header between hops, and keys every span and ``serve.*`` flight
event a request touches.  Client-supplied ids are forged-id-stripped
at the router exactly like ``resume`` payloads: the edge always mints.

Each process keeps a bounded :class:`SpanStore` — same ring discipline
and per-event overhead budget (< 2 µs) as the flight recorder: an
``add`` is one dict build + one locked append, no I/O, no syscalls
(span ids come from a per-process seed + counter, not urandom).  On
overflow the OLDEST trace is evicted and counted (surfaced as the
``veles_trace_dropped_total`` counter).  Replicas expose their store
via ``GET /api/trace/<id>``; the router aggregates its own spans with
every live replica's and decomposes completed requests into
queue/prefill/decode/stream phases.  Post-mortem, ``veles-tpu-trace``
rebuilds the same timeline from merged crashdumps (flight events carry
the trace id), so a request that crossed a SIGKILL still reconstructs.

The terminal-span rule: **the process that minted the trace id records
the one terminal span** (the router for routed requests, the replica
when serving bare).  A replica that received its context on the header
never terminates the trace — that is what keeps "exactly one terminal
span" an invariant worth gating on.

Stdlib-only; jax-free; every public mutator is fail-soft."""

import collections
import itertools
import os
import re
import threading
import time

#: HTTP header carrying the trace context between serving hops:
#: ``X-Veles-Trace: <trace_id>`` or ``<trace_id>/<parent_span_id>``
TRACE_HEADER = "X-Veles-Trace"

#: default bound on distinct traces held per process;
#: root.common.trace.capacity overrides at first use
DEFAULT_CAPACITY = 1024

#: default bound on spans held per trace;
#: root.common.trace.max_spans overrides at first use
DEFAULT_MAX_SPANS = 128

#: the four phases a completed request decomposes into
PHASES = ("queue", "prefill", "decode", "stream")

#: histogram buckets for the per-phase latency histograms — phase
#: durations are MILLISECOND-valued, so the registry's second-flavored
#: DEFAULT_BUCKETS would collapse everything into the top bucket
PHASE_BUCKETS_MS = (1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, 200.0,
                    500.0, 1000.0, 2000.0, 5000.0, 10000.0, 30000.0)

#: ids are lowercase hex — anything else on the wire is forged/garbage
_ID_RE = re.compile(r"^[0-9a-f]{4,32}$")

#: wall = monotonic + MONO_TO_WALL: engine stamps are monotonic (they
#: must survive NTP steps), but spans and flight events merge across
#: processes on wall clock, so converted stamps key consistently
MONO_TO_WALL = time.time() - time.monotonic()

#: span ids must be unique across processes but their generation sits
#: on the admission hot path — a random per-process seed + a counter
#: costs ~0.1 µs where urandom-per-span would blow the 2 µs budget
_SPAN_SEED = os.urandom(3).hex()
_SPAN_COUNTER = itertools.count(1)


def mono_to_wall(ts):
    """A monotonic stamp as wall-clock time (cross-process mergeable)."""
    return ts + MONO_TO_WALL


def new_trace_id():
    """A fresh 16-hex-char trace id (minted once per request, at the
    edge — urandom here is off the hot path)."""
    return os.urandom(8).hex()


def new_span_id():
    """A fresh span id: per-process seed + counter, syscall-free."""
    return "%s%06x" % (_SPAN_SEED, next(_SPAN_COUNTER))


def valid_id(value):
    """True when ``value`` looks like an id WE minted (lowercase hex,
    bounded length) — the forged-id filter's yardstick."""
    return isinstance(value, str) and bool(_ID_RE.match(value))


def parse_header(value):
    """``(trace_id, parent_span_id_or_None)`` from an ``X-Veles-Trace``
    header value, or None when the header is absent or forged (a
    non-hex id is somebody else's idea — mint fresh instead)."""
    if not value:
        return None
    parts = str(value).strip().split("/", 1)
    trace = parts[0]
    if not valid_id(trace):
        return None
    parent = parts[1] if len(parts) > 1 else None
    if parent is not None and not valid_id(parent):
        parent = None
    return trace, parent


def format_header(trace, parent=None):
    """The header value for the next hop: the trace id, plus the span
    the receiver should parent onto."""
    return "%s/%s" % (trace, parent) if parent else str(trace)


def proc_label():
    """Which process a span came from: the fleet agent's
    ``VELES_TPU_FLEET_HOST``/``VELES_TPU_FLEET_REP`` env when running
    as a fleet replica (podmaster threads these at spawn), else the
    launcher's process index."""
    host = os.environ.get("VELES_TPU_FLEET_HOST")
    rep = os.environ.get("VELES_TPU_FLEET_REP")
    if host is not None and rep is not None:
        return "%s/r%s" % (host, rep)
    try:
        return "p%d" % int(os.environ.get("VELES_TPU_PROCESS_ID", "0"))
    except ValueError:
        return "p0"


class SpanStore(object):
    """Bounded per-request span store — the flight recorder's ring
    discipline applied per-trace: an OrderedDict of trace_id -> span
    list, evicting the OLDEST trace past ``capacity`` and the oldest
    span past ``max_spans``, every eviction counted."""

    def __init__(self, capacity=None, max_spans=None, enabled=None):
        if capacity is None or max_spans is None or enabled is None:
            from veles_tpu.config import root
            trace_cfg = root.common.trace
            if capacity is None:
                capacity = int(trace_cfg.get(
                    "capacity", DEFAULT_CAPACITY))
            if max_spans is None:
                max_spans = int(trace_cfg.get(
                    "max_spans", DEFAULT_MAX_SPANS))
            if enabled is None:
                enabled = bool(trace_cfg.get("enabled", True))
        self.capacity = int(capacity)
        self.max_spans = int(max_spans)
        self.enabled = bool(enabled)
        self._traces = collections.OrderedDict()
        # RLock for the same reason as the flight ring: signal handlers
        # may record from a frame already inside the critical section
        self._lock = threading.RLock()
        self._added = 0
        self.dropped_traces = 0
        self.dropped_spans = 0
        self._proc = proc_label()
        self._drop_counter = None

    # ---------------------------------------------------------- recording
    def add(self, trace, name, ts=None, dur_ms=None, parent=None,
            span=None, terminal=False, **attrs):
        """O(1) append of one span; returns its id (the caller threads
        it to the next hop as the parent).  The hot-path surface: one
        dict build + one locked append, budgeted like flight.record."""
        if not self.enabled or not trace:
            return None
        sp = {"trace": trace, "span": span or new_span_id(),
              "parent": parent, "name": name,
              "ts": time.time() if ts is None else ts,
              "proc": self._proc}
        if dur_ms is not None:
            sp["dur_ms"] = dur_ms
        if terminal:
            sp["terminal"] = True
        if attrs:
            sp.update(attrs)
        with self._lock:
            spans = self._traces.get(trace)
            if spans is None:
                if len(self._traces) >= self.capacity:
                    self._traces.popitem(last=False)
                    self.dropped_traces += 1
                    self._count_drop("trace")
                spans = self._traces[trace] = []
            else:
                self._traces.move_to_end(trace)
                if len(spans) >= self.max_spans:
                    del spans[0]
                    self.dropped_spans += 1
                    self._count_drop("span")
            spans.append(sp)
            self._added += 1
        return sp["span"]

    def _count_drop(self, kind):
        """Evictions (only) touch the metrics registry — fail-soft, so
        a broken registry never stalls admission."""
        try:
            if self._drop_counter is None:
                from veles_tpu import telemetry
                self._drop_counter = telemetry.registry.counter(
                    "veles_trace_dropped_total",
                    "traces/spans evicted from the bounded span store",
                    labelnames=("kind",))
            self._drop_counter.inc(kind=kind)
        except Exception:   # noqa: BLE001 — instrumentation never kills
            self._drop_counter = None

    # ------------------------------------------------------------ reading
    def spans(self, trace):
        """This trace's spans, oldest first ([] when unknown/evicted)."""
        with self._lock:
            return list(self._traces.get(trace, ()))

    def traces(self):
        """Known trace ids, oldest first."""
        with self._lock:
            return list(self._traces)

    def __len__(self):
        with self._lock:
            return len(self._traces)

    @property
    def added(self):
        with self._lock:
            return self._added

    @property
    def dropped(self):
        """Total evictions (traces + spans) — the counted-gauge read."""
        with self._lock:
            return self.dropped_traces + self.dropped_spans

    def clear(self):
        with self._lock:
            self._traces.clear()
            self._added = 0
            self.dropped_traces = 0
            self.dropped_spans = 0

    def set_capacity(self, capacity=None, max_spans=None):
        """Re-bound the store (config applied after import, like the
        flight ring).  Keeps the newest traces when shrinking."""
        with self._lock:
            if capacity is not None:
                self.capacity = int(capacity)
                while len(self._traces) > self.capacity:
                    self._traces.popitem(last=False)
                    self.dropped_traces += 1
            if max_spans is not None:
                self.max_spans = int(max_spans)


# ----------------------------------------------------------- timeline math
def phases_of(spans):
    """{phase: dur_ms} summed from ``phase.<name>`` spans — several
    legs (failover resubmit, prefill handoff) each contribute their
    share of the same phase."""
    out = {}
    for sp in spans:
        name = sp.get("name", "")
        if name.startswith("phase.") and sp.get("dur_ms") is not None:
            phase = name[len("phase."):]
            out[phase] = out.get(phase, 0.0) + float(sp["dur_ms"])
    return out


def validate(spans):
    """The gaplessness check the chaos gates pin: every parent id
    resolves inside the trace, exactly one root, exactly one terminal
    span.  -> ``{"ok": bool, "problems": [str, ...]}``.

    A replica SIGKILL loses that replica's spans entirely — which
    stays gapless (the router's own leg/failover spans form a
    connected chain); what it can never produce is a DANGLING parent
    or a second terminal."""
    problems = []
    if not spans:
        return {"ok": False, "problems": ["no spans"]}
    ids = set()
    for sp in spans:
        sid = sp.get("span")
        if sid in ids:
            problems.append("duplicate span id %s" % sid)
        ids.add(sid)
    roots, terminals = 0, 0
    for sp in spans:
        parent = sp.get("parent")
        if parent is None:
            roots += 1
        elif parent not in ids:
            problems.append(
                "span %s (%s) has unresolved parent %s"
                % (sp.get("span"), sp.get("name"), parent))
        if sp.get("terminal"):
            terminals += 1
    if roots != 1:
        problems.append("%d root spans (want exactly 1)" % roots)
    if terminals != 1:
        problems.append("%d terminal spans (want exactly 1)" % terminals)
    return {"ok": not problems, "problems": problems}


def render_timeline(spans, title=None):
    """The operator view of one trace — the blackbox timeline format
    (offsets from the first span, ``[proc]`` tags), plus the phase
    decomposition footer."""
    out = []
    if title:
        out.append(title)
    if not spans:
        out.append("(no spans)")
        return "\n".join(out)
    ordered = sorted(spans, key=lambda s: (s.get("ts", 0.0),
                                           s.get("span") or ""))
    t0 = ordered[0].get("ts", 0.0)
    for sp in ordered:
        line = "  %+10.3fs [%s] %-18s" % (
            sp.get("ts", 0.0) - t0, sp.get("proc", "?"),
            sp.get("name", "?"))
        extra = []
        if sp.get("dur_ms") is not None:
            extra.append("dur_ms=%.3f" % float(sp["dur_ms"]))
        for k in sorted(sp):
            if k in ("trace", "span", "parent", "name", "ts", "proc",
                     "dur_ms", "terminal"):
                continue
            extra.append("%s=%s" % (k, sp[k]))
        if sp.get("terminal"):
            extra.append("TERMINAL")
        if extra:
            line += " " + " ".join(extra)
        out.append(line.rstrip())
    phases = phases_of(ordered)
    if phases:
        out.append("  phases: " + "  ".join(
            "%s=%.3fms" % (p, phases[p])
            for p in PHASES if p in phases))
    verdict = validate(spans)
    out.append("  gapless: %s%s"
               % ("yes" if verdict["ok"] else "NO",
                  "" if verdict["ok"]
                  else "  (" + "; ".join(verdict["problems"]) + ")"))
    return "\n".join(out)


def spans_from_flight(events, trace):
    """Pseudo-spans synthesized from flight events carrying this trace
    id — the post-mortem path (``veles-tpu-trace --dumps``): every
    process's crashdump events become one timeline even when every
    span store died with its process."""
    out = []
    for ev in events:
        if ev.get("trace") != trace:
            continue
        sp = dict(ev)
        sp.setdefault("name", ev.get("kind", "?"))
        sp.setdefault("span", None)
        sp.setdefault("parent", None)
        sp.setdefault("proc", ev.get("proc", "?"))
        out.append(sp)
    return out


#: the process-global span store (one per process, like the flight
#: recorder); ``span_add`` below is the framework-facing surface
store = SpanStore()


def span_add(trace, name, **fields):
    """Append one span to the process store.  Never raises —
    instrumentation must not kill the request it observes."""
    try:
        return store.add(trace, name, **fields)
    except Exception:   # noqa: BLE001
        return None
