"""``veles-tpu-metrics`` — summarize a ``--metrics-out`` JSON-lines file.

A run's metrics JSONL interleaves live records (spans, step telemetry,
MFU checks) with the end-of-run instrument dump.  This reads the whole
file and prints the operator's view: run/step throughput, the per-unit
time table, compile cost, device-memory high water, and the
predicted-vs-measured MFU verdict.  ``--format json`` emits the same
summary as one JSON object for scripting."""

import argparse
import json
import sys


def load_records(path):
    records, bad = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                bad += 1
    return records, bad


def summarize(records):
    """The summary dict ``main`` renders.  Aggregates are cumulative in
    the stream, so "last record wins" per key."""
    by_kind = {}
    for r in records:
        by_kind.setdefault(r.get("kind", "?"), []).append(r)

    spans = {}
    for r in by_kind.get("span", []):
        if r.get("name") == "unit.run":
            spans[(r.get("workflow"), r.get("unit"))] = r
    workflow_runs = [r for r in by_kind.get("span", [])
                     if r.get("name") == "workflow.run"]

    steps = {}
    for r in by_kind.get("step", []):
        cls = r.get("class", "?")
        agg = steps.setdefault(cls, {"sweeps": 0, "steps": 0,
                                     "examples": 0, "wall_s": 0.0,
                                     "last_loss": None})
        agg["sweeps"] += 1
        agg["steps"] += int(r.get("steps", 0))
        agg["examples"] += int(r.get("examples", 0))
        agg["wall_s"] += float(r.get("wall_s", 0.0))
        if r.get("loss") is not None:
            agg["last_loss"] = r["loss"]
    for agg in steps.values():
        agg["examples_per_sec"] = (agg["examples"] / agg["wall_s"]
                                   if agg["wall_s"] > 0 else 0.0)

    counters, gauges = {}, {}
    for r in by_kind.get("counter", []):
        key = (r.get("name"), tuple(sorted((r.get("labels") or {})
                                           .items())))
        counters[key] = r.get("value")
    for r in by_kind.get("gauge", []):
        key = (r.get("name"), tuple(sorted((r.get("labels") or {})
                                           .items())))
        gauges[key] = r.get("value")

    compile_secs = sum(v for (n, _), v in counters.items()
                       if n == "veles_compile_seconds_total")
    compile_events = sum(v for (n, _), v in counters.items()
                         if n == "veles_compile_events_total")
    live_bytes = {dict(l).get("device", "?"): v for (n, l), v
                  in gauges.items() if n == "veles_device_live_bytes"}
    peak = [v for (n, _), v in gauges.items()
            if n == "veles_device_peak_bytes"]

    mfu_records = by_kind.get("mfu", [])
    return {
        "records": len(records),
        "kinds": {k: len(v) for k, v in sorted(by_kind.items())},
        "workflow_runs": [
            {"workflow": r.get("workflow"), "dur_s": r.get("dur_s")}
            for r in workflow_runs],
        "units": sorted(
            ({"workflow": wf, "unit": u,
              "count": r.get("count"), "total_s": r.get("total_s"),
              "mean_s": r.get("mean_s")} for (wf, u), r in spans.items()),
            key=lambda x: -(x["total_s"] or 0.0)),
        "steps": steps,
        "compile": {"events": compile_events, "seconds": compile_secs},
        "device_live_bytes": live_bytes,
        "device_peak_bytes": peak[0] if peak else None,
        "mfu": mfu_records[-1] if mfu_records else None,
    }


def _render_text(path, summary, bad):
    out = ["%s: %d records (%s)%s" % (
        path, summary["records"],
        ", ".join("%s=%d" % kv for kv in summary["kinds"].items()),
        " [%d unparseable lines]" % bad if bad else "")]
    for r in summary["workflow_runs"]:
        out.append("workflow %-20s %8.3fs" % (r["workflow"],
                                              r["dur_s"] or 0.0))
    if summary["units"]:
        out.append("-- unit spans (aggregated; gated/skipped excluded)")
        for u in summary["units"][:12]:
            out.append("  %-28s %6d runs %9.3fs (mean %.3f ms)"
                       % (u["unit"], u["count"] or 0, u["total_s"] or 0,
                          1e3 * (u["mean_s"] or 0)))
    if summary["steps"]:
        out.append("-- step telemetry")
        for cls, agg in sorted(summary["steps"].items()):
            out.append(
                "  %-12s %6d steps %8d examples %9.1f ex/s"
                "  last loss %s"
                % (cls, agg["steps"], agg["examples"],
                   agg["examples_per_sec"],
                   "%.4f" % agg["last_loss"]
                   if agg["last_loss"] is not None else "-"))
    comp = summary["compile"]
    if comp["events"]:
        out.append("-- compile: %d events, %.2fs total"
                   % (comp["events"], comp["seconds"]))
    if summary["device_peak_bytes"] is not None:
        out.append("-- device memory: peak %.1f MiB%s" % (
            summary["device_peak_bytes"] / 2 ** 20,
            "; live " + ", ".join(
                "%s %.1f MiB" % (d, b / 2 ** 20) for d, b
                in sorted(summary["device_live_bytes"].items()))
            if summary["device_live_bytes"] else ""))
    m = summary["mfu"]
    if m:
        out.append(
            "-- MFU vs %s roofline: predicted %.3g  measured %.3g  "
            "ratio %.3f%s" % (m.get("device", "?"),
                              m.get("predicted", 0.0),
                              m.get("measured", 0.0),
                              m.get("ratio", 0.0),
                              "  ** SHORTFALL **"
                              if m.get("warned") else ""))
    return "\n".join(out)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="veles-tpu-metrics",
        description="summarize a --metrics-out JSONL file")
    p.add_argument("path", help="metrics .jsonl written by "
                   "`python -m veles_tpu ... --metrics-out FILE`")
    p.add_argument("--format", choices=("text", "json"), default="text")
    args = p.parse_args(argv)
    try:
        records, bad = load_records(args.path)
    except OSError as e:
        print("veles-tpu-metrics: %s" % e, file=sys.stderr)
        return 2
    summary = summarize(records)
    if args.format == "json":
        print(json.dumps(summary, indent=1, default=str))
    else:
        print(_render_text(args.path, summary, bad))
    return 0 if records else 1


if __name__ == "__main__":
    sys.exit(main())
