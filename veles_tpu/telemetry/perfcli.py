"""veles-tpu-perf — read, diff and gate the performance ledger.

The machine-checked replacement for eyeballing BENCH_r0x.json: every
banked number (bench phases, chaos-harness gates, MFU checks, trainer
sweeps — telemetry.ledger) is reported per key with its median/MAD
band, declared target, and last sentinel verdict.

Subcommands::

    report   per-key history summary: n, last, median, MAD band,
             drift, target, verdict
    diff     latest value per key vs a baseline ledger (or, without
             --baseline, vs the key's own prior median)
    gate     the CI verdict: fresh regressions (VL1210, error) +
             missed targets (VL1211, warning) + the VL12xx
             target-contract lint, through the ONE shared exit gate
             (analysis.findings.threshold_reached)
    targets  the declared registry vs what the ledger has measured

Exit status (identical to every lint surface): 0 = no findings at or
above ``--fail-on``, 1 = threshold reached, 2 = usage error."""

import argparse
import json
import sys

from veles_tpu.telemetry import ledger as led


def _book(args):
    return led.PerfLedger(args.ledger) if args.ledger else led.default()


def _fmt(v, nd=4):
    if v is None:
        return "-"
    if isinstance(v, float):
        return ("%%.%dg" % nd) % v
    return str(v)


def _assess_keys(book):
    """[(key, latest record, verdict)] for every key in the ledger —
    the freshest record judged against everything before it."""
    out = []
    for key, recs in sorted(book.by_key().items()):
        latest, prior = recs[-1], recs[:-1]
        out.append((key, latest, book.assess(latest, prior)))
    return out


def cmd_report(args):
    book = _book(args)
    rows = [(k, r, v) for k, r, v in _assess_keys(book)
            if not args.key or args.key in k]
    if args.format == "json":
        print(json.dumps([{"key": k, "record": r, "verdict": v}
                          for k, r, v in rows], indent=2,
                         default=str))
        return 0
    if not rows:
        print("ledger %s: no records" % book.path)
        return 0
    print("ledger %s: %d keys" % (book.path, len(rows)))
    hdr = ("%-44s %5s %10s %10s %10s %8s %10s %s"
           % ("key", "n", "last", "median", "band", "drift",
              "target", "verdict"))
    print(hdr)
    print("-" * len(hdr))
    for k, r, v in rows:
        print("%-44s %5d %10s %10s %10s %8s %10s %s"
              % (k[:44], v["n"] + 1, _fmt(r.get("value")),
                 _fmt(v["median"]), _fmt(v["band"]),
                 ("%+.1f%%" % (100 * v["drift"])
                  if v["drift"] is not None else "-"),
                 _fmt(v["target"]), v["status"]
                 + ("" if v.get("target_met") is None
                    else " target_met" if v["target_met"]
                    else " target_MISSED")))
    return 0


def cmd_diff(args):
    book = _book(args)
    base = led.PerfLedger(args.baseline) if args.baseline else None
    rows = []
    for key, latest, verdict in _assess_keys(book):
        if base is not None:
            brecs = base.records(key=key)
            ref = brecs[-1].get("value") if brecs else None
        else:
            ref = verdict["median"]
        val = latest.get("value")
        delta = (None if ref in (None, 0)
                 or not isinstance(val, (int, float))
                 else (val - ref) / ref)
        rows.append((key, val, ref, delta))
    if args.format == "json":
        print(json.dumps([{"key": k, "value": v, "baseline": r,
                           "delta": d} for k, v, r, d in rows],
                         indent=2, default=str))
        return 0
    ref_name = args.baseline or "prior median"
    print("diff vs %s" % ref_name)
    for k, v, r, d in rows:
        print("%-44s %10s -> %10s  %s"
              % (k[:44], _fmt(r), _fmt(v),
                 "%+.1f%%" % (100 * d) if d is not None else "-"))
    return 0


def cmd_targets(args):
    book = _book(args)
    measured = {}
    for rec in book.records():
        m = rec.get("metric")
        if m in led.TARGETS_BY_METRIC:
            measured.setdefault(m, []).append(rec)
    if args.format == "json":
        print(json.dumps(
            [{"metric": t.metric, "goal": t.goal, "better": t.better,
              "unit": t.unit, "source": t.source, "note": t.note,
              "measured": len(measured.get(t.metric, [])),
              "last": (measured[t.metric][-1].get("value")
                       if t.metric in measured else None),
              "met": (t.met(measured[t.metric][-1]["value"])
                      if t.metric in measured and isinstance(
                          measured[t.metric][-1].get("value"),
                          (int, float)) else None)}
             for t in led.TARGETS], indent=2, default=str))
        return 0
    for t in led.TARGETS:
        recs = measured.get(t.metric, [])
        last = recs[-1].get("value") if recs else None
        status = ("NEVER MEASURED" if not recs
                  else "met" if isinstance(last, (int, float))
                  and t.met(last) else "MISSED")
        print("%-24s %s %-8s [%s]  n=%d last=%s  %s  (%s)"
              % (t.metric, "<=" if t.better == "lower" else ">=",
                 _fmt(t.goal), t.unit, len(recs), _fmt(last),
                 status, t.source))
    return 0


def gate_findings(book):
    """The gate's finding list: fresh sentinel verdicts (VL1210
    regression = error, VL1211 missed target = warning — component
    named when the anatomy knows it) + the VL12xx target-contract
    lint."""
    from veles_tpu.analysis.findings import ERROR, WARNING, Finding
    from veles_tpu.analysis.perf_lint import lint_perf
    findings = []
    records = book.records()
    for key, latest, v in _assess_keys(book):
        metric = str(latest.get("metric", key))
        if v["status"] == "regression":
            comp = v.get("component")
            findings.append(Finding(
                "VL1210", ERROR, key,
                "regression: %s drifted %+.1f%% off its history "
                "median %s (band %s)%s"
                % (metric, 100 * (v["drift"] or 0.0),
                   _fmt(v["median"]), _fmt(v["band"]),
                   " — drifted component: %s" % comp if comp
                   else ""),
                "bisect the drifted component"
                + (" (%s)" % comp if comp else "")
                + "; veles-tpu-perf report shows the key's history"))
        if v.get("target_met") is False:
            findings.append(Finding(
                "VL1211", WARNING, key,
                "declared target missed: %s=%s vs goal %s %s"
                % (metric, _fmt(latest.get("value")),
                   "<=" if v["better"] == "lower" else ">=",
                   _fmt(v["target"])),
                "the pre-registered bar (telemetry.ledger.TARGETS) "
                "— fix-and-remeasure on the next TPU window"))
    findings.extend(lint_perf(records=records))
    return findings


def cmd_gate(args):
    from veles_tpu.analysis.findings import (format_findings,
                                             sort_findings,
                                             threshold_reached)
    book = _book(args)
    findings = sort_findings(gate_findings(book))
    print(format_findings(findings,
                          "json" if args.format == "json" else "text"))
    return 1 if threshold_reached(findings, args.fail_on) else 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="veles-tpu-perf",
        description="performance-ledger reporter + regression gate "
                    "(telemetry.ledger; docs/perf.md)",
        epilog="exit codes: 0 below --fail-on threshold, 1 threshold "
               "reached, 2 usage (the shared findings gate)")
    sub = p.add_subparsers(dest="cmd", required=True)
    for name, fn in (("report", cmd_report), ("diff", cmd_diff),
                     ("gate", cmd_gate), ("targets", cmd_targets)):
        sp = sub.add_parser(name)
        sp.set_defaults(fn=fn)
        sp.add_argument("--ledger", default=None, metavar="PATH",
                        help="ledger JSONL (default: root.common."
                        "perf.ledger > VELES_TPU_PERF_LEDGER > "
                        "<dirs.cache>/perf_ledger.jsonl)")
        sp.add_argument("--format", choices=("text", "json"),
                        default="text")
        if name == "report":
            sp.add_argument("--key", default=None,
                            help="substring filter on the full "
                            "metric|workload|backend|mesh|dtype key")
        if name == "diff":
            sp.add_argument("--baseline", default=None, metavar="PATH",
                            help="baseline ledger to diff against "
                            "(default: each key's own prior median)")
        if name == "gate":
            sp.add_argument("--fail-on", choices=("error", "warning"),
                            default="error",
                            metavar="{error,warning}",
                            help="severity threshold for exit 1 "
                            "(the shared findings gate)")
    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
