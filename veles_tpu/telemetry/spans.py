"""Span tracing: host wall-time spans that double as
``jax.profiler.TraceAnnotation`` regions (ref: the reference's per-unit
timing prints, veles/units.py:144-149/805-817 — aggregated instead of
printed, and named identically in the device trace).

``SpanAggregate`` is the per-site accumulator (count/total/min/max/last)
that replaces the ad-hoc ``Unit.run_time``/``run_count`` bookkeeping;
the ``span`` context manager times a region, enters a TraceAnnotation of
the same name (so an xplane capture shows the host span's name against
the device timeline), and optionally feeds an aggregate and/or emits a
JSONL record."""

import time

_trace_annotation = None


def trace_annotation():
    """The ``jax.profiler.TraceAnnotation`` class, resolved lazily (the
    first unit run, not import time — conftest/CLI code must be able to
    pin the platform before jax wakes up), or None without jax."""
    global _trace_annotation
    if _trace_annotation is None:
        try:
            from jax.profiler import TraceAnnotation
            _trace_annotation = TraceAnnotation
        except Exception:   # noqa: BLE001 — no jax: spans stay host-only
            _trace_annotation = False
    return _trace_annotation or None


class SpanAggregate(object):
    """count/total/min/max/last seconds for one span site."""

    __slots__ = ("name", "count", "total", "min", "max", "last")

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = 0.0
        self.last = 0.0

    def add(self, seconds):
        self.count += 1
        self.total += seconds
        self.last = seconds
        if self.min is None or seconds < self.min:
            self.min = seconds
        if seconds > self.max:
            self.max = seconds

    def reset(self):
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = 0.0
        self.last = 0.0

    def record(self, **extra):
        """JSONL-shaped summary of this aggregate."""
        rec = {"name": self.name, "count": self.count,
               "total_s": self.total, "max_s": self.max,
               "mean_s": self.total / self.count if self.count else 0.0}
        rec.update(extra)
        return rec


class span(object):
    """``with span("unit.run:loader")`` — wall-times the body, shares the
    name with the device trace via TraceAnnotation, and on exit feeds
    ``aggregate`` and/or emits a ``kind="span"`` record when
    ``emit=True`` (extra kwargs become record fields)."""

    def __init__(self, name, aggregate=None, emit=False, registry=None,
                 **fields):
        self.name = name
        self.aggregate = aggregate
        self.emit = emit
        self.registry = registry
        self.fields = fields
        self.seconds = None
        self._t0 = None
        self._ann = None

    def __enter__(self):
        ann = trace_annotation()
        if ann is not None:
            self._ann = ann(self.name)
            self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.seconds = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
            self._ann = None
        if self.aggregate is not None:
            self.aggregate.add(self.seconds)
        if self.emit:
            reg = self.registry
            if reg is None:
                from veles_tpu.telemetry import registry as _default
                reg = _default
            reg.emit("span", name=self.name, dur_s=self.seconds,
                     **self.fields)
        return False


def emit_workflow_spans(workflow, wall_s, registry=None):
    """End-of-run span export: one ``workflow.run`` record plus one
    aggregated ``unit.run`` record per unit that actually ran (units a
    gate blocked or skipped for the whole run have ``count == 0`` and
    are excluded), mirrored into per-unit gauges for ``/metrics``."""
    if registry is None:
        from veles_tpu.telemetry import registry
    registry.emit("span", name="workflow.run", workflow=workflow.name,
                  dur_s=wall_s)
    # gauges (set to the aggregate each run end), so no _total suffix:
    # that's counter-reserved in prometheus naming and rate() over a
    # set-once-per-run series would lie
    g_time = registry.gauge(
        "veles_unit_run_seconds",
        "total seconds spent inside unit.run(), per unit "
        "(set at each workflow run end)", ("workflow", "unit"))
    g_runs = registry.gauge(
        "veles_unit_runs", "unit.run() invocations, per unit "
        "(set at each workflow run end)", ("workflow", "unit"))
    for u in workflow.units:
        agg = getattr(u, "span", None)
        if agg is None or not agg.count:
            continue
        registry.emit("span", **agg.record(
            workflow=workflow.name, unit=u.name, cls=type(u).__name__))
        g_time.set(agg.total, workflow=workflow.name, unit=u.name)
        g_runs.set(agg.count, workflow=workflow.name, unit=u.name)
