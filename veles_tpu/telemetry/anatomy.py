"""Step-anatomy attribution: decompose the training step the way PR 18
decomposed serving requests.

PR 18 taught the serving plane to partition every request's
admitted→finished span into prefill/decode phases that must sum
exactly; this module gives the *training* step the same treatment, so
a ledger regression on ``train_step_ms`` names a component instead of
"step got slower".  Components, and where each number comes from:

* ``compile_ms``   — measured: the compile listener's
  ``veles_compile_seconds_total`` counter delta since the previous
  sweep (compile_cache.py), amortized per step.  Nonzero means the
  sweep paid a recompile — the classic silent step-time cliff.
* ``host_ms``      — priced: the calibrated per-step host floor
  (``h_step``) from tools/cost_model.py's device constants.
* ``dispatch_ms``  — priced: the dispatch-queue floor
  (``t_dispatch / steps_per_dispatch``) — the number the
  steps-per-dispatch knob exists to amortize.
* ``collective_ms`` — measured: the multi-host heartbeat's
  sync-point cost when a pod is up (0 on one host).
* ``compute_ms``   — residual: measured step time minus everything
  above, floored at 0 — device compute plus anything the model
  doesn't price (the honest "unexplained" bucket rides here, exactly
  like cost_model's postdiction residuals).

Each measured component is priced against the cost model's floors
(:func:`tools.cost_model.anatomy_floors` when the repo's tools/ is
importable, else the same baked-in v5e constants mfu.py carries), so
``attribute()`` can say WHICH share outgrew its floor.  Stdlib-only,
fail-soft: attribution rides the telemetry path and must never kill
the loop it observes."""

import threading

from veles_tpu.telemetry import mfu

#: component order is the display/report order (docs/perf.md)
COMPONENTS = ("compile_ms", "host_ms", "dispatch_ms",
              "collective_ms", "compute_ms")

_state_lock = threading.Lock()
_last_compile_s = {}   # id(registry) -> cumulative compile seconds


def predicted_floors(steps_per_dispatch=1, kernels=8,
                     compute_ms=None):
    """Per-component predicted floors in ms, from the calibrated
    device constants (tools/cost_model.anatomy_floors preferred — the
    single calibration source — else mfu's baked-in mirror)."""
    try:
        from tools.cost_model import anatomy_floors
        floors = anatomy_floors(steps_per_dispatch=steps_per_dispatch,
                                kernels=kernels)
    except Exception:   # noqa: BLE001 — installed without tools/
        dm = mfu.device_model()
        spd = max(int(steps_per_dispatch), 1)
        floors = {"compile_ms": 0.0,
                  "host_ms": dm["h_step"] * 1e3,
                  "dispatch_ms": dm["t_dispatch"] / spd * 1e3,
                  "collective_ms": 0.0,
                  "compute_ms": kernels * dm["t_kernel"] * 1e3}
    if compute_ms is not None:
        floors["compute_ms"] = compute_ms
    return floors


def _compile_delta_s(registry, steps):
    """Compile seconds this registry accumulated since the previous
    sweep, amortized per step (the compile listener's counter is
    cumulative; the anatomy wants per-sweep)."""
    total = 0.0
    try:
        for sample in registry.snapshot():
            if sample.get("name") == "veles_compile_seconds_total":
                total += float(sample.get("value", 0.0))
    except Exception:   # noqa: BLE001 — observational
        return 0.0
    with _state_lock:
        prev = _last_compile_s.get(id(registry), 0.0)
        _last_compile_s[id(registry)] = total
    return max(total - prev, 0.0) / max(steps, 1)


def _collective_ms(registry, steps):
    """Per-step collective-wait proxy: the multi-host heartbeat's
    straggler spread (``veles_step_wall_skew_seconds``,
    telemetry.health) amortized over the sweep — the time the
    allgather spent waiting for the slowest host; 0 on one host."""
    try:
        for sample in registry.snapshot():
            if sample.get("name") == "veles_step_wall_skew_seconds":
                return (float(sample.get("value", 0.0))
                        / max(steps, 1) * 1e3)
    except Exception:   # noqa: BLE001
        pass
    return 0.0


def step_components(trainer, steps, wall_s, registry):
    """Measured per-step component decomposition (ms) of one finished
    class sweep, ready to ride a ledger record's ``components``
    field.  Fail-soft: returns None rather than raising."""
    try:
        if not steps or wall_s <= 0.0:
            return None
        step_ms = wall_s / steps * 1e3
        spd = max(int(getattr(trainer, "steps_per_dispatch", 1)), 1)
        floors = predicted_floors(steps_per_dispatch=spd)
        compile_ms = _compile_delta_s(registry, steps) * 1e3
        host_ms = min(floors["host_ms"], step_ms)
        dispatch_ms = min(floors["dispatch_ms"],
                          max(step_ms - host_ms - compile_ms, 0.0))
        collective_ms = min(_collective_ms(registry, steps),
                            max(step_ms - host_ms - dispatch_ms
                                - compile_ms, 0.0))
        compute_ms = max(step_ms - compile_ms - host_ms - dispatch_ms
                         - collective_ms, 0.0)
        return {"compile_ms": round(compile_ms, 6),
                "host_ms": round(host_ms, 6),
                "dispatch_ms": round(dispatch_ms, 6),
                "collective_ms": round(collective_ms, 6),
                "compute_ms": round(compute_ms, 6)}
    except Exception:   # noqa: BLE001 — observe, never abort
        return None


def attribute(measured, predicted=None):
    """(component, excess_ms) whose measured time exceeds its priced
    floor the most — the drift-attribution verdict.  None when
    nothing exceeds its floor (the step is AT the model)."""
    if not isinstance(measured, dict):
        return None
    if predicted is None:
        predicted = predicted_floors()
    worst, excess = None, 0.0
    for name in COMPONENTS:
        m = measured.get(name)
        if not isinstance(m, (int, float)):
            continue
        delta = m - float(predicted.get(name, 0.0))
        if delta > excess:
            worst, excess = name, delta
    return (worst, excess) if worst else None
