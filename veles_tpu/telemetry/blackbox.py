"""``veles-tpu-blackbox`` — read, filter, and merge crashdump
directories written by the flight recorder
(:mod:`veles_tpu.telemetry.flight`).

One dump renders as an operator timeline: the meta header (why, where,
which process), then the recorded events with wall-clock offsets.
Several dumps — one per process of a multi-host run — merge into a
single cross-host timeline keyed by wall clock, each line tagged with
its process index, so "host 2 stopped stepping 40 s before host 0
hung" is one read instead of N files of archaeology.

Stdlib-only, jax-free: runs anywhere the artifact landed, including
hosts with no accelerator stack at all."""

import argparse
import json
import os
import sys
import time


def load_dump(path):
    """Parse one crashdump directory -> {meta, header, events, stacks}.
    Raises ValueError when ``path`` is not a readable dump."""
    events_path = os.path.join(path, "events.jsonl")
    if not os.path.isfile(events_path):
        raise ValueError("%s: not a crashdump (no events.jsonl)" % path)
    header, events, bad = None, [], 0
    with open(events_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if rec.get("kind") == "flight.meta" and header is None:
                header = rec
            else:
                events.append(rec)
    meta = {}
    meta_path = os.path.join(path, "meta.json")
    if os.path.isfile(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except ValueError:
            pass
    stacks = None
    stacks_path = os.path.join(path, "stacks.txt")
    if os.path.isfile(stacks_path):
        with open(stacks_path) as f:
            stacks = f.read()
    return {"path": path, "meta": meta, "header": header or {},
            "events": events, "stacks": stacks, "bad_lines": bad}


def merge_timeline(dumps):
    """One cross-host event list: every event tagged with its dump's
    process index, sorted by wall-clock ts (stable within a host)."""
    merged = []
    for d in dumps:
        proc = d["meta"].get("process_index", "?")
        for ev in d["events"]:
            ev = dict(ev)
            ev["proc"] = proc
            merged.append(ev)
    merged.sort(key=lambda e: e.get("ts", 0.0))
    return merged


def _fmt_fields(ev, skip=("ts", "kind", "proc")):
    parts = []
    for k in sorted(ev):
        if k in skip:
            continue
        v = ev[k]
        if isinstance(v, float):
            v = "%.6g" % v
        parts.append("%s=%s" % (k, v))
    return " ".join(parts)


def render_text(dumps, events, last=None):
    """The operator view: per-dump summary block, then the (merged)
    timeline with offsets from the first event."""
    out = []
    for d in dumps:
        meta, header = d["meta"], d["header"]
        err = meta.get("error")
        out.append("%s" % d["path"])
        out.append(
            "  reason=%s  proc=%s  pid=%s  events=%d  dropped=%s%s"
            % (meta.get("reason", "?"),
               meta.get("process_index", "?"), meta.get("pid", "?"),
               len(d["events"]), header.get("dropped", "?"),
               "  [%d unparseable lines]" % d["bad_lines"]
               if d["bad_lines"] else ""))
        if err:
            out.append("  error: %s: %s" % (err.get("type"),
                                            err.get("message")))
        la = meta.get("live_arrays")
        if isinstance(la, dict) and "total_bytes" in la:
            out.append("  live arrays: %d (%.1f MiB)"
                       % (la.get("count", 0),
                          la["total_bytes"] / 2 ** 20))
    if not events:
        out.append("(no events matched)")
        return "\n".join(out)
    if last:
        events = events[-last:]
    t0 = events[0].get("ts", 0.0)
    multi = len(dumps) > 1
    out.append("-- timeline (%d events, t0=%s)"
               % (len(events),
                  time.strftime("%Y-%m-%d %H:%M:%S",
                                time.localtime(t0))))
    for ev in events:
        line = "  %+10.3fs " % (ev.get("ts", 0.0) - t0)
        if multi:
            line += "[p%s] " % ev.get("proc", "?")
        line += "%-16s %s" % (ev.get("kind", "?"), _fmt_fields(ev))
        out.append(line.rstrip())
    return "\n".join(out)


def find_dumps(paths):
    """Expand each argument: a dump dir itself, or a parent directory
    holding ``crashdump-*`` children in chronological (oldest-first)
    name order — ``veles-tpu-blackbox artifacts/`` reads a whole run's
    dumps as one timeline."""
    found = []
    for p in paths:
        if os.path.isfile(os.path.join(p, "events.jsonl")):
            found.append(p)
            continue
        children = sorted(
            os.path.join(p, n) for n in os.listdir(p)
            if n.startswith("crashdump-")
            and os.path.isfile(os.path.join(p, n, "events.jsonl")))
        if not children:
            raise ValueError(
                "%s: neither a crashdump nor a directory containing "
                "crashdump-*" % p)
        found.extend(children)
    return found


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="veles-tpu-blackbox",
        description="pretty-print, filter, and merge flight-recorder "
        "crashdump directories (one per process) into a single "
        "cross-host timeline")
    p.add_argument("dumps", nargs="+", metavar="DUMP",
                   help="crashdump-* directory, or a directory "
                   "containing them (e.g. artifacts/)")
    p.add_argument("--kind", default=None,
                   help="only events of this kind (e.g. step, "
                   "unit.stop, snapshot, hang)")
    p.add_argument("--trace", default=None, metavar="ID",
                   help="only events of this request trace id — the "
                   "post-mortem reconstruction of one serving "
                   "request's cross-process timeline (works with "
                   "every replica dead: the ids ride the serve.* "
                   "flight events into each process's crashdump)")
    p.add_argument("--grep", default=None,
                   help="only events whose JSON contains this "
                   "substring")
    p.add_argument("--last", type=int, default=None, metavar="N",
                   help="only the newest N events of the (merged) "
                   "timeline")
    p.add_argument("--stacks", action="store_true",
                   help="also print each dump's all-thread stacks")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="json emits {dumps, events} for scripting")
    args = p.parse_args(argv)

    try:
        paths = find_dumps(args.dumps)
        dumps = [load_dump(d) for d in paths]
    except (OSError, ValueError) as e:
        print("veles-tpu-blackbox: %s" % e, file=sys.stderr)
        return 2
    events = merge_timeline(dumps)
    if args.trace:
        events = [e for e in events if e.get("trace") == args.trace]
    if args.kind:
        events = [e for e in events if e.get("kind") == args.kind]
    if args.grep:
        events = [e for e in events
                  if args.grep in json.dumps(e, default=str)]
    if args.format == "json":
        out = {"dumps": [{"path": d["path"], "meta": d["meta"],
                          "header": d["header"],
                          "events": len(d["events"])} for d in dumps],
               "events": events[-args.last:] if args.last else events}
        print(json.dumps(out, indent=1, default=str))
    else:
        print(render_text(dumps, events, last=args.last))
        if args.stacks:
            for d in dumps:
                if d["stacks"]:
                    print("\n== stacks: %s ==\n%s"
                          % (d["path"], d["stacks"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
