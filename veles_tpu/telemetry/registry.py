"""Process-global metrics registry (ref: the reference's master status
server + per-unit timing tables, veles/web_status.py:113-314 and
units.py:805-817 — redesigned as a pull/scrape surface).

One :class:`MetricsRegistry` holds every instrument the process creates:
``Counter`` (monotonic), ``Gauge`` (set/inc/dec), ``Histogram``
(bucketed observations), each optionally labeled.  Two export surfaces:

* **JSON-lines sink** (``open_sink``): structured records — spans, step
  telemetry, MFU checks — stream out as they happen via :meth:`emit`,
  and :meth:`dump_state` appends one record per live instrument sample
  (registered ``atexit`` when ``dump_at_exit=True``), so a run's
  ``.jsonl`` is self-contained: what happened AND where every counter
  ended up.
* **Prometheus text format** (``render_prometheus``): the current
  instrument state as a ``/metrics`` scrape body (served by
  services.web_status) — the production-fleet surface the reference's
  POST-driven status server never had.

Everything here is stdlib-only and thread-safe under one lock: records
arrive from the scheduler thread, service threads, and jax's compile
listeners alike."""

import atexit
import bisect
import json
import math
import os
import re
import threading
import time

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_LABEL_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")

#: default histogram buckets (seconds-flavored, same spread as the
#: Prometheus client default)
DEFAULT_BUCKETS = (.001, .0025, .005, .01, .025, .05, .1, .25, .5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _fmt(v):
    """Prometheus sample-value formatting: integers bare, floats via
    repr (shortest round-trip), infinities as +Inf/-Inf."""
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _esc_help(s):
    return str(s).replace("\\", r"\\").replace("\n", r"\n")


def _esc_label(s):
    return (str(s).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


class _Instrument(object):
    kind = None

    def __init__(self, registry, name, help="", labelnames=()):
        if not _NAME_RE.match(name):
            raise ValueError("invalid metric name %r" % name)
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError("invalid label name %r" % ln)
        self._registry = registry
        self._lock = registry._lock
        self.name = name
        self.help = help
        #: declared order is preserved for the sample KEY; rendering
        #: sorts by label name so the text output is deterministic
        #: regardless of declaration order
        self.labelnames = tuple(labelnames)
        self._samples = {}

    def _key(self, labels):
        if set(labels) != set(self.labelnames):
            raise ValueError(
                "%s expects labels %s, got %s"
                % (self.name, sorted(self.labelnames), sorted(labels)))
        return tuple(str(labels[ln]) for ln in self.labelnames)

    def _label_dict(self, key):
        return dict(zip(self.labelnames, key))

    def samples(self):
        """[(label_dict, value)] — value is a float for counter/gauge,
        a state dict for histograms."""
        with self._lock:
            return [(self._label_dict(k), v)
                    for k, v in sorted(self._samples.items())]


class Counter(_Instrument):
    kind = "counter"

    def inc(self, amount=1.0, **labels):
        if amount < 0:
            raise ValueError("counter %s cannot decrease" % self.name)
        key = self._key(labels)
        with self._lock:
            self._samples[key] = self._samples.get(key, 0.0) + amount

    def value(self, **labels):
        with self._lock:
            return self._samples.get(self._key(labels), 0.0)


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value, **labels):
        key = self._key(labels)
        with self._lock:
            self._samples[key] = float(value)

    def inc(self, amount=1.0, **labels):
        key = self._key(labels)
        with self._lock:
            self._samples[key] = self._samples.get(key, 0.0) + amount

    def dec(self, amount=1.0, **labels):
        self.inc(-amount, **labels)

    def value(self, **labels):
        with self._lock:
            return self._samples.get(self._key(labels), 0.0)


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, registry, name, help="", labelnames=(),
                 buckets=DEFAULT_BUCKETS):
        for reserved in ("le", "quantile"):
            if reserved in labelnames:
                # the bucket's own le label would duplicate it and
                # produce exposition text scrapers reject wholesale
                raise ValueError(
                    "histogram %s: label name %r is reserved"
                    % (name, reserved))
        super(Histogram, self).__init__(registry, name, help, labelnames)
        b = sorted(float(x) for x in buckets)
        if not b:
            raise ValueError("histogram %s needs at least one bucket"
                             % name)
        self.buckets = tuple(b)

    def observe(self, value, **labels):
        key = self._key(labels)
        v = float(value)
        with self._lock:
            st = self._samples.get(key)
            if st is None:
                st = self._samples[key] = {
                    "counts": [0] * len(self.buckets),
                    "sum": 0.0, "count": 0}
            i = bisect.bisect_left(self.buckets, v)
            if i < len(self.buckets):
                st["counts"][i] += 1
            st["sum"] += v
            st["count"] += 1

    def state(self, **labels):
        with self._lock:
            st = self._samples.get(self._key(labels))
            return None if st is None else {
                "counts": list(st["counts"]), "sum": st["sum"],
                "count": st["count"]}


class MetricsRegistry(object):
    """Instrument factory + export surface.  ``counter``/``gauge``/
    ``histogram`` are create-or-return by name: asking twice with the
    same name yields the same instrument; asking with a different kind
    or label set raises (one name, one meaning)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics = {}
        self._sink = None
        self._sink_path = None
        self._records = []          # small ring of recent emit()s
        self._records_cap = 512
        self._atexit_registered = False

    # ------------------------------------------------------- instruments
    def _get(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            inst = self._metrics.get(name)
            if inst is not None:
                if not isinstance(inst, cls) \
                        or set(inst.labelnames) != set(labelnames):
                    raise ValueError(
                        "metric %r already registered as %s%s"
                        % (name, inst.kind, sorted(inst.labelnames)))
                return inst
            inst = cls(self, name, help, labelnames, **kwargs)
            self._metrics[name] = inst
            return inst

    def counter(self, name, help="", labelnames=()):
        return self._get(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()):
        return self._get(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(), buckets=None):
        """``buckets=None`` means "don't care" (DEFAULT_BUCKETS when
        creating, whatever exists when returning); explicit buckets
        that disagree with an existing instrument raise — same
        one-name-one-meaning rule as kind/label mismatches."""
        inst = self._get(Histogram, name, help, labelnames,
                         buckets=buckets or DEFAULT_BUCKETS)
        if buckets is not None \
                and tuple(sorted(float(b) for b in buckets)) \
                != inst.buckets:
            raise ValueError(
                "histogram %r already registered with buckets %s"
                % (name, inst.buckets))
        return inst

    def metrics(self):
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    # ------------------------------------------------------- JSONL sink
    def open_sink(self, path, dump_at_exit=False):
        """Append structured records to ``path`` (created along with its
        directory).  With ``dump_at_exit`` the final instrument state is
        dumped and the sink closed at interpreter exit."""
        with self._lock:
            self.close_sink()
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._sink = open(path, "a")
            self._sink_path = path
        if dump_at_exit and not self._atexit_registered:
            self._atexit_registered = True
            atexit.register(self._atexit_dump)
        return path

    def _atexit_dump(self):
        self._atexit_registered = False
        if self._sink is not None:
            self.dump_state()
            self.close_sink()

    @property
    def sink_path(self):
        return self._sink_path

    def close_sink(self):
        with self._lock:
            if self._sink is not None:
                try:
                    self._sink.close()
                except OSError:
                    pass
                self._sink = None
                self._sink_path = None

    def emit(self, kind, **fields):
        """One structured record: ``{"ts": now, "kind": kind, **fields}``
        — appended to the JSONL sink (if open) and a small in-memory
        ring (the dashboard's recent-records view)."""
        record = {"ts": time.time(), "kind": kind}
        record.update(fields)
        with self._lock:
            self._records.append(record)
            if len(self._records) > self._records_cap:
                del self._records[:self._records_cap // 2]
            if self._sink is not None:
                try:
                    self._sink.write(json.dumps(record, default=str)
                                     + "\n")
                    self._sink.flush()
                except (OSError, ValueError):
                    # telemetry must never kill the loop it instruments
                    # (ENOSPC, closed fd, ...): drop the sink, keep the
                    # in-memory ring and /metrics alive
                    path = self._sink_path
                    try:
                        self._sink.close()
                    except OSError:
                        pass
                    self._sink = None
                    self._sink_path = None
                    import logging
                    logging.getLogger("MetricsRegistry").warning(
                        "metrics sink %s failed — telemetry JSONL "
                        "disabled for the rest of the run", path)
        return record

    def records(self, kind=None):
        with self._lock:
            recs = list(self._records)
        if kind is not None:
            recs = [r for r in recs if r.get("kind") == kind]
        return recs

    def dump_state(self):
        """Append one record per live instrument sample — counters,
        gauges, histograms — so the JSONL file carries the final state,
        not just the event stream."""
        for inst in self.metrics():
            for labels, value in inst.samples():
                if inst.kind == "histogram":
                    cum, counts = 0, []
                    for le, c in zip(inst.buckets, value["counts"]):
                        cum += c
                        counts.append([le, cum])
                    self.emit("histogram", name=inst.name, labels=labels,
                              count=value["count"], sum=value["sum"],
                              buckets=counts)
                else:
                    self.emit(inst.kind, name=inst.name, labels=labels,
                              value=value)

    # ------------------------------------------------------- prometheus
    def render_prometheus(self):
        """The registry as Prometheus exposition text (format 0.0.4):
        families sorted by name, label names sorted within a sample,
        samples sorted by label values — deterministic output, with
        HELP/label-value escaping per the spec."""
        lines = []
        for inst in self.metrics():
            lines.append("# HELP %s %s" % (inst.name,
                                           _esc_help(inst.help)))
            lines.append("# TYPE %s %s" % (inst.name, inst.kind))
            for labels, value in inst.samples():
                if inst.kind == "histogram":
                    cum = 0
                    for le, c in zip(inst.buckets, value["counts"]):
                        cum += c
                        lines.append("%s_bucket%s %s" % (
                            inst.name,
                            self._label_str(labels, le=_fmt(le)), cum))
                    lines.append("%s_bucket%s %s" % (
                        inst.name, self._label_str(labels, le="+Inf"),
                        value["count"]))
                    lines.append("%s_sum%s %s" % (
                        inst.name, self._label_str(labels),
                        _fmt(value["sum"])))
                    lines.append("%s_count%s %s" % (
                        inst.name, self._label_str(labels),
                        value["count"]))
                else:
                    lines.append("%s%s %s" % (
                        inst.name, self._label_str(labels), _fmt(value)))
        return "\n".join(lines) + ("\n" if lines else "")

    @staticmethod
    def _label_str(labels, **extra):
        items = sorted(labels.items()) + sorted(extra.items())
        if not items:
            return ""
        return "{%s}" % ",".join(
            '%s="%s"' % (k, _esc_label(v)) for k, v in items)

    def snapshot(self):
        """JSON-able instrument state for ``/api/telemetry``."""
        out = []
        for inst in self.metrics():
            for labels, value in inst.samples():
                rec = {"name": inst.name, "kind": inst.kind,
                       "labels": labels}
                if inst.kind == "histogram":
                    rec["count"] = value["count"]
                    rec["sum"] = value["sum"]
                else:
                    rec["value"] = value
                out.append(rec)
        return out

    def reset(self):
        """Drop every instrument and record and close the sink (tests)."""
        with self._lock:
            self.close_sink()
            self._metrics.clear()
            self._records[:] = []
