"""Crash forensics & multi-host health layer (ref: the reference's
operable distributed training — master/slave runs that could be watched,
diagnosed, and resumed; this is the "what happened when it died/stalled"
half that PR 3's live telemetry cannot answer).

Three capabilities, all fail-soft and default-off until
:func:`install` (called by ``Launcher.initialize``) arms them:

* **crash forensics** — ``sys.excepthook`` / ``threading.excepthook``
  wrappers, ``faulthandler`` for C-level faults, and SIGTERM/SIGABRT
  handlers that append a flight event and write an atomic
  ``crashdump-*`` directory (:mod:`veles_tpu.telemetry.flight`) before
  chaining to whatever handler was installed first — the CLI's
  preemption SIGTERM keeps working, it just leaves a black box behind.
* **hang watchdog** — a daemon thread that dumps the flight record and
  all-thread stacks when no unit/step progress is observed for a
  configurable window (``root.common.blackbox.watchdog_seconds``;
  default off — the Launcher arms it in spmd mode).  It observes and
  dumps; it never kills the run.
* **multi-host health** — a heartbeat + step-counter allgather at the
  staged trainer's sync point: hosts on different steps raise a desync
  error event + dump, and per-host step-wall gauges attribute
  stragglers (``veles_host_step``/``veles_host_step_wall_seconds`` +
  the ``veles_step_wall_skew_seconds`` spread).

The module is import-cheap (stdlib only); jax is touched only inside
the multihost check, which the Launcher enables exclusively for real
multi-process runs."""

import os
import sys
import threading
import time

from veles_tpu.telemetry import flight

_state = {
    "installed": False,
    "mode": None,
    "prev_excepthook": None,
    "prev_threading_hook": None,
    "prev_sigterm": None,
    "prev_sigabrt": None,
    "faulthandler_file": None,
    "watchdog": None,
    "multihost": False,
    "desync_latched": False,
    "last_progress": None,        # monotonic of the last step/unit
    "last_step": None,
    # the pod-agent progress bridge (services.podmaster): when
    # VELES_TPU_PROGRESS_FILE is set, liveness is mirrored into that
    # file (throttled) so the per-host agent can heartbeat real step
    # progress to the pod master without parsing worker output —
    # False = env not read yet, None = bridge disabled
    "progress_file": False,
    "progress_file_written": 0.0,
    # the snapshotter's reject_nonfinite poison valve: how many
    # commits this process refused, and the last refusal's detail —
    # the /api/health "degraded" surface (a run that can no longer
    # commit must stop probing healthy)
    "nonfinite_commits": 0,
    "nonfinite_last": None,
}
_lock = threading.Lock()

#: minimum seconds between progress-file writes (the bridge is a
#: liveness signal, not a metrics channel — its reader keys off mtime)
PROGRESS_FILE_INTERVAL = 0.25


def _progress_file():
    lazy = _state["progress_file"]
    if lazy is False:
        lazy = os.environ.get("VELES_TPU_PROGRESS_FILE") or None
        _state["progress_file"] = lazy
    return lazy


# ------------------------------------------------------------- progress
def note_progress(step=None):
    """Record liveness — called per unit run by ``Workflow._drive`` and
    per sweep by the staged trainer.  One float store: cheap enough for
    the hot loop, signal-safe, never raises.  With
    ``VELES_TPU_PROGRESS_FILE`` set (pod agents set it on their
    workers) the liveness also lands in that file, throttled to one
    small write per :data:`PROGRESS_FILE_INTERVAL` — the collective-
    hang detector's ground truth: a wedged pod stops moving this file
    on EVERY host at once."""
    now = time.monotonic()
    _state["last_progress"] = now
    if step is not None:
        _state["last_step"] = step
    path = _progress_file()
    if path is not None and \
            now - _state["progress_file_written"] >= \
            PROGRESS_FILE_INTERVAL:
        _state["progress_file_written"] = now
        try:
            with open(path, "w") as f:
                f.write("%s\n" % (_state["last_step"]
                                  if _state["last_step"] is not None
                                  else ""))
        except OSError:
            _state["progress_file"] = None   # dead path: stop trying


def last_progress_age():
    """Seconds since the last observed progress, or None before any."""
    t = _state["last_progress"]
    return None if t is None else time.monotonic() - t


def note_nonfinite_commit(prefix=None, leaves=None):
    """Record one commit refused by the snapshotter's
    ``reject_nonfinite`` poison valve — flips the ``/api/health``
    payload to ``degraded`` so a poisoned run stops reporting healthy
    while silently never committing.  Never raises (the valve must
    fire regardless)."""
    try:
        _state["nonfinite_commits"] += 1
        _state["nonfinite_last"] = {"ts": time.time(),
                                    "prefix": prefix,
                                    "leaves": list(leaves or [])[:5]}
    except Exception:   # noqa: BLE001 — observability only
        pass


# ---------------------------------------------------------------- install
def install(mode=None, workflow=None):
    """Install the crash-forensics hooks (idempotent).  Signal handlers
    land only from the main thread; everything else works anywhere."""
    with _lock:
        if _state["installed"]:
            _state["mode"] = mode or _state["mode"]
            return
        _state["installed"] = True
        _state["mode"] = mode
    _install_excepthooks()
    _install_faulthandler()
    _install_signal_handlers()
    flight.record("health.install", mode=mode,
                  workflow=getattr(workflow, "name", None))
    try:
        from veles_tpu.config import root
        cap = root.common.blackbox.get("capacity", 4096)
        if cap:
            flight.recorder.set_capacity(cap)
    except Exception:   # noqa: BLE001 — config is advisory here
        pass


def uninstall():
    """Restore the pre-install hooks (tests)."""
    with _lock:
        if not _state["installed"]:
            return
        _state["installed"] = False
    if _state["prev_excepthook"] is not None:
        sys.excepthook = _state["prev_excepthook"]
        _state["prev_excepthook"] = None
    if _state["prev_threading_hook"] is not None:
        threading.excepthook = _state["prev_threading_hook"]
        _state["prev_threading_hook"] = None
    import signal
    if threading.current_thread() is threading.main_thread():
        if _state["prev_sigterm"] is not None:
            signal.signal(signal.SIGTERM, _state["prev_sigterm"])
            _state["prev_sigterm"] = None
        if _state["prev_sigabrt"] is not None:
            signal.signal(signal.SIGABRT, _state["prev_sigabrt"])
            _state["prev_sigabrt"] = None
    f = _state["faulthandler_file"]
    if f is not None:
        _state["faulthandler_file"] = None
        try:
            import faulthandler
            faulthandler.disable()
            f.close()
        except Exception:   # noqa: BLE001
            pass
    disarm_watchdog()
    _state["multihost"] = False
    _state["desync_latched"] = False
    _state["nonfinite_commits"] = 0
    _state["nonfinite_last"] = None


def _install_excepthooks():
    prev = sys.excepthook

    def hook(exc_type, exc, tb):
        # record + dump FIRST: the chained hook may terminate printing
        try:
            flight.record("crash", error=exc_type.__name__,
                          message=str(exc))
            flight.dump(reason="excepthook", error=exc)
        except Exception:   # noqa: BLE001 — forensics never mask the crash
            pass
        prev(exc_type, exc, tb)

    _state["prev_excepthook"] = prev
    sys.excepthook = hook

    prev_t = threading.excepthook

    def thook(args):
        try:
            if args.exc_type is not SystemExit:
                flight.record(
                    "crash", thread=getattr(args.thread, "name", "?"),
                    error=args.exc_type.__name__,
                    message=str(args.exc_value))
                flight.dump(reason="thread-excepthook",
                            error=args.exc_value)
        except Exception:   # noqa: BLE001
            pass
        prev_t(args)

    _state["prev_threading_hook"] = prev_t
    threading.excepthook = thook


def _install_faulthandler():
    """C-level faults (SIGSEGV/SIGBUS/SIGFPE, real abort()) bypass
    python excepthooks entirely — faulthandler writes the stacks to a
    per-process file in the blackbox dir so even those leave evidence."""
    try:
        import faulthandler
        from veles_tpu.config import root
        d = root.common.blackbox.get("dir", "artifacts")
        os.makedirs(d, exist_ok=True)
        f = open(os.path.join(
            d, "faulthandler-p%d.log" % flight._process_index()), "a")
        faulthandler.enable(file=f, all_threads=True)
        _state["faulthandler_file"] = f
    except Exception:   # noqa: BLE001 — read-only fs: skip, don't fail boot
        pass


def _install_signal_handlers():
    if threading.current_thread() is not threading.main_thread():
        return
    import signal

    def on_sigterm(signum, frame):
        note_signal("SIGTERM")
        prev = _state["prev_sigterm"]
        if callable(prev):
            prev(signum, frame)
        elif prev is None or prev == signal.SIG_DFL:
            # no chainable python handler (SIG_DFL, or None when the
            # prior handler came from C code): the black box must not
            # change the signal's meaning — restore the default and
            # re-deliver so the process still terminates honestly
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)

    def on_sigabrt(signum, frame):
        note_signal("SIGABRT")
        # SIGABRT is not survivable: restore the default disposition
        # and re-deliver so the exit status stays honest
        signal.signal(signal.SIGABRT, _state["prev_sigabrt"]
                      or signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGABRT)

    try:
        _state["prev_sigterm"] = signal.signal(signal.SIGTERM, on_sigterm)
        _state["prev_sigabrt"] = signal.signal(signal.SIGABRT, on_sigabrt)
    except (ValueError, OSError):
        pass


def note_signal(name):
    """Record + dump for a delivered signal.  Also the hook the CLI's
    own preemption SIGTERM handler calls (it replaces this module's
    handler when installed later — both paths leave a black box)."""
    try:
        flight.record("signal", signal=name)
        flight.dump(reason=name.lower())
    except Exception:   # noqa: BLE001 — handlers must never raise
        pass


# --------------------------------------------------------------- watchdog
class Watchdog(threading.Thread):
    """Dump-on-stall: when no progress lands for ``window`` seconds the
    flight record + stacks go to a crashdump and ``tripped`` rises (the
    ``/api/health`` 503 surface).  Progress resuming re-arms it; the
    run is never killed."""

    def __init__(self, window):
        super(Watchdog, self).__init__(name="VelesWatchdog", daemon=True)
        self.window = float(window)
        self.tripped = False
        self.trip_count = 0
        self._stop_evt = threading.Event()
        # arming counts as progress: a run that stalls before its first
        # step still trips after one full window
        note_progress()

    def stop(self):
        self._stop_evt.set()

    def run(self):
        poll = max(min(self.window / 4.0, 5.0), 0.05)
        while not self._stop_evt.wait(poll):
            age = last_progress_age()
            if age is None:
                continue
            if age < self.window:
                if self.tripped:
                    self.tripped = False
                    flight.record("watchdog.recovered", stalled_s=age)
                continue
            if self.tripped:
                continue              # one dump per stall, not per poll
            self.trip_count += 1
            flight.record("hang", stalled_s=age, window_s=self.window,
                          last_step=_state["last_step"])
            path = flight.dump(reason="watchdog")
            # tripped rises only after the dump is on disk: readers of
            # the /api/health 503 (and tests) may react immediately
            self.tripped = True
            try:
                import logging
                logging.getLogger("Watchdog").error(
                    "no unit/step progress for %.1fs (window %.1fs) — "
                    "flight record + stacks dumped to %s",
                    age, self.window, path)
            except Exception:   # noqa: BLE001
                pass


def arm_watchdog(seconds):
    """Start (or retune) the hang watchdog.  ``seconds <= 0`` disarms."""
    disarm_watchdog()
    if not seconds or seconds <= 0:
        return None
    wd = Watchdog(seconds)
    _state["watchdog"] = wd
    wd.start()
    flight.record("watchdog.armed", window_s=float(seconds))
    return wd


def disarm_watchdog():
    wd = _state["watchdog"]
    if wd is not None:
        _state["watchdog"] = None
        wd.stop()


def watchdog():
    return _state["watchdog"]


# -------------------------------------------------------------- multihost
def enable_multihost(enabled=True):
    """Turn on the per-sweep heartbeat/desync allgather (Launcher, spmd
    mode only — the collective would deadlock a single process that
    merely *thinks* it has peers)."""
    _state["multihost"] = enabled
    _state["desync_latched"] = False


def multihost_check(step, step_wall_s, registry=None):
    """Heartbeat + step-counter allgather at the staged sync point:
    every host contributes (step, sweep wall); disagreement on the step
    counter is a desync — error event + dump, once.  The gathered walls
    feed per-host gauges and the skew spread for straggler attribution.

    Collective discipline: the trainer calls this OUTSIDE its fail-soft
    telemetry guard (sweep close is SPMD-lockstep, so every host makes
    the same allgather calls), only the allgather itself can raise
    (symmetrically — a broken collective should fail the run loudly),
    and everything after it is guarded here so a host-local reporting
    failure can never skip a later host's collective.  A host that
    stops calling entirely (crashed, wedged in device code) stalls the
    peers inside the allgather until the DCN timeout — that is the
    hang watchdog's case, not this check's: the peers' watchdogs fire
    and dump while they wait."""
    if not _state["multihost"]:
        return None
    import jax
    if jax.process_count() <= 1:
        return None
    import numpy as np
    from jax.experimental import multihost_utils
    local = np.asarray([float(jax.process_index()), float(step),
                        float(step_wall_s)], np.float64)
    gathered = np.asarray(multihost_utils.process_allgather(local))
    try:
        return _report_heartbeat(gathered, step, registry)
    except Exception:   # noqa: BLE001 — reporting is fail-soft
        return None


def _report_heartbeat(gathered, step, registry):
    import numpy as np
    gathered = np.asarray(gathered)
    if gathered.ndim == 1:
        gathered = gathered[None, :]
    if registry is None:
        from veles_tpu import telemetry
        registry = telemetry.registry
    g_step = registry.gauge(
        "veles_host_step", "per-host staged step counter at the last "
        "health heartbeat", ("proc",))
    g_wall = registry.gauge(
        "veles_host_step_wall_seconds",
        "per-host wall seconds of the last class sweep (straggler "
        "attribution)", ("proc",))
    for proc, st, wall in gathered:
        g_step.set(st, proc=int(proc))
        g_wall.set(wall, proc=int(proc))
    walls = gathered[:, 2]
    skew = float(walls.max() - walls.min())
    registry.gauge(
        "veles_step_wall_skew_seconds",
        "max-min spread of per-host sweep wall time (stragglers)").set(
        skew)
    steps = gathered[:, 1]
    desync = bool(steps.max() != steps.min())
    flight.record("heartbeat", step=int(step), skew_s=skew,
                  hosts=int(gathered.shape[0]), desync=desync)
    if desync and not _state["desync_latched"]:
        _state["desync_latched"] = True
        per_host = {int(p): int(s) for p, s, _ in gathered}
        flight.record("desync", steps=per_host)
        registry.emit("desync", steps=per_host)
        flight.dump(reason="desync")
        import logging
        logging.getLogger("Health").error(
            "multi-host DESYNC: hosts report different step counters "
            "%s — flight record dumped", per_host)
    return {"skew_s": skew, "desync": desync}


# ----------------------------------------------------------------- status
def status():
    """The ``/api/health`` payload: liveness, watchdog state, and how
    many black boxes this process has written."""
    wd = _state["watchdog"]
    age = last_progress_age()
    return {
        "pid": os.getpid(),
        "process_index": flight._process_index(),
        "mode": _state["mode"],
        "installed": _state["installed"],
        "last_progress_age_s": (round(age, 3)
                                if age is not None else None),
        "last_step": _state["last_step"],
        "watchdog": {
            "armed": wd is not None,
            "window_s": wd.window if wd is not None else None,
            "tripped": bool(wd is not None and wd.tripped),
            "trips": wd.trip_count if wd is not None else 0,
        },
        "multihost": _state["multihost"],
        "desync": _state["desync_latched"],
        # the numeric-fault surfaces: refused (non-finite) commits and
        # the aggregate degraded verdict — a run that cannot commit or
        # has desynced is NOT healthy, even while it keeps stepping
        "snapshot_nonfinite": {
            "count": _state["nonfinite_commits"],
            "last": _state["nonfinite_last"],
        },
        "degraded": bool(_state["nonfinite_commits"]
                         or _state["desync_latched"]),
        "crashdumps": flight.recorder.dump_count,
        "last_dump": flight.recorder.last_dump,
        "flight_events": len(flight.recorder),
        "flight_dropped": flight.recorder.dropped,
    }
